"""Filesystem-rendezvous cluster supervision for multi-host training.

A multi-host run (resilience.distributed two-phase-commit saves over a
shared checkpoint directory) survives every SINGLE-host failure we can
inject, but the hosts have no view of EACH OTHER: a dead peer turns the
next collective (a gloo transfer, a commit barrier) into an indefinite
hang with no typed failure; a SIGTERM delivered to one host never
reaches the others; and skipping an async save is a collective decision
no host can make alone, so multi-process runs had to disable coalescing.
This module is the coordination layer, using the same medium the saves
already trust — durable files in a shared directory (no new transport,
no new deps; the ``dckpt`` barrier discipline applied to liveness):

  <cluster_dir>/
    gen000/                              one directory per cluster GENERATION
      hb_proc00000.json (+ .sha256)      per-host heartbeats (seq + hostname),
      hb_proc00001.json                  rewritten atomically every interval
      stop_request.json                  the durable stop flag (any host)
      stop_ack_proc00000.json            "saw the flag at step boundary B"
      stop_go.json                       leader's agreed drain step (max of acks)
      rounds/
        r000000_proc00000.json           save-cursor consensus: proposals
        r000000_decision.json            ... and the leader's save/skip verdict
    gen001/...                           re-formed topology after a PeerDown
    reform_gen001_proc00000.json         elastic re-formation rendezvous
    coord_gen001.json                    survivor rank 0's new coordinator

Four capabilities:

  * **Health supervision** — every host beats ``hb_proc<P>`` on a writer
    thread; a monitor thread tracks peer beat SEQUENCE changes against
    its own monotonic clock (no cross-host clock sync needed) and
    declares a peer dead after ``staleness_s`` without a change.
    ``check()`` then raises a typed :class:`PeerDown` — the deadline
    check collective call sites run instead of hanging: the training
    loop at every step boundary, `parallel.mesh.checked_collective` at
    every cross-process array assembly, and the sharded-save barrier
    polls via ``save_sharded(health_check=...)``. Detection latency is
    bounded by ``staleness_s`` + one monitor poll. The budget must also
    cover startup skew; start the supervisor only AFTER
    ``jax.distributed.initialize`` has barriered the processes.
  * **Coordinated preemption** — `publish_stop` durably publishes
    ``stop_request.json`` (`PreemptionGuard(cluster=...)` calls it from
    the signal handler, lock-free, so a signal on ANY host reaches all).
    Each host polls the flag at step boundaries (`stop_requested`, a
    throttled stat) and then drives `drain_step` — a NON-BLOCKING state
    machine: ack the flag with the current step and KEEP TRAINING
    (including the regular collective save schedule — flag visibility
    skews across hosts, and a host blocked waiting for acks while a
    peer enters a collective save barrier would deadlock the run; the
    collective schedule is also what bounds inter-host step skew to one
    save interval). Once all acks are in, the leader publishes
    ``stop_go`` with a drain step safely AHEAD of every host
    (``max(acks) + save interval + 2``); each host picks it up at a
    later boundary, trains up to exactly that step, and writes the
    final cursor save there — every host commits the SAME final step.
  * **Save-cursor consensus** — `agree_save_cursor(step, busy)` is the
    `AsyncCheckpointer` coalesce arbiter for multi-process sharded runs:
    each host durably proposes whether its writer is busy; the leader
    decides SKIP if any host is (no host backpressures — the coalescing
    win) and SAVE only when all are free, so every host skips or saves
    the same step, deterministically, and the commit barrier can never
    see divergent save sequences. Note the freshness trade: a collective
    skip drops the NEWER snapshot (the queued older one still gets
    written) — superseding in place would itself need consensus.
  * **Elastic restart** — :class:`ElasticSupervisor` runs the training
    process as a child; a child exiting :data:`EXIT_PEER_DOWN` (the
    typed `PeerDown` exit, ``scripts/train.py --elastic``) triggers
    re-formation: survivors rendezvous via ``reform_gen<G>_proc<P>``
    files inside a bounded window, re-rank by original process index,
    survivor rank 0 publishes a fresh coordinator, and the children
    relaunch at the surviving topology — resuming from
    ``latest_valid_save`` through the topology-independent `SaveReader`
    restore. A host that misses the window is excluded (bounded-join
    semantics, the standard elastic-agent trade).

Fault points (`resilience.faultinject`): ``cluster.heartbeat`` (writer
thread, before each beat — a kill is a dying host the peers must
detect), ``cluster.stopflag`` (before the stop flag publishes — a kill
loses the drain request), ``cluster.propose`` / ``cluster.ack`` (before
a consensus proposal / the leader's decision write — a kill mid-round
must surface as `PeerDown` on the peers, not a hang).

Threading: the heartbeat and monitor threads are ledger-tracked and
joined by `close()` under a bounded budget (`report()` lists stragglers
once closed, the serve-engine convention). Cross-thread state (the
peer-liveness maps) is guarded by one named lock; the drain/consensus
state machines run only on the step thread (the `AsyncCheckpointer`
single-producer contract extends to its arbiter), and `publish_stop` /
`stop_requested` are lock-free so the signal handler can never deadlock
against a step-thread wait.

Stdlib-only (the `resilience` import-light contract): topology is passed
in explicitly (``process_index``/``process_count``), never read from jax.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

from ncnet_tpu.analysis import concurrency
from ncnet_tpu.resilience import durable, faultinject
from ncnet_tpu.telemetry.registry import default_registry

#: the typed "peer died, re-form and resume" exit status the elastic
#: supervisor restarts on (EX_TEMPFAIL; anything else propagates)
EXIT_PEER_DOWN = 75


class ClusterError(RuntimeError):
    """A cluster protocol step failed (timeout, malformed rendezvous)."""


class PeerDown(ClusterError):
    """A peer host's heartbeat went stale past the staleness budget.

    ``host`` is the peer's process index; ``last_seen`` is how many
    seconds ago its heartbeat last changed (None: never seen at all).
    Raised by `ClusterSupervisor.check` — i.e. at step boundaries, at
    collective entry, and inside barrier/consensus waits — so a dead
    peer surfaces as a typed failure instead of a hung collective.
    """

    def __init__(self, host, last_seen, budget=None, where=None):
        self.host = int(host)
        self.last_seen = last_seen
        self.budget = budget
        ago = (
            f"last heartbeat {last_seen:.1f}s ago"
            if last_seen is not None
            else "no heartbeat ever observed"
        )
        at = f" at {where}" if where else ""
        super().__init__(
            f"peer {self.host} down{at}: {ago}"
            + (f" (staleness budget {budget}s)" if budget is not None else "")
        )


def _proc_tag(p):
    return f"proc{int(p):05d}"


def _write_json(path, payload):
    # the same temp+fsync+rename discipline as every other rendezvous
    # file; the checkpoint.* fault windows stay out of cluster traffic
    # (cluster.* points fire at the call sites, per protocol phase)
    durable.durable_write_bytes(
        path,
        json.dumps(payload, sort_keys=True).encode("utf-8"),
        write_point=None,
        rename_point=None,
        bytes_point=None,
    )


def _read_json(path):
    """Parse a rendezvous file, or None while it is absent/not-yet-whole.

    Writers publish via atomic rename, so a reader sees old-or-new bytes,
    never a mixture; the digest SIDECAR however lands in a second rename,
    so (unlike checkpoint loads) liveness reads must not require it."""
    try:
        with open(path, "rb") as f:
            return json.loads(f.read().decode("utf-8"))
    except (FileNotFoundError, ValueError, OSError):
        return None


class ClusterSupervisor:
    """Heartbeats + peer-death detection + stop-flag drain + save-cursor
    consensus over a shared directory (module docstring has the layout).

    Use as a context manager or call `start()`/`close()` explicitly;
    `close()` joins the heartbeat and monitor threads under a bounded
    budget and `report()["straggler_threads"]` must be empty after it.
    """

    def __init__(
        self,
        base_dir,
        process_index,
        process_count,
        generation=0,
        heartbeat_interval_s=2.0,
        staleness_s=15.0,
        consensus_timeout_s=120.0,
        poll_interval_s=0.05,
        stop_poll_s=0.25,
        join_timeout_s=10.0,
        registry=None,
    ):
        self._p = int(process_index)
        self._n = int(process_count)
        self._gen = int(generation)
        self._base = os.path.abspath(base_dir)
        self._dir = os.path.join(self._base, f"gen{self._gen:03d}")
        self._rounds_dir = os.path.join(self._dir, "rounds")
        self._interval = float(heartbeat_interval_s)
        self._staleness = float(staleness_s)
        self._consensus_timeout = float(consensus_timeout_s)
        self._poll = float(poll_interval_s)
        self._stop_poll_s = float(stop_poll_s)
        self._join_timeout = float(join_timeout_s)
        self._peers = [q for q in range(self._n) if q != self._p]

        # lock-order: _lock
        # (a leaf: nothing is ever acquired while held, and publish_stop
        # is lock-free because a signal handler may interrupt a thread
        # that holds it)
        self._lock = concurrency.make_lock("resilience.cluster")
        self._last = {}  # guarded-by: _lock  (peer -> [seq, mono_of_change])
        self._dead = {}  # guarded-by: _lock  (peer -> age_s when declared)
        self._started_at = None  # set once in start(), read-only after
        self._closed_evt = threading.Event()
        self._started = False

        # drain + consensus state machines run ONLY on the step thread
        # (the AsyncCheckpointer single-producer contract extends to its
        # arbiter), so these fields need no lock; the signal handler
        # touches only the lock-free _stop_local event below.
        self._stop_acked_at = None  # step-thread only
        self._drain_at = None  # step-thread only
        self._round = 0  # step-thread only
        self._stop_local = threading.Event()
        self._stop_poll_last = 0.0  # step-thread only (poll throttle)

        reg = registry if registry is not None else default_registry()
        self._m_hb_age = reg.gauge(
            "cluster_heartbeat_age_s",
            "seconds since the stalest peer heartbeat changed",
        )
        self._m_peers_down = reg.counter(
            "cluster_peers_down_total",
            "peer hosts declared dead (heartbeat past the staleness budget)",
        )
        self._m_rounds = reg.counter(
            "ckpt_consensus_rounds_total",
            "save-cursor propose/ack consensus rounds completed",
        )
        # joined in close() under a bounded budget; report() lists them
        # as stragglers (serve-engine thread-ledger convention) if they
        # outlive it
        # daemon (repo thread convention) and load-bearing here: a host
        # dying of an UNHANDLED error must stop heartbeating — process
        # death is exactly the signal peers detect — not keep the
        # interpreter (and its beats) alive from a non-daemon thread
        self._thread_ledger = [
            threading.Thread(
                target=self._heartbeat_loop, name="cluster-hb", daemon=True
            ),
            threading.Thread(
                target=self._monitor_loop, name="cluster-mon", daemon=True
            ),
        ]

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        """Begin heartbeating and monitoring. Call AFTER the distributed
        runtime has barriered the processes (its init is the startup-skew
        bound the staleness budget must only cover from then on)."""
        if self._started:
            return self
        os.makedirs(self._rounds_dir, exist_ok=True)
        self._started_at = time.monotonic()
        self._started = True
        for t in self._thread_ledger:
            t.start()
        return self

    def close(self):
        """Stop the threads and join them under the bounded budget."""
        self._closed_evt.set()
        for t in self._thread_ledger:
            if t.is_alive():
                t.join(self._join_timeout)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    def report(self):
        """Telemetry/shutdown summary; ``straggler_threads`` is only
        populated once closed (serve-engine report convention)."""
        with self._lock:
            dead = dict(self._dead)
        stragglers = (
            sorted(t.name for t in self._thread_ledger if t.is_alive())
            if self._closed_evt.is_set()
            else []
        )
        return {
            "process_index": self._p,
            "process_count": self._n,
            "generation": self._gen,
            "peers_down": dead,
            "consensus_rounds": self._round,
            "drain_at": self._drain_at,
            "straggler_threads": stragglers,
        }

    # --- health supervision --------------------------------------------------

    def _hb_path(self, p):
        return os.path.join(self._dir, f"hb_{_proc_tag(p)}.json")

    def _heartbeat_loop(self):
        seq = 0
        while True:
            seq += 1
            # the kill window: a host dying between beats is exactly what
            # the peers' staleness monitor must detect
            faultinject.fire("cluster.heartbeat")
            try:
                _write_json(
                    self._hb_path(self._p),
                    {"proc": self._p, "seq": seq, "host": socket.gethostname(),
                     "pid": os.getpid(), "time": time.time()},
                )
            except OSError as e:
                # a shared-filesystem hiccup is a MISSED BEAT (the peers'
                # budget absorbs it), not a reason to kill this host
                print(f"[cluster] heartbeat write failed: {e!r}", flush=True)
            if self._closed_evt.wait(self._interval):
                return

    def _monitor_loop(self):
        poll = max(min(self._interval / 2.0, self._staleness / 4.0), 0.02)
        while not self._closed_evt.wait(poll):
            now = time.monotonic()
            worst = 0.0
            for peer in self._peers:
                blob = _read_json(self._hb_path(peer))
                seq = blob.get("seq") if isinstance(blob, dict) else None
                with self._lock:
                    prev = self._last.get(peer)
                    if seq is not None and (prev is None or seq != prev[0]):
                        prev = (seq, now)
                        self._last[peer] = prev
                    since = prev[1] if prev is not None else self._started_at
                    age = now - since
                    worst = max(worst, age)
                    if age > self._staleness and peer not in self._dead:
                        self._dead[peer] = age if prev is not None else None
                        self._m_peers_down.inc()
                        print(
                            f"[cluster] peer {peer} declared down: no "
                            f"heartbeat for {age:.1f}s "
                            f"(budget {self._staleness}s)",
                            flush=True,
                        )
            self._m_hb_age.set(worst)

    def check(self, what=None):
        """Raise typed `PeerDown` if any peer is past the staleness budget
        — the deadline check run at step boundaries, at collective entry
        (`parallel.mesh.checked_collective`), and inside every cluster/
        barrier wait, so a dead peer can never wedge a collective for the
        full barrier timeout. Safe from any thread."""
        with self._lock:
            if not self._dead:
                return
            peer = sorted(self._dead)[0]
            age = self._dead[peer]
        raise PeerDown(peer, age, budget=self._staleness, where=what)

    def peers_down(self):
        with self._lock:
            return dict(self._dead)

    def _wait(self, predicate, what, timeout=None, stop_escape=False):
        """`distributed._wait_for` with the health check folded into every
        poll: a dead peer raises `PeerDown` promptly instead of burning
        the whole timeout. Returns the predicate's first truthy value.

        ``stop_escape``: return None as soon as the cluster stop flag is
        up. Consensus rounds use it to resolve the drain-entry race — a
        host that saw the flag first skipped this round entirely, so the
        value being waited for will never arrive; abandoning (and
        skipping the save) converges every host on "skip" instead of
        burning the timeout against a peer that already moved on.
        """
        timeout = self._consensus_timeout if timeout is None else timeout
        deadline = time.monotonic() + timeout
        while True:
            value = predicate()
            if value:
                return value
            self.check(what)
            if stop_escape and self.stop_requested():
                return None
            if time.monotonic() >= deadline:
                raise ClusterError(
                    f"cluster wait timed out after {timeout}s "
                    f"waiting for {what}"
                )
            time.sleep(self._poll)

    # --- coordinated preemption (stop flag + drain) --------------------------

    @property
    def _stop_request_path(self):
        return os.path.join(self._dir, "stop_request.json")

    def publish_stop(self, reason="signal"):
        """Durably publish the cluster-wide stop flag (idempotent).

        LOCK-FREE by design: `PreemptionGuard(cluster=...)` calls this
        from inside a signal handler that may have interrupted a step
        thread holding the supervisor lock — taking it here would
        self-deadlock. The write is a bounded durable rename; a racing
        double-publish is harmless (same flag, last rename wins).
        """
        self._stop_local.set()
        if os.path.exists(self._stop_request_path):
            return
        # the kill window: a host dying before the flag lands has
        # requested nothing — peers keep training
        faultinject.fire("cluster.stopflag")
        _write_json(
            self._stop_request_path,
            {"from": self._p, "reason": str(reason), "time": time.time()},
        )
        print(
            f"[cluster] stop flag published by process {self._p} ({reason})",
            flush=True,
        )

    def stop_requested(self):
        """Whether any host published the stop flag. A set local event
        short-circuits; otherwise one throttled ``os.path.exists`` per
        ``stop_poll_s`` — the steady-state per-step cost is a monotonic
        clock read. Lock-free (single step-thread consumer + the signal
        handler's event set)."""
        if self._stop_local.is_set():
            return True
        now = time.monotonic()
        if now - self._stop_poll_last < self._stop_poll_s:
            return False
        self._stop_poll_last = now
        if os.path.exists(self._stop_request_path):
            self._stop_local.set()
            return True
        return False

    def drain_step(self, boundary, interval=1):
        """Advance the coordinated-drain state machine; step-thread only.

        NON-BLOCKING by design. Call at EVERY step boundary once
        `stop_requested()` is true, with the host's current step number
        and the collective save interval (``save_every_steps``, >= 1).
        The first call acks the flag with ``boundary``; the host then
        KEEPS TRAINING — blocking here would deadlock against a peer
        that has not seen the flag yet and walks into the next
        collective save barrier expecting this host to join it. The
        collective save schedule both keeps the cluster live while the
        acks settle and bounds inter-host step skew to about one
        ``interval``. Once all acks are visible, the leader publishes
        ``stop_go`` with ``max(acks, own boundary) + interval + 2`` —
        ahead of every host's possible position at publish time, so no
        host has already trained past it. Returns the agreed drain step
        once published (train until the boundary reaches it, then write
        the final collective save there: every host commits the SAME
        step), else None — keep training. Raises `PeerDown` if a peer
        dies mid-protocol (the ack that never arrives).
        """
        if self._drain_at is not None:
            return self._drain_at
        go_path = os.path.join(self._dir, "stop_go.json")
        if self._stop_acked_at is None:
            self._stop_acked_at = int(boundary)
            _write_json(
                os.path.join(self._dir, f"stop_ack_{_proc_tag(self._p)}.json"),
                {"proc": self._p, "boundary": int(boundary)},
            )
        if self._p == 0 and not os.path.exists(go_path):
            acks = [
                _read_json(
                    os.path.join(self._dir, f"stop_ack_{_proc_tag(q)}.json")
                )
                for q in range(self._n)
            ]
            if all(a is not None for a in acks):
                # margin: one `interval` for the skew the collective save
                # schedule permits, +2 boundaries so the leader's notice
                # of the last ack and the ackers' next go-poll both land
                # before any host can reach the agreed step
                agreed = max(
                    [int(boundary)] + [int(a["boundary"]) for a in acks]
                ) + max(int(interval), 1) + 2
                _write_json(go_path, {"step": agreed})
        self.check("coordinated drain")
        go = _read_json(go_path)
        if go is None:
            return None
        self._drain_at = int(go["step"])
        print(
            f"[cluster] coordinated drain: all hosts stop at step "
            f"{self._drain_at}",
            flush=True,
        )
        return self._drain_at

    # --- save-cursor consensus (the coalesce arbiter) ------------------------

    def agree_save_cursor(self, step, busy):
        """One propose/ack round on an overlapped save cursor; returns
        True to SAVE, False to SKIP — identical on every host. Step-thread
        only; wired as ``AsyncCheckpointer(coalesce_arbiter=...)``.

        Each host durably proposes whether its writer queue is busy; the
        leader decides SKIP if ANY host is (the host that would otherwise
        backpressure instead coalesces — on every host at once) and SAVE
        only when all are free. Rounds are numbered by call order, which
        the deterministic save schedule keeps identical across hosts.

        A drain in progress (`stop_requested`) skips without a round:
        flag visibility skews across hosts, so a peer may already have
        skipped this round at entry and its proposal will never come —
        every wait below escapes on the flag for the same reason, and
        both paths converge on SKIP (consistent: the coordinated final
        save at the drain step is the one that matters, and the flag
        never clears, so round numbering can never diverge between two
        LIVE rounds).
        """
        if self.stop_requested():
            return False
        r = self._round
        self._round += 1
        tag = f"r{r:06d}"
        # the kill window peers must survive typed: a host dying before
        # its proposal leaves the leader waiting -> PeerDown via _wait
        faultinject.fire("cluster.propose")
        _write_json(
            os.path.join(self._rounds_dir, f"{tag}_{_proc_tag(self._p)}.json"),
            {"round": r, "step": int(step), "busy": bool(busy)},
        )
        decision_path = os.path.join(self._rounds_dir, f"{tag}_decision.json")
        if self._p == 0:
            prop_paths = [
                os.path.join(self._rounds_dir, f"{tag}_{_proc_tag(q)}.json")
                for q in range(self._n)
            ]

            def _all_props():
                props = [_read_json(pp) for pp in prop_paths]
                return props if all(p is not None for p in props) else None

            props = self._wait(
                _all_props, f"consensus round {r} proposals",
                stop_escape=True,
            )
            if props is None:  # drain started mid-round: abandon -> SKIP
                return False
            save = not any(bool(p["busy"]) for p in props)
            # the leader-dies-before-deciding window: followers wait on
            # the decision file -> PeerDown, drilled at cluster.ack
            faultinject.fire("cluster.ack")
            _write_json(
                decision_path,
                {"round": r, "step": int(step), "save": save},
            )
            self._prune_rounds(r)
        decision = self._wait(
            lambda: _read_json(decision_path),
            f"consensus round {r} decision",
            stop_escape=True,
        )
        if decision is None:  # drain started mid-round: abandon -> SKIP
            return False
        self._m_rounds.inc()
        return bool(decision["save"])

    def _prune_rounds(self, current, keep=8):
        """Best-effort cleanup of rendezvous files from long-settled
        rounds (leader only; every host has read them by ``keep`` rounds
        later — consensus rounds are strictly ordered on each host)."""
        cutoff = current - keep
        if cutoff < 0:
            return
        try:
            names = os.listdir(self._rounds_dir)
        except OSError:
            return
        for name in names:
            if not name.startswith("r") or len(name) < 7:
                continue
            try:
                r = int(name[1:7])
            except ValueError:
                continue
            if r < cutoff:
                try:
                    os.remove(os.path.join(self._rounds_dir, name))
                except OSError:
                    pass  # nclint: disable=swallowed-exception -- cleanup race: a peer's prune already removed it


# --- elastic restart ---------------------------------------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class ElasticSupervisor:
    """Run training as a child process; on a typed `PeerDown` exit
    (:data:`EXIT_PEER_DOWN`), re-form the cluster at the surviving
    topology and relaunch — resuming from the latest valid save.

    The child receives its topology in ``NCNET_ELASTIC_RUN`` /
    ``NCNET_ELASTIC_GEN`` / ``NCNET_ELASTIC_PID`` /
    ``NCNET_ELASTIC_NPROCS`` / ``NCNET_ELASTIC_COORD``;
    ``build_argv(topology)`` maps that dict to the child command line
    (``scripts/train.py --elastic`` appends the resume checkpoint for
    generations > 0). Exit-code contract: 0 propagates (done),
    ``EXIT_PEER_DOWN`` re-forms and relaunches (at most ``max_restarts``
    times), anything else propagates unchanged — a kill stays a kill.

    Re-formation: each surviving supervisor durably writes
    ``reform_gen<G>_proc<P>`` (keyed by ORIGINAL process index — the
    stable identity across generations) and waits ``reform_window_s``;
    the survivors present after the window re-rank by original index,
    rank 0 picks a free port and publishes ``coord_gen<G>.json`` from
    its recorded hostname, and everyone relaunches. A survivor missing
    the window is excluded (bounded-join semantics); a single survivor
    relaunches as a plain single-process run (no coordinator).
    """

    def __init__(
        self,
        cluster_dir,
        build_argv,
        process_index,
        process_count,
        coordinator=None,
        max_restarts=3,
        reform_window_s=5.0,
        poll_interval_s=0.05,
    ):
        self._base = os.path.abspath(cluster_dir)
        self._build_argv = build_argv
        self._orig_pid = int(process_index)  # stable across generations
        self._pid = int(process_index)
        self._n = int(process_count)
        self._coord = coordinator
        self._max_restarts = int(max_restarts)
        self._window = float(reform_window_s)
        self._poll = float(poll_interval_s)

    def _topology(self, gen):
        return {
            "generation": gen,
            "process_index": self._pid,
            "process_count": self._n,
            "coordinator": self._coord,
        }

    def run(self):
        """Supervise until the training run completes or fails
        non-elastically; returns the exit status to propagate."""
        gen, restarts = 0, 0
        while True:
            topo = self._topology(gen)
            env = dict(
                os.environ,
                NCNET_ELASTIC_RUN="1",
                NCNET_ELASTIC_GEN=str(gen),
                NCNET_ELASTIC_PID=str(self._pid),
                NCNET_ELASTIC_NPROCS=str(self._n),
                NCNET_ELASTIC_COORD=self._coord or "",
            )
            print(
                f"[elastic] gen {gen}: launching process "
                f"{self._pid}/{self._n}"
                + (f" (coordinator {self._coord})" if self._coord else ""),
                flush=True,
            )
            child = subprocess.Popen(self._build_argv(topo), env=env)
            rc = child.wait()
            if rc != EXIT_PEER_DOWN:
                if rc != 0:
                    print(f"[elastic] child exited {rc}: propagating "
                          "(only a typed PeerDown restarts)", flush=True)
                return rc
            restarts += 1
            if restarts > self._max_restarts:
                print(
                    f"[elastic] restart budget exhausted "
                    f"({self._max_restarts}); giving up",
                    flush=True,
                )
                return rc
            gen += 1
            self._reform(gen)

    def _reform(self, gen):
        _write_json(
            os.path.join(
                self._base, f"reform_gen{gen:03d}_{_proc_tag(self._orig_pid)}.json"
            ),
            {"orig": self._orig_pid, "host": socket.gethostname(),
             "pid": os.getpid()},
        )
        time.sleep(self._window)
        survivors = []
        prefix = f"reform_gen{gen:03d}_proc"
        for name in sorted(os.listdir(self._base)):
            if name.startswith(prefix) and name.endswith(".json"):
                blob = _read_json(os.path.join(self._base, name))
                if blob is not None:
                    survivors.append((int(blob["orig"]), blob))
        survivors.sort()
        ranks = [orig for orig, _ in survivors]
        if self._orig_pid not in ranks:
            # our own durable write should always be visible post-window;
            # if not, the shared filesystem is gone — nothing to re-form
            raise ClusterError(
                f"re-formation gen {gen}: own reform file missing "
                f"from {self._base}"
            )
        self._pid = ranks.index(self._orig_pid)
        self._n = len(ranks)
        coord_path = os.path.join(self._base, f"coord_gen{gen:03d}.json")
        if self._n == 1:
            self._coord = None
        elif self._pid == 0:
            host = dict(survivors)[self._orig_pid]["host"]
            self._coord = f"{host}:{_free_port()}"
            _write_json(coord_path, {"addr": self._coord})
        else:
            deadline = time.monotonic() + self._window * 4
            while True:
                blob = _read_json(coord_path)
                if blob is not None:
                    self._coord = blob["addr"]
                    break
                if time.monotonic() >= deadline:
                    raise ClusterError(
                        f"re-formation gen {gen}: no coordinator from "
                        f"survivor rank 0 within {self._window * 4}s"
                    )
                time.sleep(self._poll)
        print(
            f"[elastic] re-formed gen {gen}: {self._n} survivor(s), "
            f"this host is now process {self._pid}"
            + (f", coordinator {self._coord}" if self._coord else ""),
            flush=True,
        )
