"""Durable artifact writes: temp + fsync + atomic rename, digests, retention.

The failure this module exists for: a preemption landing mid-``write()``
of the ONLY resume point. A plain ``open(path, "wb")`` rewrite leaves a
torn file — history and weights both gone. Here every write goes

  1. to a temp file in the same directory (same filesystem, so rename is
     atomic), fully written and ``fsync``'d;
  2. ``os.replace`` onto the final name — readers see the old bytes or the
     new bytes, never a mixture;
  3. a sidecar ``<path>.sha256`` (written the same way) records the
     payload digest, so silent corruption (bitrot, torn pre-durability
     files, a truncating copy) is DETECTED at load instead of surfacing
     as a confusing deserialization error;
  4. the parent directory is fsync'd so the rename itself survives a
     crash.

Retention keeps the last K step-tagged copies (``<path>.step<N>``,
hardlinked — no extra bytes) so a reader can walk BACK past an invalid
latest file: `candidates` yields paths newest-first and `latest_valid`
returns the first one whose payload verifies.

Kill-window semantics (tested via `faultinject`): a kill before the
rename leaves the previous artifact untouched; a kill between the data
rename and the sidecar rename leaves a digest mismatch, so the new file
is treated as invalid and recovery falls back one artifact — conservative
by design.

Threading contract: these functions are thread-agnostic — the discipline
is identical whichever thread runs it, and with async checkpointing
(`resilience.async_ckpt`) the whole sequence runs on the dedicated
writer thread. At-most-one-writer PER PATH is the caller's job; the
`AsyncCheckpointer` enforces it for checkpoints (one save in flight,
ever), so concurrent temp files never collide.
"""

import hashlib
import os
import re

from ncnet_tpu.resilience import faultinject

DIGEST_SUFFIX = ".sha256"

_STEP_RE = re.compile(r"\.step(\d+)$")


class IntegrityError(RuntimeError):
    """An artifact's bytes do not match its recorded digest."""


def digest_path(path):
    return path + DIGEST_SUFFIX


def _fsync_dir(dirname):
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return  # platforms/filesystems without directory fds: best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_atomic(path, blob, mid_write_point=None, rename_point=None):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        if mid_write_point:
            # the torn-write window: half the payload is on disk
            f.write(blob[: len(blob) // 2])
            faultinject.fire(mid_write_point)
            f.write(blob[len(blob) // 2:])
        else:
            f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    if rename_point:
        # temp complete + fsynced, the publish rename still pending
        faultinject.fire(rename_point)
    os.replace(tmp, path)


def durable_write_bytes(
    path,
    blob,
    write_point="checkpoint.write",
    rename_point="checkpoint.rename",
    bytes_point="checkpoint.bytes",
):
    """Durably write ``blob`` to ``path`` with a sidecar digest.

    The digest is computed over the INTENDED bytes before any injected
    corruption, so the ``bytes_point`` fault models disk damage that
    verification must catch. Callers with their own failure-drill
    vocabulary (e.g. the sharded layout's ``dckpt.*`` points) override the
    point names; ``None`` disables that window's hook.
    """
    path = os.path.abspath(path)
    dirname = os.path.dirname(path)
    os.makedirs(dirname, exist_ok=True)
    digest = hashlib.sha256(blob).hexdigest()
    if bytes_point:
        blob = faultinject.fire(bytes_point, blob)
    _write_atomic(
        path, blob,
        mid_write_point=write_point,
        rename_point=rename_point,
    )
    _write_atomic(digest_path(path), digest.encode("ascii"))
    _fsync_dir(dirname)
    return path


def link_or_copy(src, dst):
    """Publish ``dst`` (+ sidecar) as a hardlink to the already-durable
    ``src`` — O(1) bytes where the filesystem supports links, falling back
    to a copy where it does not. Used for ``best_`` pointers: the source
    artifact is already committed, so re-serializing the payload would be
    pure O(state) waste."""
    for s in (src, digest_path(src)):
        d = dst if s == src else digest_path(dst)
        if not os.path.exists(s):
            continue
        try:
            if os.path.exists(d):
                os.remove(d)
            os.link(s, d)
        except OSError:
            import shutil

            shutil.copyfile(s, d)
    _fsync_dir(os.path.dirname(os.path.abspath(dst)))


def verify_digest(path):
    """``True``/``False`` when a sidecar digest exists and matches/differs;
    ``None`` when there is no sidecar (a pre-durability legacy file)."""
    dpath = digest_path(path)
    if not os.path.exists(dpath):
        return None
    with open(dpath, "rb") as f:
        want = f.read().strip().decode("ascii", errors="replace")
    with open(path, "rb") as f:
        got = hashlib.sha256(f.read()).hexdigest()
    return got == want


def read_verified_bytes(path):
    """Read ``path``, raising :class:`IntegrityError` on digest mismatch.

    Legacy files without a sidecar are returned as-is (the caller's parser
    is the only check available for them).
    """
    ok = verify_digest(path)
    if ok is False:
        raise IntegrityError(
            f"{path} does not match its recorded digest "
            f"({digest_path(path)}); treating as corrupt"
        )
    with open(path, "rb") as f:
        return f.read()


def step_path(path, step):
    return f"{path}.step{int(step):09d}"


def retain(path, step, keep=3):
    """Hardlink ``path`` (+ sidecar) to its step-tagged history name and
    prune history beyond the newest ``keep`` entries. ``keep <= 0``
    disables retention entirely.

    Hardlinks cost no bytes; the newest history entry shares its inode
    with the primary until the NEXT save replaces the primary (os.replace
    allocates a new inode, leaving history pointing at the old one). The
    durable writer never modifies files in place, so the only shared-fate
    hazard is bitrot of that one inode — which the walk-back then skips,
    at the cost of one extra fallback step."""
    if keep <= 0:
        return
    hist = step_path(path, step)
    for src in (path, digest_path(path)):
        dst = hist if src == path else digest_path(hist)
        if not os.path.exists(src):
            continue
        try:
            if os.path.exists(dst):
                os.remove(dst)
            os.link(src, dst)
        except OSError:
            import shutil

            shutil.copyfile(src, dst)
    steps = sorted(_history_steps(path), reverse=True)
    for old in steps[keep:]:
        for stale in (step_path(path, old), digest_path(step_path(path, old))):
            try:
                os.remove(stale)
            except FileNotFoundError:
                pass
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def _history_steps(path):
    dirname = os.path.dirname(os.path.abspath(path))
    base = os.path.basename(path)
    steps = []
    try:
        names = os.listdir(dirname)
    except FileNotFoundError:
        return steps
    for name in names:
        if not name.startswith(base) or name.endswith(DIGEST_SUFFIX):
            continue
        m = _STEP_RE.search(name)
        if m and name == base + f".step{m.group(1)}":
            steps.append(int(m.group(1)))
    return steps


def candidates(path):
    """Resume candidates newest-first: the primary file, then step-tagged
    history in descending step order."""
    out = []
    if os.path.exists(path):
        out.append(path)
    for step in sorted(_history_steps(path), reverse=True):
        out.append(step_path(path, step))
    return out


def latest_valid(path, loader):
    """Walk `candidates` newest-first, returning ``(loader(p), p)`` for the
    first one that verifies AND parses; a torn/corrupt latest file costs
    one fallback, not the run. Raises ``FileNotFoundError`` when nothing
    loads."""
    errors = []
    for cand in candidates(path):
        try:
            if verify_digest(cand) is False:
                raise IntegrityError(f"{cand}: digest mismatch")
            return loader(cand), cand
        except Exception as e:  # a corrupt candidate must not end the walk
            errors.append(f"{cand}: {e!r}")
            print(f"[resilience] skipping invalid artifact {cand}: {e!r}",
                  flush=True)
    detail = "; ".join(errors) if errors else "no candidate files exist"
    raise FileNotFoundError(f"no valid artifact for {path} ({detail})")
