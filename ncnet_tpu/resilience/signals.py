"""Preemption signals -> a checkpoint-once-and-exit-cleanly flag.

Cloud TPU preemption is delivered as SIGTERM with a grace window; an
interactive Ctrl-C is SIGINT. Both mean the same thing to a training
loop: finish the current step, write one durable checkpoint with a resume
cursor, and return — not die mid-write. `PreemptionGuard` converts the
first signal into a flag the loop polls at step boundaries; a SECOND
signal falls through to the previous handler (so a stuck run still dies
on a double Ctrl-C).

Second-signal flush hooks: with async checkpointing
(resilience.async_ckpt) the final cursor save may still be in flight on
the writer thread when the second signal lands. Falling through
immediately would kill the process mid-write and ORPHAN that save (the
walk-back contract keeps recovery correct, but the final cursor is
lost). The loop registers a bounded flush hook (`add_flush_hook`); the
second-signal path restores the previous handlers FIRST — a third
signal during the grace still kills instantly — then drains the hooks
best-effort, then re-delivers. Hooks must be bounded and reentrant-safe
(they run inside a signal handler, possibly interrupting the very flush
they call into).

Cluster mode: a preemption SIGTERM lands on ONE host of a multi-host
run, but every host must drain to the same final save step or the
resumed run diverges. With a `resilience.cluster.ClusterSupervisor`
bound (``PreemptionGuard(cluster=...)`` or `bind_cluster`), the first
signal ALSO publishes the cluster's durable stop flag — lock-free and
best-effort (publishing must never turn a clean drain into a handler
crash) — so the signal reaches every peer via the shared filesystem and
the loop's `drain_step` round lands all hosts on one step.

Only the main thread may install signal handlers; constructing the guard
elsewhere (or where handlers are unavailable) degrades to a never-set
flag rather than crashing — a loop guarded in a worker context simply
never sees a preemption request.
"""

import signal
import threading


class PreemptionGuard:
    """Context manager: ``guard.requested`` flips on SIGTERM/SIGINT.

    >>> with PreemptionGuard() as guard:
    ...     for batch in loader:
    ...         step(batch)
    ...         if guard.requested:
    ...             checkpoint_and_return()
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 cluster=None):
        self.signals = tuple(signals)
        self._requested = threading.Event()
        self._previous = {}
        self._installed = False
        self._flush_hooks = []
        self._cluster = cluster

    def bind_cluster(self, cluster):
        """Attach (or detach, with None) a cluster supervisor whose
        durable stop flag the first signal publishes — a preemption on
        this host then drains EVERY host (module docstring)."""
        self._cluster = cluster

    @property
    def requested(self):
        return self._requested.is_set()

    def request(self):
        """Programmatic preemption (tests, in-process orchestrators)."""
        self._requested.set()
        self._publish_cluster_stop("programmatic request")

    def _publish_cluster_stop(self, reason):
        # best-effort and lock-free (cluster.publish_stop's contract):
        # this runs inside the signal handler, and a shared-filesystem
        # error must not turn a clean local drain into a handler crash —
        # the loop's step-boundary publish retries via stop_requested()
        if self._cluster is None:
            return
        try:
            self._cluster.publish_stop(reason=reason)
        except Exception as e:
            print(
                f"[resilience] cluster stop-flag publish failed: {e!r}",
                flush=True,
            )

    def add_flush_hook(self, hook):
        """Register a bounded callable drained before a second signal is
        re-delivered (e.g. ``lambda: ackpt.flush(timeout=5, reraise=False)``
        — don't let the in-flight final save die half-written). Hooks run
        inside a signal handler: keep them short, never let them raise
        for control flow."""
        self._flush_hooks.append(hook)

    def remove_flush_hook(self, hook):
        try:
            self._flush_hooks.remove(hook)
        except ValueError:
            pass

    def _handle(self, signum, frame):
        if self._requested.is_set():
            # second signal: restore FIRST (a third signal during the
            # flush grace kills instantly — impatient operators and
            # process supervisors keep their kill semantics), then give
            # any in-flight durable write its bounded chance to commit,
            # then re-deliver
            self._restore()
            for hook in list(self._flush_hooks):
                try:
                    hook()
                except Exception as e:  # a failed flush must not block death
                    print(f"[resilience] flush hook failed: {e!r}", flush=True)
            signal.raise_signal(signum)
            return
        self._requested.set()
        print(
            f"[resilience] received signal {signum}: will checkpoint at the "
            "next step boundary and exit cleanly (signal again to force)",
            flush=True,
        )
        self._publish_cluster_stop(f"signal {signum}")

    def __enter__(self):
        try:
            for sig in self.signals:
                self._previous[sig] = signal.signal(sig, self._handle)
            self._installed = True
        except ValueError:
            # not the main thread / interpreter without handler support:
            # run unguarded rather than refusing to train
            self._previous.clear()
        return self

    def _restore(self):
        if not self._installed:
            return
        for sig, old in self._previous.items():
            try:
                signal.signal(sig, old)
            except ValueError:
                pass
        self._previous.clear()
        self._installed = False

    def __exit__(self, *exc):
        self._restore()
        return False
