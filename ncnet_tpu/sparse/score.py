"""Weak-supervision match scores on a correlation band.

``band_match_score_per_sample`` is the band variant of
``train.loss.match_score_per_sample``: scores are computed ON the band —
off-band cells carry no probability mass (softmax), no L1 mass, and no
max candidates — and the per-B direction averages over COVERED B-cells
only (cells no band entry lands on have no defined score; at
``K = hB*wB`` every cell is covered and both directions reduce to the
dense score bitwise).

The band is expanded to the masked dense ``[b, nA, nB]`` score tensor at
this boundary: the expansion is one static scatter of an O(corr)-sized
1-channel tensor — the same size as the raw correlation the selection
already materialized, and ~k^4*c times smaller than what the NC stack
avoids — so the hot path stays sparse while the score math reuses the
exact dense expression structure (the full-K bitwise contract).
"""

import jax
import jax.numpy as jnp

from ncnet_tpu.ops.band import band_coverage, band_to_dense


def normalize_scores(x, axis, normalization):
    """Score normalization shared by the dense and band losses (the
    reference's softmax/l1/none choice, train.py:110-134)."""
    if normalization is None or normalization == "none":
        return x
    if normalization == "softmax":
        return jax.nn.softmax(x, axis=axis)
    if normalization == "l1":
        return x / (jnp.sum(x, axis=axis, keepdims=True) + 1e-4)
    raise ValueError(f"unknown score normalization {normalization!r}")


def band_match_score_per_sample(values, indices, grid_b,
                                normalization="softmax"):
    """Per-sample best normalized match score, both directions averaged.

    Args:
      values: ``[b, hA, wA, K]`` filtered band (f32, post mutual
        matching).
      indices: ``[b, hA, wA, K]`` int32 sorted B-indices.
      grid_b: static ``(hB, wB)``.
      normalization: 'softmax' (reference default) | 'l1' | 'none'.

    Returns:
      ``[b]`` scores, the band counterpart of
      ``match_score_per_sample(corr, normalization)``.
    """
    b, ha, wa, k = values.shape
    hb, wb = grid_b
    # softmax needs off-band entries at -inf (zero mass, exp(-inf) == 0
    # exactly); the additive l1/none statistics need them at 0
    fill = -jnp.inf if normalization == "softmax" else 0.0
    dense = band_to_dense(values, indices, grid_b, fill=fill)
    covered = band_coverage(indices, grid_b)

    b_avec = dense.reshape(b, ha * wa, hb, wb)  # scores over A per B cell
    a_bvec = dense.reshape(b, ha, wa, hb * wb)  # scores over B per A cell
    scores_b = jnp.max(normalize_scores(b_avec, 1, normalization), axis=1)
    scores_a = jnp.max(normalize_scores(a_bvec, 3, normalization), axis=3)

    # every A-cell holds K >= 1 band entries: plain mean. B-cells only
    # average where covered (an all-(-inf) softmax column is NaN by
    # construction — masked out here, impossible at full K). The masked
    # mean is jnp.mean over the zero-filled scores RESCALED by
    # nB/covered-count: at full coverage the factor is exactly 1.0 (a
    # bitwise identity — jnp.mean must be called, not decomposed into
    # sum/n, because XLA's fused mean reduction rounds differently from
    # a standalone reduce_sum followed by a div, which was measured to
    # break the full-K bitwise contract by 1 ulp).
    count = jnp.sum(covered, axis=(1, 2)).astype(scores_b.dtype)
    nb_total = jnp.asarray(float(hb * wb), scores_b.dtype)
    scores_b = jnp.where(covered, scores_b, jnp.zeros((), scores_b.dtype))
    mean_b = jnp.mean(scores_b, axis=(1, 2)) * (nb_total / count)  # nclint: disable=unguarded-division -- count >= 1 by construction (K >= 1 band entries per A-cell always cover at least one B-cell), and an epsilon would break the exact-1.0 full-coverage factor
    return (jnp.mean(scores_a, axis=(1, 2)) + mean_b) / 2
