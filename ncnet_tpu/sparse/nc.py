"""Submanifold neighbourhood-consensus stack on a top-K correlation band.

Each layer is ONE gathered dense GEMM per pass: gather every band entry's
``k^4`` conv-window neighbours (off-band/off-grid reads are exact zeros)
into ``[b, N, k^4*c_in]`` with ``N = hA*wA*K`` and contract with the
flattened kernel ``[k^4*c_in, c_out]`` — full-width MXU rows, no Toeplitz
FLOP inflation, analytic FLOPs ``2 * (hA*wA) * K * k^4 * c_in * c_out``
per layer versus the dense ``(hB*wB)/K``-times-larger count.

Symmetric mode never builds a B-major band REPRESENTATION: restricted to
the band support, ``T(net(T(x)))`` equals running the same flattened
kernels over a gather whose taps take the A/B roles swapped
(`band_neighbor_pointers(swapped=True)`). The swapped pass runs over the
band entries ENUMERATED B-major (a stable argsort of the band's
B-indices — pure placement): term-for-term and row-for-row that is
exactly the dense transposed pass, which is what keeps the full-K eager
equivalence against the dense ``'gemm4/gemm4'`` / ``symmetric_batch=
False`` reference bitwise-tight through training (losses AND updated NC
params), not merely allclose — see tests/test_sparse.py.

The pointer tables depend only on the band indices and each layer's
kernel size, so they are built once per band (per distinct kernel size)
and shared by every layer.
"""

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ncnet_tpu.analysis import sanitizer
from ncnet_tpu.ops.band import band_conv_gemm, band_neighbor_pointers

# the gather+GEMM primitive lives in ops.band (shared with the fused
# Pallas kernel's gather-only VJP — one definition of the contraction)
_band_conv_impl = band_conv_gemm


@jax.custom_vjp
def _band_conv(x_entries, w, ptr):
    """`_band_conv_impl` with a scatter-free custom VJP.

    Autodiff's transpose of the neighbour gather is a scatter-add whose
    per-destination accumulation order differs from the dense conv
    transpose (and scatters are the slow path on TPU). On the FIXED band
    support there is a gather-only identity instead: the cotangent of
    entry ``e`` sums contributions from entries whose tap window covers
    ``e`` — exactly a submanifold conv of the output cotangent with the
    spatially-flipped, channel-transposed kernel over the SAME pointer
    table (flipping the kernel negates every tap offset; odd kernels
    only, like the dense composite dx). This keeps the backward
    scatter-free AND makes it the arithmetic mirror of the dense
    ``'gemm4/gemm4'`` composite — term-for-term, which is what the
    full-K bitwise training-equivalence contract of tests/test_sparse.py
    holds against.
    """
    return _band_conv_impl(x_entries, w, ptr)


def _band_conv_fwd(x_entries, w, ptr):
    return _band_conv_impl(x_entries, w, ptr), (x_entries, w, ptr)


def _band_conv_bwd(res, gy):
    x_entries, w, ptr = res
    if any(int(k) % 2 == 0 for k in w.shape[:4]):
        # the flipped-kernel dx identity needs symmetric tap offsets
        # (raise, not assert: must survive python -O)
        raise ValueError(
            f"sparse band conv requires odd kernel sizes, got {w.shape[:4]}"
        )
    wflip = jnp.flip(w, axis=(0, 1, 2, 3)).transpose(0, 1, 2, 3, 5, 4)
    dx = _band_conv_impl(gy, wflip.astype(gy.dtype), ptr)
    dx = dx.astype(x_entries.dtype)
    # kernel gradient: linear transpose of the forward wrt w (conv is
    # linear in w) — the gather is recomputed (integer-indexed copy)
    # rather than saved, and the transpose machinery emits the same
    # swapped-operand dot the dense composite's does (an explicit
    # 'bnf,bno->fo' einsum was measured NOT bitwise against it: XLA picks
    # a different reduction strategy per operand order)
    transpose_w = jax.linear_transpose(
        lambda ww: _band_conv_impl(x_entries, ww, ptr), w
    )
    (dw,) = transpose_w(gy)
    return dx, dw, None


_band_conv.defvjp(_band_conv_fwd, _band_conv_bwd)


def sparse_neigh_consensus_apply(params, values, indices, grid_b,
                                 symmetric=True, band_impl="xla"):
    """Filter a correlation band with the learned NC stack.

    Args:
      params: `init_neigh_consensus` layer list (same params as the dense
        stack — the sparse path is an inference/training-time
        reformulation, not a different model).
      values: ``[b, hA, wA, K]`` band values (no channel axis).
      indices: ``[b, hA, wA, K]`` int32 sorted B-indices (`topk_band`).
      grid_b: static ``(hB, wB)`` of the B feature grid.
      symmetric: reference ``symmetric_mode`` — adds the transposed-pass
        term via the swapped-tap gather (works for rectangular A/B grids
        too: nothing is ever transposed, only tap roles).
      band_impl: ``'xla'`` (default: the eager gather+GEMM composite) or
        ``'pallas'`` — the fused gather+GEMM+bias+ReLU TPU kernel
        (``ncnet_tpu/kernels/band_gemm_pallas.py``). ``'pallas'`` on a
        non-TPU backend silently resolves back to ``'xla'`` (the serve /
        recompile contracts never see a broken lowering); set
        ``NCNET_BAND_PALLAS_INTERPRET=1`` to force the kernel through
        the Pallas interpreter instead (CPU integration tests).

    Returns:
      ``[b, hA, wA, K]`` filtered band on the SAME support (submanifold
      semantics; final layer must have 1 output channel).
    """
    dtype = values.dtype
    b, ha, wa, k = values.shape
    n = ha * wa * k

    if band_impl not in ("xla", "pallas"):
        raise ValueError(
            f"band_impl={band_impl!r}: expected 'xla' or 'pallas'"
        )
    fused_band = None
    if band_impl == "pallas":
        from ncnet_tpu.kernels.band_gemm_pallas import (
            band_conv_bias_relu_pallas,
            resolve_band_impl,
        )

        if resolve_band_impl(band_impl) != "xla":
            interpret = resolve_band_impl(band_impl) == "pallas_interpret"

            def fused_band(xp, w, bias, ptr):
                return band_conv_bias_relu_pallas(
                    xp, w, bias, ptr, interpret=interpret
                )

    ptr_cache = {}

    def pointers(kernel, swapped):
        key = (kernel, swapped)
        if key not in ptr_cache:
            ptr_cache[key] = band_neighbor_pointers(
                indices, grid_b, kernel, swapped=swapped
            ).reshape(b, n, -1)
        return ptr_cache[key]

    def net(x_entries, ptr_for, tag):
        xp = x_entries
        for li, p in enumerate(params):
            w = p["kernel"]
            if fused_band is not None:
                # one fused kernel per layer: gather + GEMM + bias + ReLU
                # never round-trip through HBM; the save-policy tag moves
                # to the post-ReLU activation (the pre-activation never
                # exists as a program value)
                xp = fused_band(
                    xp, w, p["bias"], ptr_for(tuple(w.shape[:4]))
                )
                xp = checkpoint_name(xp, "nc_conv")
            else:
                y = _band_conv(xp, w, ptr_for(tuple(w.shape[:4])))
                # params follow the activation dtype and the bias is
                # added once, exactly like the dense conv4d layers
                y = y + p["bias"].astype(dtype)
                # same save-policy tag as the dense stack: the loss-chunk
                # remat saves these GEMM outputs and recomputes only the
                # cheap elementwise rest (train/loss.py)
                y = checkpoint_name(y, "nc_conv")
                xp = jax.nn.relu(y)
            xp = sanitizer.tap(f"nc_layer{li}{tag}", xp)
        return xp

    x = values.reshape(b, n, 1)
    out = net(x, lambda kern: pointers(kern, False), "")

    if symmetric:
        # B-major entry permutation: stable argsort of the B-index, so
        # ties (same B-cell) keep A-major order — row-for-row the dense
        # transposed pass's (iB, jB, iA, jA) row-major enumeration. All
        # pure placement: forward values are unchanged, but GEMM row
        # order (hence the backward's reduction order) matches dense.
        bidx = indices.reshape(b, n)
        perm = jnp.argsort(bidx, axis=-1, stable=True)
        inv = jnp.argsort(perm, axis=-1, stable=True)

        def ptr_swapped(kernel):
            ptr = pointers(kernel, True)
            rows = jnp.take_along_axis(
                ptr, perm[..., None], axis=1, mode="promise_in_bounds"
            )
            # pointer VALUES address the cell-major entry list; remap to
            # the permuted list (the null slot stays the null slot)
            remap = jnp.concatenate(
                [inv.astype(jnp.int32),
                 jnp.full((b, 1), n, jnp.int32)], axis=1
            )
            return jnp.take_along_axis(
                remap, rows.reshape(b, -1), axis=1,
                mode="promise_in_bounds",
            ).reshape(rows.shape)

        x2 = jnp.take_along_axis(
            x, perm[..., None], axis=1, mode="promise_in_bounds"
        )
        out2 = net(x2, ptr_swapped, "_sym")
        out2 = jnp.take_along_axis(
            out2, inv[..., None], axis=1, mode="promise_in_bounds"
        )
        out = out + out2

    if out.shape[-1] != 1:
        raise ValueError("last NeighConsensus layer must have 1 output channel")
    return out[..., 0].reshape(b, ha, wa, k)
