"""End-to-end sparse-band matching pipeline.

Dense pipeline (models/immatchnet.py):  corr -> MM -> NC -> MM.
Sparse pipeline:                        corr -> MM -> top-K band ->
                                        submanifold NC -> band MM.

Selection runs on the RAW correlation (per A-cell `lax.top_k` over the
flattened B grid; optional symmetric/mutual union), the band VALUES carry
the mutual-matching-gated correlation — the same tensor the dense NC
stack consumes, gathered onto the band. Everything downstream of the
(cheap, 1-channel, O(nA*nB)) correlation runs on the dense-regular band:
the k^4-channel NC convolutions — 97.6% of analytic step FLOPs at the
PF-Pascal 400px config — cost O(K/(hB*wB)) of their dense count.

With ``K = hB*wB`` the band is complete and every stage above reproduces
its dense counterpart exactly (the test harness for all smaller K).

``config.corr_impl`` selects how the band is produced: ``'dense'``
(default, and what legacy config dicts get) materializes the full
correlation volume first; ``'stream'`` computes the identical band —
bitwise, values and indices — one B-grid tile at a time
(ops/corr_stream.py), dropping the pipeline's peak memory from
O(hA*wA*hB*wB) to O(hA*wA*(K+tile)).
"""

import jax.numpy as jnp

from ncnet_tpu.analysis import sanitizer
from ncnet_tpu.ops.band import band_to_dense, topk_band
from ncnet_tpu.ops.corr_stream import corr_stream_band
from ncnet_tpu.ops.correlation import correlation_4d
from ncnet_tpu.ops.matching import mutual_matching
from ncnet_tpu.sparse.matching import band_mutual_matching
from ncnet_tpu.sparse.nc import sparse_neigh_consensus_apply

#: correlation->band implementations selectable via ``config.corr_impl``
CORR_IMPLS = ("dense", "stream")


def resolve_corr_impl(config):
    """Validate and return the configured correlation implementation
    (the ``check_sparse_config`` discipline: a bad static config fails
    at construction, not deep inside jit). Legacy configs/dicts without
    the field run the dense path unchanged."""
    impl = getattr(config, "corr_impl", "dense")
    if impl not in CORR_IMPLS:
        raise ValueError(
            f"corr_impl={impl!r} is not one of {CORR_IMPLS}: 'dense' "
            "materializes the full correlation volume, 'stream' tiles "
            "B's grid and selects the band with O(hA*wA*(K+tile)) peak "
            "memory (ops/corr_stream.py)"
        )
    return impl


def resolve_band_width(nc_topk, grid_b):
    """Effective static band width: ``nc_topk`` clamped to the B-grid size
    (so sweep scripts can pass one K across image sizes; ``K >= hB*wB``
    simply runs the complete band)."""
    nb = int(grid_b[0]) * int(grid_b[1])
    k = int(nc_topk)
    if k <= 0:
        raise ValueError(
            f"nc_topk={nc_topk}: the sparse pipeline needs a positive "
            "band width (0 selects the dense path upstream)"
        )
    return min(k, nb)


def sparse_match_pipeline(nc_params, config, feat_a, feat_b):
    """Features -> filtered correlation band.

    Returns ``(values, indices, grid_b)``: the post-NC, post-MM band in
    float32 on the top-K support. Use `sparse_corr_to_dense` for dense
    readout (`corr_to_matches`), `sparse.score.band_match_score_per_sample`
    for the weak loss.
    """
    if config.relocalization_k_size > 1:
        raise ValueError(
            "sparse NC (nc_topk > 0) does not support relocalization "
            "configs: the 4D max-pool offsets are a dense-readout "
            "construct (set relocalization_k_size to 0)"
        )
    dtype = jnp.bfloat16 if config.half_precision else None
    grid_b = (feat_b.shape[1], feat_b.shape[2])
    k = resolve_band_width(config.nc_topk, grid_b)
    mutual = getattr(config, "nc_topk_mutual", True)
    if resolve_corr_impl(config) == "stream":
        # streamed selection is BITWISE equal to the dense branch below
        # (tests/test_corr_stream.py) but never materializes the volume;
        # the sanitizer probes therefore see the selected band, not the
        # full corr/gated tensors (same stage names, band support)
        values, indices = corr_stream_band(
            feat_a, feat_b, k, mutual=mutual,
            tile=getattr(config, "corr_stream_tile", 128),
        )
        values = sanitizer.tap(
            "mutual_matching_pre", sanitizer.tap("correlation", values)
        )
    else:
        corr = correlation_4d(feat_a, feat_b)
        corr = sanitizer.tap("correlation", corr)
        gated = sanitizer.tap("mutual_matching_pre", mutual_matching(corr))
        values, indices = topk_band(
            corr, k, values_from=gated, mutual=mutual,
        )
    if dtype:
        values = values.astype(dtype)
    band = sparse_neigh_consensus_apply(
        nc_params, values, indices, grid_b,
        symmetric=config.symmetric_mode,
        band_impl=getattr(config, "band_impl", "xla"),
    )
    band = sanitizer.tap("neigh_consensus", band)
    band = sanitizer.tap(
        "mutual_matching_post",
        band_mutual_matching(band, indices, grid_b).astype(jnp.float32),
    )
    return band, indices, grid_b


def sparse_corr_to_dense(values, indices, grid_b):
    """Readout densification: the filtered band as a ``[b, hA, wA, hB,
    wB]`` tensor with exact zeros off-band, consumable by the unchanged
    dense readout (`ops.matches.corr_to_matches`, the PCK evals, the
    InLoc dump). One static scatter of the 1-channel output — negligible
    next to the NC stack the band path avoids."""
    return band_to_dense(values, indices, grid_b, fill=0.0)
