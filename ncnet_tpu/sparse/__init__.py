"""TPU-native sparse-band neighbourhood consensus (Sparse-NCNet line).

The dense NC stack is O((hA*wA) * (hB*wB) * k^4 * c) — 97.6% of the
analytic step FLOPs at the PF-Pascal 400px config — yet the 4D
correlation it filters is overwhelmingly noise (arXiv:2004.10566,
arXiv:2012.09842). This package keeps only the top-K B-candidates per
A-cell (a dense-regular band, static shapes under jit, no scatter on the
hot path) and runs the NC stack with submanifold semantics on that band:
output support = input support, off-band neighbours read as exact zeros,
each layer one gathered MXU GEMM — O((hA*wA) * K * k^4 * c) per layer.

Exactness is the design contract: with ``K = hB*wB`` the band is complete
and the sparse path reproduces the dense path (eager: bitwise against the
arithmetic-mirror ``conv4d`` lowering ``'gemm4'``; jitted: ULP-allclose)
— the equivalence harness every smaller K is tested under
(tests/test_sparse.py).

Enable with ``ImMatchNetConfig(nc_topk=K)`` (0 = dense); training, eval
readout, and the weak loss all follow the config.
"""

from ncnet_tpu.sparse.matching import band_mutual_matching
from ncnet_tpu.sparse.nc import sparse_neigh_consensus_apply
from ncnet_tpu.sparse.pipeline import (
    resolve_band_width,
    sparse_corr_to_dense,
    sparse_match_pipeline,
)
from ncnet_tpu.sparse.score import band_match_score_per_sample

__all__ = [
    "band_match_score_per_sample",
    "band_mutual_matching",
    "resolve_band_width",
    "sparse_corr_to_dense",
    "sparse_match_pipeline",
    "sparse_neigh_consensus_apply",
]
