"""Soft mutual-NN filtering on a correlation band.

Band in, band out, submanifold semantics: the gate is computed as if the
band stood in a dense tensor whose off-band cells are exact zeros — which
is literally how it is evaluated: scatter the band into the 1-channel
dense ``[b, nA, nB]`` tensor (the same size the selection's raw
correlation already materialized — the band's memory/FLOP win is the
``k^4 * c``-channel NC stack, not this tensor), apply the DENSE
``ops.matching.mutual_matching``, gather the band entries back.

Routing through the dense op is deliberate: both direction maxima see the
same off-band zeros the dense semantics prescribe, and forward AND
backward are the dense op's own (scatter/gather are pure placement), so
at ``K = hB*wB`` the stage is bitwise-identical to the dense pipeline —
a segment-max formulation was measured to break the full-K
gradient-equivalence contract through different max-tie structure in the
backward (post-ReLU NC outputs carry many exact zeros).
"""

import jax.numpy as jnp

from ncnet_tpu.ops.band import band_to_dense
from ncnet_tpu.ops.matching import mutual_matching


def band_mutual_matching(values, indices, grid_b, eps=1e-5):
    """Mutual-matching gate on band values (`ops.matching.mutual_matching`).

    Args:
      values: ``[b, hA, wA, K]`` band values (post-ReLU NC outputs: the
        implied off-band zeros are a valid floor for both maxima).
      indices: ``[b, hA, wA, K]`` int32 sorted B-indices.
      grid_b: static ``(hB, wB)``.

    Returns:
      gated band ``[b, hA, wA, K]`` on the same support.
    """
    b, ha, wa, k = values.shape
    hb, wb = grid_b
    dense = band_to_dense(values, indices, grid_b, fill=0.0)
    gated = mutual_matching(dense, eps=eps)
    return jnp.take_along_axis(
        gated.reshape(b, ha, wa, hb * wb),
        indices,
        axis=-1,
        mode="promise_in_bounds",
    )
