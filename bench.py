"""Benchmark: weakly-supervised training throughput, pairs/sec on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference repo publishes no throughput numbers (BASELINE.md).
``V100_EST_PAIRS_PER_SEC`` is an analytic estimate for the reference
implementation on a single V100 at the PF-Pascal training config (batch 16,
400x400, NC 5-5-5/16-16-1): ~2 TFLOP/pair with the Python-loop conv4d
(25 iterations x 11 cuDNN conv3d calls per Conv4d, launch-latency bound,
lib/conv4d.py:39-48) on a 15.7 TFLOPs fp32 part => ~4 pairs/sec.
``vs_baseline`` = measured pairs/sec/chip divided by that estimate.
"""

import json
import time

import numpy as np

V100_EST_PAIRS_PER_SEC = 4.0


def main():
    import jax
    import jax.numpy as jnp

    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.train.step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    batch_size = 16
    config = ImMatchNetConfig(
        ncons_kernel_sizes=(5, 5, 5),
        ncons_channels=(16, 16, 1),
        half_precision=True,  # bf16 correlation/NC path (TPU-native)
        conv4d_impl="scan",  # memory-bounded conv4d for the backward pass
        nc_remat=True,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), config)
    optimizer = make_optimizer()
    state = create_train_state(params, optimizer)
    step = make_train_step(config, optimizer)

    rng = np.random.RandomState(0)
    batch = {
        "source_image": jnp.asarray(
            rng.randn(batch_size, 400, 400, 3).astype(np.float32)
        ),
        "target_image": jnp.asarray(
            rng.randn(batch_size, 400, 400, 3).astype(np.float32)
        ),
    }

    # compile + warmup
    state, loss = step(state, batch)
    jax.block_until_ready(loss)

    n_steps = 10
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, loss = step(state, batch)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    pairs_per_sec = batch_size * n_steps / dt
    print(
        json.dumps(
            {
                "metric": "train_pairs_per_sec_per_chip_400px_resnet101",
                "value": round(pairs_per_sec, 3),
                "unit": "pairs/s",
                "vs_baseline": round(pairs_per_sec / V100_EST_PAIRS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
