"""Benchmark: weakly-supervised training throughput, pairs/sec on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Honest timing: ``jax.block_until_ready`` does NOT block on this platform
(round-1 finding — it timed dispatch, not execution). Every timed segment
here ends with a device-to-host transfer of the loss (``float(loss)``),
which does force execution, and the loss is asserted finite so a broken
step can't report a throughput.

Extras report achieved model FLOP utilization (MFU) against BOTH v5e
peaks (`mfu_vs_bf16_peak`, `mfu_vs_f32_peak` — the MXU has no native
f32 multiply, so the honest denominator depends on the compute dtype,
reported as `compute_dtype`) so absurd numbers are self-evident:
analytic FLOPs per step are derived from the config below (the
25^4 x 5^4 NC convolutions dominate: conv2 alone is ~125
GFLOP/pair/direction). Training compute is bf16 by default
(`--no-bf16` for the f32 step; master params/loss/opt state are f32
either way).

``--feature-cache [DIR]`` benchmarks the frozen-trunk feature-cache step
(ncnet_tpu.features): the trunk runs ONCE outside the timed region (with
a DIR, round-tripping through the real durable store) and the timed step
contains zero backbone ops — the analytic count and MFU then use the
reduced, trunk-free total, so the cached step's MFU is not inflated by
FLOPs it never executed.

``--nc-topk K`` benchmarks the sparse-band NC step (ncnet_tpu.sparse,
arXiv:2004.10566): the NC stack runs on the top-K correlation band, so
its analytic FLOPs shrink by (grid^2)/K. Same honest-accounting rule as
the feature cache: the reported count and MFU use the BAND total, and
the JSON carries nc_topk, band_occupancy, and the dense-equivalent
analytic TFLOP/step so sparse and dense BENCH_r*.json trajectories stay
comparable.

``--corr-impl stream`` (band paths only) swaps the band's producer for
the streamed tiled correlation (ops/corr_stream.py): bitwise the same
band, identical FLOPs, peak memory O(hA*wA*(K+tile)) instead of the
O(hA*wA*hB*wB) volume. The JSON records corr_impl and the traced
liveness peaks of BOTH impls (corr_peak_bytes_dense /
corr_peak_bytes_stream); benchmarks/micro_corr_stream.py sweeps the
tile size. Step-time parity on CPU says nothing about the TPU win —
the claim is bandwidth/HBM, re-measure on hardware (ROADMAP).

Measured formulation ceiling (rounds 2-3, v5e). Round-3 calibrations: a
plain [M, 400] @ [400, 400] GEMM sustains ~200 TFLOP/s on this chip and
the tlc conv3d runs at 137 TFLOP/s hardware — the MXU is NOT the limit;
XLA's data movement is. Three layout findings drive everything:
  (1) 6D/7D intermediates draw pathological XLA layouts on TPU (4-10x
      tile padding, measured OOMs) — every gather/epilogue must stay <=5D
      with the natural minor dim (the round-3 rewrites of cf/btl/tf2);
  (2) slice-sum epilogues do not fuse (each term re-reads the padded
      tensor), so tap foldings whose conv output is kj*kk/cout times the
      activation ('cf1': conv1d core measured 84 TFLOP/s true!) lose it
      all to a 25-term epilogue over a 5 GB tensor;
  (3) buffers saved across the loss-chunk lax.map loop get
      layout-pessimized (5.1x pad), so only the compact packed 'nc_conv'
      outputs are worth saving.
A Pallas kernel cannot beat this either: Mosaic requires 8-aligned
sublane offsets, but conv4d row shifts have granularity 1 in the fused
(j,k) dims, forcing the same banded/inflated formulations (>=3.2x
effective with K/N pads) that XLA already runs at 70% peak.
Best known config (17.43 pairs/s, 15.3% MFU, vs_baseline 4.36): PER-LAYER
impl mixing 'tlc//btl,btl4,tlc/tlc/tf3' + loss_chunk 8 WITHOUT the chunk
remat. Round 4 added (a) the dw (kernel-gradient) slot: the edge layers'
dw transposes a DIFFERENT formulation than their forward ('btl' for
1->16: 22.4 ms vs tlc's 24.8; 'tf3' for 16->1: 13.2 ms vs 18.3), while
the middle layer keeps btl4's own transpose (39.7 ms — every measured
alternative loses: tlc 83.7, cf 113.7, btl5 42.9, rank-4 'xla' 174.2,
and the direct tap-folded GEMM 'dwe*' forms are gather-bound at 450-1150
ms); and (b) dropping the per-chunk remat (16.17 -> 17.43): the
composite custom-VJPs save only (x, w) per conv, so the un-remat'd
residuals now fit where they OOM'd in r2 — while the gather-heavy impls
(cf1/cf/tf2 forwards, btl4/cf dx) still OOM without remat, closing that
design space from both sides. Block re-sweep: btl3 15.3, btl4 16.17,
btl5 14.3, btl6 13.1 pairs/s — block 4 stays the sweet spot. The middle 16->16 layer (89% of stack FLOPs) uses the 5D-safe
blocked Toeplitz at block 4 (1.79x inflation, the measured sweet spot:
block 2 = 14.0 pairs/s end-to-end, block 5 = 14.0, block 8 = 14.6, dense
'tlc' = 11.9); the 1-channel edge layers keep the dense Toeplitz
('tlc'), with the LAST layer's input gradient computed via an explicit
'tlc' conv4d instead of XLA's autodiff transpose (the '<fwd>/<dx>'
composite — XLA's transpose of the 16->1 tlc conv was the hottest
single op of the step). dx-composites measured WORSE elsewhere:
'tlc/btl' on layer 3 = 15.1, 'btl4/btl4' middle = 15.4, 'tf2/tlc' =
15.3, composite on layer 1 = 15.7. 'tf2' forward on the 16->1 layer
wins in isolation (8.4 vs 27.4 ms/pass) but loses end-to-end under the
remat loop (13.6). Batch 32 changes nothing (15.9 — per-pair
cost is flat), and fusing the pos+neg pipelines into one double-batch
call measures 14.0 (the larger live batch through the stack loses more
than the halved op count saves). Negative results kept as impls for the
record: 'cf1' (epilogue-bound), 'cf1s'/'ck1'/'tk1' (scan kills fusion /
6D gathers), 'tlcv' (true-FLOP dw slower than the inflated one it
replaces).

Baseline: the reference repo publishes no throughput numbers (BASELINE.md).
``V100_EST_PAIRS_PER_SEC`` is an analytic estimate for the reference
implementation on a single V100 at the PF-Pascal training config (batch 16,
400x400, NC 5-5-5/16-16-1): ~2 TFLOP/pair with the Python-loop conv4d
(25 iterations x 11 cuDNN conv3d calls per Conv4d, launch-latency bound,
reference lib/conv4d.py:39-48) on a 15.7 TFLOPs fp32 part => ~4 pairs/sec.
``vs_baseline`` = measured pairs/sec/chip divided by that estimate.
"""

import argparse
import json
import os
import time

import numpy as np

# Kept for external readers (BENCH_r*.json history); == pfpascal anchor.
V100_EST_PAIRS_PER_SEC = 4.0

# The FLOP accounting moved into the library (ncnet_tpu.ops.accounting)
# so the training loop's live MFU gauge and this CLI report the same
# number; re-exported here for existing importers (tests, older bench
# JSON tooling).
from ncnet_tpu.ops.accounting import (  # noqa: E402
    V5E_BF16_PEAK_FLOPS,
    compute_dtype,
    peak_flops,
    train_step_flops,
    train_step_flops_for_batch,
)

# Named flagship configs (reference README.md:42,48 — PF-Pascal trains
# 5-5-5/16-16-1, IVD/InLoc trains 3-3/16-1; both at 400x400 / batch 16).
#
# Each carries its own analytic V100 anchor with error bounds (derivation
# in BASELINE.md "Anchor bounds"); the reference publishes no throughput,
# so vs_baseline reads "x an estimate bounded in [lo, hi]":
#   pfpascal — ~2 TFLOP/pair, dominated by the 5^4 NC stack run through
#     the Python-loop conv4d (25 slices x 11 cuDNN conv3d calls/layer,
#     reference lib/conv4d.py:39-48). Upper bound 6.5 pairs/s = conv3d
#     shapes at ~80% of the 15.7 TFLOPs fp32 peak with free launches;
#     lower bound 2.4 = ~35% efficiency + ~10 us x ~3.3k launches/step.
#   ivd — NC shrinks 70x (3^4 kernels, 2 layers: ~24 GFLOP/pair) and the
#     4 unshared trunk passes/pair (the reference re-extracts features
#     for the rolled negatives, train.py:138-152) dominate at ~83
#     GFLOP/pair => ~1.74 TFLOP/step. Upper bound 64 pairs/s = 60%
#     fp32 efficiency + ~100 ms/step of Python/launch overhead for the
#     ~1.8k-launch conv4d loop; lower bound 19 = 35% efficiency + ~200 us
#     per torch-0.3 autograd op. Estimate 35 = midpoint of that range.
CONFIGS = {
    "pfpascal": {
        "kernels": (5, 5, 5),
        "channels": (16, 16, 1),
        # measured-best per-layer mix at the 5^4 shapes (PERF.md)
        "impl": "tlc//btl,btl4,tlc/tlc/tf3",
        "metric": "train_pairs_per_sec_per_chip_400px_resnet101",
        "loss_chunk": 8,
        "v100_est": 4.0,
        "v100_bounds": (2.4, 6.5),
    },
    "ivd": {
        "kernels": (3, 3),
        "channels": (16, 1),
        # measured-best at the 3^4 shapes (PERF.md "IVD config"): the
        # composite VJPs that win at 5^4 all LOSE here — plain tlc with
        # XLA's own transposes is fastest on both layers
        "impl": "tlc,tlc",
        "metric": "train_pairs_per_sec_per_chip_400px_resnet101_ivd",
        # chunk 4 beats 8 here (125.7/125.9 vs 120.8/121.4 across reruns);
        # 2 and 16 fall to ~92 (benchmarks/ivd_sweep*.log)
        "loss_chunk": 4,
        "v100_est": 35.0,
        "v100_bounds": (19.0, 64.0),
    },
}


def _corr_peak_bytes(batch, grid, feat_ch, k, mutual, tile):
    """Traced liveness peaks (bytes) of BOTH correlation->band impls at
    this run's band geometry — the memory half of the --corr-impl story,
    measured the same way the audit's 0.35x gate is
    (analysis.hlo_audit.jaxpr_memory_highwater over the jaxpr; trace
    only, nothing compiles or runs). FLOPs are identical between the
    impls (ops.accounting.corr_select_flops), so peak bytes is the
    number that justifies flipping the switch."""
    import numpy as np

    from ncnet_tpu.analysis.hlo_audit import jaxpr_memory_highwater
    from ncnet_tpu.ops.band import topk_band
    from ncnet_tpu.ops.corr_stream import corr_stream_band
    from ncnet_tpu.ops.correlation import correlation_4d
    from ncnet_tpu.ops.matching import mutual_matching

    import jax

    feats = np.zeros((batch, grid, grid, feat_ch), np.float32)

    def dense(fa, fb):
        corr = correlation_4d(fa, fb)
        return topk_band(
            corr, k, values_from=mutual_matching(corr), mutual=mutual
        )

    def stream(fa, fb):
        return corr_stream_band(fa, fb, k, mutual=mutual, tile=tile)

    return (
        jaxpr_memory_highwater(jax.make_jaxpr(dense)(feats, feats).jaxpr),
        jaxpr_memory_highwater(jax.make_jaxpr(stream)(feats, feats).jaxpr),
    )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="pfpascal", choices=sorted(CONFIGS),
                   help="flagship training config: 'pfpascal' (NC 5-5-5/"
                        "16-16-1) or 'ivd' (NC 3-3/16-1, the config that "
                        "trains the model the InLoc chain consumes — "
                        "reference README.md:48)")
    p.add_argument("--conv4d_impl", default=None,
                   help="one impl or a comma-separated per-NC-layer list; "
                        "'<fwd>/<dx>' composes forward and input-grad "
                        "lowerings (default: the measured-best mix for "
                        "--config)")
    p.add_argument("--nc_remat", action="store_true")
    p.add_argument("--chunk_remat", action="store_true",
                   help="re-enable per-chunk rematerialization (the r2-r3 "
                        "regime; a net loss since the composite VJPs "
                        "shrank the un-remat'd residuals — see PERF.md)")
    p.add_argument("--loss_chunk", type=int, default=None,
                   help="default: the measured-best chunk for --config "
                        "(pfpascal 8, ivd 4)")
    p.add_argument("--sym_seq", action="store_true",
                   help="run the symmetric NC passes sequentially instead "
                        "of double-batched (halves stack live memory)")
    p.add_argument("--feature-cache", type=str, nargs="?", const="",
                   default=None, dest="feature_cache", metavar="DIR",
                   help="bench the frozen-trunk feature-cache step "
                        "(ncnet_tpu.features): trunk features are "
                        "extracted ONCE outside the timed region and the "
                        "timed step runs from them with zero backbone "
                        "FLOPs — the analytic count and MFU use the "
                        "reduced (trunk-free) total. With a DIR the "
                        "features round-trip through a real durable "
                        "on-disk store first (digest-guarded, verified "
                        "read); without one they stay in device memory, "
                        "modeling a pinned cache")
    p.add_argument("--compile-cache", type=str, default=None,
                   dest="compile_cache", metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(default ~/.cache/ncnet_tpu/xla; 'none' "
                        "disables): the minute-scale conv4d NC-stack "
                        "compiles are paid once per machine, not once "
                        "per run")
    p.add_argument("--nc-topk", type=int, default=0, dest="nc_topk",
                   metavar="K",
                   help="sparse-band neighbourhood consensus "
                        "(ncnet_tpu.sparse): keep only the top-K "
                        "B-candidates per A-cell and run the NC stack on "
                        "that band — analytic NC FLOPs drop by "
                        "(grid^2)/K. 0 = dense. The analytic count and "
                        "MFU use the BAND total; the JSON also records "
                        "the dense-equivalent count "
                        "(analytic_tflop_per_step_dense) and the band "
                        "occupancy so sparse and dense trajectories stay "
                        "comparable")
    p.add_argument("--nc-topk-mutual", action=argparse.BooleanOptionalAction,
                   default=True, dest="nc_topk_mutual",
                   help="with --nc-topk: symmetric/mutual band selection "
                        "(union of per-A and per-B ranks, swap-closed up "
                        "to capacity) vs plain per-A top-K")
    p.add_argument("--refine", type=int, default=0, metavar="R",
                   help="coarse-to-fine training step (ncnet_tpu.refine): "
                        "pool features by R, run the coarse band at "
                        "--refine-topk, re-score the survivors at high "
                        "res. Takes precedence over --nc-topk. The "
                        "analytic count and MFU use the refined total "
                        "(ops.accounting.refine_train_step_flops); the "
                        "JSON records refine geometry and the dense-"
                        "equivalent count, mirroring the --nc-topk "
                        "accounting. 0 = off")
    p.add_argument("--refine-topk", type=int, default=16,
                   dest="refine_topk", metavar="K",
                   help="with --refine: coarse-band width")
    p.add_argument("--refine-radius", type=int, default=0,
                   dest="refine_radius",
                   help="with --refine: extra window reach in coarse cells")
    p.add_argument("--corr-impl", default="dense",
                   choices=("dense", "stream"), dest="corr_impl",
                   help="band paths only (--nc-topk or --refine): 'dense' "
                        "materializes the full correlation volume before "
                        "selecting; 'stream' (ops/corr_stream.py) tiles "
                        "B's grid and folds each GEMM slab into a running "
                        "top-K merge — the SAME band bitwise and the SAME "
                        "FLOPs, at O(hA*wA*(K+tile)) peak memory instead "
                        "of O(hA*wA*hB*wB). The JSON records corr_impl "
                        "and the traced liveness peaks of both impls "
                        "(corr_peak_bytes_dense / corr_peak_bytes_stream)")
    p.add_argument("--corr-tile", type=int, default=128, dest="corr_tile",
                   metavar="T",
                   help="with --corr-impl stream: static B-grid slab "
                        "width (clamped to hB*wB; 128 aligns with the "
                        "TPU lane width)")
    p.add_argument("--bf16", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="bf16 features/correlation/NC compute with f32 "
                        "master params and f32 loss/optimizer state (the "
                        "default train path); --no-bf16 runs the full-f32 "
                        "step for the bf16-vs-f32 ratio in PERF.md. The "
                        "JSON records compute_dtype and reports MFU "
                        "against BOTH dtype peaks")
    p.add_argument("--image_size", type=int, default=400,
                   help="square input size; 400 is the flagship config — "
                        "smaller sizes are CPU-proxy runs (the JSON is "
                        "tagged with the size when non-default)")
    p.add_argument("--batch", type=int, default=16)
    # the platform's ~80 ms D2H roundtrip is paid ONCE for the whole timed
    # chain; more steps amortize that measurement constant (it is not part
    # of the training step itself)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--sanitize", action="store_true",
                   help="enable per-stage finiteness/bf16 probes "
                        "(ncnet_tpu.analysis.sanitizer); on a non-finite "
                        "loss the bench stops with the per-stage report "
                        "and the first non-finite stage instead of a bare "
                        "assert. The probes add work — a --sanitize run "
                        "is a diagnostic, NOT a throughput number (the "
                        "JSON is tagged \"sanitized\")")
    p.add_argument("--telemetry", type=str, default=None, metavar="DIR",
                   help="write a telemetry run under DIR "
                        "(ncnet_tpu.telemetry): bench/warmup + "
                        "bench/timed_chain spans and the headline "
                        "gauges, renderable with "
                        "scripts/telemetry_report.py DIR")
    p.add_argument("--save-every-steps", type=int, default=0,
                   dest="save_every_steps",
                   help="checkpoint every N steps INSIDE the timed chain "
                        "(legacy layout, throwaway temp dir): the "
                        "sync-vs-async checkpoint A/B — per-save "
                        "step-thread stall lands in the JSON as "
                        "ckpt_stall_ms_p50/p95 and the chain wall time "
                        "absorbs the saves. 0 = no checkpointing "
                        "(the default throughput bench)")
    p.add_argument("--async-checkpoints", action="store_true",
                   dest="async_checkpoints",
                   help="with --save-every-steps: overlap the saves via "
                        "resilience.async_ckpt instead of blocking the "
                        "chain for each one (coalescing counted in the "
                        "JSON as ckpt_coalesced_total)")
    args = p.parse_args()

    from ncnet_tpu import telemetry

    if args.telemetry:
        telemetry.start(args.telemetry, label="bench")
    try:
        _run(args)
    finally:
        telemetry.stop()  # no-op without --telemetry


def _run(args):
    from ncnet_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(args.compile_cache)

    import jax
    import jax.numpy as jnp

    from ncnet_tpu.analysis import sanitizer
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.train.step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    if args.sanitize:  # before any tracing: taps are bound at trace time
        sanitizer.enable()

    preset = CONFIGS[args.config]
    impl = args.conv4d_impl if args.conv4d_impl is not None else preset["impl"]
    loss_chunk = (
        args.loss_chunk if args.loss_chunk is not None
        else preset["loss_chunk"]
    )
    batch_size = args.batch
    config = ImMatchNetConfig(
        ncons_kernel_sizes=preset["kernels"],
        ncons_channels=preset["channels"],
        half_precision=args.bf16,  # bf16 correlation/NC path (TPU-native)
        conv4d_impl=impl,
        nc_remat=args.nc_remat,
        loss_chunk=loss_chunk,
        loss_chunk_remat=args.chunk_remat,
        symmetric_batch=not args.sym_seq,
        nc_topk=args.nc_topk,
        nc_topk_mutual=args.nc_topk_mutual,
        refine_factor=args.refine,
        refine_topk=args.refine_topk,
        refine_radius=args.refine_radius,
        corr_impl=args.corr_impl,
        corr_stream_tile=args.corr_tile,
    )
    if args.corr_impl != "dense" and not (args.nc_topk or args.refine):
        raise SystemExit(
            f"--corr-impl {args.corr_impl} requires a band path "
            "(--nc-topk K or --refine R): the dense NC stack consumes "
            "the full correlation volume, so there is nothing to stream"
        )
    if args.refine and (args.image_size // 16) % args.refine:
        raise SystemExit(
            f"--image_size {args.image_size} gives a "
            f"{args.image_size // 16}-cell feature grid, which does not "
            f"divide by --refine {args.refine} (at 400x400 use 5; at "
            "128x128 use 2 or 4)"
        )
    params = init_immatchnet(jax.random.PRNGKey(0), config)
    optimizer = make_optimizer()
    state = create_train_state(params, optimizer)
    from_features = args.feature_cache is not None
    step = make_train_step(config, optimizer, from_features=from_features)

    size = args.image_size
    rng = np.random.RandomState(0)
    batch = {
        "source_image": jnp.asarray(
            rng.randn(batch_size, size, size, 3).astype(np.float32)
        ),
        "target_image": jnp.asarray(
            rng.randn(batch_size, size, size, 3).astype(np.float32)
        ),
    }
    if from_features:
        # the one-time trunk pass the cache amortizes away: extracted
        # OUTSIDE the timed region; the timed step never sees an image
        from ncnet_tpu.features import (
            FeatureStore,
            make_batch_extractor,
            trunk_digest,
        )

        extractor = make_batch_extractor(params, config)
        feat_src = extractor(batch["source_image"])
        feat_tgt = extractor(batch["target_image"])
        if args.feature_cache:
            # round-trip through the REAL durable store: digest-guarded
            # manifest, atomic shard writes, verified reads — the bench
            # then measures exactly what --feature-cache training runs
            store = FeatureStore.open_or_create(
                args.feature_cache,
                trunk_digest(params["feature_extraction"], config,
                             (size, size)),
                config, (size, size), batch_size,
            )
            src_np, tgt_np = np.asarray(feat_src), np.asarray(feat_tgt)
            for i in range(batch_size):
                if not store.has(i):
                    store.put(i, src_np[i], tgt_np[i])
            pairs = [store.get(i) for i in range(batch_size)]
            feat_src = jnp.asarray(np.stack([p[0] for p in pairs]))
            feat_tgt = jnp.asarray(np.stack([p[1] for p in pairs]))
        batch = {"source_features": feat_src, "target_features": feat_tgt}

    def check_finite(loss_host, context):
        # the finite-loss gate exists so a numerically broken config can
        # never report a throughput; sanitized runs upgrade the bare
        # failure to a per-stage report naming the first non-finite stage
        if args.sanitize:
            sanitizer.check_finite_or_report(loss_host, context=context)
        else:
            assert np.isfinite(loss_host), (
                f"non-finite loss {loss_host} at {context} "
                "(re-run with --sanitize to localize the first "
                "non-finite stage)"
            )

    from ncnet_tpu.telemetry import trace

    # Compile + warmup with a per-step D2H sync (the ONLY reliable way to
    # force execution here; block_until_ready is a no-op on this platform).
    with trace.span("bench/warmup"):
        for w in range(2):
            state, loss = step(state, batch)
            check_finite(float(loss), f"warmup step {w}")

    # Optional checkpoint arm: durable legacy-layout saves inside the
    # timed chain (throwaway dir), mirroring the training loop's
    # mid-epoch cursor snapshots — sync blocks the chain per save, async
    # hands off to the writer thread. The per-save STALL (what the step
    # thread actually lost) is timed separately from the chain wall.
    ackpt = None
    ckpt_stalls = []
    if args.save_every_steps:
        import shutil
        import tempfile

        from ncnet_tpu.resilience.async_ckpt import (
            AsyncCheckpointer,
            device_snapshot,
        )
        from ncnet_tpu.train.checkpoint import (
            CheckpointData,
            materialize_on_host,
            save_checkpoint,
        )

        ckpt_dir = tempfile.mkdtemp(prefix="bench_ckpt_")
        ckpt_path = os.path.join(ckpt_dir, "bench.msgpack")
        ackpt = AsyncCheckpointer(async_mode=args.async_checkpoints)

        def submit_save(state, step_idx):
            params_ref, opt_ref = state.params, state.opt_state
            if args.async_checkpoints:
                # the jitted step donates its carried state: overlapped
                # saves snapshot through device-side copies (loop.py does
                # the same) — dispatch only, no host sync
                params_ref = device_snapshot(params_ref)
                opt_ref = device_snapshot(opt_ref)
            data = CheckpointData(
                config=config, params=params_ref, opt_state=opt_ref,
                step=step_idx,
            )
            ackpt.submit(
                data,
                lambda d: save_checkpoint(ckpt_path, d, keep=2),
                prepare=materialize_on_host,
                step=step_idx,
                wait=not args.async_checkpoints,
            )

    # Timed: steps chain through the state dependency, so ONE final D2H
    # forces the whole sequence; the ~80 ms roundtrip latency of this
    # platform is amortized over n_steps instead of paid per step.
    n_steps = args.steps
    with trace.span("bench/timed_chain"):
        t0 = time.perf_counter()
        for s in range(n_steps):
            state, loss = step(state, batch)
            if ackpt is not None and (s + 1) % args.save_every_steps == 0:
                t_save = time.perf_counter()
                submit_save(state, s + 1)
                ckpt_stalls.append(time.perf_counter() - t_save)
        if ackpt is not None:
            # epoch-end barrier semantics: the chain wall honestly
            # includes draining the writer, exactly like the loop
            ackpt.flush()
        loss_host = float(loss)
        dt = time.perf_counter() - t0
    ckpt_extras = {}
    if ackpt is not None:
        rep = ackpt.report()
        ackpt.close()
        shutil.rmtree(ckpt_dir, ignore_errors=True)
        stall_ms = np.asarray(ckpt_stalls) * 1e3
        ckpt_extras = {
            "ckpt_mode": "async" if args.async_checkpoints else "sync",
            "save_every_steps": args.save_every_steps,
            "ckpt_saves_submitted": rep["submitted_total"],
            "ckpt_saves_written": rep["written_total"],
            "ckpt_coalesced_total": rep["coalesced_total"],
            "ckpt_stall_ms_p50": round(float(np.percentile(stall_ms, 50)), 2),
            "ckpt_stall_ms_p95": round(float(np.percentile(stall_ms, 95)), 2),
        }
    check_finite(loss_host, f"timed chain ({n_steps} steps)")
    if args.sanitize:
        print(sanitizer.report_text(), flush=True)

    pairs_per_sec = batch_size * n_steps / dt
    grid = size // 16
    if args.refine:
        # derives grid/feat_ch from the batch and branches to
        # refine_train_step_flops — the same number the training loop's
        # MFU gauge reports for a --refine run
        step_flops = train_step_flops_for_batch(
            config, batch, from_features=from_features
        )
    else:
        step_flops = train_step_flops(
            batch_size, preset["kernels"], preset["channels"],
            grid=grid, image=size, from_features=from_features,
            nc_topk=args.nc_topk,
        )
    achieved_flops = step_flops * n_steps / dt
    mfu = achieved_flops / V5E_BF16_PEAK_FLOPS
    # the dual-MFU pair: the same achieved rate against both dtype peaks,
    # so a --no-bf16 run is judged against the ceiling f32 compute can
    # actually reach and a bf16 run is not flattered by the lower bar
    mfu_f32 = achieved_flops / peak_flops("float32")
    dtype = compute_dtype(config)
    from ncnet_tpu.telemetry import default_registry

    reg = default_registry()
    reg.gauge("bench_pairs_per_s", "bench headline throughput").set(
        pairs_per_sec
    )
    reg.gauge("bench_step_ms", "bench mean step time").set(
        dt / n_steps * 1e3
    )
    reg.gauge("bench_mfu", "bench analytic MFU vs v5e bf16 peak").set(mfu)
    reg.gauge(
        "bench_mfu_vs_f32_peak", "bench analytic MFU vs v5e f32 peak"
    ).set(mfu_f32)
    sparse_extras = {}
    if args.refine:
        from ncnet_tpu.ops.accounting import refine_window

        dense_flops = train_step_flops(
            batch_size, preset["kernels"], preset["channels"],
            grid=grid, image=size, from_features=from_features,
        )
        grid_lo = grid // args.refine
        peak_d, peak_s = _corr_peak_bytes(
            batch_size, grid_lo,
            256 if config.feature_extraction_cnn == "patch16" else 1024,
            min(args.refine_topk, grid_lo**2), args.nc_topk_mutual,
            args.corr_tile,
        )
        sparse_extras = {
            "refine_factor": args.refine,
            "refine_topk": min(args.refine_topk, grid_lo**2),
            "refine_window": refine_window(args.refine, args.refine_radius),
            "analytic_tflop_per_step_dense": round(dense_flops / 1e12, 2),
            "corr_impl": args.corr_impl,
            "corr_peak_bytes_dense": peak_d,
            "corr_peak_bytes_stream": peak_s,
        }
    elif args.nc_topk:
        # the dense-vs-band analytic pair: BENCH_r*.json trajectories stay
        # comparable across sparse and dense runs (mirrors the
        # --feature-cache accounting, which also reports the reduced count)
        dense_flops = train_step_flops(
            batch_size, preset["kernels"], preset["channels"],
            grid=grid, image=size, from_features=from_features,
        )
        k_eff = min(args.nc_topk, grid**2)
        peak_d, peak_s = _corr_peak_bytes(
            batch_size, grid,
            256 if config.feature_extraction_cnn == "patch16" else 1024,
            k_eff, args.nc_topk_mutual, args.corr_tile,
        )
        sparse_extras = {
            "nc_topk": k_eff,
            "band_occupancy": round(k_eff / grid**2, 4),
            "analytic_tflop_per_step_dense": round(dense_flops / 1e12, 2),
            "corr_impl": args.corr_impl,
            "corr_peak_bytes_dense": peak_d,
            "corr_peak_bytes_stream": peak_s,
        }
    print(
        json.dumps(
            {
                "metric": preset["metric"],
                "value": round(pairs_per_sec, 3),
                "unit": "pairs/s",
                "vs_baseline": round(pairs_per_sec / preset["v100_est"], 3),
                "vs_baseline_bounds": [
                    round(pairs_per_sec / preset["v100_bounds"][1], 3),
                    round(pairs_per_sec / preset["v100_bounds"][0], 3),
                ],
                "step_ms": round(dt / n_steps * 1e3, 1),
                "analytic_tflop_per_step": round(step_flops / 1e12, 2),
                "compute_dtype": dtype,
                "mfu_vs_v5e_bf16_peak": round(mfu, 4),
                "mfu_vs_bf16_peak": round(mfu, 4),
                "mfu_vs_f32_peak": round(mfu_f32, 4),
                **sparse_extras,
                **ckpt_extras,
                **({"feature_cache": True} if from_features else {}),
                **({"image_size": size} if size != 400 else {}),
                **({"sanitized": True} if args.sanitize else {}),
            }
        )
    )


if __name__ == "__main__":
    main()
