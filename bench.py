"""Benchmark: weakly-supervised training throughput, pairs/sec on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Honest timing: ``jax.block_until_ready`` does NOT block on this platform
(round-1 finding — it timed dispatch, not execution). Every timed segment
here ends with a device-to-host transfer of the loss (``float(loss)``),
which does force execution, and the loss is asserted finite so a broken
step can't report a throughput.

Extras report achieved model FLOP utilization (MFU) against the v5e bf16
peak so absurd numbers are self-evident: analytic FLOPs per step are
derived from the config below (the 25^4 x 5^4 NC convolutions dominate:
conv2 alone is ~125 GFLOP/pair/direction).

Measured formulation ceiling (round 2, v5e): the NC convolutions cap at
~20-30 TFLOP/s f+b across every lowering tried (direct rank-4, tap sums,
channel-fused conv2d 'cf'/'cfs', im2col GEMM, Toeplitz 'tlc'); only
5x-FLOP-inflated wide-lane forms reach >130 TFLOP/s hardware rate, netting
~26 useful — the 16-channel, 25-grid shapes are the binding constraint.
Best known config (11.9 pairs/s, 10.4% MFU): tlc + loss_chunk 8 + chunk
remat with the 'nc_conv' save-policy (convs not recomputed in backward) —
tlc's 5x-inflated wide-lane forward wins end-to-end once the policy stops
the backward from re-running forwards; cfs + chunk 4 = 10.5. The blocked
Toeplitz 'btl' (3.1x inflation, 192/128 lanes) measures 11.0 at chunk 4 —
the per-block window gather costs what the FLOP reduction saves. 'tlcv'
(tlc forward + custom-VJP true-FLOP rank-4 kernel gradient) measures 6.5:
the rank-4 dw is slower than the 5x-inflated Toeplitz dw it replaces.

Baseline: the reference repo publishes no throughput numbers (BASELINE.md).
``V100_EST_PAIRS_PER_SEC`` is an analytic estimate for the reference
implementation on a single V100 at the PF-Pascal training config (batch 16,
400x400, NC 5-5-5/16-16-1): ~2 TFLOP/pair with the Python-loop conv4d
(25 iterations x 11 cuDNN conv3d calls per Conv4d, launch-latency bound,
reference lib/conv4d.py:39-48) on a 15.7 TFLOPs fp32 part => ~4 pairs/sec.
``vs_baseline`` = measured pairs/sec/chip divided by that estimate.
"""

import argparse
import json
import time

import numpy as np

V100_EST_PAIRS_PER_SEC = 4.0
V5E_BF16_PEAK_FLOPS = 197e12


def train_step_flops(batch, grid=25, feat_ch=1024, image=400):
    """Analytic FLOPs (2*MACs) per training step at the PF-Pascal config.

    Counted: 2 trunk forwards/sample (features reused for the rolled
    negatives), pos+neg correlation einsums, the symmetric NC stack
    (5-5-5 / 1-16-16-1 channels) forward for pos+neg, and its backward
    (~2x forward; the frozen trunk takes no backward).
    """
    resnet101_layer3_224 = 6.5e9  # conv1..layer3 @ 224x224 per image
    trunk = 2 * resnet101_layer3_224 * (image / 224.0) ** 2
    corr = 2 * 2.0 * grid**4 * feat_ch  # pos + neg
    nc_channels = [1, 16, 16, 1]
    nc_pass = sum(
        2.0 * grid**4 * 5**4 * cin * cout
        for cin, cout in zip(nc_channels[:-1], nc_channels[1:])
    )
    nc_fwd = nc_pass * 2 * 2  # symmetric x (pos + neg)
    nc_bwd = 2 * nc_fwd
    return batch * (trunk + corr + nc_fwd + nc_bwd)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--conv4d_impl", default="tlc")
    p.add_argument("--nc_remat", action="store_true")
    p.add_argument("--no_chunk_remat", action="store_true",
                   help="disable per-chunk rematerialization (needs the "
                        "packed-layout residuals to fit in HBM)")
    p.add_argument("--loss_chunk", type=int, default=8)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--steps", type=int, default=10)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.train.step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    batch_size = args.batch
    config = ImMatchNetConfig(
        ncons_kernel_sizes=(5, 5, 5),
        ncons_channels=(16, 16, 1),
        half_precision=True,  # bf16 correlation/NC path (TPU-native)
        conv4d_impl=args.conv4d_impl,
        nc_remat=args.nc_remat,
        loss_chunk=args.loss_chunk,
        loss_chunk_remat=not args.no_chunk_remat,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), config)
    optimizer = make_optimizer()
    state = create_train_state(params, optimizer)
    step = make_train_step(config, optimizer)

    rng = np.random.RandomState(0)
    batch = {
        "source_image": jnp.asarray(
            rng.randn(batch_size, 400, 400, 3).astype(np.float32)
        ),
        "target_image": jnp.asarray(
            rng.randn(batch_size, 400, 400, 3).astype(np.float32)
        ),
    }

    # Compile + warmup with a per-step D2H sync (the ONLY reliable way to
    # force execution here; block_until_ready is a no-op on this platform).
    for _ in range(2):
        state, loss = step(state, batch)
        loss_host = float(loss)
        assert np.isfinite(loss_host), f"non-finite loss {loss_host}"

    # Timed: steps chain through the state dependency, so ONE final D2H
    # forces the whole sequence; the ~80 ms roundtrip latency of this
    # platform is amortized over n_steps instead of paid per step.
    n_steps = args.steps
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, loss = step(state, batch)
    loss_host = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(loss_host), f"non-finite loss {loss_host}"

    pairs_per_sec = batch_size * n_steps / dt
    step_flops = train_step_flops(batch_size)
    mfu = (step_flops * n_steps / dt) / V5E_BF16_PEAK_FLOPS
    print(
        json.dumps(
            {
                "metric": "train_pairs_per_sec_per_chip_400px_resnet101",
                "value": round(pairs_per_sec, 3),
                "unit": "pairs/s",
                "vs_baseline": round(pairs_per_sec / V100_EST_PAIRS_PER_SEC, 3),
                "step_ms": round(dt / n_steps * 1e3, 1),
                "analytic_tflop_per_step": round(step_flops / 1e12, 2),
                "mfu_vs_v5e_bf16_peak": round(mfu, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
