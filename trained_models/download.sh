#!/bin/bash
# Released reference checkpoints (torch .pth.tar). Convert for ncnet_tpu
# with:
#   python scripts/convert_checkpoint.py trained_models/ncnet_pfpascal.pth.tar \
#       trained_models/ncnet_pfpascal.msgpack
# or pass the .pth.tar directly to the eval/train CLIs, which convert
# on the fly (scripts/eval_pf_pascal.py, scripts/train.py --checkpoint).
set -euo pipefail
cd "$(dirname "$0")"
wget -nc https://www.di.ens.fr/willow/research/ncnet/models/ncnet_pfpascal.pth.tar
wget -nc https://www.di.ens.fr/willow/research/ncnet/models/ncnet_ivd.pth.tar
# ImageNet trunk weights (torchvision); any of these works for --fe_weights:
wget -nc https://download.pytorch.org/models/resnet101-63fe2227.pth
wget -nc https://download.pytorch.org/models/vgg16-397923af.pth
