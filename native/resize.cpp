// Host-side bilinear align-corners resize, float32 HWC.
//
// The C++ fast path for the data loader's hot preprocessing op
// (ncnet_tpu/data/images.py resize_bilinear_np). Semantics match the
// reference's identity-affine grid_sample resize under PyTorch-0.3
// align_corners behavior (lib/transformation.py:41-63): output pixel o
// samples input position o * (L_in - 1) / (L_out - 1).
//
// Called through ctypes (ncnet_tpu/data/native.py), which releases the
// GIL for the duration of the call — so the threaded DataLoader's workers
// genuinely overlap. Build with native/build.sh.

#include <cstdint>

extern "C" {

// in:  [h, w, c] contiguous float32
// out: [oh, ow, c] contiguous float32 (caller-allocated)
void ncnet_resize_bilinear_f32(const float* in, int64_t h, int64_t w,
                               int64_t c, float* out, int64_t oh,
                               int64_t ow) {
  for (int64_t oy = 0; oy < oh; ++oy) {
    const float py =
        (oh == 1) ? 0.0f
                  : static_cast<float>(oy) * static_cast<float>(h - 1) /
                        static_cast<float>(oh - 1);
    const int64_t y0 = static_cast<int64_t>(py);
    const int64_t y1 = (y0 + 1 < h) ? y0 + 1 : h - 1;
    const float fy = py - static_cast<float>(y0);
    for (int64_t ox = 0; ox < ow; ++ox) {
      const float px =
          (ow == 1) ? 0.0f
                    : static_cast<float>(ox) * static_cast<float>(w - 1) /
                          static_cast<float>(ow - 1);
      const int64_t x0 = static_cast<int64_t>(px);
      const int64_t x1 = (x0 + 1 < w) ? x0 + 1 : w - 1;
      const float fx = px - static_cast<float>(x0);
      const float* p00 = in + (y0 * w + x0) * c;
      const float* p01 = in + (y0 * w + x1) * c;
      const float* p10 = in + (y1 * w + x0) * c;
      const float* p11 = in + (y1 * w + x1) * c;
      float* dst = out + (oy * ow + ox) * c;
      for (int64_t ch = 0; ch < c; ++ch) {
        const float top = p00[ch] * (1.0f - fx) + p01[ch] * fx;
        const float bot = p10[ch] * (1.0f - fx) + p11[ch] * fx;
        dst[ch] = top * (1.0f - fy) + bot * fy;
      }
    }
  }
}

}  // extern "C"
