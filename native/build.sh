#!/bin/sh
# Build the host-side native fast paths into ncnet_tpu/data/_native/.
# Requires g++ (baked into the image); no other dependencies.
set -e
cd "$(dirname "$0")"
mkdir -p ../ncnet_tpu/data/_native
g++ -O3 -shared -fPIC -std=c++17 resize.cpp \
    -o ../ncnet_tpu/data/_native/libncnet_native.so
echo "built ncnet_tpu/data/_native/libncnet_native.so"
