"""HTTP serving CLI: the network front door over the serving stack.

Boots a warmed `ServeEngine` (or `ServeFleet` with --fleet/--replicas)
behind `ncnet_tpu.serve.http` and serves:

  POST /v1/match   JSON {"payload": {name: nested lists}} with
                   X-Deadline-Ms (budget propagated into admission
                   control, deadline-aware micro-batch flush, and the
                   per-bucket cost ladders) and X-Quality (pin a rung:
                   refined / standard / degraded) headers
  GET  /healthz    200 while serving; 503 before warmup and from the
                   moment a drain begins (LB stops routing before
                   SIGTERM completes)
  GET  /metrics    Prometheus snapshot of the shared registry

Typed outcomes map to wire status codes (`serve.http.outcome_status`):
429 shed/admission-rejected (with Retry-After), 503 draining, 504
deadline exceeded (failing stage in the body), 502 replica down, 500
stage failure. SIGTERM runs the ordered drain: healthz flips unready ->
in-flight requests finish -> listener closes -> the final JSON report
prints -> exit 0 (drilled over a real subprocess in tests/test_http.py).

Model sources:
  --checkpoint CK          .msgpack checkpoint or reference .pth.tar
  --synthetic              randomly-initialized TINY patch16 trunk — no
                           checkpoint file needed; the chaos-drill /
                           CI-smoke mode (shapes and contracts are real,
                           weights are not)

Warmup compiles every (bucket, batch-size, variant) program for the
square --warm-sizes image buckets before the listener opens, so
recompiles_after_warmup stays 0 across any traffic mix, rung flips, and
X-Quality pins over those buckets.

Example:
  python scripts/serve_http.py --synthetic --image-size 32 --port 8080 \
      --degrade 4 --per-bucket-quality --telemetry /tmp/t --telemetry-stream-s 2
"""

import argparse
import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="ncnet_tpu HTTP serving front door")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--checkpoint", type=str,
                     help=".msgpack checkpoint or reference .pth.tar")
    src.add_argument("--synthetic", action="store_true",
                     help="serve a randomly-initialized TINY patch16 "
                          "model (drill/smoke mode; no checkpoint)")
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080,
                   help="listen port (0 picks an ephemeral port; the "
                        "bound address is printed on the 'serving:' line)")
    p.add_argument("--image-size", type=int, default=400,
                   help="bucket universe: max image side after resize")
    p.add_argument("--warm-sizes", type=str, default=None,
                   help="comma-separated square image sizes to warm as "
                        "buckets (default: --image-size only)")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--queue-limit", type=int, default=64)
    p.add_argument("--host-workers", type=int, default=2)
    p.add_argument("--nc-topk", type=int, default=-1,
                   help="override config.nc_topk (-1 keeps the model's)")
    p.add_argument("--conv4d_impl", type=str, default="tlc")
    p.add_argument("--degrade", type=int, default=-1,
                   help="nc_topk of the pre-warmed DEGRADED program "
                        "(-1 disables the cheap rung)")
    p.add_argument("--refine", type=int, default=0, metavar="R",
                   help="pool factor of the pre-warmed REFINED program "
                        "(0 disables the rich rung)")
    p.add_argument("--refine-topk", type=int, default=16, dest="refine_topk")
    p.add_argument("--refine-radius", type=int, default=0,
                   dest="refine_radius")
    p.add_argument("--per-bucket-quality",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="cost-aware per-bucket QualityLadder (rung per "
                        "bucket from ETA vs the tightest queued budget); "
                        "--no-per-bucket-quality keeps one global "
                        "controller")
    p.add_argument("--deadline-flush",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="deadline-aware micro-batch flush; "
                        "--no-deadline-flush is the fixed-wait baseline")
    p.add_argument("--fleet", action="store_true",
                   help="serve through a ServeFleet (one engine per "
                        "device behind the best-ETA router)")
    p.add_argument("--replicas", type=int, default=0,
                   help="fleet size (implies --fleet; on CPU provisions "
                        "an N-virtual-device proxy mesh)")
    p.add_argument("--hang-timeout", type=float, default=30.0,
                   help="dispatch heartbeat watchdog seconds (0 off)")
    p.add_argument("--drain-timeout", type=float, default=10.0,
                   help="graceful-drain deadline on SIGTERM")
    p.add_argument("--request-timeout", type=float, default=60.0,
                   help="handler-thread wait ceiling for requests "
                        "without a deadline header")
    p.add_argument("--telemetry", type=str, default=None, metavar="DIR",
                   help="write a telemetry run under DIR (render with "
                        "scripts/telemetry_report.py DIR)")
    p.add_argument("--telemetry-stream-s", type=float, default=0.0,
                   help="with --telemetry: flush incremental metric "
                        "records every S seconds so a scraper can tail "
                        "the live events JSONL (0 = only at stop)")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.replicas > 0:
        args.fleet = True
    if args.fleet and args.replicas > 1:
        # CPU proxy mesh: must precede any jax import (XLA reads the
        # flag once at client creation); no-op on real TPUs
        flags = os.environ.get("XLA_FLAGS", "")
        if ("jax" not in sys.modules
                and "xla_force_host_platform_device_count" not in flags):
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.replicas}"
            ).strip()

    from ncnet_tpu import telemetry

    if args.telemetry:
        telemetry.start(args.telemetry, label="serve_http")
        if args.telemetry_stream_s > 0:
            telemetry.active().start_streaming(args.telemetry_stream_s)
        print(f"telemetry: {args.telemetry} "
              "(render with scripts/telemetry_report.py)", flush=True)
    try:
        return _run(args, telemetry)
    finally:
        telemetry.stop()


def _load_model(args):
    """(config, params) from a checkpoint or the synthetic TINY trunk."""
    if args.synthetic:
        import jax

        from ncnet_tpu.models.immatchnet import (
            ImMatchNetConfig,
            init_immatchnet,
        )

        config = ImMatchNetConfig(
            ncons_kernel_sizes=(3,), ncons_channels=(1,),
            feature_extraction_cnn="patch16",
        )
        params = init_immatchnet(jax.random.PRNGKey(0), config)
        return config, params
    if args.checkpoint.endswith((".pth.tar", ".pth")):
        from ncnet_tpu.utils.convert_torch import convert_checkpoint

        return convert_checkpoint(args.checkpoint)
    from ncnet_tpu.train.checkpoint import load_checkpoint

    ck = load_checkpoint(args.checkpoint)
    return ck.config, ck.params


def _run(args, telemetry):
    import numpy as np

    from ncnet_tpu.resilience.signals import PreemptionGuard
    from ncnet_tpu.serve import (
        BucketSpec,
        HttpFrontDoor,
        ServeEngine,
        ServeFleet,
        default_bucket_key,
        make_http_server,
        make_serve_match_step,
        payload_spec,
    )

    config, params = _load_model(args)
    if args.conv4d_impl:
        config = config.replace(conv4d_impl=args.conv4d_impl)
    if args.nc_topk >= 0:
        config = config.replace(nc_topk=args.nc_topk)
    if getattr(config, "refine_factor", 0):
        # refinement is a dispatch TIER here, not a baked-in config
        config = config.replace(refine_factor=0)

    apply_fn = make_serve_match_step(config)
    degraded_apply_fn = None
    refined_apply_fn = None
    if args.degrade >= 0:
        degraded_apply_fn = make_serve_match_step(
            config.replace(nc_topk=args.degrade)
        )
    if args.refine > 0:
        refined_apply_fn = make_serve_match_step(
            config.replace(
                refine_factor=args.refine,
                refine_topk=args.refine_topk,
                refine_radius=args.refine_radius,
            )
        )

    hang = args.hang_timeout if args.hang_timeout > 0 else None
    common = dict(
        max_batch=args.max_batch,
        max_wait=args.max_wait_ms / 1e3,
        queue_limit=args.queue_limit,
        host_workers=args.host_workers,
        degraded_apply_fn=degraded_apply_fn,
        refined_apply_fn=refined_apply_fn,
        deadline_flush=args.deadline_flush,
        per_bucket_quality=args.per_bucket_quality,
    )
    registry = telemetry.default_registry() if args.telemetry else None
    if args.fleet:
        server = ServeFleet(
            apply_fn, params,
            replicas=(args.replicas if args.replicas > 0 else None),
            replica_hang_timeout=hang,
            registry=registry,
            **common,
        )
        if args.telemetry:
            session = telemetry.active()
            for rid, eng in server.engines().items():
                session.add_registry(eng.metrics, tags={"replica": rid})
    else:
        server = ServeEngine(
            apply_fn, params, registry=registry, hang_timeout=hang,
            **common,
        )

    # warmup: square image buckets at each --warm-sizes side, keyed by
    # the SAME default_bucket_key the front door computes per request
    spec = BucketSpec(args.image_size, max(config.relocalization_k_size, 1))
    sizes = (
        [int(s) for s in args.warm_sizes.split(",")]
        if args.warm_sizes else [args.image_size]
    )
    bucket_specs = []
    for side in sizes:
        h, w = spec.bucket(side, side)
        payload = {
            "source_image": np.zeros((h, w, 3), np.float32),
            "target_image": np.zeros((h, w, 3), np.float32),
        }
        bucket_specs.append(
            (default_bucket_key(payload), payload_spec(payload))
        )
    n_programs = server.warmup(bucket_specs)
    print(f"warmup: {n_programs} programs over {len(bucket_specs)} "
          "bucket(s)", flush=True)

    front = HttpFrontDoor(
        server,
        registry=(registry if registry is not None
                  else getattr(server, "metrics", None)),
        request_timeout_s=args.request_timeout,
        drain_timeout_s=args.drain_timeout,
    )
    httpd = make_http_server(front, host=args.host, port=args.port)
    host, port = httpd.server_address[:2]
    front.mark_ready()
    print(f"serving: http://{host}:{port}", flush=True)

    with PreemptionGuard() as guard:

        def _watch():
            # the HTTP-ordered drain: healthz unready -> engine drain ->
            # listener close (serve_forever then returns below)
            while True:
                if guard.requested:
                    front.begin_drain(timeout=args.drain_timeout)
                    return
                time.sleep(0.05)

        watcher = threading.Thread(
            target=_watch, name="http-preemption-drain", daemon=True
        )
        watcher.start()
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            # Ctrl-C without a SIGTERM: run the same ordered drain
            os.kill(os.getpid(), signal.SIGTERM)
        watcher.join(timeout=args.drain_timeout + 5.0)
    httpd.server_close()

    stats = server.report()
    if args.fleet:
        for rep_stats in stats.get("per_replica", {}).values():
            rep_stats.pop("latencies_s", None)
    else:
        stats.pop("latencies_s", None)
    stats["http_status_tally"] = front.status_tally()
    text = json.dumps(stats, indent=2, sort_keys=True)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
