"""Batched correspondence serving driver (ncnet_tpu.serve).

Feeds a CSV (or directory) of image-pair requests through the serving
engine at a given concurrency and emits a JSON report: pairs/s, batch
occupancy, p50/p95/p99 latency, and the compile accounting (recompiles
after warmup MUST be 0 — the engine AOT-compiles every (bucket,
batch-size) shape up front from the request sweep's shape headers).

Request sources:
  --pairs requests.csv     columns ``source_image,target_image`` (header
                           optional); relative paths resolve against
                           --root (default: the CSV's directory)
  --images DIR             sorted image files paired consecutively
                           ((f0,f1), (f2,f3), ...) — a quick smoke mode

Modes:
  default                  trunk + NC match per batch (dense, or sparse
                           with --nc-topk)
  --feature-store DIR      `GalleryFeatureStore` serving: each image's
                           trunk features are looked up by path (extracted
                           and durably cached on first visit), and the
                           device step runs the NC match from features —
                           the many-queries-against-shared-gallery shape
  --sequential             per-pair baseline on the SAME workload (one
                           jitted per-pair call, host prep inline): the
                           denominator of the speedup PERF.md records

SLOs & resilience (ncnet_tpu.serve.resilience):
  --deadline-ms N          per-request deadline; requests the EWMA says
                           cannot finish in time are SHED at admission,
                           accepted ones whose budget expires in-pipeline
                           resolve with DeadlineExceeded — both tallied
  --admission-timeout-ms   bound submit blocking; on a full queue the
                           client sees a typed AdmissionRejected with a
                           retry-after hint and retries (counted)
  --degrade K              pre-warm a second program at nc_topk=K and let
                           the hysteresis controller flip dispatch to it
                           under sustained queue pressure (back when it
                           clears); flips + degraded batches reported
  --refine R               pre-warm a THIRD program family per bucket: the
                           coarse-to-fine refined forward (ncnet_tpu.refine)
                           at pool factor R — the quality ladder's top
                           rung. With --degrade the ladder walks
                           refined <-> standard <-> degraded on queue
                           pressure; without it, refined <-> standard.
                           Every rung is AOT-warmed, so a tier flip never
                           compiles: quality itself becomes the SLO knob
  --hang-timeout S         dispatch heartbeat watchdog (must exceed the
                           worst-case batch latency incl. live compiles)
  --drain-timeout S        SIGTERM stops admission and drains under this
                           deadline; every accepted future resolves
                           (result or typed shed) before exit

Fleet & mesh (ncnet_tpu.serve.fleet / PR 11):
  --fleet / --replicas N   one device-pinned warmed engine per chip
                           behind the bucket-affinity best-ETA router;
                           fleet-wide admission sheds only when NO
                           replica can meet the budget, a dead replica's
                           queued work requeues onto survivors. On a
                           CPU-only machine --replicas N provisions an
                           N-virtual-device proxy mesh automatically
                           (XLA_FLAGS, set before jax imports).
  --shard-batch N          single-engine mode: batches padded to >= N
                           rows run a shard_map variant of the bucket
                           program spanning the device mesh (bitwise
                           the single-device program per shard);
                           mutually exclusive with --fleet — a pinned
                           replica owns one chip, the sharded program
                           owns the mesh.

Fault drills: the engine fires the ``serve.request``,
``serve.worker.crash``, ``serve.dispatch.hang``, and
``serve.readout.delay`` points — and the fleet adds
``serve.replica.kill`` + ``serve.router.route`` — so e.g.
``NCNET_FAULTS="serve.worker.crash=crash@3"`` proves from the command
line that a crashed prep worker fails ONLY its in-flight request
(typed StageFailure), restarts, and recompiles_after_warmup stays 0,
and ``NCNET_FAULTS="serve.replica.kill=crash@40"`` runs the replica
chaos drill under real traffic.

Example:
  python scripts/serve.py --checkpoint ck.msgpack --pairs req.csv \
      --concurrency 8 --max-batch 8 --deadline-ms 250 --degrade 16 \
      --report serve_report.json
"""

import argparse
import csv
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp")


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="ncnet_tpu batched serving driver")
    p.add_argument("--checkpoint", type=str, required=True,
                   help=".msgpack checkpoint or reference .pth.tar")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--pairs", type=str,
                     help="CSV of source_image,target_image requests")
    src.add_argument("--images", type=str,
                     help="directory; sorted files paired consecutively")
    p.add_argument("--root", type=str, default=None,
                   help="base dir for relative CSV paths (default: CSV dir)")
    p.add_argument("--image-size", type=int, default=400,
                   help="bucket universe: max image side after resize")
    p.add_argument("--concurrency", type=int, default=8,
                   help="client threads submitting requests")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="micro-batcher deadline: max ms a request waits "
                        "for batch-mates before a partial batch flushes")
    p.add_argument("--queue-limit", type=int, default=64,
                   help="bounded submit queue (backpressure)")
    p.add_argument("--host-workers", type=int, default=2,
                   help="host decode/resize worker threads")
    p.add_argument("--prep-retries", type=int, default=0,
                   help="per-request prep retries with exponential "
                        "backoff (the data loader's transient-I/O "
                        "retry, data.loader.retry_call)")
    p.add_argument("--repeat", type=int, default=1,
                   help="serve the request list this many times")
    p.add_argument("--nc-topk", type=int, default=-1,
                   help="override config.nc_topk (sparse NC band; -1 keeps "
                        "the checkpoint's setting)")
    p.add_argument("--bf16", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="bf16 features/correlation/NC compute for the "
                        "serving forward (readout stays f32). Default: "
                        "the checkpoint's recorded dtype; --bf16 / "
                        "--no-bf16 override in either direction (master "
                        "weights are f32 either way)")
    p.add_argument("--conv4d_impl", type=str, default="tlc",
                   help="conv4d lowering for the serving forward (empty "
                        "keeps the checkpoint's; 'tlc' measured fastest "
                        "forward-only, benchmarks/micro_pck.py)")
    p.add_argument("--feature-store", type=str, default=None,
                   help="GalleryFeatureStore dir: serve the NC match from "
                        "path-keyed cached trunk features")
    p.add_argument("--compile-cache", type=str, default="none",
                   help="persistent XLA compile cache dir ('none' off)")
    p.add_argument("--sequential", action="store_true",
                   help="run the per-pair sequential baseline instead of "
                        "the batched engine")
    p.add_argument("--fleet", action="store_true",
                   help="serve through a ServeFleet: one device-pinned "
                        "engine per chip behind the best-ETA router")
    p.add_argument("--replicas", type=int, default=0,
                   help="fleet size (implies --fleet; 0 with --fleet "
                        "means one replica per visible device). On CPU "
                        "this provisions an N-virtual-device proxy mesh")
    p.add_argument("--shard-batch", type=int, default=0,
                   help="single-engine: run batches padded to >= N rows "
                        "through the shard_map bucket program spanning "
                        "the device mesh (0 disables; exclusive with "
                        "--fleet)")
    p.add_argument("--deadline-ms", type=float, default=0.0,
                   help="per-request SLO deadline in ms (0 disables); "
                        "drives admission-control shedding and "
                        "in-pipeline deadline drops")
    p.add_argument("--admission-timeout-ms", type=float, default=-1.0,
                   help="max ms submit may block on a full queue before "
                        "AdmissionRejected (client retries after its "
                        "hint; -1 blocks indefinitely, 0 never blocks)")
    p.add_argument("--degrade", type=int, default=-1,
                   help="nc_topk for the DEGRADED program the overload "
                        "controller flips to (-1 disables degradation)")
    p.add_argument("--degrade-high", type=float, default=0.75,
                   help="queue-pressure fraction that flips dispatch to "
                        "the degraded program (hysteresis high water)")
    p.add_argument("--degrade-low", type=float, default=0.25,
                   help="queue-pressure fraction that flips back "
                        "(hysteresis low water)")
    p.add_argument("--refine", type=int, default=0, metavar="R",
                   help="pre-warm the coarse-to-fine REFINED program "
                        "(ncnet_tpu.refine) at pool factor R as the "
                        "quality ladder's top rung; dispatch walks down "
                        "to standard (and --degrade, when set) under "
                        "sustained queue pressure and back up when it "
                        "clears — zero recompiles across tier flips "
                        "(0 disables; the feature grid image_size/16 "
                        "must divide by R)")
    p.add_argument("--refine-topk", type=int, default=16,
                   dest="refine_topk", metavar="K",
                   help="with --refine: coarse-band width (survivor "
                        "count re-scored at high res)")
    p.add_argument("--refine-radius", type=int, default=0,
                   dest="refine_radius",
                   help="with --refine: extra window reach in coarse "
                        "cells around each survivor")
    p.add_argument("--hang-timeout", type=float, default=30.0,
                   help="dispatch heartbeat watchdog seconds (0 "
                        "disables); must exceed the worst-case batch "
                        "latency including live compiles")
    p.add_argument("--drain-timeout", type=float, default=10.0,
                   help="graceful-drain deadline on SIGTERM/shutdown: "
                        "unresolved futures past it get a typed shed")
    p.add_argument("--report", type=str, default=None,
                   help="write the JSON report here too")
    p.add_argument("--telemetry", type=str, default=None, metavar="DIR",
                   help="write a telemetry run under DIR "
                        "(ncnet_tpu.telemetry): the engine's metrics and "
                        "per-stage spans land in a durable per-process "
                        "events_proc<P>.jsonl plus a .prom snapshot; render "
                        "with scripts/telemetry_report.py DIR")
    return p.parse_args(argv)


def load_requests(args):
    """[(src_path, tgt_path), ...] absolute, in request order."""
    if args.images:
        files = sorted(
            os.path.join(args.images, f)
            for f in os.listdir(args.images)
            if f.lower().endswith(_IMAGE_EXTS)
        )
        if len(files) < 2:
            raise ValueError(f"--images {args.images}: need >= 2 images")
        pairs = [
            (files[i], files[i + 1]) for i in range(0, len(files) - 1, 2)
        ]
    else:
        root = args.root or os.path.dirname(os.path.abspath(args.pairs))
        pairs = []
        with open(args.pairs, newline="") as f:
            for row in csv.reader(f):
                if len(row) < 2:
                    continue
                a, b = row[0].strip(), row[1].strip()
                if "source" in a.lower() and "target" in b.lower():
                    continue  # header row
                pairs.append(
                    (os.path.join(root, a), os.path.join(root, b))
                )
        if not pairs:
            raise ValueError(f"--pairs {args.pairs}: no requests parsed")
    return pairs * args.repeat


def image_shape(path):
    """(h, w) from the file header only — no pixel decode."""
    from PIL import Image

    with Image.open(path) as im:
        w, h = im.size
    return h, w


def main(argv=None):
    args = parse_args(argv)
    if args.replicas > 0:
        args.fleet = True
    if args.fleet and args.sequential:
        raise SystemExit("--fleet and --sequential are exclusive")
    if args.fleet and args.shard_batch > 0:
        raise SystemExit(
            "--fleet and --shard-batch are exclusive: a pinned replica "
            "owns one chip, the sharded program owns the whole mesh"
        )
    if args.fleet and args.replicas > 1:
        # CPU proxy mesh: must happen BEFORE anything imports jax (the
        # backend reads XLA_FLAGS once at client creation). A no-op when
        # jax is already in, the flag is already set, or on real TPUs
        # (the flag only multiplies the HOST platform's device count).
        flags = os.environ.get("XLA_FLAGS", "")
        if ("jax" not in sys.modules
                and "xla_force_host_platform_device_count" not in flags):
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.replicas}"
            ).strip()

    from ncnet_tpu import telemetry

    if args.telemetry:
        # one process-wide registry: the engine registers its metrics in
        # it, the session snapshots it at stop()
        telemetry.start(args.telemetry, label="serve")
        print(f"telemetry: {args.telemetry} "
              "(render with scripts/telemetry_report.py)", flush=True)
    try:
        return _run(args, telemetry)
    finally:
        telemetry.stop()  # no-op without --telemetry


def _run(args, telemetry):
    from ncnet_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(args.compile_cache)

    import numpy as np

    import jax

    from ncnet_tpu.data.images import (
        load_image,
        normalize_image_np,
        resize_bilinear_np,
    )
    from ncnet_tpu.resilience.signals import PreemptionGuard
    from ncnet_tpu.serve import (
        AdmissionRejected,
        BucketSpec,
        DeadlineExceeded,
        HysteresisController,
        QualityLadder,
        ReplicaDown,
        RequestShed,
        ServeEngine,
        ServeFleet,
        drain_on_preemption,
        make_serve_match_step,
        pair_bucket,
        payload_spec,
    )

    if args.checkpoint.endswith((".pth.tar", ".pth")):
        from ncnet_tpu.utils.convert_torch import convert_checkpoint

        config, params = convert_checkpoint(args.checkpoint)
    else:
        from ncnet_tpu.train.checkpoint import load_checkpoint

        ck = load_checkpoint(args.checkpoint)
        config, params = ck.config, ck.params
    if args.conv4d_impl:
        config = config.replace(conv4d_impl=args.conv4d_impl)
    if args.nc_topk >= 0:
        config = config.replace(nc_topk=args.nc_topk)
    if args.bf16 is not None:
        config = config.replace(half_precision=args.bf16)

    requests = load_requests(args)
    spec = BucketSpec(args.image_size, max(config.relocalization_k_size, 1))

    def load_resized(path):
        img = load_image(path)
        h, w = spec.bucket(img.shape[0], img.shape[1])
        return normalize_image_np(resize_bilinear_np(img, h, w)).astype(
            np.float32
        )

    store = None
    extractor = None
    if args.feature_store:
        from ncnet_tpu.features import GalleryFeatureStore, trunk_digest
        from ncnet_tpu.models.immatchnet import extract_features

        store = GalleryFeatureStore.open_or_create(
            args.feature_store,
            trunk_digest(params["feature_extraction"], config, None),
            config,
        )
        extractor = jax.jit(
            lambda p, img: extract_features(p, config, img)
        )

        def featurize(path):
            key = os.path.basename(path)
            if store.has(key):
                return np.asarray(store.get(key))[0]
            feats = np.asarray(
                extractor(params, load_resized(path)[None])
            )
            store.put(key, feats)
            return feats[0]

        def prep(pair):
            src, tgt = (featurize(p) for p in pair)
            return (src.shape, tgt.shape), {
                "source_image": src, "target_image": tgt,
            }
    else:
        def prep(pair):
            src, tgt = (load_resized(p) for p in pair)
            return (src.shape[:2], tgt.shape[:2]), {
                "source_image": src, "target_image": tgt,
            }

    if getattr(config, "refine_factor", 0):
        # serving treats refinement as a dispatch TIER, not a baked-in
        # config: the standard program strips it, --refine rebuilds it
        # as the ladder's top rung
        config = config.replace(refine_factor=0)
    apply_fn = make_serve_match_step(
        config, from_features=bool(args.feature_store)
    )
    degraded_apply_fn = None
    refined_apply_fn = None
    controller = None
    quality_controller = None
    if args.degrade >= 0:
        # the overload fallback: the SAME serving forward at a sparse
        # nc_topk band (arXiv:2004.10566 reproduction, PR 4) — ~3x
        # analytic NC FLOP reduction at K=16, pre-warmed alongside the
        # dense program so a flip never compiles
        degraded_apply_fn = make_serve_match_step(
            config.replace(nc_topk=args.degrade),
            from_features=bool(args.feature_store),
        )
    if args.refine > 0:
        grid = max(args.image_size // 16, 1)
        if grid % args.refine:
            raise SystemExit(
                f"--refine {args.refine}: the {grid}x{grid} feature grid "
                f"at --image-size {args.image_size} does not divide by "
                f"the pool factor (each bucket's grid must divide)"
            )
        # the quality ceiling: coarse band at --refine-topk on pooled
        # features, gather-only re-score of the survivors at high res
        # (ncnet_tpu.refine, same no-scatter discipline as the band) —
        # pre-warmed per (bucket, batch-size) alongside the others
        refined_apply_fn = make_serve_match_step(
            config.replace(
                refine_factor=args.refine,
                refine_topk=args.refine_topk,
                refine_radius=args.refine_radius,
            ),
            from_features=bool(args.feature_store),
        )
    if args.refine > 0:
        quality_controller = QualityLadder(
            rungs=(("refined", "standard", "degraded")
                   if degraded_apply_fn is not None
                   else ("refined", "standard")),
            high=args.degrade_high, low=args.degrade_low,
        )
    elif args.degrade >= 0:
        controller = HysteresisController(
            high=args.degrade_high, low=args.degrade_low
        )

    report = {
        "mode": ("sequential" if args.sequential
                 else "fleet" if args.fleet else "serve"),
        "n_requests": len(requests),
        "concurrency": args.concurrency,
        "max_batch": args.max_batch,
        "max_wait_ms": args.max_wait_ms,
        "nc_topk": int(config.nc_topk),
        "feature_store": bool(args.feature_store),
        "deadline_ms": args.deadline_ms,
        "degrade_topk": args.degrade,
        "refine_factor": args.refine,
    }

    if args.sequential:
        # the per-pair baseline: one jitted wrapper (per-shape cache),
        # host prep inline on this thread, synchronous readout. Latency
        # accounting runs through the same telemetry histogram as the
        # batched engine, so both modes report identical keys from one
        # implementation (telemetry.registry.percentiles).
        from ncnet_tpu.telemetry import trace
        from ncnet_tpu.telemetry.registry import percentiles

        m_lat = telemetry.default_registry().histogram(
            "serve_request_latency_seconds",
            "sequential-baseline per-pair latency",
            buckets=telemetry.DEFAULT_LATENCY_BUCKETS,
        )
        jitted = jax.jit(apply_fn)
        t0 = time.perf_counter()
        for pair in requests:
            t_req = time.perf_counter()
            with trace.span("serve/prep"):
                _, payload = prep(pair)
            with trace.span("serve/dispatch"):
                out = jitted(
                    params, {k: v[None] for k, v in payload.items()}
                )
            with trace.span("serve/readout"):
                jax.tree_util.tree_map(np.asarray, out)
            m_lat.observe(time.perf_counter() - t_req)
        wall = time.perf_counter() - t0
        report.update(wall_s=wall, pairs_per_s=len(requests) / wall)
        for pname, v in percentiles(m_lat.samples).items():
            report[f"latency_{pname}_ms"] = float(v) * 1e3
    else:
        deadline_s = (
            args.deadline_ms / 1e3 if args.deadline_ms > 0 else None
        )
        adm_timeout = (
            None if args.admission_timeout_ms < 0
            else args.admission_timeout_ms / 1e3
        )
        hang = args.hang_timeout if args.hang_timeout > 0 else None
        shard_mesh = None
        if args.shard_batch > 0:
            from ncnet_tpu.parallel.mesh import make_mesh

            shard_mesh = make_mesh()
        common = dict(
            max_batch=args.max_batch,
            max_wait=args.max_wait_ms / 1e3,
            queue_limit=args.queue_limit,
            host_workers=args.host_workers,
            prep_fn=prep,
            prep_retries=args.prep_retries,
            degraded_apply_fn=degraded_apply_fn,
            refined_apply_fn=refined_apply_fn,
        )
        if args.fleet:
            # per-replica engines keep PRIVATE registries (and, with
            # --degrade/--refine, private default-threshold controllers
            # — one shared mutable controller would race across dispatch
            # threads); the session snapshots each with a {replica=R}
            # tag, the fleet's own counters land in the default registry
            server = ServeFleet(
                apply_fn, params,
                replicas=(args.replicas if args.replicas > 0 else None),
                replica_hang_timeout=hang,
                registry=(telemetry.default_registry() if args.telemetry
                          else None),
                **common,
            )
            report["replicas"] = len(server.replica_ids())
            if args.telemetry:
                session = telemetry.active()
                for rid, eng in server.engines().items():
                    session.add_registry(
                        eng.metrics, tags={"replica": rid}
                    )
        else:
            server = ServeEngine(
                apply_fn, params,
                registry=(telemetry.default_registry() if args.telemetry
                          else None),
                degrade_controller=controller,
                quality_controller=quality_controller,
                hang_timeout=hang,
                shard_mesh=shard_mesh,
                shard_min_batch=args.shard_batch,
                **common,
            )
        with PreemptionGuard() as guard, server as engine:
            # SIGTERM -> stop admission (clients poll guard.requested),
            # drain under the deadline: every accepted future resolves
            drain_on_preemption(
                engine, guard, timeout=args.drain_timeout
            )
            # warmup: one prep per distinct bucket discovers the payload
            # spec (for images this only needs the file header; the
            # feature path additionally primes the store), then every
            # (bucket, batch-size) program AOT-compiles before the clock
            seen = {}
            for pair in requests:
                key = pair_bucket(
                    spec, image_shape(pair[0]), image_shape(pair[1])
                )
                if key not in seen:
                    real_key, payload = prep(pair)
                    seen[key] = (real_key, payload_spec(payload))
            n_programs = engine.warmup(seen.values())
            report["buckets"] = len(seen)
            report["compiled_programs"] = n_programs
            print(f"warmup: {n_programs} programs over {len(seen)} "
                  f"bucket(s); serving {len(requests)} requests",
                  flush=True)

            fut_lock = threading.Lock()
            idx = iter(range(len(requests)))
            slots = [None] * len(requests)
            tally = {"admission_retries": 0}

            def client():
                while True:
                    if guard.requested:
                        return  # admission stopped: drain in progress
                    with fut_lock:
                        i = next(idx, None)
                    if i is None:
                        return
                    while True:
                        try:
                            if args.fleet:
                                # fleet routing owns placement; a full
                                # replica queue blocks inside dispatch
                                # (natural backpressure)
                                slots[i] = engine.submit(
                                    requests[i], deadline_s=deadline_s
                                )
                            else:
                                slots[i] = engine.submit(
                                    requests[i],
                                    timeout=adm_timeout,
                                    deadline_s=deadline_s,
                                )
                            break
                        except AdmissionRejected as exc:
                            # typed backpressure: honor the engine's
                            # retry-after hint instead of hot-spinning
                            with fut_lock:
                                tally["admission_retries"] += 1
                            time.sleep(exc.retry_after_s or 0.005)
                            if guard.requested:
                                return
                        except RuntimeError:
                            return  # engine closed mid-drain

            t0 = time.perf_counter()
            threads = [
                threading.Thread(target=client)
                for _ in range(args.concurrency)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            # bounded drain (idempotent with the context close): after
            # this EVERY accepted future below is resolved
            engine.drain(timeout=args.drain_timeout)
            ok = failed = shed = deadline_exceeded = unsubmitted = 0
            replica_down = 0
            for fut in slots:
                if fut is None:
                    unsubmitted += 1  # preemption stopped admission
                    continue
                try:
                    fut.result(timeout=0)
                    ok += 1
                except DeadlineExceeded:
                    deadline_exceeded += 1
                except RequestShed:
                    shed += 1
                except ReplicaDown:
                    replica_down += 1  # dispatched batch died with its replica
                except Exception:  # nclint: disable=swallowed-exception -- tallied: the per-type breakdown lives in the engine's typed counters
                    failed += 1
            wall = time.perf_counter() - t0
            stats = engine.report()
        if args.fleet:
            for rep_stats in stats["per_replica"].values():
                rep_stats.pop("latencies_s", None)
            report["replica_down_results"] = replica_down
        else:
            stats.pop("latencies_s")
        report.update(stats)
        report.update(
            wall_s=wall,
            pairs_per_s=ok / wall,
            failed=failed,
            shed=shed,
            deadline_exceeded=deadline_exceeded,
            unsubmitted=unsubmitted,
            admission_retries=tally["admission_retries"],
            preempted=guard.requested,
        )

    text = json.dumps(report, indent=2, sort_keys=True)
    print(text)
    if args.report:
        with open(args.report, "w") as f:
            f.write(text + "\n")
    return report


if __name__ == "__main__":
    main()
