"""`lock_drill` — the runtime concurrency audit's CI gate.

The dynamic counterpart of the `unguarded-shared-state` static rules:
enables the `OrderedLock` audit (`ncnet_tpu.analysis.concurrency`),
installs a seeded `ScheduleFuzzer`, and drives a 3-replica CPU toy
fleet through the PR-11 chaos scenario — replica kill mid-load,
quarantine, rejoin, more traffic, close — while every serve-layer lock
records its acquisition graph. Exit status is 0 only when the observed
graph has no lock-order cycle and no unsuppressed finding at or above
``--fail-on`` remains; the CI gate is simply

    JAX_PLATFORMS=cpu python scripts/lock_drill.py

Output defaults to a human report (per-lock held-time stats, edges,
findings); with ``--format json|sarif`` it shares the `Finding` schema
nclint and `scripts/audit.py` emit, so one consumer handles all three
analyzers.
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from ncnet_tpu.analysis import concurrency  # noqa: E402
from ncnet_tpu.analysis.findings import (  # noqa: E402
    SEVERITY_ORDER,
    format_json,
    format_sarif,
    format_text,
)


def run_drill(submits=60, kill_at=10, seed=1311, fuzz_p=0.25):
    """Kill/rejoin chaos drill on a toy CPU fleet with the lock audit
    live. Returns the number of resolved futures (all submits must
    settle — lost requests fail the drill before any lock finding)."""
    import numpy as np
    import jax.numpy as jnp

    from ncnet_tpu.resilience import faultinject
    from ncnet_tpu.serve.fleet import ServeFleet
    from ncnet_tpu.serve.resilience import ReplicaDown

    params = {"w": jnp.asarray(3.0, jnp.float32)}
    key = ("k", 2)
    spec = {"x": ((2,), np.float32)}

    def apply_fn(p, batch):
        return {"y": batch["x"] * p["w"]}

    resolved = 0
    with concurrency.ScheduleFuzzer(seed=seed, p=fuzz_p, max_sleep_s=5e-5):
        fleet = ServeFleet(
            apply_fn, params, replicas=3, max_batch=4, max_wait=0.002,
        )
        try:
            fleet.warmup([(key, spec)])
            faultinject.inject("serve.replica.kill", "crash", at=kill_at)
            futs = [
                fleet.submit(
                    key=key,
                    payload={"x": np.full((2,), float(i), np.float32)},
                )
                for i in range(submits)
            ]
            for f in futs:
                try:
                    f.result(timeout=30)
                except ReplicaDown as exc:
                    if not exc.dispatched:
                        raise
                resolved += 1
            faultinject.clear()
            for rid in fleet.quarantined_ids():
                fleet.rejoin(rid)
            post = [
                fleet.submit(
                    key=key,
                    payload={"x": np.full((2,), float(i), np.float32)},
                )
                for i in range(submits // 3)
            ]
            for f in post:
                f.result(timeout=30)
                resolved += 1
        finally:
            faultinject.clear()
            fleet.close()
    return resolved


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="lock_drill",
        description="chaos drill under the runtime lock audit (rule "
                    "catalog: ncnet_tpu/analysis/README.md)",
    )
    p.add_argument("--format", choices=["text", "json", "sarif"],
                   default="text", dest="fmt",
                   help="output format (default: human report)")
    p.add_argument("--fail-on", choices=sorted(SEVERITY_ORDER),
                   default="error",
                   help="lowest severity that fails the run (default: "
                        "error — held-time outliers on a fuzzed CPU "
                        "drill are advisory)")
    p.add_argument("--seed", type=int, default=1311,
                   help="ScheduleFuzzer seed (default: 1311)")
    p.add_argument("--submits", type=int, default=60,
                   help="requests before the rejoin phase (default: 60)")
    args = p.parse_args(argv)

    concurrency.clear()
    concurrency.enable()
    resolved = run_drill(submits=args.submits, seed=args.seed)
    findings = concurrency.lock_findings()
    rep = concurrency.report()

    if args.fmt == "json":
        print(format_json(findings, tool="lock_drill"))
    elif args.fmt == "sarif":
        print(format_sarif(
            findings, "lock-audit", concurrency.runtime_rules_meta()
        ))
    else:
        print(f"lock drill: {resolved} request(s) resolved, "
              f"{len(rep['locks'])} audited lock(s), "
              f"{len(rep['edges'])} acquisition edge(s)")
        for name in sorted(rep["locks"]):
            s = rep["locks"][name]
            print(f"  {name}: {s['acquires']} acquires, "
                  f"max held {s['max_held_s'] * 1e3:.3f} ms")
        if rep["cycles"]:
            for cyc in rep["cycles"]:
                print(f"  CYCLE: {' -> '.join(cyc + cyc[:1])}")
        print()
        print(format_text(findings))
    threshold = SEVERITY_ORDER[args.fail_on]
    gating = [f for f in findings if SEVERITY_ORDER[f.severity] >= threshold]
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
