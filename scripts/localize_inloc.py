"""InLoc localization CLI — the MATLAB stages as one Python command.

Equivalent to compute_densePE_NCNet.m: for every query in the shortlist,
load the matches dumped by scripts/eval_inloc.py, estimate a pose per
top-N pano with P3P LO-RANSAC (ncnet_tpu.eval.localize — the
ir_top100_NC4D_localization_pnponly.m stage), optionally re-rank the
candidates by dense pose verification (--densePV, the
ht_top10_NC4D_PV_localization.m stage: render the scan point cloud at
each candidate pose, dense-descriptor similarity), and — when
ground-truth poses are provided — print the localization-rate curve
(ht_plotcurve_WUSTL.m semantics: position threshold sweep 0..2 m,
orientation gated at 10 deg).

Data layout mirrors the InLoc distribution: RGBD cutouts as .mat files
containing ``XYZcut`` [h, w, 3]; scan alignment transforms as text files
whose last 4 whitespace-separated lines hold the 4x4 local-to-global
matrix (load_WUSTL_transformation's ``P_after``).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


import functools


@functools.lru_cache(maxsize=64)
def load_cutout(path):
    """Cached cutout loader: the 356 queries' top-10 shortlists overlap
    heavily, so caching cuts thousands of multi-MB loadmat calls down to
    the number of distinct cutouts."""
    from scipy.io import loadmat

    return loadmat(path)["XYZcut"]


def parse_cutout_name(pano_fn):
    """'<floor>/<scene>_cutout_<scan>_<yaw>_<pitch>.jpg' ->
    (floor, scene_id, scan_id) — the parse_WUSTL_cutoutname role."""
    floor = pano_fn.split("/")[0]
    parts = os.path.basename(pano_fn).split("_")
    return floor, parts[0], parts[2]


def _solve_shortlist_jax(tentatives, task):
    """Batched device solve of ONE query's whole shortlist: every pano's
    tentative set padded to a common pose bucket, one `localize_poses`
    call across the shortlist (batch axis = panos). The per-pair NumPy
    LO-RANSAC becomes a single static-shape XLA program invocation."""
    import numpy as np

    from ncnet_tpu.localize import (
        PoseRequest,
        localize_poses,
        prep_pose_request,
    )

    preps = [
        prep_pose_request(
            PoseRequest.from_tentatives(t, seed=task["seed"])
        )
        for t in tentatives
    ]
    n_pad = max(key[1] for key, _ in preps)

    def pad_to(a, fill):
        short = n_pad - a.shape[0]
        if short == 0:
            return a
        return np.concatenate(
            [a, np.full((short,) + a.shape[1:], fill, a.dtype)], axis=0
        )

    batch = {
        name: np.stack([pad_to(p[name], 0) for _, p in preps])
        for name in ("rays", "points", "mask")
    }
    out = localize_poses(
        batch["rays"],
        batch["points"],
        batch["mask"],
        np.stack([p["seed"] for _, p in preps]),
        n_hypotheses=task["n_hypotheses"],
        thr_deg=task["pnp_thr_deg"],
    )
    found = np.asarray(out["found"])
    poses = np.asarray(out["P"], np.float64)
    return [
        poses[j].tolist() if found[j] else None for j in range(len(preps))
    ]


def _localize_query(task):
    """One query's PnP stage (worker-safe: module-level + picklable args;
    the reference runs exactly this loop under MATLAB parfor,
    parfor_NC4D_PE_pnponly.m). Returns the result entry dict."""
    from scipy.io import loadmat

    from ncnet_tpu.eval.localize import pnp_localize_pair

    q = task["q"]
    use_jax = task["backend"] == "jax"
    matches = loadmat(task["match_path"])["matches"]  # [1, Npanos, N, 5]
    from PIL import Image

    with Image.open(task["query_img"]) as im:
        qw, qh = im.size
    entry = {"queryname": task["query_fn"], "topNname": [], "P": []}
    tentatives = []
    for idx, pano_fn in enumerate(task["pano_fns"][: matches.shape[1]]):
        cutout = load_cutout(
            os.path.join(task["cutout_dir"], pano_fn + ".mat")
        )
        align = None
        if task["transform_dir"]:
            floor, scene_id, scan_id = parse_cutout_name(pano_fn)
            align = load_alignment(
                os.path.join(
                    task["transform_dir"], floor, "transformations",
                    f"{scene_id}_trans_{scan_id}.txt",
                )
            )
        out = pnp_localize_pair(
            matches[0, idx],
            (qh, qw),
            cutout.shape[:2],
            cutout,
            task["focal"],
            alignment=align,
            score_thr=task["score_thr"],
            pnp_thr_deg=task["pnp_thr_deg"],
            seed=task["seed"],
            solve=not use_jax,
        )
        entry["topNname"].append(pano_fn)
        if use_jax:
            tentatives.append(out["tentatives_3d"])
        else:
            entry["P"].append(
                None if out["P"] is None else out["P"].tolist()
            )
    if use_jax:
        entry["P"] = _solve_shortlist_jax(tentatives, task)
    return q, entry


@functools.lru_cache(maxsize=256)
def load_alignment(path):
    """Last 4 numeric rows of the transformation txt -> [4, 4] P_after."""
    rows = []
    with open(path) as f:
        for line in f:
            vals = line.split()
            if len(vals) == 4:
                try:
                    rows.append([float(v) for v in vals])
                except ValueError:
                    rows = []
    if len(rows) < 4:
        raise ValueError(f"no 4x4 transform found in {path}")
    return np.asarray(rows[-4:], np.float64)


def main():
    from scipy.io import loadmat

    from ncnet_tpu.eval.inloc import _to_str
    from ncnet_tpu.eval.localize import (
        localization_rate_curve,
        pose_distance,
    )

    p = argparse.ArgumentParser(description="InLoc PnP localization")
    p.add_argument("--matches_dir", required=True,
                   help="matches/<experiment> dir from scripts/eval_inloc.py")
    p.add_argument("--shortlist", required=True)
    p.add_argument("--cutout_dir", required=True,
                   help="dir of RGBD cutout .mat files (XYZcut)")
    p.add_argument("--transform_dir", default="",
                   help="dir of per-scan alignment txt files; empty = "
                        "identity (cutouts already global)")
    p.add_argument("--query_dir", required=True)
    p.add_argument("--focal", type=float, default=4032 * 28.0 / 36.0,
                   help="query focal length in pixels (iPhone 7 default)")
    p.add_argument("--n_queries", type=int, default=356)
    p.add_argument("--n_panos", type=int, default=10)
    p.add_argument("--score_thr", type=float, default=0.75)
    p.add_argument("--pnp_thr_deg", type=float, default=0.2)
    p.add_argument("--backend", choices=("numpy", "jax"), default="numpy",
                   help="PnP back-end: 'numpy' = per-pair host LO-RANSAC "
                        "(eval.localize, adaptive iteration count); "
                        "'jax' = the batched fixed-hypothesis XLA program "
                        "(ncnet_tpu.localize) — one solve per query "
                        "across its whole shortlist")
    p.add_argument("--n_hypotheses", type=int, default=64,
                   help="--backend jax: static RANSAC hypothesis count "
                        "per pair (the serving path's primary rung)")
    p.add_argument("--seed", type=int, default=0,
                   help="RANSAC sample seed (both back-ends)")
    p.add_argument("--refposes", default="",
                   help=".mat with DUC1_RefList/DUC2_RefList GT poses; "
                        "prints the localization curve when given")
    p.add_argument("--densePV", action="store_true",
                   help="re-rank pose candidates by dense pose "
                        "verification (render the scan at each candidate "
                        "pose, dense-descriptor similarity); needs "
                        "--scan_dir")
    p.add_argument("--scan_dir", default="",
                   help="scan point-cloud root: "
                        "<scan_dir>/<floor>/<scene>_scan_<scan>.mat "
                        "(cell array A: columns X Y Z _ R G B)")
    p.add_argument("--out", default="localization.json")
    p.add_argument("--method", default="ncnet_tpu",
                   help="method label used in the persisted artifact names "
                        "(error_<method>.txt, curve_<method>.png)")
    p.add_argument("--workers", type=int, default=1,
                   help="parallelize the per-query PnP stage over this "
                        "many processes (the reference runs it under "
                        "MATLAB parfor); cutout caches are per worker")
    args = p.parse_args()
    if args.densePV and not args.scan_dir:
        p.error("--densePV requires --scan_dir")

    from PIL import Image

    db = loadmat(args.shortlist)["ImgList"][0, :]
    tasks = []
    for q in range(min(args.n_queries, len(db))):
        match_path = os.path.join(args.matches_dir, f"{q + 1}.mat")
        if not os.path.exists(match_path):
            print(f"skip query {q + 1}: {match_path} missing", flush=True)
            continue
        query_fn = _to_str(db[q][0])
        tasks.append({
            "q": q,
            "match_path": match_path,
            "query_fn": query_fn,
            "query_img": os.path.join(args.query_dir, query_fn),
            "pano_fns": [
                _to_str(v) for v in db[q][1].ravel()[: args.n_panos]
            ],
            "cutout_dir": args.cutout_dir,
            "transform_dir": args.transform_dir,
            "focal": args.focal,
            "score_thr": args.score_thr,
            "pnp_thr_deg": args.pnp_thr_deg,
            "backend": args.backend,
            "n_hypotheses": args.n_hypotheses,
            "seed": args.seed,
        })

    results = []
    pool = None
    if args.workers > 1:
        import multiprocessing

        # 'spawn', not the default fork: the parent imports jax (via
        # eval.inloc._to_str), and forking after the XLA backend starts
        # threads can deadlock workers (advisor finding, round 4)
        pool = multiprocessing.get_context("spawn").Pool(args.workers)
        # contiguous chunks keep each worker on NEIGHBORING queries,
        # whose top-10 shortlists overlap heavily — that locality is
        # what the per-worker load_cutout/load_alignment caches need
        chunk = max(1, len(tasks) // (4 * args.workers))
        outputs = pool.imap(_localize_query, tasks, chunk)
    else:
        outputs = map(_localize_query, tasks)
    try:
        for q, entry in outputs:
            results.append(entry)
            print(f"query {q + 1}: "
                  f"{sum(p_ is not None for p_ in entry['P'])} poses",
                  flush=True)
    finally:
        if pool is not None:
            pool.close()
            pool.join()

    if args.densePV:
        from ncnet_tpu.eval.pose_verify import (
            prepare_query,
            rerank_by_pose_verification,
            score_prepared,
        )

        @functools.lru_cache(maxsize=4)
        def load_scan(floor, scene_id, scan_id):
            """Colored scan point cloud, GLOBAL coords (at_pv_wrapper.m:
            A{1..3}=XYZ, A{5..7}=RGB, homogeneous P_after transform).
            Cached per SCAN — many cutouts (yaw/pitch views) share one."""
            from scipy.io import loadmat

            cells = loadmat(
                os.path.join(
                    args.scan_dir, floor,
                    f"{scene_id}_scan_{scan_id}.mat",
                )
            )["A"].ravel()
            xyz = np.concatenate([cells[0], cells[1], cells[2]], axis=1)
            rgb = np.concatenate([cells[4], cells[5], cells[6]], axis=1)
            if args.transform_dir:
                P_after = load_alignment(
                    os.path.join(
                        args.transform_dir, floor, "transformations",
                        f"{scene_id}_trans_{scan_id}.txt",
                    )
                )
                # affine application, IDENTICAL to pnp_localize_pair's —
                # the PV render and the PnP pose must share one frame
                xyz = xyz @ P_after[:3, :3].T + P_after[:3, 3]
            return rgb, xyz

        prep_cache = {}

        def score_candidate(entry, j):
            P = entry["P"][j]
            if P is None:
                return 0.0
            if entry["queryname"] not in prep_cache:
                with Image.open(
                    os.path.join(args.query_dir, entry["queryname"])
                ) as im:
                    img = np.asarray(im)
                prep_cache.clear()  # one query's prep live at a time
                prep_cache[entry["queryname"]] = prepare_query(
                    img, args.focal
                )
            rgb, xyz = load_scan(*parse_cutout_name(entry["topNname"][j]))
            return score_prepared(
                prep_cache[entry["queryname"]], rgb, xyz, np.asarray(P)
            )

        results = rerank_by_pose_verification(
            results, score_candidate, top_n=args.n_panos
        )
        print("densePV re-ranking done")

    with open(args.out, "w") as f:
        json.dump(results, f)
    print(f"wrote {args.out}")

    if args.refposes:
        gt = loadmat(args.refposes, squeeze_me=True)
        names, pos_err, ori_err = [], [], []
        for list_name, floor in (("DUC1_RefList", "DUC1"),
                                 ("DUC2_RefList", "DUC2")):
            if list_name not in gt:  # single-floor GT files are legal
                continue
            for rec in np.atleast_1d(gt[list_name]):
                qname = str(rec["queryname"])
                match = next(
                    (r for r in results if r["queryname"] == qname), None
                )
                ok = (
                    match is not None
                    and match["P"]
                    and match["P"][0] is not None
                    and match["topNname"][0].split("/")[0] == floor
                )
                if ok:
                    dp, do = pose_distance(
                        np.asarray(rec["P"]), np.asarray(match["P"][0])
                    )
                else:
                    dp, do = np.inf, np.inf
                names.append(qname)
                pos_err.append(dp)
                ori_err.append(do)
        thr, rate = localization_rate_curve(pos_err, ori_err)
        for t, r in zip(thr, rate):
            print(f"  {t:6.4f} m : {r:5.1f} %")

        # Persist the benchmark's deliverables next to --out, in the
        # spirit of ht_plotcurve_WUSTL.m: a per-query error file
        # (error_<method>.txt, ':15,36,65' — "<queryname> <pos> <ori>"
        # lines, orientation in degrees like max_orierr) and the
        # localization-rate curve figure (':107-111', PNG instead of
        # .fig/.eps).
        out_dir = os.path.dirname(os.path.abspath(args.out))
        err_path = os.path.join(out_dir, f"error_{args.method}.txt")
        with open(err_path, "w") as f:
            for qname, dp, do in zip(names, pos_err, ori_err):
                f.write(f"{qname} {dp:f} {np.rad2deg(do):f}\n")
        from ncnet_tpu.utils.plot import plot_localization_curve, save_plot

        fig = plot_localization_curve(thr, rate, label=args.method)
        curve_path = os.path.join(out_dir, f"curve_{args.method}.png")
        save_plot(curve_path, fig=fig)
        print(f"wrote {err_path} and {curve_path}")


if __name__ == "__main__":
    main()
