"""Populate a frozen-trunk feature cache (ncnet_tpu.features) from a
pair dataset — the one-time backbone pass that `--feature-cache` training
then never re-runs.

Writes one durable digest-guarded store per split under
``<--feature-cache>/<split>`` (the layout ``scripts/train.py
--feature-cache DIR`` consumes). Idempotent: only missing shards are
extracted, so an interrupted extraction resumes where it stopped and a
complete cache is a no-op directory scan.

Example (PF-Pascal paper config):
  python scripts/extract_features.py --feature-cache features/pf-pascal \
      --dataset_image_path datasets/pf-pascal \
      --dataset_csv_path datasets/pf-pascal/image_pairs \
      --fe_weights trained_models/resnet101.pth

With no dataset on disk, pass --synthetic (same generated pairs as
scripts/train.py --synthetic, so the cache slots straight into training).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(
        description="extract frozen-trunk features into a durable cache"
    )
    p.add_argument("--feature-cache", type=str, required=True,
                   dest="feature_cache", metavar="DIR",
                   help="cache root; one store per split is written under "
                        "DIR/<split>")
    p.add_argument("--dataset_image_path", type=str,
                   default="datasets/pf-pascal")
    p.add_argument("--dataset_csv_path", type=str,
                   default="datasets/pf-pascal/image_pairs")
    p.add_argument("--synthetic", action="store_true",
                   help="extract for the synthetic pair datasets (same "
                        "sizes/seeds as scripts/train.py --synthetic)")
    p.add_argument("--synthetic_n", type=int, default=256,
                   help="synthetic train-set size; keep the default to "
                        "match scripts/train.py --synthetic (CI smoke "
                        "runs shrink it)")
    p.add_argument("--synthetic_val_n", type=int, default=32,
                   help="synthetic val-set size (train.py uses 32)")
    p.add_argument("--splits", nargs="+", default=["train", "val"],
                   choices=("train", "val"),
                   help="which splits to extract")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--batch_size", type=int, default=8,
                   help="trunk-forward batch during extraction (per split)")
    p.add_argument("--fe_arch", type=str, default="resnet101")
    p.add_argument("--fe_weights", type=str, default="",
                   help="pretrained trunk weights: reference .pth.tar, raw "
                        "torchvision state dict (.pth), or ncnet_tpu "
                        ".msgpack")
    p.add_argument("--checkpoint", type=str, default="",
                   help="take trunk weights AND architecture from an "
                        "ncnet_tpu .msgpack checkpoint")
    p.add_argument("--allow_random_fe", action="store_true",
                   help="explicitly allow a randomly-initialized trunk "
                        "(synthetic proofs only — ImageNet features are "
                        "what make real training work)")
    p.add_argument("--bf16", action="store_true",
                   help="extract (and store) bfloat16 features — half the "
                        "disk/HBM of f32; matches training with --bf16")
    p.add_argument("--device_normalize", action="store_true",
                   help="mirror train.py --device_normalize: datasets "
                        "yield uint8 and normalization runs on device")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--compile-cache", type=str, default=None,
                   dest="compile_cache", metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(default ~/.cache/ncnet_tpu/xla; 'none' disables)")
    args = p.parse_args(argv)

    from ncnet_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(args.compile_cache)

    import jax

    from ncnet_tpu.data.pairs import ImagePairDataset, SyntheticPairDataset
    from ncnet_tpu.features import FeatureStore, populate_store, trunk_digest
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet

    if args.checkpoint:
        from ncnet_tpu.train.checkpoint import load_latest_valid

        ck, used = load_latest_valid(args.checkpoint)
        config = ck.config.replace(half_precision=args.bf16)
        params = ck.params
        print(f"trunk + architecture from checkpoint {used}")
    else:
        if (
            not args.fe_weights
            and not args.synthetic
            and not args.allow_random_fe
        ):
            p.error(
                "no pretrained trunk: pass --fe_weights or --checkpoint, "
                "or opt in to a random trunk with --allow_random_fe"
            )
        config = ImMatchNetConfig(
            feature_extraction_cnn=args.fe_arch,
            half_precision=args.bf16,
        )
        params = init_immatchnet(jax.random.PRNGKey(args.seed), config)
        if args.fe_weights:
            from ncnet_tpu.utils.convert_torch import load_trunk_weights

            params = dict(params)
            params["feature_extraction"] = load_trunk_weights(
                args.fe_weights, cnn=config.feature_extraction_cnn
            )
            print(f"loaded trunk weights from {args.fe_weights}")

    size = (args.image_size, args.image_size)
    if args.synthetic:
        datasets = {
            "train": SyntheticPairDataset(
                n=args.synthetic_n, output_size=size, seed=args.seed
            ),
            "val": SyntheticPairDataset(
                n=args.synthetic_val_n, output_size=size, seed=args.seed + 1
            ),
        }
    else:
        datasets = {
            split: ImagePairDataset(
                os.path.join(args.dataset_csv_path, f"{split}_pairs.csv"),
                args.dataset_image_path, output_size=size, seed=args.seed,
                uint8_output=args.device_normalize,
            )
            for split in args.splits
        }

    digest = trunk_digest(params["feature_extraction"], config, size)
    for split in args.splits:
        ds = datasets[split]
        store = FeatureStore.open_or_create(
            os.path.join(args.feature_cache, split),
            digest, config, size, len(ds),
        )
        n = populate_store(
            store, params, config, ds,
            batch_size=min(args.batch_size, len(ds)), log_every=5,
        )
        state = "extracted" if n else "already complete;"
        print(
            f"[features] {split}: {state} {n or store.num_items} pairs "
            f"-> {store.root} (digest {digest[:12]}..., "
            f"dtype {store.manifest['feature_dtype']})",
            flush=True,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
