"""`audit` — trace the repo's real entry programs and check the compiled IR.

The program-level counterpart of `scripts/lint.py`: where nclint reads
source text, this traces the ACTUAL jitted train/serve/eval programs to
jaxprs (`ncnet_tpu.analysis.jaxpr_audit`) and checks the IR for f64
leaks, bf16 promotion drift, compiled-in host callbacks, missing buffer
donation, closure-captured constants, and FLOP-accounting drift against
`ops.accounting` (the telemetry MFU numerator).

Exit status is 0 only when no unsuppressed finding at or above
``--fail-on`` remains — the CI gate is simply

    JAX_PLATFORMS=cpu python scripts/audit.py

Output defaults to a human table (per-program stats + findings); with
``--format json|sarif`` it shares the `Finding` schema nclint emits, so
one consumer handles both analyzers.
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from ncnet_tpu.analysis.findings import (  # noqa: E402
    SEVERITY_ORDER,
    format_json,
    format_sarif,
    format_text,
)
from ncnet_tpu.analysis.hlo_audit import HLO_RULES  # noqa: E402
from ncnet_tpu.analysis.jaxpr_audit import (  # noqa: E402
    JAXPR_RULES,
    PROGRAMS,
    audit,
    format_report_table,
    rules_meta,
)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="audit",
        description="jaxpr-level audit of the repo's real entry programs "
                    "(rule catalog: ncnet_tpu/analysis/README.md)",
    )
    p.add_argument("--programs", default="",
                   help="comma-separated program names to audit "
                        "(default: all; see --list-programs)")
    p.add_argument("--format", choices=["text", "json", "sarif"],
                   default="text", dest="fmt",
                   help="output format (default: human table)")
    p.add_argument("--fail-on", choices=sorted(SEVERITY_ORDER),
                   default="warning",
                   help="lowest severity that fails the run (default: "
                        "warning)")
    p.add_argument("--select", default="",
                   help="comma-separated jaxpr rule ids to run "
                        "(default: all)")
    p.add_argument("--list-programs", action="store_true",
                   help="print the entry-program registry and exit")
    p.add_argument("--list-rules", action="store_true",
                   help="print the jaxpr + HLO rule catalog and exit")
    p.add_argument("--no-hlo", action="store_true",
                   help="skip the HLO-level pass (no compilation: trace-"
                        "only jaxpr rules, faster but blind to lowering "
                        "regressions)")
    args = p.parse_args(argv)

    if args.list_programs:
        for name in sorted(PROGRAMS):
            spec = PROGRAMS[name]
            print(f"{name}: {spec.description}")
            for rule_id, reason in sorted(spec.waivers.items()):
                print(f"  waived {rule_id}: {reason}")
        return 0
    if args.list_rules:
        catalog = list(JAXPR_RULES.values()) + list(HLO_RULES.values())
        for r in sorted(catalog, key=lambda r: r.rule_id):
            print(f"{r.rule_id} ({r.severity}): {' '.join(r.doc.split())}")
        return 0

    programs = None
    if args.programs:
        programs = [s.strip() for s in args.programs.split(",") if s.strip()]
        unknown = [s for s in programs if s not in PROGRAMS]
        if unknown:
            p.error(f"unknown program(s): {', '.join(unknown)} "
                    f"(see --list-programs)")
    selected = None
    if args.select:
        selected = [s.strip() for s in args.select.split(",") if s.strip()]
        unknown = [
            s for s in selected
            if s not in JAXPR_RULES and s not in HLO_RULES
        ]
        if unknown:
            p.error(f"unknown rule id(s): {', '.join(unknown)} "
                    f"(see --list-rules)")

    result = audit(programs, selected, hlo=not args.no_hlo)
    findings = result.all_findings

    if args.fmt == "json":
        print(format_json(findings, tool="audit"))
    elif args.fmt == "sarif":
        print(format_sarif(findings, "audit", rules_meta()))
    else:
        print(format_report_table(result.reports))
        if result.waived:
            print(f"\n{len(result.waived)} waived finding(s):")
            for f in result.waived:
                print(f"  {f.format()}")
        print()
        print(format_text(findings))
    threshold = SEVERITY_ORDER[args.fail_on]
    gating = [f for f in findings if SEVERITY_ORDER[f.severity] >= threshold]
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
