"""PF-Pascal PCK evaluation CLI (reference eval_pf_pascal.py equivalent)."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description="ncnet_tpu PF-Pascal PCK eval")
    p.add_argument("--checkpoint", type=str, required=True,
                   help=".msgpack checkpoint or reference .pth.tar")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--eval_dataset_path", type=str, default="datasets/pf-pascal")
    p.add_argument("--batch_size", type=int, default=1)
    p.add_argument("--num_workers", type=int, default=4)
    args = p.parse_args()

    from ncnet_tpu.data.loader import DataLoader
    from ncnet_tpu.data.pairs import PFPascalDataset
    from ncnet_tpu.eval.pf_pascal import evaluate

    if args.checkpoint.endswith((".pth.tar", ".pth")):
        from ncnet_tpu.utils.convert_torch import convert_checkpoint

        config, params = convert_checkpoint(args.checkpoint)
    else:
        from ncnet_tpu.train.checkpoint import load_checkpoint

        ck = load_checkpoint(args.checkpoint)
        config, params = ck.config, ck.params

    dataset = PFPascalDataset(
        os.path.join(args.eval_dataset_path, "image_pairs", "test_pairs.csv"),
        args.eval_dataset_path,
        output_size=(args.image_size, args.image_size),
        pck_procedure="scnet",
    )
    loader = DataLoader(dataset, args.batch_size, num_workers=args.num_workers)
    stats = evaluate(params, config, loader)
    print(f"Total: {len(dataset)}")
    print(f"Valid: {stats['n_valid']}")
    print(f"PCK: {stats['pck']:.2%}")


if __name__ == "__main__":
    main()
