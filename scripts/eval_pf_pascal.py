"""PF-Pascal PCK evaluation CLI (reference eval_pf_pascal.py equivalent)."""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description="ncnet_tpu PF-Pascal PCK eval")
    p.add_argument("--checkpoint", type=str, required=True,
                   help=".msgpack checkpoint or reference .pth.tar")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--eval_dataset_path", type=str, default="datasets/pf-pascal")
    p.add_argument("--batch_size", type=int, default=1)
    p.add_argument("--num_workers", type=int, default=4)
    p.add_argument("--batch", type=int, default=0,
                   help="serve the eval through the dynamic micro-batcher "
                        "(ncnet_tpu.serve) with this max batch size: pairs "
                        "are coalesced into padded fixed-shape batches from "
                        "AOT-warmed programs; per-pair PCK matches the "
                        "sequential path (padding masked at readout, "
                        "tests/test_serve.py). 0 = sequential "
                        "per-loader-batch eval")
    p.add_argument("--bf16", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="bf16 features/correlation/NC compute for the "
                        "eval forward (readout stays f32). Default: the "
                        "checkpoint's recorded dtype; --bf16 / --no-bf16 "
                        "override in either direction")
    p.add_argument("--refine", type=int, default=None, metavar="R",
                   help="coarse-to-fine refinement (ncnet_tpu.refine) for "
                        "the eval forward: pool features by R, run the "
                        "coarse band at --refine_topk, re-score the "
                        "surviving neighbourhoods at high res. 0 forces "
                        "refinement OFF; unset keeps the checkpoint's "
                        "recorded value")
    p.add_argument("--refine_topk", type=int, default=None, metavar="K",
                   help="with --refine: coarse-band width")
    p.add_argument("--refine_radius", type=int, default=None,
                   help="with --refine: extra window reach in coarse cells")
    p.add_argument("--conv4d_impl", type=str, default="tlc",
                   help="conv4d lowering for the eval forward (overrides "
                        "the checkpoint's training-tuned mix, whose "
                        "composite VJPs are irrelevant forward-only and "
                        "whose btl4 middle layer loses at eval: measured "
                        "at the 25x25 grid, batch 16 — training mix 25.2 "
                        "pairs/s, cfs 35.4, 'tlc' 48.4 — "
                        "benchmarks/micro_pck.py). Empty string keeps "
                        "the checkpoint's impl")
    args = p.parse_args()

    from ncnet_tpu.data.loader import DataLoader
    from ncnet_tpu.data.pairs import PFPascalDataset
    from ncnet_tpu.eval.pf_pascal import evaluate, evaluate_serving

    if args.checkpoint.endswith((".pth.tar", ".pth")):
        from ncnet_tpu.utils.convert_torch import convert_checkpoint

        config, params = convert_checkpoint(args.checkpoint)
    else:
        from ncnet_tpu.train.checkpoint import load_checkpoint

        ck = load_checkpoint(args.checkpoint)
        config, params = ck.config, ck.params

    if args.conv4d_impl:
        config = config.replace(conv4d_impl=args.conv4d_impl)
    if args.bf16 is not None:
        config = config.replace(half_precision=args.bf16)
    if args.refine is not None:
        config = config.replace(refine_factor=args.refine)
    if args.refine_topk is not None:
        config = config.replace(refine_topk=args.refine_topk)
    if args.refine_radius is not None:
        config = config.replace(refine_radius=args.refine_radius)
    if config.refine_factor:
        grid = max(args.image_size // 16, 1)
        if grid % config.refine_factor:
            p.error(
                f"--image_size {args.image_size} gives a {grid}x{grid} "
                f"feature grid, which does not divide by --refine "
                f"{config.refine_factor}"
            )

    dataset = PFPascalDataset(
        os.path.join(args.eval_dataset_path, "image_pairs", "test_pairs.csv"),
        args.eval_dataset_path,
        output_size=(args.image_size, args.image_size),
        pck_procedure="scnet",
    )
    loader = DataLoader(dataset, args.batch_size, num_workers=args.num_workers)
    if args.batch:
        stats = evaluate_serving(params, config, loader, max_batch=args.batch)
    else:
        stats = evaluate(params, config, loader)
    print(f"Total: {len(dataset)}")
    print(f"Valid: {stats['n_valid']}")
    print(f"PCK: {stats['pck']:.2%}")
    if args.batch:
        s = stats["serve"]
        print(
            f"Serve: {s['completed']} pairs in {s['batches']} batches, "
            f"occupancy {s['mean_occupancy']:.2f}, "
            f"p50 {s['latency_p50_ms']:.0f} ms / "
            f"p95 {s['latency_p95_ms']:.0f} ms, "
            f"recompiles after warmup: {s['recompiles_after_warmup']}"
        )


if __name__ == "__main__":
    main()
