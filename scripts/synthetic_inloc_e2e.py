"""Synthetic end-to-end InLoc proof: the REAL chain on a generated scene.

Zero-egress stands in for the InLoc dataset (SURVEY.md §2.4): neither the
images, the RGBD cutouts, nor the reference `.pth.tar` weights are
reachable, so the strongest attainable whole-system accuracy proof is a
synthetic scene with KNOWN geometry and poses pushed through the exact
production pipeline:

  1. train the NC head on synthetic pairs (`synthetic_convergence.run` —
     weak loss, frozen 'patch16' random-orthogonal patch-embed trunk
     with feature centering: the pretrained-free trunk whose features
     are genuinely discriminative, models/patch.py);
  2. build a scene: a textured near-planar surface observed by a cutout
     camera (RGBD `XYZcut` .mat + colored scan point cloud, exactly the
     InLoc data layout) and by a query camera at a KNOWN pose — the query
     image is a stride-aligned crop of the same texture, which a pinhole
     camera pair reproduces exactly for a plane (the 1% depth ripple keeps
     the PnP stage away from the coplanar DLT degeneracy and costs <1 px
     of reprojection consistency);
  3. run the real dump: `eval.inloc.dump_matches` at relocalization
     k_size=2 (model forward -> fused corr+maxpool4d -> both-direction
     `corr_to_matches` -> sort/dedup/recenter -> `.mat` contract);
  4. run the real localization CLI `scripts/localize_inloc.py` with
     `--densePV` (P3P LO-RANSAC + dense pose-verification re-ranking
     against the scan) and `--refposes` (localization-rate curve,
     per-query error file);
  5. report position/orientation error of the estimated pose vs the
     planted one, and the rate curve.

Reference chain being proven: compute_densePE_NCNet.m:1-57 ->
parfor_NC4D_PE_pnponly.m -> ht_top10_NC4D_PV_localization.m ->
ht_plotcurve_WUSTL.m.

Usage: python scripts/synthetic_inloc_e2e.py [--steps 200] [--out_dir DIR]
Prints one JSON summary line (pos_err_m, ori_err_deg, rate curve points).
"""

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Scene constants: 512px images -> 32x32 feature grid (stride 16), k=2.
# The depth map is PIECEWISE CONSTANT over 128px blocks with depths chosen
# so each block's disparity d = FC/Z is an exact multiple of the 16px
# feature stride: query cells are then pixel-exact copies of cutout cells
# (perfect patch16 matches, no quantization error on inliers), while the
# five distinct depth planes break the single-plane pose ambiguity that
# made a rippled plane unlocalizable under cell-quantized matches
# (measured in round 4: ripple-plane pose errors 0.07-1.4 m across seeds;
# blocky depth 0.04-0.14 m).
SIZE = 512
FOCAL = 600.0
FC = 512.0  # FOCAL * C_x = FOCAL * C_y: the disparity scale numerator
DEPTHS = [32.0 / m for m in (6, 7, 8, 9, 10)]  # disparities 96..160 px
BLOCK = 128
PANO_FN = "DUC1/s1_cutout_001_0_0.jpg"  # parse_cutout_name-compatible
DECOY_FN = "DUC1/s1_cutout_001_30_0.jpg"


def _depth_map_ext(n):
    """Piecewise-constant block depth over an n x n domain (the scene
    extends beyond the cutout so query visibility is well defined)."""
    u, v = np.meshgrid(np.arange(n), np.arange(n))
    z = np.empty((n, n))
    bu, bv = u // BLOCK, v // BLOCK
    idx = (bu + 2 * bv) % len(DEPTHS)
    for i, d in enumerate(DEPTHS):
        z[idx == i] = d
    return z


def render_query(texture, z):
    """Inverse-warp the query view: query pixel q shows the NEAREST scene
    point among the per-depth candidates c = q + d(Z) whose cutout block
    really has that depth (an exact visibility test for piecewise-
    constant depth; disocclusions fall back to the deepest plane)."""
    qy, qx = np.mgrid[0:SIZE, 0:SIZE]
    out = np.zeros((SIZE, SIZE, 3), np.float32)
    have = np.full((SIZE, SIZE), np.inf)
    for zb in sorted(DEPTHS, reverse=True):  # near planes overwrite far
        d = int(round(FC / zb))
        cx, cy = qx + d, qy + d
        inb = (cx < z.shape[1]) & (cy < z.shape[0])
        valid = np.zeros_like(inb)
        valid[inb] = z[cy[inb], cx[inb]] == zb
        # disocclusion fallback: the deepest plane paints everything inb
        take = valid | (np.isinf(have) & inb & (zb == max(DEPTHS)))
        out[take] = texture[cy[take], cx[take]]
        have[take] = zb
    return out


def build_scene(out_dir, seed=5):
    """Write the InLoc-layout fixture; returns the planted query pose.

    Texture: 8 px bilinear noise (the `SyntheticPairDataset` family the NC
    head is trained on), sized SIZE + max-disparity so every query pixel
    has real texture. Cutout camera at the origin looking down +z; query
    camera translated diagonally in-plane by C = (FC/FOCAL, FC/FOCAL, 0).
    """
    from PIL import Image
    from scipy.io import savemat

    from ncnet_tpu.data.images import resize_bilinear_np

    margin = int(round(FC / min(DEPTHS)))  # largest disparity (160 px)
    tex_size = SIZE + margin
    rng = np.random.RandomState(seed)
    base = rng.rand(tex_size // 8, tex_size // 8, 3).astype(np.float32)
    T = resize_bilinear_np(base * 255.0, tex_size, tex_size)

    z_ext = _depth_map_ext(tex_size)
    z = z_ext[:SIZE, :SIZE]
    cut = T[:SIZE, :SIZE]
    qry = render_query(T, z_ext)
    decoy = resize_bilinear_np(
        np.random.RandomState(seed + 1).rand(64, 64, 3).astype(np.float32)
        * 255.0,
        SIZE,
        SIZE,
    )

    # RGBD cutout: P(u, v) = ((u - c)/f * Z, (v - c)/f * Z, Z)
    u, v = np.meshgrid(np.arange(SIZE), np.arange(SIZE))  # u = x (cols)
    c = SIZE / 2.0
    xyz = np.stack(
        [(u - c) / FOCAL * z, (v - c) / FOCAL * z, z], axis=-1
    )

    # planted query pose: R = I, camera center C -> t = -C
    C = np.array([FC / FOCAL, FC / FOCAL, 0.0])
    P_gt = np.concatenate([np.eye(3), -C[:, None]], axis=1)

    qdir = os.path.join(out_dir, "query")
    os.makedirs(qdir, exist_ok=True)
    Image.fromarray(qry.astype(np.uint8)).save(os.path.join(qdir, "q0.png"))
    pdir = os.path.join(out_dir, "panos", "DUC1")
    os.makedirs(pdir, exist_ok=True)
    Image.fromarray(cut.astype(np.uint8)).save(
        os.path.join(out_dir, "panos", PANO_FN)
    )
    Image.fromarray(decoy.astype(np.uint8)).save(
        os.path.join(out_dir, "panos", DECOY_FN)
    )
    cdir = os.path.join(out_dir, "cutouts", "DUC1")
    os.makedirs(cdir, exist_ok=True)
    savemat(
        os.path.join(out_dir, "cutouts", PANO_FN + ".mat"), {"XYZcut": xyz}
    )
    savemat(
        os.path.join(out_dir, "cutouts", DECOY_FN + ".mat"), {"XYZcut": xyz}
    )

    # colored scan point cloud for densePV (at_pv_wrapper.m cell layout)
    sdir = os.path.join(out_dir, "scans", "DUC1")
    os.makedirs(sdir, exist_ok=True)
    pts = xyz.reshape(-1, 3)
    rgb = cut.reshape(-1, 3).astype(np.float64)
    cells = np.empty((1, 7), object)
    for i in range(3):
        cells[0, i] = pts[:, i : i + 1]
    cells[0, 3] = np.zeros((len(pts), 1))
    for i in range(3):
        cells[0, 4 + i] = rgb[:, i : i + 1]
    savemat(os.path.join(sdir, "s1_scan_001.mat"), {"A": cells})

    # shortlist: the true cutout and a decoy, decoy ranked first so the
    # PnP+densePV stages have to do real work to rank the truth on top
    dt = np.dtype([("queryname", object), ("topN", object)])
    entry = np.zeros((1, 1), dt)
    entry[0, 0] = (
        np.array(["q0.png"], object),
        np.array([[DECOY_FN, PANO_FN]], object),
    )
    savemat(os.path.join(out_dir, "shortlist.mat"), {"ImgList": entry})

    ref_dt = np.dtype([("queryname", object), ("P", object)])
    duc1 = np.zeros((1, 1), ref_dt)
    duc1[0, 0] = (np.array(["q0.png"], object), P_gt)
    savemat(os.path.join(out_dir, "refposes.mat"), {"DUC1_RefList": duc1})
    return P_gt


def run(out_dir, steps=300, train_size=256, seed=0, bf16_check=False,
        verbose=True):
    """Train -> build scene -> dump matches -> localize (+densePV) -> errors.

    ``train_size=256`` matters for score CALIBRATION, not just accuracy:
    the weak loss normalizes scores by softmax over the training grid, so
    training at a 16x16 grid (softmax over 256 cells = the eval dump's
    pooled grid at SIZE=512, k=2) produces scores that genuinely cross
    the reference's hard 0.75 threshold at eval (measured: 106 of 384
    dump slots > 0.75), while a 128px-trained model's scores collapse to
    the uniform-softmax floor at the larger eval grid.

    ``bf16_check=True`` additionally re-dumps the matches through the
    bf16 pipeline (the production InLoc eval numerics) and localizes
    from them too, returning the pose disagreement between the fp32 and
    bf16 chains — the downstream half of the score-threshold robustness
    question (VERDICT r3 #4; the fast numeric half lives in
    tests/test_bf16_threshold.py).

    Returns a dict with the training PCK, match-dump stats, the PnP pose
    errors, the densePV ranking outcome and the rate-curve points.
    """
    import jax

    from synthetic_convergence import run as train_run

    from ncnet_tpu.eval.inloc import dump_matches
    from ncnet_tpu.eval.localize import pose_distance

    res = train_run(
        image_size=train_size,
        steps=steps,
        batch=8,
        lr=5e-4,
        seed=seed,
        # the reference's InLoc NC architecture (5-5-5 / 16-16-1) with the
        # round-4 proven synthetic recipe: patch16 trunk + identity NC
        # init (see synthetic_convergence.run)
        ncons_kernel_sizes=(5, 5, 5),
        ncons_channels=(16, 16, 1),
        conv4d_impl="cfs",
        verbose=verbose,
    )
    params, config = res["params"], res["config"]

    P_gt = build_scene(out_dir, seed=5 + seed)
    eval_config = config.replace(
        relocalization_k_size=2,
        # eval pairs may have rectangular grids in general; sequential
        # symmetric passes are the memory-lean eval default
        symmetric_batch=False,
    )
    mdir = os.path.join(out_dir, "matches")
    dump_matches(
        params,
        eval_config,
        os.path.join(out_dir, "shortlist.mat"),
        os.path.join(out_dir, "query"),
        os.path.join(out_dir, "panos"),
        mdir,
        image_size=SIZE,
        n_queries=1,
        n_panos=2,
        verbose=verbose,
    )

    from scipy.io import loadmat

    dumped = loadmat(os.path.join(mdir, "1.mat"))["matches"]
    scores = dumped[0, :, :, 4]
    # the reference's hard threshold (parfor_NC4D_PE_pnponly.m:16-18) is
    # used verbatim when the trained model's calibration supports it
    # (train_size=256 does — see run() docstring); a quantile fallback
    # keeps the script usable for shorter/smaller training configs
    n_ref = int((scores > 0.75).sum())
    score_thr = (
        0.75 if n_ref >= 12
        else float(np.percentile(scores[scores > 0], 60))
    )

    out_json = os.path.join(out_dir, "localization.json")
    cmd = [
        sys.executable,
        os.path.join(REPO, "scripts", "localize_inloc.py"),
        "--matches_dir", mdir,
        "--shortlist", os.path.join(out_dir, "shortlist.mat"),
        "--cutout_dir", os.path.join(out_dir, "cutouts"),
        "--query_dir", os.path.join(out_dir, "query"),
        "--focal", str(FOCAL),
        "--n_queries", "1",
        "--n_panos", "2",
        "--score_thr", str(score_thr),
        # block disparities are exact multiples of the 16 px cell, so
        # inlier matches are pixel-exact; 1.5 deg rejects the seam bands
        "--pnp_thr_deg", "1.5",
        "--refposes", os.path.join(out_dir, "refposes.mat"),
        "--densePV",
        "--scan_dir", os.path.join(out_dir, "scans"),
        "--out", out_json,
        "--method", "synthetic_e2e",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=1800)
    if proc.returncode != 0:
        raise RuntimeError(
            f"localize_inloc failed:\n{proc.stdout}\n{proc.stderr}"
        )

    with open(out_json) as f:
        results = json.load(f)
    entry = results[0]
    top1 = entry["topNname"][0]
    P_est = entry["P"][0]
    pos_err = ori_err = float("inf")
    if P_est is not None:
        dp, do = pose_distance(P_gt, np.asarray(P_est))
        pos_err, ori_err = float(dp), float(np.rad2deg(do))

    err_path = os.path.join(out_dir, "error_synthetic_e2e.txt")
    curve = []
    for line in proc.stdout.splitlines():
        # localize_inloc.py prints "  {t:6.4f} m : {r:5.1f} %"
        parts = line.split()
        if (
            len(parts) == 5
            and parts[1] == "m"
            and parts[2] == ":"
            and parts[4] == "%"
        ):
            curve.append((float(parts[0]), float(parts[3])))

    if bf16_check:
        from ncnet_tpu.eval.localize import pnp_localize_pair

        mdir16 = os.path.join(out_dir, "matches_bf16")
        dump_matches(
            params,
            eval_config.replace(half_precision=True),
            os.path.join(out_dir, "shortlist.mat"),
            os.path.join(out_dir, "query"),
            os.path.join(out_dir, "panos"),
            mdir16,
            image_size=SIZE,
            n_queries=1,
            n_panos=2,
            verbose=verbose,
        )
        d16 = loadmat(os.path.join(mdir16, "1.mat"))["matches"]
        xyz = loadmat(
            os.path.join(out_dir, "cutouts", PANO_FN + ".mat")
        )["XYZcut"]
        poses = []
        for dump in (dumped, d16):
            out = pnp_localize_pair(
                dump[0, 1], (SIZE, SIZE), (SIZE, SIZE), xyz, FOCAL,
                score_thr=score_thr, pnp_thr_deg=1.5, seed=seed,
            )
            poses.append(out["P"])
        if poses[0] is None or poses[1] is None:
            bf16_pos = bf16_ori = float("inf")
        else:
            dp, do = pose_distance(poses[0], poses[1])
            bf16_pos, bf16_ori = float(dp), float(np.rad2deg(do))

    summary = {
        "pck_after_training": res["pck_after"],
        "score_thr": score_thr,
        "n_scored_matches": int((scores > score_thr).sum()),
        "n_above_reference_thr_0.75": int((scores > 0.75).sum()),
        "densePV_top1": top1,
        "densePV_top1_is_true_pano": top1 == PANO_FN,
        "pos_err_m": pos_err,
        "ori_err_deg": ori_err,
        "rate_at_1m_10deg_pct": next(
            (r for t, r in curve if abs(t - 1.0) < 0.05), None
        ),
        "error_file": err_path,
    }
    if bf16_check:
        summary["bf16_vs_fp32_pose_pos_m"] = bf16_pos
        summary["bf16_vs_fp32_pose_ori_deg"] = bf16_ori
        summary["bf16_n_above_reference_thr_0.75"] = int(
            (d16[0, :, :, 4] > 0.75).sum()
        )
    if verbose:
        print(json.dumps(summary))
    return summary


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out_dir", default="synthetic_inloc")
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--train_size", type=int, default=256)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--bf16_check", action="store_true",
                   help="also dump through the bf16 pipeline and report "
                        "the fp32-vs-bf16 pose disagreement")
    p.add_argument("--json_out", default="",
                   help="write the summary metrics as JSON (the committed-"
                        "artifact form of the chain's results)")
    args = p.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)
    summary = run(args.out_dir, steps=args.steps, train_size=args.train_size,
                  seed=args.seed, bf16_check=args.bf16_check)
    if args.json_out:
        summary = dict(summary, steps=args.steps, seed=args.seed,
                       train_size=args.train_size)
        with open(args.json_out, "w") as f:
            json.dump(summary, f, indent=1)
        print(f"wrote {args.json_out}")


if __name__ == "__main__":
    main()
