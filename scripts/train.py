"""Weakly-supervised training CLI (reference train.py equivalent).

Example (PF-Pascal paper config, reference README.md:42):
  python scripts/train.py --dataset_image_path datasets/pf-pascal \
      --dataset_csv_path datasets/pf-pascal/image_pairs \
      --ncons_kernel_sizes 5 5 5 --ncons_channels 16 16 1

With no dataset on disk, pass --synthetic to train on generated pairs.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from ncnet_tpu.data.loader import DataLoader
from ncnet_tpu.data.pairs import ImagePairDataset, SyntheticPairDataset
from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
from ncnet_tpu.resilience.cluster import EXIT_PEER_DOWN, PeerDown
from ncnet_tpu.resilience.signals import PreemptionGuard
from ncnet_tpu.train.checkpoint import load_latest_valid_any, sharded_dir_for
from ncnet_tpu.train.loop import train


def _conv4d_impl_arg(value):
    """Every advertised value trains on TPU; 'pallas' (interpret-mode
    only) is deliberately absent. A comma-separated list picks an impl
    per NC layer. The registry lives next to the dispatch it mirrors."""
    from ncnet_tpu.ops.conv4d import CONV4D_IMPLS, is_valid_impl

    for name in value.split(","):
        if not is_valid_impl(name):
            raise argparse.ArgumentTypeError(
                f"unknown conv4d impl {name!r} (choose from "
                f"{', '.join(CONV4D_IMPLS)}; comma-separate for per-layer; "
                "'<fwd>/<dx>[/<dw>]' composes forward/input-grad/"
                "kernel-grad lowerings)"
            )
    return value


def _run_elastic_supervisor(args):
    """The ``--elastic`` parent: supervise the training process and, when
    it exits with the typed `PeerDown` status, re-form the cluster at the
    surviving topology and relaunch resuming from the latest valid save
    (resilience.cluster.ElasticSupervisor). Initial topology comes from
    ``NCNET_ELASTIC_PID`` / ``NCNET_ELASTIC_NPROCS`` /
    ``NCNET_ELASTIC_COORD`` (single-process by default); the worker child
    is this same script with ``NCNET_ELASTIC_RUN=1``."""
    from ncnet_tpu.resilience.cluster import ElasticSupervisor

    cluster_dir = args.cluster_dir or os.path.join(
        args.result_model_dir, "cluster"
    )
    os.makedirs(cluster_dir, exist_ok=True)
    pid = int(os.environ.get("NCNET_ELASTIC_PID", "0"))
    nprocs = int(os.environ.get("NCNET_ELASTIC_NPROCS", "1"))
    coord = os.environ.get("NCNET_ELASTIC_COORD", "") or None
    base_argv = [a for a in sys.argv[1:] if a != "--elastic"]
    ckpt_path = os.path.join(args.result_model_dir, args.result_model_fn)

    def build_argv(topo):
        argv = [sys.executable, os.path.abspath(__file__)] + list(base_argv)
        if topo["generation"] > 0 and "--checkpoint" not in base_argv:
            # generation > 0 IS a resume: the previous generation left a
            # committed save the surviving topology restores from
            argv += ["--checkpoint", ckpt_path]
        return argv

    return ElasticSupervisor(
        cluster_dir, build_argv, pid, nprocs, coordinator=coord
    ).run()


def main():
    p = argparse.ArgumentParser(description="ncnet_tpu training")
    p.add_argument("--dataset_image_path", type=str, default="datasets/pf-pascal")
    p.add_argument("--dataset_csv_path", type=str,
                   default="datasets/pf-pascal/image_pairs")
    p.add_argument("--synthetic", action="store_true",
                   help="train on synthetic pairs (no dataset needed)")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--num_epochs", type=int, default=5)
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--lr", type=float, default=5e-4)
    p.add_argument("--ncons_kernel_sizes", nargs="+", type=int, default=[5, 5, 5])
    p.add_argument("--ncons_channels", nargs="+", type=int, default=[16, 16, 1])
    p.add_argument("--fe_arch", type=str, default="resnet101")
    p.add_argument("--train_fe", action="store_true")
    p.add_argument("--fe_finetune_params", type=int, default=0,
                   help="finetune the last N blocks of the trunk's final "
                        "stage (reference train.py:60-63 semantics)")
    p.add_argument("--fe_weights", type=str, default="",
                   help="pretrained trunk weights: reference .pth.tar, raw "
                        "torchvision state dict (.pth), or ncnet_tpu .msgpack")
    p.add_argument("--allow_random_fe", action="store_true",
                   help="explicitly allow a randomly-initialized frozen trunk "
                        "(the reference always uses ImageNet weights)")
    p.add_argument("--checkpoint", type=str, default="",
                   help="resume/initialize from a checkpoint "
                        "(.msgpack or reference .pth.tar)")
    p.add_argument("--result_model_dir", type=str, default="trained_models")
    p.add_argument("--result_model_fn", type=str, default="ncnet_tpu.msgpack")
    p.add_argument("--num_workers", type=int, default=4)
    p.add_argument("--save-every-steps", type=int, default=0, dest="save_every_steps",
                   help="also checkpoint every N optimizer steps (durable, "
                        "with a mid-epoch resume cursor); 0 = epoch "
                        "boundaries only")
    p.add_argument("--keep-checkpoints", type=int, default=3,
                   dest="keep_checkpoints",
                   help="rotating retention: keep the newest K step-tagged "
                        "checkpoint copies for corrupt-file fallback "
                        "(0 disables history)")
    p.add_argument("--sample-retries", type=int, default=2,
                   dest="sample_retries",
                   help="extra per-sample load attempts (exponential "
                        "backoff) before a sample counts as corrupt")
    p.add_argument("--skip-budget", type=int, default=0, dest="skip_budget",
                   help="total corrupt samples the loaders may skip (each "
                        "substituted by the next loadable index, "
                        "shape-preserving) before failing loudly; 0 = "
                        "fail on the first bad sample")
    p.add_argument("--feature-cache", type=str, default="",
                   dest="feature_cache", metavar="DIR",
                   help="train the NC head from cached trunk features "
                        "(ncnet_tpu.features): DIR/train and DIR/val hold "
                        "one durable digest-guarded store per split, "
                        "populated lazily on first use (or up front by "
                        "scripts/extract_features.py). Steps then contain "
                        "ZERO backbone ops. Frozen-trunk configs only — "
                        "refused with --train_fe/--fe_finetune_params; a "
                        "cache extracted under different trunk weights, "
                        "backbone, image size, dtype, or normalize/center "
                        "flags is DETECTED (manifest digest) and rejected")
    p.add_argument("--pin-features", action="store_true",
                   dest="pin_features",
                   help="with --feature-cache: device_put the WHOLE "
                        "feature set once and gather batches on device "
                        "(PF-Pascal train is ~7.6 GB in bf16 — fits a "
                        "16 GB v5e); refused when the set exceeds the "
                        "device's reported memory")
    p.add_argument("--compile-cache", type=str, default=None,
                   dest="compile_cache", metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "(default ~/.cache/ncnet_tpu/xla; 'none' "
                        "disables): the minute-scale conv4d NC-stack "
                        "compiles are paid once per machine, not once "
                        "per run")
    p.add_argument("--device_normalize", action="store_true",
                   help="ship training images as uint8 and ImageNet-"
                        "normalize on device (4x less H2D traffic; "
                        "rounding-level numerics difference). Real "
                        "datasets only; ignored with --synthetic")
    p.add_argument("--loader_backend", choices=("thread", "process"),
                   default="thread",
                   help="data-loader worker backend; on multi-core hosts "
                        "'process' scales past the GIL (one core decodes "
                        "~68 images/s; the IVD config consumes ~240 — "
                        "PERF.md)")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--bf16", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="bf16 features/correlation/NC compute with f32 "
                        "master params and f32 loss/optimizer state (see "
                        "train/step.py). Default ON for fresh runs — the "
                        "raw-speed train path; a resume keeps the "
                        "checkpoint's recorded dtype unless the flag is "
                        "given explicitly (--bf16 / --no-bf16 override "
                        "in either direction)")
    p.add_argument("--sanitize", action="store_true",
                   help="enable the numerical sanitizer "
                        "(ncnet_tpu.analysis.sanitizer): per-stage "
                        "finiteness + bf16-range probes at every pipeline "
                        "boundary, a per-step loss sync, and on the first "
                        "non-finite loss an immediate stop naming the "
                        "first non-finite stage. ~10-30% step overhead "
                        "plus host callbacks — for debugging runs, not "
                        "production throughput")
    p.add_argument("--profile_dir", type=str, default="",
                   help="capture a jax.profiler trace of a few early steps "
                        "into this directory")
    p.add_argument("--profile-steps", type=str, default="3:8",
                   dest="profile_steps", metavar="A:B",
                   help="with --profile_dir: the half-open step window "
                        "[A:B) of the first epoch to trace (default 3:8 — "
                        "past the compile step, short enough to keep the "
                        "trace small)")
    p.add_argument("--telemetry", type=str, default="", metavar="DIR",
                   help="write a telemetry run under DIR "
                        "(ncnet_tpu.telemetry): a durable per-process "
                        "events_proc<P>.jsonl span/metric log plus a .prom "
                        "snapshot at exit; render with "
                        "scripts/telemetry_report.py DIR")
    p.add_argument("--multihost", action="store_true",
                   help="join a multi-host JAX runtime (TPU pod slices: "
                        "auto-detected); shards the data loaders per host")
    p.add_argument("--cluster", action="store_true",
                   help="multi-host cluster supervision "
                        "(resilience.cluster): per-host heartbeats over "
                        "the shared checkpoint filesystem, typed PeerDown "
                        "instead of hung collectives when a peer dies, a "
                        "durable stop flag so a SIGTERM on ANY host drains "
                        "ALL hosts to the same final save step, and save-"
                        "cursor consensus re-enabling async coalescing "
                        "multi-process. No-op single-host")
    p.add_argument("--cluster-dir", type=str, default="", dest="cluster_dir",
                   help="shared directory for cluster rendezvous files "
                        "(default <result_model_dir>/cluster); must be on "
                        "the same shared filesystem as the checkpoints")
    p.add_argument("--cluster-heartbeat-s", type=float, default=2.0,
                   dest="cluster_heartbeat_s",
                   help="heartbeat write interval in seconds")
    p.add_argument("--cluster-staleness-s", type=float, default=15.0,
                   dest="cluster_staleness_s",
                   help="seconds without a peer heartbeat change before it "
                        "is declared dead (typed PeerDown)")
    p.add_argument("--elastic", action="store_true",
                   help="supervise the run for elastic restart: training "
                        "runs as a child process (implies --cluster "
                        "semantics multi-host); when a peer dies the child "
                        "exits with the typed PeerDown status, the "
                        "survivors re-form at the surviving topology, and "
                        "training resumes from the latest valid save. "
                        "Initial topology via NCNET_ELASTIC_PID/NPROCS/"
                        "COORD (single-process by default)")
    p.add_argument("--synthetic_pairs", type=int, default=256,
                   help="with --synthetic: number of generated training "
                        "pairs (validation uses a fixed 32)")
    p.add_argument("--distributed-checkpoints", action="store_true",
                   dest="distributed_checkpoints",
                   help="per-host sharded checkpoint layout "
                        "(resilience.distributed): every process durably "
                        "writes only its own shards under "
                        "<result_model_fn stem>.dckpt/step_<N>/ with a "
                        "two-phase commit — no O(state) process-0 gather. "
                        "Resume reads the sharded layout when present, "
                        "else auto-migrates from the legacy single file "
                        "on the first save")
    p.add_argument("--async-checkpoints", action="store_true",
                   dest="async_checkpoints",
                   help="overlap mid-epoch cursor saves with training "
                        "(resilience.async_ckpt): the step thread hands "
                        "the snapshot to a dedicated writer thread and "
                        "keeps stepping; epoch-end/best/preemption saves "
                        "still block. Crash contract unchanged — torn "
                        "async saves are walked back like torn sync ones")
    # 'pallas' is deliberately NOT offered: the kernel lowers only in
    # interpret mode (kernels/conv4d_pallas.py STATUS) — advertising it
    # here would crash mid-training on the target hardware.
    p.add_argument("--conv4d_impl", type=_conv4d_impl_arg, default=None,
                   help="conv4d lowering, one name or a comma-separated "
                        "per-NC-layer list ('<fwd>/<dx>[/<dw>]' composes "
                        "forward/input-grad/kernel-grad lowerings). "
                        "RECOMMENDED (measured, benchmarks/PERF.md): the "
                        "default per-layer mix, or 'tlc' / 'btl4' / their "
                        "composites uniformly. The remaining registry "
                        "names (cf1, cf1s, gemms, tlcv, ...) are kept as "
                        "measured NEGATIVE results — valid but slower on "
                        "TPU. Default: the measured-best mix for 3-layer "
                        "NC configs, 'tlc' otherwise (see ops/conv4d.py)")
    p.add_argument("--nc_topk", type=int, default=None, metavar="K",
                   help="sparse-band neighbourhood consensus "
                        "(ncnet_tpu.sparse, arXiv:2004.10566): keep the "
                        "top-K B-candidates per A-cell and train the NC "
                        "stack on that band — analytic NC FLOPs drop by "
                        "(grid^2)/K at equal-or-better PCK for moderate "
                        "K (see README 'Sparse neighbourhood "
                        "consensus'). 0 = dense; K >= grid^2 is exactly "
                        "the dense math. Unset keeps a resumed "
                        "checkpoint's recorded value. Incompatible with "
                        "relocalization configs")
    p.add_argument("--nc_topk_mutual", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="with --nc_topk: symmetric/mutual band selection "
                        "(swap-closed up to capacity, better B-grid "
                        "coverage; the default) vs plain per-A top-K "
                        "(--no-nc_topk_mutual)")
    p.add_argument("--corr-impl", choices=("dense", "stream"), default=None,
                   dest="corr_impl",
                   help="band-path correlation->top-K selection impl "
                        "(ncnet_tpu.ops.corr_stream): 'stream' tiles "
                        "B's grid and never materializes the "
                        "[hA*wA, hB*wB] volume — bitwise-identical band, "
                        "identical FLOPs, O(hA*wA*(K+tile)) peak memory "
                        "(see README 'Streaming correlation'). Requires "
                        "--nc_topk or --refine. Unset keeps a resumed "
                        "checkpoint's recorded value (fresh configs: "
                        "dense)")
    p.add_argument("--corr-tile", type=int, default=None, dest="corr_tile",
                   metavar="T",
                   help="with --corr-impl stream: B-grid slab width of "
                        "the streaming GEMM (default 128 = one TPU lane "
                        "width; clamped to hB*wB)")
    p.add_argument("--refine", type=int, default=None, metavar="R",
                   help="coarse-to-fine refinement (ncnet_tpu.refine): "
                        "pool features by R, run the sparse band at the "
                        "coarse grid (width --refine_topk), then re-score "
                        "only the surviving neighbourhoods against the "
                        "high-res features. 0 = off; takes precedence "
                        "over --nc_topk. Unset keeps a resumed "
                        "checkpoint's recorded value")
    p.add_argument("--refine_topk", type=int, default=None, metavar="K",
                   help="with --refine: coarse-band width (default 16; "
                        "unset keeps a resumed checkpoint's value)")
    p.add_argument("--refine_radius", type=int, default=None,
                   help="with --refine: extra window reach in coarse "
                        "cells around each surviving candidate "
                        "(default 0 — the R x R block under it)")
    p.add_argument("--loss_chunk", type=int, default=None,
                   help="run the correlation->NC->score loss over sample "
                        "chunks of this size (0 = whole batch; when "
                        "resuming, unset keeps the checkpoint's value). "
                        "The measured-best single-chip config is 8 (see "
                        "bench.py); leave unset for multi-device data "
                        "parallelism")
    p.add_argument("--chunk_remat", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="rematerialize each loss chunk (the r2-r3 regime; "
                        "measured a net LOSS since the composite conv4d "
                        "VJPs shrank the un-remat'd residuals — "
                        "benchmarks/PERF.md). Fresh configs default off; "
                        "checkpoint resumes keep their recorded value "
                        "unless --chunk_remat/--no-chunk_remat is given")
    args = p.parse_args()

    elastic_child = os.environ.get("NCNET_ELASTIC_RUN") == "1"
    if args.elastic and not elastic_child:
        # the supervising parent never touches the XLA backend: it only
        # spawns/reaps the training child and runs the re-formation
        # rendezvous between generations
        sys.exit(_run_elastic_supervisor(args))

    from ncnet_tpu.telemetry.profiler import parse_steps

    try:
        profile_steps = parse_steps(args.profile_steps)
    except ValueError as e:
        p.error(str(e))

    if args.telemetry:
        # started before any instrumented work so compile-time spans and
        # the feature-cache populate pass land in the log too
        from ncnet_tpu import telemetry

        telemetry.start(args.telemetry, label="train")
        print(f"telemetry: {args.telemetry} "
              "(render with scripts/telemetry_report.py)", flush=True)

    from ncnet_tpu.utils.compile_cache import enable_compile_cache

    cache_dir = enable_compile_cache(args.compile_cache)
    if cache_dir:
        print(f"persistent compilation cache: {cache_dir}", flush=True)

    if args.feature_cache and (args.train_fe or args.fe_finetune_params):
        # checked before any device work: the cache holds features of the
        # PRE-training trunk and would silently go stale after one step
        p.error(
            "--feature-cache requires a fully frozen trunk; drop "
            "--train_fe/--fe_finetune_params or train without the cache"
        )

    if args.sanitize:
        # must happen before any jit tracing: taps are identity at trace
        # time when disabled (analysis/sanitizer.py)
        from ncnet_tpu.analysis import sanitizer

        sanitizer.enable()
        print("numerical sanitizer ON: per-stage finiteness/bf16 probes "
              "(expect slower steps)", flush=True)

    def default_impl(n_layers):
        # per-layer defaults must match the NC layer count (checkpoints
        # carry their own architecture; an explicit flag always wins)
        return "tlc//btl,btl4,tlc/tlc/tf3" if n_layers == 3 else "tlc"

    host_id, n_hosts = 0, 1
    if elastic_child:
        # topology is dictated by the elastic supervisor (it shrinks at
        # each re-formation); a single survivor runs without any
        # distributed runtime at all
        n = int(os.environ.get("NCNET_ELASTIC_NPROCS", "1"))
        if n > 1:
            from ncnet_tpu.parallel.mesh import initialize_multihost

            host_id, n_hosts = initialize_multihost(
                coordinator_address=os.environ["NCNET_ELASTIC_COORD"],
                num_processes=n,
                process_id=int(os.environ.get("NCNET_ELASTIC_PID", "0")),
            )
            print(f"elastic gen {os.environ.get('NCNET_ELASTIC_GEN', '0')}: "
                  f"process {host_id}/{n_hosts}, "
                  f"{jax.device_count()} global devices")
    elif args.multihost:
        from ncnet_tpu.parallel.mesh import initialize_multihost

        host_id, n_hosts = initialize_multihost()
        print(f"multihost: process {host_id}/{n_hosts}, "
              f"{jax.device_count()} global devices")
    if n_hosts > 1:
        n_dev = jax.device_count()
        if args.batch_size % n_dev:
            p.error(
                f"--batch_size {args.batch_size} (global) must be "
                f"divisible by the {n_dev} global devices (the data-"
                f"parallel shard axis), hence also the {n_hosts} hosts"
            )

    cluster = None
    if (args.cluster or elastic_child) and n_hosts > 1:
        # started AFTER jax.distributed.initialize barriered the
        # processes, so the staleness budget never has to absorb launch
        # skew (resilience.cluster docstring)
        from ncnet_tpu.resilience.cluster import ClusterSupervisor

        cluster_dir = args.cluster_dir or os.path.join(
            args.result_model_dir, "cluster"
        )
        cluster = ClusterSupervisor(
            cluster_dir, host_id, n_hosts,
            generation=int(os.environ.get("NCNET_ELASTIC_GEN", "0")),
            heartbeat_interval_s=args.cluster_heartbeat_s,
            staleness_s=args.cluster_staleness_s,
        ).start()
        print(f"cluster supervision ON: {cluster_dir} "
              f"(heartbeat {args.cluster_heartbeat_s}s, "
              f"staleness {args.cluster_staleness_s}s)", flush=True)

    if (
        not args.fe_weights
        and not args.checkpoint
        and not args.synthetic
        and not args.allow_random_fe
    ):
        # The reference ALWAYS trains on an ImageNet-pretrained frozen trunk
        # (lib/model.py:39, pretrained=True); silently training NC over
        # random-feature correlations looks like it works but learns noise.
        # Checked before any device/param init so the error is immediate.
        p.error(
            "no pretrained trunk: pass --fe_weights (torchvision/reference "
            "weights) or --checkpoint, or opt in to a random trunk with "
            "--allow_random_fe"
        )

    start_epoch, start_step, opt_state, best_val = 0, 0, None, None
    start_batch, start_epoch_losses = 0, None
    train_hist = val_hist = None
    if args.checkpoint and args.checkpoint.endswith((".pth.tar", ".pth")):
        import torch

        from ncnet_tpu.utils.convert_torch import convert_checkpoint

        blob = torch.load(
            args.checkpoint, map_location="cpu", weights_only=False
        )
        if not (isinstance(blob, dict) and "state_dict" in blob):
            # A raw torchvision state dict (trunk-only weights) has no
            # 'state_dict'/'args' envelope — that file belongs to
            # --fe_weights. Genuine conversion failures of a full
            # checkpoint fall through with their real traceback.
            p.error(
                f"{args.checkpoint} is not a full reference training "
                "checkpoint (no 'state_dict' key); for trunk-only weights "
                "(e.g. a raw torchvision .pth) use --fe_weights"
            )
        config, params = convert_checkpoint(args.checkpoint)
        chunk = args.loss_chunk or 0
        config = config.replace(
            half_precision=(True if args.bf16 is None else args.bf16),
            conv4d_impl=args.conv4d_impl
            or default_impl(len(config.ncons_channels)),
            loss_chunk=chunk, nc_remat=chunk == 0,
            loss_chunk_remat=bool(args.chunk_remat),
            nc_topk=args.nc_topk or 0,
            nc_topk_mutual=(True if args.nc_topk_mutual is None
                            else args.nc_topk_mutual),
            corr_impl=args.corr_impl or "dense",
            corr_stream_tile=(128 if args.corr_tile is None
                              else args.corr_tile),
            refine_factor=args.refine or 0,
            refine_topk=(16 if args.refine_topk is None
                         else args.refine_topk),
            refine_radius=args.refine_radius or 0,
        )
        print(f"initialized from reference checkpoint {args.checkpoint} "
              "(weights-only: torch optimizer state is not portable)")
    elif args.checkpoint:
        # walks back past a torn/corrupt latest save to the newest valid
        # checkpoint — in BOTH layouts: the sharded shadow directory
        # (committed step_<N>/ dirs, every manifest entry verified) when
        # one exists, else the legacy file and its .step<N> history. A
        # legacy resume with --distributed-checkpoints auto-migrates on
        # the first save (the sharded dir shadows the legacy name).
        ck, used_path = load_latest_valid_any(args.checkpoint)
        # a sharded resume ALWAYS lands on a step_<N>/ dir, so only a
        # load from outside both expected locations is a fallback (the
        # sharded walk-back prints its own per-save skip lines)
        if used_path != args.checkpoint and not used_path.startswith(
            sharded_dir_for(args.checkpoint) + os.sep
        ):
            print(f"latest checkpoint invalid; fell back to {used_path}")
        config, params = ck.config, ck.params
        if args.conv4d_impl:  # explicit flag overrides the checkpoint's
            config = config.replace(conv4d_impl=args.conv4d_impl)
        if args.loss_chunk is not None:  # explicit flag overrides
            config = config.replace(
                loss_chunk=args.loss_chunk,
                nc_remat=args.loss_chunk == 0,
            )
        if args.chunk_remat is not None:  # override in EITHER direction;
            # unset keeps the checkpoint's recorded value
            config = config.replace(loss_chunk_remat=args.chunk_remat)
        if args.nc_topk is not None:  # sparse band: override in either
            # direction; unset keeps the checkpoint's recorded value (the
            # NC params are the same model either way)
            config = config.replace(nc_topk=args.nc_topk)
        if args.nc_topk_mutual is not None:
            config = config.replace(nc_topk_mutual=args.nc_topk_mutual)
        if args.corr_impl is not None:  # selection impl: override in
            # either direction; the band is bitwise-identical, so the
            # resumed NC params are the same model under both impls
            config = config.replace(corr_impl=args.corr_impl)
        if args.corr_tile is not None:
            config = config.replace(corr_stream_tile=args.corr_tile)
        if args.refine is not None:  # coarse-to-fine: override in either
            # direction; unset keeps the checkpoint's recorded value
            config = config.replace(refine_factor=args.refine)
        if args.refine_topk is not None:
            config = config.replace(refine_topk=args.refine_topk)
        if args.refine_radius is not None:
            config = config.replace(refine_radius=args.refine_radius)
        if args.bf16 is not None:  # explicit flag overrides the
            # checkpoint's compute dtype in either direction (master
            # params are f32 in both modes, so the weights are portable)
            config = config.replace(half_precision=args.bf16)
        # the checkpoint records WHICH params were training (the opt-state
        # pytree shape depends on it); default flags adopt its mode, an
        # explicit different mode restarts the optimizer
        if not args.train_fe and not args.fe_finetune_params:
            args.train_fe = ck.train_fe
            args.fe_finetune_params = ck.fe_finetune_blocks
        elif (args.train_fe, args.fe_finetune_params) != (
            ck.train_fe, ck.fe_finetune_blocks
        ):
            print(
                "finetune mode differs from the checkpoint "
                f"(ckpt: train_fe={ck.train_fe}, "
                f"fe_finetune_blocks={ck.fe_finetune_blocks}); "
                "starting a fresh optimizer state",
                flush=True,
            )
            import dataclasses

            ck = dataclasses.replace(ck, opt_state=None)
        start_epoch = ck.epoch
        start_step = ck.step
        opt_state = ck.opt_state  # raw state dict; train() restores into shape
        best_val = ck.best_val_loss
        train_hist, val_hist = ck.train_loss, ck.val_loss
        if ck.cursor:
            # mid-epoch snapshot: resume at the exact step, replaying the
            # same shuffle (the cursor pins the loader seed)
            start_epoch = int(ck.cursor["epoch"])
            start_batch = int(ck.cursor["batch_index"])
            start_epoch_losses = ck.cursor["epoch_losses"]
            if int(ck.cursor["shuffle_seed"]) != args.seed:
                print(
                    f"WARNING: --seed {args.seed} differs from the "
                    f"checkpoint's loader seed {ck.cursor['shuffle_seed']}; "
                    "the resumed epoch will replay a DIFFERENT shuffle",
                    flush=True,
                )
        print(f"resuming from {used_path} at epoch {start_epoch} "
              f"(step {start_step}"
              + (f", batch {start_batch}" if start_batch else "")
              + ")")
        print(f"  config: {config}")
    else:
        config = ImMatchNetConfig(
            feature_extraction_cnn=args.fe_arch,
            ncons_kernel_sizes=tuple(args.ncons_kernel_sizes),
            ncons_channels=tuple(args.ncons_channels),
            half_precision=(True if args.bf16 is None else args.bf16),
            conv4d_impl=args.conv4d_impl
            or default_impl(len(args.ncons_channels)),
            loss_chunk=args.loss_chunk or 0,
            # per-layer remat is the memory bound for the unchunked path;
            # chunk remat is off by default since round 4 (PERF.md)
            nc_remat=not args.loss_chunk,
            loss_chunk_remat=bool(args.chunk_remat),
            nc_topk=args.nc_topk or 0,
            nc_topk_mutual=(True if args.nc_topk_mutual is None
                            else args.nc_topk_mutual),
            corr_impl=args.corr_impl or "dense",
            corr_stream_tile=(128 if args.corr_tile is None
                              else args.corr_tile),
            refine_factor=args.refine or 0,
            refine_topk=(16 if args.refine_topk is None
                         else args.refine_topk),
            refine_radius=args.refine_radius or 0,
        )
        params = init_immatchnet(jax.random.PRNGKey(args.seed), config)

    # validate the EFFECTIVE refine geometry (wherever the config came
    # from) against the feature grid: the pool needs an even division
    if config.refine_factor:
        grid = max(args.image_size // 16, 1)
        if grid % config.refine_factor:
            p.error(
                f"--image_size {args.image_size} gives a {grid}x{grid} "
                f"feature grid, which does not divide by --refine "
                f"{config.refine_factor}"
            )

    # validate the EFFECTIVE chunking (wherever the config came from)
    # against the batch: weak_loss treats chunk >= batch as unchunked, so
    # remat must come from nc_remat in that case, and partial chunks raise
    if config.loss_chunk:
        if config.loss_chunk >= args.batch_size:
            print(
                f"loss_chunk {config.loss_chunk} >= batch {args.batch_size}: "
                "running unchunked with per-layer remat",
                flush=True,
            )
            config = config.replace(loss_chunk=0, nc_remat=True)
        elif args.batch_size % config.loss_chunk:
            p.error(
                f"batch size {args.batch_size} must be divisible by "
                f"loss_chunk {config.loss_chunk}"
            )

    if args.fe_weights:
        from ncnet_tpu.utils.convert_torch import load_trunk_weights

        params = dict(params)
        params["feature_extraction"] = load_trunk_weights(
            args.fe_weights, cnn=config.feature_extraction_cnn
        )
        print(f"loaded trunk weights from {args.fe_weights}")

    size = (args.image_size, args.image_size)
    if args.synthetic:
        train_ds = SyntheticPairDataset(
            n=args.synthetic_pairs, output_size=size, seed=args.seed
        )
        val_ds = SyntheticPairDataset(n=32, output_size=size, seed=args.seed + 1)
    else:
        train_ds = ImagePairDataset(
            os.path.join(args.dataset_csv_path, "train_pairs.csv"),
            args.dataset_image_path, output_size=size, seed=args.seed,
            uint8_output=args.device_normalize,
        )
        val_ds = ImagePairDataset(
            os.path.join(args.dataset_csv_path, "val_pairs.csv"),
            args.dataset_image_path, output_size=size, seed=args.seed,
            uint8_output=args.device_normalize,
        )
    # --batch_size is GLOBAL; each host loads its 1/n_hosts slice and the
    # global array is assembled in shard_batch (parallel/mesh.py)
    local_bs = args.batch_size // n_hosts
    from_features = bool(args.feature_cache)
    if from_features:
        # one digest-guarded store per split; a stale/mismatched cache
        # raises (FeatureCacheMismatch) instead of training on it. The
        # populate step is the lazy fill-on-first-epoch: it extracts only
        # MISSING shards, so a complete cache costs a directory scan.
        from ncnet_tpu.data.features_loader import FeatureBatchLoader
        from ncnet_tpu.features import (
            FeatureStore,
            populate_store,
            trunk_digest,
        )

        digest = trunk_digest(params["feature_extraction"], config, size)
        stores = {}
        for split, ds in (("train", train_ds), ("val", val_ds)):
            store = FeatureStore.open_or_create(
                os.path.join(args.feature_cache, split),
                digest, config, size, len(ds),
            )
            n_new = populate_store(
                store, params, config, ds,
                batch_size=min(8, max(1, len(ds))), log_every=5,
            )
            print(
                f"feature cache {split}: "
                + (f"extracted {n_new} pairs into" if n_new else "complete,")
                + f" {store.root}",
                flush=True,
            )
            stores[split] = store

        def make_loader(split, shuffle):
            return FeatureBatchLoader(
                stores[split], local_bs, shuffle=shuffle, seed=args.seed,
                num_workers=args.num_workers, drop_last=True,
                host_id=host_id, n_hosts=n_hosts,
                backend=args.loader_backend,
                sample_retries=args.sample_retries,
                skip_budget=args.skip_budget,
                pin_hbm=args.pin_features,
            )

    else:

        def make_loader(split, shuffle):
            return DataLoader(
                train_ds if split == "train" else val_ds, local_bs,
                shuffle=shuffle, seed=args.seed if shuffle else 0,
                num_workers=args.num_workers, drop_last=True,
                host_id=host_id, n_hosts=n_hosts,
                backend=args.loader_backend,
                sample_retries=args.sample_retries,
                skip_budget=args.skip_budget,
            )

    # context-managed loaders + the preemption guard: a SIGTERM (cloud TPU
    # preemption notice) or Ctrl-C checkpoints once at the next step
    # boundary and exits cleanly, with the worker pools shut down on every
    # path (train() also closes the loaders from its own finally)
    peer_down = False
    try:
        with PreemptionGuard(cluster=cluster) as guard, make_loader(
            "train", True
        ) as train_loader, make_loader("val", False) as val_loader:
            _, history = train(
                config,
                params,
                train_loader,
                val_loader,
                num_epochs=args.num_epochs,
                learning_rate=args.lr,
                train_fe=args.train_fe,
                fe_finetune_blocks=args.fe_finetune_params,
                checkpoint_dir=args.result_model_dir,
                checkpoint_name=args.result_model_fn,
                start_epoch=start_epoch,
                start_step=start_step,
                start_batch=start_batch,
                start_epoch_losses=start_epoch_losses,
                opt_state=opt_state,
                initial_best_val=best_val,
                initial_train_hist=train_hist,
                initial_val_hist=val_hist,
                profile_dir=args.profile_dir or None,
                profile_steps=profile_steps,
                save_every_steps=args.save_every_steps,
                keep_checkpoints=args.keep_checkpoints,
                preemption=guard,
                from_features=from_features,
                distributed_checkpoints=args.distributed_checkpoints,
                async_checkpoints=args.async_checkpoints,
                cluster=cluster,
            )
    except PeerDown as e:
        # the typed elastic-restart path: the supervisor parent re-forms
        # the cluster at the surviving topology and relaunches resuming
        # from the latest valid save; without --elastic the status still
        # tells the operator's process manager this is a retryable
        # topology failure, not a crash
        print(f"[cluster] {e}; exiting {EXIT_PEER_DOWN} "
              "(elastic restart status)", flush=True)
        peer_down = True
        history = {}
    finally:
        if cluster is not None:
            cluster.close()
            print(f"[cluster] report: {cluster.report()}", flush=True)
        # flushes the event log + .prom snapshot on EVERY exit path, the
        # same posture as the loaders' context managers (no-op without
        # --telemetry)
        from ncnet_tpu import telemetry

        telemetry.stop()
    if peer_down:
        # HARD exit, after the cleanup above: a host departing on
        # PeerDown must not join the jax distributed runtime's atexit
        # shutdown barrier — with the peer dead, the coordination
        # service aborts the process (SIGABRT), clobbering the typed
        # status the elastic supervisor keys restarts on
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(EXIT_PEER_DOWN)
    if history.get("preempted"):
        print("exiting after preemption checkpoint (resume with "
              f"--checkpoint {os.path.join(args.result_model_dir, args.result_model_fn)})",
              flush=True)


if __name__ == "__main__":
    main()
