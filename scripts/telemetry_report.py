"""Render a telemetry JSONL event log into per-surface summary tables.

Consumes the event log(s) a ``--telemetry DIR`` run writes
(``scripts/train.py``, ``scripts/serve.py``, ``bench.py`` — one schema,
`ncnet_tpu.telemetry.export`). A run dir may hold the legacy
single-process ``events.jsonl`` OR per-process ``events_proc<P>.jsonl``
files (multihost runs share one dir); both layouts are globbed, spans
aggregate across all processes, and metric names are tagged
``{proc=P}`` when more than one log contributes. Prints:

  * a **span table** per surface (the first path segment: ``step``,
    ``serve``, ``eval``, ``checkpoint``, ``ckpt`` — the async handoff
    (``ckpt/handoff``) vs writer-thread save (``ckpt/write_async``)
    split — and ``features``): count, total seconds, SELF seconds
    (total minus the time attributed to child spans — the span tree's
    exclusive time), and p50/p95/p99 of the span duration;
  * a **metrics table**: final counter/gauge values and histogram
    count/sum/percentiles.

Pure host-side rendering: imports `ncnet_tpu.telemetry` (stdlib + numpy)
but never jax, so it runs anywhere the log file does — a laptop reading
a log scp'd off a pod.

Usage:
  python scripts/telemetry_report.py RUN_DIR_or_events.jsonl [--json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ncnet_tpu.telemetry.export import find_event_logs, read_events  # noqa: E402
from ncnet_tpu.telemetry.registry import percentiles  # noqa: E402


def load_events(path):
    """All events for ``path``: a JSONL file, or a run dir holding the
    legacy ``events.jsonl`` and/or per-process ``events_proc<P>.jsonl``
    logs. Multi-log runs get each event tagged with its log's
    process index (the meta record's, falling back to file order) so
    `final_metrics` can keep per-process values apart."""
    if not os.path.isdir(path):
        return read_events(path)
    logs = find_event_logs(path)
    if not logs:
        raise FileNotFoundError(
            f"{path}: no events.jsonl or events_proc*.jsonl found"
        )
    events = []
    for i, log in enumerate(logs):
        chunk = read_events(log)
        proc = i
        for e in chunk:
            if e.get("type") == "meta" and "process_index" in e:
                proc = int(e["process_index"])
                break
        if len(logs) > 1:
            for e in chunk:
                e.setdefault("proc", proc)
        events.extend(chunk)
    return events


def aggregate_spans(events):
    """Span aggregation by path: ``{path: {count, total_s, self_s,
    p50/p95/p99, name}}``.

    Self time = the path's total minus its DIRECT children's totals
    (exclusive time in the span tree). Paths are the nesting record —
    "a>b" is a "b" span that ran inside an "a" span (``>`` is the
    nesting separator; ``/`` belongs to span NAMES like
    "step/loss_sync") — so parentage is pure string structure;
    aggregation is across threads and repeats.

    Spans carrying a ``replica`` tag (a serving fleet tags each
    replica's worker threads — `telemetry.trace.set_thread_tag`) key as
    ``path{replica=R}``: the fleet view stays one merged table while
    per-replica rows remain distinguishable, the same convention
    `final_metrics` uses for ``{proc=P}``.
    """
    durs = {}
    for e in events:
        if e.get("type") != "span":
            continue
        path = e["path"]
        if "replica" in e:
            path = f"{path}{{replica={e['replica']}}}"
        durs.setdefault(path, []).append(float(e["dur_s"]))
    child_total = {}
    for path, samples in durs.items():
        # the {replica=R} suffix rides along to the parent key: parent
        # and child spans come from the same (tagged) worker thread
        base, _, tag = path.partition("{")
        if ">" not in base:
            continue
        parent = base.rsplit(">", 1)[0] + (f"{{{tag}" if tag else "")
        child_total[parent] = child_total.get(parent, 0.0) + sum(samples)
    out = {}
    for path, samples in sorted(durs.items()):
        total = sum(samples)
        row = {
            "name": path.rsplit(">", 1)[-1],
            "count": len(samples),
            "total_s": total,
            "self_s": total - child_total.get(path, 0.0),
        }
        row.update(percentiles(samples))
        out[path] = row
    return out


def final_metrics(events):
    """Last metric record per name (the stop()-time snapshot wins).
    Events carrying a ``proc`` tag (multi-log runs — see `load_events`)
    and/or a ``replica`` tag (a serving fleet publishes each replica
    engine's private registry via `TelemetrySession.add_registry`) keep
    one final value per tag combination, keyed ``name{proc=P}`` /
    ``name{replica=R}`` / ``name{proc=P,replica=R}``, so neither two
    hosts nor two replicas last-wins-clobber each other."""
    out = {}
    for e in events:
        if e.get("type") == "metric":
            name = e["name"]
            tags = [
                f"{k}={e[k]}" for k in ("proc", "replica") if k in e
            ]
            if tags:
                name = f"{name}{{{','.join(tags)}}}"
            out[name] = e
    return out


def by_surface(span_rows):
    """Group by the ROOT span's surface prefix ("serve/dispatch>x" and
    "serve/prep" both land under "serve")."""
    surfaces = {}
    for path, row in span_rows.items():
        root = path.split(">", 1)[0]
        surfaces.setdefault(root.split("/", 1)[0], {})[path] = row
    return surfaces


def _fmt_s(v):
    if v != v:  # NaN
        return "nan"
    if abs(v) >= 1.0:
        return f"{v:.3f}s"
    return f"{v * 1e3:.2f}ms"


def _fmt_num(v):
    if v != v:  # NaN
        return "nan"
    return f"{v:g}"


def _table(rows, headers):
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*headers), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*r) for r in rows]
    return "\n".join(lines)


def render(events):
    """The human-readable report for a parsed event list."""
    spans = aggregate_spans(events)
    metrics = final_metrics(events)
    blocks = []
    for surface, rows in sorted(by_surface(spans).items()):
        table = [
            [
                path,
                str(r["count"]),
                _fmt_s(r["total_s"]),
                _fmt_s(r["self_s"]),
                _fmt_s(r["p50"]),
                _fmt_s(r["p95"]),
                _fmt_s(r["p99"]),
            ]
            for path, r in rows.items()
        ]
        blocks.append(
            f"== {surface} spans ==\n"
            + _table(
                table,
                ["path", "count", "total", "self", "p50", "p95", "p99"],
            )
        )
    if metrics:
        table = []
        for name in sorted(metrics):
            m = metrics[name]
            if m.get("kind") == "histogram":
                # durations render as s/ms; other histograms (batch
                # sizes, byte counts) as plain numbers
                fmt = _fmt_s if name.endswith("_seconds") else _fmt_num
                value = f"count={m['count']} sum={fmt(m['sum'])}"
                pcts = " ".join(
                    f"{p}={fmt(m[p])}"
                    for p in ("p50", "p95", "p99")
                    if p in m
                )
                table.append([name, m["kind"], value, pcts])
            else:
                table.append([name, m["kind"], str(m.get("value")), ""])
        blocks.append(
            "== metrics ==\n"
            + _table(table, ["name", "kind", "value", "percentiles"])
        )
    if not blocks:
        blocks.append("(no span or metric events in the log)")
    return "\n\n".join(blocks)


def report(path):
    """Machine-readable report dict for a log path (file or run dir)."""
    events = load_events(path)
    return {
        "events": len(events),
        "spans": aggregate_spans(events),
        "metrics": final_metrics(events),
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        description="render a telemetry events.jsonl into summary tables"
    )
    p.add_argument("path", help="run dir (containing events.jsonl or "
                                "events_proc<P>.jsonl logs) or a JSONL "
                                "file")
    p.add_argument("--json", action="store_true",
                   help="emit the aggregation as JSON instead of tables")
    args = p.parse_args(argv)

    events = load_events(args.path)
    if args.json:
        print(json.dumps(
            {
                "events": len(events),
                "spans": aggregate_spans(events),
                "metrics": final_metrics(events),
            },
            indent=2, sort_keys=True, default=str,
        ))
    else:
        print(render(events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
