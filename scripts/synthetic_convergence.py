"""End-to-end learning proof on synthetic data: train the NC head with the
weak loss on `SyntheticPairDataset` (known cyclic-shift ground truth) and
report (a) the training-loss curve, (b) a PCK-style keypoint-transfer
metric before vs after, and (c) the DEGENERATE zero-shift baseline the
metric must beat — demonstrating convergence with no dataset on disk.

Measured on a v5e (round 4; defaults: patch16 trunk, identity NC init,
lr 5e-4, 128px): loss -0.13 -> -0.76 (decile means) and transfer
PCK@0.15 0.73 -> 0.98 against a 0.31 degenerate-diagonal baseline.
Negative results kept honest in-code: with a randomly-initialized DEEP
trunk, or from the reference's uniform NC init, the same weak loss falls
just as happily while PCK lands AT or BELOW that degenerate baseline —
the pre-round-4 version of this script was certifying exactly that.
Runs anywhere (TPU or CPU):
  python scripts/synthetic_convergence.py [--image_size 128 --steps 200]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run(image_size=128, steps=400, batch=8, n_pairs=32, lr=5e-4, seed=0,
        ncons_kernel_sizes=(3, 3), ncons_channels=(16, 1), alpha=0.15,
        conv4d_impl="cfs", fe_arch="patch16", nc_init="identity",
        log_every=20, verbose=True):
    import jax

    from ncnet_tpu.data.loader import DataLoader
    from ncnet_tpu.data.pairs import SyntheticPairDataset
    from ncnet_tpu.eval.synthetic import evaluate_synthetic
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.train.step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    config = ImMatchNetConfig(
        feature_extraction_cnn=fe_arch,
        ncons_kernel_sizes=tuple(ncons_kernel_sizes),
        ncons_channels=tuple(ncons_channels),
        conv4d_impl=conv4d_impl,
        # no pretrained weights exist in this environment: centering gives
        # the random trunk's correlations real contrast (see
        # feature_extraction_apply docstring)
        center_features=True,
        nc_init=nc_init,
    )
    # Round-4 measured defaults that make this a REAL proof:
    # - trunk 'patch16' (models/patch.py): a randomly-initialized DEEP
    #   trunk has near-constant pairwise feature cosines (~0.96 on
    #   textured pairs), so its correlations carry almost no signal;
    # - nc_init 'identity': from the reference's uniform init the weak
    #   loss falls while transfer PCK drops BELOW the degenerate
    #   zero-shift baseline (a non-matching optimum); from the near-
    #   identity basin the same loss drives PCK 0.73 -> 0.98.
    params = init_immatchnet(jax.random.PRNGKey(seed), config)

    size = (image_size, image_size)
    train_ds = SyntheticPairDataset(n=n_pairs, output_size=size, seed=seed)
    eval_ds = SyntheticPairDataset(
        n=16, output_size=size, seed=seed + 999, return_shift=True
    )
    train_loader = DataLoader(
        train_ds, batch, shuffle=True, seed=seed, num_workers=2, drop_last=True
    )
    eval_loader = DataLoader(eval_ds, 8, shuffle=False, num_workers=2)

    pck_before = evaluate_synthetic(params, config, eval_loader, alpha=alpha)

    optimizer = make_optimizer(lr)
    state = create_train_state(params, optimizer)
    step_fn = make_train_step(config, optimizer, donate=False)

    losses = []
    it = iter(train_loader)
    for i in range(steps):
        try:
            batch_np = next(it)
        except StopIteration:
            it = iter(train_loader)
            batch_np = next(it)
        jb = {
            "source_image": batch_np["source_image"],
            "target_image": batch_np["target_image"],
        }
        state, loss = step_fn(state, jb)
        losses.append(float(loss))
        if verbose and (i + 1) % log_every == 0:
            print(f"step {i + 1}/{steps} loss {losses[-1]:+.6f}", flush=True)

    pck_after = evaluate_synthetic(state.params, config, eval_loader, alpha=alpha)
    first = float(np.mean(losses[: max(len(losses) // 10, 1)]))
    last = float(np.mean(losses[-max(len(losses) // 10, 1):]))
    # Honesty gauge (round 4): the PCK a DEGENERATE zero-shift (diagonal)
    # predictor would score on this eval set — a point is "correct" for it
    # whenever the pair's shift is under the PCK radius. A trained model
    # must clear this, not just chance; deep random trunks do not.
    pck_diagonal = float(np.mean([
        eval_ds[i]["shift"] <= alpha * image_size for i in range(len(eval_ds))
    ]))
    if verbose:
        print(f"loss: first-decile mean {first:+.6f} -> last-decile mean {last:+.6f}")
        print(f"synthetic transfer PCK@{alpha}: {pck_before:.3f} -> {pck_after:.3f} "
              f"(degenerate-diagonal baseline {pck_diagonal:.3f})")
    return {
        "loss_first": first,
        "loss_last": last,
        "losses": losses,
        "pck_before": pck_before,
        "pck_after": pck_after,
        "pck_diagonal_baseline": pck_diagonal,
        # trained params + config so downstream synthetic end-to-end
        # proofs (scripts/synthetic_inloc_e2e.py) can reuse the model
        "params": state.params,
        "config": config,
    }


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--image_size", type=int, default=128)
    p.add_argument("--steps", type=int, default=400)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--lr", type=float, default=5e-4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--alpha", type=float, default=0.15)
    # same surface as scripts/train.py: no 'pallas' (interpret-mode only);
    # comma-separated per-layer lists allowed; registry from the library
    def impl_arg(value):
        from ncnet_tpu.ops.conv4d import CONV4D_IMPLS, is_valid_impl

        for name in value.split(","):
            if not is_valid_impl(name):
                raise argparse.ArgumentTypeError(
                    f"unknown conv4d impl {name!r} (choose from "
                    f"{', '.join(CONV4D_IMPLS)}; '<fwd>/<dx>' composes)"
                )
        return value

    p.add_argument("--conv4d_impl", type=impl_arg, default="cfs")
    p.add_argument("--fe_arch", default="patch16",
                   help="trunk; 'patch16' (default) is the random-"
                        "orthogonal patch embed — deep random trunks "
                        "train to the degenerate diagonal (see run())")
    p.add_argument("--nc_init", default="identity",
                   choices=["identity", "reference"],
                   help="NC weight init; 'reference' demonstrably lands "
                        "the weak loss in a non-matching optimum on this "
                        "synthetic task (kept for the record)")
    p.add_argument("--ncons_kernel_sizes", nargs="+", type=int, default=[3, 3])
    p.add_argument("--ncons_channels", nargs="+", type=int, default=[16, 1])
    p.add_argument("--json_out", default="",
                   help="write the run metrics (loss trajectory, PCK "
                        "before/after, degenerate baseline) as JSON")
    p.add_argument("--plot_out", default="",
                   help="write a loss-curve + PCK figure (PNG)")
    args = p.parse_args()
    out = run(
        image_size=args.image_size,
        fe_arch=args.fe_arch,
        nc_init=args.nc_init,
        steps=args.steps,
        batch=args.batch,
        lr=args.lr,
        seed=args.seed,
        alpha=args.alpha,
        conv4d_impl=args.conv4d_impl,
        ncons_kernel_sizes=tuple(args.ncons_kernel_sizes),
        ncons_channels=tuple(args.ncons_channels),
    )
    # the gate must beat the DEGENERATE predictor, not just the random
    # init: a model that collapsed to the diagonal scores exactly the
    # baseline (the round-4 finding for deep random trunks)
    ok = (
        out["loss_last"] < out["loss_first"]
        and out["pck_after"] > out["pck_before"]
        and out["pck_after"] > out["pck_diagonal_baseline"]
    )
    if args.json_out:
        import json

        metrics = {k: v for k, v in out.items()
                   if k not in ("params", "config")}
        metrics.update(
            convergence_ok=ok, steps=args.steps, alpha=args.alpha,
            image_size=args.image_size, fe_arch=args.fe_arch,
            nc_init=args.nc_init, seed=args.seed,
        )
        with open(args.json_out, "w") as f:
            json.dump(metrics, f, indent=1)
        print(f"wrote {args.json_out}")
    if args.plot_out:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(9, 3.2))
        ax1.plot(out["losses"], lw=0.8)
        ax1.set_xlabel("step")
        ax1.set_ylabel("weak loss")
        ax1.set_title("training loss")
        bars = [out["pck_before"], out["pck_after"],
                out["pck_diagonal_baseline"]]
        ax2.bar(["before", "after", "degenerate\nbaseline"], bars,
                color=["#999", "#2a6", "#c66"])
        ax2.set_ylim(0, 1.05)
        ax2.set_title(f"transfer PCK@{args.alpha}")
        fig.tight_layout()
        fig.savefig(args.plot_out, dpi=120)
        print(f"wrote {args.plot_out}")
    print(f"convergence {'OK' if ok else 'NOT DEMONSTRATED'}")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
