"""InLoc match-dump CLI (reference eval_inloc.py equivalent).

Writes matches/<experiment>/<q+1>.mat files consumed by the MATLAB
PnP-RANSAC + pose-verification pipeline.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description="ncnet_tpu InLoc match dump")
    p.add_argument("--checkpoint", type=str, required=True)
    p.add_argument("--inloc_shortlist", type=str,
                   default="datasets/inloc/densePE_top100_shortlist_cvpr18.mat")
    p.add_argument("--k_size", type=int, default=2)
    p.add_argument("--image_size", type=int, default=3200)
    p.add_argument("--n_queries", type=int, default=356)
    p.add_argument("--n_panos", type=int, default=10)
    def str2bool(v):
        return str(v).lower() in ("1", "true", "yes", "y")

    p.add_argument("--softmax", type=str2bool, default=True,
                   help="softmax-normalize match scores over the source "
                        "dim (reference eval_inloc.py --softmax)")
    p.add_argument("--matching_both_directions", type=str2bool, default=True)
    p.add_argument("--flip_matching_direction", type=str2bool, default=False)
    p.add_argument("--pano_path", type=str, default="datasets/inloc/pano/")
    p.add_argument("--query_path", type=str, default="datasets/inloc/query/iphone7/")
    p.add_argument("--output_root", type=str, default="matches")
    p.add_argument("--bf16", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="bf16 features/correlation/NC compute — the "
                        "reference eval's fp16 memory toolkit, TPU-native "
                        "(default ON: the 3200px pooled correlation does "
                        "not fit in f32); --no-bf16 runs full f32")
    p.add_argument("--conv4d_impl", type=str, default="cfs",
                   help="conv4d lowering for the eval forward (overrides "
                        "the checkpoint's training-time choice, which is "
                        "tuned for the 25x25 training grid; 'cfs' is the "
                        "measured-best at InLoc grids: 0.92 s/pair vs "
                        "btl4 2.55, scan 14.6 — see "
                        "benchmarks/micro_inloc.py)")
    p.add_argument("--device_preprocess", type=str2bool, default=True,
                   help="ship images to the device as uint8 and ImageNet-"
                        "normalize there (4x less transfer; differs from "
                        "the host-fp32 path only by uint8 rounding of the "
                        "resized pixels). false = exact host-fp32 "
                        "preprocessing")
    p.add_argument("--device_resize", type=str2bool, default=None,
                   help="when an image must be UPSCALED to its resize "
                        "bucket (InLoc panos: 1600x1200 -> 2400x3200), "
                        "ship the original uint8 and bilinear-resize on "
                        "device — ~4x less transfer for panos. Requires "
                        "--device_preprocess; downscaled images (queries) "
                        "keep the host resize either way. Default: on "
                        "whenever --device_preprocess is on. NOTE: "
                        "upscaled originals ship UNQUANTIZED, so each "
                        "distinct original image size costs one extra "
                        "jit compile of the device resize (free on real "
                        "InLoc — panos are uniformly 1600x1200; turn "
                        "this off for datasets with many heterogeneous "
                        "original sizes). The upscale check is area-"
                        "based and assumes the aspect-preserving resize "
                        "rule (see eval/inloc.py:load_and_preprocess)")
    p.add_argument("--feature-store", type=str, default=None,
                   dest="feature_store", metavar="DIR",
                   help="gallery feature store "
                        "(ncnet_tpu.features.GalleryFeatureStore): cache "
                        "database-pano trunk features in DIR, keyed by "
                        "image path under a trunk-weights digest — each "
                        "pano's backbone forward runs once EVER (across "
                        "queries and dump restarts) instead of once per "
                        "query-pano pair; the query trunk runs once per "
                        "query. A store extracted under different trunk "
                        "weights/config is rejected (digest mismatch), "
                        "never silently matched against. Incompatible "
                        "with --spatial_shards/--device_preprocess/"
                        "--device_resize (the store path has its own "
                        "host pipeline)")
    p.add_argument("--refine", type=int, default=None, metavar="R",
                   help="coarse-to-fine refinement (ncnet_tpu.refine): "
                        "pool features by R, run the coarse band at "
                        "--refine_topk, re-score the survivors at high "
                        "res. Requires --k_size 1 (refinement replaces "
                        "the 4D-maxpool relocalization — both are "
                        "memory ladders, refinement reads out at the "
                        "full grid). 0 forces refinement OFF; unset "
                        "keeps the checkpoint's value")
    p.add_argument("--refine_topk", type=int, default=None, metavar="K",
                   help="with --refine: coarse-band width")
    p.add_argument("--refine_radius", type=int, default=None,
                   help="with --refine: extra window reach in coarse cells")
    p.add_argument("--spatial_shards", type=int, default=0,
                   help="shard the correlation pipeline over this many "
                        "devices ('spatial' mesh axis) for grids beyond "
                        "single-chip HBM; 0 = unsharded")
    args = p.parse_args()

    if args.device_resize and not args.device_preprocess:
        p.error("--device_resize requires --device_preprocess")
    if args.device_resize is None:
        args.device_resize = args.device_preprocess
    if args.feature_store:
        if args.spatial_shards > 1:
            p.error("--feature-store is incompatible with --spatial_shards")
        # the store path ships features, not images: the uint8/device
        # resize transfer engineering does not apply there
        args.device_preprocess = False
        args.device_resize = False

    if args.checkpoint.endswith((".pth.tar", ".pth")):
        from ncnet_tpu.utils.convert_torch import convert_checkpoint

        config, params = convert_checkpoint(args.checkpoint)
    else:
        from ncnet_tpu.train.checkpoint import load_checkpoint

        ck = load_checkpoint(args.checkpoint)
        config, params = ck.config, ck.params

    # bf16 + relocalization: the memory toolkit of the reference eval
    # (fp16 + maxpool4d, eval_inloc.py:50,32), TPU-native. The conv4d
    # impl is OVERRIDDEN for eval: checkpoints carry the training-grid
    # (l=25) winner, whose dense-Toeplitz edge layers inflate FLOPs by
    # l/kl = 20x at InLoc's l=100 pooled grid. 'cfs' (true FLOPs, wide
    # lanes, scanned) measures 0.92 s/pair steady-state at (2400, 3200)
    # k=2 vs btl4 2.55 and 'scan' 14.6; 'xla'/'tf3'/'btl2'/'btl6' fail
    # to compile at this shape (benchmarks/micro_inloc.py).
    config = config.replace(
        half_precision=args.bf16,
        relocalization_k_size=args.k_size,
        conv4d_impl=args.conv4d_impl,
    )
    if args.refine is not None:
        config = config.replace(refine_factor=args.refine)
    if args.refine_topk is not None:
        config = config.replace(refine_topk=args.refine_topk)
    if args.refine_radius is not None:
        config = config.replace(refine_radius=args.refine_radius)
    if config.refine_factor and args.k_size > 1:
        # refine_match_pipeline raises on relocalization configs deep in
        # the first trace; fail at the flag boundary instead
        p.error(
            f"--refine {config.refine_factor} requires --k_size 1 "
            "(refinement replaces the 4D-maxpool relocalization)"
        )

    exp = os.path.basename(args.inloc_shortlist).split(".")[0]
    exp += f"_SZ_NEW_{args.image_size}_K_{args.k_size}"
    # both_directions takes precedence over flip (reference if/elif order,
    # eval_inloc.py:61-63)
    exp += "_BOTHDIRS" if args.matching_both_directions else (
        "_AtoB" if args.flip_matching_direction else "_BtoA"
    )
    if args.softmax:
        exp += "_SOFTMAX"
    if args.checkpoint:
        exp += "_CHECKPOINT_" + os.path.basename(args.checkpoint).split(".")[0]
    out_dir = os.path.join(args.output_root, exp)
    print(f"Output matches folder: {out_dir}")

    mesh = None
    if args.spatial_shards > 1:
        import jax

        from ncnet_tpu.parallel.mesh import make_mesh

        n_dev = len(jax.devices())
        if args.spatial_shards > n_dev:
            p.error(
                f"--spatial_shards {args.spatial_shards} exceeds the "
                f"{n_dev} available device(s)"
            )
        mesh = make_mesh(
            (args.spatial_shards,), ("spatial",),
            devices=jax.devices()[: args.spatial_shards],
        )

    from ncnet_tpu.eval.inloc import dump_matches

    dump_matches(
        params,
        config,
        shortlist_path=args.inloc_shortlist,
        query_path=args.query_path,
        pano_path=args.pano_path,
        output_dir=out_dir,
        image_size=args.image_size,
        n_queries=args.n_queries,
        n_panos=args.n_panos,
        both_directions=args.matching_both_directions,
        flip_direction=args.flip_matching_direction
        and not args.matching_both_directions,
        mesh=mesh,
        softmax=args.softmax,
        device_preprocess=args.device_preprocess,
        device_resize=args.device_resize,
        feature_store_dir=args.feature_store,
    )


if __name__ == "__main__":
    main()
