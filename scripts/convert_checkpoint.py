"""Convert a reference PyTorch checkpoint (.pth.tar) to an ncnet_tpu
msgpack checkpoint (self-describing: architecture config embedded).

Usage:
  python scripts/convert_checkpoint.py IN.pth.tar OUT.msgpack
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("src", help="reference .pth.tar checkpoint")
    p.add_argument("dst", help="output .msgpack path")
    args = p.parse_args()

    from ncnet_tpu.train.checkpoint import CheckpointData, save_checkpoint
    from ncnet_tpu.utils.convert_torch import convert_checkpoint

    config, params = convert_checkpoint(args.src)
    save_checkpoint(args.dst, CheckpointData(config=config, params=params))
    print(f"wrote {args.dst}")
    print(f"  config: {config}")


if __name__ == "__main__":
    main()
