"""Keypoint-transfer demo — the reference ``point_transfer_demo.ipynb``
(cells 1-7) as a script: load a model, pick a PF-Pascal test pair, forward,
``corr_to_matches(do_softmax=True)`` -> bilinear keypoint transfer -> save a
side-by-side PNG a human can eyeball (via ncnet_tpu.utils.plot, the
lib/plot.py equivalent).

With no dataset on disk (zero-egress environments), ``--synthetic`` runs the
same pipeline on a generated pair with KNOWN cyclic-shift ground truth and
reports the transfer PCK in the figure title.

Example:
  python scripts/demo_point_transfer.py --checkpoint trained_models/ncnet_tpu.msgpack
  python scripts/demo_point_transfer.py --synthetic --out demo.png
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp


def main():
    p = argparse.ArgumentParser(description="ncnet_tpu point-transfer demo")
    p.add_argument("--checkpoint", type=str, default="",
                   help=".msgpack or reference .pth.tar checkpoint "
                        "(random weights if omitted)")
    p.add_argument("--dataset_image_path", type=str, default="datasets/pf-pascal")
    p.add_argument("--dataset_csv_path", type=str,
                   default="datasets/pf-pascal/image_pairs")
    p.add_argument("--pair_idx", type=int, default=-1,
                   help="test-pair index (-1 = random, like the notebook)")
    p.add_argument("--synthetic", action="store_true",
                   help="use a generated pair with known ground truth")
    p.add_argument("--image_size", type=int, default=400)
    p.add_argument("--out", type=str, default="demo_point_transfer.png")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    from ncnet_tpu.models.immatchnet import (
        ImMatchNetConfig,
        immatchnet_apply,
        init_immatchnet,
    )
    from ncnet_tpu.ops.coords import (
        points_to_pixel_coords,
        points_to_unit_coords,
    )
    from ncnet_tpu.ops.matches import bilinear_point_transfer, corr_to_matches
    from ncnet_tpu.utils.plot import draw_point_transfer

    if args.checkpoint.endswith((".pth.tar", ".pth")):
        from ncnet_tpu.utils.convert_torch import convert_checkpoint

        config, params = convert_checkpoint(args.checkpoint)
    elif args.checkpoint:
        from ncnet_tpu.train.checkpoint import load_checkpoint

        ck = load_checkpoint(args.checkpoint)
        config, params = ck.config, ck.params
    elif args.synthetic:
        # pretrained-free model that genuinely matches synthetic pairs
        # (round 4): patch16 random-orthogonal trunk + EXACT identity NC
        # (noise-free: init_neigh_consensus's identity_noise=0.02 scaled
        # by the 5^4-tap fan-in would swamp the pass-through when
        # untrained) — the demo figure shows REAL transfers, like the
        # reference's stored-output notebook does with released weights
        from ncnet_tpu.models.neigh_consensus import init_neigh_consensus

        config = ImMatchNetConfig(
            feature_extraction_cnn="patch16",
            ncons_kernel_sizes=(5, 5, 5), ncons_channels=(16, 16, 1),
            conv4d_impl="cf", center_features=True,
        )
        params = init_immatchnet(jax.random.PRNGKey(args.seed), config)
        params["neigh_consensus"] = init_neigh_consensus(
            jax.random.PRNGKey(args.seed),
            config.ncons_kernel_sizes,
            config.ncons_channels,
            scheme="identity",
            identity_noise=0.0,
        )
    else:
        print("WARNING: no --checkpoint — using RANDOM weights; the transfer "
              "will be noise (this exercises the pipeline, not the model)")
        config = ImMatchNetConfig(
            ncons_kernel_sizes=(5, 5, 5), ncons_channels=(16, 16, 1),
            conv4d_impl="cf",
        )
        params = init_immatchnet(jax.random.PRNGKey(args.seed), config)

    size = (args.image_size, args.image_size)
    title = None
    if args.synthetic:
        from ncnet_tpu.data.pairs import SyntheticPairDataset
        from ncnet_tpu.eval.synthetic import _query_grid

        ds = SyntheticPairDataset(
            n=8, output_size=size, seed=args.seed, return_shift=True,
            # coarse texture so the constructed patch16+identity model's
            # cell-quantized matching resolves arbitrary (non-16-aligned)
            # shifts — see SyntheticPairDataset.granularity
            granularity=48 if not args.checkpoint else 8,
        )
        idx = (
            np.random.RandomState(args.seed).randint(len(ds))
            if args.pair_idx < 0
            else args.pair_idx
        )
        sample = ds[idx]
        h, w = size
        tgt_px = _query_grid(h, w)  # [2, 16] in the right half (no wrap)
        gt_src_px = tgt_px.copy()
        gt_src_px[0] -= float(sample["shift"])
        src_pts, tgt_pts = gt_src_px, tgt_px
        im_size = np.asarray([[h, w, 3]], np.float32)
        src_size = tgt_size = im_size
    else:
        from ncnet_tpu.data.pairs import PFPascalDataset

        csv = os.path.join(args.dataset_csv_path, "test_pairs.csv")
        ds = PFPascalDataset(
            csv, args.dataset_image_path, output_size=size, pck_procedure="pf"
        )
        idx = (
            np.random.RandomState(args.seed).randint(len(ds))
            if args.pair_idx < 0
            else args.pair_idx
        )
        sample = ds[idx]
        src_pts = np.asarray(sample["source_points"])
        tgt_pts = np.asarray(sample["target_points"])
        src_size = np.asarray(sample["source_im_size"], np.float32)[None]
        tgt_size = np.asarray(sample["target_im_size"], np.float32)[None]

    src = jnp.asarray(sample["source_image"])[None]
    tgt = jnp.asarray(sample["target_image"])[None]
    print(f"pair {idx}: forward on {jax.default_backend()} ...", flush=True)
    corr = immatchnet_apply(params, config, src, tgt)
    x_a, y_a, x_b, y_b, _ = corr_to_matches(corr, do_softmax=True)

    tgt_norm = points_to_unit_coords(
        jnp.asarray(tgt_pts)[None], jnp.asarray(tgt_size)
    )
    warped_norm = bilinear_point_transfer((x_a, y_a, x_b, y_b), tgt_norm)
    warped_px = np.asarray(
        points_to_pixel_coords(warped_norm, jnp.asarray(src_size))
    )[0]

    if args.synthetic:
        valid = src_pts[0] != -1
        err = np.linalg.norm(warped_px[:, valid] - src_pts[:, valid], axis=0)
        pck = float((err <= 0.1 * args.image_size).mean())
        title = (
            f"synthetic pair {idx} (shift={int(sample['shift'])}px): "
            f"transfer PCK@0.1 = {pck:.2f}"
        )
        print(title)

    # Points are in ORIGINAL image pixels; the displayed images are resized
    # to `size`, so scale points into the displayed frame.
    def to_display(pts, im_size):
        s = np.asarray(
            [size[1] / im_size[0, 1], size[0] / im_size[0, 0]], np.float32
        )
        out = pts * s[:, None]
        out[:, pts[0] == -1] = -1
        return out

    out_path = draw_point_transfer(
        np.asarray(src[0]),
        np.asarray(tgt[0]),
        to_display(src_pts, src_size),
        to_display(warped_px, src_size),
        to_display(tgt_pts, tgt_size),
        args.out,
        title=title,
    )
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
