"""Repo lint gate: `python scripts/lint.py ncnet_tpu scripts benchmarks`.

Thin wrapper over `ncnet_tpu.analysis.cli` (the `nclint` console script of
an installed package); the sys.path insert keeps it runnable straight from
a checkout.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ncnet_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
