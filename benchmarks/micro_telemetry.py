"""Microbench: instrumentation overhead of ncnet_tpu.telemetry.

The subsystem's contract is that DISABLED instrumentation is free — the
serving hot loops and the per-step training loop keep their spans and
counter increments unconditionally, so the disabled cost is paid on
every production step. This bench pins that cost, three ways:

  span_off    — ``with trace.span(...)`` while tracing is disabled: one
                bound-method call, one ``_enabled`` check, the shared
                no-op singleton's enter/exit. The number that must sit
                below the noise floor of any real step.
  span_on     — the same region with tracing enabled into an in-memory
                buffer (two perf_counter reads + dict build + append);
                the price a ``--telemetry`` run pays per span.
  counter/histogram — ``Counter.inc`` and ``Histogram.observe`` (lock +
                add; bisect + three updates), the per-request metric
                cost in the serving readout loop.

Context: a no-op ``with`` block over a pass body (the floor the null
span adds to), and the repo's real step scales — the serving engine's
~ms-scale stages and the training loop's ~100 ms steps — are what
"below noise" is measured against.

Prints one JSON line with per-op nanoseconds. Pure host bench: no jax,
no device, stable on any box.

Usage:
  python benchmarks/micro_telemetry.py [--iters 200000]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ncnet_tpu.telemetry import trace  # noqa: E402
from ncnet_tpu.telemetry.registry import MetricsRegistry  # noqa: E402


class _NoopCtx:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NOOP = _NoopCtx()


def _per_op_ns(fn, iters):
    """min-of-5 per-op nanoseconds for ``fn(iters)`` (min discards
    scheduler noise; the loop body carries the op)."""
    best = min(fn(iters) for _ in range(5))
    return best / iters * 1e9


def bench_empty_loop(iters):
    t0 = time.perf_counter()
    for _ in range(iters):
        pass
    return time.perf_counter() - t0


def bench_noop_with(iters):
    t0 = time.perf_counter()
    for _ in range(iters):
        with _NOOP:
            pass
    return time.perf_counter() - t0


def bench_span(iters):
    t0 = time.perf_counter()
    for _ in range(iters):
        with trace.span("bench/span"):
            pass
    return time.perf_counter() - t0


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=200_000)
    args = p.parse_args()
    iters = args.iters

    if trace.is_enabled():
        raise RuntimeError("tracer unexpectedly enabled at bench start")

    empty_ns = _per_op_ns(bench_empty_loop, iters)
    noop_ns = _per_op_ns(bench_noop_with, iters)
    span_off_ns = _per_op_ns(bench_span, iters)

    trace.enable()  # in-memory buffer sink
    span_on_ns = _per_op_ns(bench_span, iters)
    trace.disable()
    trace.drain()

    reg = MetricsRegistry()
    counter = reg.counter("bench_total", "bench")
    hist = reg.histogram("bench_seconds", "bench")

    def bench_counter(n):
        t0 = time.perf_counter()
        for _ in range(n):
            counter.inc()
        return time.perf_counter() - t0

    def bench_hist(n):
        t0 = time.perf_counter()
        for _ in range(n):
            hist.observe(0.004)
        return time.perf_counter() - t0

    counter_ns = _per_op_ns(bench_counter, iters)
    hist_ns = _per_op_ns(bench_hist, iters)

    print(json.dumps({
        "iters": iters,
        "empty_loop_ns": round(empty_ns, 1),
        "noop_with_ns": round(noop_ns, 1),
        "span_disabled_ns": round(span_off_ns, 1),
        "span_disabled_over_noop_ns": round(span_off_ns - noop_ns, 1),
        "span_enabled_ns": round(span_on_ns, 1),
        "counter_inc_ns": round(counter_ns, 1),
        "histogram_observe_ns": round(hist_ns, 1),
        # the contract number: disabled spans per 100 ms training step
        # if every step carried 10 spans
        "disabled_overhead_per_step_pct": round(
            10 * span_off_ns / (100e6) * 100, 6
        ),
    }))


if __name__ == "__main__":
    main()
