"""Microbench: the streamed tiled correlation->top-K band vs dense.

What it measures, per tile size in the sweep:

  * traced liveness peak (analysis.hlo_audit.jaxpr_memory_highwater) of
    the streamed program vs the dense baseline — the number the tentpole
    claims: O(hA*wA*(K+tile)) vs the O(hA*wA*hB*wB) volume;
  * jitted step wall-time for both impls on this host;
  * the exactness contract, hard-asserted before any timing: the
    streamed band (values AND indices) is bitwise the dense
    ``topk_band(correlation_4d(...), ...)`` reference.

CPU-proxy discipline (PR 3/4): the EXACTNESS and PEAK-BYTES results
transfer to TPU as-is — they are backend-independent program
properties. The WALL-TIME comparison does not: on CPU both impls are
compute-bound through the same GEMMs and the scan's sequential merge
usually makes 'stream' slower; the streaming win is HBM footprint and
bandwidth on TPU, where the dense volume's materialization is the cost.
Re-measure on hardware before quoting a speedup (ROADMAP follow-up) —
this file's honest claim is the memory column, not the ms column.

Prints one JSON document.

Usage:
  python benchmarks/micro_corr_stream.py [--grid 25] [--feat-ch 256]
      [--k 16] [--batch 4] [--tiles 32,64,128,256] [--steps 20]
      [--no-mutual]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--grid", type=int, default=25,
                   help="feature grid side (25 = the 400px config)")
    p.add_argument("--feat-ch", type=int, default=256, dest="feat_ch")
    p.add_argument("--k", type=int, default=16, help="band width")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--tiles", default="32,64,128,256",
                   help="comma-separated tile-size sweep")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--no-mutual", action="store_false", dest="mutual",
                   default=True)
    args = p.parse_args()

    import jax

    from ncnet_tpu.analysis.hlo_audit import jaxpr_memory_highwater
    from ncnet_tpu.ops.band import topk_band
    from ncnet_tpu.ops.corr_stream import corr_stream_band, resolve_corr_tile
    from ncnet_tpu.ops.correlation import correlation_4d
    from ncnet_tpu.ops.matching import mutual_matching

    g, c, k, b = args.grid, args.feat_ch, args.k, args.batch
    nb = g * g
    rng = np.random.RandomState(0)
    fa = jax.device_put(rng.randn(b, g, g, c).astype(np.float32))
    fb = jax.device_put(rng.randn(b, g, g, c).astype(np.float32))

    def dense(a, t):
        corr = correlation_4d(a, t)
        return topk_band(
            corr, k, values_from=mutual_matching(corr), mutual=args.mutual
        )

    def timed(fn):
        jfn = jax.jit(fn)
        out = jax.block_until_ready(jfn(fa, fb))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out = jfn(fa, fb)
        jax.block_until_ready(out)
        return out, (time.perf_counter() - t0) / args.steps * 1e3

    (want_v, want_i), dense_ms = timed(dense)
    dense_peak = jaxpr_memory_highwater(jax.make_jaxpr(dense)(fa, fb).jaxpr)

    sweep = []
    for tile in (int(t) for t in args.tiles.split(",")):
        def stream(a, t, tile=tile):
            return corr_stream_band(a, t, k, mutual=args.mutual, tile=tile)

        (got_v, got_i), ms = timed(stream)
        # the contract, hard-asserted before the numbers mean anything:
        # same band, bitwise (values compared as raw bits)
        np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
        np.testing.assert_array_equal(
            np.asarray(got_v).view(np.uint32),
            np.asarray(want_v).view(np.uint32),
        )
        peak = jaxpr_memory_highwater(jax.make_jaxpr(stream)(fa, fb).jaxpr)
        sweep.append({
            "tile": resolve_corr_tile(tile, nb),
            "step_ms": round(ms, 3),
            "peak_bytes": peak,
            "peak_vs_dense": round(peak / dense_peak, 4),
        })

    print(json.dumps({
        "metric": "corr_stream_tile_sweep",
        "backend": jax.default_backend(),
        "grid": g, "feat_ch": c, "k": k, "batch": b,
        "mutual": args.mutual,
        "bitwise_equal": True,  # the asserts above would have raised
        "corr_peak_bytes_dense": dense_peak,
        "dense_step_ms": round(dense_ms, 3),
        "sweep": sweep,
        "note": "step_ms is a CPU proxy unless backend says tpu; the "
                "transferable columns are peak_bytes and bitwise_equal",
    }, indent=2))


if __name__ == "__main__":
    main()
