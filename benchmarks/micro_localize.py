"""Wall-clock the host-side localization stage at reference scale.

The reference runs 10,000 LO-RANSAC iterations per (query, pano) pair over
356 queries x 10 panos under MATLAB parfor
(lib_matlab/parfor_NC4D_PE_pnponly.m:77,
 ir_top100_NC4D_localization_pnponly.m:25). This benchmark measures our
`lo_ransac_p3p` (vectorized chunks, round 5) on synthetic match sets sized
like real InLoc pairs, compares against the round-4 serial hypothesis
loop, times the densePV scoring stage, and projects the full sweep at a
given worker count.

Run: python benchmarks/micro_localize.py [--serial] [--workers N]
Prints one JSON line per measurement.
"""

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from ncnet_tpu.eval.localize import (  # noqa: E402
    _angular_inliers,
    dlt_pnp,
    lo_ransac_p3p,
    p3p_grunert,
)

N_QUERIES = 356
N_PANOS = 10
MAX_ITERS = 10000
THR_RAD = np.deg2rad(0.2)


def synth_pair(n, inlier_ratio, seed, noise_rad=0.0005):
    """A reference-scale tentative set: n matches, a fraction consistent
    with a ground-truth pose (angular noise ~0.03 deg), the rest random."""
    rng = np.random.RandomState(seed)
    Q, _ = np.linalg.qr(rng.randn(3, 3))
    if np.linalg.det(Q) < 0:
        Q[:, 0] *= -1
    t = rng.randn(3)
    X = rng.randn(n, 3) * 4.0 + np.array([0, 0, 8.0])
    Xc = X @ Q.T + t
    rays = Xc / np.linalg.norm(Xc, axis=1, keepdims=True)
    # rotate each ray slightly (angular noise)
    rays += rng.randn(n, 3) * noise_rad
    n_out = int(n * (1.0 - inlier_ratio))
    out_idx = rng.permutation(n)[:n_out]
    rand = rng.randn(n_out, 3)
    rays[out_idx] = rand / np.linalg.norm(rand, axis=1, keepdims=True)
    return rays, X


def serial_lo_ransac(rays, points, thr_rad, max_iters, seed=0,
                     confidence=0.999):
    """The round-4 per-hypothesis Python loop, kept for comparison."""
    n = len(points)
    rng = np.random.RandomState(seed)
    cos_thr = np.cos(thr_rad)
    rays = rays / np.linalg.norm(rays, axis=1, keepdims=True)
    best_P, best_inl = None, np.zeros(n, bool)
    it, needed = 0, max_iters
    while it < min(max_iters, needed):
        it += 1
        sel = rng.choice(n, 3, replace=False)
        for P in p3p_grunert(rays[sel], points[sel]):
            inl = _angular_inliers(P, rays, points, cos_thr)
            if inl.sum() > best_inl.sum():
                best_P, best_inl = P, inl
                for _ in range(2):
                    if best_inl.sum() >= 6:
                        P_lo = dlt_pnp(rays[best_inl], points[best_inl])
                        if P_lo is None:
                            break
                        inl_lo = _angular_inliers(P_lo, rays, points, cos_thr)
                        if inl_lo.sum() >= best_inl.sum():
                            best_P, best_inl = P_lo, inl_lo
                        else:
                            break
                w = best_inl.sum() / n
                if w > 0:
                    denom = np.log(max(1.0 - w**3, 1e-12))
                    needed = int(np.ceil(np.log(1 - confidence) / denom))
    return best_P, best_inl


def time_ransac(fn, n, inlier_ratio, reps=3):
    best = np.inf
    inl_frac = 0.0
    for r in range(reps):
        rays, X = synth_pair(n, inlier_ratio, seed=100 + r)
        t0 = time.perf_counter()
        _, inl = fn(rays, X)
        best = min(best, time.perf_counter() - t0)
        inl_frac = max(inl_frac, inl.mean())
    return best, inl_frac


def bench_pnp(serial=False):
    out = []
    # (tentatives, inlier ratio): 0-inlier worst case runs the full 10k
    # budget; realistic InLoc pairs land 5-30% after the 0.75 score gate
    cases = [(2000, 0.0), (2000, 0.05), (8000, 0.15), (15000, 0.3)]
    for n, ratio in cases:
        dt, inl = time_ransac(
            lambda r, X: lo_ransac_p3p(r, X, THR_RAD, max_iters=MAX_ITERS),
            n, ratio,
        )
        out.append({
            "metric": "lo_ransac_p3p_s_per_pair",
            "impl": "chunked",
            "tentatives": n,
            "inlier_ratio": ratio,
            "value": round(dt, 4),
            "unit": "s",
            "found_inlier_frac": round(float(inl), 3),
        })
        if serial:
            dt_s, _ = time_ransac(
                lambda r, X: serial_lo_ransac(r, X, THR_RAD, MAX_ITERS),
                n, ratio, reps=1,
            )
            out[-1]["serial_s"] = round(dt_s, 3)
            out[-1]["speedup"] = round(dt_s / dt, 1)
    return out


def bench_jax_batched(reps=3):
    """NumPy-sequential vs jax-batched fixed-schedule RANSAC, poses/s.

    CPU proxy of the serving geometry (round 15): tentatives padded to a
    pose bucket, STATIC hypothesis count, batch axis = queries — the
    exact program `ncnet_tpu.localize.request` serves. Both sides run
    the same fixed schedule (score-all-then-argmax + LO refits), so the
    comparison isolates batching + XLA fusion, not iteration-count
    tricks. Compile time is reported separately: warmed serving programs
    take it off the request path entirely.
    """
    import jax

    from ncnet_tpu.localize import make_ransac_step
    from ncnet_tpu.localize.ransac import ransac_pose_np

    out = []
    n, ratio = 512, 0.3
    for b, hyp in [(1, 64), (8, 64), (32, 64), (32, 16)]:
        rays = np.zeros((b, n, 3), np.float32)
        pts = np.zeros((b, n, 3), np.float32)
        for j in range(b):
            r, X = synth_pair(n, ratio, seed=200 + j)
            rays[j] = r / np.linalg.norm(r, axis=1, keepdims=True)
            pts[j] = X
        mask = np.ones((b, n), bool)
        seeds = np.arange(b, dtype=np.int32)

        step = make_ransac_step(n_hypotheses=hyp, thr_deg=0.2)
        t0 = time.perf_counter()
        res = jax.block_until_ready(step(rays, pts, mask, seeds))
        compile_s = time.perf_counter() - t0
        t_jax = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(step(rays, pts, mask, seeds))
            t_jax = min(t_jax, time.perf_counter() - t0)

        idx = [
            np.random.RandomState(300 + j).randint(0, n, size=(hyp, 3))
            for j in range(b)
        ]
        t_np = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            for j in range(b):
                ransac_pose_np(
                    rays[j].astype(np.float64),
                    pts[j].astype(np.float64),
                    mask[j], idx[j], thr_rad=THR_RAD,
                )
            t_np = min(t_np, time.perf_counter() - t0)

        out.append({
            "metric": "fixed_schedule_ransac_poses_per_s",
            "queries": b,
            "hypotheses": hyp,
            "tentatives": n,
            "numpy_sequential": round(b / t_np, 2),
            "jax_batched": round(b / t_jax, 2),
            "speedup": round(t_np / t_jax, 1),
            "jax_compile_s": round(compile_s, 2),
            "found_inlier_frac": round(
                float(np.asarray(res["n_inliers"]).mean()) / n, 3
            ),
        })
    return out


def bench_densepv():
    from ncnet_tpu.eval.pose_verify import prepare_query, score_prepared

    rng = np.random.RandomState(0)
    qh, qw = 1200, 1600  # reference caps sides at 1920 (at_imageresize)
    n_pts = 1200 * 1600  # one RGBD cutout's worth of scan points
    query = rng.randint(0, 255, (qh, qw, 3)).astype(np.float64)
    rgb = rng.randint(0, 255, (n_pts, 3)).astype(np.float64)
    gx, gy = np.meshgrid(np.arange(1600) * 0.01, np.arange(1200) * 0.01)
    xyz = np.stack(
        [gx.ravel() - 8.0, gy.ravel() - 6.0, np.full(n_pts, 5.0)], axis=1
    )
    P = np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1)

    t0 = time.perf_counter()
    prep = prepare_query(query, focal_length=1400.0)
    t_prep = time.perf_counter() - t0
    t0 = time.perf_counter()
    score_prepared(prep, rgb, xyz, P)
    t_score = time.perf_counter() - t0
    return [{
        "metric": "densePV_s_per_candidate",
        "value": round(t_score, 3),
        "unit": "s",
        "prepare_query_s": round(t_prep, 3),
        "note": "prepare once per query; score per candidate pose "
                "(reference re-ranks top-10)",
    }]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--serial", action="store_true",
                    help="also time the round-4 serial hypothesis loop")
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--skip_densepv", action="store_true")
    ap.add_argument("--skip_jax", action="store_true",
                    help="skip the batched-XLA vs NumPy-sequential rows")
    args = ap.parse_args()

    rows = bench_pnp(serial=args.serial)
    for r in rows:
        print(json.dumps(r), flush=True)

    # full-sweep projection: the mid case approximates the typical pair
    mid = rows[1]["value"]
    worst = rows[0]["value"]
    pnp_total = N_QUERIES * N_PANOS * mid / args.workers
    print(json.dumps({
        "metric": "pnp_sweep_projected_minutes",
        "value": round(pnp_total / 60.0, 1),
        "unit": "min",
        "queries": N_QUERIES,
        "panos": N_PANOS,
        "workers": args.workers,
        "s_per_pair_typical": mid,
        "s_per_pair_worst": worst,
        "worst_case_minutes": round(
            N_QUERIES * N_PANOS * worst / args.workers / 60.0, 1
        ),
    }), flush=True)

    if not args.skip_jax:
        for r in bench_jax_batched():
            print(json.dumps(r), flush=True)

    if not args.skip_densepv:
        for r in bench_densepv():
            print(json.dumps(r), flush=True)


if __name__ == "__main__":
    main()
