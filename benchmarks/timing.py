"""Shared honest-timing helpers for the microbenchmarks.

Platform facts (measured, rounds 1-2): ``jax.block_until_ready`` does NOT
block on the tunneled axon platform — only a device-to-host transfer forces
execution — and a D2H roundtrip costs ~75-95 ms, which swamps per-op
timings. So: every sync is a D2H reduction, and per-op costs come from the
SLOPE between a short and a long chain of dependent applications inside one
jit (the sync constant and dispatch overheads cancel).
"""

import time

import jax
import jax.numpy as jnp


def sync(out):
    """Force execution of ``out`` via a device-to-host reduction."""
    return float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))


def time_once(fn, *args):
    """Seconds for one synced call (includes the D2H constant)."""
    out = fn(*args)
    t0 = time.perf_counter()
    sync(out)
    return time.perf_counter() - t0


def percentiles(samples, ps=(50, 95, 99)):
    """``{'p50': ..., 'p95': ..., 'p99': ...}`` over ``samples`` (seconds
    or any unit — values pass through), linear interpolation. Empty input
    gives NaNs rather than raising: a benchmark that timed nothing should
    still emit a well-formed report."""
    import numpy as np

    if len(samples) == 0:
        return {f"p{p}": float("nan") for p in ps}
    arr = np.asarray(samples, dtype=np.float64)
    return {f"p{p}": float(np.percentile(arr, p)) for p in ps}


def time_chain(make_chain, n_lo=1, n_hi=6, iters=3):
    """Per-iteration seconds via the (n_hi - n_lo) slope.

    ``make_chain(n)`` must return ``(jitted_fn, args)`` running the op n
    times with data dependencies between repeats — beware XLA DCE: every
    repeat must contribute to the returned value (accumulate, don't
    overwrite).
    """
    results = {}
    for n in (n_lo, n_hi):
        fn, args = make_chain(n)
        fn(*args)  # compile
        time_once(fn, *args)  # warmup
        results[n] = min(time_once(fn, *args) for _ in range(iters))
    return (results[n_hi] - results[n_lo]) / (n_hi - n_lo)
