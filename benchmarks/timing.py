"""Shared honest-timing helpers for the microbenchmarks.

Platform facts (measured, rounds 1-2): ``jax.block_until_ready`` does NOT
block on the tunneled axon platform — only a device-to-host transfer forces
execution — and a D2H roundtrip costs ~75-95 ms, which swamps per-op
timings. So: every sync is a D2H reduction, and per-op costs come from the
SLOPE between a short and a long chain of dependent applications inside one
jit (the sync constant and dispatch overheads cancel).

`percentiles` / `summarize_latencies` are re-export shims: the one
implementation lives in `ncnet_tpu.telemetry.registry` (the metrics
registry's histogram snapshots use the same code), kept importable here
so existing ``from timing import percentiles`` benchmark call sites keep
working.
"""

import os
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from ncnet_tpu.telemetry.registry import (  # noqa: E402,F401
    percentiles,
    summarize_latencies,
)


def sync(out):
    """Force execution of ``out`` via a device-to-host reduction."""
    return float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))


def time_once(fn, *args):
    """Seconds for one synced call (includes the D2H constant)."""
    out = fn(*args)
    t0 = time.perf_counter()
    sync(out)
    return time.perf_counter() - t0


def time_chain(make_chain, n_lo=1, n_hi=6, iters=3):
    """Per-iteration seconds via the (n_hi - n_lo) slope.

    ``make_chain(n)`` must return ``(jitted_fn, args)`` running the op n
    times with data dependencies between repeats — beware XLA DCE: every
    repeat must contribute to the returned value (accumulate, don't
    overwrite).
    """
    results = {}
    for n in (n_lo, n_hi):
        fn, args = make_chain(n)
        fn(*args)  # compile
        time_once(fn, *args)  # warmup
        results[n] = min(time_once(fn, *args) for _ in range(iters))
    return (results[n_hi] - results[n_lo]) / (n_hi - n_lo)
