"""Training input-pipeline throughput vs the device's consumption rate.

Round-4 gap: the threaded loader (data/loader.py, the reference's
multiprocess-DataLoader role — lib/dataloader.py:154-183) was
correctness-tested but never measured against the device rate it must
sustain. The PF-Pascal step at 17.43 pairs/s (BENCH_r04) consumes 34.9
images/s (JPEG decode -> bilinear resize to 400x400 -> ImageNet normalize
-> collate); the IVD config at ~120 pairs/s needs ~240 images/s.

This benchmark writes PF-Pascal-sized JPEGs to a temp dir, streams them
through `ImagePairDataset` + `DataLoader` (batch 16, the training config),
and reports steady-state images/s per worker count. Prints one JSON line
per configuration.

Run: python benchmarks/micro_loader.py [--n_images 64] [--n_batches 24]
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def make_dataset_dir(root, n_images, seed=0):
    """PF-Pascal-like JPEGs (typical source sizes ~300-500 px sides)."""
    from PIL import Image

    rng = np.random.RandomState(seed)
    names = []
    for i in range(n_images):
        h = int(rng.randint(280, 500))
        w = int(rng.randint(280, 500))
        # low-frequency content so JPEG decode cost is realistic (pure
        # noise images decode slower than natural images encode-wise but
        # compress terribly; mix a gradient + noise)
        gy, gx = np.mgrid[0:h, 0:w]
        base = (
            127
            + 80 * np.sin(gx / 37.0 + i)
            + 40 * np.cos(gy / 23.0)
        )[..., None]
        img = base + rng.randn(h, w, 3) * 12
        name = f"img_{i:04d}.jpg"
        Image.fromarray(
            np.clip(img, 0, 255).astype(np.uint8)
        ).save(os.path.join(root, name), quality=90)
        names.append(name)
    return names


def write_pairs_csv(path, names, n_rows, seed=0):
    rng = np.random.RandomState(seed)
    with open(path, "w") as f:
        f.write("source_image,target_image,class,flip\n")
        for _ in range(n_rows):
            a, b = rng.choice(len(names), 2, replace=False)
            f.write(f"{names[a]},{names[b]},1,{rng.randint(2)}\n")


def bench(workers, batch_size, n_batches, csv_path, img_dir,
          backend="thread"):
    from ncnet_tpu.data.loader import DataLoader
    from ncnet_tpu.data.pairs import ImagePairDataset

    ds = ImagePairDataset(csv_path, img_dir)
    loader = DataLoader(
        ds, batch_size, shuffle=True, num_workers=workers, drop_last=True,
        backend=backend,
    )
    it = iter(loader)
    # warmup: fill the prefetch window + page caches (+ spawn the pool)
    for _ in range(2):
        next(it)
    t0 = time.perf_counter()
    seen = 0
    for _ in range(n_batches):
        b = next(it)
        seen += len(b["source_image"]) * 2  # two images per pair
    dt = time.perf_counter() - t0
    loader.close()
    return seen / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n_images", type=int, default=64)
    ap.add_argument("--n_batches", type=int, default=24)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--workers", type=int, nargs="*", default=[1, 2, 4, 8])
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as root:
        names = make_dataset_dir(root, args.n_images)
        csv_path = os.path.join(root, "pairs.csv")
        # enough rows that n_batches never wraps
        write_pairs_csv(
            csv_path, names, max(4000, args.n_batches * args.batch * 2)
        )
        for backend in ("thread", "process"):
            for w in args.workers:
                rate = bench(
                    w, args.batch, args.n_batches, csv_path, root, backend
                )
                print(json.dumps({
                    "metric": "train_loader_images_per_sec",
                    "backend": backend,
                    "host_cores": os.cpu_count(),
                    "workers": w,
                    "batch": args.batch,
                    "value": round(rate, 1),
                    "unit": "images/s",
                    "device_demand_pfpascal": 34.9,
                    "device_demand_ivd": 240.0,
                    "keeps_up_pfpascal": rate > 34.9,
                    "keeps_up_ivd": rate > 240.0,
                }), flush=True)


if __name__ == "__main__":
    main()
