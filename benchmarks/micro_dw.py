"""Microbenchmark: conv4d KERNEL-gradient (dw) formulations in isolation.

Round-4 question (VERDICT #1): the middle 16->16 NC layer carries 89% of
the stack FLOPs and its dw is computed by `jax.linear_transpose` of the
blocked-Toeplitz forward — a 1.79x-inflated conv3d. Candidates:

  * transpose:<impl>  — linear_transpose of that forward formulation
                        (what plain/composite impls do today; 'btl4' is
                        the incumbent, 'xla' is the true-FLOP rank-4
                        conv dw the 'tlcv' experiment used).
  * dwe / dweN        — the direct wide GEMM of `_dw_fold`: (dk, dl)
                        taps folded into x channels, (di, dj) into g
                        channels, one [kk*kl*cin, ki*kj*cout]
                        contraction (N-row scan bounds gather memory).

Usage: python benchmarks/micro_dw.py dwe4 dwe2 transpose:btl4 transpose:xla
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from timing import time_chain


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=16,
                   help="net batch (loss chunk x2 for the symmetric pass)")
    p.add_argument("--grid", type=int, default=25)
    p.add_argument("--ksize", type=int, default=5)
    p.add_argument("--cin", type=int, default=16)
    p.add_argument("--cout", type=int, default=16)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument(
        "forms", nargs="*",
        default=["transpose:btl4", "transpose:xla", "transpose:tlc",
                 "dwe8", "dwe4", "dwe2", "dwe1"],
    )
    args = p.parse_args()

    from ncnet_tpu.ops.conv4d import conv4d, _dw_direct, DW_IMPLS

    b, g, k = args.batch, args.grid, args.ksize
    cin, cout = args.cin, args.cout
    dtype = jnp.dtype(args.dtype)
    rng = np.random.RandomState(0)
    x0 = jnp.asarray(rng.randn(b, g, g, g, g, cin), dtype)
    gr = jnp.asarray(rng.randn(b, g, g, g, g, cout), dtype)
    w0 = jnp.asarray(rng.randn(k, k, k, k, cin, cout) * 1e-2, dtype)

    true_flops = 2.0 * b * g**4 * k**4 * cin * cout
    print(
        f"dw [{b},{g}^4] {cin}->{cout} k={k}^4 {dtype.name}: "
        f"{true_flops / 1e12:.3f} TFLOP true"
    )

    for form in args.forms:
        if form.startswith("transpose:"):
            impl = form.split(":", 1)[1]

            def dw_fn(x, gg, w, impl=impl):
                tw = jax.linear_transpose(
                    lambda ww: conv4d(x, ww, impl=impl), w
                )
                (dw,) = tw(gg)
                return dw.astype(jnp.float32)

        else:
            assert form in DW_IMPLS, form  # nclint: disable=bare-assert -- bench-internal invariant over its own sweep table; measurement scripts never run under -O

            def dw_fn(x, gg, w, form=form):
                return _dw_direct(form, x, gg, w.shape).astype(jnp.float32)

        def make_chain(n, dw_fn=dw_fn):
            @jax.jit
            def f(x, gg, w):
                acc = jnp.zeros(w.shape, jnp.float32)
                for t in range(n):
                    # vary g so repeats can't be CSE'd; keep a data dep
                    # (cast: cotangents must match the primal dtype)
                    bump = acc[0, 0, 0, 0, 0, 0].astype(gg.dtype)
                    acc = acc + dw_fn(x, gg + bump, w)
                return acc

            return f, (x0, gr, w0)

        try:
            dt = time_chain(make_chain)
        except Exception as e:
            print(f"  {form:16s}: FAILED {type(e).__name__}: {str(e)[:110]}")
            continue
        print(
            f"  {form:16s}: {dt * 1e3:8.2f} ms  "
            f"{true_flops / dt / 1e12:7.2f} TFLOP/s true-rate"
        )


if __name__ == "__main__":
    main()
