"""Microbench: maxpool4d strided-slice accumulation vs the 9D reshape.

The original `ops.matching.maxpool4d` built a transposed 9D blocked
intermediate (``[b, d1/k, d2/k, d3/k, d4/k, k, k, k, k]``) before one
argmax — the repo's measured layout law (bench.py header, law 1) is that
>=6D intermediates draw pathological TPU layouts (4-10x tile padding).
The shipped reformulation max-accumulates ``k^4`` strided 5D slices, the
same shape `correlation_maxpool4d` uses, with bit-identical
``(pooled, offsets)`` outputs (tie-break preserved: ascending combo
order with strict ``>`` == argmax-first).

Usage:
  python benchmarks/micro_maxpool.py [--grid 48] [--batch 4] [--k 2]
                                     [--iters 20]

Prints one JSON line per variant with ms/call; the 9D variant is kept
inline here (only) as the measured baseline.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from ncnet_tpu.ops.matching import maxpool4d


def maxpool4d_9d(corr, k_size):
    """The pre-fix blocked formulation (transposed 9D intermediate)."""
    k = int(k_size)
    b, d1, d2, d3, d4 = corr.shape
    blocks = corr.reshape(b, d1 // k, k, d2 // k, k, d3 // k, k, d4 // k, k)
    blocks = blocks.transpose(0, 1, 3, 5, 7, 2, 4, 6, 8)
    flat = blocks.reshape(b, d1 // k, d2 // k, d3 // k, d4 // k, k**4)
    pooled = jnp.max(flat, axis=-1)
    idx = jnp.argmax(flat, axis=-1).astype(jnp.int32)
    dl = idx % k
    dk = (idx // k) % k
    dj = (idx // (k * k)) % k
    di = idx // (k * k * k)
    return pooled, (di, dj, dk, dl)


def time_fn(fn, corr, iters):
    out = fn(corr)
    # force execution: D2H of a scalar reduce of every output
    float(jnp.sum(out[0]))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(corr)
    host = float(jnp.sum(out[0]) + sum(jnp.sum(d) for d in out[1]))
    dt = (time.perf_counter() - t0) / iters
    if not np.isfinite(host):
        raise RuntimeError("non-finite microbench output")
    return dt * 1e3


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--grid", type=int, default=48)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--iters", type=int, default=20)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    corr = jnp.asarray(
        rng.randn(args.batch, args.grid, args.grid, args.grid, args.grid)
        .astype(np.float32)
    )

    slices = jax.jit(lambda c: maxpool4d(c, args.k))
    blocked = jax.jit(lambda c: maxpool4d_9d(c, args.k))

    # identical outputs before timing anything
    a, da = slices(corr)
    b, db = blocked(corr)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for x, y in zip(da, db):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    for name, fn in (("strided-slices", slices), ("blocked-9d", blocked)):
        ms = time_fn(fn, corr, args.iters)
        print(
            json.dumps(
                {
                    "metric": f"maxpool4d_{name}",
                    "value": round(ms, 3),
                    "unit": "ms/call",
                    "grid": args.grid,
                    "batch": args.batch,
                    "k": args.k,
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
