"""Microbench: legacy single-file checkpoint vs per-host sharded layout.

Measures durable save and restore wall time plus on-disk bytes written by
THIS host for the same synthetic state in both layouts:

  legacy   — `save_checkpoint` / `load_checkpoint`: one msgpack blob
             (process 0 would device_get the whole tree at pod scale)
  sharded  — `save_checkpoint_sharded` / `load_latest_valid_sharded`:
             one `step_<N>/` directory, one durable .npy chunk per leaf,
             per-host manifest + atomically-renamed commit marker

On one process the sharded layout writes the SAME total bytes (every
leaf is host-local) plus manifest overhead — the win it exists for is
per-host I/O scaling (bytes/host = state/n_hosts on a pod) and the
removal of the process-0 device_get funnel, neither of which a
single-host microbench can show. What it CAN show, and what this
measures, is the price of the layout on one host: chunk-granular fsync
and digest traffic vs one big blob.

Usage:
  python benchmarks/micro_ckpt.py [--iters 3] [--leaf-kb 256] [--out DIR]

Prints one JSON line per (layout, size) with `ckpt_save_ms`,
`ckpt_restore_ms`, `ckpt_bytes_host0`.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ncnet_tpu.models.immatchnet import ImMatchNetConfig
from ncnet_tpu.train.checkpoint import (
    CheckpointData,
    load_checkpoint,
    load_latest_valid_sharded,
    save_checkpoint,
    save_checkpoint_sharded,
    sharded_dir_for,
)

CFG = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))

# leaf counts roughly shaped like the repo's states: "head" is the
# NC-head-only training state (few dozen small tensors), "trunk" adds a
# backbone's worth of leaves
SIZES = {"head": 32, "trunk": 320}


def synthetic_state(n_leaves, leaf_kb, seed=0):
    rng = np.random.RandomState(seed)
    elems = max(1, (leaf_kb * 1024) // 4)
    return {
        f"layer{i:04d}": rng.randn(elems).astype(np.float32)
        for i in range(n_leaves)
    }


def tree_bytes(root):
    """Unique bytes under ``root`` — hardlinked rotation history (legacy
    ``.step<N>`` files, sharded ``best`` pointers) counts once."""
    seen = set()
    total = 0
    for dirpath, _, names in os.walk(root):
        for n in names:
            st = os.stat(os.path.join(dirpath, n))
            key = (st.st_dev, st.st_ino)
            if key in seen:
                continue
            seen.add(key)
            total += st.st_size
    return total


def bench(fn, iters):
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--leaf-kb", type=int, default=256)
    p.add_argument("--out", default=None,
                   help="work dir (default: a fresh temp dir, removed)")
    args = p.parse_args()

    work = args.out or tempfile.mkdtemp(prefix="micro_ckpt_")
    try:
        for size_name, n_leaves in SIZES.items():
            params = synthetic_state(n_leaves, args.leaf_kb)
            data = CheckpointData(config=CFG, params=params, step=1)
            state_mb = sum(v.nbytes for v in params.values()) / 1e6

            for layout in ("legacy", "sharded"):
                base = os.path.join(work, f"{layout}_{size_name}")
                os.makedirs(base, exist_ok=True)
                path = os.path.join(base, "ck.msgpack")
                sdir = sharded_dir_for(path)

                if layout == "legacy":
                    save_ms = bench(
                        lambda: save_checkpoint(path, data, keep=1),
                        args.iters,
                    )
                    restore_ms = bench(lambda: load_checkpoint(path),
                                       args.iters)
                    nbytes = tree_bytes(base)
                else:
                    # keep=1 so re-saves measure a steady-state rotation,
                    # same as the legacy branch
                    save_ms = bench(
                        lambda: save_checkpoint_sharded(sdir, data, keep=1),
                        args.iters,
                    )
                    restore_ms = bench(
                        lambda: load_latest_valid_sharded(sdir), args.iters
                    )
                    nbytes = tree_bytes(sdir)

                for metric, value, unit in (
                    ("ckpt_save_ms", round(save_ms, 2), "ms"),
                    ("ckpt_restore_ms", round(restore_ms, 2), "ms"),
                    ("ckpt_bytes_host0", nbytes, "bytes"),
                ):
                    print(
                        json.dumps({
                            "metric": metric,
                            "value": value,
                            "unit": unit,
                            "layout": layout,
                            "size": size_name,
                            "state_mb": round(state_mb, 1),
                            "n_leaves": n_leaves,
                        }),
                        flush=True,
                    )
    finally:
        if args.out is None:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
