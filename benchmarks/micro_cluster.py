"""Microbench: steady-state overhead of cluster supervision on the step path.

The supervisor's contract (ncnet_tpu/resilience/cluster.py) is that
health supervision rides the step loop for ~free: heartbeats and peer
monitoring run on their own daemon threads, and the ONLY per-boundary
costs a training step pays are

  check          — `ClusterSupervisor.check`: one lock + dict look at the
                   monitor's declared-dead map (no filesystem I/O; the
                   monitor thread pays that);
  stop_requested — the durable stop-flag poll: a set-event short-circuit
                   or one throttled ``os.path.exists`` per ``stop_poll_s``
                   (steady state: a monotonic clock read);
  consensus      — one `agree_save_cursor` propose/ack ROUND WALL, paid
                   once per overlapped-save attempt (every
                   ``save_every_steps`` boundaries, not every step) and
                   only in async+multi-process runs.

This bench pins those with numbers against a LIVE 2-supervisor pair
(heartbeat + monitor threads running, shared tmpdir rendezvous — the
real medium), then derives the acceptance ratio:

  overhead_pct = (check + stop_requested
                  + round_wall / save_every) / step_wall * 100

which must stay < 1% of step wall (ISSUE 20; --step-wall-ms defaults to
the B=4 TPU step from benchmarks/PERF.md, override to match your box).
Prints one JSON line. Pure host bench: no jax, no device.

Usage:
  python benchmarks/micro_cluster.py [--iters 100000] [--rounds 50]
      [--step-wall-ms 35.0] [--save-every 100]
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ncnet_tpu.resilience.cluster import ClusterSupervisor  # noqa: E402
from ncnet_tpu.telemetry.registry import MetricsRegistry  # noqa: E402


def _per_op_ns(fn, iters, repeats=5):
    """min-of-repeats per-op nanoseconds (min discards scheduler noise)."""
    best = min(fn(iters) for _ in range(repeats))
    return best / iters * 1e9


def _bench_call(call):
    def run(iters):
        t0 = time.perf_counter()
        for _ in range(iters):
            call()
        return time.perf_counter() - t0

    return run


def _consensus_round_ms(s0, s1, rounds):
    """Median wall of a full 2-party propose/ack round (leader + follower
    driven concurrently, the loop's real shape)."""
    walls = []
    for r in range(rounds):
        step = 2 * (r + 1)
        out = {}
        follower = threading.Thread(
            target=lambda: out.__setitem__("f", s1.agree_save_cursor(step, False))
        )
        t0 = time.perf_counter()
        follower.start()
        out["l"] = s0.agree_save_cursor(step, False)
        follower.join()
        walls.append(time.perf_counter() - t0)
        if not (out["l"] and out["f"]):
            raise RuntimeError(f"consensus round {r} did not agree SAVE: {out}")
    walls.sort()
    return walls[len(walls) // 2] * 1e3


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=100_000)
    p.add_argument("--rounds", type=int, default=50)
    p.add_argument("--step-wall-ms", type=float, default=35.0,
                   dest="step_wall_ms",
                   help="step wall to ratio against (default: the B=4 "
                        "train step from benchmarks/PERF.md)")
    p.add_argument("--save-every", type=int, default=100, dest="save_every",
                   help="boundaries per overlapped save attempt — the "
                        "consensus round amortizes over this")
    args = p.parse_args()

    with tempfile.TemporaryDirectory(prefix="micro_cluster_") as base:
        sups = [
            ClusterSupervisor(
                base, p_, 2,
                heartbeat_interval_s=0.5, staleness_s=60.0,
                poll_interval_s=0.002, registry=MetricsRegistry(),
            ).start()
            for p_ in range(2)
        ]
        s0, s1 = sups
        time.sleep(1.0)  # both monitors see live heartbeats

        check_ns = _per_op_ns(
            _bench_call(lambda: s0.check("bench boundary")), args.iters
        )
        stop_ns = _per_op_ns(_bench_call(s0.stop_requested), args.iters)
        round_ms = _consensus_round_ms(s0, s1, args.rounds)

        for s in sups:
            s.close()
        for s in sups:
            if s.report()["straggler_threads"]:
                raise RuntimeError(f"straggler threads: {s.report()}")

    boundary_ms = (check_ns + stop_ns) / 1e6
    per_step_ms = boundary_ms + round_ms / max(args.save_every, 1)
    overhead_pct = per_step_ms / args.step_wall_ms * 100

    print(json.dumps({
        "iters": args.iters,
        "rounds": args.rounds,
        "check_ns": round(check_ns, 1),
        "stop_requested_ns": round(stop_ns, 1),
        "consensus_round_ms": round(round_ms, 3),
        "save_every": args.save_every,
        "step_wall_ms": args.step_wall_ms,
        "per_step_overhead_ms": round(per_step_ms, 6),
        # the acceptance number: must stay < 1.0
        "overhead_pct_of_step": round(overhead_pct, 4),
    }))


if __name__ == "__main__":
    main()
