"""Microbenchmark: one sparse-band NC layer (gather + GEMM + bias + ReLU)
across the three lowerings the dispatch can pick.

  xla     the production composite (ops.band.band_conv_gemm via the
          custom-VJP `_band_conv` + bias + relu) — gather and GEMM are
          separate XLA ops with an HBM round-trip between them
  pallas  the fused kernel (ncnet_tpu/kernels/band_gemm_pallas.py):
          gather + MXU GEMM + bias + ReLU in one launch. Off-TPU this
          runs in INTERPRET mode — a correctness-grade number (the
          Python interpreter of the kernel, orders of magnitude slow),
          recorded so the JSON schema is stable; the perf claim can
          only be measured on a TPU backend
  gemm4   the dense conv4d at the same geometry (conv4d_packed
          impl='gemm4', the band path's bitwise oracle at K = hB*wB) —
          the dense-equivalent work the band avoids; its analytic
          FLOPs are the DENSE count, so the gap between its and the
          band rows' useful-FLOP rates is the band's real win

Two default geometries: the PF-Pascal flagship band layer (grid 25,
5^4 kernels, K=40, 16->16 — the shape that carries ~89% of the sparse
step's FLOPs) and the IVD band layer (3^4 kernels, K=20, 16->1-ish mid
shape). JSON lines on stdout, one per (geometry, impl): ms/step via
honest slope timing (benchmarks/timing.py), analytic GFLOPs, and the
achieved useful rate.

Usage:
  JAX_PLATFORMS=cpu python benchmarks/micro_band_gemm.py           # both
  python benchmarks/micro_band_gemm.py --geometry pfpascal --grad
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from timing import time_chain

GEOMETRIES = {
    # grid, kernel, K, cin, cout — band-layer shapes of the two flagship
    # configs (middle layer: widest lanes, dominant FLOP share). TPU-
    # sized: at grid 25 the XLA path's gathered block is
    # [b, 25^2*K, k^4*cin] — GBs on a CPU host, fine in HBM.
    "pfpascal": dict(grid=25, k=5, K=40, cin=16, cout=16),
    "ivd": dict(grid=25, k=3, K=20, cin=16, cout=16),
    # CPU-proxy shapes (the off-TPU default): same structure, small
    # enough that the interpret-mode Pallas rows finish in seconds —
    # these rows VALIDATE the harness and the relative XLA-vs-dense
    # shape; absolute rates only mean something from a TPU run
    "pfpascal-proxy": dict(grid=8, k=5, K=12, cin=16, cout=16),
    "ivd-proxy": dict(grid=8, k=3, K=8, cin=16, cout=16),
}


def build_band(rng, b, grid, K, cin, k):
    """A realistic random band: top-K of a random correlation, plus the
    layer input entries and the conv pointer table."""
    from ncnet_tpu.ops.band import band_neighbor_pointers, topk_band

    scores = jnp.asarray(
        rng.randn(b, grid, grid, grid, grid).astype(np.float32)
    )
    _, indices = topk_band(scores, K)
    n = grid * grid * K
    x = jnp.asarray(rng.randn(b, n, cin).astype(np.float32))
    ptr = band_neighbor_pointers(indices, (grid, grid), (k, k, k, k))
    return x, ptr.reshape(b, n, -1)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--geometry", choices=[*GEOMETRIES, "all"], default=None,
                   help="default: the two flagship shapes on a TPU "
                        "backend, their CPU-proxy shrinks elsewhere")
    p.add_argument("--impls", default="xla,pallas,gemm4",
                   help="comma-separated subset of xla,pallas,gemm4")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--grad", action="store_true",
                   help="also time forward+backward (3x fwd FLOPs)")
    args = p.parse_args()

    from ncnet_tpu.kernels.band_gemm_pallas import band_conv_bias_relu_pallas
    from ncnet_tpu.ops.conv4d import conv4d_packed
    from ncnet_tpu.sparse.nc import _band_conv

    dtype = jnp.dtype(args.dtype)
    b = args.batch
    interpret = jax.default_backend() != "tpu"
    if args.geometry is None:
        names = (
            ["pfpascal", "ivd"] if not interpret
            else ["pfpascal-proxy", "ivd-proxy"]
        )
    elif args.geometry == "all":
        names = list(GEOMETRIES)
    else:
        names = [args.geometry]
    impls = [s for s in args.impls.split(",") if s]

    for name in names:
        geo = GEOMETRIES[name]
        grid, k, K, cin, cout = (
            geo["grid"], geo["k"], geo["K"], geo["cin"], geo["cout"]
        )
        rng = np.random.RandomState(0)
        x, ptr = build_band(rng, b, grid, K, cin, k)
        x = x.astype(dtype)
        w = jnp.asarray(
            rng.randn(k, k, k, k, cin, cout) * (cin * k**4) ** -0.5, dtype
        )
        bias = jnp.asarray(rng.randn(cout) * 0.01, dtype)
        xp_dense = jnp.asarray(
            rng.randn(b, grid, grid, grid * grid * cin).astype(np.float32),
            dtype,
        )
        band_flops = 2.0 * b * grid**2 * K * k**4 * cin * cout
        dense_flops = 2.0 * b * grid**4 * k**4 * cin * cout

        # weights/pointers ride as jit ARGUMENTS, not closure constants:
        # captured constants get constant-folded per chain length (XLA
        # warns and burns minutes at the 625-tap pointer tables)
        def layer(impl):
            if impl == "xla":
                return (
                    lambda xx, w_, b_, p_: jax.nn.relu(
                        _band_conv(xx, w_, p_) + b_.astype(dtype)
                    ),
                    x, (w, bias, ptr), band_flops,
                )
            if impl == "pallas":
                return (
                    lambda xx, w_, b_, p_: band_conv_bias_relu_pallas(
                        xx, w_, b_, p_, interpret=interpret
                    ),
                    x, (w, bias, ptr), band_flops,
                )
            if impl == "gemm4":
                return (
                    lambda xx, w_, b_: jax.nn.relu(
                        conv4d_packed(xx, w_, (grid, grid), b_, impl="gemm4")
                    ),
                    xp_dense, (w, bias), dense_flops,
                )
            raise ValueError(impl)

        for impl in impls:
            fn, x0, fargs, flops = layer(impl)
            # cout == cin at these geometries, so the layer output feeds
            # the next repeat directly (accumulate against DCE)
            def make_chain(n, fn=fn):
                @jax.jit
                def f(xx, *rest):
                    acc = xx
                    for _ in range(n):
                        acc = acc + fn(acc, *rest)
                    return acc

                return f, (x0, *fargs)

            row = {
                "bench": "band_gemm",
                "geometry": name,
                "impl": impl,
                "dtype": dtype.name,
                "batch": b,
                "grid": grid,
                "k": k,
                "K": K,
                "analytic_gflop": round(flops / 1e9, 3),
                **({"interpret": True}
                   if impl == "pallas" and interpret else {}),
            }
            try:
                dt = time_chain(make_chain)
            except Exception as e:
                row["error"] = f"{type(e).__name__}: {str(e)[:120]}"
                print(json.dumps(row), flush=True)
                continue
            row["ms"] = round(dt * 1e3, 3)
            row["gflops_per_s"] = round(flops / dt / 1e9, 2)
            print(json.dumps(row), flush=True)

            if not args.grad:
                continue

            def make_grad_chain(n, fn=fn):
                def loss(xx, *rest):
                    return jnp.sum(fn(xx, *rest).astype(jnp.float32))

                gradf = jax.grad(loss)

                @jax.jit
                def f(xx, *rest):
                    acc = xx
                    for _ in range(n):
                        acc = acc + gradf(acc, *rest).astype(dtype)
                    return acc

                return f, (x0, *fargs)

            grow = dict(row, pass_="fwd+bwd")
            grow.pop("ms", None)
            grow.pop("gflops_per_s", None)
            try:
                dt = time_chain(make_grad_chain)
            except Exception as e:
                grow["error"] = f"{type(e).__name__}: {str(e)[:120]}"
                print(json.dumps(grow), flush=True)
                continue
            grow["ms"] = round(dt * 1e3, 3)
            grow["gflops_per_s"] = round(3 * flops / dt / 1e9, 2)
            print(json.dumps(grow), flush=True)


if __name__ == "__main__":
    main()
