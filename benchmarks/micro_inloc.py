"""Steady-state InLoc dump characterization (VERDICT r3 weak #2).

Times the full per-pair match function (`eval.inloc.make_match_fn`: trunk
x2 -> fused correlation+maxpool4d -> MM -> NC -> MM -> both-direction
corr_to_matches) at the REAL InLoc shape bucket on one chip, per conv4d
impl, separating compile time from steady state. The resize-rule census
(see PERF.md) puts EVERY real InLoc image (4032x3024 queries, 1600x1200
cutouts) in the single bucket (2400, 3200) -> 150x200 feature grid ->
75x100 pooled grid at k=2, so one compile serves the whole 356x10 dump.

Eval is forward-only: impls compete on forward cost + memory only (the
training winners' dx/dw slots are irrelevant here, and l-dense 'tlc' is
hopeless at l=100 where its Toeplitz inflation is l/kl = 20x).

Usage: python benchmarks/micro_inloc.py [--impls xla scan btl4 ...]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--height", type=int, default=2400)
    p.add_argument("--width", type=int, default=3200)
    p.add_argument("--k_size", type=int, default=2)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--impls", nargs="*",
                   default=["cfs", "btl4", "scan"])
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from ncnet_tpu.eval.inloc import make_match_fn
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet

    rng = np.random.RandomState(0)
    src = jnp.asarray(rng.rand(1, args.height, args.width, 3), jnp.float32)
    tgt = jnp.asarray(rng.rand(1, args.height, args.width, 3), jnp.float32)

    for impl in args.impls:
        config = ImMatchNetConfig(
            ncons_kernel_sizes=(5, 5, 5),
            ncons_channels=(16, 16, 1),
            half_precision=True,
            relocalization_k_size=args.k_size,
            conv4d_impl=impl,
            symmetric_batch=False,
        )
        params = init_immatchnet(jax.random.PRNGKey(0), config)
        fn = jax.jit(make_match_fn(config))  # nclint: disable=recompile-hazard -- one deliberate compile per conv4d impl; compile_s is part of what this benchmark measures

        def sync(out):
            # D2H forces execution on this platform (block_until_ready
            # does not); pull one score scalar
            return float(np.asarray(out[0][4])[0, 0])

        try:
            t0 = time.perf_counter()
            sync(fn(params, src, tgt))
            compile_s = time.perf_counter() - t0
            steady = []
            for i in range(args.iters):
                # vary the input so no caching; same shapes -> no recompile
                t0 = time.perf_counter()
                sync(fn(params, src + float(i + 1), tgt))
                steady.append(time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 — record OOMs as data
            print(json.dumps({
                "impl": impl,
                "error": f"{type(e).__name__}: {str(e)[:160]}",
            }), flush=True)
            continue
        best = min(steady)
        print(json.dumps({
            "impl": impl,
            "shape": [args.height, args.width],
            "compile_s": round(compile_s, 1),
            "steady_pair_s": round(best, 2),
            "projected_356x10_dump_h": round(356 * 10 * best / 3600, 2),
        }), flush=True)


if __name__ == "__main__":
    main()
