"""Latency + analytic FLOPs of the coarse-to-fine refined forward.

Times the three serving-tier programs of the quality ladder
(serve/engine.py) at the same bucket geometry — dense, sparse band at
K, and refined (pooled coarse band at K + high-res window re-score,
ncnet_tpu.refine) — and prints each tier's analytic match FLOPs from
the same ledger the auditor cross-checks (`ops.accounting`), so the
measured step time can be read against the compute the tier actually
buys. The dense-equivalent ledger entry is the factor-1 complete-band
form, which tests/test_refine.py pins bitwise to the dense pipeline.

Run: python benchmarks/micro_refine.py [--image 128] [--factor 2]
     [--topk 8] [--radius 0] [--batch 4] [--steps 20]
Prints one JSON line per tier.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--image", type=int, default=128)
    ap.add_argument("--factor", type=int, default=2)
    ap.add_argument("--topk", type=int, default=8)
    ap.add_argument("--radius", type=int, default=0)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--cnn", default="patch16")
    args = ap.parse_args()

    import jax

    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.ops.accounting import refine_match_flops
    from ncnet_tpu.serve import make_serve_match_step

    base = ImMatchNetConfig(
        feature_extraction_cnn=args.cnn,
        ncons_kernel_sizes=(5, 5, 5),
        ncons_channels=(16, 16, 1),
    )
    grid = args.image // 16
    if grid % args.factor:
        raise SystemExit(
            f"grid {grid} does not divide by --factor {args.factor}"
        )
    params = init_immatchnet(jax.random.PRNGKey(0), base)
    feat_ch = 256 if args.cnn == "patch16" else 1024

    rng = np.random.RandomState(0)
    batch = {
        "source_image": rng.rand(
            args.batch, args.image, args.image, 3
        ).astype(np.float32),
        "target_image": rng.rand(
            args.batch, args.image, args.image, 3
        ).astype(np.float32),
    }

    def ledger(cfg):
        if cfg.refine_factor:
            return refine_match_flops(
                args.batch, cfg.ncons_kernel_sizes, cfg.ncons_channels,
                grid_hi=grid, factor=cfg.refine_factor,
                nc_topk=cfg.refine_topk, radius=cfg.refine_radius,
                feat_ch=feat_ch, image=args.image, cnn=args.cnn,
            )
        # dense / band through the SAME ledger: factor 1 is the band,
        # and the complete band is the dense-equivalent form
        k = cfg.nc_topk if cfg.nc_topk else grid * grid
        return refine_match_flops(
            args.batch, cfg.ncons_kernel_sizes, cfg.ncons_channels,
            grid_hi=grid, factor=1, nc_topk=k, feat_ch=feat_ch,
            image=args.image, cnn=args.cnn,
        )

    tiers = {
        "dense": base,
        f"band_k{args.topk}": base.replace(nc_topk=args.topk),
        f"refined_r{args.factor}_k{args.topk}": base.replace(
            refine_factor=args.factor,
            refine_topk=args.topk,
            refine_radius=args.radius,
        ),
    }
    for name, cfg in tiers.items():
        step = jax.jit(make_serve_match_step(cfg))  # nclint: disable=recompile-hazard -- one compile per tier is the point of the sweep; each config is a distinct program
        t0 = time.perf_counter()
        jax.tree_util.tree_map(np.asarray, step(params, batch))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out = step(params, batch)
        jax.tree_util.tree_map(np.asarray, out)
        dt = (time.perf_counter() - t0) / args.steps
        print(json.dumps({
            "metric": "refine_serve_step_ms",
            "tier": name,
            "value": round(dt * 1e3, 2),
            "unit": "ms",
            "pairs_per_s": round(args.batch / dt, 1),
            "analytic_match_gflops": round(ledger(cfg) / 1e9, 4),
            "grid": grid,
            "batch": args.batch,
            "compile_s": round(compile_s, 1),
        }))


if __name__ == "__main__":
    main()
