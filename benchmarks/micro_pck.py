"""Throughput of the batched PF-Pascal PCK eval step (SURVEY §2.1-25).

The reference's eval_pf_pascal.py is hard-coded to batch_size=1
(eval_pf_pascal.py:52-53) and runs one forward per pair on the V100;
ours batches and jits the whole PCK step (`eval/pf_pascal.py:24-39`:
forward -> corr_to_matches(softmax) -> bilinear point transfer -> pck).
This micro times that step on synthetic eval-shaped batches (400x400
images, 20 keypoint slots) at the paper NC config and projects the full
299-pair PF-Pascal test sweep.

Run: python benchmarks/micro_pck.py [--batch 16] [--steps 20]
Prints one JSON line.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--conv4d_impl", default=None,
                    help="default: the model config's training-tuned mix "
                         "(forward lowerings only matter here)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from ncnet_tpu.eval.pf_pascal import make_pck_step
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet

    kw = {}
    if args.conv4d_impl:
        kw["conv4d_impl"] = args.conv4d_impl
    config = ImMatchNetConfig(
        ncons_kernel_sizes=(5, 5, 5),
        ncons_channels=(16, 16, 1),
        half_precision=True,
        **kw,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), config)
    step = make_pck_step(config)

    rng = np.random.RandomState(0)
    b = args.batch
    batch = {
        "source_image": jnp.asarray(
            rng.rand(b, 400, 400, 3).astype(np.float32)
        ),
        "target_image": jnp.asarray(
            rng.rand(b, 400, 400, 3).astype(np.float32)
        ),
        "source_points": jnp.asarray(
            np.where(
                np.arange(20) < 8,
                rng.rand(b, 2, 20) * 380 + 10,
                -1.0,
            ).astype(np.float32)
        ),
        "target_points": jnp.asarray(
            np.where(
                np.arange(20) < 8,
                rng.rand(b, 2, 20) * 380 + 10,
                -1.0,
            ).astype(np.float32)
        ),
        "source_im_size": jnp.asarray(
            np.tile([400.0, 400.0, 3.0], (b, 1)).astype(np.float32)
        ),
        "target_im_size": jnp.asarray(
            np.tile([400.0, 400.0, 3.0], (b, 1)).astype(np.float32)
        ),
        "L_pck": jnp.asarray(np.full((b, 1), 224.0, np.float32)),
    }

    t0 = time.perf_counter()
    out = step(params, batch)
    np.asarray(out)  # D2H sync: block_until_ready is a no-op on axon
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(args.steps):
        out = step(params, batch)
        np.asarray(out)
    dt = (time.perf_counter() - t0) / args.steps
    pairs_per_s = b / dt
    print(json.dumps({
        "metric": "pck_eval_pairs_per_sec",
        "value": round(pairs_per_s, 2),
        "unit": "pairs/s",
        "batch": b,
        "step_ms": round(dt * 1000, 1),
        "compile_s": round(compile_s, 1),
        "projected_299_pair_test_sweep_s": round(299 / pairs_per_s, 1),
    }))


if __name__ == "__main__":
    main()
