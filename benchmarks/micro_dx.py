"""Microbenchmark: conv4d INPUT-gradient (dx) formulations in isolation.

Companion to micro_dw.py (round 4). Candidates per NC-layer shape:

  * transpose:<impl> — jax.linear_transpose of that forward formulation
                       wrt x (what a plain impl's autodiff does);
  * explicit:<impl>  — dx computed as a forward conv4d of the cotangent
                       with flipped/channel-transposed filters in that
                       lowering (what the '<fwd>/<dx>' composites do;
                       note the channel shape REVERSES: a 16->1 layer's
                       dx is a 1->16-shaped conv).

Usage: python benchmarks/micro_dx.py --cin 16 --cout 1 transpose:tlc explicit:tlc
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from timing import time_chain


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--grid", type=int, default=25)
    p.add_argument("--ksize", type=int, default=5)
    p.add_argument("--cin", type=int, default=16)
    p.add_argument("--cout", type=int, default=16)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument(
        "forms", nargs="*",
        default=["transpose:btl4", "explicit:btl4", "explicit:tlc",
                 "explicit:tf3", "explicit:cf", "transpose:tlc"],
    )
    args = p.parse_args()

    from ncnet_tpu.ops.conv4d import conv4d, _flip_transpose

    b, g, k = args.batch, args.grid, args.ksize
    cin, cout = args.cin, args.cout
    dtype = jnp.dtype(args.dtype)
    rng = np.random.RandomState(0)
    x0 = jnp.asarray(rng.randn(b, g, g, g, g, cin), dtype)
    gr = jnp.asarray(rng.randn(b, g, g, g, g, cout), dtype)
    w0 = jnp.asarray(rng.randn(k, k, k, k, cin, cout) * 1e-2, dtype)

    true_flops = 2.0 * b * g**4 * k**4 * cin * cout
    print(
        f"dx [{b},{g}^4] {cin}->{cout} k={k}^4 {dtype.name}: "
        f"{true_flops / 1e12:.3f} TFLOP true"
    )

    for form in args.forms:
        kind, impl = form.split(":", 1)
        if kind == "transpose":

            def dx_fn(gg, w, impl=impl):
                tx = jax.linear_transpose(
                    lambda xx: conv4d(xx, w, impl=impl), x0
                )
                (dx,) = tx(gg)
                return dx

        else:
            assert kind == "explicit", form  # nclint: disable=bare-assert -- bench-internal invariant over its own sweep table; measurement scripts never run under -O

            def dx_fn(gg, w, impl=impl):
                return conv4d(
                    gg, _flip_transpose(w).astype(gg.dtype), impl=impl
                )

        def make_chain(n, dx_fn=dx_fn):
            @jax.jit
            def f(gg, w):
                acc = gg
                for _ in range(n):
                    dx = dx_fn(acc, w)
                    # chain through a cheap reduction back to g's shape
                    acc = acc + jnp.mean(dx, axis=-1, keepdims=True).astype(
                        gg.dtype
                    ) * jnp.ones((cout,), dtype)
                return acc

            return f, (gr, w0)

        try:
            dt = time_chain(make_chain)
        except Exception as e:
            print(f"  {form:16s}: FAILED {type(e).__name__}: {str(e)[:110]}")
            continue
        print(
            f"  {form:16s}: {dt * 1e3:8.2f} ms  "
            f"{true_flops / dt / 1e12:7.2f} TFLOP/s true-rate"
        )


if __name__ == "__main__":
    main()
