"""Microbench: overhead of the `make_lock` concurrency-audit seam.

The audit's contract (ncnet_tpu/analysis/concurrency.py) is that the
DISABLED path is free: `make_lock` decides at construction time, so a
production serve stack with ``NCNET_LOCK_AUDIT`` unset holds exactly
the `threading.Lock` objects it held before PR 16 — the only possible
residue is the one `is_enabled()` check paid at LOCK CONSTRUCTION, not
per acquisition. This bench pins that claim with numbers:

  bare_lock     — ``with threading.Lock()`` acquire/release, the floor.
  disabled_lock — the same loop over `make_lock`'s disabled product;
                  the acceptance bar is <= 5% over bare (it is the SAME
                  type, so any delta is measurement noise).
  audited_lock  — the same loop over an enabled `OrderedLock` (held-set
                  bookkeeping + perf_counter reads + edge recording);
                  the price an NCNET_LOCK_AUDIT=1 chaos drill pays.

Prints one JSON line with per-op nanoseconds and the disabled-vs-bare
overhead percentage. Pure host bench: no jax, no device, stable on any
box.

Usage:
  python benchmarks/micro_lock_audit.py [--iters 200000]
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ncnet_tpu.analysis import concurrency  # noqa: E402


def _per_op_ns(fn, iters):
    """min-of-5 per-op nanoseconds for ``fn(iters)`` (min discards
    scheduler noise; the loop body carries the op)."""
    best = min(fn(iters) for _ in range(5))
    return best / iters * 1e9


def _per_op_ns_paired(fn_a, fn_b, iters, rounds=7):
    """min-of-rounds per-op ns for two benches measured in INTERLEAVED
    rounds (a, b, a, b, ...) so warmup and frequency drift hit both
    equally — the right shape for an A/B overhead claim."""
    best_a = min(fn_a(iters) for _ in range(2))  # warm both first
    best_b = min(fn_b(iters) for _ in range(2))
    for _ in range(rounds):
        best_a = min(best_a, fn_a(iters))
        best_b = min(best_b, fn_b(iters))
    return best_a / iters * 1e9, best_b / iters * 1e9


def _bench_with(lock):
    def run(iters):
        t0 = time.perf_counter()
        for _ in range(iters):
            with lock:
                pass
        return time.perf_counter() - t0

    return run


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=200_000)
    args = p.parse_args()
    iters = args.iters

    concurrency.clear()
    if concurrency.is_enabled():
        raise RuntimeError("lock audit unexpectedly enabled at bench start")

    bare = threading.Lock()
    disabled = concurrency.make_lock("bench.disabled")
    if type(disabled) is not type(bare):
        raise RuntimeError(
            f"disabled make_lock returned {type(disabled).__name__}, "
            "not a bare lock — the 'disabled is free' contract is broken"
        )

    bare_ns, disabled_ns = _per_op_ns_paired(
        _bench_with(bare), _bench_with(disabled), iters
    )

    concurrency.enable()
    audited = concurrency.make_lock("bench.audited")
    audited_ns = _per_op_ns(_bench_with(audited), iters)
    concurrency.clear()

    print(json.dumps({
        "iters": iters,
        "bare_lock_ns": round(bare_ns, 1),
        "disabled_make_lock_ns": round(disabled_ns, 1),
        # the acceptance number: must stay <= 5% (same type; noise only)
        "disabled_overhead_pct": round(
            (disabled_ns - bare_ns) / bare_ns * 100, 2
        ),
        "audited_lock_ns": round(audited_ns, 1),
        "audited_multiplier": round(audited_ns / bare_ns, 1),
    }))


if __name__ == "__main__":
    main()
