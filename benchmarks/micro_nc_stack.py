"""Microbenchmark: the full PF-Pascal NC stack (1->16->16->1, 5^4 kernels)
with PER-LAYER conv4d implementation choices, honest slope timing.

Motivation (round 3): the uniform-impl sweep showed every formulation caps
at ~20-30 TFLOP/s useful f+b — but the three layers have very different
shapes. The middle 16->16 layer carries 89% of the stack's true FLOPs and
offers 80-wide lanes to the true-FLOP channel-fused forms, while the 1->16
and 16->1 edge layers (11% of FLOPs) are where the 5x-inflated Toeplitz
form pays least in absolute terms. Mixing was never measured before.

Usage: python benchmarks/micro_nc_stack.py --combos tlc,tlc,tlc tlc,cf,tlc
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from timing import time_chain


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=16,
                   help="net batch (loss chunk x2 for the symmetric pass)")
    p.add_argument("--grid", type=int, default=25)
    p.add_argument("--ksize", type=int, default=5)
    p.add_argument("--channels", default="16,16,1")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--grad", action="store_true")
    p.add_argument(
        "combos", nargs="*",
        default=["tlc,tlc,tlc", "tlc,cf,tlc", "tlc,cfs,tlc", "tlc,gemm,tlc",
                 "tlc,btl,tlc", "tlc,tf3,tlc", "cf,cf,cf", "tlc,xla,tlc"],
        help="comma-separated per-layer impls",
    )
    args = p.parse_args()

    from ncnet_tpu.ops.conv4d import conv4d_packed

    b, g, k = args.batch, args.grid, args.ksize
    channels = [int(c) for c in args.channels.split(",")]
    dtype = jnp.dtype(args.dtype)
    rng = np.random.RandomState(0)
    x0 = jnp.asarray(rng.randn(b, g, g, g * g * 1), dtype)  # packed, cin=1

    ws, bs = [], []
    cin = 1
    for cout in channels:
        ws.append(
            jnp.asarray(
                rng.randn(k, k, k, k, cin, cout) * (cin * k**4) ** -0.5, dtype
            )
        )
        bs.append(jnp.asarray(rng.randn(cout) * 0.01, dtype))
        cin = cout

    layer_flops = []
    cin = 1
    for cout in channels:
        layer_flops.append(2.0 * b * g**4 * k**4 * cin * cout)
        cin = cout
    flops = sum(layer_flops)
    print(
        f"NC stack [{b},{g}^4] ch 1->{'->'.join(map(str, channels))} "
        f"k={k}^4 {dtype.name}: {flops / 1e12:.3f} TFLOP fwd "
        f"(layers: {[round(f / 1e12, 3) for f in layer_flops]})"
    )

    def stack(xp, ws_, bs_, impls):
        for w, bias, impl in zip(ws_, bs_, impls):
            xp = conv4d_packed(xp, w, (g, g), bias, impl=impl)
            xp = jax.nn.relu(xp)
        return xp

    for combo in args.combos:
        impls = combo.split(",")
        assert len(impls) == len(channels), combo  # nclint: disable=bare-assert -- bench-internal check of the user-typed --combos string; measurement scripts never run under -O

        def make_fwd_chain(n, impls=impls):
            @jax.jit
            def f(xp, ws_, bs_):
                acc = xp
                for _ in range(n):
                    # cout=1 -> packed out dim k*l*1 == packed in dim: chain
                    acc = acc + stack(acc, ws_, bs_, impls)
                return acc

            return f, (x0, ws, bs)

        try:
            dt = time_chain(make_fwd_chain)
        except Exception as e:
            print(f"  {combo:14s}: FAILED {type(e).__name__}: {str(e)[:100]}")
            continue
        print(
            f"  {combo:14s} fwd : {dt * 1e3:8.2f} ms  "
            f"{flops / dt / 1e12:7.2f} TFLOP/s useful"
        )
        if not args.grad:
            continue

        def make_grad_chain(n, impls=impls):
            def loss(xp, ws_, bs_):
                return jnp.sum(stack(xp, ws_, bs_, impls).astype(jnp.float32))

            gradf = jax.grad(loss, argnums=(0, 1))

            @jax.jit
            def f(xp, ws_, bs_):
                xx, ww = xp, ws_
                for _ in range(n):
                    dx, dw = gradf(xx, ww, bs_)
                    xx = xx + 1e-3 * dx.astype(dtype)
                    ww = [w + 1e-3 * d.astype(dtype) for w, d in zip(ww, dw)]
                return xx

            return f, (x0, ws, bs)

        try:
            dt = time_chain(make_grad_chain)
        except Exception as e:
            print(f"  {combo:14s}: grad FAILED {type(e).__name__}: {str(e)[:100]}")
            continue
        print(
            f"  {combo:14s} f+b : {dt * 1e3:8.2f} ms  "
            f"{3 * flops / dt / 1e12:7.2f} TFLOP/s useful (3x fwd FLOPs)"
        )


if __name__ == "__main__":
    main()
