"""A/B part-timings for the conv4d formulations: isolate the conv from the
epilogue, and measure XLA conv throughput vs channel widths/ranks."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax


def timeit(fn, *args, iters=5, warmup=2):
    for _ in range(warmup):
        float(jnp.sum(fn(*args)))
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        float(jnp.sum(fn(*args)))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def conv_nd(x, w, nspatial):
    letters = "jkl"[:nspatial]
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape, (f"N{letters}C", f"{letters}IO", f"N{letters}C")
    )
    return lax.conv_general_dilated(
        x, w, (1,) * nspatial, "SAME", dimension_numbers=dn,
        preferred_element_type=x.dtype,
    )


def main():
    rng = np.random.RandomState(0)
    dt = jnp.bfloat16
    cases = [
        # (name, batch, spatial, cin, cout, ksize)
        ("conv3d 400x25^3 16->16 k5", 400, (25, 25, 25), 16, 16, 5),
        ("conv3d 400x25^3 16->80 k5", 400, (25, 25, 25), 16, 80, 5),
        ("conv3d 400x25^3 16->128 k5", 400, (25, 25, 25), 16, 128, 5),
        ("conv2d 10000x25^2 16->400 k5", 10000, (25, 25), 16, 400, 5),
        ("conv2d 10000x25^2 16->512 k5", 10000, (25, 25), 16, 512, 5),
        ("conv2d 2500x50^2 16->400 k5", 2500, (50, 50), 16, 400, 5),
        ("conv1d 250000x25 16->2000 k5", 250000, (25,), 16, 2000, 5),
        # the cf formulation's inner conv (layer 2 of the PF-Pascal NC
        # stack is EXACTLY case A's work: 2 TFLOP) + lane-padding probes
        ("conv2d 10000x25^2 80->80 k5 (cf inner)", 10000, (25, 25), 80, 80, 5),
        ("conv2d 10000x25^2 80->128 k5", 10000, (25, 25), 80, 128, 5),
        ("conv2d 10000x25^2 128->128 k5", 10000, (25, 25), 128, 128, 5),
        ("conv2d 2500x25^2 80->80 k5 (chunk4 cf)", 2500, (25, 25), 80, 80, 5),
    ]
    for name, b, sp, cin, cout, k in cases:
        x = jnp.asarray(rng.randn(b, *sp, cin), dt)
        w = jnp.asarray(rng.randn(*([k] * len(sp)), cin, cout) * 0.01, dt)
        f = jax.jit(lambda x_, w_, n=len(sp): conv_nd(x_, w_, n))  # nclint: disable=recompile-hazard -- each case IS a distinct shape/program; one deliberate compile per benchmarked case
        try:
            t = timeit(f, x, w)
        except Exception as e:
            print(f"{name}: FAILED {type(e).__name__}")
            continue
        flops = 2.0 * b * np.prod(sp) * k ** len(sp) * cin * cout
        print(f"{name}: {t*1e3:8.2f} ms  {flops/t/1e12:7.2f} TFLOP/s")

    # epilogue cost of tf3: pad + 5 shifted slice adds on [16,25,25,25,25,5,16]
    y = jnp.asarray(rng.randn(16, 25, 25, 25, 25, 5, 16), dt)

    def epilogue(y_):
        yp = jnp.pad(y_, ((0, 0), (2, 2)) + ((0, 0),) * 5)
        out = None
        for di in range(5):
            t_ = yp[:, di : di + 25, :, :, :, di, :]
            out = t_ if out is None else out + t_
        return out

    t = timeit(jax.jit(epilogue), y)
    print(f"tf3 epilogue: {t*1e3:8.2f} ms")

    # giant GEMM sanity: [250k, 2000] @ [2000, 128]
    a = jnp.asarray(rng.randn(250000, 2000), dt)
    bm = jnp.asarray(rng.randn(2000, 128) * 0.01, dt)
    t = timeit(jax.jit(lambda a_, b_: a_ @ b_), a, bm)
    print(f"gemm 250k x 2000 x 128: {t*1e3:8.2f} ms  {2*250000*2000*128/t/1e12:7.2f} TFLOP/s")
    bm2 = jnp.asarray(rng.randn(2000, 512) * 0.01, dt)
    t = timeit(jax.jit(lambda a_, b_: a_ @ b_), a, bm2)
    print(f"gemm 250k x 2000 x 512: {t*1e3:8.2f} ms  {2*250000*2000*512/t/1e12:7.2f} TFLOP/s")


if __name__ == "__main__":
    main()
