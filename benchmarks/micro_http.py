"""Microbench: goodput-under-chaos load drills for the HTTP front door.

The ISSUE-17 acceptance workload, three drills over REAL sockets
(`ncnet_tpu.serve.http` on an ephemeral port, concurrent closed-loop
urllib clients):

  slo_curve    — the deadline-flush A/B. The same traffic (every request
                 carrying an X-Deadline-Ms budget) is swept across SLO
                 points against two engines: ``deadline_flush`` (the
                 micro-batcher pulls a flush forward once the tightest
                 member's remaining budget stops covering further
                 waiting, and admission stops charging max_wait) vs
                 ``fixed_wait`` (the pre-ISSUE baseline: every
                 non-full group waits the full max_wait). Goodput =
                 2xx responses per second. At SLOs below max_wait the
                 fixed arm burns the whole budget coalescing; the aware
                 arm flushes early and keeps serving — the PERF.md
                 goodput-vs-SLO curve.
  chaos_engine — concurrent clients against a single engine while
                 ``serve.worker.crash`` (prep worker dies mid-request),
                 ``serve.dispatch.hang`` (dispatch wedges past the
                 watchdog), and ``serve.request`` (per-request delay)
                 fire. Every HTTP request must get EXACTLY ONE response
                 with a typed status code, and the engine's accounting
                 identity must reconcile against the per-status HTTP
                 tallies — crash chaos may cost goodput, never
                 accounting.
  chaos_fleet  — the same contract through a ServeFleet while
                 ``serve.replica.kill`` murders a replica mid-traffic:
                 dispatched work fails typed 502, queued work requeues
                 onto the survivor and still answers 200.

The engine runs a trivial jitted program (the serving/batching/HTTP
mechanics under test are model-independent — CPU proxy discipline as
PR 3/4: mechanics transfer, absolute ms do not), so the whole drill is
CI-sized. Prints one JSON document; every drill hard-asserts its
contract before reporting numbers.

Usage:
  python benchmarks/micro_http.py [--concurrency 8] [--requests-per-slo 64]
      [--slo-ms 5,10,25,60] [--max-wait-ms 25] [--chaos-requests 120]
      [--replicas 2] [--skip-fleet]
"""

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PAYLOAD_N = 8  # one bucket: every request the same tiny vector shape


def _require(cond, *context):
    """Contract check that survives ``python -O`` (a bare assert does
    not) — every drill's acceptance gate goes through here."""
    if not cond:
        raise AssertionError(context[0] if len(context) == 1 else context)


def _post(base, body, headers, timeout=30.0):
    req = urllib.request.Request(
        base + "/v1/match", data=body, headers=headers, method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            status, raw = resp.status, resp.read()
    except urllib.error.HTTPError as exc:
        status, raw = exc.code, exc.read()
    try:
        err = json.loads(raw).get("error")
    except ValueError:
        err = None
    return status, err


def run_load(base, n_requests, concurrency, deadline_ms=None):
    """Closed-loop clients: each thread posts its share sequentially.
    Returns (list of (status, error), elapsed_s) — one entry per
    request sent, enforced."""
    headers = {"Content-Type": "application/json"}
    if deadline_ms is not None:
        headers["X-Deadline-Ms"] = str(deadline_ms)
    body = json.dumps({"payload": {"x": [1.0] * PAYLOAD_N}}).encode()
    results = []
    lock = threading.Lock()
    share = [n_requests // concurrency] * concurrency
    for i in range(n_requests % concurrency):
        share[i] += 1

    def client(count):
        mine = [_post(base, body, headers) for _ in range(count)]
        with lock:
            results.extend(mine)

    threads = [
        threading.Thread(target=client, args=(c,)) for c in share if c
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    _require(
        len(results) == n_requests,
        f"exactly-one-response violated: sent {n_requests}, "
        f"got {len(results)} responses",
    )
    return results, elapsed


def tally(results):
    out = {}
    for status, err in results:
        k = f"{status}:{err}" if err else str(status)
        out[k] = out.get(k, 0) + 1
    return out


def count(results, status, err=None):
    return sum(
        1 for s, e in results
        if s == status and (err is None or e == err)
    )


def reconcile_engine(stats, results, front):
    """The accounting identity, reconciled three ways: engine ledger ==
    client-observed statuses == the front door's per-status counters."""
    _require(
        stats["submitted"] == (
            stats["completed"] + stats["failed"] + stats["shed"]
            + stats["deadline_exceeded"]
        ),
        stats,
    )
    _require(count(results, 200) == stats["completed"], stats)
    _require(count(results, 504) == stats["deadline_exceeded"], stats)
    _require(count(results, 429, "shed") == stats["shed"], stats)
    _require(
        count(results, 429, "admission_rejected")
        == stats["admission_rejected"],
        stats,
    )
    _require(
        count(results, 500) + count(results, 502) == stats["failed"], stats
    )
    http = front.status_tally()
    for status in (200, 429, 500, 502, 504):
        _require(
            http.get(status, 0) == count(results, status),
            status, http, tally(results),
        )


def _serve(server, jnp):
    from ncnet_tpu.serve import (
        default_bucket_key,
        payload_spec,
        start_http_server,
    )

    payload = {"x": np.zeros((PAYLOAD_N,), np.float32)}
    server.warmup([(default_bucket_key(payload), payload_spec(payload))])
    front, httpd, thread = start_http_server(server)
    base = "http://%s:%d" % httpd.server_address[:2]
    return front, httpd, thread, base


def _stop(front, httpd, thread):
    front.begin_drain(timeout=10.0)
    httpd.server_close()
    thread.join(timeout=5.0)


def slo_sweep(args, jnp, make_engine):
    """The A/B: identical traffic against deadline-aware vs fixed-wait
    flush; returns {arm: [per-SLO rows]}."""
    curves = {}
    for arm, aware in (("deadline_flush", True), ("fixed_wait", False)):
        eng = make_engine(deadline_flush=aware)
        front, httpd, thread, base = _serve(eng, jnp)
        rows = []
        try:
            # prime the EWMA so admission control has an estimate — the
            # same warm traffic for both arms, excluded from the curve
            run_load(base, 2 * args.concurrency, args.concurrency)
            all_results = []
            for slo in args.slo_ms:
                results, elapsed = run_load(
                    base, args.requests_per_slo, args.concurrency,
                    deadline_ms=slo,
                )
                all_results.extend(results)
                ok = count(results, 200)
                rows.append({
                    "slo_ms": slo,
                    "sent": args.requests_per_slo,
                    "ok": ok,
                    "shed_429": count(results, 429),
                    "late_504": count(results, 504),
                    "goodput_rps": round(ok / elapsed, 1),
                    "goodput_frac": round(ok / args.requests_per_slo, 3),
                })
        finally:
            _stop(front, httpd, thread)
        stats = eng.report()
        reconcile_engine(
            stats,
            all_results + [(200, None)] * (2 * args.concurrency),
            front,
        )
        _require(stats["recompiles_after_warmup"] == 0, stats)
        _require(stats["deadline_flush"] is aware, stats)
        curves[arm] = rows
        eng.shutdown()
    return curves


def chaos_engine(args, jnp, make_engine):
    from ncnet_tpu.resilience import faultinject

    eng = make_engine(
        deadline_flush=True, degrade=True, hang_timeout=0.5,
    )
    front, httpd, thread, base = _serve(eng, jnp)
    try:
        run_load(base, args.concurrency, args.concurrency)  # prime
        faultinject.inject("serve.request", "delay", arg=0.002)
        # worker.crash fires per REQUEST (prep stage), dispatch.hang per
        # BATCH — arm the hang at a batch index the coalesced traffic is
        # sure to reach (>= chaos_requests / max_batch batches remain)
        faultinject.inject(
            "serve.worker.crash", "crash", at=args.chaos_requests // 4
        )
        faultinject.inject("serve.dispatch.hang", "delay", arg=2.0, at=5)
        results, elapsed = run_load(
            base, args.chaos_requests, args.concurrency, deadline_ms=500,
        )
    finally:
        faultinject.clear()
        _stop(front, httpd, thread)
    statuses = {s for s, _ in results}
    _require(statuses <= {200, 429, 500, 504}, tally(results))
    stats = eng.report()
    reconcile_engine(
        stats, results + [(200, None)] * args.concurrency, front
    )
    # crash chaos restarts stages; it never reaches the compiler
    _require(stats["recompiles_after_warmup"] == 0, stats)
    _require(count(results, 200) >= 1, tally(results))
    _require(
        count(results, 500) >= 1,
        "the injected crash/hang never surfaced as a typed 500",
    )
    _require(stats["stage_restarts"]["prep"] >= 1, stats)
    _require(
        stats["dispatch_hangs"] >= 1,
        "the dispatch hang never tripped the watchdog", stats,
    )
    eng.shutdown()
    return {
        "sent": args.chaos_requests,
        "elapsed_s": round(elapsed, 2),
        "statuses": tally(results),
        "stage_restarts": stats["stage_restarts"],
        "dispatch_hangs": stats["dispatch_hangs"],
        "goodput_rps": round(count(results, 200) / elapsed, 1),
    }


def chaos_fleet(args, jnp, engine_kwargs):
    from ncnet_tpu.resilience import faultinject
    from ncnet_tpu.serve import ServeFleet

    params = {"w": jnp.asarray(3.0, jnp.float32)}

    def apply(p, batch):
        return {"y": batch["x"] * p["w"]}

    fleet = ServeFleet(
        apply, params, replicas=args.replicas,
        replica_hang_timeout=1.0, **engine_kwargs,
    )
    front, httpd, thread, base = _serve(fleet, jnp)
    try:
        run_load(base, args.concurrency, args.concurrency)  # prime
        faultinject.inject(
            "serve.replica.kill", "crash", at=args.chaos_requests // 4
        )
        results, elapsed = run_load(
            base, args.chaos_requests, args.concurrency, deadline_ms=500,
        )
    finally:
        faultinject.clear()
        _stop(front, httpd, thread)
    statuses = {s for s, _ in results}
    _require(statuses <= {200, 429, 500, 502, 504}, tally(results))
    stats = fleet.report()
    # the fleet ledger: requeued-then-completed is its own bin, and the
    # client cannot tell it from a first-try 200 — that is the point
    _require(
        stats["submitted"] == (
            stats["completed"] + stats["failed"] + stats["shed"]
            + stats["deadline_exceeded"]
            + stats["requeued_then_completed"]
        ),
        stats,
    )
    ok = count(results, 200) + args.concurrency  # + the priming traffic
    _require(
        ok == stats["completed"] + stats["requeued_then_completed"], stats
    )
    _require(
        count(results, 502) + count(results, 500) == stats["failed"], stats
    )
    _require(stats["replicas_down"] >= 1, "the replica kill never landed")
    _require(count(results, 200) >= 1, "no goodput survived the kill")
    fleet.close()
    return {
        "sent": args.chaos_requests,
        "elapsed_s": round(elapsed, 2),
        "statuses": tally(results),
        "replicas_down": stats["replicas_down"],
        "requeued": stats["requeued"],
        "requeued_then_completed": stats["requeued_then_completed"],
        "goodput_rps": round(count(results, 200) / elapsed, 1),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--requests-per-slo", type=int, default=64)
    p.add_argument("--slo-ms", type=str, default="40,80,150,400",
                   help="X-Deadline-Ms sweep points for the A/B curve; "
                        "bracket the stack's end-to-end latency floor "
                        "(~20-40 ms of Python/HTTP overhead on CPU) and "
                        "the floor + max_wait the fixed arm pays")
    p.add_argument("--max-batch", type=int, default=16,
                   help="> concurrency, so the FLUSH POLICY (not the cap) "
                        "decides when every group dispatches")
    p.add_argument("--max-wait-ms", type=float, default=150.0,
                   help="the coalescing window the fixed arm always pays")
    p.add_argument("--queue-limit", type=int, default=64)
    p.add_argument("--chaos-requests", type=int, default=120)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--skip-fleet", action="store_true")
    args = p.parse_args()
    args.slo_ms = [float(s) for s in args.slo_ms.split(",")]
    _require(
        args.concurrency >= 8, "the acceptance drill demands concurrency >= 8"
    )

    if not args.skip_fleet and "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.replicas}"
            ).strip()

    import jax.numpy as jnp

    from ncnet_tpu.serve import ServeEngine

    common = dict(
        max_batch=args.max_batch,
        max_wait=args.max_wait_ms / 1e3,
        queue_limit=args.queue_limit,
        host_workers=2,
    )

    def make_engine(deadline_flush, degrade=False, hang_timeout=None):
        params = {"w": jnp.asarray(3.0, jnp.float32)}

        def apply(p, batch):
            return {"y": batch["x"] * p["w"]}

        def degraded(p, batch):
            return {"y": batch["x"] * p["w"] * 0.5}

        return ServeEngine(
            apply, params,
            degraded_apply_fn=(degraded if degrade else None),
            per_bucket_quality=degrade,
            deadline_flush=deadline_flush,
            hang_timeout=hang_timeout,
            **common,
        )

    out = {
        "config": {
            "concurrency": args.concurrency,
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            "requests_per_slo": args.requests_per_slo,
            "chaos_requests": args.chaos_requests,
        },
        "slo_curve": slo_sweep(args, jnp, make_engine),
        "chaos_engine": chaos_engine(args, jnp, make_engine),
    }
    # the tentpole claim, checked not just plotted: across the sweep the
    # deadline-aware arm never serves FEWER requests than fixed-wait
    aware_ok = sum(r["ok"] for r in out["slo_curve"]["deadline_flush"])
    fixed_ok = sum(r["ok"] for r in out["slo_curve"]["fixed_wait"])
    _require(aware_ok >= fixed_ok, (aware_ok, fixed_ok))
    if not args.skip_fleet:
        out["chaos_fleet"] = chaos_fleet(args, jnp, common)
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
