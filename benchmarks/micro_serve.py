"""Microbench: batched+pipelined serving vs sequential per-pair eval.

The workload the ISSUE's acceptance criterion names: N image-pair
requests over a small set of shape buckets, served two ways —

  sequential — the per-pair eval shape (`eval/inloc.py` before this PR):
               host decode+resize+normalize, then a jitted single-pair
               match, then a synchronous D2H readout, one request at a
               time on one thread. Host and device strictly alternate.
  serve      — `ncnet_tpu.serve.ServeEngine`: the same requests fed from
               --concurrency client threads; host prep workers overlap
               the device step of the previous micro-batch, requests
               coalesce into padded fixed-shape batches (amortizing
               per-dispatch overhead), every (bucket, batch-size)
               program AOT-compiled before the clock starts, results
               read back on a dedicated thread via async D2H.

Pairs are real PNG files on disk (written by this script) so the host
stage pays real decode work, as serving would. Prints one JSON line with
sequential_pairs_s, served_pairs_s, speedup, occupancy, and
p50/p95/p99 latency — both paths now accounted through
`ncnet_tpu.telemetry` histograms (the engine's own
``serve_request_latency_seconds`` and a baseline histogram here), the
one percentile implementation — the PERF.md round-10 numbers. CPU proxy discipline as PR 3/4: the overlap
and amortization mechanics are platform-independent; absolute ms are
not.

SLO mode (PR 10): `--deadline-ms D` submits every request with a
deadline — sheds and in-pipeline deadline drops become tallied
outcomes and the JSON line grows `goodput_pairs_s` (requests that met
their SLO per second) next to raw throughput; `--degrade K` pre-warms
the `nc_topk=K` band program as a DEGRADED variant the hysteresis
controller may flip dispatch to under queue pressure (shrink
`--queue-limit` to provoke it), reporting `degraded_batches` /
`degrade_flips`.

Fleet mode (PR 11): `--replicas N` serves the same workload through a
`ServeFleet` of N device-pinned replicas (a CPU proxy mesh of N virtual
devices is provisioned automatically); the JSON line grows
`fleet_pairs_s` and per-replica occupancy — the fleet-scaling numbers
PERF.md's round-11 entry records.

Usage:
  python benchmarks/micro_serve.py [--pairs 32] [--image-size 96]
      [--concurrency 8] [--max-batch 8] [--nc-topk 0]
      [--deadline-ms 0] [--degrade -1] [--queue-limit 64]
      [--replicas 0]
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ncnet_tpu.telemetry import (  # noqa: E402
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
)


def write_pngs(root, n_images, sizes, seed=0):
    """Synthetic PNGs across the given raw sizes; returns paths."""
    from PIL import Image

    rng = np.random.RandomState(seed)
    paths = []
    for i in range(n_images):
        h, w = sizes[i % len(sizes)]
        arr = rng.randint(0, 256, size=(h, w, 3), dtype=np.uint8)
        path = os.path.join(root, f"img_{i:04d}.png")
        Image.fromarray(arr).save(path)
        paths.append(path)
    return paths


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--pairs", type=int, default=48)
    p.add_argument("--image-size", type=int, default=64,
                   help="bucket universe max side (small: CPU proxy)")
    p.add_argument("--raw-size", type=int, default=240,
                   help="synthetic source PNG max side — sets the host "
                        "decode cost per request")
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-wait-ms", type=float, default=60.0)
    p.add_argument("--host-workers", type=int, default=2)
    p.add_argument("--nc-topk", type=int, default=0)
    p.add_argument("--queue-limit", type=int, default=64,
                   help="bounded submit queue; shrink it to raise the "
                        "queue-pressure fraction the degradation "
                        "controller sees")
    p.add_argument("--deadline-ms", type=float, default=0.0,
                   help="per-request SLO for the served pass (0 off): "
                        "sheds + in-pipeline deadline drops are tallied "
                        "instead of counted as served throughput")
    p.add_argument("--degrade", type=int, default=-1,
                   help="nc_topk of the pre-warmed DEGRADED program the "
                        "hysteresis controller may flip to under queue "
                        "pressure (-1 off); flips/degraded batches are "
                        "reported")
    p.add_argument("--replicas", type=int, default=0,
                   help="serve through a ServeFleet of N device-pinned "
                        "replicas (0: single engine). On CPU this "
                        "provisions an N-virtual-device proxy mesh; the "
                        "JSON line grows fleet_pairs_s + per-replica "
                        "occupancy")
    args = p.parse_args()

    if args.replicas > 1 and "jax" not in sys.modules:
        # CPU proxy mesh: one virtual device per replica, set before the
        # backend reads XLA_FLAGS (no-op when the flag is already there)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.replicas}"
            ).strip()

    import jax

    from ncnet_tpu.data.images import (
        load_image,
        normalize_image_np,
        resize_bilinear_np,
    )
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.serve import (
        BucketSpec,
        RequestShed,
        ServeEngine,
        make_serve_match_step,
        payload_spec,
    )

    config = ImMatchNetConfig(
        ncons_kernel_sizes=(3,),
        ncons_channels=(1,),
        nc_topk=args.nc_topk,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), config)
    apply_fn = make_serve_match_step(config)
    spec = BucketSpec(args.image_size, 1)

    def prep(pair):
        out = []
        for path in pair:
            img = load_image(path)
            h, w = spec.bucket(img.shape[0], img.shape[1])
            out.append(
                normalize_image_np(resize_bilinear_np(img, h, w)).astype(
                    np.float32
                )
            )
        return (out[0].shape[:2], out[1].shape[:2]), {
            "source_image": out[0], "target_image": out[1],
        }

    with tempfile.TemporaryDirectory() as root:
        # two raw aspect ratios -> two pair buckets in the mix
        long = args.raw_size
        short = (3 * args.raw_size) // 4
        sizes = [(short, long), (long, short)]
        images = write_pngs(root, 2 * args.pairs, sizes)
        requests = [
            (images[2 * i], images[2 * i + 1]) for i in range(args.pairs)
        ]

        # --- sequential per-pair baseline --------------------------------
        jitted = jax.jit(apply_fn)
        for pair in requests[:2]:  # compile both buckets outside the clock
            _, payload = prep(pair)
            jax.tree_util.tree_map(
                np.asarray,
                jitted(params, {k: v[None] for k, v in payload.items()}),
            )
        seq_hist = MetricsRegistry().histogram(
            "sequential_request_latency_seconds",
            "per-pair baseline latency",
            buckets=DEFAULT_LATENCY_BUCKETS,
        )
        t0 = time.perf_counter()
        for pair in requests:
            t_req = time.perf_counter()
            _, payload = prep(pair)
            out = jitted(params, {k: v[None] for k, v in payload.items()})
            jax.tree_util.tree_map(np.asarray, out)
            seq_hist.observe(time.perf_counter() - t_req)
        seq_wall = time.perf_counter() - t0

        # --- batched serving ---------------------------------------------
        slo = args.deadline_ms > 0 or args.degrade >= 0
        degraded_fn = (
            make_serve_match_step(config.replace(nc_topk=args.degrade))
            if args.degrade >= 0
            else None
        )
        deadline_s = args.deadline_ms / 1e3 if args.deadline_ms > 0 else None
        common = dict(
            max_batch=args.max_batch,
            max_wait=args.max_wait_ms / 1e3,
            host_workers=args.host_workers,
            prep_fn=prep,
            queue_limit=args.queue_limit,
            degraded_apply_fn=degraded_fn,
        )
        if args.replicas > 0:
            from ncnet_tpu.serve import ServeFleet

            server = ServeFleet(
                apply_fn, params, replicas=args.replicas, **common
            )
        else:
            server = ServeEngine(apply_fn, params, **common)
        with server as engine:
            seen = {}
            for pair in requests:
                key, payload = prep(pair)
                if key not in seen:
                    seen[key] = (key, payload_spec(payload))
            engine.warmup(seen.values())

            slots = [None] * len(requests)
            it = iter(range(len(requests)))
            lock = threading.Lock()

            def client():
                while True:
                    with lock:
                        i = next(it, None)
                    if i is None:
                        return
                    slots[i] = engine.submit(
                        requests[i], deadline_s=deadline_s
                    )

            t0 = time.perf_counter()
            threads = [
                threading.Thread(target=client)
                for _ in range(args.concurrency)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            completed = 0
            for fut in slots:
                try:
                    fut.result()
                    completed += 1
                except RequestShed:
                    # SLO mode: shed / deadline-dropped requests are a
                    # tallied outcome, not a benchmark failure
                    pass
            serve_wall = time.perf_counter() - t0
            stats = engine.report()
            if args.replicas > 0:
                # fleet: roll the per-replica engine stats up to the
                # totals the single-engine JSON line reports, keep the
                # per-replica occupancy next to them, and pool the
                # latency samples (one histogram per private registry)
                from ncnet_tpu.telemetry.registry import percentiles

                per = stats["per_replica"]
                real = sum(r["real_samples"] for r in per.values())
                padded = sum(r["padded_samples"] for r in per.values())
                stats["batches"] = sum(r["batches"] for r in per.values())
                # padded_samples counts TOTAL padded rows (engine's
                # _mean_occupancy convention: real / padded)
                stats["mean_occupancy"] = real / padded if padded else 0.0
                stats["recompiles_after_warmup"] = sum(
                    r["recompiles_after_warmup"] for r in per.values()
                )
                stats["degraded_batches"] = sum(
                    r["degraded_batches"] for r in per.values()
                )
                stats["degrade_flips"] = sum(
                    r["degrade_flips"] for r in per.values()
                )
                replica_occupancy = {
                    str(rid): round(r["mean_occupancy"], 3)
                    for rid, r in sorted(per.items())
                }
                samples = []
                for eng in engine.engines().values():
                    samples.extend(
                        eng.metrics.get(
                            "serve_request_latency_seconds"
                        ).samples
                    )
                pct = percentiles(samples)
            else:
                # the engine's OWN latency histogram is the percentile
                # source (report()'s latencies_s views the same samples)
                pct = engine.metrics.get(
                    "serve_request_latency_seconds"
                ).percentiles()

    out = {
        "pairs": args.pairs,
        "concurrency": args.concurrency,
        "max_batch": args.max_batch,
        "nc_topk": args.nc_topk,
        "sequential_pairs_s": round(args.pairs / seq_wall, 2),
        "served_pairs_s": round(args.pairs / serve_wall, 2),
        "speedup": round(seq_wall / serve_wall, 2),
        "mean_occupancy": round(stats["mean_occupancy"], 3),
        "batches": stats["batches"],
        "recompiles_after_warmup": stats["recompiles_after_warmup"],
        "serve_p50_ms": round(pct["p50"] * 1e3, 1),
        "serve_p95_ms": round(pct["p95"] * 1e3, 1),
        "serve_p99_ms": round(pct["p99"] * 1e3, 1),
        "seq_p50_ms": round(seq_hist.percentiles()["p50"] * 1e3, 1),
    }
    if args.replicas > 0:
        out.update({
            "replicas": args.replicas,
            "fleet_pairs_s": out["served_pairs_s"],
            "replica_occupancy": replica_occupancy,
            "requeued": stats["requeued"],
            "replicas_down": stats["replicas_down"],
        })
    if slo:
        # SLO mode: sheds are a tallied outcome, so report goodput
        # (requests that met their deadline) alongside raw throughput
        out.update({
            "deadline_ms": args.deadline_ms,
            "degrade_topk": args.degrade,
            "completed": completed,
            "goodput_pairs_s": round(completed / serve_wall, 2),
            "shed": stats["shed"],
            "deadline_exceeded": stats["deadline_exceeded"],
            "degraded_batches": stats["degraded_batches"],
            "degrade_flips": stats["degrade_flips"],
        })
    print(json.dumps(out))


if __name__ == "__main__":
    main()
