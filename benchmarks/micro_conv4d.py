"""Microbenchmark: conv4d implementations on the real chip, honest timing.

Two platform facts (measured, round 1-2):
  * ``jax.block_until_ready`` does not block — only a D2H transfer forces
    execution;
  * a D2H roundtrip costs ~75-95 ms on the tunneled axon platform, which
    swamps per-op timings.

So this bench times a CHAIN of N dependent applications inside one jit
with a single D2H sync, at two values of N, and reports the slope — the
sync constant and dispatch overheads cancel.

Shapes follow the PF-Pascal training config hot layer (SURVEY.md §3.1):
corr [16, 25, 25, 25, 25], NC layer 2: 5^4 kernel, 16 -> 16 channels
(~125 GFLOP/sample => 2 TFLOP/batch forward).
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from timing import time_chain


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--grid", type=int, default=25)
    p.add_argument("--ch", type=int, default=16)
    p.add_argument("--ksize", type=int, default=5)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--impls", default="xla,taps,scan,tlc,tf3,tf2")
    p.add_argument("--grad", action="store_true", help="also time fwd+bwd")
    args = p.parse_args()

    from ncnet_tpu.ops.conv4d import conv4d

    b, g, c, k = args.batch, args.grid, args.ch, args.ksize
    dtype = jnp.dtype(args.dtype)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, g, g, g, g, c), dtype)
    w = jnp.asarray(rng.randn(k, k, k, k, c, c) * 0.01, dtype)
    bias = jnp.asarray(rng.randn(c) * 0.01, dtype)

    flops = 2.0 * b * g**4 * k**4 * c * c
    print(
        f"conv4d [{b},{g}^4,{c}]->[{c}] k={k}^4 {dtype.name}: "
        f"{flops / 1e12:.3f} TFLOP fwd (slope timing)"
    )

    for impl in args.impls.split(","):

        def make_fwd_chain(n, impl=impl):
            @jax.jit
            def f(x0, w_, b_):
                y = x0
                for _ in range(n):
                    y = conv4d(y, w_, b_, impl=impl)
                    y = jnp.tanh(y)  # keep magnitudes bounded, break CSE
                return y

            return f, (x, w, bias)

        try:
            dt = time_chain(make_fwd_chain)
        except Exception as e:
            print(f"  {impl:5s}: FAILED {type(e).__name__}: {str(e)[:120]}")
            continue
        print(
            f"  {impl:5s} fwd : {dt * 1e3:8.2f} ms  "
            f"{flops / dt / 1e12:7.2f} TFLOP/s"
        )
        if not args.grad:
            continue

        def make_grad_chain(n, impl=impl):
            def loss(x_, w_, b_):
                return jnp.sum(
                    jnp.tanh(conv4d(x_, w_, b_, impl=impl)).astype(jnp.float32)
                )

            gradf = jax.grad(loss, argnums=(0, 1, 2))

            @jax.jit
            def f(x0, w_, b_):
                xx, ww, bb = x0, w_, b_
                for _ in range(n):
                    dx, dw, db = gradf(xx, ww, bb)
                    xx = xx + 1e-3 * dx.astype(dtype)
                    ww = ww + 1e-3 * dw.astype(dtype)
                    bb = bb + 1e-3 * db.astype(dtype)
                return ww

            return f, (x, w, bias)

        try:
            dt = time_chain(make_grad_chain)
        except Exception as e:
            print(f"  {impl:5s}: grad FAILED {type(e).__name__}: {str(e)[:120]}")
            continue
        print(
            f"  {impl:5s} f+b : {dt * 1e3:8.2f} ms  "
            f"{3 * flops / dt / 1e12:7.2f} TFLOP/s (3x fwd FLOPs)"
        )


if __name__ == "__main__":
    main()
