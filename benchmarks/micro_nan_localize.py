"""Localize the first non-finite stage of the 'Not shipped' NaN config.

PERF.md records a config that was stepped around, not understood:
sym-sequential + single-chunk-16 + no-remat measured 18.6 pairs/s over 4
steps but NaN'd the bench's 30-step random-init training on iid-noise
inputs (loss wanders 0 -> 0.06 -> NaN while the chunk-8 trajectory stays
at +-3e-5 — identical math, different float order). The bench's finite-
loss assertion caught it but said nothing about WHERE.

This harness reproduces that config's TOPOLOGY (symmetric_batch=False,
loss_chunk == batch with loss_chunk_remat=False — which weak_loss runs as
the plain unchunked no-remat path, exactly what `bench.py --sym_seq
--loss_chunk 16` compiles at batch 16 — bf16, the shipped per-layer impl
mix, random init, one fixed iid-noise batch) with the numerical sanitizer
enabled, so the run ends with a per-stage finiteness table and the name
of the first non-finite stage in dataflow order instead of a bare assert.

Scale knobs (--image/--batch) exist because the original shape (400x400,
batch 16) is TPU-sized; on the CPU test platform run e.g.

    python benchmarks/micro_nan_localize.py --image 128 --batch 8 \
        --steps 120 --lr 5e-4

and escalate --lr when the divergence needs a push at small scale (the
bf16-ordering-noise amplifier is weaker at 8^4 correlation cells than at
25^4; record the lr used). Prints one JSON line with the outcome.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--image", type=int, default=128)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--steps", type=int, default=120)
    p.add_argument("--lr", type=float, default=5e-4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log_every", type=int, default=5)
    p.add_argument("--conv4d_impl", default="tlc//btl,btl4,tlc/tlc/tf3",
                   help="the shipped PF-Pascal per-layer mix (PERF.md)")
    p.add_argument("--chunk8_control", action="store_true",
                   help="run the SHIPPED chunk-8 + symmetric-batch config "
                        "instead (the trajectory that stays finite) as an "
                        "A/B control at the same scale")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from ncnet_tpu.analysis import sanitizer
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.train.step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    sanitizer.enable()

    if args.chunk8_control:
        chunk, sym_batch = min(8, args.batch // 2 or 1), True
    else:
        # the NaN config: a single chunk covering the batch, no remat,
        # sequential symmetric passes
        chunk, sym_batch = args.batch, False
    config = ImMatchNetConfig(
        ncons_kernel_sizes=(5, 5, 5),
        ncons_channels=(16, 16, 1),
        half_precision=True,
        conv4d_impl=args.conv4d_impl,
        nc_remat=False,
        loss_chunk=chunk,
        loss_chunk_remat=False,
        symmetric_batch=sym_batch,
    )
    params = init_immatchnet(jax.random.PRNGKey(args.seed), config)
    optimizer = make_optimizer(args.lr)
    state = create_train_state(params, optimizer)
    step = make_train_step(config, optimizer, donate=False)

    rng = np.random.RandomState(args.seed)
    batch = {
        "source_image": jnp.asarray(
            rng.randn(args.batch, args.image, args.image, 3).astype(np.float32)
        ),
        "target_image": jnp.asarray(
            rng.randn(args.batch, args.image, args.image, 3).astype(np.float32)
        ),
    }

    t0 = time.perf_counter()
    outcome = {"nan_step": None, "first_nonfinite": None,
               "losses_head": [], "loss_last": None}
    for i in range(args.steps):
        state, loss = step(state, batch)
        loss_host = float(loss)
        if i < 10 or (i + 1) % args.log_every == 0:
            print(f"step {i + 1}: loss {loss_host:.6g} "
                  f"({time.perf_counter() - t0:.0f}s)", flush=True)
        if len(outcome["losses_head"]) < 10:
            outcome["losses_head"].append(loss_host)
        outcome["loss_last"] = loss_host
        if not np.isfinite(loss_host):
            outcome["nan_step"] = i + 1
            fnf = sanitizer.first_nonfinite()
            outcome["first_nonfinite"] = (
                {"stage": fnf[0], **fnf[1]} if fnf else None
            )
            break

    print(sanitizer.report_text(), flush=True)
    outcome["stage_summary"] = sanitizer.summary()
    outcome["config"] = {
        "image": args.image, "batch": args.batch, "lr": args.lr,
        "loss_chunk": chunk, "symmetric_batch": sym_batch,
        "impl": args.conv4d_impl, "steps_run": min(args.steps, i + 1),
    }
    print(json.dumps(outcome))


if __name__ == "__main__":
    main()
