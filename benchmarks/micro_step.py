"""Bisect the training step: slope-time its pieces on the real chip.

Pieces: trunk features, one chunk's match pipeline fwd, whole loss fwd,
whole train step (f+b). Slope timing (chained repeats, one D2H) cancels
the ~80 ms sync latency of this platform.
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from timing import sync as _sync
from timing import time_chain


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--conv4d_impl", default="cf")
    p.add_argument("--loss_chunk", type=int, default=4)
    p.add_argument("--batch", type=int, default=16)
    args = p.parse_args()

    from ncnet_tpu.models.immatchnet import (
        ImMatchNetConfig,
        extract_features,
        init_immatchnet,
        match_pipeline,
    )
    from ncnet_tpu.train.loss import weak_loss
    from ncnet_tpu.train.step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    config = ImMatchNetConfig(
        ncons_kernel_sizes=(5, 5, 5),
        ncons_channels=(16, 16, 1),
        half_precision=True,
        conv4d_impl=args.conv4d_impl,
        loss_chunk=args.loss_chunk,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), config)
    rng = np.random.RandomState(0)
    batch = {
        "source_image": jnp.asarray(
            rng.randn(args.batch, 400, 400, 3).astype(np.float32)
        ),
        "target_image": jnp.asarray(
            rng.randn(args.batch, 400, 400, 3).astype(np.float32)
        ),
    }

    # 1) trunk features, 2*batch images
    imgs = jnp.concatenate([batch["source_image"], batch["target_image"]])

    def mk_feat(n):
        @jax.jit
        def f(p, x):
            # accumulate so no iteration is dead code (an overwritten
            # `out` lets XLA DCE all but the last repeat)
            acc = 0.0
            y = x
            for _ in range(n):
                feat = extract_features(p, config, y)
                acc = acc + jnp.sum(feat.astype(jnp.float32))
                y = y + 1e-6
            return acc

        return f, (params, imgs)

    print(f"trunk fwd x{2 * args.batch} imgs: {time_chain(mk_feat) * 1e3:8.1f} ms")

    # 2) one chunk's pipeline fwd (pos only), chunk samples
    c = args.loss_chunk or args.batch
    extract = jax.jit(lambda p, x: extract_features(p, config, x))
    feat = extract(params, imgs[: 2 * c])
    fa, fb = feat[:c], feat[c : 2 * c]

    def mk_pipe(n):
        @jax.jit
        def f(nc, fa_, fb_):
            acc = 0.0
            x = fa_
            for _ in range(n):
                out = match_pipeline(nc, config, x, fb_)
                acc = acc + jnp.sum(out.astype(jnp.float32))
                x = x + 1e-6
            return acc

        return f, (params["neigh_consensus"], fa, fb)

    print(f"pipeline fwd (chunk {c}):     {time_chain(mk_pipe) * 1e3:8.1f} ms")

    # 3) whole loss fwd
    def mk_loss(n):
        @jax.jit
        def f(p, b):
            out = 0.0
            bb = b
            for _ in range(n):
                out = out + weak_loss(p, config, bb)
                bb = {k: v + 1e-6 for k, v in bb.items()}
            return out

        return f, (params, batch)

    print(f"loss fwd (batch {args.batch}):         {time_chain(mk_loss) * 1e3:8.1f} ms")

    # 4) full train step
    optimizer = make_optimizer()
    state = create_train_state(params, optimizer)
    step = make_train_step(config, optimizer, donate=False)
    state, loss = step(state, batch)
    _sync(loss)
    ts = {}
    for n in (1, 5):
        t0 = time.perf_counter()
        s = state
        for _ in range(n):
            s, loss = step(s, batch)
        _sync(loss)
        ts[n] = time.perf_counter() - t0
    print(f"train step (f+b):           {(ts[5] - ts[1]) / 4 * 1e3:8.1f} ms")


if __name__ == "__main__":
    main()
