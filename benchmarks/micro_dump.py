"""Host-pipeline wall clock of the InLoc dump at real image sizes.

Reproduces the round-4 "mini dump" measurement (PERF.md "Host pipeline")
against the current `dump_matches`: uint8 H2D + on-device normalize,
decode-prefetch thread, 4-deep device pre-transfer, single stacked D2H
per direction, and the round-5 atomic+async `.mat` writer. Synthetic JPEGs at the real InLoc sizes
(queries 4032x3024, panos 1600x1200 — both land in the single (2400,
3200) resize bucket), randomized NC weights; the timing is host-pipeline
bound, not accuracy-relevant.

Run: python benchmarks/micro_dump.py [--queries 6] [--panos 2]
Prints one JSON line (steady-state s/pair, excluding the first query,
whose pairs pay the XLA compiles).
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_fixture(root, n_queries, n_panos, seed=0):
    from PIL import Image
    from scipy.io import savemat

    rng = np.random.RandomState(seed)
    qdir = os.path.join(root, "query")
    pdir = os.path.join(root, "pano")
    os.makedirs(qdir)
    os.makedirs(pdir)

    def save_jpg(path, h, w):
        gy, gx = np.mgrid[0:h, 0:w]
        base = (127 + 70 * np.sin(gx / 41.0) + 30 * np.cos(gy / 29.0))[
            ..., None
        ]
        img = np.clip(base + rng.randn(h, w, 3) * 10, 0, 255).astype(
            np.uint8
        )
        Image.fromarray(img).save(path, quality=85)

    pano_names = []
    for i in range(n_panos * 2):
        name = f"p{i}.jpg"
        save_jpg(os.path.join(pdir, name), 1200, 1600)
        pano_names.append(name)

    dt = np.dtype([("queryname", object), ("topN", object)])
    entries = np.zeros((1, n_queries), dt)
    for q in range(n_queries):
        qname = f"q{q}.jpg"
        save_jpg(os.path.join(qdir, qname), 3024, 4032)
        top = rng.choice(pano_names, n_panos, replace=False)
        entries[0, q] = (
            np.array([qname], object),
            np.array([[t] for t in top], object),
        )
    savemat(os.path.join(root, "shortlist.mat"), {"ImgList": entries})
    return qdir, pdir, os.path.join(root, "shortlist.mat")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--queries", type=int, default=6)
    ap.add_argument("--panos", type=int, default=2)
    ap.add_argument("--image_size", type=int, default=3200)
    ap.add_argument("--conv4d_impl", default="cfs")
    ap.add_argument("--host_fp32", action="store_true",
                    help="time the exact host-normalize path instead of "
                         "the uint8 device-preprocess default of the CLI")
    ap.add_argument("--no_device_resize", action="store_true",
                    help="disable the on-device pano upscale (ship the "
                         "host-resized 23 MB bucket image instead of the "
                         "5.8 MB original)")
    args = ap.parse_args()
    device_resize = not (args.host_fp32 or args.no_device_resize)

    import jax

    from ncnet_tpu.eval.inloc import dump_matches
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet

    config = ImMatchNetConfig(
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(16, 1),
        half_precision=True,
        relocalization_k_size=2,
        conv4d_impl=args.conv4d_impl,
        symmetric_batch=False,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), config)

    with tempfile.TemporaryDirectory() as root:
        qdir, pdir, shortlist = make_fixture(
            root, args.queries, args.panos
        )
        out_dir = os.path.join(root, "matches")

        times = []
        t_all = time.perf_counter()

        # warm + steady in one pass: time each query by intercepting the
        # consume loop's per-query progress line through a print hook
        t_prev = [time.perf_counter()]

        real_print = print

        def timed_dump():
            dump_matches(
                params,
                config,
                shortlist_path=shortlist,
                query_path=qdir,
                pano_path=pdir,
                output_dir=out_dir,
                image_size=args.image_size,
                n_queries=args.queries,
                n_panos=args.panos,
                verbose=True,
                device_preprocess=not args.host_fp32,
                device_resize=device_resize,
            )

        import builtins

        def hook(*a, **k):
            # only the consume loop's "query N/M -> path" lines mark a
            # query boundary; any other print passes through untimed
            if a and isinstance(a[0], str) and a[0].startswith("query "):
                now = time.perf_counter()
                times.append(now - t_prev[0])
                t_prev[0] = now
            real_print(*a, **k)

        builtins.print, saved = hook, builtins.print
        try:
            timed_dump()
        finally:
            builtins.print = saved

        total = time.perf_counter() - t_all
        # first query pays the compiles; steady state = the rest
        steady = times[1:]
        s_per_pair = float(np.mean(steady)) / args.panos if steady else None
        print(json.dumps({
            "metric": "inloc_dump_s_per_pair_steady",
            "value": round(s_per_pair, 3) if s_per_pair else None,
            "unit": "s",
            "first_query_s": round(times[0], 1) if times else None,
            "queries": args.queries,
            "panos_per_query": args.panos,
            "total_s": round(total, 1),
            "device_preprocess": not args.host_fp32,
            "device_resize": device_resize,
            "projected_356x10_h": round(
                356 * 10 * s_per_pair / 3600.0, 2
            ) if s_per_pair else None,
        }))


if __name__ == "__main__":
    main()
