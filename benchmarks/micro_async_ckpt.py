"""Microbench: sync vs async checkpointing — what does the step loop pay?

Simulates the training loop's mid-epoch cursor saves at the micro_ckpt
geometry: a donating jitted update advances a synthetic state; every
``--save-every-steps`` steps the state is durably checkpointed, either

  sync   — the loop blocks for the full save (D2H funnel + serialization
           + temp/fsync/rename), exactly the pre-async behaviour;
  async  — `resilience.async_ckpt.AsyncCheckpointer` overlap: the loop
           pays only the handoff (plus the donation-proof device-side
           copy dispatch) and keeps stepping while the writer thread
           saves; the epoch ends on a `flush()` barrier.

Reported per (mode, layout, size):

  ackpt_stall_ms_p50/p95  — per-save STEP-THREAD stall (the submit call:
                            for sync that is the whole save wall; for
                            async the handoff + snapshot dispatch)
  ackpt_epoch_wall_ms     — end-to-end loop wall incl. the final flush
  ackpt_coalesced         — overlapped saves superseded by a newer one

plus a derived ``ackpt_stall_vs_sync_save`` ratio per (layout, size):
async p50 stall / sync p50 save wall — the ISSUE-19 acceptance number
(<= 0.2 on the sharded layout).

The update is jitted with ``donate_argnums`` so the async arm exercises
the real hazard: raw refs handed to the writer would be invalidated by
the next step; `device_snapshot` copies are what make the overlap safe.

Usage:
  JAX_PLATFORMS=cpu python benchmarks/micro_async_ckpt.py \
      [--steps 20] [--save-every-steps 5] [--leaf-kb 256] [--out DIR]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ncnet_tpu.models.immatchnet import ImMatchNetConfig
from ncnet_tpu.resilience.async_ckpt import AsyncCheckpointer, device_snapshot
from ncnet_tpu.train.checkpoint import (
    CheckpointData,
    materialize_on_host,
    save_checkpoint,
    save_checkpoint_sharded,
    sharded_dir_for,
)

CFG = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))

# same leaf-count geometry as micro_ckpt.py so rounds stay comparable
SIZES = {"head": 32, "trunk": 320}


def synthetic_state(n_leaves, leaf_kb, seed=0):
    rng = np.random.RandomState(seed)
    elems = max(1, (leaf_kb * 1024) // 4)
    return {
        f"layer{i:04d}": rng.randn(elems).astype(np.float32)
        for i in range(n_leaves)
    }


def run_epoch(async_mode, layout, base, host_params, steps, save_every):
    import jax
    import jax.numpy as jnp

    # donating update: the buffers behind a handed-off snapshot die when
    # the NEXT step dispatches — the hazard device_snapshot exists for
    update = jax.jit(
        lambda t: jax.tree.map(lambda x: x + 1.0, t), donate_argnums=(0,)
    )
    state = jax.tree.map(jnp.asarray, host_params)
    path = os.path.join(base, "ck.msgpack")
    sdir = sharded_dir_for(path)
    ackpt = AsyncCheckpointer(async_mode=async_mode)
    stalls = []
    t_epoch = time.perf_counter()
    for s in range(steps):
        state = update(state)
        if (s + 1) % save_every == 0:
            t0 = time.perf_counter()
            params_ref = device_snapshot(state) if async_mode else state
            data = CheckpointData(config=CFG, params=params_ref, step=s + 1)
            if layout == "sharded":
                ackpt.submit(
                    data,
                    lambda d: save_checkpoint_sharded(sdir, d, keep=1),
                    step=s + 1,
                    wait=not async_mode,
                )
            else:
                ackpt.submit(
                    data,
                    lambda d: save_checkpoint(path, d, keep=1),
                    prepare=materialize_on_host,
                    step=s + 1,
                    wait=not async_mode,
                )
            stalls.append(time.perf_counter() - t0)
    ackpt.flush()
    epoch_ms = (time.perf_counter() - t_epoch) * 1e3
    rep = ackpt.report()
    ackpt.close()
    return np.asarray(stalls) * 1e3, epoch_ms, rep


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--save-every-steps", type=int, default=5,
                   dest="save_every_steps")
    p.add_argument("--leaf-kb", type=int, default=256)
    p.add_argument("--out", default=None,
                   help="work dir (default: a fresh temp dir, removed)")
    args = p.parse_args()

    work = args.out or tempfile.mkdtemp(prefix="micro_async_ckpt_")
    try:
        for size_name, n_leaves in SIZES.items():
            host_params = synthetic_state(n_leaves, args.leaf_kb)
            state_mb = sum(v.nbytes for v in host_params.values()) / 1e6
            for layout in ("legacy", "sharded"):
                sync_p50 = None
                for mode in ("sync", "async"):
                    base = os.path.join(work, f"{mode}_{layout}_{size_name}")
                    os.makedirs(base, exist_ok=True)
                    stalls, epoch_ms, rep = run_epoch(
                        mode == "async", layout, base, host_params,
                        args.steps, args.save_every_steps,
                    )
                    p50 = float(np.percentile(stalls, 50))
                    p95 = float(np.percentile(stalls, 95))
                    if mode == "sync":
                        sync_p50 = p50
                    tags = {
                        "mode": mode, "layout": layout, "size": size_name,
                        "state_mb": round(state_mb, 1),
                        "saves": len(stalls),
                    }
                    for metric, value, unit in (
                        ("ackpt_stall_ms_p50", round(p50, 2), "ms"),
                        ("ackpt_stall_ms_p95", round(p95, 2), "ms"),
                        ("ackpt_epoch_wall_ms", round(epoch_ms, 1), "ms"),
                        ("ackpt_coalesced", rep["coalesced_total"], "saves"),
                    ):
                        print(
                            json.dumps({
                                "metric": metric, "value": value,
                                "unit": unit, **tags,
                            }),
                            flush=True,
                        )
                    if mode == "async":
                        print(
                            json.dumps({
                                "metric": "ackpt_stall_vs_sync_save",
                                "value": round(p50 / max(sync_p50, 1e-9), 4),
                                "unit": "ratio",
                                "layout": layout, "size": size_name,
                                "state_mb": round(state_mb, 1),
                            }),
                            flush=True,
                        )
    finally:
        if args.out is None:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
