#!/bin/bash
# Dataset bootstrap for ncnet_tpu. Pair-list CSVs are vendored in-repo;
# images must be fetched (no network egress in the build environment, so
# run this wherever you have connectivity).
#
# Sources match the reference repo's download scripts
# (reference datasets/pf-pascal/download.sh, datasets/ivd/download.sh,
# datasets/inloc/download.sh).
set -euo pipefail
cd "$(dirname "$0")"

case "${1:-all}" in
  pf-pascal|all)
    (
      cd pf-pascal
      wget -nc https://www.di.ens.fr/willow/research/proposalflow/dataset/PF-dataset-PASCAL.zip
      unzip -n PF-dataset-PASCAL.zip 'PF-dataset-PASCAL/JPEGImages/*'
    )
    ;;&
  ivd|all)
    (
      cd ivd
      # one directory per venue, then 3708 Google-hosted images
      while read -r path _; do mkdir -p "$path"; done < dirs.txt
      <urls.txt xargs -n2 -P8 wget -nc -O
    )
    ;;&
  inloc|all)
    (
      mkdir -p inloc
      cd inloc
      wget -nc http://www.ok.sc.e.titech.ac.jp/INLOC/materials/cutouts.tar.gz
      wget -nc http://www.ok.sc.e.titech.ac.jp/INLOC/materials/iphone7.tar.gz
      # densePE_top100_shortlist_cvpr18.mat (the query->pano shortlist) is
      # distributed with the InLoc_demo project; place it in this directory.
    )
    ;;&
esac
