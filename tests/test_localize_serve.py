"""`PoseRequest` through the serve engine (PR 13): warmup compiles the
pose bucket family in both hypothesis rungs, the hysteresis controller
degrades ``n_hypotheses`` exactly like it degrades ``nc_topk`` — the
served result is BITWISE the degraded program's own output, with ZERO
recompiles across the flip — and a ``serve.request`` fault through the
pose prep path fails typed while the accounting ledger stays exact."""

import numpy as np
import pytest

import jax

from ncnet_tpu.localize import (
    PoseRequest,
    make_pose_apply,
    make_pose_engine,
    pose_bucket_specs,
    prep_pose_request,
)
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.serve import HysteresisController, StageFailure

PRIMARY, DEGRADED = 16, 8  # small rungs: two cheap warmup traces


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.clear()
    yield
    faultinject.clear()


def _forced_controller():
    # every pressure reading (>= 0) is "overload": flips on the dispatch
    # loop's first observation (the test_serve_resilience idiom)
    return HysteresisController(high=0.0, low=-1.0, up_count=1)


def _pose_request(seed=3, n=100, inlier_ratio=0.7):
    rng = np.random.RandomState(seed)
    q, _ = np.linalg.qr(rng.randn(3, 3))
    if np.linalg.det(q) < 0:
        q[:, 0] *= -1
    t = rng.randn(3)
    x = rng.randn(n, 3) * 4.0 + np.array([0, 0, 8.0])
    xc = x @ q.T + t
    rays = xc / np.linalg.norm(xc, axis=1, keepdims=True)
    n_out = int(n * (1.0 - inlier_ratio))
    out_idx = rng.permutation(n)[:n_out]
    rand = rng.randn(n_out, 3)
    rays[out_idx] = rand / np.linalg.norm(rand, axis=1, keepdims=True)
    return PoseRequest(
        rays.astype(np.float32), x.astype(np.float32), seed=seed
    )


def _invariant(stats):
    assert stats["submitted"] == (
        stats["completed"] + stats["failed"] + stats["shed"]
        + stats["deadline_exceeded"]
    )


def test_pose_degradation_flip_zero_recompiles():
    """Under forced overload the engine serves the DEGRADED-rung pose
    program, bitwise that program's own output, without compiling
    anything after warmup — the hypothesis count degrades exactly like
    nc_topk does on the match path."""
    req = _pose_request()
    _, payload = prep_pose_request(req)
    batch = {k: np.asarray(v)[None] for k, v in payload.items()}
    expected = jax.jit(make_pose_apply(DEGRADED))({}, batch)

    with make_pose_engine(
        n_hypotheses=PRIMARY, degraded_hypotheses=DEGRADED,
        max_batch=1, degrade_controller=_forced_controller(),
    ) as eng:
        eng.warmup(pose_bucket_specs((128,)))
        warm = eng.compile_count
        assert warm == 2  # both rungs pre-warmed at bs 1
        got = eng.submit(req).result(timeout=60)
        assert eng.compile_count == warm  # the flip compiled NOTHING
        stats = eng.report()
    assert bool(got["found"])
    for k in ("P", "inliers", "n_inliers", "found", "best_hyp"):
        np.testing.assert_array_equal(
            np.asarray(got[k]), np.asarray(expected[k])[0]
        )
    assert stats["degraded_mode"] is True
    assert stats["degraded_batches"] == 1
    assert stats["degrade_flips"] >= 1
    assert stats["recompiles_after_warmup"] == 0
    _invariant(stats)


def test_pose_primary_rung_without_pressure():
    req = _pose_request(seed=4)
    _, payload = prep_pose_request(req)
    batch = {k: np.asarray(v)[None] for k, v in payload.items()}
    expected = jax.jit(make_pose_apply(PRIMARY))({}, batch)

    with make_pose_engine(
        n_hypotheses=PRIMARY, degraded_hypotheses=DEGRADED, max_batch=1,
    ) as eng:  # default controller: idle traffic never reaches high water
        eng.warmup(pose_bucket_specs((128,)))
        got = eng.submit(req).result(timeout=60)
        stats = eng.report()
    for k in ("P", "n_inliers", "best_hyp"):
        np.testing.assert_array_equal(
            np.asarray(got[k]), np.asarray(expected[k])[0]
        )
    assert stats["degraded_batches"] == 0
    assert stats["recompiles_after_warmup"] == 0
    _invariant(stats)


def test_pose_request_fault_fails_typed_ledger_exact():
    """A ``serve.request`` crash injected into the pose prep path: the
    victim fails ALONE with the typed fault, the next request is served
    from the intact warm cache, and every accepted request lands in
    exactly one outcome bin. A killed prep WORKER is the StageFailure
    case: only the in-flight pose request fails, the stage restarts."""
    faultinject.inject("serve.request", "crash", at=1)
    with make_pose_engine(
        n_hypotheses=PRIMARY, degraded_hypotheses=DEGRADED,
        max_batch=1, host_workers=1,
    ) as eng:
        eng.warmup(pose_bucket_specs((128,)))
        warm = eng.compile_count
        victim = eng.submit(_pose_request(seed=5))
        with pytest.raises(faultinject.InjectedFault):
            victim.result(timeout=60)
        ok = eng.submit(_pose_request(seed=6)).result(timeout=60)
        assert eng.compile_count == warm
        stats = eng.report()
    assert bool(ok["found"])
    assert stats["failed"] == 1 and stats["completed"] == 1
    assert stats["recompiles_after_warmup"] == 0
    _invariant(stats)

    faultinject.clear()
    faultinject.inject("serve.worker.crash", "crash", at=1)
    with make_pose_engine(
        n_hypotheses=PRIMARY, degraded_hypotheses=DEGRADED,
        max_batch=1, host_workers=1,
    ) as eng:
        eng.warmup(pose_bucket_specs((128,)))
        warm = eng.compile_count
        victim = eng.submit(_pose_request(seed=7))
        with pytest.raises(StageFailure) as ei:
            victim.result(timeout=60)
        assert ei.value.stage == "prep" and not ei.value.hang
        ok = eng.submit(_pose_request(seed=8)).result(timeout=60)
        assert eng.compile_count == warm
        stats = eng.report()
    assert bool(ok["found"])
    assert stats["stage_restarts"]["prep"] == 1
    assert stats["failed"] == 1 and stats["completed"] == 1
    assert stats["recompiles_after_warmup"] == 0
    _invariant(stats)


def test_pose_engine_rejects_inverted_rungs():
    with pytest.raises(ValueError, match="below primary"):
        make_pose_engine(n_hypotheses=8, degraded_hypotheses=8)
