import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.ops.conv4d import conv4d


def run_conv4d(x, w, bias, impl):
    if impl == "pallas":
        # the Pallas kernel is interpret-mode-only (Mosaic cannot lower its
        # in-kernel reshape); force the interpreter so the parametrization
        # also passes on TPU hosts
        return conv4d(x, w, bias, impl=impl, interpret=True)
    return conv4d(x, w, bias, impl=impl)


def conv4d_bruteforce(x, w, bias=None):
    """Direct shift-and-multiply 4D SAME convolution oracle."""
    ki, kj, kk, kl, cin, cout = w.shape
    b, di, dj, dk, dl, _ = x.shape
    pads = [(k // 2, k // 2) for k in (ki, kj, kk, kl)]
    xp = np.pad(x, [(0, 0)] + pads + [(0, 0)])
    out = np.zeros((b, di, dj, dk, dl, cout), dtype=np.float64)
    for a in range(ki):
        for bb in range(kj):
            for c in range(kk):
                for d in range(kl):
                    patch = xp[:, a : a + di, bb : bb + dj, c : c + dk, d : d + dl, :]
                    out += np.einsum("bijklc,co->bijklo", patch, w[a, bb, c, d])
    if bias is not None:
        out += bias
    return out


@pytest.mark.parametrize(
    "impl",
    ["xla", "taps", "scan", "tlc", "btl", "tlcv", "tf3", "tf2", "cf",
     "cfs", "cf1", "cf1s", "ck1", "tk1", "btl2", "btl4", "btl5", "gemm", "gemms", "pallas"],
)
@pytest.mark.parametrize("ksize,cin,cout", [(3, 1, 2), (5, 2, 1)])
def test_conv4d_matches_bruteforce(impl, ksize, cin, cout):
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 5, 4, 6, cin).astype(np.float32)
    w = rng.randn(ksize, ksize, ksize, ksize, cin, cout).astype(np.float32)
    bias = rng.randn(cout).astype(np.float32)
    got = run_conv4d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(bias), impl)
    want = conv4d_bruteforce(x, w, bias)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize(
    "impl",
    ["taps", "scan", "tlc", "btl", "tlcv", "tf3", "tf2", "cf", "cfs",
     "cf1", "cf1s", "ck1", "tk1", "btl2", "btl4", "btl5", "gemm", "gemms", "pallas"],
)
def test_conv4d_impls_agree_with_grad(impl):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(1, 4, 4, 4, 4, 2).astype(np.float32))
    w = jnp.asarray(rng.randn(3, 3, 3, 3, 2, 2).astype(np.float32))
    b = jnp.asarray(rng.randn(2).astype(np.float32))

    f_xla = lambda x_, w_, b_: jnp.sum(jnp.sin(conv4d(x_, w_, b_, impl="xla")))
    f_imp = lambda x_, w_, b_: jnp.sum(jnp.sin(run_conv4d(x_, w_, b_, impl)))
    np.testing.assert_allclose(f_xla(x, w, b), f_imp(x, w, b), rtol=1e-5)
    g_xla = jax.grad(f_xla, argnums=(0, 1, 2))(x, w, b)
    g_imp = jax.grad(f_imp, argnums=(0, 1, 2))(x, w, b)
    for a, bgrad in zip(g_xla, g_imp):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bgrad), rtol=1e-3, atol=1e-4
        )


@pytest.mark.parametrize("l", [9, 16, 25])
def test_conv4d_btl_multiblock(l):
    """btl's default block is 8, so the l<=6 shapes of the shared tests
    degenerate to a single block; these sizes exercise the inter-block
    window stacking, reshape and trailing :l slice (l=25 = training grid)."""
    rng = np.random.RandomState(3)
    x = rng.randn(1, 3, 3, 3, l, 2).astype(np.float32)
    w = rng.randn(5, 5, 5, 5, 2, 3).astype(np.float32)
    want = np.asarray(conv4d(jnp.asarray(x), jnp.asarray(w), impl="xla"))
    got = np.asarray(conv4d(jnp.asarray(x), jnp.asarray(w), impl="btl"))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_conv4d_matches_torch_conv3d_decomposition():
    """Cross-check against a torch conv3d tap decomposition (the reference's
    formulation, lib/conv4d.py:39-48: bias only on the center tap)."""
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    rng = np.random.RandomState(2)
    ksize, cin, cout = 3, 2, 3
    x = rng.randn(1, 5, 4, 4, 5, cin).astype(np.float32)
    w = rng.randn(ksize, ksize, ksize, ksize, cin, cout).astype(np.float32)
    bias = rng.randn(cout).astype(np.float32)

    got = np.asarray(conv4d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(bias)))

    # torch conv3d expects [b, c, D, H, W]; tap over the first kernel dim.
    xt = torch.from_numpy(x.transpose(0, 5, 1, 2, 3, 4))  # [b, c, i, j, k, l]
    wt = torch.from_numpy(w.transpose(5, 4, 0, 1, 2, 3))  # [cout, cin, ki, kj, kk, kl]
    bt = torch.from_numpy(bias)
    pad = ksize // 2
    b_, c_, i_, j_, k_, l_ = xt.shape
    xpad = torch.nn.functional.pad(xt, (0, 0, 0, 0, 0, 0, pad, pad))
    out = torch.zeros(b_, cout, i_, j_, k_, l_)
    for i in range(i_):
        for p in range(ksize):
            out[:, :, i] += F.conv3d(
                xpad[:, :, i + p],
                wt[:, :, p],
                bias=bt if p == pad else None,
                padding=pad,
            )
    want = out.numpy().transpose(0, 2, 3, 4, 5, 1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_registry_names_all_dispatch():
    """Every name in the canonical CONV4D_IMPLS registry (the CLI
    validators' source of truth) must actually dispatch in conv4d()."""
    from ncnet_tpu.ops.conv4d import CONV4D_IMPLS

    rng = np.random.RandomState(9)
    x = jnp.asarray(rng.randn(1, 3, 3, 3, 3, 2).astype(np.float32))
    w = jnp.asarray(rng.randn(3, 3, 3, 3, 2, 2).astype(np.float32))
    want = np.asarray(conv4d(x, w, impl="xla"))
    for impl in CONV4D_IMPLS:
        got = np.asarray(conv4d(x, w, impl=impl))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4,
                                   err_msg=impl)


def test_composite_impl_grads_match_xla():
    """'<fwd>/<dx>' composites: forward uses one lowering, the input
    gradient another (round-3 fix for XLA's pathological conv transposes
    on asymmetric-channel layers); values and ALL grads must match."""
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(1, 4, 4, 4, 4, 3).astype(np.float32))
    w = jnp.asarray(rng.randn(3, 3, 3, 3, 3, 1).astype(np.float32))
    b = jnp.asarray(rng.randn(1).astype(np.float32))

    f_xla = lambda x_, w_, b_: jnp.sum(jnp.sin(conv4d(x_, w_, b_, impl="xla")))
    f_cmp = lambda x_, w_, b_: jnp.sum(
        jnp.sin(conv4d(x_, w_, b_, impl="tlc/btl"))
    )
    np.testing.assert_allclose(f_xla(x, w, b), f_cmp(x, w, b), rtol=1e-5)
    g_xla = jax.grad(f_xla, argnums=(0, 1, 2))(x, w, b)
    g_cmp = jax.grad(f_cmp, argnums=(0, 1, 2))(x, w, b)
    for a, bb in zip(g_xla, g_cmp):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=1e-3, atol=1e-4
        )


@pytest.mark.parametrize(
    "impl",
    [
        "btl4//dwe",     # empty dx (autodiff transpose) + direct-GEMM dw
        "btl4//dwe2",    # blocked-scan direct dw
        "tlc/btl/dwe4",
        "tlc//btl",      # dw via transpose of ANOTHER formulation
        "tlc/tlc/tf3",   # the round-4 measured-best L3 combination
        "btl4/btl4/dwe1",
    ],
)
def test_three_way_composite_grads_match_xla(impl):
    """'<fwd>/<dx>/<dw>' composites (round 4): the dw slot may transpose a
    different formulation or compute the kernel gradient directly via the
    tap-folded GEMM of `_dw_fold`; values and ALL grads must match
    autodiff through the rank-4 conv."""
    rng = np.random.RandomState(13)
    x = jnp.asarray(rng.randn(2, 4, 5, 4, 5, 2).astype(np.float32))
    w = jnp.asarray(rng.randn(3, 3, 3, 3, 2, 3).astype(np.float32))
    b = jnp.asarray(rng.randn(3).astype(np.float32))

    f_xla = lambda x_, w_, b_: jnp.sum(jnp.sin(conv4d(x_, w_, b_, impl="xla")))
    f_cmp = lambda x_, w_, b_: jnp.sum(jnp.sin(conv4d(x_, w_, b_, impl=impl)))
    np.testing.assert_allclose(f_xla(x, w, b), f_cmp(x, w, b), rtol=1e-5)
    g_xla = jax.grad(f_xla, argnums=(0, 1, 2))(x, w, b)
    g_cmp = jax.grad(f_cmp, argnums=(0, 1, 2))(x, w, b)
    for a, bb in zip(g_xla, g_cmp):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=1e-3, atol=1e-4
        )


def test_dw_fold_blocked_matches_unblocked_and_autodiff():
    """`_dw_fold` (the direct tap-folded kernel-gradient GEMM): every block
    size agrees with the single-GEMM path and with autodiff, including
    rectangular grids and a 5^4 kernel."""
    from ncnet_tpu.ops.conv4d import _dw_fold

    rng = np.random.RandomState(17)
    for shape, ks in [((2, 5, 6, 4, 5, 3), 3), ((1, 6, 6, 6, 6, 1), 5)]:
        cin, cout = shape[-1], 2
        x = jnp.asarray(rng.randn(*shape).astype(np.float32))
        w = jnp.asarray(rng.randn(ks, ks, ks, ks, cin, cout).astype(np.float32))
        g = jnp.asarray(rng.randn(*shape[:-1], cout).astype(np.float32))
        dw_ref = jax.grad(
            lambda w_: jnp.vdot(conv4d(x, w_, impl="xla"), g)
        )(w)
        for block in (0, 1, 2, 4):
            dw = _dw_fold(x, g, w.shape, block=block)
            np.testing.assert_allclose(
                np.asarray(dw), np.asarray(dw_ref), rtol=1e-4, atol=1e-4,
                err_msg=f"block={block}",
            )


def test_composite_even_kernel_raises():
    """Even kernels break the flip/transpose dx identity and the _dw_fold
    contraction domain: both must fail loudly, not return wrong grads."""
    rng = np.random.RandomState(19)
    x = jnp.asarray(rng.randn(1, 4, 4, 4, 4, 2).astype(np.float32))
    w_even = jnp.asarray(rng.randn(2, 2, 2, 2, 2, 2).astype(np.float32))
    g = jnp.asarray(rng.randn(1, 4, 4, 4, 4, 2).astype(np.float32))
    f = lambda x_, w_: jnp.sum(conv4d(x_, w_, impl="tlc/tlc"))
    with pytest.raises(ValueError, match="odd kernel"):
        jax.grad(f, argnums=1)(x, w_even)
    from ncnet_tpu.ops.conv4d import _dw_fold

    with pytest.raises(ValueError, match="odd kernel"):
        _dw_fold(x, g, w_even.shape)
