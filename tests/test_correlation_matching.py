import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.ops.correlation import (
    correlation_3d,
    correlation_4d,
    correlation_maxpool4d,
)
from ncnet_tpu.ops.matching import maxpool4d, mutual_matching
from ncnet_tpu.ops.norm import feature_l2norm


def test_correlation_4d_is_all_pairs_dot():
    rng = np.random.RandomState(0)
    fa = rng.randn(2, 3, 4, 8).astype(np.float32)
    fb = rng.randn(2, 5, 6, 8).astype(np.float32)
    got = np.asarray(correlation_4d(jnp.asarray(fa), jnp.asarray(fb)))
    want = np.einsum("bijc,bklc->bijkl", fa, fb)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_correlation_4d_normalized_branch():
    rng = np.random.RandomState(1)
    fa = rng.randn(1, 3, 3, 4).astype(np.float32)
    fb = rng.randn(1, 3, 3, 4).astype(np.float32)
    got = np.asarray(
        correlation_4d(jnp.asarray(fa), jnp.asarray(fb), normalization=True)
    )
    raw = np.maximum(np.einsum("bijc,bklc->bijkl", fa, fb), 0)
    flat = raw.reshape(1, 3, 3, 9)
    want = (flat / np.sqrt((flat**2).sum(-1, keepdims=True) + 1e-6)).reshape(raw.shape)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_correlation_3d_matches_torch_reference():
    """Parity with the reference's shape='3D' branch (lib/model.py:97-105):
    bmm of a column-major-flattened A against B, ReLU + L2 norm."""
    torch = pytest.importorskip("torch")

    rng = np.random.RandomState(3)
    b, h, w, c = 2, 3, 4, 5
    fa = rng.randn(b, h, w, c).astype(np.float32)
    fb = rng.randn(b, h, w, c).astype(np.float32)

    # torch reference math on NCHW tensors
    ta = torch.from_numpy(fa.transpose(0, 3, 1, 2))
    tb = torch.from_numpy(fb.transpose(0, 3, 1, 2))
    fa_t = ta.transpose(2, 3).contiguous().view(b, c, h * w)
    fb_t = tb.reshape(b, c, h * w).transpose(1, 2)
    mul = torch.bmm(fb_t, fa_t)
    ref = mul.view(b, h, w, h * w).transpose(2, 3).transpose(1, 2)
    ref = torch.relu(ref)
    ref = ref / (ref.pow(2).sum(1, keepdim=True) + 1e-6).sqrt()
    want = ref.numpy().transpose(0, 2, 3, 1)  # -> [b, hB, wB, hA*wA]

    got = np.asarray(correlation_3d(jnp.asarray(fa), jnp.asarray(fb)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_feature_l2norm():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 4, 5).astype(np.float32)
    got = np.asarray(feature_l2norm(jnp.asarray(x)))
    want = x / np.sqrt((x**2).sum(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_mutual_matching_formula_and_symmetry():
    rng = np.random.RandomState(3)
    corr = rng.rand(2, 3, 4, 5, 6).astype(np.float32)
    got = np.asarray(mutual_matching(jnp.asarray(corr)))
    max_a = corr.max(axis=(1, 2), keepdims=True)
    max_b = corr.max(axis=(3, 4), keepdims=True)
    want = corr * ((corr / (max_b + 1e-5)) * (corr / (max_a + 1e-5)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # MM(x^T) == MM(x)^T where ^T swaps the A/B index pairs
    corr_t = corr.transpose(0, 3, 4, 1, 2)
    got_t = np.asarray(mutual_matching(jnp.asarray(corr_t)))
    np.testing.assert_allclose(got_t, got.transpose(0, 3, 4, 1, 2), rtol=1e-5)


def maxpool4d_bruteforce(corr, k):
    b, d1, d2, d3, d4 = corr.shape
    pooled = np.zeros((b, d1 // k, d2 // k, d3 // k, d4 // k), corr.dtype)
    offs = [np.zeros_like(pooled, dtype=np.int32) for _ in range(4)]
    for bi in range(b):
        for i in range(d1 // k):
            for j in range(d2 // k):
                for p in range(d3 // k):
                    for q in range(d4 // k):
                        block = corr[
                            bi,
                            i * k : (i + 1) * k,
                            j * k : (j + 1) * k,
                            p * k : (p + 1) * k,
                            q * k : (q + 1) * k,
                        ]
                        flat = block.reshape(-1)
                        m = int(np.argmax(flat))
                        pooled[bi, i, j, p, q] = flat[m]
                        o = np.unravel_index(m, (k, k, k, k))
                        for a in range(4):
                            offs[a][bi, i, j, p, q] = o[a]
    return pooled, tuple(offs)


def test_maxpool4d_matches_bruteforce():
    rng = np.random.RandomState(4)
    corr = rng.randn(1, 4, 4, 6, 6).astype(np.float32)
    pooled, deltas = maxpool4d(jnp.asarray(corr), 2)
    want_pooled, want_deltas = maxpool4d_bruteforce(corr, 2)
    np.testing.assert_allclose(np.asarray(pooled), want_pooled, rtol=1e-6)
    for got_d, want_d in zip(deltas, want_deltas):
        np.testing.assert_array_equal(np.asarray(got_d), want_d)


@pytest.mark.parametrize("k", [2, 3])
def test_fused_correlation_maxpool_equals_unfused(k):
    rng = np.random.RandomState(5)
    fa = rng.randn(2, 2 * k, 3 * k, 7).astype(np.float32)
    fb = rng.randn(2, 3 * k, 2 * k, 7).astype(np.float32)
    fused, fused_d = correlation_maxpool4d(jnp.asarray(fa), jnp.asarray(fb), k)
    full = correlation_4d(jnp.asarray(fa), jnp.asarray(fb))
    unfused, unfused_d = maxpool4d(full, k)
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(unfused), rtol=1e-5, atol=1e-6
    )
    for a, b_ in zip(fused_d, unfused_d):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
