"""Dense pose verification on synthetic scenes: rendering, descriptors,
and the discriminative property (correct pose out-scores wrong ones)."""

import numpy as np
import pytest

from ncnet_tpu.eval.pose_verify import (
    dense_root_sift,
    image_normalization,
    inpaint_nearest,
    pose_verification_score,
    project_points_persp,
    rerank_by_pose_verification,
)


def _scene(rng, n=40000):
    """A textured plane at z=0 viewed from above: colorful checkerboard."""
    xy = rng.rand(n, 2) * 8.0 - 4.0
    xyz = np.concatenate([xy, np.zeros((n, 1))], axis=1)
    checker = ((np.floor(xy[:, 0] * 2) + np.floor(xy[:, 1] * 2)) % 2)
    stripes = (np.floor(xy[:, 0] * 4) % 2)
    rgb = np.stack(
        [checker * 255, stripes * 255, (checker + stripes) % 2 * 255], axis=1
    )
    return rgb, xyz


def _pose(tz=6.0, tx=0.0, angle=0.0):
    """Proper rotation: camera at (tx, 0, tz) looking straight down at the
    plane (z_cam = tz - z_world > 0), optionally yawed by ``angle``."""
    c, s = np.cos(angle), np.sin(angle)
    Rz = np.array([[c, -s, 0.0], [s, c, 0.0], [0.0, 0.0, 1.0]])
    R = np.diag([1.0, -1.0, -1.0]) @ Rz  # det +1
    C = np.array([tx, 0.0, tz])
    return np.concatenate([R, (-R @ C)[:, None]], axis=1)


def _render_query(rgb, xyz, P, fl, h, w):
    K = np.array([[fl, 0, w / 2.0], [0, fl, h / 2.0], [0, 0, 1.0]])
    img, _, valid = project_points_persp(rgb, xyz, K @ P, h, w)
    return inpaint_nearest(img, valid), valid


def test_projection_zbuffer_and_bounds():
    rgb = np.array([[255.0, 0, 0], [0, 255.0, 0]])
    # two points on the same ray; the nearer (z=1) must win
    xyz = np.array([[0.0, 0.0, 1.0], [0.0, 0.0, 2.0]])
    KP = np.array([[10.0, 0, 5, 0], [0, 10.0, 5, 0], [0, 0, 1.0, 0]])
    img, xyzp, valid = project_points_persp(rgb, xyz, KP, 10, 10)
    assert valid[5, 5]
    np.testing.assert_array_equal(img[5, 5], [255.0, 0, 0])
    np.testing.assert_allclose(xyzp[5, 5], [0, 0, 1.0])
    assert valid.sum() == 1


def test_inpaint_and_normalization():
    img = np.arange(16, dtype=np.float64).reshape(4, 4)
    valid = np.ones((4, 4), bool)
    valid[0, 0] = False
    filled = inpaint_nearest(img, valid)
    assert filled[0, 0] in (img[0, 1], img[1, 0], img[1, 1])
    norm = image_normalization(img, valid)
    vals = norm[valid]
    np.testing.assert_allclose(vals.mean(), 0.0, atol=1e-12)
    np.testing.assert_allclose(vals.std(), 1.0, atol=1e-9)


def test_dense_root_sift_shape_and_norm():
    rng = np.random.RandomState(0)
    img = rng.rand(64, 80)
    centers, desc = dense_root_sift(img)
    assert desc.shape[1] == 128
    assert len(centers) == len(desc) > 0
    # RootSIFT: squared descriptors are L1-normalized
    np.testing.assert_allclose((desc**2).sum(axis=1), 1.0, atol=1e-6)
    # centers lie inside the image
    assert centers[:, 0].max() < 80 and centers[:, 1].max() < 64


def test_correct_pose_outscores_wrong_poses():
    """The discriminative property the PV stage exists for
    (parfor_nc4d_PV.m): rendering at the true pose matches the query far
    better than rendering at perturbed poses."""
    rng = np.random.RandomState(1)
    rgb, xyz = _scene(rng)
    fl = 150.0
    h, w = 120, 160
    P_true = _pose(tz=6.0)
    query, _ = _render_query(rgb, xyz, P_true, fl, h, w)
    # score at native scale (downsample=1, smaller descriptor support —
    # the 8x stage default assumes multi-megapixel InLoc queries)
    kw = dict(downsample=1.0, bin_size=4, step=4)
    score_true = pose_verification_score(query, rgb, xyz, P_true, fl, **kw)
    score_shift = pose_verification_score(
        query, rgb, xyz, _pose(tz=6.0, tx=1.5), fl, **kw
    )
    score_rot = pose_verification_score(
        query, rgb, xyz, _pose(tz=6.0, angle=0.6), fl, **kw
    )
    score_nan = pose_verification_score(
        query, rgb, xyz, np.full((3, 4), np.nan), fl, **kw
    )
    assert score_nan == 0.0
    assert score_true > score_shift
    assert score_true > score_rot


def test_rerank_orders_by_score():
    entries = [
        {"queryname": "q", "topNname": ["a", "b", "c"],
         "P": [np.eye(3, 4), np.eye(3, 4), np.eye(3, 4)]}
    ]
    scores = {0: 0.1, 1: 0.9, 2: 0.5}
    out = rerank_by_pose_verification(
        entries, lambda e, j: scores[j], top_n=3
    )
    assert out[0]["topNname"] == ["b", "c", "a"]
    assert out[0]["topNscore"] == [0.9, 0.5, 0.1]
