"""Sharded-checkpoint recovery drills (resilience.distributed + train wiring).

Directory-layout analog of tests/test_resilience.py: every phase of the
two-phase commit gets a fault injected (``dckpt.shard_write``,
``dckpt.manifest``, ``dckpt.barrier``, ``dckpt.commit``) and in each case
the previous committed save must stay loadable and a resumed TRAINING run
must match the uninterrupted one bitwise — plus the topology-change
restores the format exists for: a save written on a 1-process/4-device
mesh restored onto 2-device and real 2-process meshes (and back), with
chunks re-tiled per device via `SaveReader.read(..., sharding=...)`.

The 2-process cases run this file as the child script of
`conftest.spawn_cpu_cluster` (the tests/test_multihost.py technique).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ != "__main__":  # children must not import pytest plugins
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from conftest import multiprocess_cpu_supported, spawn_cpu_cluster
    from ncnet_tpu.data.loader import DataLoader
    from ncnet_tpu.data.pairs import SyntheticPairDataset
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.parallel.mesh import make_mesh
    from ncnet_tpu.resilience import distributed, durable, faultinject
    from ncnet_tpu.train.checkpoint import (
        CheckpointData,
        load_checkpoint,
        load_checkpoint_sharded,
        load_latest_valid_any,
        save_checkpoint,
        save_checkpoint_sharded,
        sharded_dir_for,
    )
    from ncnet_tpu.train.loop import train

    CFG = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))

    @pytest.fixture(autouse=True)
    def _no_leaked_faults():
        faultinject.clear()
        yield
        faultinject.clear()


# Deterministic fixtures shared between the parent and cluster children
# (module level, numpy only, so the child script can call them too).

def _x_global():
    """A leaf that is GENUINELY sharded along the data axis when saved."""
    return (np.arange(64, dtype=np.float32) * 3.0 + 1.0).reshape(8, 8)


def _y_repl():
    """A fully-replicated / host leaf (round-robin ownership path)."""
    return np.linspace(-2.0, 2.0, 7).astype(np.float32)


def _tiny_leaves(fill):
    return [
        ("['params']['w']", np.full((16, 4), fill, np.float32)),
        ("['params']['b']", np.arange(8, dtype=np.float32) + fill),
    ]


# Hit indices within ONE armed save over `_tiny_leaves` (2 chunks/save on
# one process): shard_write fires twice per chunk (mid-write +
# rename-pending); manifest covers meta then manifest (2 windows each);
# barrier fires once; commit fires at verification-done plus the commit
# file's two durable windows. Chosen to land in DIFFERENT windows: second
# chunk mid-write, manifest mid-write, the barrier itself, and the commit
# rename-pending window (temp fully written, never published).
_TINY_SAVE_AT = {
    "dckpt.shard_write": 3,
    "dckpt.manifest": 3,
    "dckpt.barrier": 1,
    "dckpt.commit": 3,
}


# --- direct save/restore drills (no training loop) ---------------------------


def _save_tiny(base, step, fill):
    return distributed.save_sharded(
        base, step, _tiny_leaves(fill), f"meta-{step}".encode()
    )


def _load_w(base):
    """(w, step_dir) from the newest valid save."""
    return distributed.latest_valid_save(base, lambda r: r.read(0))


def test_save_reader_roundtrip_and_reshard(tmp_path):
    """Chunks written by a 4-device sharded leaf reassemble bitwise as
    host numpy AND as a re-sharded global array on a 2-device mesh."""
    base = str(tmp_path)
    mesh4 = make_mesh(devices=jax.devices()[:4])
    x = jax.device_put(_x_global(), NamedSharding(mesh4, P("data")))
    step_dir = distributed.save_sharded(
        base, 1, [("x", x), ("y", _y_repl())], b"meta-1"
    )
    assert distributed.is_committed(step_dir)

    r = distributed.SaveReader(step_dir)
    assert r.n_leaves == 2
    assert r.meta_bytes() == b"meta-1"
    np.testing.assert_array_equal(r.read(0), _x_global())
    np.testing.assert_array_equal(r.read(1), _y_repl())
    # the sharded leaf produced one chunk per device tile, not one blob
    assert r.leaf_info(0)["key"] == "x"
    assert len(r._chunks[0]) == 4

    mesh2 = make_mesh(devices=jax.devices()[:2])
    x2 = r.read(0, sharding=NamedSharding(mesh2, P("data")))
    assert len(x2.sharding.device_set) == 2
    np.testing.assert_array_equal(np.asarray(jax.device_get(x2)), _x_global())
    y2 = r.read(1, sharding=NamedSharding(mesh2, P()))
    np.testing.assert_array_equal(np.asarray(jax.device_get(y2)), _y_repl())


@pytest.mark.parametrize("point", sorted(_TINY_SAVE_AT))
def test_crash_in_each_phase_leaves_previous_save(tmp_path, point):
    """The acceptance drill at save granularity: a crash in ANY phase of
    save 2 leaves save 1 the newest valid save; the torn ``step_<N>/`` is
    on disk but uncommitted and never selected."""
    base = str(tmp_path)
    _save_tiny(base, 1, 1.0)
    faultinject.inject(point, "crash", at=_TINY_SAVE_AT[point])
    with pytest.raises(faultinject.InjectedFault):
        _save_tiny(base, 2, 2.0)
    faultinject.clear()

    torn = os.path.join(base, distributed.step_dir_name(2))
    assert os.path.isdir(torn), "the torn save directory should exist"
    assert not distributed.is_committed(torn)
    w, used = _load_w(base)
    assert used == os.path.join(base, distributed.step_dir_name(1))
    np.testing.assert_array_equal(w, np.full((16, 4), 1.0, np.float32))

    # recovery after the crash: re-running the save commits over the torn
    # directory and becomes the newest valid save
    _save_tiny(base, 2, 2.0)
    w, used = _load_w(base)
    assert used == torn and float(w[0, 0]) == 2.0


def test_uncommitted_directory_is_never_selected(tmp_path):
    base = str(tmp_path)
    _save_tiny(base, 1, 1.0)
    _save_tiny(base, 2, 2.0)
    # a newer directory without a commit manifest (writer killed pre-commit)
    fake = os.path.join(base, distributed.step_dir_name(9))
    os.makedirs(os.path.join(fake, distributed.ARRAYS_SUBDIR))
    w, used = _load_w(base)
    assert used == os.path.join(base, distributed.step_dir_name(2))
    # a commit file whose atomic rename pair is incomplete (no verifying
    # sidecar) counts as uncommitted too
    with open(os.path.join(fake, distributed.COMMIT_NAME), "wb") as f:
        f.write(b"{}")
    assert not distributed.is_committed(fake)
    _, used = _load_w(base)
    assert used == os.path.join(base, distributed.step_dir_name(2))


def test_committed_save_with_missing_or_corrupt_shard_walks_back(tmp_path):
    base = str(tmp_path)
    _save_tiny(base, 1, 1.0)
    step2 = _save_tiny(base, 2, 2.0)
    arrays = os.path.join(step2, distributed.ARRAYS_SUBDIR)
    victim = sorted(
        n for n in os.listdir(arrays) if n.endswith(".npy")
    )[0]
    os.remove(os.path.join(arrays, victim))
    with pytest.raises(FileNotFoundError, match="missing"):
        distributed.SaveReader(step2)
    w, used = _load_w(base)
    assert used == os.path.join(base, distributed.step_dir_name(1))
    np.testing.assert_array_equal(w, np.full((16, 4), 1.0, np.float32))

    # corrupt (rather than missing) shard bytes: manifest digest catches it
    step3 = _save_tiny(base, 3, 3.0)
    arrays3 = os.path.join(step3, distributed.ARRAYS_SUBDIR)
    victim3 = sorted(n for n in os.listdir(arrays3) if n.endswith(".npy"))[0]
    with open(os.path.join(arrays3, victim3), "r+b") as f:
        blob = bytearray(f.read())
        blob[-1] ^= 0xFF
        f.seek(0)
        f.write(bytes(blob))
    with pytest.raises(durable.IntegrityError):
        distributed.SaveReader(step3)
    _, used = _load_w(base)
    assert used == os.path.join(base, distributed.step_dir_name(1))


def test_best_pointer_is_o1_and_survives_pruning(tmp_path):
    base = str(tmp_path)
    _save_tiny(base, 1, 1.0)
    best_dir = distributed.save_sharded(
        base, 2, _tiny_leaves(2.0), b"meta-2", is_best=True
    )
    assert distributed.read_best_pointer(base) == best_dir
    # later non-best saves leave the pointer alone
    for step in (3, 4, 5):
        _save_tiny(base, step, float(step))
    assert distributed.read_best_pointer(base) == best_dir
    # retention keeps the newest `keep` saves PLUS the best target
    distributed.prune_saves(base, keep=2)
    kept = distributed.save_candidates(base)
    assert best_dir in kept and len(kept) == 3
    r = distributed.SaveReader(best_dir)
    np.testing.assert_array_equal(r.read(0), np.full((16, 4), 2.0, np.float32))


def tiny_ckpt(step=1, fill=0.0):
    return CheckpointData(
        config=CFG,
        params={"w": np.full((64,), fill, np.float32)},
        step=step,
    )


def test_legacy_best_is_a_hardlink_not_a_copy(tmp_path):
    """Satellite: the legacy layout's ``best_`` file is now a hardlinked
    pointer to already-durable bytes — no re-serialization, no second
    fsync of the payload."""
    path = str(tmp_path / "ck.msgpack")
    save_checkpoint(path, tiny_ckpt(step=1, fill=3.0), is_best=True)
    best = str(tmp_path / "best_ck.msgpack")
    assert os.path.samefile(path, best)
    assert os.path.samefile(
        durable.digest_path(path), durable.digest_path(best)
    )
    assert durable.verify_digest(best) is True
    ck = load_checkpoint(best)
    np.testing.assert_array_equal(
        ck.params["w"], np.full((64,), 3.0, np.float32)
    )


def test_load_latest_valid_any_auto_migration(tmp_path):
    """A run migrated mid-history resumes from the right place: the legacy
    file until a sharded save commits, the sharded shadow directory after,
    and back to legacy if every sharded save is torn."""
    path = str(tmp_path / "ck.msgpack")
    save_checkpoint(path, tiny_ckpt(step=1, fill=1.0))
    ck, used = load_latest_valid_any(path)
    assert used == path and int(ck.step) == 1

    sdir = sharded_dir_for(path)
    save_checkpoint_sharded(sdir, tiny_ckpt(step=2, fill=2.0))
    ck, used = load_latest_valid_any(path)
    assert used == os.path.join(sdir, distributed.step_dir_name(2))
    assert int(ck.step) == 2
    np.testing.assert_array_equal(
        ck.params["w"], np.full((64,), 2.0, np.float32)
    )

    # every sharded save torn -> one fallback to the legacy file, not a crash
    os.remove(os.path.join(sdir, distributed.step_dir_name(2),
                           distributed.COMMIT_NAME))
    ck, used = load_latest_valid_any(path)
    assert used == path and int(ck.step) == 1


def test_topology_change_restore_resharded_params(tmp_path):
    """Save on a 1-process/4-device mesh, restore onto a 2-device mesh as
    global jax.Arrays: bitwise-equal params and an identical resume
    cursor. (The 2-process directions live in
    `test_cross_topology_save_restore_two_process`.)"""
    sdir = str(tmp_path / "ck.dckpt")
    mesh4 = make_mesh(devices=jax.devices()[:4])
    repl4 = NamedSharding(mesh4, P())
    cursor = {
        "epoch": 1, "batch_index": 2, "shuffle_seed": 5,
        "epoch_losses": [0.5, 0.25],
    }
    data = tiny_ckpt(step=4, fill=7.0)
    data.params = jax.device_put(data.params, repl4)
    data.cursor = cursor
    save_checkpoint_sharded(sdir, data)

    mesh2 = make_mesh(devices=jax.devices()[:2])
    ck, used = load_latest_valid_any(
        sdir, shardings=lambda key, info: NamedSharding(mesh2, P())
    )
    assert used == os.path.join(sdir, distributed.step_dir_name(4))
    w = ck.params["w"]
    assert isinstance(w, jax.Array) and len(w.sharding.device_set) == 2
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(w)), np.full((64,), 7.0, np.float32)
    )
    assert ck.cursor == cursor
    # without shardings the same save restores as host numpy
    ck_host, _ = load_latest_valid_any(sdir)
    np.testing.assert_array_equal(
        np.asarray(ck_host.params["w"]), np.full((64,), 7.0, np.float32)
    )


def test_hard_kill_mid_shard_write_via_env(tmp_path):
    """A true preemption (``NCNET_FAULTS`` env -> os._exit, no cleanup)
    landing mid-write of a shard chunk: torn temp on disk, directory
    uncommitted, previous save selected."""
    base = str(tmp_path / "saves")
    script = f"""
import sys
sys.path.insert(0, {REPO!r})
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from ncnet_tpu.resilience import distributed

def leaves(fill):
    return [
        ("['params']['w']", np.full((16, 4), fill, np.float32)),
        ("['params']['b']", np.arange(8, dtype=np.float32) + fill),
    ]

base = {base!r}
distributed.save_sharded(base, 1, leaves(1.0), b"meta-1")
distributed.save_sharded(base, 2, leaves(2.0), b"meta-2")  # dies mid-chunk
raise SystemExit("unreachable: the kill fault did not fire")
"""
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "NCNET_FAULTS": "dckpt.shard_write=kill@5"},
    )
    assert proc.returncode == 137, proc.stderr

    torn = os.path.join(base, distributed.step_dir_name(2))
    assert os.path.isdir(torn) and not distributed.is_committed(torn)
    tmps = [
        n for n in os.listdir(os.path.join(torn, distributed.ARRAYS_SUBDIR))
        if ".tmp." in n
    ]
    assert tmps, "kill should have left a torn temp chunk behind"
    w, used = _load_w(base)
    assert used == os.path.join(base, distributed.step_dir_name(1))
    np.testing.assert_array_equal(w, np.full((16, 4), 1.0, np.float32))


# --- end-to-end: crash inside a sharded save, resume equals uninterrupted ----

N_PAIRS, BATCH, EPOCHS, SIZE = 8, 2, 2, 32
STEPS_PER_EPOCH = N_PAIRS // BATCH
CKNAME = "ncnet_tpu.msgpack"


def _loader(**kw):
    ds = SyntheticPairDataset(n=N_PAIRS, output_size=(SIZE, SIZE), seed=11)
    kw.setdefault("num_workers", 1)
    kw.setdefault("prefetch", 0)
    return DataLoader(ds, BATCH, shuffle=True, seed=5, drop_last=True, **kw)


def _run(ckdir, **train_kw):
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    kw = dict(
        num_epochs=EPOCHS, checkpoint_dir=str(ckdir), data_parallel=False,
        log_every=100, save_every_steps=2, keep_checkpoints=4,
        distributed_checkpoints=True,
    )
    kw.update(train_kw)
    return train(CFG, kw.pop("params", params), _loader(), None, **kw)


def _resume(ckdir, **train_kw):
    ck, used = load_latest_valid_any(os.path.join(str(ckdir), CKNAME))
    kw = dict(
        params=ck.params,
        opt_state=ck.opt_state,
        start_epoch=ck.epoch,
        start_step=ck.step,
        initial_best_val=ck.best_val_loss,
        initial_train_hist=ck.train_loss,
        initial_val_hist=ck.val_loss,
    )
    if ck.cursor:
        kw["start_epoch"] = ck.cursor["epoch"]
        kw["start_batch"] = ck.cursor["batch_index"]
        kw["start_epoch_losses"] = ck.cursor["epoch_losses"]
    kw.update(train_kw)
    return _run(ckdir, **kw), ck, used


def _final_state(ckdir):
    ck, _ = load_latest_valid_any(os.path.join(str(ckdir), CKNAME))
    lines = [
        json.loads(l)
        for l in open(os.path.join(str(ckdir), "metrics.jsonl"))
    ]
    return ck, lines


def _assert_bitwise_equal(ck_a, ck_b):
    flat_a, _ = jax.tree_util.tree_flatten_with_path(ck_a.params)
    flat_b, _ = jax.tree_util.tree_flatten_with_path(ck_b.params)
    assert len(flat_a) == len(flat_b)
    for (path_a, leaf_a), (_, leaf_b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(
            np.asarray(leaf_a), np.asarray(leaf_b),
            err_msg=f"params differ at {jax.tree_util.keystr(path_a)}",
        )
    for a, b in zip(
        jax.tree.leaves(ck_a.opt_state), jax.tree.leaves(ck_b.opt_state)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(ck_a.step) == int(ck_b.step)
    np.testing.assert_array_equal(
        np.asarray(ck_a.train_loss), np.asarray(ck_b.train_loss)
    )


def _assert_metrics_tails_match(lines_a, lines_b):
    strip = lambda l: {k: v for k, v in l.items() if k != "epoch_seconds"}
    assert [strip(l) for l in lines_a] == [strip(l) for l in lines_b]


@pytest.fixture(scope="module")
def uninterrupted(uninterrupted_run):
    """The session-shared uninterrupted run (tests/conftest.py): the
    same sharded-save schedule `_run` executes, paid once for the whole
    suite instead of once per module."""
    return uninterrupted_run


def _n_state_chunks(ckdir):
    """Chunks per training save = leaves of {params, opt_state} (single
    process, everything fully replicated -> one chunk each)."""
    sdir = sharded_dir_for(os.path.join(str(ckdir), CKNAME))
    committed = [
        d for d in distributed.save_candidates(sdir)
        if distributed.is_committed(d)
    ]
    return distributed.SaveReader(committed[0]).n_leaves


@pytest.mark.parametrize(
    "point",
    ["dckpt.shard_write", "dckpt.manifest", "dckpt.barrier", "dckpt.commit"],
)
def test_resume_after_crash_in_sharded_save(point, tmp_path, uninterrupted):
    """THE acceptance drill: kill the writer inside each phase of the
    two-phase commit during training. The torn save must never be
    selected, resume lands on the previous committed save (cursor at
    batch 2 of epoch 0), and the resumed run is bitwise-identical —
    params, opt_state, metrics — to the uninterrupted run."""
    ck_u, lines_u, udir = uninterrupted
    # arm the hit that lands inside the SECOND training save (the first
    # save must commit so there is something to resume from); per-save hit
    # counts: shard_write 2/chunk, manifest 4 (meta+manifest), barrier 1,
    # commit 3 (fire + the commit file's two durable windows)
    at = {
        "dckpt.shard_write": 2 * _n_state_chunks(udir) + 1,
        "dckpt.manifest": 5,
        "dckpt.barrier": 2,
        "dckpt.commit": 4,
    }[point]
    faultinject.inject(point, "crash", at=at)
    with pytest.raises(faultinject.InjectedFault):
        _run(tmp_path)
    faultinject.clear()

    sdir = sharded_dir_for(os.path.join(str(tmp_path), CKNAME))
    torn = os.path.join(sdir, distributed.step_dir_name(4))
    assert os.path.isdir(torn), "crash should have left the step-4 attempt"
    assert not distributed.is_committed(torn)

    (_, history), ck_at_resume, used = _resume(tmp_path)
    assert used == os.path.join(sdir, distributed.step_dir_name(2))
    assert ck_at_resume.cursor is not None
    assert ck_at_resume.cursor["epoch"] == 0
    assert ck_at_resume.cursor["batch_index"] == 2
    assert not history["preempted"]

    ck_b, lines_b = _final_state(tmp_path)
    _assert_bitwise_equal(ck_u, ck_b)
    _assert_metrics_tails_match(lines_u, lines_b)


def test_sharded_training_matches_legacy_bitwise(
    uninterrupted, legacy_format_run
):
    """Switching the save format must not perturb training: a legacy-mode
    run of the same schedule ends bitwise-identical to the sharded-mode
    fixture (params, opt_state, metrics). Both arms are the session-shared
    fixtures (tests/conftest.py, the tier-1 budget lever): the comparison
    is unchanged, only the duplicate 2-epoch legacy training is."""
    ck_u, lines_u, _ = uninterrupted
    ck_l, lines_l, _ = legacy_format_run
    _assert_bitwise_equal(ck_u, ck_l)
    _assert_metrics_tails_match(lines_u, lines_l)


# --- real 2-process topology: save and restore across process counts ---------


def _child_main():
    """Cluster child: restore the parent's 1-process save onto this
    2-process mesh, then collectively write a 2-process save (real
    cross-host two-phase commit, filesystem barrier included)."""
    import jax

    # same load-bearing guard as test_multihost: JAX_PLATFORMS env is
    # ignored when this image's TPU plugin is present
    jax.config.update("jax_platforms", "cpu")

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ncnet_tpu.parallel.mesh import initialize_multihost, make_hybrid_mesh
    from ncnet_tpu.resilience import distributed

    coordinator = os.environ["_NCNET_MH_COORD"]
    pid = int(os.environ["_NCNET_MH_PID"])
    initialize_multihost(
        coordinator_address=coordinator, num_processes=2, process_id=pid
    )
    assert jax.device_count() == 4 and jax.local_device_count() == 2

    mesh = make_hybrid_mesh()
    data_sh = NamedSharding(mesh, P("data"))
    repl_sh = NamedSharding(mesh, P())

    # (a) 1-process save -> 2-process restore: each process assembles only
    # its local devices' tiles and checks them against the global oracle
    ra = distributed.SaveReader(
        os.path.join(os.environ["_NCNET_DCKPT_A"],
                     distributed.step_dir_name(1))
    )
    assert ra.meta_bytes() == b"meta-parent"
    xa = ra.read(0, sharding=data_sh)
    assert len(xa.sharding.device_set) == 4
    for shard in xa.addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), _x_global()[shard.index]
        )
    ya = ra.read(1, sharding=repl_sh)
    for shard in ya.addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data), _y_repl())

    # (b) 2-process collective save: this process writes ONLY its own
    # addressable tiles of x; the replicated y lands on process 1 by
    # round-robin, so both hosts contribute chunks
    xg = _x_global()
    x = jax.make_array_from_callback(
        xg.shape, data_sh, lambda idx: xg[idx]
    )
    step_dir = distributed.save_sharded(
        os.environ["_NCNET_DCKPT_B"], 3,
        [("x", x), ("y", _y_repl())], b"meta-2proc",
    )
    # every process returns only once the commit marker is durably visible
    assert distributed.is_committed(step_dir)
    print(f"DCKPT OK pid={pid} procs={jax.process_count()}", flush=True)


def test_cross_topology_save_restore_two_process(tmp_path):
    """Both topology directions through a REAL 2-process cluster:
    1-process/4-device save -> 2-process restore (in the children), and
    2-process collective save -> 1-process restore onto 4- and 2-device
    meshes (back in the parent), all bitwise."""
    if not multiprocess_cpu_supported():
        pytest.skip(
            "this jaxlib lacks multiprocess CPU collectives (no gloo "
            "implementation to back jax.distributed on CPU)"
        )
    dir_a = str(tmp_path / "from_1proc")
    dir_b = str(tmp_path / "from_2proc")

    mesh4 = make_mesh(devices=jax.devices()[:4])
    x = jax.device_put(_x_global(), NamedSharding(mesh4, P("data")))
    distributed.save_sharded(
        dir_a, 1, [("x", x), ("y", _y_repl())], b"meta-parent"
    )

    results = spawn_cpu_cluster(
        os.path.abspath(__file__), n_procs=2, local_devices=2, timeout=280,
        extra_env={"_NCNET_DCKPT_A": dir_a, "_NCNET_DCKPT_B": dir_b},
    )
    for code, out in results:
        assert code == 0, f"cluster child failed:\n{out}"
        assert "DCKPT OK" in out

    rb = distributed.SaveReader(
        os.path.join(dir_b, distributed.step_dir_name(3))
    )
    assert rb.meta_bytes() == b"meta-2proc"
    # both hosts wrote: two per-host manifests, each listing chunks
    assert len(rb.commit["manifests"]) == 2
    assert rb.commit["process_count"] == 2
    np.testing.assert_array_equal(rb.read(0), _x_global())
    np.testing.assert_array_equal(rb.read(1), _y_repl())
    for n_dev in (2, 4):
        mesh = make_mesh(devices=jax.devices()[:n_dev])
        xr = rb.read(0, sharding=NamedSharding(mesh, P("data")))
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(xr)), _x_global()
        )


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    _child_main()
