"""Exactness contract of the batched localizer (PR 13).

`eval.localize` is the oracle: the jitted Grunert P3P must reproduce its
pose slate on the same minimal samples (set-wise — f32 vs f64 LAPACK
order the companion eigenvalues differently), degenerate triples must be
masked on both sides, and with the same sample-index sequence the
fixed-schedule batched RANSAC must select the same best pose as the
NumPy reference on synthetic InLoc-scale fixtures. Compilation is pure
plumbing: jit-vs-eager and batched-vs-sequential are held to bitwise
equality, and padding to a bucket must never perturb the result.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.eval.localize import p3p_grunert, pose_distance
from ncnet_tpu.localize import (
    POSE_MATCH_BUCKETS,
    PoseRequest,
    localize_poses,
    make_ransac_step,
    pose_bucket,
    prep_pose_request,
    ransac_pose_np,
    sample_triplets,
)
from ncnet_tpu.localize.ransac import ransac_pose
from ncnet_tpu.localize.solver import p3p_solve
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.registry import default_registry

THR_RAD = np.deg2rad(0.2)
COS_THR = float(np.cos(THR_RAD))


def _random_pose(rng):
    q, _ = np.linalg.qr(rng.randn(3, 3))
    if np.linalg.det(q) < 0:
        q[:, 0] *= -1
    return q, rng.randn(3)


def _synth_matches(n, inlier_ratio, seed, noise_rad=0.0005):
    """InLoc-scale tentative set: a fraction consistent with a ground
    truth pose up to ~0.03 deg of angular noise, the rest random rays
    (the benchmark's fixture, kept in sync by hand)."""
    rng = np.random.RandomState(seed)
    r, t = _random_pose(rng)
    x = rng.randn(n, 3) * 4.0 + np.array([0, 0, 8.0])
    xc = x @ r.T + t
    rays = xc / np.linalg.norm(xc, axis=1, keepdims=True)
    rays += rng.randn(n, 3) * noise_rad
    n_out = int(n * (1.0 - inlier_ratio))
    out_idx = rng.permutation(n)[:n_out]
    rand = rng.randn(n_out, 3)
    rays[out_idx] = rand / np.linalg.norm(rand, axis=1, keepdims=True)
    p_true = np.concatenate([r, t[:, None]], axis=1)
    return rays.astype(np.float32), x.astype(np.float32), p_true


def _pad(rays, points, n_pad):
    n = len(rays)
    mask = np.zeros(n_pad, bool)
    mask[:n] = True
    rp = np.zeros((n_pad, 3), np.float32)
    pp = np.zeros((n_pad, 3), np.float32)
    rp[:n], pp[:n] = rays, points
    return rp, pp, mask


# ----------------------------------------------------------------------
# the P3P slate vs the oracle


def test_p3p_slate_matches_oracle_on_random_triples():
    """On random non-degenerate minimal samples the slate tracks the f64
    oracle as tightly as f32 conditioning allows, stated as measured
    quantiles with margin: the TRUE pose's error is ~1e-5 at the median
    and < 2e-2 at the 90th percentile (a near-double quartic root can
    blow a single minimal sample up to ~7e-2 — RANSAC's hypothesis
    redundancy absorbs those, which the fixed-sample parity test below
    pins end to end), and >= 85% of ALL oracle poses, spurious roots
    included, appear among the valid slots set-wise at 2e-2."""
    rng = np.random.RandomState(0)
    solve = jax.jit(p3p_solve)
    errs_true = []
    n_oracle, n_matched = 0, 0
    for _ in range(50):
        r, t = _random_pose(rng)
        x = rng.randn(3, 3) * 4.0 + np.array([0, 0, 8.0])
        xc = x @ r.T + t
        if np.min(np.linalg.norm(xc, axis=1)) < 0.5:
            continue  # too close to the center: ill-posed by design
        rays = xc / np.linalg.norm(xc, axis=1, keepdims=True)
        oracle_poses = p3p_grunert(rays, x)
        if not oracle_poses:
            continue
        poses, valid = solve(
            rays.astype(np.float32), x.astype(np.float32)
        )
        poses, valid = np.asarray(poses, np.float64), np.asarray(valid)
        assert valid.any()
        p_true = np.concatenate([r, t[:, None]], axis=1)
        errs_true.append(min(
            np.abs(poses[i] - p_true).max() for i in range(4) if valid[i]
        ))
        for p in oracle_poses:
            err = min(
                np.abs(poses[i] - p).max() for i in range(4) if valid[i]
            )
            n_oracle += 1
            n_matched += bool(err < 2e-2)
    errs_true = np.sort(errs_true)
    assert len(errs_true) >= 40  # the fixtures exercised the contract
    assert np.median(errs_true) < 1e-4
    assert errs_true[int(0.9 * len(errs_true))] < 2e-2
    assert errs_true[-1] < 0.2
    assert n_matched >= 0.85 * n_oracle


def test_p3p_masks_degenerate_triples():
    """Every oracle early-return is a mask bit: coincident world points
    (vanishing triangle sides) yield NO valid slot, and the masked slate
    still reads as finite identity poses — degeneracy can never NaN-
    poison a batched program."""
    rng = np.random.RandomState(1)
    f = rng.randn(3, 3)
    f /= np.linalg.norm(f, axis=1, keepdims=True)
    f = f.astype(np.float32)
    coincident = np.tile(rng.randn(1, 3), (3, 1)).astype(np.float32)
    poses, valid = p3p_solve(f, coincident)
    assert not np.asarray(valid).any()
    assert np.all(np.isfinite(np.asarray(poses)))
    np.testing.assert_array_equal(
        np.asarray(poses)[:, :, :3], np.broadcast_to(np.eye(3), (4, 3, 3))
    )
    # one repeated point: a single vanishing side must also mask
    two_dup = np.stack(
        [coincident[0], coincident[0], coincident[0] + 1.0]
    ).astype(np.float32)
    _, valid2 = p3p_solve(f, two_dup)
    assert not np.asarray(valid2).any()
    assert not p3p_grunert(np.asarray(f, np.float64),
                           np.asarray(coincident, np.float64))


# ----------------------------------------------------------------------
# fixed-sample RANSAC vs the NumPy reference


def test_fixed_sample_ransac_matches_numpy_reference():
    """Same sample-index sequence -> same best pose: identical inlier
    masks and counts, pose agreement to f32 round-off, both a hair from
    the ground truth."""
    rays, points, p_true = _synth_matches(200, 0.7, seed=2)
    rp, pp, mask = _pad(rays, points, 256)
    idx = np.asarray(
        sample_triplets(jax.random.PRNGKey(5), jnp.asarray(mask), 32)
    )
    out_j = jax.jit(functools.partial(ransac_pose, cos_thr=COS_THR))(
        rp, pp, mask, idx
    )
    out_n = ransac_pose_np(rp, pp, mask, idx, thr_rad=THR_RAD)
    assert bool(out_j["found"]) and out_n["found"]
    assert int(out_j["n_inliers"]) == int(out_n["n_inliers"])
    np.testing.assert_array_equal(
        np.asarray(out_j["inliers"]), out_n["inliers"]
    )
    p_j = np.asarray(out_j["P"], np.float64)
    assert np.abs(p_j - out_n["P"]).max() < 1e-3
    for p in (p_j, out_n["P"]):
        dp, do = pose_distance(p_true, p)
        assert dp < 1e-2 and do < 1e-2


def test_ransac_low_inlier_inloc_fixture():
    """At InLoc-typical inlier rates (~35% after the score gate) the
    batched solver still localizes: found, a dominant inlier set, pose
    near the ground truth."""
    rays, points, p_true = _synth_matches(120, 0.35, seed=7)
    rp, pp, mask = _pad(rays, points, 128)
    step = make_ransac_step(n_hypotheses=64, thr_deg=0.2)
    out = step(
        rp[None], pp[None], mask[None], np.asarray([7], np.int32)
    )
    assert bool(np.asarray(out["found"])[0])
    assert int(np.asarray(out["n_inliers"])[0]) >= 0.8 * (120 * 0.35)
    dp, do = pose_distance(
        p_true, np.asarray(out["P"], np.float64)[0]
    )
    assert dp < 0.05 and do < 0.01


def test_ransac_all_outliers_reports_not_found():
    rays, points, _ = _synth_matches(64, 0.0, seed=9)
    rp, pp, mask = _pad(rays, points, 128)
    step = make_ransac_step(n_hypotheses=16, thr_deg=0.2)
    out = step(
        rp[None], pp[None], mask[None], np.asarray([1], np.int32)
    )
    if not bool(np.asarray(out["found"])[0]):
        np.testing.assert_array_equal(
            np.asarray(out["P"])[0, :, :3], np.eye(3, dtype=np.float32)
        )
        assert not np.asarray(out["inliers"])[0].any()
    # 0% inliers can still fluke 1-2 consistent rays; the contract is
    # only that the report stays typed + finite either way
    assert np.all(np.isfinite(np.asarray(out["P"])))


# ----------------------------------------------------------------------
# compilation is pure plumbing


def test_jit_matches_eager_bitwise():
    rays, points, _ = _synth_matches(100, 0.6, seed=3)
    rp, pp, mask = _pad(rays, points, 128)
    idx = np.asarray(
        sample_triplets(jax.random.PRNGKey(11), jnp.asarray(mask), 8)
    )
    fn = functools.partial(ransac_pose, cos_thr=COS_THR)
    eager = fn(rp, pp, mask, idx)
    jitted = jax.jit(fn)(rp, pp, mask, idx)
    for k in eager:
        np.testing.assert_array_equal(
            np.asarray(eager[k]), np.asarray(jitted[k])
        )


def test_batched_matches_sequential_bitwise():
    """The vmapped batch program returns, per query, exactly what the
    batch-1 program returns — batching never perturbs a row."""
    b, n_pad, hyp = 4, 128, 16
    rp = np.zeros((b, n_pad, 3), np.float32)
    pp = np.zeros((b, n_pad, 3), np.float32)
    mask = np.zeros((b, n_pad), bool)
    for j in range(b):
        rays, points, _ = _synth_matches(90 + j, 0.5, seed=20 + j)
        rp[j], pp[j], mask[j] = _pad(rays, points, n_pad)
    seeds = np.arange(b, dtype=np.int32)
    step = make_ransac_step(n_hypotheses=hyp, thr_deg=0.2)
    out_b = step(rp, pp, mask, seeds)
    for j in range(b):
        out_1 = step(
            rp[j : j + 1], pp[j : j + 1], mask[j : j + 1],
            seeds[j : j + 1],
        )
        for k in out_b:
            np.testing.assert_array_equal(
                np.asarray(out_b[k])[j], np.asarray(out_1[k])[0]
            )


def test_padding_to_a_larger_bucket_is_invariant():
    """`sample_triplets` draws the same triplets at every bucket size for
    a fixed (key, n_valid), and the zero pad rows carry zero weight all
    the way through scoring and the DLT refit — so re-bucketing a request
    cannot change its answer."""
    rays, points, _ = _synth_matches(100, 0.6, seed=4)
    small = _pad(rays, points, 128)
    large = _pad(rays, points, 256)
    idx_s = np.asarray(
        sample_triplets(jax.random.PRNGKey(3), jnp.asarray(small[2]), 16)
    )
    idx_l = np.asarray(
        sample_triplets(jax.random.PRNGKey(3), jnp.asarray(large[2]), 16)
    )
    np.testing.assert_array_equal(idx_s, idx_l)
    fn = jax.jit(functools.partial(ransac_pose, cos_thr=COS_THR))
    out_s = fn(*small, idx_s)
    out_l = fn(*large, idx_l)
    assert int(out_s["n_inliers"]) == int(out_l["n_inliers"])
    np.testing.assert_array_equal(
        np.asarray(out_s["inliers"]), np.asarray(out_l["inliers"])[:128]
    )
    assert not np.asarray(out_l["inliers"])[128:].any()
    np.testing.assert_allclose(
        np.asarray(out_s["P"]), np.asarray(out_l["P"]), atol=1e-5
    )


# ----------------------------------------------------------------------
# request prep + the staged driver's telemetry


def test_prep_pose_request_buckets_pads_and_subsamples():
    rays, points, _ = _synth_matches(100, 0.5, seed=5)
    key, payload = prep_pose_request(PoseRequest(rays, points, seed=3))
    assert key == ("pose", 128) == pose_bucket(100)
    assert payload["rays"].shape == (128, 3)
    assert payload["mask"].sum() == 100
    assert not payload["mask"][100:].any()
    np.testing.assert_array_equal(payload["rays"][100:], 0.0)
    assert payload["seed"] == np.int32(3)
    # above the largest bucket: seeded subsample down to it
    big = POSE_MATCH_BUCKETS[-1] + 50
    rays_b = np.ones((big, 3), np.float32)
    key_b, payload_b = prep_pose_request(PoseRequest(rays_b, rays_b))
    assert key_b == ("pose", POSE_MATCH_BUCKETS[-1])
    assert payload_b["mask"].all()
    with pytest.raises(ValueError, match=r"\[n, 3\]"):
        prep_pose_request(PoseRequest(rays[:, :2], points[:, :2]))
    # the [6, n] tentative layout of the oracle round-trips
    req = PoseRequest.from_tentatives(
        np.concatenate([rays.T, points.T]), seed=1
    )
    np.testing.assert_array_equal(req.rays, rays)
    np.testing.assert_array_equal(req.points, points)


def test_localize_poses_emits_spans_and_counter():
    rays, points, _ = _synth_matches(80, 0.6, seed=6)
    rp, pp, mask = _pad(rays, points, 128)
    before = default_registry().counter(
        "localize_poses_total",
        "camera poses estimated by the batched JAX localizer",
    ).value
    trace.enable()
    try:
        out = localize_poses(
            rp[None], pp[None], mask[None],
            np.asarray([0], np.int32), n_hypotheses=8,
        )
        events = trace.drain()
    finally:
        trace.disable()
        trace.drain()
    assert bool(np.asarray(out["found"])[0])
    names = [e["name"] for e in events]
    for stage in ("localize/sample", "localize/solve", "localize/score"):
        assert stage in names
    after = default_registry().counter("localize_poses_total").value
    assert after == before + 1
