"""Runtime numerical sanitizer tests (ncnet_tpu.analysis.sanitizer).

The contract under test: taps are exact identities when disabled (zero
trace residue), and when enabled they localize an injected NaN to the
first non-finite stage in dataflow order — including through the full
instrumented train step (the `--sanitize` path of scripts/train.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.analysis import sanitizer


@pytest.fixture
def sanitized():
    """Enable for one test; restore the global default (off) afterwards."""
    sanitizer.clear(stage_order=True)
    sanitizer.enable()
    yield
    sanitizer.enable(False)
    sanitizer.clear(stage_order=True)


def test_tap_disabled_is_identity_and_silent():
    sanitizer.clear(stage_order=True)
    x = jnp.arange(4.0)
    assert sanitizer.tap("nope", x) is x
    assert sanitizer.sanitize_pytree("nope", {"a": x})["a"] is x
    assert sanitizer.reports() == []


def test_tap_records_finite_stats(sanitized):
    @jax.jit
    def f(x):
        y = sanitizer.tap("double", x * 2)
        return sanitizer.tap("out", y - 1)

    out = f(jnp.asarray([1.0, 2.0, 3.0]))
    out.block_until_ready()
    recs = sanitizer.reports()
    stages = {r["stage"] for r in recs}
    assert stages == {"double", "out"}
    by = {r["stage"]: r for r in recs}
    assert by["double"]["finite_frac"] == 1.0
    assert by["double"]["absmax"] == pytest.approx(6.0)
    assert sanitizer.first_nonfinite() is None


def test_first_nonfinite_names_earliest_dataflow_stage(sanitized):
    """A NaN born at stage b propagates to c; the report must blame b,
    not c — that IS the localization feature."""

    @jax.jit
    def f(x):
        a = sanitizer.tap("a", x * 2)
        poisoned = a + jnp.where(x > 2, jnp.nan, 0.0)
        b = sanitizer.tap("b", poisoned)
        return sanitizer.tap("c", b + 1)

    f(jnp.asarray([1.0, 2.0, 3.0])).block_until_ready()
    fnf = sanitizer.first_nonfinite()
    assert fnf is not None
    stage, rec = fnf
    assert stage == "b"
    assert rec["finite_frac"] < 1.0


def test_bf16_overflow_probe(sanitized):
    """Values finite in f32 but beyond bfloat16's largest finite value
    are flagged — the early-warning shape of an exp/product blowup."""
    sanitizer.tap("big", jnp.asarray([3.4e38], jnp.float32))
    (rec,) = [r for r in sanitizer.reports() if r["stage"] == "big"]
    assert rec["finite_frac"] == 1.0
    assert rec["bf16_overflow"]


def test_integer_leaves_pass_unprobed(sanitized):
    x = jnp.arange(5)
    assert sanitizer.tap("ints", x) is x
    assert all(r["stage"] != "ints" for r in sanitizer.reports())


def test_sanitize_pytree_names_leaves_by_path(sanitized):
    tree = {"w": jnp.ones((2, 2)), "b": jnp.zeros((2,))}
    sanitizer.sanitize_pytree("grad", tree)
    stages = {r["stage"] for r in sanitizer.reports()}
    assert stages == {"grad['w']", "grad['b']"}


def test_report_text_and_summary(sanitized):
    sanitizer.tap("s0", jnp.ones((3,)))
    sanitizer.tap("s0", jnp.ones((3,)) * 2)
    text = sanitizer.report_text()
    assert "s0" in text and "all observed stages finite" in text
    (row,) = [s for s in sanitizer.summary() if s["stage"] == "s0"]
    assert row["observations"] == 2
    assert row["absmax"] == pytest.approx(2.0)


def test_check_finite_or_report_raises_with_stage(sanitized, capsys):
    sanitizer.tap("poison", jnp.asarray([jnp.nan]))
    with pytest.raises(FloatingPointError) as e:
        sanitizer.check_finite_or_report(float("nan"), context="step 3")
    assert "poison" in str(e.value)
    assert "step 3" in str(e.value)
    assert "poison" in capsys.readouterr().out  # the per-stage table printed


def test_injected_nan_in_toy_train_step_is_localized(sanitized, capsys):
    """The `--sanitize` acceptance path: a toy train step fed a poisoned
    batch stops with the first non-finite stage named. The NaN enters
    through the source image, so the earliest instrumented stage —
    'features' — must take the blame, not the loss where it surfaces."""
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.train.step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    cfg = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    opt = make_optimizer(1e-3)
    state = create_train_state(params, opt)
    step = make_train_step(cfg, opt, donate=False)
    rng = np.random.RandomState(0)
    batch = {
        k: jnp.asarray(rng.randn(2, 48, 48, 3).astype(np.float32))
        for k in ("source_image", "target_image")
    }

    state, loss = step(state, batch)
    assert np.isfinite(float(loss))
    assert sanitizer.first_nonfinite() is None

    sanitizer.clear()  # keep trace order, drop the healthy step's records
    poisoned = dict(batch)
    poisoned["source_image"] = batch["source_image"].at[0, 0, 0, 0].set(
        jnp.nan
    )
    _, bad_loss = step(state, poisoned)
    bad = float(bad_loss)
    assert not np.isfinite(bad)
    fnf = sanitizer.first_nonfinite()
    assert fnf is not None and fnf[0] == "features"

    with pytest.raises(FloatingPointError) as e:
        sanitizer.check_finite_or_report(bad, context="toy step")
    assert "features" in str(e.value)
    capsys.readouterr()


def test_train_loop_sanitize_stops_on_nan(sanitized, capsys):
    """loop.train() under the sanitizer: a poisoned batch mid-epoch stops
    training immediately with a FloatingPointError naming the stage,
    instead of averaging NaN into the epoch metrics."""
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.train.loop import train as train_loop

    cfg = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)

    def mk(poison=False):
        img = rng.randn(2, 48, 48, 3).astype(np.float32)
        if poison:
            img[0, 0, 0, 0] = np.nan
        return {
            "source_image": img,
            "target_image": rng.randn(2, 48, 48, 3).astype(np.float32),
        }

    batches = [mk(), mk(poison=True), mk()]
    with pytest.raises(FloatingPointError) as e:
        train_loop(
            cfg, params, batches, val_loader=None, num_epochs=1,
            checkpoint_dir="/tmp/_sanitize_test_unused",
            data_parallel=False, log_every=100,
        )
    assert "first non-finite stage" in str(e.value)
    capsys.readouterr()


def test_taps_survive_loss_chunking(sanitized):
    """Taps inside the lax.map chunk loop + remat still report (twice per
    step under remat is fine); the chunked loss path is where the
    un-understood NaN config lived."""
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.train.loss import weak_loss

    cfg = ImMatchNetConfig(
        ncons_kernel_sizes=(3,), ncons_channels=(1,),
        loss_chunk=2, loss_chunk_remat=True,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(1)
    batch = {
        k: jnp.asarray(rng.randn(4, 48, 48, 3).astype(np.float32))
        for k in ("source_image", "target_image")
    }
    loss = float(weak_loss(params, cfg, batch))
    assert np.isfinite(loss)
    stages = {r["stage"] for r in sanitizer.reports()}
    for expected in ("correlation", "nc_layer0", "score_pos",
                     "score_pos_chunks", "weak_loss"):
        assert expected in stages, stages


def test_chunked_grad_keeps_score_and_grad_visibility(sanitized):
    """KNOWN LIMITATION, pinned: differentiating the no-remat chunk loop
    drops the debug callbacks staged in the lax.map primal (jax 0.4.37),
    so the in-chunk stage probes go silent — but the out-of-map probes on
    the stacked chunk outputs, the loss, and the grads must still report
    (that is the guaranteed minimum under `--sanitize` on any config)."""
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.train.loss import weak_loss

    cfg = ImMatchNetConfig(
        ncons_kernel_sizes=(3,), ncons_channels=(1,),
        loss_chunk=2, loss_chunk_remat=False,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(2)
    batch = {
        k: jnp.asarray(rng.randn(4, 48, 48, 3).astype(np.float32))
        for k in ("source_image", "target_image")
    }

    @jax.jit
    def loss_and_grad(nc):
        p = dict(params)
        p["neigh_consensus"] = nc
        return jax.value_and_grad(
            lambda n: weak_loss({**params, "neigh_consensus": n}, cfg, batch)
        )(nc)

    loss, _ = loss_and_grad(params["neigh_consensus"])
    assert np.isfinite(float(loss))
    stages = {r["stage"] for r in sanitizer.reports()}
    for expected in ("score_pos_chunks", "score_neg_chunks", "weak_loss"):
        assert expected in stages, stages
