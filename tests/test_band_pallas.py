"""Fused Pallas band-GEMM kernel (ncnet_tpu/kernels/band_gemm_pallas.py).

The contract under test, in interpret mode (CPU-exact emulation of the
kernel's arithmetic): the fused gather+GEMM+bias+ReLU layer and its
gather-only custom VJP are BITWISE-equal in eager mode to the XLA band
composite (`sparse.nc._band_conv` + bias + relu — the production path
whose backward is the shared `ops.band.band_conv_gemm` einsum), in f32
AND bf16, symmetric on/off, on rectangular grids and at full K where
the dense gemm4 lowering is the oracle. Under jit the whole-pipeline
contract relaxes to ULP-allclose (XLA refuses to promise fusion-order
stability; the chunked/remat path happens to stay bitwise and is pinned
as such). Dispatch: `resolve_band_impl` must fall back to 'xla' off-TPU
so a TPU-trained band_impl='pallas' checkpoint serves anywhere.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.kernels.band_gemm_pallas import (
    band_conv_bias_relu_pallas,
    resolve_band_impl,
)
from ncnet_tpu.models.immatchnet import (
    ImMatchNetConfig,
    init_immatchnet,
    match_pipeline,
)
from ncnet_tpu.ops.band import band_neighbor_pointers, topk_band
from ncnet_tpu.sparse.nc import _band_conv
from ncnet_tpu.train.loss import weak_loss_core

BASE = dict(ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1))


def _band_inputs(rng, b, ha, wa, hb, wb, K, cin, k, dtype=jnp.float32):
    """A random band + pointer table + layer weights at one geometry."""
    scores = jnp.asarray(
        rng.randn(b, ha, wa, hb, wb).astype(np.float32)
    )
    _, indices = topk_band(scores, K)
    n = ha * wa * min(K, hb * wb)
    x = jnp.asarray(rng.randn(b, n, cin).astype(np.float32), dtype)
    ptr = band_neighbor_pointers(indices, (hb, wb), (k, k, k, k))
    w = jnp.asarray(
        rng.randn(k, k, k, k, cin, cin) * (cin * k**4) ** -0.5, dtype
    )
    bias = jnp.asarray(rng.randn(cin) * 0.01, dtype)
    return x, ptr.reshape(b, n, -1), w, bias


def _xla_layer(x, w, bias, ptr):
    return jax.nn.relu(_band_conv(x, w, ptr) + bias.astype(x.dtype))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_forward_bitwise_eager(dtype):
    rng = np.random.RandomState(0)
    x, ptr, w, bias = _band_inputs(rng, 2, 4, 4, 4, 4, 6, 3, 3, dtype)
    out_k = band_conv_bias_relu_pallas(x, w, bias, ptr, interpret=True)
    out_x = _xla_layer(x, w, bias, ptr)
    assert out_k.dtype == out_x.dtype
    np.testing.assert_array_equal(np.asarray(out_k), np.asarray(out_x))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_vjp_bitwise_eager(dtype):
    """dx, dw, db all bitwise vs the XLA composite's custom VJP — the
    invariant that lets checkpoints hop between backends mid-training."""
    rng = np.random.RandomState(1)
    x, ptr, w, bias = _band_inputs(rng, 2, 4, 4, 4, 4, 6, 3, 3, dtype)

    def loss_k(x, w, bias):
        y = band_conv_bias_relu_pallas(x, w, bias, ptr, interpret=True)
        return jnp.sum(y.astype(jnp.float32))

    def loss_x(x, w, bias):
        return jnp.sum(_xla_layer(x, w, bias, ptr).astype(jnp.float32))

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(x, w, bias)
    gx = jax.grad(loss_x, argnums=(0, 1, 2))(x, w, bias)
    for a, b, nm in zip(gk, gx, ("dx", "dw", "db")):
        assert a.dtype == b.dtype, nm
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=nm)


def test_rectangular_grid_and_partial_band():
    """Non-square A and B grids with K < hB*wB (padding rows in play)."""
    rng = np.random.RandomState(2)
    x, ptr, w, bias = _band_inputs(rng, 2, 3, 5, 4, 2, 5, 3, 3)
    out_k = band_conv_bias_relu_pallas(x, w, bias, ptr, interpret=True)
    out_x = _xla_layer(x, w, bias, ptr)
    np.testing.assert_array_equal(np.asarray(out_k), np.asarray(out_x))


def test_small_block_rows_padding_path():
    """block_rows smaller than the entry count exercises the grid loop
    AND the ptr-row padding (n not a multiple of the block)."""
    rng = np.random.RandomState(3)
    x, ptr, w, bias = _band_inputs(rng, 1, 3, 3, 3, 3, 5, 2, 3)
    out_ref = band_conv_bias_relu_pallas(x, w, bias, ptr, interpret=True)
    out_blk = band_conv_bias_relu_pallas(
        x, w, bias, ptr, interpret=True, block_rows=7
    )
    np.testing.assert_array_equal(np.asarray(out_blk), np.asarray(out_ref))


def test_even_kernel_backward_raises():
    rng = np.random.RandomState(4)
    x, ptr, w, bias = _band_inputs(rng, 1, 3, 3, 3, 3, 4, 2, 2)

    def loss(x):
        return jnp.sum(
            band_conv_bias_relu_pallas(x, w, bias, ptr, interpret=True)
        )

    with pytest.raises(ValueError, match="odd"):
        jax.grad(loss)(x)


# --- pipeline integration: full-K exactness + jit ULP contract ---------------


@pytest.mark.parametrize("symmetric", [True, False])
def test_full_k_pipeline_bitwise_eager(symmetric):
    """band_impl='pallas' (interpret) vs 'xla' through the WHOLE sparse
    pipeline at full K, symmetric on and off."""
    cfg = ImMatchNetConfig(
        nc_topk=16, symmetric_mode=symmetric, **BASE
    )
    rng = np.random.RandomState(5)
    fa = jnp.asarray(rng.randn(2, 4, 4, 7).astype(np.float32))
    fb = jnp.asarray(rng.randn(2, 4, 4, 7).astype(np.float32))
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    nc = params["neigh_consensus"]
    out_x = np.asarray(match_pipeline(nc, cfg, fa, fb))
    with _force_interpret():
        out_p = np.asarray(
            match_pipeline(nc, cfg.replace(band_impl="pallas"), fa, fb)
        )
    np.testing.assert_array_equal(out_x, out_p)


def test_three_training_steps_bitwise_eager():
    """3 optimizer steps on the band loss: identical NC params and losses
    whether the layers run through XLA or the fused kernel."""
    cfg = ImMatchNetConfig(nc_topk=4, **BASE)
    rng = np.random.RandomState(6)
    fa = jnp.asarray(rng.randn(2, 4, 4, 7).astype(np.float32))
    fb = jnp.asarray(rng.randn(2, 4, 4, 7).astype(np.float32))
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)

    def train3(c):
        nc = params["neigh_consensus"]
        opt = optax.adam(5e-4)
        st = opt.init(nc)
        losses = []
        for _ in range(3):
            loss, g = jax.value_and_grad(
                lambda p: weak_loss_core(p, c, fa, fb)
            )(nc)
            up, st2 = opt.update(g, st, nc)
            st = st2
            nc = optax.apply_updates(nc, up)
            losses.append(np.asarray(loss))
        return losses, nc

    losses_x, nc_x = train3(cfg)
    with _force_interpret():
        losses_p, nc_p = train3(cfg.replace(band_impl="pallas"))
    np.testing.assert_array_equal(losses_x, losses_p)
    for va, vb in zip(
        jax.tree_util.tree_leaves(nc_x), jax.tree_util.tree_leaves(nc_p)
    ):
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def test_jitted_loss_ulp_allclose():
    """Under jit the contract is ULP-allclose: XLA's fusion choices may
    differ by 1 ulp between the two band lowerings (the chunked/remat
    production path stays bitwise — pinned in the chunked variant)."""
    cfg = ImMatchNetConfig(nc_topk=4, **BASE)
    rng = np.random.RandomState(7)
    fa = jnp.asarray(rng.randn(2, 4, 4, 7).astype(np.float32))
    fb = jnp.asarray(rng.randn(2, 4, 4, 7).astype(np.float32))
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    nc = params["neigh_consensus"]

    def loss(c):
        return jax.jit(
            lambda p: weak_loss_core(p, c, fa, fb)
        )(nc)

    l_x = np.asarray(loss(cfg))
    with _force_interpret():
        l_p = np.asarray(loss(cfg.replace(band_impl="pallas")))
    np.testing.assert_allclose(l_p, l_x, rtol=1e-6, atol=1e-7)


def test_jitted_chunked_loss_bitwise():
    cfg = ImMatchNetConfig(nc_topk=4, loss_chunk=1, **BASE)
    rng = np.random.RandomState(8)
    fa = jnp.asarray(rng.randn(2, 4, 4, 7).astype(np.float32))
    fb = jnp.asarray(rng.randn(2, 4, 4, 7).astype(np.float32))
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    nc = params["neigh_consensus"]

    def loss(c):
        return np.asarray(
            jax.jit(lambda p: weak_loss_core(p, c, fa, fb))(nc)
        )

    with _force_interpret():
        l_p = loss(cfg.replace(band_impl="pallas"))
    np.testing.assert_array_equal(loss(cfg), l_p)


# --- dispatch ----------------------------------------------------------------


def _force_interpret():
    """Route band_impl='pallas' to the interpret kernel on this CPU host
    (the env knob the STATUS docs as the off-TPU validation path)."""
    import os
    from contextlib import contextmanager

    @contextmanager
    def ctx():
        os.environ["NCNET_BAND_PALLAS_INTERPRET"] = "1"
        try:
            yield
        finally:
            os.environ.pop("NCNET_BAND_PALLAS_INTERPRET", None)

    return ctx()


def test_resolve_band_impl_fallback():
    """Off-TPU, 'pallas' resolves to 'xla' (clean serving fallback);
    the interpret env knob opts into the emulated kernel; 'xla' is
    always itself."""
    assert resolve_band_impl("xla") == "xla"
    if jax.default_backend() != "tpu":
        assert resolve_band_impl("pallas") == "xla"
        with _force_interpret():
            assert resolve_band_impl("pallas") == "pallas_interpret"


def test_pipeline_pallas_config_falls_back_cleanly():
    """A band_impl='pallas' config must run (via the XLA fallback) on a
    non-TPU host without the env knob — TPU-trained checkpoints stay
    servable anywhere, bitwise-identically to 'xla'."""
    cfg = ImMatchNetConfig(nc_topk=4, band_impl="pallas", **BASE)
    rng = np.random.RandomState(9)
    fa = jnp.asarray(rng.randn(1, 4, 4, 7).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 4, 4, 7).astype(np.float32))
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    nc = params["neigh_consensus"]
    out_p = np.asarray(match_pipeline(nc, cfg, fa, fb))
    out_x = np.asarray(
        match_pipeline(nc, cfg.replace(band_impl="xla"), fa, fb)
    )
    np.testing.assert_array_equal(out_p, out_x)


def test_config_rejects_unknown_band_impl():
    cfg = ImMatchNetConfig(nc_topk=4, band_impl="mosaic", **BASE)
    rng = np.random.RandomState(10)
    fa = jnp.asarray(rng.randn(1, 4, 4, 7).astype(np.float32))
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="band_impl"):
        match_pipeline(params["neigh_consensus"], cfg, fa, fa)


def test_config_roundtrip_keeps_band_impl():
    cfg = ImMatchNetConfig(nc_topk=4, band_impl="pallas", **BASE)
    assert ImMatchNetConfig.from_dict(cfg.to_dict()).band_impl == "pallas"
    # legacy checkpoint dicts (no band_impl key) get the default
    d = cfg.to_dict()
    d.pop("band_impl")
    assert ImMatchNetConfig.from_dict(d).band_impl == "xla"
