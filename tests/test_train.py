import jax
import jax.numpy as jnp
import os

import numpy as np

from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
from ncnet_tpu.parallel.mesh import make_mesh, replicate, shard_batch
from ncnet_tpu.train.loss import match_score, weak_loss
from ncnet_tpu.train.step import (
    create_train_state,
    make_eval_step,
    make_optimizer,
    make_train_step,
    trainable_subset,
)

CFG = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))


def _batch(rng, b=4, hw=64):
    return {
        "source_image": jnp.asarray(rng.randn(b, hw, hw, 3).astype(np.float32)),
        "target_image": jnp.asarray(rng.randn(b, hw, hw, 3).astype(np.float32)),
    }


def test_match_score_softmax_reference_semantics():
    """Planted-peak check of the reference score math (train.py:125-134)."""
    fs = 3
    corr = np.zeros((1, fs, fs, fs, fs), np.float32)
    corr[0, 0, 0, 0, 0] = 50.0  # near-hard max in both directions
    s = float(match_score(jnp.asarray(corr), "softmax"))
    # direction B->A: cell (0,0) of B gets score ~1, other 8 cells get 1/9
    per_dir = (1.0 + 8 * (1.0 / 9.0)) / 9.0
    np.testing.assert_allclose(s, per_dir, rtol=1e-3)


def test_weak_loss_finite_and_grad_nonzero():
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    batch = _batch(np.random.RandomState(0))
    loss = weak_loss(params, CFG, batch)
    assert np.isfinite(float(loss))

    def f(nc):
        p = dict(params)
        p["neigh_consensus"] = nc
        return weak_loss(p, CFG, batch)

    g = jax.grad(f)(params["neigh_consensus"])
    gnorm = sum(float(jnp.sum(jnp.abs(x))) for x in jax.tree.leaves(g))
    assert gnorm > 0


def test_weak_loss_uint8_batch_matches_host_normalized():
    """A uint8 batch (the loader's ``uint8_output`` 4x-H2D-saving path)
    must produce the same loss as host-side ImageNet normalization of the
    same integer pixels — the on-device normalize in weak_loss is keyed
    on batch dtype."""
    from ncnet_tpu.data.images import normalize_image_np

    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    rng = np.random.RandomState(7)
    u8 = {
        "source_image": rng.randint(0, 256, (4, 64, 64, 3)).astype(np.uint8),
        "target_image": rng.randint(0, 256, (4, 64, 64, 3)).astype(np.uint8),
    }
    host = {
        k: jnp.asarray(
            np.stack([normalize_image_np(img.astype(np.float32))
                      for img in v])
        )
        for k, v in u8.items()
    }
    dev = {k: jnp.asarray(v) for k, v in u8.items()}
    l_host = float(weak_loss(params, CFG, host))
    l_dev = float(weak_loss(params, CFG, dev))
    np.testing.assert_allclose(l_dev, l_host, rtol=1e-5, atol=1e-6)

    # MIXED batch (a hand-built loader): each image keyed on its OWN
    # dtype — the already-normalized float half must not be ImageNet-
    # normalized a second time
    mixed = {
        "source_image": dev["source_image"],  # uint8
        "target_image": host["target_image"],  # float, pre-normalized
    }
    l_mixed = float(weak_loss(params, CFG, mixed))
    np.testing.assert_allclose(l_mixed, l_host, rtol=1e-5, atol=1e-6)


def test_image_pair_dataset_uint8_output():
    """uint8_output returns rounded resized pixels, dtype uint8."""
    import tempfile

    from PIL import Image

    from ncnet_tpu.data.pairs import ImagePairDataset

    with tempfile.TemporaryDirectory() as root:
        rng = np.random.RandomState(0)
        for n in ("a.png", "b.png"):
            Image.fromarray(
                rng.randint(0, 255, (50, 40, 3), np.uint8)
            ).save(f"{root}/{n}")
        with open(f"{root}/pairs.csv", "w") as f:
            f.write("source_image,target_image,class,flip\na.png,b.png,1,0\n")
        ds8 = ImagePairDataset(f"{root}/pairs.csv", root,
                               output_size=(32, 32), uint8_output=True)
        ds32 = ImagePairDataset(f"{root}/pairs.csv", root,
                                output_size=(32, 32), normalize=False)
        s8, s32 = ds8[0], ds32[0]
        assert s8["source_image"].dtype == np.uint8
        np.testing.assert_allclose(
            s8["source_image"].astype(np.float32),
            np.rint(np.clip(s32["source_image"], 0, 255)),
        )


def test_train_step_updates_only_head():
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    opt = make_optimizer(1e-3)
    state = create_train_state(params, opt)
    step = make_train_step(CFG, opt, donate=False)
    batch = _batch(np.random.RandomState(1))
    new_state, loss = step(state, batch)
    assert np.isfinite(float(loss))
    # head moved
    before = jax.tree.leaves(params["neigh_consensus"])
    after = jax.tree.leaves(new_state.params["neigh_consensus"])
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b)) for a, b in zip(before, after)
    )
    # trunk untouched
    tb = jax.tree.leaves(params["feature_extraction"])
    ta = jax.tree.leaves(new_state.params["feature_extraction"])
    for a, b in zip(tb, ta):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(new_state.step) == 1


def test_train_step_data_parallel_matches_single_device():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    opt = make_optimizer(1e-3)
    batch = _batch(np.random.RandomState(2), b=8)

    state1 = create_train_state(params, opt)
    step1 = make_train_step(CFG, opt, donate=False)
    _, loss_single = step1(state1, batch)

    mesh = make_mesh()
    state8 = create_train_state(replicate(mesh, params), opt)
    state8 = state8._replace(opt_state=replicate(mesh, state8.opt_state))
    sharded = shard_batch(mesh, batch)
    step8 = make_train_step(CFG, opt, donate=False)
    new8, loss_dp = step8(state8, sharded)

    # losses at random init are ~1e-6; allow cross-device reduction-order noise
    np.testing.assert_allclose(float(loss_dp), float(loss_single), atol=1e-7)


def test_eval_step_matches_loss():
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    batch = _batch(np.random.RandomState(3))
    ev = make_eval_step(CFG)
    np.testing.assert_allclose(
        float(ev(params, batch)), float(weak_loss(params, CFG, batch)), atol=1e-7
    )


def test_checkpoint_resume_with_opt_state(tmp_path):
    from ncnet_tpu.train.checkpoint import (
        CheckpointData,
        load_checkpoint,
        save_checkpoint,
    )

    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    opt = make_optimizer(1e-3)
    state = create_train_state(params, opt)
    step = make_train_step(CFG, opt, donate=False)
    batch = _batch(np.random.RandomState(4))
    state, _ = step(state, batch)

    path = str(tmp_path / "ck.msgpack")
    save_checkpoint(
        path,
        CheckpointData(
            config=CFG, params=state.params, opt_state=state.opt_state, step=1
        ),
    )
    fresh_opt_state = opt.init(trainable_subset(params))
    loaded = load_checkpoint(path, opt_state_target=fresh_opt_state)
    assert loaded.step == 1
    import chex

    chex.assert_trees_all_close(
        loaded.opt_state, jax.tree.map(np.asarray, state.opt_state)
    )


def test_fe_finetune_updates_only_tail_blocks():
    """fe_finetune_params semantics (reference train.py:60-63): the last N
    blocks of the trunk's final stage train; everything earlier stays
    frozen."""
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    opt = make_optimizer(1e-3)
    state = create_train_state(params, opt, fe_finetune_blocks=2)
    step = make_train_step(CFG, opt, donate=False, fe_finetune_blocks=2)
    new_state, loss = step(state, _batch(np.random.RandomState(6)))
    assert np.isfinite(float(loss))

    old_l3 = params["feature_extraction"]["layer3"]
    new_l3 = new_state.params["feature_extraction"]["layer3"]
    # last 2 blocks moved
    for ob, nb in zip(old_l3[-2:], new_l3[-2:]):
        assert any(
            not np.allclose(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(ob), jax.tree.leaves(nb))
        )
    # earlier blocks and stages frozen
    for ob, nb in zip(old_l3[:-2], new_l3[:-2]):
        for a, b in zip(jax.tree.leaves(ob), jax.tree.leaves(nb)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for key in ("conv1", "bn1", "layer1", "layer2"):
        for a, b in zip(
            jax.tree.leaves(params["feature_extraction"][key]),
            jax.tree.leaves(new_state.params["feature_extraction"][key]),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # NC head still trains
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(params["neigh_consensus"]),
            jax.tree.leaves(new_state.params["neigh_consensus"]),
        )
    )


def test_chunked_loss_with_save_policy_matches_unchunked():
    """The loss_chunk + save_only_these_names('nc_conv') remat path must be
    a pure performance transform: loss AND gradients identical to the
    unchunked path (locks in the checkpoint_name contract between
    train/loss.py and neigh_consensus_apply)."""
    cfg_chunked = CFG.replace(loss_chunk=2, loss_chunk_remat=True)
    params = init_immatchnet(jax.random.PRNGKey(5), CFG)
    batch = _batch(np.random.RandomState(5), b=4)

    def loss_of(cfg):
        def f(nc):
            p = dict(params)
            p["neigh_consensus"] = nc
            return weak_loss(p, cfg, batch)

        return f

    l_plain = float(weak_loss(params, CFG, batch))
    l_chunk = float(weak_loss(params, cfg_chunked, batch))
    np.testing.assert_allclose(l_chunk, l_plain, rtol=1e-5, atol=1e-8)

    g_plain = jax.grad(loss_of(CFG))(params["neigh_consensus"])
    g_chunk = jax.grad(loss_of(cfg_chunked))(params["neigh_consensus"])
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_chunk)):
        # atol covers f32 reduction-order noise on ~1e-4 magnitude grads
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-7
        )


def test_bf16_three_step_drill_f32_master_params():
    """The mixed-precision contract of the default train path (see
    make_train_step): 3 bf16 steps with the sanitizer armed — every
    staged probe finite, the loss f32 and finite each step, and the
    MASTER params + optimizer state f32 throughout (bf16 lives only
    inside the pipeline; checkpoints never hold bf16 weights)."""
    from ncnet_tpu.analysis import sanitizer

    cfg = CFG.replace(half_precision=True)
    params = init_immatchnet(jax.random.PRNGKey(3), cfg)
    opt = make_optimizer(1e-3)
    state = create_train_state(params, opt)
    batch = _batch(np.random.RandomState(3))
    sanitizer.clear(stage_order=True)
    sanitizer.enable()
    try:
        step = make_train_step(cfg, opt, donate=False)
        for i in range(3):
            state, loss = step(state, batch)
            loss_host = np.asarray(loss)
            assert loss_host.dtype == np.float32
            assert np.isfinite(float(loss_host)), f"step {i}"
        jax.block_until_ready(state)
        assert sanitizer.first_nonfinite() is None, sanitizer.report_text()
        assert any(
            r["stage"] == "features" for r in sanitizer.reports()
        ), "bf16 pipeline probes never fired"
    finally:
        sanitizer.enable(False)
        sanitizer.clear(stage_order=True)
    for leaf in jax.tree.leaves(state.params):
        assert leaf.dtype == jnp.float32
    for leaf in jax.tree.leaves(state.opt_state):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32
    # and the params actually moved — the f32 masters are being trained
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(params["neigh_consensus"]),
            jax.tree.leaves(state.params["neigh_consensus"]),
        )
    )


def test_synthetic_convergence_slow():
    """End-to-end learning proof (VERDICT r1 item 3): loss decreases and
    the synthetic keypoint-transfer PCK improves over training. Slow
    (~minutes); opt in with NCNET_RUN_SLOW=1. The driver-runnable form is
    scripts/synthetic_convergence.py."""
    import pytest

    if not os.environ.get("NCNET_RUN_SLOW"):
        pytest.skip("slow test; set NCNET_RUN_SLOW=1")
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "synthetic_convergence",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
            "synthetic_convergence.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run(
        image_size=96, steps=60, batch=4, n_pairs=16, log_every=20,
        verbose=False,
    )
    assert out["loss_last"] < out["loss_first"]
    assert out["pck_after"] > out["pck_before"]


def test_prefetch_device_batches_order_and_count():
    """The H2D double-buffer must preserve batch order and count, and
    handle empty and shorter-than-depth loaders."""
    from ncnet_tpu.train.loop import _prefetch_device_batches

    def loader(n):
        return [
            {"source_image": np.full((1, 4, 4, 3), i, np.float32),
             "target_image": np.full((1, 4, 4, 3), -i, np.float32)}
            for i in range(n)
        ]

    for n in (0, 1, 2, 5):
        out = list(_prefetch_device_batches(None, loader(n)))
        assert len(out) == n
        for i, b in enumerate(out):
            assert float(b["source_image"][0, 0, 0, 0]) == i
            assert float(b["target_image"][0, 0, 0, 0]) == -i


def test_loss_log_converts_each_loss_exactly_once():
    """The mid-epoch snapshot path (loop._LossLog) must transfer each
    device loss to host EXACTLY once, however many times the host list is
    requested — the old code re-float()ed the whole prefix per snapshot,
    O(n^2) D2H syncs per epoch."""
    from ncnet_tpu.train.loop import _LossLog

    conversions = []

    class FakeDeviceScalar:
        def __init__(self, v):
            self.v = v

        def __float__(self):
            conversions.append(self.v)
            return self.v

    log = _LossLog(seed_losses=[1.0, 2.0])  # seeded host floats: no syncs
    for i in range(5):
        log.append(FakeDeviceScalar(float(i)))
        # a snapshot after every step — the worst case for the old code
        assert log.host() == [1.0, 2.0] + [float(j) for j in range(i + 1)]
        assert len(log) == 2 + i + 1
    # 5 appends, 5 snapshots, exactly 5 conversions (not 1+2+3+4+5)
    assert conversions == [0.0, 1.0, 2.0, 3.0, 4.0]
    assert log.host() == [1.0, 2.0, 0.0, 1.0, 2.0, 3.0, 4.0]
    assert len(conversions) == 5


def test_train_loop_persists_metrics_and_curve(tmp_path):
    """One tiny epoch end-to-end through loop.train(): metrics.jsonl and
    loss_curve.png are written next to the checkpoint (SURVEY §5 — the
    reference is print-only)."""
    import json

    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.train.loop import train as train_loop

    cfg = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)

    def batches(n):
        return [
            {"source_image": rng.randn(2, 48, 48, 3).astype(np.float32),
             "target_image": rng.randn(2, 48, 48, 3).astype(np.float32)}
            for _ in range(n)
        ]

    train_loop(
        cfg, params, batches(2), val_loader=batches(1), num_epochs=2,
        checkpoint_dir=str(tmp_path), data_parallel=False, log_every=100,
    )
    lines = [
        json.loads(l)
        for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    assert [l["epoch"] for l in lines] == [1, 2]
    assert all(np.isfinite(l["train_loss"]) for l in lines)
    assert all(np.isfinite(l["val_loss"]) for l in lines)
    assert lines[-1]["steps"] == 4
    assert (tmp_path / "loss_curve.png").stat().st_size > 1000

    # a fresh run into the same dir truncates (no epoch mixing), and a
    # missing val loader serializes as strict-JSON null, not bare NaN
    params2 = init_immatchnet(jax.random.PRNGKey(1), cfg)  # first run's
    # params were donated to its jitted step
    train_loop(
        cfg, params2, batches(1), val_loader=None, num_epochs=1,
        checkpoint_dir=str(tmp_path), data_parallel=False, log_every=100,
    )
    text = (tmp_path / "metrics.jsonl").read_text()
    assert "NaN" not in text
    lines = [json.loads(l) for l in text.splitlines()]
    assert [l["epoch"] for l in lines] == [1]
    assert lines[0]["val_loss"] is None
