import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.eval.inloc import (
    match_pair,
    make_match_fn,
    n_match_slots,
    quantized_resize_shape,
    recenter,
)
from ncnet_tpu.eval.pf_pascal import evaluate, make_pck_step
from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet

TINY = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))


def write_shortlist(path, queries):
    """Write an InLoc retrieval-shortlist .mat in the schema `dump_matches`
    parses: a MATLAB struct array ``ImgList[0, q]`` with the query
    filename at field 0 and the pano shortlist at field 1.

    ``queries``: list of ``(query_name, [pano_names])``.
    """
    from scipy.io import savemat

    dt = np.dtype([("queryname", object), ("topN", object)])
    entries = np.zeros((1, len(queries)), dt)
    for q, (qname, panos) in enumerate(queries):
        entries[0, q] = (
            np.array([qname], object),
            np.array([[p] for p in panos], object),
        )
    savemat(path, {"ImgList": entries})


def test_quantized_resize_shape_reference_formula():
    # reference formula (eval_inloc.py:84-89) on a 1600x1200 image at
    # image_size=3200, k=2: ratio 0.5 -> 3200x2400 -> quantized to 32-mult.
    h, w = quantized_resize_shape(1600, 1200, 3200, 2)
    assert h % 32 == 0 and w % 32 == 0
    s = 0.0625
    want_h = int(np.floor(1600 / (1600 / 3200) * s / 2) / s * 2)
    want_w = int(np.floor(1200 / (1600 / 3200) * s / 2) / s * 2)
    assert (h, w) == (want_h, want_w)
    # k=1: plain aspect-preserving resize
    assert quantized_resize_shape(1600, 1200, 3200, 1) == (3200, 2400)


def test_n_match_slots():
    # reference N formula (eval_inloc.py:116-118)
    n = n_match_slots(3200, 2, both_directions=True)
    g = 3200 * 0.0625 / 2
    assert n == 2 * int(g * np.floor(g * 0.75))


def test_recenter():
    # grid of 4 cells: corner 0 -> cell center 1/8
    assert np.isclose(recenter(np.float32(0.0), 4), 0.125)
    assert np.isclose(recenter(np.float32(1.0), 4), 1 - 0.125)


@pytest.fixture(scope="module")
def tiny():
    return init_immatchnet(jax.random.PRNGKey(0), TINY)


def test_match_pair_rectangular(tiny):
    rng = np.random.RandomState(0)
    src = jnp.asarray(rng.randn(1, 64, 96, 3).astype(np.float32))
    tgt = jnp.asarray(rng.randn(1, 96, 64, 3).astype(np.float32))
    fn = jax.jit(make_match_fn(TINY))
    xa, ya, xb, yb, score = match_pair(fn, tiny, src, tgt, k_size=0)
    # both directions, deduped: between max(grid) and sum of both grids
    assert 24 <= len(xa) <= 48
    for v in (xa, ya, xb, yb):
        assert np.all((v >= 0) & (v <= 1))
    # descending score order after sort+dedup is not guaranteed post-unique;
    # but scores must be valid probabilities after softmax
    assert np.all(score >= 0) and np.all(score <= 1)


def test_match_fn_softmax_toggle(tiny):
    """--softmax False (reference eval_inloc.py flag): raw correlation
    scores instead of softmax probabilities — coordinates unchanged."""
    rng = np.random.RandomState(5)
    src = jnp.asarray(rng.randn(1, 64, 64, 3).astype(np.float32))
    tgt = jnp.asarray(rng.randn(1, 64, 64, 3).astype(np.float32))
    fwd_sm, _ = jax.jit(make_match_fn(TINY, softmax=True))(tiny, src, tgt)
    fwd_raw, _ = jax.jit(make_match_fn(TINY, softmax=False))(tiny, src, tgt)
    # same argmax coordinates (softmax is monotone along the source dim
    # it normalizes, so the per-cell best match is unchanged)...
    for a, b in zip(fwd_sm[:4], fwd_raw[:4]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # ...different score scale (probabilities vs raw correlations)
    assert not np.allclose(np.asarray(fwd_sm[4]), np.asarray(fwd_raw[4]))


def test_match_pair_relocalization(tiny):
    cfg = TINY.replace(relocalization_k_size=2)
    rng = np.random.RandomState(1)
    src = jnp.asarray(rng.randn(1, 128, 128, 3).astype(np.float32))
    tgt = jnp.asarray(rng.randn(1, 128, 128, 3).astype(np.float32))
    fn = jax.jit(make_match_fn(cfg))
    xa, ya, xb, yb, score = match_pair(fn, tiny, src, tgt, k_size=2)
    assert np.all((xa >= 0) & (xa <= 1))


def test_pck_eval_pipeline(tiny):
    """Identity pairs + bypassed NC should give near-perfect PCK; with the
    random NC head the pipeline must still run end-to-end."""
    rng = np.random.RandomState(0)
    img = rng.rand(1, 64, 64, 3).astype(np.float32)
    batch = {
        "source_image": jnp.asarray(img),
        "target_image": jnp.asarray(img),
        "source_points": jnp.asarray([[[10, 40, -1], [12, 30, -1]]], jnp.float32),
        "target_points": jnp.asarray([[[10, 40, -1], [12, 30, -1]]], jnp.float32),
        "source_im_size": jnp.asarray([[64, 64, 3]], jnp.float32),
        "target_im_size": jnp.asarray([[64, 64, 3]], jnp.float32),
        "L_pck": jnp.asarray([[224.0]], jnp.float32),
    }
    step = make_pck_step(TINY)
    out = np.asarray(step(tiny, batch))
    assert out.shape == (1,)
    assert 0.0 <= float(out[0]) <= 1.0


def test_dump_matches_contract(tiny, tmp_path):
    """End-to-end .mat dump with a synthetic shortlist: the [1,Npanos,N,5]
    contract consumed by lib_matlab (SURVEY.md §1 L6)."""
    from scipy.io import loadmat

    from ncnet_tpu.eval.inloc import dump_matches

    rng = np.random.RandomState(0)
    qdir = tmp_path / "query"
    pdir = tmp_path / "pano"
    qdir.mkdir()
    pdir.mkdir()
    from PIL import Image

    for d, name in ((qdir, "q0.png"), (pdir, "p0.png"), (pdir, "p1.png")):
        Image.fromarray(
            rng.randint(0, 255, (80, 60, 3), np.uint8)
        ).save(d / name)

    shortlist = tmp_path / "shortlist.mat"
    write_shortlist(shortlist, [("q0.png", ["p0.png", "p1.png"])])

    cfg = TINY.replace(relocalization_k_size=2)
    out_dir = tmp_path / "matches"
    dump_matches(
        tiny,
        cfg,
        shortlist_path=str(shortlist),
        query_path=str(qdir),
        pano_path=str(pdir),
        output_dir=str(out_dir),
        image_size=128,
        n_queries=1,
        n_panos=2,
        verbose=False,
    )
    out = loadmat(out_dir / "1.mat")
    n_slots = n_match_slots(128, 2, True)
    assert out["matches"].shape == (1, 2, n_slots, 5)
    assert np.all(out["matches"][..., :4] >= 0)
    assert np.all(out["matches"][..., :4] <= 1)
    # at least some slots filled for both panos
    assert (np.abs(out["matches"][0, 0]).sum() > 0)
    assert (np.abs(out["matches"][0, 1]).sum() > 0)


def test_dump_matches_multi_query_pipeline(tiny, tmp_path):
    """Three queries with distinct panos: the 1-pair-behind consume loop
    must route every pair's matches into the right query's matrix across
    query boundaries (pair i is consumed while pair i+1 — possibly of
    the NEXT query — is already dispatched), and per-query .mat files
    must land under the right names with per-query distinct content."""
    from PIL import Image
    from scipy.io import loadmat

    from ncnet_tpu.eval.inloc import dump_matches

    rng = np.random.RandomState(21)
    qdir, pdir = tmp_path / "query", tmp_path / "pano"
    qdir.mkdir()
    pdir.mkdir()
    n_q, n_p = 3, 2
    shortlists = []
    for q in range(n_q):
        Image.fromarray(
            rng.randint(0, 255, (80, 60, 3), np.uint8)
        ).save(qdir / f"q{q}.png")
        names = []
        for j in range(n_p):
            name = f"p{q}_{j}.png"
            Image.fromarray(
                rng.randint(0, 255, (64, 96, 3), np.uint8)
            ).save(pdir / name)
            names.append(name)
        shortlists.append(names)
    write_shortlist(
        tmp_path / "shortlist.mat",
        [(f"q{q}.png", shortlists[q]) for q in range(n_q)],
    )

    cfg = TINY.replace(relocalization_k_size=2)
    out_dir = tmp_path / "matches"
    dump_matches(
        tiny,
        cfg,
        shortlist_path=str(tmp_path / "shortlist.mat"),
        query_path=str(qdir),
        pano_path=str(pdir),
        output_dir=str(out_dir),
        image_size=128,
        n_queries=n_q,
        n_panos=n_p,
        verbose=False,
        device_preprocess=True,
        device_resize=True,
    )
    outs = [loadmat(out_dir / f"{q + 1}.mat") for q in range(n_q)]
    n_slots = n_match_slots(128, 2, True)
    for q, out in enumerate(outs):
        assert out["matches"].shape == (1, n_p, n_slots, 5)
        assert str(np.ravel(out["query_fn"])[0]).strip() == f"q{q}.png"
        for j in range(n_p):
            assert np.abs(out["matches"][0, j]).sum() > 0
    # distinct inputs -> distinct match score patterns per query (would
    # fail if the pipeline wrote one query's pairs into another's matrix)
    scores = [out["matches"][0, :, :, 4].copy() for out in outs]
    for a in range(n_q):
        for b in range(a + 1, n_q):
            assert not np.allclose(scores[a], scores[b]), (a, b)


def test_dump_matches_crash_safe_resume(tiny, tmp_path, monkeypatch):
    """A crash mid-savemat must not leave a file resume would trust: the
    write goes to a temp name + atomic rename (round-4 weakness #6), and
    stale temp files from a killed run are cleaned up on start."""
    import scipy.io

    from PIL import Image
    from scipy.io import loadmat, savemat

    from ncnet_tpu.eval.inloc import dump_matches

    rng = np.random.RandomState(5)
    qdir, pdir = tmp_path / "query", tmp_path / "pano"
    qdir.mkdir()
    pdir.mkdir()
    Image.fromarray(rng.randint(0, 255, (70, 60, 3), np.uint8)).save(
        qdir / "q0.png"
    )
    Image.fromarray(rng.randint(0, 255, (70, 60, 3), np.uint8)).save(
        pdir / "p0.png"
    )
    write_shortlist(tmp_path / "shortlist.mat", [("q0.png", ["p0.png"])])

    out_dir = tmp_path / "matches"
    out_dir.mkdir()
    # a guaranteed-DEAD owner pid: spawn and reap a child (pid 999 or any
    # literal could be a live process on a full host, and the cleanup
    # correctly leaves live owners' temps alone)
    import subprocess

    child = subprocess.Popen(["true"])
    child.wait()
    dead_pid = child.pid
    stale = out_dir / f"1.mat.tmp.{dead_pid}"
    stale.write_bytes(b"torn write from a killed run")

    kw = dict(
        shortlist_path=str(tmp_path / "shortlist.mat"),
        query_path=str(qdir),
        pano_path=str(pdir),
        output_dir=str(out_dir),
        image_size=64,
        n_queries=1,
        n_panos=1,
        verbose=False,
    )

    real_savemat = scipy.io.savemat

    def crashing_savemat(path, *a, **k):
        real_savemat(path, *a, **k)  # the bytes DID hit the temp file
        raise OSError("simulated crash mid-write")

    cfg = TINY.replace(relocalization_k_size=1)
    monkeypatch.setattr(scipy.io, "savemat", crashing_savemat)
    with pytest.raises(OSError, match="simulated crash"):
        dump_matches(tiny, cfg, **kw)
    assert not (out_dir / "1.mat").exists()  # resume can't see a torn file
    assert not stale.exists()  # stale temp cleaned on start
    assert list(out_dir.iterdir()) == []  # and no new temp left behind

    monkeypatch.setattr(scipy.io, "savemat", real_savemat)
    dump_matches(tiny, cfg, **kw)  # resume completes the query
    out = loadmat(out_dir / "1.mat")
    assert out["matches"].shape[0:2] == (1, 1)


def test_device_preprocess_matches_host_path(tiny, tmp_path):
    """The uint8 + on-device-normalize dump path (round 4, a 4x H2D
    saving on tunneled hosts) must agree with the host-fp32 path to
    within the uint8 rounding of resized pixels: same match INDICES,
    scores within a loose tolerance."""
    from PIL import Image

    from ncnet_tpu.eval.inloc import load_and_preprocess

    rng = np.random.RandomState(3)
    p = tmp_path / "img.png"
    Image.fromarray(rng.randint(0, 255, (70, 90, 3), np.uint8)).save(p)
    p2 = tmp_path / "img2.png"
    Image.fromarray(rng.randint(0, 255, (80, 64, 3), np.uint8)).save(p2)

    host = [load_and_preprocess(str(q), 64, 1) for q in (p, p2)]
    dev = [
        load_and_preprocess(str(q), 64, 1, device_normalize=True)
        for q in (p, p2)
    ]
    assert dev[0].dtype == np.uint8
    assert dev[0].shape == host[0].shape

    fn_host = make_match_fn(TINY)
    fn_dev = make_match_fn(TINY, device_preprocess=True)
    out_h = match_pair(
        fn_host, tiny, jnp.asarray(host[0]), jnp.asarray(host[1]), 0
    )
    out_d = match_pair(
        fn_dev, tiny, jnp.asarray(dev[0]), jnp.asarray(dev[1]), 0
    )
    # match_pair's sort+dedup makes element ORDER (and possibly length)
    # depend on tiny score perturbations, so compare the match SETS:
    # nearly all (xa, ya, xb, yb) rows must coincide
    rows_h = {tuple(np.round(r, 6)) for r in np.stack(out_h[:4], axis=1)}
    rows_d = {tuple(np.round(r, 6)) for r in np.stack(out_d[:4], axis=1)}
    overlap = len(rows_h & rows_d) / max(len(rows_h), 1)
    assert overlap > 0.9, (overlap, len(rows_h), len(rows_d))
    # score distributions agree in scale
    assert abs(float(np.mean(out_h[4])) - float(np.mean(out_d[4]))) < 0.05


def test_device_resize_matches_host_resize(tmp_path):
    """The on-device pano upscale (`device_resize`, round 5 — ships the
    uint8 ORIGINAL and bilinear-resizes on device, ~4x less H2D) must
    produce the same uint8 bucket image as the host resize path, up to
    float-order rounding at rint boundaries (<=1 gray level, rare)."""
    from PIL import Image

    from ncnet_tpu.eval.inloc import (
        device_resize_uint8,
        load_and_preprocess,
        quantized_resize_shape,
    )

    rng = np.random.RandomState(7)
    p = tmp_path / "pano.png"
    # small "pano": upscaled by the bucket rule (image_size > max side)
    Image.fromarray(rng.randint(0, 255, (48, 64, 3), np.uint8)).save(p)

    host = load_and_preprocess(str(p), 128, 1, device_normalize=True)
    dev, target_hw = load_and_preprocess(
        str(p), 128, 1, device_normalize=True, device_resize=True
    )
    assert dev.dtype == np.uint8 and dev.shape == (1, 48, 64, 3)
    assert target_hw == quantized_resize_shape(48, 64, 128, 1)
    resized = np.asarray(device_resize_uint8(jnp.asarray(dev), *target_hw))
    assert resized.shape == host.shape
    diff = np.abs(resized.astype(np.int32) - host.astype(np.int32))
    assert diff.max() <= 1
    assert (diff > 0).mean() < 0.01, (diff > 0).mean()

    # downscale (a "query"): device_resize falls back to the host resize
    # and the wire image IS the bucket image
    q = tmp_path / "query.png"
    Image.fromarray(rng.randint(0, 255, (200, 160, 3), np.uint8)).save(q)
    host_q = load_and_preprocess(str(q), 128, 1, device_normalize=True)
    dev_q, hw_q = load_and_preprocess(
        str(q), 128, 1, device_normalize=True, device_resize=True
    )
    assert hw_q is None
    np.testing.assert_array_equal(dev_q, host_q)


def test_dump_matches_device_resize_requires_preprocess(tiny, tmp_path):
    from ncnet_tpu.eval.inloc import dump_matches

    with pytest.raises(ValueError, match="device_resize requires"):
        dump_matches(
            tiny, TINY, shortlist_path="unused", query_path="unused",
            pano_path="unused", output_dir=str(tmp_path / "m"),
            device_preprocess=False, device_resize=True,
        )


def test_dump_matches_device_resize_equivalent(tiny, tmp_path):
    """`dump_matches(device_resize=True)` writes the same matches as the
    plain device-preprocess path on an upscale-bound pair."""
    from PIL import Image
    from scipy.io import loadmat

    from ncnet_tpu.eval.inloc import dump_matches

    rng = np.random.RandomState(11)
    qdir, pdir = tmp_path / "query", tmp_path / "pano"
    qdir.mkdir()
    pdir.mkdir()
    # both below the 128 bucket -> both take the device-resize branch
    Image.fromarray(rng.randint(0, 255, (60, 80, 3), np.uint8)).save(
        qdir / "q0.png"
    )
    Image.fromarray(rng.randint(0, 255, (52, 72, 3), np.uint8)).save(
        pdir / "p0.png"
    )
    write_shortlist(tmp_path / "shortlist.mat", [("q0.png", ["p0.png"])])

    cfg = TINY.replace(relocalization_k_size=2)
    outs = {}
    for name, dr in (("plain", False), ("device_resize", True)):
        out_dir = tmp_path / f"matches_{name}"
        dump_matches(
            tiny,
            cfg,
            shortlist_path=str(tmp_path / "shortlist.mat"),
            query_path=str(qdir),
            pano_path=str(pdir),
            output_dir=str(out_dir),
            image_size=128,
            n_queries=1,
            n_panos=1,
            verbose=False,
            device_preprocess=True,
            device_resize=dr,
        )
        outs[name] = loadmat(out_dir / "1.mat")["matches"]
    a, b = outs["plain"], outs["device_resize"]
    assert a.shape == b.shape
    # same match coordinate sets (order may differ on score ties); the
    # <=1-gray-level resize delta can perturb scores marginally
    rows_a = {tuple(np.round(r[:4], 6)) for r in a[0, 0] if np.any(r)}
    rows_b = {tuple(np.round(r[:4], 6)) for r in b[0, 0] if np.any(r)}
    overlap = len(rows_a & rows_b) / max(len(rows_a), 1)
    assert overlap > 0.9, (overlap, len(rows_a), len(rows_b))


def test_dump_matches_feature_store_matches_image_path(tiny, tmp_path):
    """The gallery-feature-store dump (ROADMAP InLoc open item) must
    produce the SAME .mat matches as the image-path dump — the store only
    moves the trunk forward out of the per-pair loop — and a second run
    must serve every pano from the store (zero trunk reruns), enforced
    here by deleting the pano images before the rerun."""
    import os

    from PIL import Image
    from scipy.io import loadmat

    from ncnet_tpu.eval.inloc import dump_matches
    from ncnet_tpu.features import FeatureCacheMismatch, GalleryFeatureStore

    rng = np.random.RandomState(3)
    qdir, pdir = tmp_path / "query", tmp_path / "pano"
    qdir.mkdir()
    pdir.mkdir()
    for d, name in ((qdir, "q0.png"), (pdir, "p0.png"), (pdir, "p1.png")):
        Image.fromarray(
            rng.randint(0, 255, (80, 60, 3), np.uint8)
        ).save(d / name)
    shortlist = tmp_path / "shortlist.mat"
    write_shortlist(shortlist, [("q0.png", ["p0.png", "p1.png"])])

    cfg = TINY.replace(relocalization_k_size=2)
    common = dict(
        shortlist_path=str(shortlist), query_path=str(qdir),
        pano_path=str(pdir), image_size=128, n_queries=1, n_panos=2,
        verbose=False,
    )
    dump_matches(tiny, cfg, output_dir=str(tmp_path / "img"), **common)
    store_dir = tmp_path / "gallery"
    dump_matches(
        tiny, cfg, output_dir=str(tmp_path / "st"),
        feature_store_dir=str(store_dir), **common,
    )
    img = loadmat(tmp_path / "img" / "1.mat")["matches"]
    st = loadmat(tmp_path / "st" / "1.mat")["matches"]
    np.testing.assert_allclose(st, img, rtol=1e-5, atol=1e-6)

    # rerun from the populated store with the pano IMAGES GONE: every
    # pano must come from the durable shards
    os.unlink(pdir / "p0.png")
    os.unlink(pdir / "p1.png")
    dump_matches(
        tiny, cfg, output_dir=str(tmp_path / "st2"),
        feature_store_dir=str(store_dir), **common,
    )
    st2 = loadmat(tmp_path / "st2" / "1.mat")["matches"]
    np.testing.assert_allclose(st2, st, rtol=0, atol=0)

    # a store extracted under a DIFFERENT trunk digest is rejected, never
    # silently matched against
    with pytest.raises(FeatureCacheMismatch):
        GalleryFeatureStore.open_store(
            str(store_dir), expected_digest="not-the-trunk"
        )
