import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.models.immatchnet import (
    ImMatchNet,
    ImMatchNetConfig,
    immatchnet_apply,
    init_immatchnet,
)

TINY = ImMatchNetConfig(
    ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1)
)


@pytest.fixture(scope="module")
def tiny_model():
    params = init_immatchnet(jax.random.PRNGKey(0), TINY)
    return params


def _rand_images(rng, b=1, hw=64):
    return jnp.asarray(rng.randn(b, hw, hw, 3).astype(np.float32))


def test_forward_shape(tiny_model):
    rng = np.random.RandomState(0)
    src, tgt = _rand_images(rng), _rand_images(rng)
    corr = immatchnet_apply(tiny_model, TINY, src, tgt)
    assert corr.shape == (1, 4, 4, 4, 4)
    assert corr.dtype == jnp.float32


def test_symmetry_swap_images(tiny_model):
    """With symmetric NeighConsensus, swapping source/target transposes the
    correlation output (property implied by lib/model.py:144-150)."""
    rng = np.random.RandomState(1)
    src, tgt = _rand_images(rng), _rand_images(rng)
    corr_ab = immatchnet_apply(tiny_model, TINY, src, tgt)
    corr_ba = immatchnet_apply(tiny_model, TINY, tgt, src)
    np.testing.assert_allclose(
        np.asarray(corr_ab),
        np.asarray(corr_ba).transpose(0, 3, 4, 1, 2),
        rtol=1e-4,
        atol=1e-5,
    )


def test_relocalization_output(tiny_model):
    cfg = TINY.replace(relocalization_k_size=2)
    rng = np.random.RandomState(2)
    src, tgt = _rand_images(rng, hw=128), _rand_images(rng, hw=128)
    corr, delta4d = immatchnet_apply(tiny_model, cfg, src, tgt)
    assert corr.shape == (1, 4, 4, 4, 4)
    assert len(delta4d) == 4
    for d in delta4d:
        assert d.shape == (1, 4, 4, 4, 4)
        assert int(jnp.max(d)) <= 1


def test_half_precision_runs(tiny_model):
    cfg = TINY.replace(half_precision=True)
    rng = np.random.RandomState(3)
    src, tgt = _rand_images(rng), _rand_images(rng)
    corr = immatchnet_apply(tiny_model, cfg, src, tgt)
    assert corr.dtype == jnp.float32
    ref = immatchnet_apply(tiny_model, TINY, src, tgt)
    # bf16 path should be close to fp32 in relative terms
    np.testing.assert_allclose(
        np.asarray(corr), np.asarray(ref), rtol=0.2, atol=1e-3
    )


def test_wrapper_and_checkpoint_roundtrip(tiny_model, tmp_path):
    from ncnet_tpu.train.checkpoint import CheckpointData, load_checkpoint, save_checkpoint

    path = str(tmp_path / "ckpt.msgpack")
    save_checkpoint(path, CheckpointData(config=TINY, params=tiny_model))
    model = ImMatchNet(checkpoint=path)
    assert model.config == TINY
    rng = np.random.RandomState(4)
    src, tgt = _rand_images(rng), _rand_images(rng)
    got = model(src, tgt)
    want = immatchnet_apply(tiny_model, TINY, src, tgt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)

    loaded = load_checkpoint(path)
    chex = pytest.importorskip("chex")
    chex.assert_trees_all_close(loaded.params, jax.tree.map(np.asarray, tiny_model))


def test_per_layer_conv4d_impl_mixing():
    """A comma-separated conv4d impl list applies per NC layer and matches
    the uniform-impl result (the measured-best config mixes 'tlc' edges
    with a 'cf1' middle layer)."""
    import numpy as np

    from ncnet_tpu.models.neigh_consensus import (
        init_neigh_consensus,
        neigh_consensus_apply,
    )

    rng = np.random.RandomState(5)
    params = init_neigh_consensus(
        jax.random.PRNGKey(5), kernel_sizes=(3, 3, 3), channels=(4, 4, 1)
    )
    corr = jnp.asarray(rng.randn(2, 5, 5, 5, 5).astype(np.float32))
    want = np.asarray(neigh_consensus_apply(params, corr, impl="xla"))
    got = np.asarray(
        neigh_consensus_apply(params, corr, impl="tlc,cf1,scan")
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    with pytest.raises(ValueError, match="does not match"):
        neigh_consensus_apply(params, corr, impl="tlc,cf1")
