"""Parity of the general affine resampler with torch F.affine_grid /
F.grid_sample (align_corners=True, zeros padding) — the PyTorch-0.3
semantics of the reference's AffineGridGen/AffineTnf
(lib/transformation.py:15-63)."""

import numpy as np
import pytest

import jax.numpy as jnp

from ncnet_tpu.ops.image import (
    affine_grid,
    affine_transform,
    grid_sample,
    resize_bilinear_align_corners,
)


def _torch_affine_sample(img_nhwc, theta, out_h, out_w):
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    t_img = torch.from_numpy(img_nhwc.transpose(0, 3, 1, 2))
    t_theta = torch.from_numpy(theta)
    grid = F.affine_grid(
        t_theta, (img_nhwc.shape[0], img_nhwc.shape[3], out_h, out_w),
        align_corners=True,
    )
    out = F.grid_sample(
        t_img, grid, mode="bilinear", padding_mode="zeros", align_corners=True
    )
    return out.numpy().transpose(0, 2, 3, 1)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_affine_transform_matches_torch_random_theta(seed):
    rng = np.random.RandomState(seed)
    img = rng.rand(2, 13, 17, 3).astype(np.float32)
    # random affines around identity, large enough to push samples
    # out of bounds (exercising the zeros-padding path)
    theta = (
        np.tile(np.asarray([[1, 0, 0], [0, 1, 0]], np.float32), (2, 1, 1))
        + rng.randn(2, 2, 3).astype(np.float32) * 0.3
    )
    got = np.asarray(affine_transform(jnp.asarray(img), jnp.asarray(theta), 11, 19))
    want = _torch_affine_sample(img, theta, 11, 19)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_affine_grid_matches_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    rng = np.random.RandomState(3)
    theta = rng.randn(2, 2, 3).astype(np.float32)
    got = np.asarray(affine_grid(jnp.asarray(theta), 7, 9))
    want = F.affine_grid(
        torch.from_numpy(theta), (2, 1, 7, 9), align_corners=True
    ).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_identity_affine_reduces_to_resize():
    """The reference uses AffineTnf with identity theta purely as a resize
    (lib/transformation.py:41-46, lib/pf_dataset.py:96-97)."""
    rng = np.random.RandomState(4)
    img = rng.rand(1, 10, 14, 3).astype(np.float32)
    theta = np.asarray([[[1, 0, 0], [0, 1, 0]]], np.float32)
    got = np.asarray(affine_transform(jnp.asarray(img), jnp.asarray(theta), 21, 9))
    want = np.asarray(resize_bilinear_align_corners(jnp.asarray(img), 21, 9))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_grid_sample_zeros_padding():
    """Samples fully outside the image are exactly zero."""
    img = jnp.ones((1, 5, 5, 2), jnp.float32)
    grid = jnp.full((1, 3, 3, 2), 3.0, jnp.float32)  # far outside [-1, 1]
    out = np.asarray(grid_sample(img, grid))
    np.testing.assert_array_equal(out, np.zeros_like(out))
