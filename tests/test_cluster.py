"""Cluster supervision proofs (resilience.cluster + the wired train loop).

Layers, cheapest first:

  * protocol units — threaded supervisor pairs over one tmp dir pin the
    heartbeat/PeerDown budget, the non-blocking drain agreement, and the
    save-cursor consensus (save/skip + the stop-flag escape), plus the
    typed-crash and lock-audit posture (`ScheduleFuzzer`,
    ``find_cycles() == []``, ``straggler_threads == []``);
  * arbiter units — a fake arbiter pins the `AsyncCheckpointer`
    collective-skip semantics (skip drops the snapshot on the spot,
    save enqueues; blocking submits never consult the arbiter);
  * restore/flush regression — `load_latest_valid_any` overlapping an
    in-flight async save must flush the live writer first (PR-19
    follow-up (a)): it reads the COMMITTED newer save, no torn refs, no
    deadlock;
  * subprocess drills (`conftest.spawn_cpu_cluster`, the
    tests/test_multihost.py child-main technique) — the acceptance
    drills: kill one host mid-epoch and the survivor raises typed
    `PeerDown` within the staleness budget, then the elastic supervisor
    re-forms at the surviving topology and the resumed run matches the
    uninterrupted fixture BITWISE; a stop-flag drain lands both hosts on
    the identical committed step with consensus coalescing engaged
    (``ckpt_coalesced_total > 0`` on every host); consensus-round kills
    at ``cluster.propose`` / ``cluster.ack`` leave the survivor with a
    typed `PeerDown`, wall-bounded (these two run WITHOUT jax — the
    rendezvous protocol is pure-filesystem, so the drill doesn't pay a
    compile); and the satellite case: a SIGTERM on one host of a
    NON-cluster multi-process run still exits that host cleanly with a
    committed, walk-back-valid save (the peer's next barrier fails
    typed `ShardedSaveError` — the documented degradation cluster mode
    removes).
"""

import json
import os
import re
import signal
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # child interpreters start with sys.path[0]=tests/
    sys.path.insert(0, REPO)

from ncnet_tpu.analysis import concurrency
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.resilience.cluster import (
    EXIT_PEER_DOWN,
    ClusterError,
    ClusterSupervisor,
    ElasticSupervisor,
    PeerDown,
)

if __name__ != "__main__":  # children must not import pytest plugins
    import numpy as np

    import jax

    from conftest import multiprocess_cpu_supported, spawn_cpu_cluster
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig
    from ncnet_tpu.resilience import distributed
    from ncnet_tpu.resilience.async_ckpt import (
        AsyncCheckpointer,
        flush_live_checkpointers,
    )
    from ncnet_tpu.telemetry.registry import MetricsRegistry
    from ncnet_tpu.train.checkpoint import (
        CheckpointData,
        load_latest_valid_any,
        save_checkpoint_sharded,
        sharded_dir_for,
    )

    CFG = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))

    # Capability gate for the subprocess drills only — the protocol
    # units above them are single-process and always run.
    needs_mp = pytest.mark.skipif(
        not multiprocess_cpu_supported(),
        reason="this jaxlib lacks multiprocess CPU collectives "
        "(no gloo implementation to back jax.distributed on CPU)",
    )
else:
    # child mode: tests are never collected, but their decorators still
    # evaluate at import — resolve to the identity
    def needs_mp(f):
        return f

WAIT = 30.0  # generous Event/join budget: a hang fails the test, not CI


@pytest.fixture(autouse=True)
def _no_leaked_state():
    faultinject.clear()
    yield
    faultinject.clear()
    concurrency.clear()


def _pair(tmp_path, **kw):
    """Two supervisors over one shared dir — the in-process stand-in for
    two hosts (each gets its own heartbeat/monitor threads; the shared
    filesystem is the real medium either way)."""
    kw.setdefault("heartbeat_interval_s", 0.05)
    kw.setdefault("staleness_s", 1.0)
    kw.setdefault("poll_interval_s", 0.01)
    kw.setdefault("stop_poll_s", 0.01)
    regs = [kw.pop("registry", None) or MetricsRegistry() for _ in range(2)]
    sups = [
        ClusterSupervisor(str(tmp_path), p, 2, registry=regs[p], **kw)
        for p in range(2)
    ]
    for s in sups:
        s.start()
    return sups, regs


# --- health supervision ------------------------------------------------------


def test_peer_down_typed_within_budget(tmp_path):
    """Kill one 'host' (stop its heartbeats); the survivor must raise a
    TYPED PeerDown within the staleness budget + monitor slack — never
    hang, never a bare timeout."""
    (s0, s1), (reg0, _) = _pair(tmp_path)
    time.sleep(0.4)  # both sides see a first beat
    s0.check("warmup")  # alive cluster: no raise

    s1.close()  # the peer dies (heartbeats stop; files remain = stale)
    t0 = time.monotonic()
    err = None
    while time.monotonic() - t0 < 10.0:
        try:
            s0.check("drill")
        except PeerDown as e:
            err = e
            break
        time.sleep(0.02)
    assert err is not None, "peer never declared down"
    assert err.host == 1
    assert err.last_seen is not None and err.last_seen >= 1.0
    assert err.budget == 1.0
    assert "peer 1 down" in str(err) and "drill" in str(err)
    # detection latency bounded: budget (1.0s) + monitor poll slack
    assert time.monotonic() - t0 < 3.0
    assert list(s0.peers_down()) == [1]
    assert reg0.get("cluster_peers_down_total").value == 1
    assert reg0.get("cluster_heartbeat_age_s").value >= 1.0

    s0.close()
    assert s0.report()["straggler_threads"] == []
    assert s1.report()["straggler_threads"] == []


def test_peer_down_is_a_cluster_error(tmp_path):
    assert issubclass(PeerDown, ClusterError)
    assert EXIT_PEER_DOWN == 75  # EX_TEMPFAIL: the elastic restart code


# --- coordinated preemption (stop flag + non-blocking drain) -----------------


def test_stop_flag_reaches_peer_and_drain_agrees(tmp_path):
    """publish_stop on one host is visible to the other via the durable
    flag; the non-blocking drain lands both on ONE agreed step ahead of
    both ack boundaries."""
    (s0, s1), _ = _pair(tmp_path)
    assert not s1.stop_requested()
    s0.publish_stop("test signal")
    assert s0.stop_requested()

    res = {}

    def drive(sup, boundary):
        # the loop's shape: advance a boundary at a time, polling the
        # flag and the drain state machine — never blocking
        while True:
            if sup.stop_requested():
                at = sup.drain_step(boundary, interval=2)
                if at is not None and boundary >= at:
                    res[sup._p] = (boundary, at)
                    return
            boundary += 1
            time.sleep(0.02)

    t0 = threading.Thread(target=drive, args=(s0, 5))
    t1 = threading.Thread(target=drive, args=(s1, 7))
    t0.start()
    t1.start()
    t0.join(WAIT)
    t1.join(WAIT)
    assert res[0][1] == res[1][1], res  # ONE agreed drain step
    # the agreed step is AHEAD of both acks (margin: interval + 2)
    assert res[0][1] >= 7 + 2
    assert res[0][0] == res[0][1] and res[1][0] == res[1][1]
    s0.close()
    s1.close()
    assert s0.report()["drain_at"] == res[0][1]


def test_drain_step_nonblocking_before_acks(tmp_path):
    """A host whose peer has not acked yet gets None (keep training) —
    the deadlock-freedom property: no cluster wait ever blocks the step
    thread while a peer may be inside a collective."""
    (s0, s1), _ = _pair(tmp_path)
    s0.publish_stop("one-sided")
    t0 = time.monotonic()
    assert s0.drain_step(3, interval=1) is None  # returns immediately
    assert time.monotonic() - t0 < 0.5
    # peer acks -> leader publishes -> both resolve
    assert s1.stop_requested()
    while s1.drain_step(4, interval=1) is None:
        assert s0.drain_step(3, interval=1) is not None or True
        time.sleep(0.02)
        assert time.monotonic() - t0 < WAIT
    assert s0.drain_step(3, interval=1) == s1.drain_step(4, interval=1)
    s0.close()
    s1.close()


# --- save-cursor consensus ---------------------------------------------------


def test_consensus_save_and_skip_rounds(tmp_path):
    """All-free -> SAVE on every host; any-busy -> SKIP on every host;
    the per-host round counter metric ticks once per completed round."""
    (s0, s1), (reg0, reg1) = _pair(tmp_path)
    out = {}

    def round_pair(step, busy0, busy1):
        t = threading.Thread(
            target=lambda: out.__setitem__("b", s1.agree_save_cursor(step, busy1))
        )
        t.start()
        out["a"] = s0.agree_save_cursor(step, busy0)
        t.join(WAIT)
        return out["a"], out["b"]

    assert round_pair(2, False, False) == (True, True)
    assert round_pair(4, False, True) == (False, False)
    assert round_pair(6, True, False) == (False, False)
    assert reg0.get("ckpt_consensus_rounds_total").value == 3
    assert reg1.get("ckpt_consensus_rounds_total").value == 3
    s0.close()
    s1.close()
    assert s0.report()["consensus_rounds"] == 3


def test_consensus_skips_without_round_once_stop_flag_up(tmp_path):
    """The drain-entry race resolution: with the stop flag up, rounds
    skip at entry (and a host already inside a round escapes on the
    flag) — both paths converge on SKIP, so save sets stay identical."""
    (s0, s1), (reg0, _) = _pair(tmp_path)
    # a follower enters its round BEFORE seeing the flag; the leader
    # (flag already local) never joins round 0 -> the follower's wait
    # must escape on the flag, not burn the consensus timeout
    out = {}
    follower = threading.Thread(
        target=lambda: out.__setitem__("b", s1.agree_save_cursor(3, False))
    )
    s0.publish_stop("drain race")
    out["a"] = s0.agree_save_cursor(3, False)  # entry skip, no round
    follower.start()
    follower.join(WAIT)
    assert out == {"a": False, "b": False}
    assert reg0.get("ckpt_consensus_rounds_total").value == 0
    s0.close()
    s1.close()


def test_consensus_propose_crash_is_typed(tmp_path):
    """A crash armed at ``cluster.propose`` unwinds typed (InjectedFault)
    — the kill variant of this window is drilled in the subprocess
    tests below."""
    faultinject.inject("cluster.propose", "crash")
    s = ClusterSupervisor(
        str(tmp_path), 0, 1, heartbeat_interval_s=0.05, staleness_s=5.0
    )
    s.start()
    try:
        with pytest.raises(faultinject.InjectedFault):
            s.agree_save_cursor(1, False)
    finally:
        s.close()
    assert s.report()["straggler_threads"] == []


# --- concurrency audit -------------------------------------------------------


def test_cluster_lock_audit_fuzzed(tmp_path):
    """The full protocol surface under the runtime lock audit with a
    fuzzed schedule: no lock-order cycles, no straggler threads."""
    concurrency.clear()
    concurrency.enable()
    with concurrency.ScheduleFuzzer(seed=7, p=0.5, max_sleep_s=5e-5):
        (s0, s1), _ = _pair(tmp_path, heartbeat_interval_s=0.02)
        out = {}
        t = threading.Thread(
            target=lambda: out.__setitem__("b", s1.agree_save_cursor(1, False))
        )
        t.start()
        out["a"] = s0.agree_save_cursor(1, False)
        t.join(WAIT)
        assert out == {"a": True, "b": True}
        s0.check("fuzzed boundary")
        s0.publish_stop("fuzz drain")
        res = {}

        def drive(sup, b):
            while True:
                if sup.stop_requested():
                    at = sup.drain_step(b, interval=1)
                    if at is not None and b >= at:
                        res[sup._p] = at
                        return
                b += 1
                time.sleep(0.005)

        ths = [
            threading.Thread(target=drive, args=(s0, 2)),
            threading.Thread(target=drive, args=(s1, 3)),
        ]
        for th in ths:
            th.start()
        for th in ths:
            th.join(WAIT)
        assert res[0] == res[1]
        s0.close()
        s1.close()
    assert concurrency.find_cycles() == [], concurrency.report()["edges"]
    assert s0.report()["straggler_threads"] == []
    assert s1.report()["straggler_threads"] == []
    concurrency.clear()


# --- elastic supervisor units ------------------------------------------------


def test_elastic_propagates_non_peerdown_exits(tmp_path):
    """Only EXIT_PEER_DOWN restarts; a plain failure (or success)
    propagates unchanged — a kill stays a kill."""
    sup = ElasticSupervisor(
        str(tmp_path),
        lambda topo: [sys.executable, "-c", "raise SystemExit(3)"],
        0,
        1,
        reform_window_s=0.1,
    )
    assert sup.run() == 3

    sup_ok = ElasticSupervisor(
        str(tmp_path),
        lambda topo: [sys.executable, "-c", "pass"],
        0,
        1,
        reform_window_s=0.1,
    )
    assert sup_ok.run() == 0


def test_elastic_restart_budget_exhausts(tmp_path):
    """A child that always dies PeerDown re-forms at most max_restarts
    times, then the typed status propagates."""
    launches = []

    def argv(topo):
        launches.append(dict(topo))
        return [sys.executable, "-c", f"raise SystemExit({EXIT_PEER_DOWN})"]

    sup = ElasticSupervisor(
        str(tmp_path), argv, 0, 1, max_restarts=2, reform_window_s=0.05
    )
    assert sup.run() == EXIT_PEER_DOWN
    assert len(launches) == 3  # initial + 2 restarts
    assert [t["generation"] for t in launches] == [0, 1, 2]


# --- collective health hook + barrier health check ---------------------------


def test_collective_check_hook_roundtrip():
    from ncnet_tpu.parallel import mesh

    calls = []
    prev = mesh.set_collective_check(calls.append)
    try:
        mesh.checked_collective("drill collective")
        assert calls == ["drill collective"]
    finally:
        mesh.set_collective_check(prev)
    # uninstalled: a no-op again
    mesh.checked_collective("after uninstall")
    assert calls == ["drill collective"]


def test_sharded_barrier_health_check_beats_timeout():
    """A dead peer raises typed PeerDown from inside the save barrier
    poll loop — not a 30s ShardedSaveError burn."""

    def hc(what):
        raise PeerDown(1, 2.5, budget=1.0, where=what)

    t0 = time.monotonic()
    with pytest.raises(PeerDown):
        distributed._wait_for(
            lambda: False, timeout=30.0, poll=0.01,
            what="manifests", health_check=hc,
        )
    assert time.monotonic() - t0 < 1.0


# --- AsyncCheckpointer coalesce arbiter --------------------------------------


class _GatedWriter:
    """Deterministic writer stand-in (test_async_ckpt idiom): records
    payloads, blocks until released."""

    def __init__(self, gated=True):
        self.gated = gated
        self.gate = threading.Event()
        self.entered = threading.Event()
        self.written = []

    def __call__(self, data):
        self.entered.set()
        if self.gated and not self.gate.wait(WAIT):
            raise RuntimeError("writer gate never released")
        self.written.append(data)


def test_arbiter_skip_drops_snapshot_everywhere():
    """Arbiter says SKIP: the snapshot is dropped on the spot — counted
    as coalesced, ticket superseded, writer never sees it."""
    calls = []
    ack = AsyncCheckpointer(
        async_mode=True,
        registry=MetricsRegistry(),
        coalesce_arbiter=lambda step, busy: calls.append((step, busy)) or False,
    )
    w = _GatedWriter(gated=False)
    t = ack.submit(1, w, step=1)
    assert calls == [(1, False)]
    assert t.superseded and t.done.is_set()
    assert not w.entered.is_set() and w.written == []
    rep = ack.report()
    assert rep["consensus"] is True
    assert rep["consensus_skips_total"] == 1
    ack.close()


def test_arbiter_save_enqueues_and_busy_is_reported():
    """Arbiter says SAVE: plain enqueue. With the writer wedged and a
    save queued, the next overlapped submit reports busy=True to the
    round — the signal the leader turns into a collective SKIP."""
    calls = []

    def arbiter(step, busy):
        calls.append((step, busy))
        return step != 3  # round 3: the cluster decides SKIP

    ack = AsyncCheckpointer(
        async_mode=True, registry=MetricsRegistry(), coalesce_arbiter=arbiter
    )
    w = _GatedWriter()
    ack.submit(1, w, step=1)
    assert w.entered.wait(WAIT)  # in flight, gate held
    ack.submit(2, w, step=2)  # queued behind it
    t3 = ack.submit(3, w, step=3)  # queue busy -> arbiter skips
    assert calls == [(1, False), (2, False), (3, True)]
    assert t3.superseded
    w.gate.set()
    assert ack.flush(timeout=WAIT)
    ack.close()
    assert w.written == [1, 2]  # the skipped newer snapshot never wrote
    assert ack.report()["consensus_skips_total"] == 1


def test_arbiter_bypassed_for_blocking_submits():
    """wait=True (and sync mode) submits are part of the deterministic
    schedule on every host — they must never consult the arbiter."""
    calls = []
    ack = AsyncCheckpointer(
        async_mode=True,
        registry=MetricsRegistry(),
        coalesce_arbiter=lambda *a: calls.append(a) or True,
    )
    w = _GatedWriter(gated=False)
    ack.submit(1, w, step=1, wait=True)
    ack.close()
    assert calls == [] and w.written == [1]


# --- restore overlapping an in-flight async save (PR-19 follow-up (a)) -------


def test_restore_mid_async_save_flushes_live_checkpointer(tmp_path):
    """`load_latest_valid_any` called while an async save is mid-write
    must flush the live writer FIRST: it returns the newly committed
    save (never a torn read of it) and cannot deadlock against it."""
    path = str(tmp_path / "ncnet_tpu.msgpack")
    sdir = sharded_dir_for(path)

    def ckpt(step, fill):
        return CheckpointData(
            config=CFG,
            params={"w": np.full((16,), fill, np.float32)},
            step=step,
        )

    save_checkpoint_sharded(sdir, ckpt(1, 1.0))  # committed baseline

    entered = threading.Event()

    def slow_write(data):
        entered.set()
        time.sleep(1.0)  # the restore overlaps THIS window
        save_checkpoint_sharded(sdir, data)

    ack = AsyncCheckpointer(async_mode=True, registry=MetricsRegistry())
    ack.submit(ckpt(2, 2.0), slow_write, step=2)
    assert entered.wait(WAIT)  # the save is in flight right now

    ck, used = load_latest_valid_any(path)  # must flush, then read
    assert int(ck.step) == 2, "restore raced the in-flight save"
    assert used == os.path.join(sdir, distributed.step_dir_name(2))
    np.testing.assert_array_equal(
        np.asarray(ck.params["w"]), np.full((16,), 2.0, np.float32)
    )

    ack.close()
    # closed checkpointers leave the live registry: nothing to flush
    assert flush_live_checkpointers(timeout=1.0) is True


# --- subprocess drill helpers ------------------------------------------------

_DRAIN_RE = re.compile(r"coordinated drain: all hosts stop at step (\d+)")
_RESULT_RE = re.compile(r"^DRILL_RESULT (\{.*\})$", re.M)


def _drill_result(out):
    m = _RESULT_RE.search(out)
    assert m, f"no DRILL_RESULT line in child output:\n{out}"
    return json.loads(m.group(1))


def _assert_bitwise_equal(ck_a, ck_b):
    flat_a, _ = jax.tree_util.tree_flatten_with_path(ck_a.params)
    flat_b, _ = jax.tree_util.tree_flatten_with_path(ck_b.params)
    assert len(flat_a) == len(flat_b)
    for (path_a, leaf_a), (_, leaf_b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(
            np.asarray(leaf_a), np.asarray(leaf_b),
            err_msg=f"params differ at {jax.tree_util.keystr(path_a)}",
        )
    for a, b in zip(
        jax.tree.leaves(ck_a.opt_state), jax.tree.leaves(ck_b.opt_state)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(ck_a.step) == int(ck_b.step)
    np.testing.assert_array_equal(
        np.asarray(ck_a.train_loss), np.asarray(ck_b.train_loss)
    )


# --- drill: kill one host mid-epoch -> typed PeerDown -> elastic resume ------


@needs_mp
def test_kill_one_host_elastic_restart_bitwise(tmp_path):
    """The acceptance drill: process 1's trainer is hard-killed at step
    boundary 3 (`os._exit`, a true preemption). The survivor must raise
    a TYPED PeerDown (no hang), exit EXIT_PEER_DOWN, re-form as a
    1-process cluster, resume from the latest valid 2-process save
    through the topology-changing restore, and finish — BITWISE equal
    to an uninterrupted run of the same schedule (the 2-process phase
    computes replicated: same batches, same math).

    The uninterrupted reference runs in its OWN spawned child rather
    than reusing the session fixture: XLA CPU emits (measurably, ~1e-6)
    different float accumulation under the parent's different
    host-device-count flags, and this drill pins RESUME correctness,
    not cross-environment compilation determinism. The spawned-child
    comparison is exact: 2-proc replicated == 1-proc, bitwise."""
    results = spawn_cpu_cluster(
        os.path.abspath(__file__),
        n_procs=2,
        local_devices=1,
        timeout=540,
        args=("elastic", str(tmp_path)),
        per_proc_env={1: {"NCNET_FAULTS": "step.boundary=kill@3"}},
    )
    (rc0, out0), (rc1, out1) = results

    # the killed side: a kill stays a kill, all the way up the tree
    assert "hard kill at 'step.boundary'" in out1, out1
    assert rc1 == 137, out1
    assert "only a typed PeerDown restarts" in out1, out1

    # the survivor: typed PeerDown within budget, re-form, resume, done
    assert rc0 == 0, out0
    assert "WORKER_PEERDOWN" in out0, out0
    assert "peer 1 declared down" in out0, out0
    assert "[elastic] re-formed gen 1: 1 survivor(s)" in out0, out0
    assert "WORKER_DONE" in out0, out0

    # the uninterrupted reference, same child environment
    ref_dir = str(tmp_path / "reference")
    os.makedirs(ref_dir)
    ((rc_ref, out_ref),) = spawn_cpu_cluster(
        os.path.abspath(__file__),
        n_procs=1,
        local_devices=1,
        timeout=300,
        args=("solo", ref_dir),
    )
    assert rc_ref == 0, out_ref

    ck_a, _ = load_latest_valid_any(os.path.join(ref_dir, "ncnet_tpu.msgpack"))
    ck_b, _ = load_latest_valid_any(
        os.path.join(str(tmp_path), "ncnet_tpu.msgpack")
    )
    _assert_bitwise_equal(ck_a, ck_b)
    # the resumed run's epoch metrics also line up (proc-0-written)
    def lines(d):
        return [json.loads(l) for l in open(os.path.join(d, "metrics.jsonl"))]

    strip = lambda l: {k: v for k, v in l.items() if k != "epoch_seconds"}
    assert [strip(l) for l in lines(str(tmp_path))] == [
        strip(l) for l in lines(ref_dir)
    ]


# --- drill: stop flag drains BOTH hosts to the identical committed step ------


@needs_mp
def test_stop_flag_drains_both_hosts_to_same_step(tmp_path):
    """Coordinated preemption + regained coalescing, end to end: a
    programmatic preemption on host 0 (the SIGTERM stand-in — same
    guard path) publishes the stop flag; BOTH hosts drain to one agreed
    step and commit it; and because this is an async+consensus run with
    deliberately slow writes, every host also coalesced at least one
    overlapped save collectively (``ckpt_coalesced_total > 0``)."""
    results = spawn_cpu_cluster(
        os.path.abspath(__file__),
        n_procs=2,
        local_devices=1,
        timeout=420,
        args=("stopflag", str(tmp_path)),
        extra_env={"NCNET_FAULTS": "ackpt.write=delay:0.8"},
    )
    drains, reported = [], []
    for code, out in results:
        assert code == 0, f"stopflag child failed:\n{out}"
        m = _DRAIN_RE.search(out)
        assert m, f"no coordinated-drain line:\n{out}"
        drains.append(int(m.group(1)))
        reported.append(_drill_result(out))

    assert drains[0] == drains[1], drains
    for rep in reported:
        assert rep["preempted"] is True
        assert rep["coalesced"] > 0, rep  # consensus coalescing engaged
        assert rep["rounds"] > 0, rep

    # the shared directory's newest COMMITTED save is the drained step,
    # and nothing past it exists (identical save sets by construction:
    # a divergent sequence would have wedged the commit barrier)
    sdir = sharded_dir_for(os.path.join(str(tmp_path), "ncnet_tpu.msgpack"))
    committed = sorted(
        int(distributed.STEP_DIR_RE.match(name).group(1))
        for name in os.listdir(sdir)
        if distributed.STEP_DIR_RE.match(name)
        and distributed.is_committed(os.path.join(sdir, name))
    )
    assert committed and committed[-1] == drains[0], (committed, drains)
    ck, _ = load_latest_valid_any(os.path.join(str(tmp_path), "ncnet_tpu.msgpack"))
    assert int(ck.step) == drains[0]


# --- drills: consensus-round kills at cluster.propose / cluster.ack ----------


@needs_mp
@pytest.mark.parametrize(
    "point,dead,survivor",
    [("cluster.propose", 1, 0), ("cluster.ack", 0, 1)],
    ids=["propose", "ack"],
)
def test_consensus_round_kill_leaves_survivor_typed(
    tmp_path, point, dead, survivor
):
    """Kill a host inside the consensus round (before its proposal /
    before the leader's decision): the peer waiting on the round must
    get a typed PeerDown within the staleness budget — never the 120s
    consensus timeout, never a hang. Pure protocol drill: no jax, no
    compile — the rendezvous is plain files."""
    results = spawn_cpu_cluster(
        os.path.abspath(__file__),
        n_procs=2,
        local_devices=1,
        timeout=90,
        args=("conskill", str(tmp_path)),
        per_proc_env={dead: {"NCNET_FAULTS": f"{point}=kill@3"}},
    )
    rc_dead, out_dead = results[dead]
    rc_live, out_live = results[survivor]
    assert rc_dead == 137, out_dead
    assert f"hard kill at '{point}'" in out_dead, out_dead
    assert rc_live == EXIT_PEER_DOWN, out_live
    assert "WORKER_PEERDOWN" in out_live, out_live
    assert f"peer {dead} declared down" in out_live, out_live
    rep = _drill_result(out_live)
    assert rep["rounds_done"] >= 2  # rounds worked until the kill
    assert rep["wall_s"] < 30.0, rep  # staleness budget, not a timeout


# --- drill: non-cluster multi-process SIGTERM (the documented degradation) ---


@needs_mp
def test_noncluster_sigterm_commits_on_signalled_host(tmp_path):
    """Satellite: WITHOUT a cluster supervisor, a SIGTERM on one host of
    a multi-process sharded run still exits that host cleanly with a
    committed, walk-back-valid save (its final save coincides with the
    every-step collective schedule). The un-signalled peer's next
    barrier then fails TYPED (ShardedSaveError) — the documented
    degradation that cluster mode's coordinated drain removes."""
    results = spawn_cpu_cluster(
        os.path.abspath(__file__),
        n_procs=2,
        local_devices=1,
        timeout=420,
        args=("sigterm", str(tmp_path)),
    )
    (rc0, out0), (rc1, out1) = results

    assert rc0 == 0, out0  # the signalled host: clean exit
    rep = _drill_result(out0)
    assert rep["preempted"] is True
    assert rep["step"] == 2  # signalled at boundary 2 -> committed there

    assert rc1 == 3, out1  # the peer: typed failure, bounded
    assert "SIGTERM_TYPED ShardedSaveError" in out1, out1

    # the shared directory is walk-back-valid at the signalled step:
    # the peer's torn post-exit save never commits and is skipped
    ck, used = load_latest_valid_any(
        os.path.join(str(tmp_path), "ncnet_tpu.msgpack")
    )
    assert int(ck.step) == 2
    assert used.endswith(distributed.step_dir_name(2))


# --- child mains (run via spawn_cpu_cluster / the elastic supervisor) --------


def _pinned_train(workdir, cluster, **overrides):
    """The conftest `uninterrupted_run` schedule (pinned seeds/geometry,
    sharded saves), with resume-from-latest built in — the drills'
    bitwise comparisons against the fixture depend on this matching."""
    import jax

    from ncnet_tpu.data.loader import DataLoader
    from ncnet_tpu.data.pairs import SyntheticPairDataset
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.resilience import distributed as dist
    from ncnet_tpu.train.checkpoint import (
        load_latest_valid_any,
        sharded_dir_for,
    )
    from ncnet_tpu.train.loop import train

    cfg = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
    ds = SyntheticPairDataset(n=8, output_size=(32, 32), seed=11)
    loader = DataLoader(
        ds, 2, shuffle=True, seed=5, drop_last=True,
        num_workers=1, prefetch=0,
    )
    kw = dict(
        num_epochs=2, checkpoint_dir=workdir, data_parallel=False,
        log_every=100, save_every_steps=2, keep_checkpoints=4,
        distributed_checkpoints=True, cluster=cluster,
    )
    path = os.path.join(workdir, "ncnet_tpu.msgpack")
    sdir = sharded_dir_for(path)
    committed = os.path.isdir(sdir) and any(
        dist.is_committed(os.path.join(sdir, n))
        for n in os.listdir(sdir)
        if dist.STEP_DIR_RE.match(n)
    )
    params = None
    if committed:
        ck, used = load_latest_valid_any(path)
        print(f"CHILD_RESUME from {used}", flush=True)
        params = ck.params
        kw.update(
            opt_state=ck.opt_state, start_epoch=ck.epoch, start_step=ck.step,
            initial_best_val=ck.best_val_loss,
            initial_train_hist=ck.train_loss, initial_val_hist=ck.val_loss,
        )
        if ck.cursor:
            kw.update(
                start_epoch=ck.cursor["epoch"],
                start_batch=ck.cursor["batch_index"],
                start_epoch_losses=ck.cursor["epoch_losses"],
            )
    if params is None:
        params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    kw.update(overrides)
    return train(cfg, params, loader, None, **kw)


def _boundary_trigger(hit, action):
    """Patch `faultinject.fire` so step boundary number ``hit`` runs
    ``action`` on the step thread — the deterministic stand-in for an
    async signal landing mid-epoch (test_resilience's counting idiom)."""
    real_fire = faultinject.fire
    state = {"n": 0}

    def fire(point, data=None):
        out = real_fire(point, data)
        if point == "step.boundary":
            state["n"] += 1
            if state["n"] == hit:
                action()
        return out

    faultinject.fire = fire


def _elastic_main(workdir):
    """spawn_cpu_cluster child for the elastic drill: the per-host
    supervisor process (no jax here — only its trainer children pay
    that). Initial topology comes from the harness env; re-formation
    re-ranks the survivors."""
    pid = int(os.environ["_NCNET_MH_PID"])
    coord = os.environ["_NCNET_MH_COORD"]

    def build_argv(topo):
        return [sys.executable, os.path.abspath(__file__), "worker", workdir]

    sup = ElasticSupervisor(
        os.path.join(workdir, "cluster"), build_argv, pid, 2,
        coordinator=coord, reform_window_s=2.0,
    )
    rc = sup.run()
    print(f"ELASTIC_DONE rc={rc}", flush=True)
    raise SystemExit(rc)


def _worker_main(workdir):
    """The elastic drill's trainer: joins the generation's topology from
    the NCNET_ELASTIC_* env, supervises via the shared cluster dir, and
    converts PeerDown into the typed elastic-restart exit status —
    exactly what ``scripts/train.py --elastic`` does."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    gen = int(os.environ["NCNET_ELASTIC_GEN"])
    pid = int(os.environ["NCNET_ELASTIC_PID"])
    n = int(os.environ["NCNET_ELASTIC_NPROCS"])
    coord = os.environ.get("NCNET_ELASTIC_COORD") or None

    from ncnet_tpu.parallel.mesh import initialize_multihost

    if n > 1:
        initialize_multihost(
            coordinator_address=coord, num_processes=n, process_id=pid
        )

    cluster = None
    if n > 1:
        cluster = ClusterSupervisor(
            os.path.join(workdir, "cluster"), pid, n, generation=gen,
            heartbeat_interval_s=0.2, staleness_s=2.0,
        )
        cluster.start()
    try:
        _pinned_train(workdir, cluster)
        print("WORKER_DONE", flush=True)
    except PeerDown as e:
        print(f"WORKER_PEERDOWN {e}", flush=True)
        if cluster is not None:
            cluster.close()
        # HARD exit (scripts/train.py posture): don't join the jax
        # distributed runtime's atexit shutdown barrier with a dead
        # peer — the coordination service SIGABRTs, clobbering the
        # typed status the elastic supervisor keys restarts on
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(EXIT_PEER_DOWN)
    finally:
        if cluster is not None:
            cluster.close()


def _solo_main(workdir):
    """The elastic drill's uninterrupted reference: the pinned schedule,
    single process, no cluster — run in the SAME spawned environment as
    the drill so the bitwise comparison sees identical XLA codegen."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    _pinned_train(workdir, None)
    print("SOLO_DONE", flush=True)


def _stopflag_main(workdir):
    """Stop-flag drill child: async+consensus 2-process run; host 0
    requests preemption at step boundary 3 (programmatic — the same
    guard path a SIGTERM takes); both hosts drain to the agreed step."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    pid = int(os.environ["_NCNET_MH_PID"])
    coord = os.environ["_NCNET_MH_COORD"]

    from ncnet_tpu.parallel.mesh import initialize_multihost
    from ncnet_tpu.resilience.signals import PreemptionGuard
    from ncnet_tpu.telemetry.registry import default_registry

    initialize_multihost(
        coordinator_address=coord, num_processes=2, process_id=pid
    )
    cluster = ClusterSupervisor(
        os.path.join(workdir, "cluster"), pid, 2,
        heartbeat_interval_s=0.2, staleness_s=8.0, stop_poll_s=0.05,
    )
    cluster.start()
    guard = PreemptionGuard(cluster=cluster)
    if pid == 0:
        _boundary_trigger(3, guard.request)
    try:
        _, history = _pinned_train(
            workdir, cluster,
            num_epochs=3, save_every_steps=1, async_checkpoints=True,
            preemption=guard,
        )
    finally:
        cluster.close()
    reg = default_registry()
    coalesced = reg.get("ckpt_coalesced_total")
    rounds = reg.get("ckpt_consensus_rounds_total")
    print(
        "DRILL_RESULT "
        + json.dumps({
            "pid": pid,
            "preempted": bool(history["preempted"]),
            "coalesced": coalesced.value if coalesced else 0,
            "rounds": rounds.value if rounds else 0,
        }),
        flush=True,
    )


def _conskill_main(workdir):
    """Consensus-kill drill child: NO jax — two supervisors running
    lockstep save-cursor rounds over the shared dir; the armed fault
    kills one mid-round and the peer must fail typed, wall-bounded."""
    pid = int(os.environ["_NCNET_MH_PID"])
    sup = ClusterSupervisor(
        os.path.join(workdir, "cluster"), pid, 2,
        heartbeat_interval_s=0.1, staleness_s=1.5, poll_interval_s=0.02,
    )
    sup.start()
    t0 = time.monotonic()
    done = 0
    try:
        for step in range(1, 21):
            sup.agree_save_cursor(step, busy=False)
            done += 1
            time.sleep(0.05)
        print("CONSKILL_COMPLETED_ALL_ROUNDS", flush=True)
    except PeerDown as e:
        print(f"WORKER_PEERDOWN {e}", flush=True)
        print(
            "DRILL_RESULT "
            + json.dumps({
                "pid": pid,
                "rounds_done": done,
                "wall_s": time.monotonic() - t0,
            }),
            flush=True,
        )
        sys.exit(EXIT_PEER_DOWN)
    finally:
        sup.close()


def _sigterm_main(workdir):
    """Non-cluster SIGTERM drill child: 2-process sharded sync run with
    a save at EVERY boundary; host 0 SIGTERMs itself at boundary 2. Its
    final save coincides with the collective schedule, so it commits
    and the host exits cleanly. Host 1's next barrier must fail typed
    (bounded here by a small barrier_timeout) — the degradation cluster
    mode exists to remove."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    pid = int(os.environ["_NCNET_MH_PID"])
    coord = os.environ["_NCNET_MH_COORD"]

    from ncnet_tpu.parallel.mesh import initialize_multihost
    from ncnet_tpu.resilience.distributed import ShardedSaveError
    from ncnet_tpu.resilience.signals import PreemptionGuard
    import ncnet_tpu.train.loop as loop_mod
    from ncnet_tpu.train.checkpoint import load_latest_valid_any

    initialize_multihost(
        coordinator_address=coord, num_processes=2, process_id=pid
    )
    # bound the abandoned peer's barrier so the drill is wall-capped
    orig_save = loop_mod.save_checkpoint_sharded
    loop_mod.save_checkpoint_sharded = lambda *a, **k: orig_save(
        *a, **{**k, "barrier_timeout": 15.0}
    )
    guard = PreemptionGuard()
    if pid == 0:
        _boundary_trigger(
            2, lambda: os.kill(os.getpid(), signal.SIGTERM)
        )
    with guard:
        try:
            _, history = _pinned_train(
                workdir, None, num_epochs=1, save_every_steps=1,
                preemption=guard,
            )
        except ShardedSaveError as e:
            print(f"SIGTERM_TYPED {type(e).__name__}: {e}", flush=True)
            sys.exit(3)
    ck, _ = load_latest_valid_any(os.path.join(workdir, "ncnet_tpu.msgpack"))
    print(
        "DRILL_RESULT "
        + json.dumps({
            "pid": pid,
            "preempted": bool(history["preempted"]),
            "step": int(ck.step),
        }),
        flush=True,
    )


if __name__ == "__main__":
    # `python tests/test_cluster.py <role> <workdir>` — the child entry
    # for every subprocess drill (repo root already on sys.path above)
    _role = sys.argv[1]
    _mains = {
        "elastic": _elastic_main,
        "worker": _worker_main,
        "solo": _solo_main,
        "stopflag": _stopflag_main,
        "conskill": _conskill_main,
        "sigterm": _sigterm_main,
    }
    _mains[_role](sys.argv[2])
