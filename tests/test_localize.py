"""Synthetic-geometry tests for the PnP localization stage (the Python
port of lib_matlab/parfor_NC4D_PE_pnponly.m + p2dist.m +
ht_plotcurve_WUSTL.m)."""

import numpy as np
import pytest

from ncnet_tpu.eval.localize import (
    camera_center,
    dlt_pnp,
    lo_ransac_p3p,
    localization_rate_curve,
    p3p_grunert,
    pnp_localize_pair,
    pose_distance,
)


def _random_pose(rng):
    A = rng.randn(3, 3)
    Q, _ = np.linalg.qr(A)
    if np.linalg.det(Q) < 0:
        Q[:, 0] *= -1
    t = rng.randn(3) * 0.5 + np.array([0, 0, 4.0])
    return np.concatenate([Q, t[:, None]], axis=1)


def _project_rays(P, X):
    Xc = X @ P[:, :3].T + P[:, 3]
    return Xc / np.linalg.norm(Xc, axis=1, keepdims=True)


@pytest.mark.parametrize("seed", range(5))
def test_p3p_recovers_ground_truth(seed):
    rng = np.random.RandomState(seed)
    P_gt = _random_pose(rng)
    X = rng.randn(3, 3) * 2.0
    rays = _project_rays(P_gt, X)
    sols = p3p_grunert(rays, X)
    assert sols, "no P3P solutions"
    errs = [pose_distance(P_gt, P)[0] + pose_distance(P_gt, P)[1] for P in sols]
    assert min(errs) < 1e-6


def test_dlt_pnp_recovers_ground_truth():
    """Many trials: the SVD null vector's sign is random, so a sign-handling
    bug passes a handful of lucky seeds but fails ~half of a sweep."""
    failures = 0
    for seed in range(50):
        rng = np.random.RandomState(seed + 10)
        P_gt = _random_pose(rng)
        X = rng.randn(12, 3) * 2.0
        rays = _project_rays(P_gt, X)
        P = dlt_pnp(rays, X)
        if P is None:
            failures += 1
            continue
        dp, do = pose_distance(P_gt, P)
        if dp > 1e-6 or do > 1e-6:
            failures += 1
    assert failures == 0


def test_lo_ransac_rejects_outliers():
    rng = np.random.RandomState(42)
    P_gt = _random_pose(rng)
    n_in, n_out = 40, 40
    X = rng.randn(n_in + n_out, 3) * 2.0
    rays = _project_rays(P_gt, X)
    # corrupt the second half with random directions
    bad = rng.randn(n_out, 3)
    rays[n_in:] = bad / np.linalg.norm(bad, axis=1, keepdims=True)
    P, inl = lo_ransac_p3p(rays, X, np.deg2rad(0.2), max_iters=2000, seed=1)
    assert P is not None
    dp, do = pose_distance(P_gt, P)
    assert dp < 1e-3 and do < 1e-3
    assert inl[:n_in].sum() >= n_in - 1  # finds (nearly) all true inliers
    assert inl[n_in:].sum() <= 2  # and (nearly) no false ones


def test_pose_distance_identities():
    rng = np.random.RandomState(0)
    P = _random_pose(rng)
    dp, do = pose_distance(P, P)
    assert dp == 0.0 and do == 0.0
    # translate the camera center by 1m: position error 1, orientation 0
    P2 = P.copy()
    C = camera_center(P)
    P2[:, 3] = -P[:, :3] @ (C + np.array([1.0, 0, 0]))
    dp, do = pose_distance(P, P2)
    np.testing.assert_allclose(dp, 1.0, rtol=1e-6)
    assert do < 1e-6


def test_localization_rate_curve_reference_grid():
    pos = np.array([0.05, 0.5, 1.5, np.inf])
    ori = np.deg2rad(np.array([1.0, 1.0, 1.0, 1.0]))
    thr, rate = localization_rate_curve(pos, ori)
    assert thr[0] == 0.0 and thr[-1] == 2.0
    assert len(thr) == 17 + 8  # 0:0.0625:1 (17) + 1.125:0.125:2 (8)
    # at 2m: 3 of 4 localized
    np.testing.assert_allclose(rate[-1], 75.0)
    # orientation gate: >10 deg kills an otherwise-perfect pose
    _, rate_gated = localization_rate_curve(
        np.array([0.01]), np.deg2rad([20.0])
    )
    assert rate_gated[-1] == 0.0


def test_pnp_localize_pair_end_to_end():
    """Full parfor_NC4D_PE_pnponly math on a synthetic RGBD cutout."""
    rng = np.random.RandomState(7)
    dh, dw = 60, 80
    qh, qw = 48, 64
    fl = 50.0

    # a smooth 3D surface seen by the DB cutout, in "scan-local" coords
    gy, gx = np.mgrid[0:dh, 0:dw]
    xyz_local = np.stack(
        [gx * 0.05, gy * 0.05, 3.0 + 0.3 * np.sin(gx * 0.1)], axis=-1
    )
    xyz_local[5:8, 5:8] = np.nan  # invalid depth region
    # scan-to-global alignment
    A = _random_pose(rng)

    P_gt = _random_pose(rng)  # query camera, global frame

    # build matches: sample DB pixels, project their GLOBAL 3D into the
    # query camera to get the query-side normalized coords
    n = 120
    px = rng.randint(1, dw + 1, n)  # MATLAB 1-indexed pixels
    py = rng.randint(1, dh + 1, n)
    # force a few samples into the NaN-depth region (1-indexed 6..8)
    px[1:4] = py[1:4] = 7
    X_local = xyz_local[py - 1, px - 1]
    X_glob = X_local @ A[:3, :3].T + A[:3, 3]
    Xc = X_glob @ P_gt[:, :3].T + P_gt[:, 3]
    xq = Xc[:, 0] / Xc[:, 2] * fl + qw / 2.0
    yq = Xc[:, 1] / Xc[:, 2] * fl + qh / 2.0

    matches = np.stack(
        [
            xq / qw,
            yq / qh,
            # inverse of floor(x * dw) = px: any value in [px/dw, (px+1)/dw)
            (px + 0.5) / dw,
            (py + 0.5) / dh,
            np.full(n, 0.9),
        ],
        axis=1,
    )
    # low-score rows must be dropped by the 0.75 threshold
    matches[::10, 4] = 0.1

    out = pnp_localize_pair(
        matches, (qh, qw), (dh, dw), xyz_local, fl, alignment=A,
        max_iters=2000, seed=3,
    )
    assert out["P"] is not None
    dp, do = pose_distance(P_gt, out["P"])
    assert dp < 1e-2 and do < 1e-2
    # exact tentative count: score-filtered rows minus NaN-depth hits
    kept = np.ones(n, bool)
    kept[::10] = False  # score threshold
    nan_hit = ~np.isfinite(X_local[kept]).all(axis=1)
    expected = kept.sum() - nan_hit.sum()
    assert nan_hit.sum() > 0, "fixture must sample the NaN-depth region"
    assert out["tentatives_3d"].shape[1] == expected