"""bf16 vs fp32 score numerics around hard thresholds (VERDICT r3 #4).

The reference's MATLAB stage hard-thresholds match scores at 0.75
(lib_matlab/parfor_NC4D_PE_pnponly.m:16-18) on scores produced by its
fp16 eval pipeline (eval_inloc.py:50). This repo's eval runs bf16
(half_precision=True); these tests bound how far bf16 moves the scores
and how many matches a HARD threshold can flip relative to the fp32
pipeline — on the same pairs through the same full model forward
(trunk -> correlation+maxpool4d -> MM -> NC -> MM -> corr_to_matches).

These are the fast numerics checks; the downstream whole-chain proof
(trained model -> dump -> PnP -> densePV -> rate curve) lives in the
slow-gated synthetic end-to-end InLoc path (scripts/synthetic_inloc_e2e.py
and its test).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.data.images import normalize_image_np, resize_bilinear_np
from ncnet_tpu.models.immatchnet import (
    ImMatchNetConfig,
    immatchnet_apply,
    init_immatchnet,
)
from ncnet_tpu.ops.matches import corr_to_matches


def _pair(seed=5, size=128, off=32):
    rng = np.random.RandomState(seed)
    base = rng.rand(size // 4 + size // 32, size // 4 + size // 32, 3)
    T = resize_bilinear_np(
        base.astype(np.float32) * 255.0, size + off, size + off
    )
    cut, qry = T[:size, :size], T[off:, off:]
    prep = lambda im: jnp.asarray(normalize_image_np(im)[None])
    return prep(qry), prep(cut)


def _scores(half_precision, k_size=2):
    config = ImMatchNetConfig(
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(16, 1),
        half_precision=half_precision,
        relocalization_k_size=k_size,
        center_features=True,
        symmetric_batch=False,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), config)
    src, tgt = _pair()
    corr, delta4d = immatchnet_apply(params, config, src, tgt)
    out = []
    for invert in (False, True):
        m = corr_to_matches(
            corr, delta4d=delta4d, k_size=k_size, do_softmax=True,
            scale="positive", invert_matching_direction=invert,
        )
        out.append(np.asarray(m[4])[0])
    return np.concatenate(out)


def test_bf16_scores_match_fp32_within_tolerance():
    s32 = _scores(False)
    s16 = _scores(True)
    assert s32.shape == s16.shape
    # absolute score movement: softmax scores live in [0, 1]; bf16's ~3
    # significand digits land well inside the gap any sane threshold
    # margin has
    max_abs = float(np.max(np.abs(s32 - s16)))
    assert max_abs < 0.02, max_abs


def test_bf16_threshold_selection_stable_across_sweep():
    """A hard score threshold selects (almost) the same match set under
    bf16 as under fp32: any flip must sit within the numerics tolerance
    of the threshold itself — including at the reference's 0.75."""
    s32 = _scores(False)
    s16 = _scores(True)
    tol = 0.02
    thresholds = list(np.quantile(s32, [0.1, 0.25, 0.5, 0.75, 0.9]))
    thresholds.append(0.75)  # the reference's hard threshold
    for thr in thresholds:
        sel32 = s32 > thr
        sel16 = s16 > thr
        flipped = sel32 != sel16
        # every flipped match must be a borderline score, not a gross move
        assert np.all(np.abs(s32[flipped] - thr) < tol), (
            thr, s32[flipped]
        )
        # and flips must be rare relative to the selection size
        n_sel = max(int(sel32.sum()), 1)
        assert int(flipped.sum()) <= max(2, 0.05 * n_sel), (
            thr, int(flipped.sum()), n_sel
        )
