"""Tests for the frozen-trunk feature cache (ncnet_tpu.features + the
from-features training path).

The load-bearing guarantees:
  * the cached-feature path is NUMERICALLY IDENTICAL to the backbone
    path — same op sequence post-features, so under eager execution the
    first training steps match bitwise (losses AND NC params); jitted,
    XLA fuses the trunk-bearing program differently and the match is
    ULP-tight allclose;
  * a stale or mismatched cache (different trunk weights / config /
    dataset size) is REJECTED at open, never silently consumed;
  * shard bitrot is detected at read (durable sidecar digests);
  * the `scripts/extract_features.py` CLI stays runnable (CPU smoke on
    the synthetic dataset).
"""

import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax

from ncnet_tpu.data.features_loader import FeatureBatchLoader
from ncnet_tpu.data.loader import collate
from ncnet_tpu.data.pairs import SyntheticPairDataset
from ncnet_tpu.features import (
    FeatureCacheMismatch,
    FeatureStore,
    populate_store,
    trunk_digest,
)
from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
from ncnet_tpu.train.loss import weak_loss, weak_loss_from_features
from ncnet_tpu.train.step import (
    create_train_state,
    make_eval_step,
    make_optimizer,
    make_train_step,
)

REPO = Path(__file__).resolve().parent.parent

CFG = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
SIZE = (48, 48)
N_PAIRS = 8


@pytest.fixture(scope="module")
def cache(tmp_path_factory):
    """One populated store shared by the module's tests: params, dataset,
    digest, store (populated via the jitted extractor)."""
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    ds = SyntheticPairDataset(n=N_PAIRS, output_size=SIZE, seed=3)
    root = tmp_path_factory.mktemp("feature_cache")
    digest = trunk_digest(params["feature_extraction"], CFG, SIZE)
    store = FeatureStore.open_or_create(
        str(root / "train"), digest, CFG, SIZE, len(ds)
    )
    n = populate_store(store, params, CFG, ds, batch_size=4)
    assert n == N_PAIRS and store.complete()
    return {"params": params, "ds": ds, "digest": digest, "store": store}


def _feature_batch(store, indices):
    pairs = [store.get(i) for i in indices]
    return {
        "source_features": np.stack([p[0] for p in pairs]),
        "target_features": np.stack([p[1] for p in pairs]),
    }


# --- store ------------------------------------------------------------------


def test_populate_is_lazy_and_idempotent(cache):
    """A complete store re-populates as a no-op (the lazy fill-on-first-
    epoch contract), and shards round-trip bit-exactly."""
    assert populate_store(
        cache["store"], cache["params"], CFG, cache["ds"], batch_size=4
    ) == 0
    src, tgt = cache["store"].get(0)
    assert src.dtype == np.float32 and src.shape == (3, 3, 1024)
    src2, _ = cache["store"].get(0)
    np.testing.assert_array_equal(src, src2)


def test_store_roundtrip_bf16(tmp_path):
    """bf16 shards (half the disk/HBM) survive the write/read round-trip
    bit-exactly via ml_dtypes."""
    import ml_dtypes

    cfg16 = CFG.replace(half_precision=True)
    store = FeatureStore.create(str(tmp_path), "d" * 64, cfg16, SIZE, 1)
    rng = np.random.RandomState(0)
    feats = rng.randn(3, 3, 7).astype(ml_dtypes.bfloat16)
    store.put(0, feats, feats)
    src, tgt = store.get(0)
    assert src.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        src.view(np.uint16), feats.view(np.uint16)
    )


def test_trunk_digest_covers_weights_and_config(cache):
    """The digest must move when anything that changes the feature bytes
    moves: trunk weights, backbone name, image size, dtype, centering."""
    base = cache["digest"]
    other_params = init_immatchnet(jax.random.PRNGKey(1), CFG)
    assert trunk_digest(
        other_params["feature_extraction"], CFG, SIZE
    ) != base
    fe = cache["params"]["feature_extraction"]
    assert trunk_digest(fe, CFG, (64, 64)) != base
    assert trunk_digest(fe, CFG.replace(half_precision=True), SIZE) != base
    assert trunk_digest(fe, CFG.replace(center_features=True), SIZE) != base
    # and it is deterministic
    assert trunk_digest(fe, CFG, SIZE) == base


def test_stale_cache_rejected(cache, tmp_path):
    """A manifest/trunk-digest mismatch RAISES instead of training on
    stale features — for digest, and for dataset-size drift."""
    other = init_immatchnet(jax.random.PRNGKey(1), CFG)
    stale = trunk_digest(other["feature_extraction"], CFG, SIZE)
    with pytest.raises(FeatureCacheMismatch, match="digest"):
        FeatureStore.open_store(cache["store"].root, expected_digest=stale)
    with pytest.raises(FeatureCacheMismatch, match="items"):
        FeatureStore.open_store(
            cache["store"].root,
            expected_digest=cache["digest"],
            num_items=N_PAIRS + 1,
        )
    # open_or_create must NOT fall through to create on a mismatch
    with pytest.raises(FeatureCacheMismatch):
        FeatureStore.open_or_create(
            cache["store"].root, stale, CFG, SIZE, N_PAIRS
        )


def test_shard_bitrot_detected(cache, tmp_path):
    """Flipped shard bytes fail the sidecar digest at read."""
    from ncnet_tpu.resilience.durable import IntegrityError

    store = FeatureStore.create(
        str(tmp_path), cache["digest"], CFG, SIZE, 1
    )
    src, tgt = cache["store"].get(0)
    store.put(0, src, tgt)
    path = store.shard_path(0, "source")
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF
    with open(path, "wb") as f:  # deliberate raw rewrite: simulated bitrot
        f.write(bytes(blob))
    with pytest.raises(IntegrityError):
        store.get(0)


# --- loader -----------------------------------------------------------------


def test_feature_loader_batches_and_pinning(cache):
    """FeatureBatchLoader yields the DataLoader's exact index plan, and
    the HBM-pinned path is batch-for-batch identical to the unpinned."""
    with FeatureBatchLoader(
        cache["store"], 4, shuffle=True, seed=7, num_workers=2
    ) as ld, FeatureBatchLoader(
        cache["store"], 4, shuffle=True, seed=7, num_workers=2, pin_hbm=True
    ) as pinned:
        assert len(ld) == N_PAIRS // 4
        a = list(ld.iter_epoch(0))
        b = list(pinned.iter_epoch(0))
        assert len(a) == len(b) == N_PAIRS // 4
        for x, y in zip(a, b):
            assert x["source_features"].shape == (4, 3, 3, 1024)
            np.testing.assert_array_equal(
                np.asarray(x["source_features"]),
                np.asarray(y["source_features"]),
            )
        # skip_batches resume parity, pinned vs not
        np.testing.assert_array_equal(
            np.asarray(next(iter(ld.iter_epoch(0, skip_batches=1)))
                       ["target_features"]),
            np.asarray(next(iter(pinned.iter_epoch(0, skip_batches=1)))
                       ["target_features"]),
        )


def test_feature_loader_refuses_incomplete_store(cache, tmp_path):
    store = FeatureStore.create(
        str(tmp_path), cache["digest"], CFG, SIZE, 2
    )
    with pytest.raises(ValueError, match="missing"):
        FeatureBatchLoader(store, 2)


# --- the equivalence guarantee ---------------------------------------------


def test_cached_path_matches_backbone_path(cache, tmp_path):
    """Three training steps from the cache vs. from images: identical
    config, identical batches. Eager (disable_jit) both paths execute the
    same op sequence post-features, so losses AND the updated NC params
    match BITWISE. The store is populated eagerly too — extraction must
    run in the regime being compared, since jit-vs-eager extraction
    itself differs by ULPs. (Jitted, XLA additionally fuses the
    trunk-bearing program differently and the NC grads pick up ULP-level
    reduction-order noise — that looser jitted contract is asserted
    separately below.)"""
    from ncnet_tpu.models.immatchnet import extract_features

    ds, params = cache["ds"], cache["params"]
    store = FeatureStore.create(
        str(tmp_path / "eager"), cache["digest"], CFG, SIZE, len(ds)
    )
    idx_batches = [[0, 1, 2, 3], [4, 5, 6, 7], [0, 1, 2, 3]]
    img_batches = [collate([ds[i] for i in b]) for b in idx_batches]
    # populate with the STEP's exact batch grouping: XLA reductions are
    # not batch-size-invariant at the ULP level, so bit-identical cached
    # features require extracting the same [4,h,w,3] batches the image
    # path will run (the store round-trip itself is bit-exact)
    with jax.disable_jit():
        for b, ib in zip(idx_batches[:2], img_batches[:2]):
            fs = np.asarray(extract_features(params, CFG,
                                             ib["source_image"]))
            ft = np.asarray(extract_features(params, CFG,
                                             ib["target_image"]))
            for j, i in enumerate(b):
                store.put(i, fs[j], ft[j])
    assert store.complete()
    feat_batches = [_feature_batch(store, b) for b in idx_batches]

    opt = make_optimizer(1e-3)
    with jax.disable_jit():
        s_img = create_train_state(params, opt)
        s_ft = create_train_state(params, opt)
        step_img = make_train_step(CFG, opt, donate=False)
        step_ft = make_train_step(CFG, opt, donate=False, from_features=True)
        losses_img, losses_ft = [], []
        for bi, bf in zip(img_batches, feat_batches):
            s_img, l_img = step_img(s_img, bi)
            s_ft, l_ft = step_ft(s_ft, bf)
            losses_img.append(float(l_img))
            losses_ft.append(float(l_ft))
    assert losses_ft == losses_img  # bitwise: exact float equality
    for a, b in zip(
        jax.tree.leaves(s_img.params["neigh_consensus"]),
        jax.tree.leaves(s_ft.params["neigh_consensus"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cached_path_matches_backbone_path_jitted(cache):
    """The jitted contract: same three steps, losses and NC params
    allclose (ULP-scale fusion noise only)."""
    ds, store, params = cache["ds"], cache["store"], cache["params"]
    idx_batches = [[0, 1, 2, 3], [4, 5, 6, 7], [0, 1, 2, 3]]
    img_batches = [collate([ds[i] for i in b]) for b in idx_batches]
    feat_batches = [_feature_batch(store, b) for b in idx_batches]

    opt = make_optimizer(1e-3)
    s_img = create_train_state(params, opt)
    s_ft = create_train_state(params, opt)
    step_img = make_train_step(CFG, opt, donate=False)
    step_ft = make_train_step(CFG, opt, donate=False, from_features=True)
    for bi, bf in zip(img_batches, feat_batches):
        s_img, l_img = step_img(s_img, bi)
        s_ft, l_ft = step_ft(s_ft, bf)
        np.testing.assert_allclose(
            float(l_ft), float(l_img), rtol=1e-4, atol=1e-7
        )
    for a, b in zip(
        jax.tree.leaves(s_img.params["neigh_consensus"]),
        jax.tree.leaves(s_ft.params["neigh_consensus"]),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        )


def test_eval_step_from_features_matches_loss(cache):
    batch = _feature_batch(cache["store"], [0, 1, 2, 3])
    ev = make_eval_step(CFG, from_features=True)
    np.testing.assert_allclose(
        float(ev(cache["params"], batch)),
        float(weak_loss_from_features(cache["params"], CFG, batch)),
        atol=1e-7,
    )
    # and against the image-path loss on the matching image batch
    img = collate([cache["ds"][i] for i in (0, 1, 2, 3)])
    np.testing.assert_allclose(
        float(ev(cache["params"], batch)),
        float(weak_loss(cache["params"], CFG, img)),
        rtol=1e-5, atol=1e-7,
    )


def test_from_features_refuses_training_trunk():
    """A cache under a training trunk would silently go stale; every
    entry point must refuse loudly at construction time."""
    from ncnet_tpu.train.loop import train as train_loop

    opt = make_optimizer()
    with pytest.raises(ValueError, match="frozen"):
        make_train_step(CFG, opt, from_features=True, train_fe=True)
    with pytest.raises(ValueError, match="frozen"):
        make_train_step(CFG, opt, from_features=True, fe_finetune_blocks=1)
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    with pytest.raises(ValueError, match="frozen"):
        train_loop(
            CFG, params, [], num_epochs=1, train_fe=True,
            from_features=True, data_parallel=False,
        )


def test_train_loop_from_features_end_to_end(cache, tmp_path):
    """loop.train() consumes a FeatureBatchLoader: one epoch trains,
    validates, and persists metrics — no image ever enters the loop."""
    import json

    from ncnet_tpu.train.loop import train as train_loop

    with FeatureBatchLoader(
        cache["store"], 4, shuffle=True, seed=7, num_workers=2
    ) as tl, FeatureBatchLoader(
        cache["store"], 4, num_workers=2
    ) as vl:
        _, hist = train_loop(
            CFG, cache["params"], tl, val_loader=vl, num_epochs=1,
            checkpoint_dir=str(tmp_path), data_parallel=False,
            log_every=100, from_features=True,
        )
    assert len(hist["train_loss"]) == 1
    assert np.isfinite(hist["train_loss"][0])
    assert np.isfinite(hist["val_loss"][0])
    lines = [
        json.loads(l)
        for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    assert lines[0]["epoch"] == 1 and np.isfinite(lines[0]["val_loss"])


# --- analytic FLOP accounting (bench.py) ------------------------------------


def test_train_step_flops_drops_exactly_the_trunk():
    sys.path.insert(0, str(REPO))
    from bench import train_step_flops

    k, c = (5, 5, 5), (16, 16, 1)
    full = train_step_flops(16, k, c)
    cached = train_step_flops(16, k, c, from_features=True)
    trunk = 16 * 2 * 6.5e9 * (400 / 224.0) ** 2
    assert cached < full
    np.testing.assert_allclose(full - cached, trunk, rtol=1e-12)


# --- CLI smoke (CI/tooling: the extractor can't rot) ------------------------


def test_extract_features_cli_smoke(tmp_path):
    """scripts/extract_features.py on the synthetic dataset, CPU: first
    run populates both splits, second run is a no-op on a complete cache,
    and the stores open clean."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable,
        str(REPO / "scripts" / "extract_features.py"),
        "--feature-cache", str(tmp_path / "cache"),
        "--synthetic", "--synthetic_n", "4", "--synthetic_val_n", "2",
        "--image_size", "32", "--batch_size", "2",
        # the smoke drills CLI wiring + cache completeness, not the trunk:
        # patch16 keeps both subprocess runs off the minute-scale resnet
        # compile (same trunk choice as the serve/eval parity tests)
        "--fe_arch", "patch16",
        "--compile-cache", str(tmp_path / "xla_cache"),
    ]
    r = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=300
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "extracted 4 pairs" in r.stdout, r.stdout

    r2 = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=300
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "already complete" in r2.stdout, r2.stdout

    for split, n in (("train", 4), ("val", 2)):
        store = FeatureStore.open_store(str(tmp_path / "cache" / split))
        assert store.num_items == n and store.complete()


# --- lint gate extension ----------------------------------------------------


def test_features_tree_lints_clean():
    """The repo-wide gate (test_analysis) sweeps ncnet_tpu/ recursively —
    this pins the NEW subsystem files explicitly so a future restructure
    can't silently drop them from the sweep."""
    from ncnet_tpu.analysis import rules  # noqa: F401  (registers rules)
    from ncnet_tpu.analysis.engine import SEVERITY_ORDER, lint_paths

    paths = [
        str(REPO / "ncnet_tpu" / "features"),
        str(REPO / "ncnet_tpu" / "data" / "features_loader.py"),
        str(REPO / "ncnet_tpu" / "utils" / "compile_cache.py"),
        str(REPO / "scripts" / "extract_features.py"),
    ]
    findings = [
        f for f in lint_paths(paths)
        if SEVERITY_ORDER[f.severity] >= SEVERITY_ORDER["warning"]
    ]
    assert not findings, "\n".join(f.format() for f in findings)
