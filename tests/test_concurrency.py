"""Concurrency audit runtime prong (PR 16): `OrderedLock` acquisition-
graph recording + cycle detection, the `ScheduleFuzzer` interleaving
explorer, the `make_lock` disabled-is-bare contract, the thread-ledger
hygiene of engine/fleet shutdown, and the deterministic replay of the
PR-11 `MicroBatcher` lost-request scenario under schedule perturbation.
"""

import json
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from ncnet_tpu.analysis import concurrency
from ncnet_tpu.analysis.findings import format_sarif
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.serve.batcher import MicroBatcher, Request
from ncnet_tpu.serve.engine import ServeEngine
from ncnet_tpu.serve.fleet import ServeFleet
from ncnet_tpu.serve.resilience import LatencyEstimator, ReplicaDown


@pytest.fixture(autouse=True)
def _clean_audit():
    concurrency.clear()
    faultinject.clear()
    yield
    concurrency.clear()
    faultinject.clear()


TOY_PARAMS = {"w": jnp.asarray(3.0, jnp.float32)}
KEY = ("k", 2)
SPEC = {"x": ((2,), np.float32)}


def _toy_apply(p, batch):
    return {"y": batch["x"] * p["w"]}


def _toy_payload(n, fill):
    return {"x": np.full((n,), fill, np.float32)}


# ----------------------------------------------------------------------
# make_lock: disabled is a BARE lock, enabled is instrumented


def test_make_lock_disabled_returns_bare_lock():
    lk = concurrency.make_lock("t.plain")
    rk = concurrency.make_lock("t.reentrant", reentrant=True)
    assert type(lk) is type(threading.Lock())
    assert type(rk) is type(threading.RLock())
    # and using them records NOTHING
    with lk:
        pass
    assert concurrency.acquisition_edges() == {}
    assert concurrency.held_stats() == {}


def test_make_lock_enabled_returns_ordered_lock():
    concurrency.enable()
    lk = concurrency.make_lock("t.audited")
    assert isinstance(lk, concurrency.OrderedLock)
    with lk:
        pass
    assert concurrency.held_stats()["t.audited"]["acquires"] == 1


def test_clear_beats_stale_env(monkeypatch):
    monkeypatch.setenv(concurrency.ENV_VAR, "1")
    concurrency.clear()  # clear() pins the env as loaded+disabled
    assert not concurrency.is_enabled()
    assert type(concurrency.make_lock("t.x")) is type(threading.Lock())


def test_env_var_enables(monkeypatch):
    monkeypatch.setenv(concurrency.ENV_VAR, "1")
    concurrency.clear()
    concurrency._env_loaded = False  # simulate a fresh process
    assert concurrency.is_enabled()
    assert isinstance(
        concurrency.make_lock("t.env"), concurrency.OrderedLock
    )


# ----------------------------------------------------------------------
# the injected lock-order-inversion drill (the acceptance golden test)


def test_injected_inversion_names_the_exact_two_lock_cycle():
    concurrency.enable()
    a = concurrency.make_lock("drill.A")
    b = concurrency.make_lock("drill.B")

    def a_then_b():
        for _ in range(25):
            with a:
                with b:
                    pass

    def b_then_a():
        for _ in range(25):
            with b:
                with a:
                    pass

    # run SEQUENTIALLY: both orders are recorded (the hazard) without
    # ever risking the actual deadlock in the test process
    for fn in (a_then_b, b_then_a):
        t = threading.Thread(target=fn)
        t.start()
        t.join()

    assert concurrency.find_cycles() == [["drill.A", "drill.B"]]
    findings = concurrency.lock_findings()
    cyc = [f for f in findings if f.rule == "lock-order-cycle"]
    assert len(cyc) == 1
    assert cyc[0].severity == "error"
    assert "drill.A -> drill.B -> drill.A" in cyc[0].message
    assert cyc[0].detail["cycle"] == ["drill.A", "drill.B"]
    # and the finding rides the shared SARIF pipeline like every rule
    doc = json.loads(format_sarif(
        findings, "lock-audit", concurrency.runtime_rules_meta()
    ))
    results = doc["runs"][0]["results"]
    assert any(r["ruleId"] == "lock-order-cycle" for r in results)


def test_consistent_order_has_no_cycle():
    concurrency.enable()
    a = concurrency.make_lock("ord.A")
    b = concurrency.make_lock("ord.B")
    for _ in range(25):
        with a:
            with b:
                pass
    assert concurrency.find_cycles() == []
    assert ("ord.A", "ord.B") in concurrency.acquisition_edges()
    assert concurrency.lock_findings() == []


def test_reentrant_reacquire_adds_no_self_edge():
    concurrency.enable()
    r = concurrency.make_lock("re.R", reentrant=True)
    with r:
        with r:
            pass
    assert concurrency.acquisition_edges() == {}
    assert concurrency.held_stats()["re.R"]["acquires"] == 2


def test_held_time_outlier_finding():
    concurrency.enable(held_outlier_s=0.01)
    lk = concurrency.make_lock("slow.L")
    with lk:
        time.sleep(0.03)
    fs = [
        f for f in concurrency.lock_findings()
        if f.rule == "lock-held-outlier"
    ]
    assert len(fs) == 1
    assert fs[0].path == "lock:slow.L"
    assert fs[0].severity == "warning"
    assert fs[0].detail["held_s"] > 0.01


def test_outlier_findings_capped_per_lock():
    concurrency.enable(held_outlier_s=0.001)
    lk = concurrency.make_lock("spam.L")
    for _ in range(10):
        with lk:
            time.sleep(0.002)
    fs = [
        f for f in concurrency.lock_findings()
        if f.rule == "lock-held-outlier"
    ]
    assert len(fs) == concurrency._OUTLIER_CAP_PER_LOCK


def test_report_shape():
    concurrency.enable()
    lk = concurrency.make_lock("rep.L")
    with lk:
        pass
    rep = concurrency.report()
    assert rep["enabled"] is True
    assert rep["locks"]["rep.L"]["acquires"] == 1
    assert rep["cycles"] == []
    assert rep["findings"] == []


# ----------------------------------------------------------------------
# ScheduleFuzzer


def test_fuzzer_install_uninstall():
    fz = concurrency.ScheduleFuzzer(seed=3)
    with fz:
        assert concurrency._fuzzer is fz
    assert concurrency._fuzzer is None
    # a foreign uninstall must not clobber another fuzzer
    a, b = concurrency.ScheduleFuzzer(1), concurrency.ScheduleFuzzer(2)
    a.install()
    b.uninstall()
    assert concurrency._fuzzer is a
    a.uninstall()


def test_fuzzer_yields_are_seeded_per_thread():
    fz = concurrency.ScheduleFuzzer(seed=11, p=1.0, max_sleep_s=1e-5)
    draws = {}

    def run(tag):
        rng = fz._rng()
        draws[tag] = [rng.random() for _ in range(4)]

    t1 = threading.Thread(target=run, args=("a",))
    t1.start()
    t1.join()
    t2 = threading.Thread(target=run, args=("b",))
    t2.start()
    t2.join()
    # distinct per-thread streams, each deterministic in (seed, arrival)
    assert draws["a"] != draws["b"]
    import random as _random

    ref = _random.Random(11 * 1_000_003 + 0)
    assert draws["a"] == [ref.random() for _ in range(4)]


# ----------------------------------------------------------------------
# the PR-11 MicroBatcher lost-request scenario, fuzzed (satellite 2)


def test_microbatcher_lost_request_fuzzed_replay():
    """PR 11's bug: with max_batch=1 a fresh at-cap group was PARKED
    instead of flushed; a racing same-key add then grew it past
    batch_sizes[-1] and the request hung forever. The fix flushes
    immediately. Replay the race through the ScheduleFuzzer with a
    pinned seed: two threads hammer the same key with max_batch=1 while
    seeded yields perturb the interleaving at every lock boundary —
    every request must come back exactly once, in a size-1 batch."""
    concurrency.enable()
    with concurrency.ScheduleFuzzer(seed=1107, p=0.5, max_sleep_s=5e-5):
        mb = MicroBatcher(max_batch=1, max_wait=0.001)  # audited lock
        out_lock = threading.Lock()
        batches = []

        def hammer(tag):
            for i in range(100):
                fut = object()
                b = mb.add(Request(KEY, {"x": (tag, i)}, fut, 0.0, None))
                if b is not None:
                    with out_lock:
                        batches.append(b)

        threads = [
            threading.Thread(target=hammer, args=(tag,))
            for tag in ("t1", "t2")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        batches.extend(mb.drain())

    seen = [b.requests[0].payload["x"] for b in batches]
    assert all(len(b.requests) == 1 for b in batches), (
        "max_batch=1 group grew past the cap"
    )
    assert len(seen) == 200 and len(set(seen)) == 200, (
        f"lost or duplicated requests: {len(seen)} batches, "
        f"{len(set(seen))} unique"
    )
    # the batcher's single lock cannot deadlock; the audit proves it
    assert concurrency.find_cycles() == []


# ----------------------------------------------------------------------
# LatencyEstimator EWMA atomicity under hammer (satellite 1)


def test_latency_estimator_concurrent_hammer_stays_in_hull():
    concurrency.enable()
    with concurrency.ScheduleFuzzer(seed=5, p=0.3, max_sleep_s=2e-5):
        est = LatencyEstimator(alpha=0.5)  # audited lock
        lo, hi = 0.010, 0.020

        def observer(seed):
            for i in range(200):
                est.observe(KEY, lo if (i + seed) % 2 else hi)

        threads = [
            threading.Thread(target=observer, args=(s,)) for s in (0, 1, 2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # EWMA of samples within [lo, hi] can never leave the hull —
        # unless an unlocked read-modify-write tore an update
        assert lo <= est.estimate(KEY) <= hi
        assert lo <= est.estimate() <= hi
    assert concurrency.find_cycles() == []


# ----------------------------------------------------------------------
# thread-ledger hygiene (satellite 3)


def test_engine_shutdown_joins_ledger_no_stragglers():
    eng = ServeEngine(
        _toy_apply, TOY_PARAMS, max_batch=2, max_wait=0.001,
        hang_timeout=5.0,
    )
    eng.warmup([(KEY, SPEC)])
    futs = [
        eng.submit(key=KEY, payload=_toy_payload(2, float(i)))
        for i in range(8)
    ]
    for f in futs:
        f.result(timeout=10)
    # a live engine must NOT report its worker pool as stragglers
    assert eng.report()["straggler_threads"] == []
    names = sorted(t.name for t in eng._thread_ledger)
    assert any(n.startswith("serve-prep-") for n in names)
    assert "serve-readout" in names
    assert "serve-dispatch-0" in names
    assert "serve-watchdog" in names
    eng.close()
    assert eng.report()["straggler_threads"] == []
    assert all(not t.is_alive() for t in eng._thread_ledger)


def test_fleet_close_joins_ledger_no_stragglers():
    fleet = ServeFleet(
        _toy_apply, TOY_PARAMS, replicas=2, replica_hang_timeout=5.0,
        max_batch=2, max_wait=0.001,
    )
    fleet.warmup([(KEY, SPEC)])
    fleet.submit(key=KEY, payload=_toy_payload(2, 1.0)).result(timeout=10)
    assert fleet.report()["straggler_threads"] == []
    names = sorted(t.name for t in fleet._thread_ledger)
    assert "fleet-requeue" in names
    assert sum(n == "serve-watchdog" for n in names) == 2
    fleet.close()
    assert fleet.report()["straggler_threads"] == []
    assert all(not t.is_alive() for t in fleet._thread_ledger)


# ----------------------------------------------------------------------
# the audited chaos drill (satellite 5's gate, runnable locally):
# fleet kill/rejoin under load with every serve lock instrumented and
# the fuzzer perturbing schedules — no lock-order cycle may appear


def test_fleet_chaos_drill_under_lock_audit():
    concurrency.enable()
    with concurrency.ScheduleFuzzer(seed=1311, p=0.25, max_sleep_s=5e-5):
        fleet = ServeFleet(
            _toy_apply, TOY_PARAMS, replicas=3,
            max_batch=4, max_wait=0.002,
        )
        try:
            fleet.warmup([(KEY, SPEC)])
            faultinject.inject("serve.replica.kill", "crash", at=10)
            futs = [
                fleet.submit(key=KEY, payload=_toy_payload(2, float(i)))
                for i in range(60)
            ]
            resolved = 0
            for f in futs:
                try:
                    f.result(timeout=10)
                    resolved += 1
                except ReplicaDown as exc:
                    assert exc.dispatched
                    resolved += 1
            assert resolved == 60
            faultinject.clear()
            dead = fleet.quarantined_ids()
            if dead:  # the injected kill landed on a routed replica
                assert fleet.rejoin(dead[0]) > 0
            for i in range(20):
                fleet.submit(
                    key=KEY, payload=_toy_payload(2, float(i))
                ).result(timeout=10)
        finally:
            fleet.close()

    # the drill's gate: schedule exploration surfaced no ordering hazard
    assert concurrency.find_cycles() == [], concurrency.report()["edges"]
    gating = [
        f for f in concurrency.lock_findings() if f.severity == "error"
    ]
    assert gating == [], "\n".join(f.format() for f in gating)
    # the serve locks really were instrumented (the drill is not vacuous)
    stats = concurrency.held_stats()
    assert any(name.startswith("serve.") for name in stats)
