"""Preemption-safety proofs (ncnet_tpu.resilience + train/checkpoint/loop).

The point of this file is that recovery is DEMONSTRATED, not asserted:
faults are injected at the named crash points (checkpoint mid-write, step
boundaries, worker batch construction) and the resumed run must match the
uninterrupted run bitwise on params — plus unit coverage of the durable
write/verify/rotate/walk-back primitives, the fault registry itself, and
the SIGTERM-to-clean-exit guard.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import jax

from ncnet_tpu.data.loader import DataLoader
from ncnet_tpu.data.pairs import SyntheticPairDataset
from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
from ncnet_tpu.resilience import durable, faultinject
from ncnet_tpu.resilience.signals import PreemptionGuard
from ncnet_tpu.train.checkpoint import (
    CheckpointData,
    load_checkpoint,
    load_latest_valid,
    save_checkpoint,
)
from ncnet_tpu.train.loop import train

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    faultinject.clear()
    yield
    faultinject.clear()


def tiny_ckpt(step=1, fill=0.0):
    return CheckpointData(
        config=CFG,
        params={"w": np.full((64,), fill, np.float32)},
        step=step,
    )


# --- durable primitives -----------------------------------------------------


def test_durable_write_and_verify(tmp_path):
    path = str(tmp_path / "artifact.bin")
    durable.durable_write_bytes(path, b"payload-bytes")
    assert durable.verify_digest(path) is True
    assert durable.read_verified_bytes(path) == b"payload-bytes"
    # bitrot: flip a byte -> detected, not parsed
    blob = bytearray(open(path, "rb").read())
    blob[0] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(blob))
    assert durable.verify_digest(path) is False
    with pytest.raises(durable.IntegrityError):
        durable.read_verified_bytes(path)


def test_durable_legacy_file_without_sidecar(tmp_path):
    """Pre-durability files (no sidecar) still load; verification is just
    unknown rather than failed."""
    path = str(tmp_path / "legacy.bin")
    with open(path, "wb") as f:
        f.write(b"old-format")
    assert durable.verify_digest(path) is None
    assert durable.read_verified_bytes(path) == b"old-format"


def test_retention_rotates_and_prunes(tmp_path):
    path = str(tmp_path / "ck.msgpack")
    for step in (1, 2, 3):
        save_checkpoint(path, tiny_ckpt(step=step, fill=float(step)), keep=2)
    names = sorted(os.listdir(tmp_path))
    assert os.path.basename(durable.step_path(path, 2)) in names
    assert os.path.basename(durable.step_path(path, 3)) in names
    assert os.path.basename(durable.step_path(path, 1)) not in names
    # newest-first walk order: primary, then history by descending step
    assert durable.candidates(path) == [
        path, durable.step_path(path, 3), durable.step_path(path, 2)
    ]


def test_load_latest_valid_walks_past_truncated(tmp_path):
    """The acceptance-criteria case: a deliberately truncated latest file
    must cost one fallback, not the run."""
    path = str(tmp_path / "ck.msgpack")
    save_checkpoint(path, tiny_ckpt(step=1, fill=1.0), keep=3)
    save_checkpoint(path, tiny_ckpt(step=2, fill=2.0), keep=3)
    # tear the primary the way a mid-write kill of a NON-durable writer
    # would have: a half-written NEW file under the checkpoint name (the
    # step-2 history hardlink keeps the intact old inode)
    half = open(path, "rb").read()[: os.path.getsize(path) // 2]
    os.remove(path)
    with open(path, "wb") as f:
        f.write(half)
    ck, used = load_latest_valid(path)
    assert used == durable.step_path(path, 2)
    assert int(ck.step) == 2
    np.testing.assert_array_equal(ck.params["w"], np.full((64,), 2.0, np.float32))

    # everything torn -> loud FileNotFoundError, not a silent fresh start
    for cand in durable.candidates(path):
        with open(cand, "r+b") as f:
            f.truncate(4)
    with pytest.raises(FileNotFoundError):
        load_latest_valid(path)


def test_corrupt_bytes_fault_is_detected_at_load(tmp_path):
    """`checkpoint.bytes=corrupt` models bitrot between digest and disk:
    the sidecar records the intended bytes, so load must refuse."""
    path = str(tmp_path / "ck.msgpack")
    save_checkpoint(path, tiny_ckpt(step=1, fill=1.0), keep=3)
    faultinject.configure("checkpoint.bytes=corrupt@1")
    save_checkpoint(path, tiny_ckpt(step=2, fill=2.0), keep=3)
    assert durable.verify_digest(path) is False
    with pytest.raises(durable.IntegrityError):
        load_checkpoint(path)
    ck, used = load_latest_valid(path)
    # the corrupt step-2 bytes were also hardlinked into history; recovery
    # lands on the intact step-1 save
    assert int(ck.step) == 1 and used == durable.step_path(path, 1)


def test_crash_during_write_leaves_previous_checkpoint(tmp_path):
    """In-process crash (exception unwind) at both kill windows: the
    torn temp file never replaces the good checkpoint."""
    path = str(tmp_path / "ck.msgpack")
    save_checkpoint(path, tiny_ckpt(step=1, fill=1.0))
    for point in ("checkpoint.write", "checkpoint.rename"):
        faultinject.clear()
        faultinject.inject(point, "crash", at=1)
        with pytest.raises(faultinject.InjectedFault):
            save_checkpoint(path, tiny_ckpt(step=2, fill=2.0))
        assert durable.verify_digest(path) is True
        ck = load_checkpoint(path)
        assert int(ck.step) == 1, f"crash at {point} clobbered the checkpoint"


def test_hard_kill_mid_checkpoint_write(tmp_path):
    """A true preemption (os._exit, no cleanup) landing mid-write of the
    checkpoint temp file: the previous checkpoint must stay loadable."""
    path = str(tmp_path / "ck.msgpack")
    script = f"""
import sys
sys.path.insert(0, {REPO!r})
import numpy as np
from ncnet_tpu.models.immatchnet import ImMatchNetConfig
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.train.checkpoint import CheckpointData, save_checkpoint

cfg = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
def ck(step, fill):
    return CheckpointData(
        config=cfg, params={{"w": np.full((64,), fill, np.float32)}}, step=step
    )

path = {path!r}
save_checkpoint(path, ck(1, 1.0))
faultinject.configure("checkpoint.write=kill@1")
save_checkpoint(path, ck(2, 2.0))  # dies half-written, pre-rename
raise SystemExit("unreachable: the kill fault did not fire")
"""
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 137, proc.stderr
    ck, used = load_latest_valid(path)
    assert used == path and int(ck.step) == 1
    np.testing.assert_array_equal(ck.params["w"], np.full((64,), 1.0, np.float32))
    # the torn temp file is on disk (proof the kill landed mid-write) but
    # invisible to recovery
    tmps = [n for n in os.listdir(tmp_path) if ".tmp." in n]
    assert tmps, "kill fault should have left a torn temp file behind"


def test_best_copy_is_durable_and_verified(tmp_path):
    """The satellite fix: best_ goes through temp+rename+digest, not
    shutil.copyfile."""
    path = str(tmp_path / "ck.msgpack")
    save_checkpoint(path, tiny_ckpt(step=1, fill=3.0), is_best=True)
    best = str(tmp_path / "best_ck.msgpack")
    assert durable.verify_digest(best) is True
    ck = load_checkpoint(best)
    np.testing.assert_array_equal(ck.params["w"], np.full((64,), 3.0, np.float32))


def test_cursor_roundtrip_and_legacy_none(tmp_path):
    path = str(tmp_path / "ck.msgpack")
    cursor = {
        "epoch": 2, "batch_index": 5, "shuffle_seed": 7,
        "epoch_losses": [0.5, 0.25, 0.125],
    }
    data = tiny_ckpt(step=13)
    data.cursor = cursor
    save_checkpoint(path, data)
    loaded = load_checkpoint(path)
    assert loaded.cursor == cursor
    # epoch-boundary checkpoints carry no cursor
    save_checkpoint(path, tiny_ckpt(step=14))
    assert load_checkpoint(path).cursor is None


# --- fault registry ---------------------------------------------------------


def test_faultinject_disabled_is_identity():
    faultinject.clear()
    assert not faultinject.is_enabled()
    blob = b"untouched"
    assert faultinject.fire("checkpoint.bytes", blob) is blob
    assert faultinject.fire("step.boundary") is None


def test_faultinject_at_counts_hits():
    faultinject.configure("step.boundary=crash@3")
    faultinject.fire("step.boundary")
    faultinject.fire("step.boundary")
    with pytest.raises(faultinject.InjectedFault):
        faultinject.fire("step.boundary")
    # past its hit index the fault stays quiet
    faultinject.fire("step.boundary")


def test_faultinject_spec_errors():
    with pytest.raises(ValueError):
        faultinject.configure("step.boundary")
    with pytest.raises(ValueError):
        faultinject.inject("p", "explode")


def test_faultinject_corrupt_changes_bytes():
    faultinject.inject("checkpoint.bytes", "corrupt")
    blob = bytes(range(64))
    out = faultinject.fire("checkpoint.bytes", blob)
    assert out != blob and len(out) == len(blob)


# --- preemption guard -------------------------------------------------------


def test_preemption_guard_sets_flag_and_restores_handler():
    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard(signals=(signal.SIGTERM,)) as guard:
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.requested  # delivered synchronously in the main thread
    assert signal.getsignal(signal.SIGTERM) is before


def test_preemption_guard_second_signal_falls_through():
    hits = []
    old = signal.signal(signal.SIGTERM, lambda *a: hits.append(a))
    try:
        with PreemptionGuard(signals=(signal.SIGTERM,)) as guard:
            guard._handle(signal.SIGTERM, None)
            assert guard.requested and not hits
            guard._handle(signal.SIGTERM, None)  # impatient operator
        assert hits, "second signal must reach the previous handler"
    finally:
        signal.signal(signal.SIGTERM, old)


# --- end-to-end: kill-and-resume equals uninterrupted -----------------------

N_PAIRS, BATCH, EPOCHS, SIZE = 8, 2, 2, 32
STEPS_PER_EPOCH = N_PAIRS // BATCH


def _loader(**kw):
    ds = SyntheticPairDataset(n=N_PAIRS, output_size=(SIZE, SIZE), seed=11)
    kw.setdefault("num_workers", 1)
    kw.setdefault("prefetch", 0)
    return DataLoader(ds, BATCH, shuffle=True, seed=5, drop_last=True, **kw)


def _run(ckdir, **train_kw):
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    kw = dict(
        num_epochs=EPOCHS, checkpoint_dir=str(ckdir), data_parallel=False,
        log_every=100, save_every_steps=2, keep_checkpoints=4,
    )
    kw.update(train_kw)
    return train(CFG, kw.pop("params", params), _loader(), None, **kw)


def _resume(ckdir, **train_kw):
    ck, used = load_latest_valid(os.path.join(str(ckdir), "ncnet_tpu.msgpack"))
    kw = dict(
        params=ck.params,
        opt_state=ck.opt_state,
        start_epoch=ck.epoch,
        start_step=ck.step,
        initial_best_val=ck.best_val_loss,
        initial_train_hist=ck.train_loss,
        initial_val_hist=ck.val_loss,
    )
    if ck.cursor:
        kw["start_epoch"] = ck.cursor["epoch"]
        kw["start_batch"] = ck.cursor["batch_index"]
        kw["start_epoch_losses"] = ck.cursor["epoch_losses"]
    kw.update(train_kw)
    return _run(ckdir, **kw), ck


def _final_state(ckdir):
    ck = load_checkpoint(os.path.join(str(ckdir), "ncnet_tpu.msgpack"))
    lines = [
        json.loads(l)
        for l in open(os.path.join(str(ckdir), "metrics.jsonl"))
    ]
    return ck, lines


def _assert_bitwise_equal(ck_a, ck_b):
    flat_a, _ = jax.tree_util.tree_flatten_with_path(ck_a.params)
    flat_b, _ = jax.tree_util.tree_flatten_with_path(ck_b.params)
    assert len(flat_a) == len(flat_b)
    for (path_a, leaf_a), (_, leaf_b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(
            np.asarray(leaf_a), np.asarray(leaf_b),
            err_msg=f"params differ at {jax.tree_util.keystr(path_a)}",
        )
    for a, b in zip(jax.tree.leaves(ck_a.opt_state), jax.tree.leaves(ck_b.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(ck_a.step) == int(ck_b.step)
    np.testing.assert_array_equal(
        np.asarray(ck_a.train_loss), np.asarray(ck_b.train_loss)
    )


def _assert_metrics_tails_match(lines_a, lines_b):
    """Identical metrics.jsonl tails, modulo wall-clock epoch_seconds."""
    strip = lambda l: {k: v for k, v in l.items() if k != "epoch_seconds"}
    assert [strip(l) for l in lines_a] == [strip(l) for l in lines_b]


@pytest.fixture(scope="module")
def uninterrupted(uninterrupted_run):
    """The session-shared uninterrupted run (tests/conftest.py) — the
    same schedule `_run` executes, paid once for the whole suite. It is
    saved in the sharded format, but only the loaded VALUES are compared
    here, and test_distributed_ckpt.py pins sharded == legacy bitwise."""
    ck, lines, _ = uninterrupted_run
    return ck, lines


def test_resume_after_crash_at_step_boundary(tmp_path, uninterrupted):
    """Kill at a mid-epoch step boundary; the resumed run must be
    indistinguishable — bitwise on params/opt_state, identical metrics."""
    crash_hit = STEPS_PER_EPOCH + 3  # epoch 1, step 3: past a step-2 snapshot
    faultinject.inject("step.boundary", "crash", at=crash_hit)
    with pytest.raises(faultinject.InjectedFault):
        _run(tmp_path)
    faultinject.clear()

    (_, history), ck_at_resume = _resume(tmp_path)
    assert ck_at_resume.cursor is not None, "expected a mid-epoch snapshot"
    assert ck_at_resume.cursor["batch_index"] == 2
    assert not history["preempted"]

    ck_a, lines_a = uninterrupted
    ck_b, lines_b = _final_state(tmp_path)
    _assert_bitwise_equal(ck_a, ck_b)
    _assert_metrics_tails_match(lines_a, lines_b)


def test_resume_after_crash_in_worker_batch_construction(tmp_path, uninterrupted):
    """Kill during batch construction inside a loader worker; training dies
    loudly, resume from the last snapshot matches bitwise."""
    faultinject.inject("data.batch", "crash", at=STEPS_PER_EPOCH + 3)
    with pytest.raises(RuntimeError, match="injected crash"):
        _run(tmp_path)
    faultinject.clear()

    _resume(tmp_path)
    ck_a, lines_a = uninterrupted
    ck_b, lines_b = _final_state(tmp_path)
    _assert_bitwise_equal(ck_a, ck_b)
    _assert_metrics_tails_match(lines_a, lines_b)


def test_preemption_checkpoints_once_and_resumes(tmp_path, uninterrupted):
    """SIGTERM-style preemption mid-epoch: one cursor checkpoint, clean
    return, and the resumed run matches the uninterrupted one bitwise."""

    class _Guard:
        def __init__(self, after_steps):
            self.after = after_steps
            self.seen = 0

        @property
        def requested(self):
            return self.seen >= self.after

    guard = _Guard(after_steps=STEPS_PER_EPOCH + 1)  # epoch 1, step 1
    real_fire = faultinject.fire

    def counting_fire(point, data=None):
        if point == "step.boundary":
            guard.seen += 1
        return real_fire(point, data)

    faultinject_fire_patch = pytest.MonkeyPatch()
    faultinject_fire_patch.setattr(
        "ncnet_tpu.train.loop.faultinject.fire", counting_fire
    )
    try:
        _, history = _run(tmp_path, preemption=guard)
    finally:
        faultinject_fire_patch.undo()
    assert history["preempted"]

    ck_mid = load_checkpoint(os.path.join(str(tmp_path), "ncnet_tpu.msgpack"))
    assert ck_mid.cursor == {
        "epoch": 1, "batch_index": 1, "shuffle_seed": 5,
        "epoch_losses": ck_mid.cursor["epoch_losses"],
    }
    assert len(ck_mid.cursor["epoch_losses"]) == 1

    (_, history2), _ = _resume(tmp_path)
    assert not history2["preempted"]
    ck_a, lines_a = uninterrupted
    ck_b, lines_b = _final_state(tmp_path)
    _assert_bitwise_equal(ck_a, ck_b)
    _assert_metrics_tails_match(lines_a, lines_b)
