"""ncnet_tpu.telemetry: registry semantics, Prometheus golden text, the
disabled-tracer no-op contract, durable JSONL export under injected
faults, the report's span-tree self-time math, serve-engine stats
parity, and one in-process tiny training run producing the full
--telemetry artifact set."""

import json
import math
import os
import sys
import time
import tracemalloc
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.resilience import faultinject
from ncnet_tpu.resilience.faultinject import InjectedFault
from ncnet_tpu.telemetry import session as telemetry_session
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.export import (
    PROM_NAME,
    JsonlWriter,
    events_name,
    find_event_logs,
    metric_events,
    prom_name,
    read_events,
    write_prometheus,
)
from ncnet_tpu.telemetry.profiler import ProfileWindow, parse_steps
from ncnet_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS,
    MetricsRegistry,
    percentiles,
    summarize_latencies,
)

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))  # for scripts.telemetry_report

from scripts.telemetry_report import aggregate_spans, render  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Tracer and faults are process globals; every test starts and ends
    with both off (the session module is reset too, so a failing test
    cannot leak an active session into the next)."""
    faultinject.clear()
    trace.disable()
    trace.drain()
    telemetry_session._active = None
    yield
    faultinject.clear()
    telemetry_session.stop()
    trace.disable()
    trace.drain()


# ----------------------------------------------------------------------
# registry semantics


def test_counter_monotonic_and_kind_conflict():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", "requests")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    assert c.value == 5  # the rejected delta did not land
    assert reg.counter("reqs_total") is c  # get-or-create returns SAME obj
    with pytest.raises(TypeError):
        reg.gauge("reqs_total")  # a name means one thing
    with pytest.raises(ValueError):
        reg.counter("bad name with spaces")


def test_gauge_set_and_callback():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(3)
    assert g.value == 3
    backing = [7]
    g.set_fn(lambda: backing[0])
    backing[0] = 9
    assert g.value == 9  # sampled at read time, the queue-depth idiom

    def dead():
        raise RuntimeError("queue gone")

    g.set_fn(dead)
    assert math.isnan(g.value)  # a dead callback must not kill a scrape


def test_histogram_bucket_boundaries_le_inclusive():
    reg = MetricsRegistry()
    h = reg.histogram("lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.01, 0.1, 1.0, 5.0, 0.005):
        h.observe(v)
    # Prometheus convention: value == le lands IN that bucket (cumulative)
    assert h.bucket_counts() == [
        (0.01, 2),  # 0.005, 0.01
        (0.1, 3),  # + 0.1
        (1.0, 4),  # + 1.0
        (math.inf, 5),  # + 5.0
    ]
    assert h.count == 5
    assert h.sum == pytest.approx(6.115)
    with pytest.raises(ValueError):
        reg.histogram("bad", buckets=(1.0, 1.0))  # not strictly increasing
    with pytest.raises(ValueError):
        reg.histogram("bad2", buckets=(1.0, math.inf))  # finite bounds only


def test_percentiles_and_summary_shims_are_the_one_implementation():
    samples = [0.001 * i for i in range(1, 101)]
    p = percentiles(samples)
    assert p["p50"] == pytest.approx(np.percentile(samples, 50))
    assert p["p99"] == pytest.approx(np.percentile(samples, 99))
    s = summarize_latencies(samples)
    assert s["count"] == 100
    assert s["mean"] == pytest.approx(np.mean(samples))
    assert s["p95"] == p["p95"]
    empty = summarize_latencies([])
    assert empty["count"] == 0 and math.isnan(empty["p50"])

    # benchmarks/timing.py re-exports the SAME functions (satellite: one
    # percentile implementation repo-wide)
    sys.path.insert(0, str(REPO / "benchmarks"))
    try:
        import timing
    finally:
        sys.path.pop(0)
    assert timing.percentiles is percentiles
    assert timing.summarize_latencies is summarize_latencies


def test_prometheus_text_golden():
    reg = MetricsRegistry()
    reg.counter("reqs_total", "requests seen").inc(3)
    reg.gauge("depth").set(2.5)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    assert reg.to_prometheus() == (
        "# TYPE depth gauge\n"
        "depth 2.5\n"
        "# HELP lat_seconds latency\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 1\n'
        'lat_seconds_bucket{le="1"} 2\n'
        'lat_seconds_bucket{le="+Inf"} 2\n'
        "lat_seconds_sum 0.55\n"
        "lat_seconds_count 2\n"
        "# HELP reqs_total requests seen\n"
        "# TYPE reqs_total counter\n"
        "reqs_total 3\n"
    )


# ----------------------------------------------------------------------
# tracer: disabled-is-free contract, nesting, thread paths


def test_disabled_span_is_shared_noop_singleton():
    assert not trace.is_enabled()
    s1 = trace.span("step/device_compute")
    s2 = trace.span("anything/else")
    assert s1 is s2  # ONE cached instance, no per-call allocation
    with s1:
        pass  # enter/exit are no-ops
    assert trace.drain() == []  # and nothing was recorded


def test_disabled_span_allocates_nothing():
    """The hot loops keep their spans unconditionally; the disabled path
    must not allocate (tracemalloc sees zero new blocks from trace.py).

    Measured up to 3 times: in a long full-suite run a straggler
    background thread from an earlier test (serving drills leave
    fault-delayed threads that wake a minute later) can allocate a
    couple of trace.py blocks (thread tags, a late span emit) inside
    the tracemalloc window. That noise is transient and tiny; a REAL
    disabled-path regression allocates on every one of the 100 spans
    in every measurement, so requiring ONE clean measurement keeps the
    zero-allocation contract exact."""
    assert not trace.is_enabled()
    span = trace.span  # the bound method, as instrumentation sites use it
    with span("warm/up"):
        pass
    trace_py = os.path.join("telemetry", "trace.py")
    for _attempt in range(3):
        tracemalloc.start()
        try:
            for _ in range(100):
                with span("step/device_compute"):
                    pass
            snap = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        allocs = [
            s for s in snap.statistics("filename")
            if s.traceback[0].filename.endswith(trace_py)
        ]
        if allocs == []:
            return
        time.sleep(0.2)  # let the straggler finish, then re-measure
    assert allocs == [], f"disabled span allocated: {allocs}"


def test_enabled_spans_nest_and_time():
    trace.enable()
    with trace.span("serve/dispatch"):
        with trace.span("serve/device"):
            pass
    events = trace.drain()
    assert [e["name"] for e in events] == ["serve/device", "serve/dispatch"]
    inner, outer = events
    assert outer["path"] == "serve/dispatch"
    assert inner["path"] == "serve/dispatch>serve/device"  # ">" = nesting
    assert 0.0 <= inner["dur_s"] <= outer["dur_s"]
    assert inner["ok"] and outer["ok"]
    assert inner["ts"] >= outer["ts"]


def test_span_records_failure_and_pops_stack():
    trace.enable()
    with pytest.raises(RuntimeError):
        with trace.span("step/data_wait"):
            raise RuntimeError("loader died")
    with trace.span("step/device_compute"):
        pass
    bad, good = trace.drain()
    assert bad["ok"] is False
    assert good["path"] == "step/device_compute"  # stack popped on error


# ----------------------------------------------------------------------
# exporters: JSONL round-trip, durability under faults, .prom snapshot


def test_jsonl_round_trip_and_torn_line_skip(tmp_path):
    path = tmp_path / "events.jsonl"
    with JsonlWriter(str(path), flush_every=2) as w:
        w.write({"type": "span", "name": "a", "v": 1})
        w.write({"type": "span", "name": "b", "np": np.float32(0.5)})
    with open(path, "ab") as f:
        f.write(b'{"type": "span", "na')  # a crash-torn trailing line
    events = read_events(str(path))
    assert [e["name"] for e in events] == ["a", "b"]
    assert events[1]["np"] == 0.5  # numpy scalars serialized via .item()


def test_jsonl_crash_fault_leaves_complete_lines(tmp_path):
    """telemetry.write armed to crash on the SECOND flush: the first
    flush's lines are durably on disk, the crashed flush's are not —
    never a half-written record."""
    path = tmp_path / "events.jsonl"
    faultinject.inject("telemetry.write", "crash", at=2)
    w = JsonlWriter(str(path), flush_every=1)
    w.write({"n": 1})
    with pytest.raises(InjectedFault):
        w.write({"n": 2})
    assert [e["n"] for e in read_events(str(path))] == [1]
    faultinject.clear()
    w.write({"n": 3})  # the writer survives an injected flush failure
    w.close()
    assert [e["n"] for e in read_events(str(path))] == [1, 2, 3]


def test_write_prometheus_is_durable(tmp_path):
    reg = MetricsRegistry()
    reg.counter("x_total").inc()
    path = tmp_path / PROM_NAME
    write_prometheus(str(path), reg)
    assert path.read_text().endswith("x_total 1\n")
    assert (tmp_path / (PROM_NAME + ".sha256")).exists()  # durable sidecar

    # mid-write crash (durable temp+rename discipline): no torn snapshot
    faultinject.inject("telemetry.write", "crash")
    reg.counter("x_total").inc()
    with pytest.raises(InjectedFault):
        write_prometheus(str(path), reg)
    assert path.read_text().endswith("x_total 1\n")  # old snapshot intact


def test_metric_events_mirror_snapshot():
    reg = MetricsRegistry()
    reg.counter("a_total").inc(2)
    reg.histogram("b_seconds", buckets=DEFAULT_LATENCY_BUCKETS).observe(0.2)
    events = metric_events(reg, ts=123.0)
    assert {e["name"] for e in events} == {"a_total", "b_seconds"}
    by_name = {e["name"]: e for e in events}
    assert by_name["a_total"]["value"] == 2
    assert by_name["a_total"]["ts"] == 123.0
    assert by_name["b_seconds"]["count"] == 1


# ----------------------------------------------------------------------
# sessions + report math


def test_session_round_trip_and_single_session_contract(tmp_path):
    reg = MetricsRegistry()
    reg.counter("pairs_total").inc(3)
    telemetry_session.start(str(tmp_path), registry=reg, label="test")
    with pytest.raises(RuntimeError):
        telemetry_session.start(str(tmp_path / "other"))
    with trace.span("eval/pair"):
        pass
    telemetry_session.stop()
    telemetry_session.stop()  # idempotent

    # sessions write the per-process layout (events_proc<P>.jsonl) so
    # multihost runs can share one --telemetry dir without clobbering
    events = read_events(str(tmp_path / events_name(0)))
    kinds = [e["type"] for e in events]
    assert kinds[0] == "meta" and "span" in kinds and "metric" in kinds
    assert events[0]["process_index"] == 0
    assert not trace.is_enabled()  # stop() disabled the tracer
    prom = (tmp_path / prom_name(0)).read_text()
    assert "pairs_total 3" in prom
    assert find_event_logs(str(tmp_path)) == [str(tmp_path / events_name(0))]


def test_report_self_time_math():
    """self = total - direct children; span NAMES may contain '/' while
    '>' is the nesting separator, so 'serve/dispatch' under no parent and
    'serve/device' under it must resolve parentage correctly."""

    def span(path, dur):
        return {"type": "span", "path": path, "dur_s": dur,
                "name": path.rsplit(">", 1)[-1]}

    rows = aggregate_spans([
        span("serve/dispatch", 1.0),
        span("serve/dispatch", 1.0),
        span("serve/dispatch>serve/device", 0.7),
        span("serve/dispatch>serve/device", 0.5),
        span("serve/dispatch>serve/device>step/loss_sync", 0.2),
        span("eval/pair", 0.3),
    ])
    assert rows["serve/dispatch"]["count"] == 2
    assert rows["serve/dispatch"]["total_s"] == pytest.approx(2.0)
    assert rows["serve/dispatch"]["self_s"] == pytest.approx(0.8)
    assert rows["serve/dispatch>serve/device"]["self_s"] == pytest.approx(1.0)
    assert rows["eval/pair"]["self_s"] == pytest.approx(0.3)
    text = render([
        span("serve/dispatch", 1.0),
        {"type": "metric", "name": "x_total", "kind": "counter", "value": 1},
    ])
    assert "== serve spans ==" in text and "x_total" in text


# ----------------------------------------------------------------------
# profiler window


def test_parse_steps():
    assert parse_steps("3:8") == (3, 8)
    assert parse_steps("0:1") == (0, 1)
    for bad in ("8:3", "3:3", "-1:2", "3", "a:b", ""):
        with pytest.raises(ValueError):
            parse_steps(bad)


def test_profile_window_opens_and_closes_once(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    synced = []
    w = ProfileWindow(str(tmp_path), steps=(2, 4))
    for step in range(6):
        w.on_step(step, sync=lambda: synced.append(step))
    w.close()  # idempotent after the window already closed
    assert calls == [("start", str(tmp_path)), ("stop",)]
    assert synced == [4]  # one D2H sync right before the trace closes

    # disabled window (no dir): exact no-op
    calls.clear()
    w2 = ProfileWindow(None)
    for step in range(6):
        w2.on_step(step)
    w2.close()
    assert calls == []


# ----------------------------------------------------------------------
# serve-engine stats parity: report() is a registry view


def test_engine_report_is_registry_view():
    from ncnet_tpu.serve import ServeEngine, payload_spec

    reg = MetricsRegistry()
    params = {"w": jnp.asarray(3.0, jnp.float32)}

    def apply(p, batch):
        return {"y": batch["x"] * p["w"]}

    with ServeEngine(
        apply, params, max_batch=2, max_wait=0.01, registry=reg
    ) as eng:
        eng.warmup(
            [("A", payload_spec({"x": np.zeros((4,), np.float32)}))]
        )
        futs = [
            eng.submit(key="A", payload={"x": np.full((4,), float(i),
                                                      np.float32)})
            for i in range(3)
        ]
        for f in futs:
            f.result(timeout=30)
        stats = eng.report()

    assert eng.metrics is reg  # the injected registry IS the stats store
    assert stats["submitted"] == reg.get("serve_requests_submitted_total").value == 3
    assert stats["completed"] == reg.get("serve_requests_completed_total").value == 3
    assert stats["failed"] == reg.get("serve_requests_failed_total").value == 0
    assert stats["batches"] == reg.get("serve_batches_total").value
    assert stats["real_samples"] == reg.get("serve_samples_real_total").value
    hist = reg.get("serve_request_latency_seconds")
    assert hist.count == 3
    assert stats["latencies_s"] == hist.samples
    assert stats["latency_p50_ms"] == pytest.approx(
        percentiles(hist.samples)["p50"] * 1e3
    )
    # a second engine without an injected registry gets a PRIVATE one
    with ServeEngine(apply, params, max_batch=2, max_wait=0.01) as eng2:
        assert eng2.metrics is not reg
        assert eng2.metrics.get("serve_requests_submitted_total").value == 0


# ----------------------------------------------------------------------
# end to end: a tiny in-process training run under a telemetry session


def test_train_loop_telemetry_end_to_end(tmp_path):
    """The acceptance shape for scripts/train.py --telemetry, run
    in-process (the CLI wires exactly this pair): a session around a
    tiny train() produces events.jsonl with the per-step spans and the
    train metrics, plus a renderable .prom snapshot."""
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.telemetry.registry import default_registry
    from ncnet_tpu.train.loop import train as train_loop

    cfg = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batches = [
        {"source_image": rng.randn(2, 48, 48, 3).astype(np.float32),
         "target_image": rng.randn(2, 48, 48, 3).astype(np.float32)}
        for _ in range(2)
    ]
    steps_before = default_registry().counter("train_steps_total").value

    telem = tmp_path / "telem"
    telemetry_session.start(str(telem), label="train-test")
    try:
        train_loop(
            cfg, params, batches, val_loader=None, num_epochs=1,
            checkpoint_dir=str(tmp_path), data_parallel=False, log_every=1,
        )
    finally:
        telemetry_session.stop()

    events = read_events(str(telem / events_name(0)))
    span_paths = {e["path"] for e in events if e["type"] == "span"}
    # the step splits + the durable checkpoint span all recorded; since
    # PR 19 every save runs on the ackpt writer thread (even in sync
    # mode), so the durable span nests under ckpt/write_async and the
    # step thread records only the handoff
    assert "step/data_wait" in span_paths
    assert "step/device_compute" in span_paths
    assert "step/loss_sync" in span_paths
    assert "ckpt/handoff" in span_paths
    assert "ckpt/write_async>checkpoint/save" in span_paths

    metrics = {e["name"]: e for e in events if e["type"] == "metric"}
    assert metrics["train_steps_total"]["value"] == steps_before + 2
    assert metrics["train_step_seconds"]["count"] >= 2
    assert metrics["train_mfu"]["value"] > 0  # analytic MFU gauge was set
    assert metrics["checkpoint_bytes_written_total"]["value"] > 0

    prom = (telem / prom_name(0)).read_text()
    assert "# TYPE train_steps_total counter" in prom
    assert "# TYPE train_step_seconds histogram" in prom
    text = render(events)
    assert "== step spans ==" in text and "== ckpt spans ==" in text
