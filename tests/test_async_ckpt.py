"""Async checkpointing (resilience.async_ckpt): policy, barriers, drills.

Four layers, cheapest first:

  * policy units — deterministic Event-gated fake writers pin the
    coalesce / backpressure / failure-surfacing semantics with zero
    timing dependence;
  * concurrency audit — the fuzzed handoff run under the lock audit
    (analysis.concurrency) asserts the writer introduces no lock-order
    cycles and no straggler thread;
  * loop integration — async-written checkpoints are BYTE-identical to
    sync-written ones in both layouts (same writer code, different
    thread — the whole point), and the PreemptionGuard flush hook is
    registered/removed around training;
  * subprocess drills — `os._exit` kills at every ``ackpt.*`` fault
    point (plus `checkpoint.write` mid-async-write), proving the
    walk-back contract and bitwise resumed == uninterrupted recovery;
    and the satellite-6 double-SIGTERM drill: the second signal must not
    orphan the in-flight final cursor save.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.analysis import concurrency
from ncnet_tpu.data.loader import DataLoader
from ncnet_tpu.data.pairs import SyntheticPairDataset
from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.resilience.async_ckpt import AsyncCheckpointer, device_snapshot
from ncnet_tpu.telemetry.registry import MetricsRegistry
from ncnet_tpu.train.checkpoint import (
    load_checkpoint,
    load_latest_valid,
    sharded_dir_for,
)
from ncnet_tpu.train.loop import train

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WAIT = 30.0  # generous Event timeout: a hang fails the assert, not CI


@pytest.fixture(autouse=True)
def _no_leaked_state():
    yield
    faultinject.clear()
    concurrency.clear()


def _ackpt(**kw):
    kw.setdefault("registry", MetricsRegistry())
    return AsyncCheckpointer(**kw)


class _GatedWriter:
    """Deterministic writer stand-in: each write records its payload and
    thread, then blocks until `release()` — the test controls exactly
    when the in-flight slot frees up."""

    def __init__(self, gated=True):
        self.gated = gated
        self.gate = threading.Event()
        self.entered = threading.Event()
        self.written = []
        self.threads = []

    def __call__(self, data):
        self.threads.append(threading.current_thread().name)
        self.entered.set()
        if self.gated and not self.gate.wait(WAIT):
            raise RuntimeError("writer gate never released")
        self.written.append(data)

    def release(self):
        self.gate.set()


# --- policy units -----------------------------------------------------------


def test_sync_mode_blocks_but_writes_on_writer_thread():
    """satellite 1: sync SEMANTICS, but the D2H/serialize/fsync work runs
    on the dedicated writer thread — never the step thread."""
    w = _GatedWriter(gated=False)
    ack = _ackpt(async_mode=False)
    t = ack.submit(1, w, step=1)
    assert t.done.is_set() and w.written == [1]
    assert w.threads == ["ackpt-writer"]
    ack.submit(2, w, step=2)
    assert w.written == [1, 2]
    ack.close()
    assert ack.report()["written_total"] == 2
    assert ack.report()["straggler_threads"] == []


def test_overlapped_submits_coalesce_to_newest():
    w = _GatedWriter()
    ack = _ackpt(async_mode=True)
    t1 = ack.submit(1, w, step=1)
    assert w.entered.wait(WAIT)  # writer busy on save 1
    t2 = ack.submit(2, w, step=2)  # queued
    t3 = ack.submit(3, w, step=3)  # supersedes 2
    t4 = ack.submit(4, w, step=4)  # supersedes 3
    assert t2.done.is_set() and t2.superseded
    assert t3.done.is_set() and t3.superseded
    assert not t4.done.is_set() and not t1.superseded
    w.release()
    assert ack.flush(timeout=WAIT)
    assert w.written == [1, 4], "newest queued snapshot must win"
    rep = ack.report()
    assert rep["coalesced_total"] == 2 and rep["written_total"] == 2
    assert rep["submitted_total"] == 4
    ack.close()


def test_backpressure_mode_drops_nothing():
    """coalesce=False (multi-process sharded runs): an overlapped submit
    waits for the queued slot — every save executes, in order."""
    w = _GatedWriter()
    ack = _ackpt(async_mode=True, coalesce=False)
    ack.submit(1, w, step=1)
    assert w.entered.wait(WAIT)
    ack.submit(2, w, step=2)  # queued slot free: returns immediately
    returned = threading.Event()

    def third():
        ack.submit(3, w, step=3)
        returned.set()

    helper = threading.Thread(target=third)
    helper.start()
    assert not returned.wait(0.15), "submit must backpressure, not coalesce"
    w.release()
    assert returned.wait(WAIT)
    helper.join(WAIT)
    assert ack.flush(timeout=WAIT)
    assert w.written == [1, 2, 3]
    assert ack.report()["coalesced_total"] == 0
    ack.close()


def test_writer_failure_surfaces_on_next_submit():
    def bad_write(data):
        raise ValueError("disk on fire")

    ack = _ackpt(async_mode=True)
    ack.submit(1, bad_write, step=1)
    assert ack.flush(timeout=WAIT, reraise=False)
    with pytest.raises(ValueError, match="disk on fire"):
        ack.submit(2, bad_write, step=2)
    ack.close()  # failure already surfaced; close must not re-raise


def test_writer_failure_surfaces_on_flush_and_close():
    def bad_write(data):
        raise ValueError("disk on fire")

    ack = _ackpt(async_mode=True)
    ack.submit(1, bad_write, step=1)
    with pytest.raises(ValueError, match="disk on fire"):
        ack.flush(timeout=WAIT)  # drains, then surfaces the failure
    ack.close()

    ack2 = _ackpt(async_mode=True)
    ack2.submit(1, bad_write, step=1)
    ack2.flush(timeout=WAIT, reraise=False)
    with pytest.raises(ValueError, match="disk on fire"):
        ack2.close(reraise=True)


def test_sync_submit_raises_directly_and_recovers():
    calls = []

    def flaky(data):
        calls.append(data)
        if len(calls) == 1:
            raise ValueError("transient")

    ack = _ackpt(async_mode=False)
    with pytest.raises(ValueError, match="transient"):
        ack.submit(1, flaky, step=1)
    ack.submit(2, flaky, step=2)  # the failure was consumed by the raise
    assert calls == [1, 2]
    ack.close(reraise=True)


def test_flush_timeout_and_close_idempotence():
    w = _GatedWriter()
    ack = _ackpt(async_mode=True)
    ack.submit(1, w, step=1)
    assert w.entered.wait(WAIT)
    assert ack.flush(timeout=0.05) is False
    w.release()
    assert ack.flush(timeout=WAIT) is True
    ack.close()
    ack.close()  # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        ack.submit(2, w, step=2)
    assert ack.report()["straggler_threads"] == []


def test_metrics_inflight_gauge_and_coalesced_counter():
    reg = MetricsRegistry()
    w = _GatedWriter()
    ack = _ackpt(async_mode=True, registry=reg)
    ack.submit(1, w, step=1)
    assert w.entered.wait(WAIT)
    assert reg.gauge("ckpt_inflight").value == 1
    ack.submit(2, w, step=2)
    ack.submit(3, w, step=3)
    w.release()
    assert ack.flush(timeout=WAIT)
    assert reg.gauge("ckpt_inflight").value == 0
    assert reg.counter("ckpt_coalesced_total").value == 1
    ack.close()


def test_device_snapshot_survives_donation():
    """The hazard device_snapshot exists for: a donating jitted update
    invalidates the handed-off refs; the snapshot copies must not care.
    Non-array leaves pass through by identity (byte-identity contract)."""
    update = jax.jit(
        lambda t: jax.tree.map(lambda x: x + 1.0, t), donate_argnums=(0,)
    )
    host_leaf = np.arange(3, dtype=np.float32)
    state = {"w": jnp.arange(8, dtype=jnp.float32), "meta": host_leaf}
    snap = device_snapshot(state)
    assert snap["meta"] is host_leaf, "non-array leaves pass by identity"
    state = update({"w": state["w"], "meta": jnp.zeros(())})  # donates w
    np.testing.assert_array_equal(
        np.asarray(snap["w"]), np.arange(8, dtype=np.float32)
    )


# --- concurrency audit (satellite 4) ----------------------------------------


def test_fuzzed_handoffs_acyclic_under_lock_audit(monkeypatch):
    """NCNET_LOCK_AUDIT=1 posture: the writer's named lock joins the
    acquisition graph; a fuzzed mixed submit/flush workload must leave
    the graph acyclic and the ledger straggler-free."""
    monkeypatch.setenv(concurrency.ENV_VAR, "1")
    concurrency.clear()
    concurrency.enable()  # env was loaded pre-test; enable() is the reload
    written = []
    with concurrency.ScheduleFuzzer(seed=7, p=0.5):
        ack = _ackpt(async_mode=True)
        for i in range(40):
            ack.submit(i, written.append, step=i, wait=(i % 5 == 0))
            if i % 7 == 0:
                assert ack.flush(timeout=WAIT)
        assert ack.flush(timeout=WAIT)
        ack.close()
    assert concurrency.find_cycles() == []
    assert ack.report()["straggler_threads"] == []
    assert len(written) >= 9, "every wait=True submit must have executed"
    # the audited name was actually exercised, from both sides
    stats = concurrency.held_stats()
    assert stats.get("resilience.ackpt", {}).get("acquires", 0) > 0


# --- loop integration -------------------------------------------------------

# the pinned kill-drill schedule (tests/conftest.py session fixtures)
N_PAIRS, BATCH, EPOCHS, SIZE = 8, 2, 2, 32
STEPS_PER_EPOCH = N_PAIRS // BATCH
CFG = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))


def _loader():
    ds = SyntheticPairDataset(n=N_PAIRS, output_size=(SIZE, SIZE), seed=11)
    return DataLoader(ds, BATCH, shuffle=True, seed=5, drop_last=True,
                      num_workers=1, prefetch=0)


def _run(ckdir, **train_kw):
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    kw = dict(
        num_epochs=EPOCHS, checkpoint_dir=str(ckdir), data_parallel=False,
        log_every=100, save_every_steps=2, keep_checkpoints=4,
        async_checkpoints=True,
    )
    kw.update(train_kw)
    return train(CFG, kw.pop("params", params), _loader(), None, **kw)


def _resume(ckdir, **train_kw):
    ck, _ = load_latest_valid(os.path.join(str(ckdir), "ncnet_tpu.msgpack"))
    kw = dict(
        params=ck.params,
        opt_state=ck.opt_state,
        start_epoch=ck.epoch,
        start_step=ck.step,
        initial_best_val=ck.best_val_loss,
        initial_train_hist=ck.train_loss,
        initial_val_hist=ck.val_loss,
    )
    if ck.cursor:
        kw["start_epoch"] = ck.cursor["epoch"]
        kw["start_batch"] = ck.cursor["batch_index"]
        kw["start_epoch_losses"] = ck.cursor["epoch_losses"]
    kw.update(train_kw)
    return _run(ckdir, **kw), ck


def _assert_bitwise_equal(ck_a, ck_b):
    flat_a, _ = jax.tree_util.tree_flatten_with_path(ck_a.params)
    flat_b, _ = jax.tree_util.tree_flatten_with_path(ck_b.params)
    assert len(flat_a) == len(flat_b)
    for (path_a, leaf_a), (_, leaf_b) in zip(flat_a, flat_b):
        np.testing.assert_array_equal(
            np.asarray(leaf_a), np.asarray(leaf_b),
            err_msg=f"params differ at {jax.tree_util.keystr(path_a)}",
        )
    for a, b in zip(
        jax.tree.leaves(ck_a.opt_state), jax.tree.leaves(ck_b.opt_state)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(ck_a.step) == int(ck_b.step)
    np.testing.assert_array_equal(
        np.asarray(ck_a.train_loss), np.asarray(ck_b.train_loss)
    )


def _metrics_lines(ckdir):
    with open(os.path.join(str(ckdir), "metrics.jsonl")) as f:
        return [json.loads(line) for line in f]


def _assert_metrics_tails_match(lines_ref, lines_run):
    """The uninterrupted run's metrics must be the SUFFIX of the run's
    (modulo wall-clock). A kill landing between the epoch metrics append
    and the epoch-end checkpoint commit resumes from the last mid-epoch
    cursor, so the epoch line is legitimately re-appended — the resumed
    line must still match the uninterrupted one exactly."""
    strip = lambda l: {k: v for k, v in l.items() if k != "epoch_seconds"}
    ref, run = [strip(l) for l in lines_ref], [strip(l) for l in lines_run]
    assert len(run) >= len(ref)
    assert run[-len(ref):] == ref


def test_async_training_byte_identical_legacy(tmp_path, legacy_format_run):
    """Async vs sync legacy layout: the FINAL checkpoint file must be
    byte-for-byte identical (same serialization, same durable writer —
    only the thread changed); the writer thread must be gone at return
    (loop-exit close barrier / thread ledger)."""
    _run(tmp_path)  # async arm of the A/B; fixture ran the sync arm
    assert not [
        t for t in threading.enumerate() if t.name == "ackpt-writer"
    ], "loop exit must join the checkpoint writer"
    ck_sync, lines_sync, sync_dir = legacy_format_run
    a = open(os.path.join(str(tmp_path), "ncnet_tpu.msgpack"), "rb").read()
    b = open(os.path.join(str(sync_dir), "ncnet_tpu.msgpack"), "rb").read()
    assert a == b, "async-written checkpoint differs from sync bytes"
    ck_async = load_checkpoint(os.path.join(str(tmp_path), "ncnet_tpu.msgpack"))
    _assert_bitwise_equal(ck_async, ck_sync)
    _assert_metrics_tails_match(_metrics_lines(tmp_path), lines_sync)


def test_async_training_byte_identical_sharded(tmp_path, uninterrupted_run):
    """Async vs sync sharded layout: the final committed step directory
    must match file-by-file (chunks, manifests, MANIFEST.json)."""
    _run(tmp_path, distributed_checkpoints=True)
    _, lines_sync, sync_dir = uninterrupted_run

    def final_step_dir(ckdir):
        sdir = sharded_dir_for(os.path.join(str(ckdir), "ncnet_tpu.msgpack"))
        steps = [
            d for d in os.listdir(sdir)
            if d.startswith("step_")
            and os.path.exists(os.path.join(sdir, d, "MANIFEST.json"))
        ]
        return os.path.join(sdir, max(steps))

    da, db = final_step_dir(tmp_path), final_step_dir(sync_dir)
    assert os.path.basename(da) == os.path.basename(db)

    def tree_files(root):
        out = {}
        for dirpath, _, names in os.walk(root):
            for n in names:
                p = os.path.join(dirpath, n)
                out[os.path.relpath(p, root)] = open(p, "rb").read()
        return out

    fa, fb = tree_files(da), tree_files(db)
    assert sorted(fa) == sorted(fb)
    for rel in fa:
        assert fa[rel] == fb[rel], f"sharded file differs async vs sync: {rel}"
    _assert_metrics_tails_match(_metrics_lines(tmp_path), lines_sync)


def test_preemption_registers_flush_hook_and_commits_cursor(tmp_path):
    """The loop wires its flush barrier into the guard's second-signal
    path for the life of training (and unwires it after); the preemption
    final save is committed by the time train() returns."""

    class _HookGuard:
        def __init__(self, after_steps):
            self.after = after_steps
            self.seen = 0
            self.added = []
            self.removed = []

        @property
        def requested(self):
            return self.seen >= self.after

        def add_flush_hook(self, hook):
            self.added.append(hook)

        def remove_flush_hook(self, hook):
            self.removed.append(hook)

    guard = _HookGuard(after_steps=STEPS_PER_EPOCH + 1)
    real_fire = faultinject.fire

    def counting_fire(point, data=None):
        if point == "step.boundary":
            guard.seen += 1
        return real_fire(point, data)

    patch = pytest.MonkeyPatch()
    patch.setattr("ncnet_tpu.train.loop.faultinject.fire", counting_fire)
    try:
        _, history = _run(tmp_path, preemption=guard)
    finally:
        patch.undo()
    assert history["preempted"]
    assert len(guard.added) == 1 and guard.removed == guard.added
    ck = load_checkpoint(os.path.join(str(tmp_path), "ncnet_tpu.msgpack"))
    assert ck.cursor is not None and ck.cursor["batch_index"] == 1


# --- subprocess kill drills -------------------------------------------------


def _train_script(ckdir, epochs=EPOCHS, save_every=2, preempt=False):
    guard_import = (
        "from ncnet_tpu.resilience.signals import PreemptionGuard\n"
        if preempt else ""
    )
    enter = "with PreemptionGuard() as guard:\n    " if preempt else ""
    kw = ", preemption=guard" if preempt else ""
    return f"""
import sys
sys.path.insert(0, {REPO!r})
import jax
from ncnet_tpu.data.loader import DataLoader
from ncnet_tpu.data.pairs import SyntheticPairDataset
from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
from ncnet_tpu.train.loop import train
{guard_import}
cfg = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
ds = SyntheticPairDataset(n={N_PAIRS}, output_size=({SIZE}, {SIZE}), seed=11)
loader = DataLoader(ds, {BATCH}, shuffle=True, seed=5, drop_last=True,
                    num_workers=1, prefetch=0)
params = init_immatchnet(jax.random.PRNGKey(0), cfg)
{enter}train(cfg, params, loader, None, num_epochs={epochs},
      checkpoint_dir={str(ckdir)!r}, data_parallel=False, log_every=100,
      save_every_steps={save_every}, keep_checkpoints=4,
      async_checkpoints=True{kw})
raise SystemExit("unreachable: the injected fault did not fire")
"""


# hit indices chosen so a COMMITTED save provably precedes the kill:
# ackpt.handoff fires per submit on the step thread — hit 4 is the first
# epoch-2 submit, after the epoch-1-end save (wait=True) committed; the
# writer-side points fire per executed save on the single writer thread,
# so at hit 2 execution 1 has already committed. checkpoint.write=kill
# is the mid-async-write drill: the kill lands inside the durable temp
# write ON THE WRITER THREAD, leaving a torn temp file behind.
@pytest.mark.parametrize("fault", [
    "ackpt.handoff=kill@4",
    "ackpt.d2h=kill@2",
    "ackpt.write=kill@2",
    "ackpt.commit=kill@2",
    "checkpoint.write=kill@2",
])
def test_kill_drill_walks_back_and_resumes_bitwise(
    tmp_path, fault, legacy_format_run
):
    proc = subprocess.run(
        [sys.executable, "-c", _train_script(tmp_path)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "NCNET_FAULTS": fault},
    )
    assert proc.returncode == 137, (fault, proc.stderr[-2000:])
    if fault.startswith("checkpoint.write"):
        tmps = [n for n in os.listdir(tmp_path) if ".tmp." in n]
        assert tmps, "mid-async-write kill should leave a torn temp file"
    if fault.startswith("ackpt.commit"):
        # the kill landed AFTER the durable write returned: that save is
        # committed and recovery must land on it, not walk past it
        ck, _ = load_latest_valid(
            os.path.join(str(tmp_path), "ncnet_tpu.msgpack")
        )
        assert int(ck.step) >= 2

    (_, history), _ = _resume(tmp_path)
    assert not history["preempted"]
    ck_sync, lines_sync, _ = legacy_format_run
    ck_b = load_checkpoint(os.path.join(str(tmp_path), "ncnet_tpu.msgpack"))
    _assert_bitwise_equal(ck_sync, ck_b)
    _assert_metrics_tails_match(lines_sync, _metrics_lines(tmp_path))


def test_double_sigterm_does_not_orphan_inflight_save(tmp_path):
    """satellite 6: second SIGTERM mid-async-final-save — the guard's
    flush hook gives the in-flight cursor save its bounded grace, so the
    process dies BY SIGTERM but latest_valid() still lands on the
    committed final cursor save."""
    ckpath = os.path.join(str(tmp_path), "ncnet_tpu.msgpack")
    body = _train_script(tmp_path, epochs=3, save_every=0, preempt=True)
    script = f"""
import os, signal, threading, time
import sys
sys.path.insert(0, {REPO!r})
from ncnet_tpu.resilience import faultinject

# every durable save takes >= 1.5s on the writer: the second SIGTERM
# below provably lands while the final cursor save is still in flight
faultinject.configure("ackpt.write=delay:1.5")

def killer():
    while not os.path.exists({ckpath!r}):
        time.sleep(0.02)
    os.kill(os.getpid(), signal.SIGTERM)   # request preemption
    time.sleep(1.0)
    os.kill(os.getpid(), signal.SIGTERM)   # impatient operator, mid-save
threading.Thread(target=killer, daemon=True).start()
{body}
"""
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == -signal.SIGTERM, (
        proc.returncode, proc.stderr[-2000:]
    )
    out = proc.stdout + proc.stderr
    assert "will checkpoint at the next step boundary" in out
    ck, _ = load_latest_valid(ckpath)
    assert ck.cursor is not None, (
        "double SIGTERM orphaned the in-flight final cursor save"
    )
