"""Whole-system synthetic InLoc proof (VERDICT r3 #2): the REAL chain —
weak-loss training -> model forward at the InLoc config -> `.mat` dump ->
PnP LO-RANSAC -> densePV re-rank -> rate curve — on a generated scene
with known geometry and a planted query pose.

Slow-gated. The chain runs as a SUBPROCESS of
``scripts/synthetic_inloc_e2e.py`` (not in-process): the test session
pins jax to the 8-virtual-CPU mesh at import (conftest), where the
256px training + two 512px dumps take over an hour — the fresh process
uses the real chip when one is attached (~15 min) and is exactly the
driver-runnable form. Measured on a v5e: PCK 0.98 after training (vs
0.25 degenerate baseline), 106 dump scores above the reference's hard
0.75 threshold, pose error ~0.12 m / ~1.2 deg, rate@1m = 100%, densePV
ranks the true pano above the decoy, and the bf16 chain's pose agrees
with fp32's to within the chain's own precision (~0.12 m: the slightly
different match sets resample RANSAC, so the legs disagree by about the
method's intrinsic error, not a bf16 bias).
"""

import json
import os
import subprocess
import sys

import pytest

pytest.importorskip("scipy")
pytest.importorskip("PIL")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_synthetic_inloc_end_to_end(tmp_path):
    if not os.environ.get("NCNET_RUN_SLOW"):
        pytest.skip(
            "slow whole-chain test (~15 min on a TPU chip; >1 h CPU-only); "
            "set NCNET_RUN_SLOW=1 (driver-runnable form: "
            "scripts/synthetic_inloc_e2e.py --bf16_check)"
        )
    # strip conftest's 8-virtual-device flag so the child sees the real
    # driver environment (on a CPU-only host the split would leave the
    # single-device chain a fraction of the cores)
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "synthetic_inloc_e2e.py"),
            "--out_dir", str(tmp_path),
            "--steps", "300",
            "--train_size", "256",
            "--seed", "0",
            "--bf16_check",
        ],
        capture_output=True,
        text=True,
        timeout=3600 * 3,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    # the script prints the JSON summary as its last stdout line
    summary_line = next(
        line for line in reversed(proc.stdout.splitlines())
        if line.startswith("{")
    )
    s = json.loads(summary_line)

    # the trained model genuinely matches (not the degenerate diagonal)
    assert s["pck_after_training"] > 0.8, s
    # score calibration reaches the reference's hard threshold
    assert s["n_above_reference_thr_0.75"] >= 12, s
    # localization at loose thresholds, reference curve semantics
    assert s["pos_err_m"] < 0.5, s
    assert s["ori_err_deg"] < 5.0, s
    assert s["rate_at_1m_10deg_pct"] == 100.0, s
    # dense pose verification must rank the true pano above the decoy
    assert s["densePV_top1_is_true_pano"], s
    # bf16 (production eval numerics) agrees with fp32 downstream to
    # within the chain's own precision (see module docstring)
    assert s["bf16_vs_fp32_pose_pos_m"] < 0.3, s
    assert s["bf16_vs_fp32_pose_ori_deg"] < 3.0, s
    # persisted artifacts exist (error file written by the CLI)
    assert os.path.exists(s["error_file"])
