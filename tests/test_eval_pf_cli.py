"""End-to-end test of scripts/eval_pf_pascal.py on a synthetic PF-Pascal
fixture: checkpoint load, the `--conv4d_impl` eval override (must replace
even a composite training mix), dataset/loader wiring, and the printed
PCK summary."""

import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

pytest.importorskip("PIL")

REPO = Path(__file__).resolve().parent.parent


def test_eval_pf_pascal_cli(tmp_path):
    from PIL import Image

    import jax

    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
    from ncnet_tpu.train.checkpoint import CheckpointData, save_checkpoint

    # a checkpoint carrying a composite training impl the CLI's default
    # 'tlc' override must replace for the forward-only eval
    cfg = ImMatchNetConfig(
        ncons_kernel_sizes=(3,), ncons_channels=(1,), conv4d_impl="tlc//btl"
    )
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    ckpt = tmp_path / "tiny.msgpack"
    save_checkpoint(
        str(ckpt),
        CheckpointData(config=cfg, params=params, opt_state=None, epoch=0),
    )

    ds = tmp_path / "pf"
    (ds / "image_pairs").mkdir(parents=True)
    (ds / "JPEGImages").mkdir()
    rng = np.random.RandomState(0)
    for i in range(2):
        Image.fromarray(
            rng.randint(0, 255, (64, 64, 3), np.uint8)
        ).save(ds / "JPEGImages" / f"im{i}.png")
    with open(ds / "image_pairs" / "test_pairs.csv", "w") as f:
        f.write("source_image,target_image,class,XA,YA,XB,YB\n")
        f.write(
            "JPEGImages/im0.png,JPEGImages/im1.png,1,"
            "10;20;30,5;15;25,12;22;32,6;16;26\n"
        )

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "eval_pf_pascal.py"),
            "--checkpoint", str(ckpt),
            "--eval_dataset_path", str(ds),
            "--image_size", "64",
            "--num_workers", "0",
        ],
        capture_output=True,
        text=True,
        env=env,
        cwd=str(REPO),
        timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Valid: 1" in r.stdout
    # one pair, 3 keypoints: PCK is k/3 for some k in 0..3
    line = [ln for ln in r.stdout.splitlines() if ln.startswith("PCK:")]
    assert line, r.stdout
    pck = float(line[0].split()[1].rstrip("%")) / 100.0
    assert any(np.isclose(pck, k / 3.0, atol=5e-3) for k in range(4)), pck
