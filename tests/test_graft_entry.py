"""The driver-facing multichip contract (``__graft_entry__``).

The dry run went red in rounds 1-2 because something in the parent process
touched the real TPU backend. These tests pin the green-by-construction
property: the parent does no jax work and launches the child with the CPU
platform forced, and (slow-gated) the end-to-end dry run passes.
"""

import os

import pytest

import __graft_entry__ as graft_entry


def test_dryrun_parent_spawns_cpu_child(monkeypatch):
    """The parent must hand ALL work to a child whose environment forces
    the CPU platform and N virtual devices — it must never query or
    initialize a jax backend itself."""
    calls = {}

    def fake_run(cmd, cwd=None, env=None, check=None):
        calls["cmd"] = cmd
        calls["env"] = env
        calls["check"] = check

    monkeypatch.setattr(graft_entry.subprocess, "run", fake_run)
    monkeypatch.delenv(graft_entry._CHILD_ENV_FLAG, raising=False)
    # A stale force-count flag must be replaced, not duplicated.
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=3 --other_flag"
    )

    graft_entry.dryrun_multichip(4)

    env = calls["env"]
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env[graft_entry._CHILD_ENV_FLAG] == "1"
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]
    assert "device_count=3" not in env["XLA_FLAGS"]
    assert "--other_flag" in env["XLA_FLAGS"]
    assert calls["check"] is True
    assert "dryrun_multichip(4)" in calls["cmd"][-1]


def test_dryrun_multichip_end_to_end():
    """Full dry run (train step + sharded-eval equality) on 2 virtual CPU
    devices, exactly as the driver invokes it."""
    if not os.environ.get("NCNET_RUN_SLOW"):
        pytest.skip("slow test (CPU compile ~minutes); set NCNET_RUN_SLOW=1")
    graft_entry.dryrun_multichip(2)
