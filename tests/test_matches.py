import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.ops.coords import (
    normalize_axis,
    points_to_pixel_coords,
    points_to_unit_coords,
    unnormalize_axis,
)
from ncnet_tpu.ops.matches import (
    bilinear_point_transfer,
    corr_to_matches,
    nearest_point_transfer,
)
from ncnet_tpu.ops.metrics import pck


def planted_corr(b, fs, links):
    """corr with a strong peak corr[iA,jA,iB,jB] for each planted link."""
    corr = np.zeros((b, fs, fs, fs, fs), np.float32)
    for bi, ia, ja, ib, jb in links:
        corr[bi, ia, ja, ib, jb] = 10.0
    return corr


def test_corr_to_matches_default_direction_planted():
    fs = 4
    corr = planted_corr(1, fs, [(0, 1, 2, 3, 0)])
    xa, ya, xb, yb, score = corr_to_matches(jnp.asarray(corr), do_softmax=True)
    lin = np.linspace(-1, 1, fs)
    # B cell (3, 0) must match A cell (1, 2)
    n = 3 * fs + 0
    assert np.isclose(xa[0, n], lin[2])
    assert np.isclose(ya[0, n], lin[1])
    assert np.isclose(xb[0, n], lin[0])
    assert np.isclose(yb[0, n], lin[3])
    # softmax over 16 A-cells with one logit at 10
    want = np.exp(10.0) / (np.exp(10.0) + fs * fs - 1)
    assert np.isclose(score[0, n], want, rtol=1e-5)
    # B grid coords enumerate the meshgrid row-major
    np.testing.assert_allclose(np.asarray(xb[0]), np.tile(lin, fs), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(yb[0]), np.repeat(lin, fs), rtol=1e-6)


def test_corr_to_matches_inverted_direction():
    fs = 4
    corr = planted_corr(1, fs, [(0, 2, 1, 0, 3)])
    xa, ya, xb, yb, score = corr_to_matches(
        jnp.asarray(corr), invert_matching_direction=True
    )
    lin = np.linspace(-1, 1, fs)
    n = 2 * fs + 1  # A cell (2, 1)
    assert np.isclose(xb[0, n], lin[3])
    assert np.isclose(yb[0, n], lin[0])
    assert np.isclose(xa[0, n], lin[1])
    assert np.isclose(ya[0, n], lin[2])


def test_corr_to_matches_positive_scale_and_batch():
    fs = 3
    corr = planted_corr(2, fs, [(0, 0, 0, 0, 0), (1, 2, 2, 1, 1)])
    xa, ya, xb, yb, score = corr_to_matches(jnp.asarray(corr), scale="positive")
    lin = np.linspace(0, 1, fs)
    assert np.isclose(xa[0, 0], lin[0]) and np.isclose(ya[0, 0], lin[0])
    n = 1 * fs + 1
    assert np.isclose(xa[1, n], lin[2]) and np.isclose(ya[1, n], lin[2])


def test_corr_to_matches_relocalization_deltas():
    fs, k = 3, 2
    corr = planted_corr(1, fs, [(0, 1, 1, 2, 2)])
    deltas = tuple(
        jnp.asarray(np.full((1, fs, fs, fs, fs), v, np.int32)) for v in (1, 0, 1, 1)
    )
    xa, ya, xb, yb, score = corr_to_matches(
        jnp.asarray(corr), delta4d=deltas, k_size=k
    )
    lin = np.linspace(-1, 1, fs * k)
    n = 2 * fs + 2
    # fine indices: iA=1*2+1=3, jA=1*2+0=2, iB=2*2+1=5, jB=2*2+1=5
    assert np.isclose(ya[0, n], lin[3])
    assert np.isclose(xa[0, n], lin[2])
    assert np.isclose(yb[0, n], lin[5])
    assert np.isclose(xb[0, n], lin[5])


def identity_matches(fs, b=1):
    lin = np.linspace(-1, 1, fs).astype(np.float32)
    xb = np.tile(lin, fs)[None].repeat(b, 0)
    yb = np.repeat(lin, fs)[None].repeat(b, 0)
    return xb.copy(), yb.copy(), xb, yb


def test_bilinear_point_transfer_identity():
    fs = 5
    xa, ya, xb, yb = identity_matches(fs)
    pts = np.array([[[-0.3, 0.55, 0.0], [0.2, -0.8, 0.0]]], np.float32)
    warped = bilinear_point_transfer(
        tuple(map(jnp.asarray, (xa, ya, xb, yb))), jnp.asarray(pts)
    )
    np.testing.assert_allclose(np.asarray(warped), pts, rtol=1e-5, atol=1e-6)


def test_bilinear_point_transfer_affine():
    fs = 5
    xb, yb, _, _ = identity_matches(fs)
    xa = 0.5 * xb + 0.1
    ya = -0.25 * yb
    pts = np.array([[[-0.4, 0.3], [0.6, -0.2]]], np.float32)
    warped = np.asarray(
        bilinear_point_transfer(
            tuple(map(jnp.asarray, (xa, ya, xb, yb))), jnp.asarray(pts)
        )
    )
    np.testing.assert_allclose(warped[0, 0], 0.5 * pts[0, 0] + 0.1, rtol=1e-5)
    np.testing.assert_allclose(warped[0, 1], -0.25 * pts[0, 1], rtol=1e-5, atol=1e-6)


def rect_identity_matches(h, w, b=1):
    lx = np.linspace(-1, 1, w).astype(np.float32)
    ly = np.linspace(-1, 1, h).astype(np.float32)
    xb = np.tile(lx, h)[None].repeat(b, 0)
    yb = np.repeat(ly, w)[None].repeat(b, 0)
    return xb.copy(), yb.copy(), xb, yb


def test_bilinear_point_transfer_rectangular_grid():
    # non-square match grid (h != w): requires an explicit grid_shape,
    # then behaves exactly like the square path
    h, w = 4, 7
    xb, yb, _, _ = rect_identity_matches(h, w)
    xa = 0.5 * xb + 0.1
    ya = -0.25 * yb
    pts = np.array([[[-0.4, 0.3, 0.8], [0.6, -0.2, -0.7]]], np.float32)
    args = tuple(map(jnp.asarray, (xa, ya, xb, yb)))
    with pytest.raises(ValueError, match="grid_shape"):
        bilinear_point_transfer(args, jnp.asarray(pts))
    with pytest.raises(ValueError, match="does not factor"):
        bilinear_point_transfer(args, jnp.asarray(pts), grid_shape=(5, 5))
    warped = np.asarray(
        bilinear_point_transfer(args, jnp.asarray(pts), grid_shape=(h, w))
    )
    np.testing.assert_allclose(warped[0, 0], 0.5 * pts[0, 0] + 0.1, rtol=1e-5)
    np.testing.assert_allclose(
        warped[0, 1], -0.25 * pts[0, 1], rtol=1e-5, atol=1e-6
    )


def test_bilinear_point_transfer_square_explicit_shape_matches_default():
    fs = 5
    xa, ya, xb, yb = identity_matches(fs)
    pts = np.array([[[-0.3, 0.55], [0.2, -0.8]]], np.float32)
    args = tuple(map(jnp.asarray, (xa, ya, xb, yb)))
    default = bilinear_point_transfer(args, jnp.asarray(pts))
    explicit = bilinear_point_transfer(
        args, jnp.asarray(pts), grid_shape=(fs, fs)
    )
    np.testing.assert_array_equal(np.asarray(default), np.asarray(explicit))


def test_nearest_point_transfer():
    fs = 4
    xa, ya, xb, yb = identity_matches(fs)
    xa = xa + 0.05
    pts = np.array([[[-1.0, 0.9], [-1.0, 0.9]]], np.float32)
    warped = np.asarray(
        nearest_point_transfer(
            tuple(map(jnp.asarray, (xa, ya, xb, yb))), jnp.asarray(pts)
        )
    )
    lin = np.linspace(-1, 1, fs)
    np.testing.assert_allclose(warped[0, 0], [lin[0] + 0.05, lin[3] + 0.05], rtol=1e-5)


def test_coord_roundtrip_and_convention():
    # 1-indexed center convention: pixel (W+1)/2 -> 0
    assert np.isclose(float(normalize_axis(jnp.asarray(3.0), 5.0)), 0.0)
    assert np.isclose(float(unnormalize_axis(jnp.asarray(0.0), 5.0)), 3.0)
    pts = jnp.asarray(np.array([[[1.0, 5.0], [1.0, 3.0]]], np.float32))
    size = jnp.asarray(np.array([[3.0, 5.0]], np.float32))  # (h, w)
    unit = points_to_unit_coords(pts, size)
    back = points_to_pixel_coords(unit, size)
    np.testing.assert_allclose(np.asarray(back), np.asarray(pts), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(unit[0, 0]), [-1.0, 1.0], atol=1e-6)


def test_pck_counts_valid_only():
    src = np.full((1, 2, 5), -1, np.float32)
    src[:, :, :3] = [[10, 20, 30], [10, 20, 30]]
    warped = src.copy()
    warped[0, 0, 1] += 100.0  # one bad point
    got = np.asarray(
        pck(jnp.asarray(src), jnp.asarray(warped), jnp.asarray([100.0]))
    )
    np.testing.assert_allclose(got, [2.0 / 3.0], rtol=1e-6)
