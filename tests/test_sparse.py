"""Sparse-band neighbourhood consensus (ncnet_tpu.sparse).

The design contract under test: with ``K = hB*wB`` the band is complete
and the sparse path must reproduce the dense path — in EAGER mode
bitwise-tight (forward, losses, and the NC params updated by 3 training
steps) against the dense reference whose lowering is the arithmetic
mirror of the band GEMMs (``conv4d_impl='gemm4/gemm4'``,
``symmetric_batch=False``), and ULP-allclose under jit and against the
default 'xla' lowering. That equivalence is the harness every smaller K
rides on: partial-K semantics (off-band = exact zeros) are exercised by
the edge-gather, selection, and PCK-sweep tests.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import (
    ImMatchNetConfig,
    init_immatchnet,
    match_pipeline,
)
from ncnet_tpu.ops.band import (
    band_gather_neighbors,
    band_neighbor_pointers,
    band_to_dense,
    topk_band,
)
from ncnet_tpu.train.loss import weak_loss_core
from ncnet_tpu.train.step import check_sparse_config

BASE = dict(ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1))
#: dense reference whose conv lowering + bias placement mirror the band
#: GEMMs term-for-term (see ncnet_tpu/sparse/nc.py) — the bitwise anchor
DENSE_MIRROR = ImMatchNetConfig(
    conv4d_impl="gemm4/gemm4", symmetric_batch=False, **BASE
)


def _feats(rng, b, h, w, c=7):
    return (
        jnp.asarray(rng.randn(b, h, w, c).astype(np.float32)),
        jnp.asarray(rng.randn(b, h, w, c).astype(np.float32)),
    )


def _train3(cfg, params, fa, fb):
    nc = params["neigh_consensus"]
    opt = optax.adam(5e-4)
    st = opt.init(nc)
    losses = []
    for _ in range(3):
        loss, g = jax.value_and_grad(
            lambda p: weak_loss_core(p, cfg, fa, fb)
        )(nc)
        up, st = opt.update(g, st, nc)
        nc = optax.apply_updates(nc, up)
        losses.append(np.asarray(loss))
    return losses, nc


# --- full-K equivalence: the exactness contract ------------------------------


def test_full_k_forward_bitwise_eager():
    rng = np.random.RandomState(0)
    fa, fb = _feats(rng, 2, 5, 5)
    params = init_immatchnet(jax.random.PRNGKey(0), DENSE_MIRROR)
    nc = params["neigh_consensus"]
    sparse = DENSE_MIRROR.replace(nc_topk=25)
    out_d = np.asarray(match_pipeline(nc, DENSE_MIRROR, fa, fb))
    out_s = np.asarray(match_pipeline(nc, sparse, fa, fb))
    np.testing.assert_array_equal(out_d, out_s)


def test_full_k_forward_allclose_vs_default_impl():
    """The mirror impl is itself allclose to the default dense lowering,
    so full-K sparse == any dense lowering at float tolerance."""
    rng = np.random.RandomState(1)
    fa, fb = _feats(rng, 2, 5, 6)
    cfg_xla = ImMatchNetConfig(**BASE)
    params = init_immatchnet(jax.random.PRNGKey(1), cfg_xla)
    nc = params["neigh_consensus"]
    out_x = np.asarray(match_pipeline(nc, cfg_xla, fa, fb))
    out_s = np.asarray(
        match_pipeline(nc, cfg_xla.replace(nc_topk=30), fa, fb)
    )
    np.testing.assert_allclose(out_s, out_x, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("symmetric", [False, True])
def test_full_k_three_training_steps_bitwise_eager(symmetric):
    """Losses AND updated NC params bitwise over 3 eager Adam steps —
    gradients through band gather/GEMM, band MM, and band scores are the
    exact mirror of the dense backward."""
    rng = np.random.RandomState(2)
    fa, fb = _feats(rng, 3, 5, 5)
    cfg_d = DENSE_MIRROR.replace(symmetric_mode=symmetric)
    cfg_s = cfg_d.replace(nc_topk=25)
    params = init_immatchnet(jax.random.PRNGKey(2), cfg_d)
    losses_d, nc_d = _train3(cfg_d, params, fa, fb)
    losses_s, nc_s = _train3(cfg_s, params, fa, fb)
    for ld, ls in zip(losses_d, losses_s):
        assert ld.tobytes() == ls.tobytes()
    for leaf_d, leaf_s in zip(jax.tree.leaves(nc_d), jax.tree.leaves(nc_s)):
        np.testing.assert_array_equal(np.asarray(leaf_d), np.asarray(leaf_s))


def test_full_k_loss_and_grads_jitted_allclose():
    rng = np.random.RandomState(3)
    fa, fb = _feats(rng, 3, 5, 5)
    cfg_d = ImMatchNetConfig(**BASE)  # default lowering, jitted
    cfg_s = cfg_d.replace(nc_topk=25)
    params = init_immatchnet(jax.random.PRNGKey(3), cfg_d)
    nc = params["neigh_consensus"]

    def lg(cfg):
        f = jax.jit(
            jax.value_and_grad(lambda p: weak_loss_core(p, cfg, fa, fb))
        )
        return f(nc)

    ld, gd = lg(cfg_d)
    ls, gs = lg(cfg_s)
    np.testing.assert_allclose(float(ls), float(ld), rtol=1e-6, atol=1e-7)
    for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(gs)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_full_k_equivalence_rectangular_grids():
    """Symmetric mode on RECTANGULAR A/B grids: the dense path must run
    its sequential fallback; the band path handles it natively (taps
    swap roles, nothing is transposed)."""
    rng = np.random.RandomState(4)
    fa = jnp.asarray(rng.randn(2, 6, 5, 7).astype(np.float32))
    fb = jnp.asarray(rng.randn(2, 4, 7, 7).astype(np.float32))
    params = init_immatchnet(jax.random.PRNGKey(4), DENSE_MIRROR)
    nc = params["neigh_consensus"]
    out_d = np.asarray(match_pipeline(nc, DENSE_MIRROR, fa, fb))
    out_s = np.asarray(
        match_pipeline(nc, DENSE_MIRROR.replace(nc_topk=28), fa, fb)
    )
    np.testing.assert_array_equal(out_d, out_s)


def test_full_k_chunked_loss_matches_dense_chunked():
    cfg_d = DENSE_MIRROR.replace(loss_chunk=2, loss_chunk_remat=True)
    cfg_s = cfg_d.replace(nc_topk=25)
    rng = np.random.RandomState(5)
    fa, fb = _feats(rng, 4, 5, 5)
    params = init_immatchnet(jax.random.PRNGKey(5), cfg_d)
    nc = params["neigh_consensus"]
    ld = weak_loss_core(nc, cfg_d, fa, fb)
    ls = weak_loss_core(nc, cfg_s, fa, fb)
    assert np.asarray(ld).tobytes() == np.asarray(ls).tobytes()


# --- band selection ----------------------------------------------------------


def _numpy_mutual_band(corr, k):
    """Golden numpy reimplementation of the mutual selection rule:
    per-A-cell top-K by the key ``min(rank_in_row, rank_in_col) * nB +
    rank_in_row`` ascending, indices sorted ascending."""
    b, ha, wa, hb, wb = corr.shape
    nb = hb * wb
    flat = corr.reshape(b, ha * wa, nb)
    out = np.zeros((b, ha * wa, k), np.int32)
    for bi in range(b):
        m = flat[bi]
        order_a = np.argsort(-m, axis=1, kind="stable")
        rank_a = np.argsort(order_a, axis=1, kind="stable")
        order_b = np.argsort(-m, axis=0, kind="stable")
        rank_b = np.argsort(order_b, axis=0, kind="stable")
        key = np.minimum(rank_a, rank_b) * nb + rank_a
        sel = np.argsort(key, axis=1, kind="stable")[:, :k]
        out[bi] = np.sort(sel, axis=1)
    return out.reshape(b, ha, wa, k)


def test_topk_band_plain_matches_numpy():
    rng = np.random.RandomState(6)
    corr = rng.randn(2, 3, 4, 3, 5).astype(np.float32)
    k = 7
    vals, idx = topk_band(jnp.asarray(corr), k, mutual=False)
    flat = corr.reshape(2, 3, 4, 15)
    want_idx = np.sort(np.argsort(-flat, axis=-1)[..., :k], axis=-1)
    np.testing.assert_array_equal(np.asarray(idx), want_idx)
    want_vals = np.take_along_axis(flat, want_idx, axis=-1)
    np.testing.assert_array_equal(np.asarray(vals), want_vals)


def test_topk_band_mutual_matches_numpy_golden():
    rng = np.random.RandomState(7)
    corr = rng.randn(2, 4, 4, 4, 4).astype(np.float32)
    for k in (3, 8, 16):
        _, idx = topk_band(jnp.asarray(corr), k, mutual=True)
        np.testing.assert_array_equal(
            np.asarray(idx), _numpy_mutual_band(corr, k)
        )


def test_mutual_band_selection_key_is_swap_symmetric():
    """The PRIMARY selection key min(rank-in-row, rank-in-col) values an
    entry identically from both sides of the swap (the 'mutual union'
    growth order); per-row capacity is the only asymmetry. Checked via
    the guaranteed consequences: every row argmax AND (here, where
    capacity suffices) every column argmax is on the band, and B-grid
    coverage dominates the plain selection's."""
    rng = np.random.RandomState(8)
    corr = rng.randn(2, 5, 5, 5, 5).astype(np.float32)
    k = 12
    _, idx_mut = topk_band(jnp.asarray(corr), k, mutual=True)
    _, idx_plain = topk_band(jnp.asarray(corr), k, mutual=False)
    flat = corr.reshape(2, 25, 25)
    idx_mut = np.asarray(idx_mut).reshape(2, 25, k)
    idx_plain = np.asarray(idx_plain).reshape(2, 25, k)
    for bi in range(2):
        # row argmax always selected (its key is the global minimum 0)
        row_best = np.argmax(flat[bi], axis=1)
        for a in range(25):
            assert row_best[a] in idx_mut[bi, a]
        # column argmax selected from the B side at this capacity/seed
        col_best = np.argmax(flat[bi], axis=0)
        for b_cell in range(25):
            assert b_cell in idx_mut[bi, col_best[b_cell]]
        cov_mut = len(set(idx_mut[bi].ravel().tolist()))
        cov_plain = len(set(idx_plain[bi].ravel().tolist()))
        assert cov_mut >= cov_plain
        assert cov_mut == 25  # full B-grid coverage at K=12, this seed


def test_band_to_dense_roundtrip_full_k():
    rng = np.random.RandomState(9)
    corr = rng.randn(2, 3, 3, 3, 3).astype(np.float32)
    vals, idx = topk_band(jnp.asarray(corr), 9)
    dense = band_to_dense(vals, idx, (3, 3))
    np.testing.assert_array_equal(np.asarray(dense), corr)


# --- out-of-band / edge gather semantics -------------------------------------


def test_edge_gather_exact_zeros():
    """Neighbour reads that fall off the A grid, off the B grid, or off
    the band must contribute EXACT zeros (not clamped copies — silent
    clip would mask pointer bugs)."""
    b, h, w = 1, 3, 3
    nb = 9
    corr = jnp.asarray(np.random.RandomState(10).rand(b, h, w, h, w) + 1.0)
    vals, idx = topk_band(corr, nb)  # complete band, all values >= 1
    ptr = band_neighbor_pointers(idx, (h, w), (3, 3, 3, 3))
    n = h * w * nb
    g = np.asarray(
        band_gather_neighbors(
            vals.astype(jnp.float32).reshape(b, n, 1), ptr.reshape(b, n, -1)
        )
    ).reshape(b, h, w, nb, 81)

    corr_np = np.asarray(corr)
    taps = [
        (d1 - 1, d2 - 1, d3 - 1, d4 - 1)
        for d1 in range(3) for d2 in range(3)
        for d3 in range(3) for d4 in range(3)
    ]
    for ia in range(h):
        for ja in range(w):
            for bidx in range(nb):
                ib, jb = divmod(bidx, w)
                for t, (da, dja, dk, dl) in enumerate(taps):
                    na_i, na_j = ia + da, ja + dja
                    tb_i, tb_j = ib + dk, jb + dl
                    on_grid = (
                        0 <= na_i < h and 0 <= na_j < w
                        and 0 <= tb_i < h and 0 <= tb_j < w
                    )
                    got = g[0, ia, ja, bidx, t]
                    if on_grid:
                        assert got == corr_np[0, na_i, na_j, tb_i, tb_j]
                    else:
                        # exact zero, and provably not a clamped read:
                        # every on-band value is >= 1
                        assert got == 0.0


def test_partial_band_off_band_reads_are_zero():
    """K=1 band on a 3x3 grid: each A-cell holds only its argmax; any
    neighbour tap pointing at a B-index another cell did NOT select must
    read exact zero."""
    rng = np.random.RandomState(11)
    corr = jnp.asarray(rng.rand(1, 3, 3, 3, 3).astype(np.float32) + 1.0)
    vals, idx = topk_band(corr, 1)
    ptr = band_neighbor_pointers(idx, (3, 3), (3, 3, 3, 3))
    n = 9
    g = np.asarray(
        band_gather_neighbors(
            vals.reshape(1, n, 1), ptr.reshape(1, n, -1)
        )
    )
    vals_np = np.asarray(vals).ravel()
    # every gathered value is either an exact on-band value or exact 0
    on_band = set(vals_np.tolist())
    for v in np.unique(g):
        assert v == 0.0 or v in on_band


# --- PCK vs K ----------------------------------------------------------------


def test_synthetic_pck_vs_k_sweep():
    """Synthetic-transfer PCK over the band-width sweep, on the same
    pretrained-free setup as the committed synthetic proofs (patch16
    trunk + identity NC init, scripts/synthetic_convergence.py): the
    complete band must equal dense EXACTLY (the sweep's sanity anchor),
    and every partial-K PCK must stay within the reference band around
    dense — on this construction small K acts as a correlation denoiser
    and measures ABOVE dense (arXiv:2004.10566's equal-or-better
    regime), so the monotone K-sweep contract is 'complete band == dense
    and no collapse below it', not naive growth in K."""
    from ncnet_tpu.data.pairs import SyntheticPairDataset
    from ncnet_tpu.eval.synthetic import (
        evaluate_synthetic,
        synthetic_pck_vs_topk,
    )

    size = 64  # patch16 trunk: grid 4 -> nB = 16
    cfg = ImMatchNetConfig(
        feature_extraction_cnn="patch16",
        ncons_kernel_sizes=(3,), ncons_channels=(1,), nc_init="identity",
    )
    params = init_immatchnet(jax.random.PRNGKey(12), cfg)
    ds = SyntheticPairDataset(
        n=4, output_size=(size, size), seed=5, return_shift=True,
        granularity=32,
    )
    batch = {
        key: np.stack([ds[i][key] for i in range(len(ds))])
        for key in ("source_image", "target_image", "shift")
    }
    sweep = synthetic_pck_vs_topk(
        params, cfg, [batch], ks=(1, 4, 16), n_side=2, alpha=0.15
    )
    dense = evaluate_synthetic(params, cfg, [batch], n_side=2, alpha=0.15)
    assert dense > 0.5  # the construction resolves shifts at all
    assert sweep[16] == pytest.approx(dense, abs=1e-7)  # complete band
    # partial K stays in the useful regime (at the 128px/5-5-5 proxy
    # scale small K measures ABOVE dense — PERF.md round 8; at this tiny
    # 4x4 grid the guarantee asserted is no-collapse)
    assert sweep[4] >= 0.5 * dense
    assert sweep[1] >= 0.4 * dense


# --- config plumbing ---------------------------------------------------------


def test_check_sparse_config_validation():
    check_sparse_config(ImMatchNetConfig(nc_topk=0))
    check_sparse_config(ImMatchNetConfig(nc_topk=8))
    with pytest.raises(ValueError, match="negative"):
        check_sparse_config(ImMatchNetConfig(nc_topk=-1))
    with pytest.raises(ValueError, match="relocalization"):
        check_sparse_config(
            ImMatchNetConfig(nc_topk=8, relocalization_k_size=2)
        )


def test_sparse_pipeline_rejects_relocalization():
    cfg = ImMatchNetConfig(
        nc_topk=4, relocalization_k_size=2, **BASE
    )
    rng = np.random.RandomState(13)
    fa, fb = _feats(rng, 1, 4, 4)
    params = init_immatchnet(jax.random.PRNGKey(13), cfg)
    with pytest.raises(ValueError, match="relocalization"):
        match_pipeline(params["neigh_consensus"], cfg, fa, fb)


def test_config_roundtrip_and_legacy_dicts():
    cfg = ImMatchNetConfig(nc_topk=50, nc_topk_mutual=False)
    again = ImMatchNetConfig.from_dict(cfg.to_dict())
    assert again.nc_topk == 50 and again.nc_topk_mutual is False
    # checkpoints written before the sparse path have no nc_topk keys
    legacy = cfg.to_dict()
    del legacy["nc_topk"], legacy["nc_topk_mutual"]
    old = ImMatchNetConfig.from_dict(legacy)
    assert old.nc_topk == 0 and old.nc_topk_mutual is True
