import os

import numpy as np
import pytest

from ncnet_tpu.data.images import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    normalize_image_np,
    resize_bilinear_np,
)
from ncnet_tpu.data.loader import DataLoader, collate, shard_indices
from ncnet_tpu.data.pairs import ImagePairDataset, PFPascalDataset, SyntheticPairDataset


def test_resize_matches_torch_align_corners():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    rng = np.random.RandomState(0)
    img = rng.rand(11, 17, 3).astype(np.float32) * 255
    got = resize_bilinear_np(img, 25, 40)
    want = F.interpolate(
        torch.from_numpy(img.transpose(2, 0, 1))[None],
        size=(25, 40),
        mode="bilinear",
        align_corners=True,
    )[0].numpy().transpose(1, 2, 0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_resize_matches_jax_op():
    import jax.numpy as jnp

    from ncnet_tpu.ops.image import resize_bilinear_align_corners

    rng = np.random.RandomState(1)
    img = rng.rand(9, 13, 3).astype(np.float32)
    got_np = resize_bilinear_np(img, 20, 30)
    got_jax = np.asarray(resize_bilinear_align_corners(jnp.asarray(img), 20, 30))
    np.testing.assert_allclose(got_np, got_jax, rtol=1e-5, atol=1e-5)


def test_normalize():
    img = np.full((4, 4, 3), 255.0, np.float32)
    out = normalize_image_np(img)
    want = np.broadcast_to((1.0 - IMAGENET_MEAN) / IMAGENET_STD, out.shape)
    np.testing.assert_allclose(out, want, rtol=1e-6)


def _write_png(path, arr):
    from PIL import Image

    Image.fromarray(arr.astype(np.uint8)).save(path)


@pytest.fixture
def fake_pf_dataset(tmp_path):
    rng = np.random.RandomState(0)
    img_dir = tmp_path / "JPEGImages"
    img_dir.mkdir()
    names = []
    for i in range(4):
        name = f"JPEGImages/im{i}.png"
        _write_png(tmp_path / name, rng.randint(0, 255, (30 + i, 40 + i, 3)))
        names.append(name)
    # train/val schema
    train_csv = tmp_path / "train_pairs.csv"
    with open(train_csv, "w") as f:
        f.write("source_image,target_image,class,flip\n")
        f.write(f"{names[0]},{names[1]},1,0\n")
        f.write(f"{names[2]},{names[3]},2,1\n")
    # test schema with keypoints
    test_csv = tmp_path / "test_pairs.csv"
    with open(test_csv, "w") as f:
        f.write("source_image,target_image,class,XA,YA,XB,YB\n")
        f.write(f"{names[0]},{names[1]},1,10;20;30,5;15;25,12;22;32,6;16;26\n")
    return tmp_path, train_csv, test_csv


def test_image_pair_dataset(fake_pf_dataset):
    root, train_csv, _ = fake_pf_dataset
    ds = ImagePairDataset(str(train_csv), str(root), output_size=(32, 32))
    assert len(ds) == 2
    s = ds[0]
    assert s["source_image"].shape == (32, 32, 3)
    assert s["target_image"].shape == (32, 32, 3)
    # flip row: flipping source then resizing == resize then flip (allclose)
    s2 = ds[1]
    ds_noflip = ImagePairDataset(str(train_csv), str(root), output_size=(32, 32))
    ds_noflip.rows[1][3] = "0"
    s2_nf = ds_noflip[1]
    np.testing.assert_allclose(
        s2["source_image"], s2_nf["source_image"][:, ::-1], atol=1e-4
    )


def test_pf_pascal_dataset_scnet_procedure(fake_pf_dataset):
    root, _, test_csv = fake_pf_dataset
    ds = PFPascalDataset(str(test_csv), str(root), output_size=(32, 32),
                         pck_procedure="scnet")
    s = ds[0]
    # original image 0 is 30x40; scnet rescales points to a virtual 224x224
    assert float(s["L_pck"][0]) == 224.0
    np.testing.assert_allclose(s["source_im_size"][:2], [224, 224])
    np.testing.assert_allclose(s["source_points"][0, 0], 10 * 224 / 40, rtol=1e-5)
    np.testing.assert_allclose(s["source_points"][1, 0], 5 * 224 / 30, rtol=1e-5)
    # -1 padding beyond the 3 annotated points
    assert np.all(s["source_points"][:, 3:] == -1)


def test_pf_procedure_bbox_lpck(fake_pf_dataset):
    root, _, test_csv = fake_pf_dataset
    ds = PFPascalDataset(str(test_csv), str(root), output_size=(32, 32),
                         pck_procedure="pf")
    s = ds[0]
    # max bbox side of source points: x range 20, y range 20
    assert float(s["L_pck"][0]) == 20.0


def test_loader_deterministic_and_worker_invariant():
    ds = SyntheticPairDataset(n=12, output_size=(16, 16))
    batches1 = [b for b in DataLoader(ds, 4, shuffle=True, seed=3, num_workers=1)]
    batches4 = [b for b in DataLoader(ds, 4, shuffle=True, seed=3, num_workers=4)]
    assert len(batches1) == len(batches4) == 3
    for b1, b4 in zip(batches1, batches4):
        np.testing.assert_array_equal(b1["source_image"], b4["source_image"])


def test_loader_sharding():
    idx0 = shard_indices(10, 0, 2)
    idx1 = shard_indices(10, 1, 2)
    assert sorted(np.concatenate([idx0, idx1]).tolist()) == list(range(10))


def test_loader_surfaces_worker_exception_fast():
    """A poisoned dataset must raise the ORIGINAL exception (with its
    traceback text) promptly — not a late generic 'workers died' error."""
    import time

    class Poisoned:
        def __len__(self):
            return 8

        def __getitem__(self, idx):
            if idx == 2:
                raise ValueError("poisoned sample 2")
            return {"x": np.zeros((2,), np.float32)}

    loader = DataLoader(Poisoned(), 2, num_workers=2)
    t0 = time.time()
    with pytest.raises(RuntimeError, match="poisoned sample 2"):
        for _ in loader:
            pass
    assert time.time() - t0 < 1.0


def test_loader_process_backend_matches_thread():
    """The spawn-context process pool (the GIL-escape backend for rates
    the IVD config needs — PERF.md) must yield byte-identical batches in
    the same order as the thread backend, across epochs."""
    ds = SyntheticPairDataset(n=12, output_size=(16, 16))
    thread = DataLoader(ds, 4, shuffle=True, seed=3, num_workers=2)
    proc = DataLoader(
        ds, 4, shuffle=True, seed=3, num_workers=2, backend="process"
    )
    try:
        for _ in range(2):  # two epochs: the pool is reused
            bt = list(thread)
            bp = list(proc)
            assert len(bt) == len(bp) == 3
            for b1, b2 in zip(bt, bp):
                np.testing.assert_array_equal(
                    b1["source_image"], b2["source_image"]
                )
    finally:
        proc.close()


class _PoisonedDataset:
    """Module-level (spawn workers must pickle the dataset by reference)."""

    def __len__(self):
        return 8

    def __getitem__(self, idx):
        if idx == 2:
            raise ValueError("poisoned sample 2")
        return {"x": np.zeros((2,), np.float32)}


def test_loader_process_backend_surfaces_exception():
    loader = DataLoader(
        _PoisonedDataset(), 2, num_workers=2, backend="process"
    )
    try:
        # same error contract as the thread backend: RuntimeError wrapper
        # naming the original exception
        with pytest.raises(RuntimeError, match="poisoned sample 2"):
            for _ in loader:
                pass
    finally:
        loader.close()


def test_collate():
    out = collate([{"a": np.zeros((2, 2), np.float32)}, {"a": np.ones((2, 2), np.float32)}])
    assert out["a"].shape == (2, 2, 2)


# --- graceful degradation (ncnet_tpu.resilience satellite) -------------------


class _TransientDataset:
    """Every sample fails once (flaky NFS style), then loads — module-level
    state so the retry path, not luck, is what makes the epoch pass."""

    def __init__(self, n=8):
        self.n = n
        self.failed_once = set()

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        if idx not in self.failed_once:
            self.failed_once.add(idx)
            raise OSError(f"transient read failure on {idx}")
        return {"x": np.full((2,), float(idx), np.float32)}


def test_loader_retries_transient_failures():
    loader = DataLoader(
        _TransientDataset(8), 2, num_workers=2,
        sample_retries=2, retry_backoff=0.001,
    )
    batches = list(loader)
    assert len(batches) == 4
    assert loader.skipped == []  # retried, never substituted
    got = sorted(float(v) for b in batches for v in b["x"][:, 0])
    assert got == [float(i) for i in range(8)]


class _AlwaysBadSample:
    """Index 2 is permanently corrupt (bitrot); everything else loads."""

    def __init__(self, n=8, bad=(2,)):
        self.n = n
        self.bad = set(bad)

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        if idx in self.bad:
            raise ValueError(f"corrupt sample {idx}")
        return {"x": np.full((2,), float(idx), np.float32)}


def test_loader_skip_budget_substitutes_deterministically():
    loader = DataLoader(
        _AlwaysBadSample(8), 2, num_workers=3,
        sample_retries=0, skip_budget=2,
    )
    batches = list(loader)
    assert len(batches) == 4
    assert loader.skipped == [2]
    # the corrupt sample is replaced by the NEXT loadable index, keeping
    # batch shapes constant (no jit recompile) and worker-count invariance
    np.testing.assert_array_equal(batches[1]["x"][:, 0], [3.0, 3.0])
    # identical epoch under a different worker count
    again = list(DataLoader(
        _AlwaysBadSample(8), 2, num_workers=1,
        sample_retries=0, skip_budget=2,
    ))
    for b1, b2 in zip(batches, again):
        np.testing.assert_array_equal(b1["x"], b2["x"])


def test_loader_skip_budget_exhaustion_fails_loudly():
    loader = DataLoader(
        _AlwaysBadSample(8, bad=(2, 6)), 2, num_workers=1,
        sample_retries=0, skip_budget=1,
    )
    with pytest.raises(RuntimeError, match="skip budget exhausted"):
        for _ in loader:
            pass


def test_loader_skip_budget_zero_keeps_fail_fast():
    loader = DataLoader(
        _AlwaysBadSample(8), 2, num_workers=1, sample_retries=0,
    )
    with pytest.raises(RuntimeError, match="corrupt sample 2"):
        for _ in loader:
            pass


class _ProcBadSample(_AlwaysBadSample):
    """Module-level subclass: spawn workers pickle the dataset by value."""


def test_loader_process_backend_skip_budget():
    with DataLoader(
        _ProcBadSample(8), 2, num_workers=2, backend="process",
        sample_retries=0, skip_budget=2,
    ) as loader:
        batches = list(loader)
        assert len(batches) == 4
        assert loader.skipped == [2]
        np.testing.assert_array_equal(batches[1]["x"][:, 0], [3.0, 3.0])
    assert loader._pool is None  # the context manager shut the pool down


def test_loader_context_manager_closes_pool():
    ds = SyntheticPairDataset(n=4, output_size=(16, 16))
    with DataLoader(ds, 2, num_workers=1, backend="process") as loader:
        list(loader)
        assert loader._pool is not None
    assert loader._pool is None
    loader.close()  # idempotent


def test_loader_iter_epoch_absolute_shuffle_and_skip():
    """`iter_epoch(e)` must shuffle by ABSOLUTE epoch (resume-correct) and
    `skip_batches` must replay the identical tail of the sequence."""
    ds = SyntheticPairDataset(n=12, output_size=(16, 16))
    loader = DataLoader(ds, 4, shuffle=True, seed=3, num_workers=1)
    legacy = [list(loader) for _ in range(2)]  # epochs 0, 1 via __iter__
    addressed = [list(loader.iter_epoch(e)) for e in (0, 1)]
    for le, ae in zip(legacy, addressed):
        for b1, b2 in zip(le, ae):
            np.testing.assert_array_equal(b1["source_image"], b2["source_image"])
    tail = list(loader.iter_epoch(1, skip_batches=2))
    assert len(tail) == 1
    np.testing.assert_array_equal(
        tail[0]["source_image"], addressed[1][2]["source_image"]
    )
