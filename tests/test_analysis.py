"""Golden-file tests for the static lint suite (ncnet_tpu.analysis).

Each rule gets at least one known-bad snippet (expected finding) and one
known-good snippet (clean) — the executable form of the rule catalog in
ncnet_tpu/analysis/README.md — plus suppression-contract tests and the
repo-wide zero-findings gate that makes the rules a permanent property of
the codebase rather than a one-off review.
"""

import os

import pytest

from ncnet_tpu.analysis import rules  # noqa: F401  (registers the rule set)
from ncnet_tpu.analysis.engine import (
    RULES,
    SEVERITY_ORDER,
    lint_paths,
    lint_source,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def findings_for(src, path="snippet.py", only=None):
    out = lint_source(src, path)
    if only:
        out = [f for f in out if f.rule == only]
    return out


def rule_ids(src, path="snippet.py"):
    return [f.rule for f in lint_source(src, path)]


# --- bare-assert ------------------------------------------------------------


BAD_ASSERT = """
def combine(parts, both_directions):
    assert both_directions, "combined output implies both_directions"
    return parts
"""

CLEAN_ASSERT = """
def combine(parts, both_directions):
    if not both_directions:
        raise ValueError("combined output implies both_directions")
    return parts
"""


def test_bare_assert_bad():
    fs = findings_for(BAD_ASSERT, only="bare-assert")
    assert len(fs) == 1
    assert fs[0].line == 3
    assert fs[0].severity == "warning"


def test_bare_assert_clean():
    assert findings_for(CLEAN_ASSERT, only="bare-assert") == []


def test_bare_assert_exempts_test_files():
    """pytest-style asserts in test code are the POINT of test code."""
    assert findings_for(BAD_ASSERT, path="tests/test_foo.py") == []
    assert findings_for(BAD_ASSERT, path="test_foo.py") == []


# --- host-sync-in-jit -------------------------------------------------------


BAD_SYNC_DECORATOR = """
import jax

@jax.jit
def f(x):
    print("value:", x)
    return x * 2
"""

BAD_SYNC_WRAPPED = """
import jax

def f(x):
    return float(x) * 2

g = jax.jit(f)
"""

BAD_SYNC_TRANSITIVE = """
import jax
import numpy as np
from jax import lax

def helper(x):
    return np.asarray(x)

def body(c):
    return helper(c)

out = lax.map(body, xs)
"""

BAD_SYNC_ITEM = """
import jax
from functools import partial

@partial(jax.jit, static_argnums=(1,))
def f(x, n):
    return x.item() + n
"""

CLEAN_SYNC = """
import jax

@jax.jit
def f(x):
    jax.debug.print("value: {}", x)
    return x * 2

def host_loop(xs):
    for x in xs:
        print(float(f(x)))  # host side: sync is the point
"""

CLEAN_SYNC_MODULE_ATTR = """
import jax
import scipy.io as sio

@jax.jit
def f(x):
    return x * 2

def dump(path, x):
    sio.savemat(path, {"x": x})
"""


def test_host_sync_decorated():
    fs = findings_for(BAD_SYNC_DECORATOR, only="host-sync-in-jit")
    assert len(fs) == 1 and fs[0].line == 6


def test_host_sync_wrapped_function():
    fs = findings_for(BAD_SYNC_WRAPPED, only="host-sync-in-jit")
    assert len(fs) == 1 and "float()" in fs[0].message


def test_host_sync_transitive_local_call():
    """body -> helper propagation: the sync hides one call away from the
    lax.map root."""
    fs = findings_for(BAD_SYNC_TRANSITIVE, only="host-sync-in-jit")
    assert len(fs) == 1 and "asarray" in fs[0].message


def test_host_sync_item_method_partial_jit():
    fs = findings_for(BAD_SYNC_ITEM, only="host-sync-in-jit")
    assert len(fs) == 1 and ".item()" in fs[0].message


def test_host_sync_clean():
    assert findings_for(CLEAN_SYNC, only="host-sync-in-jit") == []
    assert findings_for(CLEAN_SYNC_MODULE_ATTR, only="host-sync-in-jit") == []


# --- unguarded-division -----------------------------------------------------


BAD_DIV_INLINE = """
import jax.numpy as jnp

def mutual(corr):
    return corr / jnp.max(corr, axis=(1, 2), keepdims=True)
"""

BAD_DIV_NAMED = """
import jax.numpy as jnp

def l1(x):
    denom = jnp.sum(x, axis=1, keepdims=True)
    return x / denom
"""

CLEAN_DIV_EPS = """
import jax.numpy as jnp

def mutual(corr, eps=1e-5):
    return corr / (jnp.max(corr, axis=(1, 2), keepdims=True) + eps)

def l1(x):
    denom = jnp.sum(x, axis=1, keepdims=True) + 1e-4
    return x / denom

def norm(x, eps=1e-6):
    denom = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True) + eps)
    return x / denom
"""

CLEAN_DIV_HOST = """
import numpy as np

def host_stat(x):
    return x / np.max(x)  # host fp64 pipeline: out of bf16 scope
"""

CLEAN_DIV_CLAMPED = """
import jax.numpy as jnp

def safe(x):
    return x / jnp.maximum(jnp.max(x, axis=1), 1e-6)
"""


def test_unguarded_division_inline():
    fs = findings_for(BAD_DIV_INLINE, only="unguarded-division")
    assert len(fs) == 1 and fs[0].line == 5


def test_unguarded_division_through_assignment():
    fs = findings_for(BAD_DIV_NAMED, only="unguarded-division")
    assert len(fs) == 1 and fs[0].line == 6


def test_unguarded_division_clean():
    assert findings_for(CLEAN_DIV_EPS, only="unguarded-division") == []
    assert findings_for(CLEAN_DIV_HOST, only="unguarded-division") == []
    assert findings_for(CLEAN_DIV_CLAMPED, only="unguarded-division") == []


# --- unstable-exp -----------------------------------------------------------


BAD_EXP = """
import jax.numpy as jnp

def softmax(logits, axis):
    e = jnp.exp(logits)
    return e / (jnp.sum(e, axis=axis, keepdims=True) + 1e-9)
"""

CLEAN_EXP = """
import jax
import jax.numpy as jnp

def softmax(logits, axis):
    return jax.nn.softmax(logits, axis=axis)

def stable(logits, axis):
    e = jnp.exp(logits - jnp.max(logits, axis=axis, keepdims=True))
    return e / (jnp.sum(e, axis=axis, keepdims=True) + 1e-9)

def decay(d2, sigma):
    return jnp.exp(-d2 / (2 * sigma**2))
"""


def test_unstable_exp_bad():
    fs = findings_for(BAD_EXP, only="unstable-exp")
    assert len(fs) == 1 and fs[0].line == 5


def test_unstable_exp_clean():
    assert findings_for(CLEAN_EXP, only="unstable-exp") == []


# --- traced-python-branch ---------------------------------------------------


BAD_BRANCH = """
import jax.numpy as jnp

def f(x):
    if jnp.any(x > 0):
        return x
    return -x
"""

CLEAN_BRANCH = """
import jax.numpy as jnp

def f(x, flag):
    if flag and x.shape[0] > 2:
        return x
    if jnp.asarray(x).dtype == jnp.float32:
        return x * 2
    return jnp.where(x > 0, x, -x)
"""


def test_traced_branch_bad():
    fs = findings_for(BAD_BRANCH, only="traced-python-branch")
    assert len(fs) == 1 and "jax.numpy.any" in fs[0].message


def test_traced_branch_clean():
    """shape/dtype metadata is static under jit; branching on it is the
    normal way to specialize a trace."""
    assert findings_for(CLEAN_BRANCH, only="traced-python-branch") == []


# --- non-atomic-artifact-write ----------------------------------------------


BAD_ARTIFACT_WRITE = """
from flax import serialization

def save_checkpoint(path, payload):
    with open(path, "wb") as f:
        f.write(serialization.msgpack_serialize(payload))
"""

BAD_ARTIFACT_WRITE_NAME = """
def dump(metrics_path, blob):
    with open(metrics_path, mode="wb") as f:
        f.write(blob)
"""

CLEAN_ARTIFACT_WRITE = """
from ncnet_tpu.resilience.durable import durable_write_bytes

def save_checkpoint(path, blob):
    durable_write_bytes(path, blob)

def write_png(path, encoded):
    # non-resume-critical binary output: out of the rule's scope
    with open(path, "wb") as f:
        f.write(encoded)

def read_checkpoint(path):
    with open(path, "rb") as f:
        return f.read()
"""


def test_non_atomic_artifact_write_bad():
    fs = findings_for(BAD_ARTIFACT_WRITE, only="non-atomic-artifact-write")
    assert len(fs) == 1 and fs[0].line == 5
    assert "durable_write_bytes" in fs[0].message
    fs = findings_for(BAD_ARTIFACT_WRITE_NAME, only="non-atomic-artifact-write")
    assert len(fs) == 1


def test_non_atomic_artifact_write_clean():
    assert findings_for(CLEAN_ARTIFACT_WRITE,
                        only="non-atomic-artifact-write") == []


def test_non_atomic_artifact_write_exempts_tests():
    assert findings_for(BAD_ARTIFACT_WRITE, path="tests/test_ck.py") == []


# --- unchecked-gather -------------------------------------------------------


BAD_GATHER_TAKE = """
import jax.numpy as jnp

def pick(values, idx):
    return jnp.take(values, idx, axis=1)
"""

BAD_GATHER_TAL = """
import jax.numpy as jnp

def pick(values, idx):
    return jnp.take_along_axis(values, idx, axis=-1)
"""

BAD_GATHER_AT_GET = """
def pick(values, idx):
    return values.at[idx].get()
"""

CLEAN_GATHER = """
import jax.numpy as jnp

def pick(values, idx):
    a = jnp.take(values, idx, axis=1, mode="fill", fill_value=0.0)
    b = jnp.take_along_axis(values, idx, axis=-1, mode="promise_in_bounds")
    c = values.at[idx].get(mode="clip")
    d = values.at[idx].set(0.0)  # writes have their own defaults; not a read
    return a + b + c + d
"""


def test_unchecked_gather_take_bad():
    fs = findings_for(BAD_GATHER_TAKE, only="unchecked-gather")
    assert len(fs) == 1 and fs[0].line == 5
    assert "mode" in fs[0].message


def test_unchecked_gather_take_along_axis_bad():
    fs = findings_for(BAD_GATHER_TAL, only="unchecked-gather")
    assert len(fs) == 1


def test_unchecked_gather_at_get_bad():
    fs = findings_for(BAD_GATHER_AT_GET, only="unchecked-gather")
    assert len(fs) == 1
    assert ".at[...].get()" in fs[0].message


def test_unchecked_gather_clean():
    assert findings_for(CLEAN_GATHER, only="unchecked-gather") == []


def test_unchecked_gather_respects_import_alias():
    """`numpy.take` (host numpy) raises on OOB by default — only the jnp
    entry points with silent-clamp jit semantics are in scope."""
    src = """
import numpy as np

def pick(values, idx):
    return np.take(values, idx, axis=1)
"""
    assert findings_for(src, only="unchecked-gather") == []


# --- mutable-default-arg ----------------------------------------------------


BAD_DEFAULT = """
def collect(x, acc=[]):
    acc.append(x)
    return acc
"""

CLEAN_DEFAULT = """
def collect(x, acc=None):
    acc = [] if acc is None else acc
    acc.append(x)
    return acc

def sized(x, shape=(3, 3)):
    return x.reshape(shape)
"""


def test_mutable_default_bad():
    fs = findings_for(BAD_DEFAULT, only="mutable-default-arg")
    assert len(fs) == 1


def test_mutable_default_clean():
    assert findings_for(CLEAN_DEFAULT, only="mutable-default-arg") == []


# --- recompile-hazard -------------------------------------------------------


BAD_JIT_IN_LOOP = """
import jax

def sweep(params, batches):
    outs = []
    for batch in batches:
        step = jax.jit(lambda p, b: p + b)
        outs.append(step(params, batch))
    return outs
"""

BAD_JIT_IMMEDIATE = """
import jax

def extract(params, img, config):
    return jax.jit(lambda p, x: p + x)(params, img)
"""

BAD_PMAP_IN_WHILE = """
import jax

def drain(params, queue):
    while queue:
        f = jax.pmap(lambda p: p * 2)
        f(params)
"""

BAD_JIT_IN_COMPREHENSION = """
import jax

def build(fns):
    return [jax.jit(f) for f in fns]
"""

CLEAN_JIT = """
import jax
from functools import partial

step = jax.jit(lambda p, b: p + b)  # module scope: one cache forever

def make_step(config):
    return jax.jit(partial(apply, config))  # factory return

def evaluate(params, batches):
    local = jax.jit(lambda p, b: p + b)  # bound once, reused in the loop
    return [local(params, b) for b in batches]

class Engine:
    def __init__(self, apply):
        self._jit = jax.jit(apply)  # one wrapper per engine instance

def nested_def_in_loop(fns):
    for f in fns:
        def runner(p):  # the def is in the loop; the jit call is not
            g = jax.jit(f)
            return g(p)
        yield runner
"""


def test_recompile_hazard_jit_in_loop():
    fs = findings_for(BAD_JIT_IN_LOOP, only="recompile-hazard")
    assert len(fs) == 1
    assert fs[0].line == 7
    assert "loop" in fs[0].message


def test_recompile_hazard_immediate_invoke():
    fs = findings_for(BAD_JIT_IMMEDIATE, only="recompile-hazard")
    assert len(fs) == 1
    assert "immediately invoked" in fs[0].message


def test_recompile_hazard_pmap_in_while():
    fs = findings_for(BAD_PMAP_IN_WHILE, only="recompile-hazard")
    assert len(fs) == 1
    assert "pmap" in fs[0].message


def test_recompile_hazard_comprehension():
    fs = findings_for(BAD_JIT_IN_COMPREHENSION, only="recompile-hazard")
    assert len(fs) == 1


def test_recompile_hazard_clean_forms():
    assert findings_for(CLEAN_JIT, only="recompile-hazard") == []


def test_recompile_hazard_respects_import_alias():
    src = BAD_JIT_IN_LOOP.replace("import jax", "from jax import jit").replace(
        "jax.jit", "jit"
    )
    assert len(findings_for(src, only="recompile-hazard")) == 1


def test_recompile_hazard_exempts_tests():
    assert (
        findings_for(BAD_JIT_IN_LOOP, path="tests/test_x.py",
                     only="recompile-hazard")
        == []
    )


# --- wall-clock-timing ------------------------------------------------------


BAD_WALL_DIRECT = """
import time

def measure(fn):
    t0 = time.time()
    fn()
    return time.time() - t0
"""

BAD_WALL_ALIASED = """
import time as clock

def measure(fn):
    start = clock.time()
    fn()
    dur = clock.time() - start
    return dur
"""

GOOD_MONOTONIC_TIMING = """
import time

def measure(fn):
    t0 = time.perf_counter()
    fn()
    dt = time.perf_counter() - t0
    stamp = time.time()  # a TIMESTAMP field, never subtracted
    return dt, stamp
"""

GOOD_WALL_AT_MODULE_SCOPE = """
import time

EPOCH_ANCHOR = time.time()
OFFSET = 1.5 - 0.5  # an unrelated subtraction stays silent
"""


def test_wall_clock_subtraction_flagged_both_operands():
    # t0-on-the-right (the common shape) and the call on either side
    assert rule_ids(BAD_WALL_DIRECT) == ["wall-clock-timing"]
    flipped = BAD_WALL_DIRECT.replace(
        "return time.time() - t0", "return t0 - time.time()"
    )
    assert rule_ids(flipped) == ["wall-clock-timing"]


def test_wall_clock_alias_and_name_expansion():
    # `import time as clock` resolves through ctx.canonical; `start` is
    # expanded one level to its `clock.time()` assignment
    assert rule_ids(BAD_WALL_ALIASED) == ["wall-clock-timing"]


def test_monotonic_timing_and_timestamps_clean():
    assert rule_ids(GOOD_MONOTONIC_TIMING) == []
    assert rule_ids(GOOD_WALL_AT_MODULE_SCOPE) == []


def test_wall_clock_rule_exempts_tests():
    assert rule_ids(BAD_WALL_DIRECT, path="tests/test_x.py") == []


def test_wall_clock_suppression_with_reason():
    src = BAD_WALL_DIRECT.replace(
        "return time.time() - t0",
        "return time.time() - t0  "
        "# nclint: disable=wall-clock-timing -- wall-time budget on purpose",
    )
    assert rule_ids(src) == []


# --- swallowed-exception ----------------------------------------------------


BAD_SWALLOW_BARE = """
def load(path):
    try:
        return open(path).read()
    except:
        pass
"""

BAD_SWALLOW_BROAD_UNUSED = """
def load(path):
    try:
        return open(path).read()
    except Exception as exc:
        return None
"""

BAD_SWALLOW_TUPLE = """
def load(path):
    try:
        return open(path).read()
    except (ValueError, Exception):
        return None
"""

GOOD_SWALLOW_RERAISES = """
def load(path):
    try:
        return open(path).read()
    except Exception:
        raise RuntimeError(path)
"""

GOOD_SWALLOW_USES_NAME = """
def load(path, log):
    try:
        return open(path).read()
    except Exception as exc:
        log.warning("load failed: %s", exc)
        return None
"""

GOOD_SWALLOW_NARROW = """
def load(path):
    try:
        return open(path).read()
    except FileNotFoundError:
        return None
"""


def test_swallowed_exception_bare_and_broad_flagged():
    assert rule_ids(BAD_SWALLOW_BARE) == ["swallowed-exception"]
    assert rule_ids(BAD_SWALLOW_BROAD_UNUSED) == ["swallowed-exception"]
    base = BAD_SWALLOW_BROAD_UNUSED.replace("Exception as exc", "BaseException")
    assert rule_ids(base) == ["swallowed-exception"]


def test_swallowed_exception_tuple_containing_broad_flagged():
    assert rule_ids(BAD_SWALLOW_TUPLE) == ["swallowed-exception"]


def test_swallowed_exception_reraise_use_and_narrow_clean():
    assert rule_ids(GOOD_SWALLOW_RERAISES) == []
    assert rule_ids(GOOD_SWALLOW_USES_NAME) == []
    assert rule_ids(GOOD_SWALLOW_NARROW) == []


def test_swallowed_exception_exempts_tests():
    assert rule_ids(BAD_SWALLOW_BARE, path="tests/test_x.py") == []


def test_swallowed_exception_suppression_with_reason():
    src = BAD_SWALLOW_BROAD_UNUSED.replace(
        "except Exception as exc:",
        "except Exception as exc:  "
        "# nclint: disable=swallowed-exception -- best-effort probe; "
        "absence of the file is the answer",
    )
    assert rule_ids(src) == []


# --- suppressions -----------------------------------------------------------


def test_suppression_with_reason_silences():
    src = BAD_ASSERT.replace(
        'assert both_directions, "combined output implies both_directions"',
        'assert both_directions  '
        "# nclint: disable=bare-assert -- exercised only from the owning "
        "test harness",
    )
    assert findings_for(src) == []


def test_suppression_without_reason_is_an_error():
    src = BAD_ASSERT.replace(
        'assert both_directions, "combined output implies both_directions"',
        "assert both_directions  # nclint: disable=bare-assert",
    )
    fs = findings_for(src)
    assert [f.rule for f in fs] == ["bad-suppression"]
    assert fs[0].severity == "error"


def test_suppression_for_other_rule_does_not_apply():
    src = BAD_ASSERT.replace(
        'assert both_directions, "combined output implies both_directions"',
        "assert both_directions  "
        "# nclint: disable=unstable-exp -- wrong rule on purpose",
    )
    assert [f.rule for f in findings_for(src)] == ["bare-assert"]


# --- engine / CLI -----------------------------------------------------------


def test_rule_catalog_size_and_severities():
    """The catalog the acceptance criteria count: >= 5 distinct rules, all
    gate-relevant (warning or stronger)."""
    assert len(RULES) >= 5
    for r in RULES.values():
        assert SEVERITY_ORDER[r.severity] >= SEVERITY_ORDER["warning"]
        assert r.doc.strip(), f"rule {r.rule_id} has no catalog doc"


def test_syntax_error_reported_not_raised():
    fs = lint_source("def broken(:\n", "bad.py")
    assert [f.rule for f in fs] == ["syntax-error"]


def test_cli_bad_tree_and_select(tmp_path, capsys):
    from ncnet_tpu.analysis.cli import main

    bad = tmp_path / "mod.py"
    bad.write_text(BAD_EXP)
    assert main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "unstable-exp" in out

    # --select narrows the rule set; a clean selection exits 0
    assert main([str(bad), "--select", "bare-assert"]) == 0


def test_cli_json_output(tmp_path, capsys):
    import json

    from ncnet_tpu.analysis.cli import main

    bad = tmp_path / "mod.py"
    bad.write_text(BAD_DEFAULT)
    assert main([str(bad), "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["count"] == 1
    assert payload["findings"][0]["rule"] == "mutable-default-arg"


# --- process-zero-only-io ---------------------------------------------------


BAD_P0_DEVICE_GET = """
import jax

def snapshot(state, path):
    if jax.process_index() != 0:
        return
    tree = jax.device_get(state.params)
    save(path, tree)
"""

BAD_P0_EQ_BODY = """
import jax

def snapshot(state, path):
    if jax.process_index() == 0:
        blob = serialize(jax.device_get(state.opt_state))
        with open(path + ".ckpt", "wb") as f:
            f.write(blob)
"""

BAD_P0_COMPOUND_GUARD = """
import jax

def snapshot(state, path, legacy):
    if legacy and jax.process_index() != 0:
        return
    tree = jax.device_get(state.params)
"""

CLEAN_P0_SCALAR = """
import jax

def log_metrics(loss, path):
    if jax.process_index() == 0:
        value = float(loss)
        with open(path + ".jsonl", "a") as f:
            f.write(str(value))
"""

CLEAN_P0_UNGUARDED = """
import jax

def snapshot_sharded(state, path):
    # collective: every process writes its own shards, no guard
    tree = jax.device_get(state.params)
    save(path, tree)
"""


def test_process_zero_io_ne_early_exit():
    fs = findings_for(BAD_P0_DEVICE_GET, only="process-zero-only-io")
    assert len(fs) == 1
    assert "device_get" in fs[0].message


def test_process_zero_io_eq_body_and_artifact_write():
    fs = findings_for(BAD_P0_EQ_BODY, only="process-zero-only-io")
    assert len(fs) == 2  # the device_get AND the wb artifact write
    assert any("device_get" in f.message for f in fs)
    assert any("artifact write" in f.message for f in fs)


def test_process_zero_io_compound_guard():
    """`if legacy and process_index() != 0: return` still gates the
    following statements on process 0 — the loop.py legacy-branch shape."""
    fs = findings_for(BAD_P0_COMPOUND_GUARD, only="process-zero-only-io")
    assert len(fs) == 1


def test_process_zero_io_scalar_metrics_clean():
    """Tiny host-side metrics I/O on process 0 is FINE — the rule targets
    O(state) funnels, not jsonl appends."""
    assert findings_for(CLEAN_P0_SCALAR, only="process-zero-only-io") == []


def test_process_zero_io_unguarded_clean():
    assert findings_for(CLEAN_P0_UNGUARDED, only="process-zero-only-io") == []


def test_process_zero_io_exempt_paths():
    assert findings_for(
        BAD_P0_DEVICE_GET, path="ncnet_tpu/resilience/distributed.py",
        only="process-zero-only-io",
    ) == []
    assert findings_for(
        BAD_P0_DEVICE_GET, path="tests/test_foo.py",
        only="process-zero-only-io",
    ) == []


# --- the repo-wide gate -----------------------------------------------------


def test_repo_lint_gate_zero_findings():
    """CI gate: the whole library + scripts + benchmarks tree is clean at
    severity >= warning (suppressions, each with a mandatory reason, are
    the only escape hatch). Equivalent to:

        python scripts/lint.py ncnet_tpu scripts benchmarks
    """
    paths = [os.path.join(REPO, d)
             for d in ("ncnet_tpu", "scripts", "benchmarks")]
    findings = lint_paths(paths)
    gating = [
        f for f in findings
        if SEVERITY_ORDER[f.severity] >= SEVERITY_ORDER["warning"]
    ]
    assert not gating, "\n" + "\n".join(f.format() for f in gating)


def test_repo_suppressions_all_carry_reasons():
    """Every inline suppression in the linted tree parses with a reason —
    the bad-suppression error path of the gate, asserted directly."""
    from ncnet_tpu.analysis.engine import _SUPPRESS_RE, iter_python_files

    paths = [os.path.join(REPO, d)
             for d in ("ncnet_tpu", "scripts", "benchmarks")]
    n_directives = 0
    for path in iter_python_files(paths):
        with open(path, encoding="utf-8") as f:
            for line in f:
                m = _SUPPRESS_RE.search(line)
                if m:
                    n_directives += 1
                    assert (m.group(2) or "").strip(), (
                        f"suppression without reason in {path}: "
                        f"{line.strip()}"
                    )
    assert n_directives >= 1, "expected at least one real suppression"


# --- interprocedural mode (ProjectIndex) ------------------------------------


def _write_pkg(tmp_path, files):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for name, src in files.items():
        (pkg / name).write_text(src)
    return tmp_path


HELPER_SYNC = """
def fetch_scalar(x):
    return x.item()
"""

CALLER_SYNC = """
import jax
from pkg.helper import fetch_scalar

@jax.jit
def step(x):
    return fetch_scalar(x) + 1
"""


def test_interprocedural_host_sync_reported_at_call_site(tmp_path):
    root = _write_pkg(
        tmp_path, {"helper.py": HELPER_SYNC, "caller.py": CALLER_SYNC}
    )
    fs = [f for f in lint_paths([str(root)]) if f.rule == "host-sync-in-jit"]
    assert len(fs) == 1
    assert fs[0].path.endswith("caller.py")  # caller owns the suppression
    assert "fetch_scalar" in fs[0].message
    assert "helper.py" in fs[0].message  # finding names the callee's home


def test_interprocedural_host_sync_prunes_nested_defs(tmp_path):
    # the sync lives in an INNER def (a host-side callback the helper
    # merely defines) — calling the helper from jit is fine
    helper = """
def make_logger():
    def log(x):
        return x.item()
    return log
"""
    caller = """
import jax
from pkg.helper import make_logger

@jax.jit
def step(x):
    logger = make_logger()
    return x + 1
"""
    root = _write_pkg(tmp_path, {"helper.py": helper, "caller.py": caller})
    fs = [f for f in lint_paths([str(root)]) if f.rule == "host-sync-in-jit"]
    assert fs == []


def test_interprocedural_host_sync_skips_static_casts(tmp_path):
    # int()/float()/bool() one call away are overwhelmingly static
    # shape/config casts — the cross-module step must not flag them
    helper = """
def grid_side(x):
    side = int(x.shape[0] ** 0.5)
    return side
"""
    root = _write_pkg(
        tmp_path,
        {"helper.py": helper, "caller.py": CALLER_SYNC.replace(
            "fetch_scalar", "grid_side"
        ).replace("pkg.helper import grid_side", "pkg.helper import grid_side")},
    )
    fs = [f for f in lint_paths([str(root)]) if f.rule == "host-sync-in-jit"]
    assert fs == []


def test_single_source_lint_stays_intraprocedural():
    # lint_source has no ProjectIndex: the cross-module call cannot be
    # resolved and must not crash or fabricate findings
    assert findings_for(CALLER_SYNC, only="host-sync-in-jit") == []


FACTORY = """
import jax

def make_step(fn):
    return jax.jit(fn)
"""

LOOP_CALLER = """
from pkg.factory import make_step

def sweep(fns, x):
    outs = []
    for fn in fns:
        step = make_step(fn)
        outs.append(step(x))
    return outs
"""

HOISTED_CALLER = """
from pkg.factory import make_step

def run(fn, xs):
    step = make_step(fn)
    return [step(x) for x in xs]
"""


def test_interprocedural_recompile_hazard_factory_in_loop(tmp_path):
    root = _write_pkg(
        tmp_path, {"factory.py": FACTORY, "caller.py": LOOP_CALLER}
    )
    fs = [f for f in lint_paths([str(root)]) if f.rule == "recompile-hazard"]
    assert len(fs) == 1
    assert fs[0].path.endswith("caller.py")
    assert "make_step" in fs[0].message


def test_interprocedural_recompile_hazard_hoisted_clean(tmp_path):
    root = _write_pkg(
        tmp_path, {"factory.py": FACTORY, "caller.py": HOISTED_CALLER}
    )
    fs = [f for f in lint_paths([str(root)]) if f.rule == "recompile-hazard"]
    assert fs == []


SAVER = """
import jax

def snapshot(state, path):
    host_params = jax.device_get(state.params)
    return host_params
"""

GUARDED_CALLER = """
import jax
from pkg.saver import snapshot

def maybe_save(state, path):
    if jax.process_index() == 0:
        snapshot(state, path)
"""


def test_interprocedural_process_zero_io(tmp_path):
    root = _write_pkg(
        tmp_path, {"saver.py": SAVER, "caller.py": GUARDED_CALLER}
    )
    fs = [
        f for f in lint_paths([str(root)])
        if f.rule == "process-zero-only-io"
    ]
    assert len(fs) == 1
    assert fs[0].path.endswith("caller.py")
    assert "snapshot" in fs[0].message


def test_interprocedural_process_zero_io_unguarded_clean(tmp_path):
    unguarded = """
from pkg.saver import snapshot

def always_save(state, path):
    snapshot(state, path)
"""
    root = _write_pkg(tmp_path, {"saver.py": SAVER, "caller.py": unguarded})
    fs = [
        f for f in lint_paths([str(root)])
        if f.rule == "process-zero-only-io"
    ]
    assert fs == []


def test_project_index_module_names_and_resolution(tmp_path):
    from ncnet_tpu.analysis.engine import (
        ProjectIndex,
        iter_python_files,
        module_name_for_path,
    )

    root = _write_pkg(tmp_path, {"helper.py": HELPER_SYNC})
    sub = root / "pkg" / "sub"
    sub.mkdir()
    (sub / "__init__.py").write_text("")
    (sub / "deep.py").write_text("def leaf():\n    return 1\n")

    assert module_name_for_path(str(root / "pkg" / "helper.py")) == (
        "pkg.helper"
    )
    assert module_name_for_path(str(sub / "deep.py")) == "pkg.sub.deep"

    idx = ProjectIndex.build(iter_python_files([str(root)]))
    assert idx.resolve("pkg.helper.fetch_scalar") is not None
    assert idx.resolve("pkg.sub.deep.leaf") is not None
    assert idx.resolve("pkg.sub.deep.missing") is None
    assert idx.resolve(None) is None


def test_lint_paths_interprocedural_opt_out(tmp_path):
    root = _write_pkg(
        tmp_path, {"helper.py": HELPER_SYNC, "caller.py": CALLER_SYNC}
    )
    fs = [
        f
        for f in lint_paths([str(root)], interprocedural=False)
        if f.rule == "host-sync-in-jit"
    ]
    assert fs == []


# --- unguarded-shared-state (concurrency, fourth audit level) ---------------


GUARDED_CLASS = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def size(self):
        with self._lock:
            return len(self._items)
"""

UNGUARDED_READ = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def size(self):
        return len(self._items)
"""

UNGUARDED_WRITE = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def reset(self):
        self._items = []
"""

SUPPRESSED_READ = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def size(self):
        return len(self._items)  # nclint: disable=unguarded-shared-state -- approximate size is fine for metrics
"""

GUARDED_BY_HELPER = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def put(self, x):
        with self._lock:
            self._flush()

    def _flush(self):  # guarded-by: _lock
        self._items = []
"""

UNKNOWN_GUARDED_BY = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def _flush(self):  # guarded-by: _mutex
        self._items = []
"""

NESTED_DEF_PRUNED = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def worker(self):
        def target():
            return len(self._items)
        return target
"""

MAKE_LOCK_FACTORY = """
from ncnet_tpu.analysis import concurrency

class Box:
    def __init__(self):
        self._lock = concurrency.make_lock("box")
        self._items = []

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def size(self):
        return len(self._items)
"""


def test_unguarded_shared_state_guarded_clean():
    assert findings_for(GUARDED_CLASS, only="unguarded-shared-state") == []


def test_unguarded_shared_state_read_flagged():
    fs = findings_for(UNGUARDED_READ, only="unguarded-shared-state")
    assert len(fs) == 1
    assert "_items" in fs[0].message and "_lock" in fs[0].message
    assert "Box.put" in fs[0].message  # names the write-under-lock witness


def test_unguarded_shared_state_write_flagged():
    fs = findings_for(UNGUARDED_WRITE, only="unguarded-shared-state")
    assert len(fs) == 1
    assert "written" in fs[0].message


def test_unguarded_shared_state_suppressed():
    assert findings_for(SUPPRESSED_READ, only="unguarded-shared-state") == []


def test_unguarded_shared_state_init_exempt():
    # the __init__ writes in every snippet above never flag — one
    # representative direct assertion
    fs = findings_for(GUARDED_CLASS, only="unguarded-shared-state")
    assert fs == []


def test_unguarded_shared_state_guarded_by_annotation():
    assert findings_for(GUARDED_BY_HELPER, only="unguarded-shared-state") == []


def test_unguarded_shared_state_unknown_guarded_by_lock():
    fs = findings_for(UNKNOWN_GUARDED_BY, only="unguarded-shared-state")
    # the bogus annotation is flagged, AND (not binding to any real lock)
    # the method's accesses still count as unguarded
    assert any("_mutex" in f.message for f in fs)
    assert any("written without holding" in f.message for f in fs)


def test_unguarded_shared_state_nested_def_pruned():
    assert findings_for(NESTED_DEF_PRUNED, only="unguarded-shared-state") == []


def test_unguarded_shared_state_make_lock_is_a_lock():
    fs = findings_for(MAKE_LOCK_FACTORY, only="unguarded-shared-state")
    assert len(fs) == 1  # same inference through the audit-lock factory


def test_unguarded_shared_state_test_files_exempt():
    assert findings_for(
        UNGUARDED_READ, path="tests/test_box.py",
        only="unguarded-shared-state",
    ) == []


CONC_HELPER = """
def clear_items(box):
    box._items = []
"""

CONC_CALLER_UNGUARDED = """
import threading

from pkg.helper import clear_items

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def put(self, x):
        with self._lock:
            self._items.append(x)

    def reset(self):
        clear_items(self)
"""

CONC_CALLER_GUARDED = CONC_CALLER_UNGUARDED.replace(
    "    def reset(self):\n        clear_items(self)",
    "    def reset(self):\n        with self._lock:\n"
    "            clear_items(self)",
)


def test_unguarded_shared_state_interprocedural_call_site(tmp_path):
    root = _write_pkg(tmp_path, {
        "helper.py": CONC_HELPER, "caller.py": CONC_CALLER_UNGUARDED,
    })
    fs = [
        f for f in lint_paths([str(root)])
        if f.rule == "unguarded-shared-state"
    ]
    assert len(fs) == 1
    assert fs[0].path.endswith("caller.py")  # flagged AT the call site
    assert "_items" in fs[0].message and "pkg.helper" in fs[0].message


def test_unguarded_shared_state_interprocedural_guarded_clean(tmp_path):
    root = _write_pkg(tmp_path, {
        "helper.py": CONC_HELPER, "caller.py": CONC_CALLER_GUARDED,
    })
    fs = [
        f for f in lint_paths([str(root)])
        if f.rule == "unguarded-shared-state"
    ]
    assert fs == []


# --- lock-order-annotation --------------------------------------------------


TWO_LOCKS_NO_ORDER = """
import threading

class Engine:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
"""

TWO_LOCKS_ORDERED = """
import threading

class Engine:
    def __init__(self):
        # lock-order: _a_lock -> _b_lock
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
"""

TWO_LOCKS_STALE = """
import threading

class Engine:
    def __init__(self):
        # lock-order: _a_lock -> _c_lock
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
"""

ONE_LOCK = """
import threading

class Engine:
    def __init__(self):
        self._a_lock = threading.Lock()
"""


def test_lock_order_annotation_missing():
    fs = findings_for(TWO_LOCKS_NO_ORDER, only="lock-order-annotation")
    assert len(fs) == 1
    assert "_a_lock" in fs[0].message and "_b_lock" in fs[0].message


def test_lock_order_annotation_present():
    assert findings_for(TWO_LOCKS_ORDERED, only="lock-order-annotation") == []


def test_lock_order_annotation_stale():
    fs = findings_for(TWO_LOCKS_STALE, only="lock-order-annotation")
    assert len(fs) == 1
    assert "stale" in fs[0].message


def test_lock_order_annotation_single_lock_exempt():
    assert findings_for(ONE_LOCK, only="lock-order-annotation") == []


# --- unjoined-thread --------------------------------------------------------


UNJOINED = """
import threading

def spawn(fn):
    t = threading.Thread(target=fn)
    t.start()
    return t
"""

UNJOINED_CHAINED = """
import threading

def spawn(fn):
    threading.Thread(target=fn).start()
"""

DAEMON_OK = """
import threading

def spawn(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t
"""

JOINED_OK = """
import threading

def run(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()
"""

CLASS_LEDGER_OK = """
import threading

class Pool:
    def start(self, fn):
        self._t = threading.Thread(target=fn)
        self._t.start()

    def stop(self):
        self._t.join()
"""


def test_unjoined_thread_flagged():
    fs = findings_for(UNJOINED, only="unjoined-thread")
    assert len(fs) == 1
    assert "spawn" in fs[0].message


def test_unjoined_thread_chained_start_flagged():
    fs = findings_for(UNJOINED_CHAINED, only="unjoined-thread")
    assert len(fs) == 1


def test_unjoined_thread_daemon_exempt():
    assert findings_for(DAEMON_OK, only="unjoined-thread") == []


def test_unjoined_thread_join_in_scope():
    assert findings_for(JOINED_OK, only="unjoined-thread") == []


def test_unjoined_thread_class_scope_join():
    # start in one method, join in another: the class is the scope
    assert findings_for(CLASS_LEDGER_OK, only="unjoined-thread") == []


def test_unjoined_thread_test_files_exempt():
    assert findings_for(
        UNJOINED, path="tests/test_pool.py", only="unjoined-thread"
    ) == []
