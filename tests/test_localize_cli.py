"""End-to-end test of scripts/localize_inloc.py on synthetic fixtures,
including the persisted eval artifacts (per-query error file + rate-curve
figure — the reference's ht_plotcurve_WUSTL.m deliverables)."""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

pytest.importorskip("scipy")
pytest.importorskip("PIL")

REPO = Path(__file__).resolve().parent.parent


def _rot(rng):
    Q, _ = np.linalg.qr(rng.randn(3, 3))
    if np.linalg.det(Q) < 0:
        Q[:, 0] *= -1
    return Q


def test_localize_cli_writes_json_errors_and_curve(tmp_path):
    from PIL import Image
    from scipy.io import savemat

    rng = np.random.RandomState(7)
    dh, dw = 60, 80
    qh, qw = 48, 64
    fl = 50.0

    # synthetic RGBD cutout surface in GLOBAL coords (no alignment file)
    gy, gx = np.mgrid[0:dh, 0:dw]
    xyz = np.stack(
        [gx * 0.05, gy * 0.05, 3.0 + 0.3 * np.sin(gx * 0.1)], axis=-1
    )
    # ground-truth query pose
    R = _rot(rng)
    t = rng.randn(3) * 0.1 + np.array([1.5, 1.0, 1.0])
    P_gt = np.concatenate([R, t[:, None]], axis=1)

    n = 120
    px = rng.randint(1, dw + 1, n)
    py = rng.randint(1, dh + 1, n)
    X = xyz[py - 1, px - 1]
    Xc = X @ R.T + t
    xq = Xc[:, 0] / Xc[:, 2] * fl + qw / 2.0
    yq = Xc[:, 1] / Xc[:, 2] * fl + qh / 2.0
    matches_rows = np.stack(
        [xq / qw, yq / qh, (px + 0.5) / dw, (py + 0.5) / dh, np.full(n, 0.9)],
        axis=1,
    )

    # fixture layout
    (tmp_path / "query").mkdir()
    Image.fromarray(rng.randint(0, 255, (qh, qw, 3), np.uint8)).save(
        tmp_path / "query" / "q0.png"
    )
    cutdir = tmp_path / "cutouts" / "DUC1"
    cutdir.mkdir(parents=True)
    savemat(cutdir / "p0.jpg.mat", {"XYZcut": xyz})
    mdir = tmp_path / "matches"
    mdir.mkdir()
    savemat(mdir / "1.mat", {"matches": matches_rows[None, None]})

    dt = np.dtype([("queryname", object), ("topN", object)])
    entry = np.zeros((1, 1), dt)
    entry[0, 0] = (
        np.array(["q0.png"], object),
        np.array([["DUC1/p0.jpg"]], object),
    )
    savemat(tmp_path / "shortlist.mat", {"ImgList": entry})

    ref_dt = np.dtype([("queryname", object), ("P", object)])
    duc1 = np.zeros((1, 1), ref_dt)
    duc1[0, 0] = (np.array(["q0.png"], object), P_gt)
    duc2 = np.zeros((1, 1), ref_dt)
    duc2[0, 0] = (  # a query with no result -> inf errors path
        np.array(["missing.png"], object),
        np.concatenate([np.eye(3), np.zeros((3, 1))], axis=1),
    )
    savemat(
        tmp_path / "refposes.mat",
        {"DUC1_RefList": duc1, "DUC2_RefList": duc2},
    )

    out_json = tmp_path / "localization.json"
    proc = subprocess.run(
        [
            sys.executable,
            str(REPO / "scripts" / "localize_inloc.py"),
            "--matches_dir", str(mdir),
            "--shortlist", str(tmp_path / "shortlist.mat"),
            "--cutout_dir", str(tmp_path / "cutouts"),
            "--query_dir", str(tmp_path / "query"),
            "--focal", str(fl),
            "--n_queries", "1",
            "--n_panos", "1",
            "--refposes", str(tmp_path / "refposes.mat"),
            "--out", str(out_json),
            "--method", "testm",
            # exercise the multiprocess-PnP parfor analog; the pool uses
            # the 'spawn' context (fork after jax import can deadlock)
            "--workers", "2",
        ],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr

    results = json.loads(out_json.read_text())
    assert results[0]["queryname"] == "q0.png"
    assert results[0]["P"][0] is not None

    err_lines = (tmp_path / "error_testm.txt").read_text().splitlines()
    assert len(err_lines) == 2
    q0 = err_lines[0].split()
    assert q0[0] == "q0.png"
    assert float(q0[1]) < 0.05  # position error, meters
    assert float(q0[2]) < 1.0  # orientation error, degrees
    missing = err_lines[1].split()
    assert missing[0] == "missing.png"
    assert missing[1] == "inf"

    curve = tmp_path / "curve_testm.png"
    assert curve.exists() and curve.stat().st_size > 1000
