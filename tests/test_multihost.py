"""Real 2-process jax.distributed cluster test (SURVEY.md §2.3).

Round-4 gap: `initialize_multihost`'s real branch (jax.distributed init +
per-process global-array assembly in `shard_batch`) only ever ran as a
single-process no-op; the 8-device dryrun lives in ONE process. Here the
multi-host path actually executes: two child interpreters (the
`__graft_entry__.py` child-env technique) each with 2 virtual CPU devices
join a coordinator, build the hybrid DCN-aware mesh, assemble the global
batch from process-local slices with `jax.make_array_from_process_local_data`,
and run one data-parallel train step. Both processes must agree on the
psum-reduced loss, and it must match a single-process run of the same
global batch on a 4-device mesh computed in the parent (this suite's
conftest already forces the CPU backend, so the parent is safe to compute
the oracle in-process).
"""

import os
import re
import sys

import numpy as np
import pytest

if __name__ != "__main__":  # children must not import pytest plugins
    from conftest import multiprocess_cpu_supported, spawn_cpu_cluster

    # Collection-time capability gate: a jaxlib without gloo CPU
    # collectives CANNOT run cross-process CPU computations at all —
    # skip (with the reason) instead of failing inside the children.
    pytestmark = pytest.mark.skipif(
        not multiprocess_cpu_supported(),
        reason="this jaxlib lacks multiprocess CPU collectives "
        "(no gloo implementation to back jax.distributed on CPU)",
    )

GRID_DEVICES = 4  # 2 processes x 2 local devices
LOCAL_DEVICES = 2
IMAGE = 32

_LOSS_RE = re.compile(r"MHLOSS (\S+) procs=(\d+) devices=(\d+)")


def _global_batch():
    rng = np.random.RandomState(7)
    return {
        "source_image": rng.randn(GRID_DEVICES, IMAGE, IMAGE, 3).astype(
            np.float32
        ),
        "target_image": rng.randn(GRID_DEVICES, IMAGE, IMAGE, 3).astype(
            np.float32
        ),
    }


def _config():
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig

    return ImMatchNetConfig(ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1))


def _child_main():
    """Runs inside each spawned process; prints the step loss."""
    import jax

    # Same load-bearing guard as __graft_entry__: the JAX_PLATFORMS env var
    # is ignored when this image's TPU plugin is present.
    jax.config.update("jax_platforms", "cpu")

    coordinator = os.environ["_NCNET_MH_COORD"]
    pid = int(os.environ["_NCNET_MH_PID"])

    from ncnet_tpu.models.immatchnet import init_immatchnet
    from ncnet_tpu.parallel.mesh import (
        initialize_multihost,
        make_hybrid_mesh,
        replicate,
        shard_batch,
    )
    from ncnet_tpu.train.step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    process_index, process_count = initialize_multihost(
        coordinator_address=coordinator, num_processes=2, process_id=pid
    )
    assert (process_index, process_count) == (pid, 2), (
        process_index,
        process_count,
    )
    assert jax.device_count() == GRID_DEVICES
    assert jax.local_device_count() == LOCAL_DEVICES

    mesh = make_hybrid_mesh()
    assert mesh.shape == {"data": GRID_DEVICES}

    config = _config()
    params = init_immatchnet(jax.random.PRNGKey(0), config)
    optimizer = make_optimizer()
    state = create_train_state(replicate(mesh, params), optimizer)
    state = state._replace(opt_state=replicate(mesh, state.opt_state))

    # Each process feeds ONLY its host-local slice of the global batch —
    # the multi-host contract of shard_batch. The hybrid mesh maps the
    # leading axis across processes in process order.
    full = _global_batch()
    lo, hi = pid * LOCAL_DEVICES, (pid + 1) * LOCAL_DEVICES
    local = {k: v[lo:hi] for k, v in full.items()}
    batch = shard_batch(mesh, local)

    step = make_train_step(config, optimizer, donate=False)
    new_state, loss = step(state, batch)
    jax.block_until_ready(loss)
    assert int(new_state.step) == 1
    print(
        f"MHLOSS {float(loss):.10e} procs={jax.process_count()} "
        f"devices={jax.device_count()}",
        flush=True,
    )


def test_two_process_cluster_matches_single_process(multihost_oracle_loss):
    results = spawn_cpu_cluster(
        os.path.abspath(__file__),
        n_procs=2,
        local_devices=LOCAL_DEVICES,
        timeout=280,
    )
    outs = []
    for code, out in results:
        outs.append(out)
        assert code == 0, f"multihost child failed:\n{out}"

    losses = []
    for out in outs:
        m = _LOSS_RE.search(out)
        assert m, f"no MHLOSS line in child output:\n{out}"
        assert (int(m.group(2)), int(m.group(3))) == (2, GRID_DEVICES)
        losses.append(float(m.group(1)))
    # the loss is psum-reduced and replicated: both processes see the same
    assert losses[0] == losses[1], losses

    # single-process oracle on a 4-device mesh over the same global
    # batch: the session-shared fixture (tests/conftest.py, the tier-1
    # budget lever) — its pinned config/seeds mirror _config() /
    # _global_batch() above, and this allclose fails loudly on drift.
    # Random-init loss is ~1e-6 (score_neg - score_pos near zero), so the
    # comparison needs an absolute floor: cross-process psum vs
    # in-process reduction order differ by O(1 ulp) = ~3e-8 here
    np.testing.assert_allclose(
        losses[0], multihost_oracle_loss, rtol=1e-5, atol=1e-6
    )


if __name__ == "__main__":
    # `python tests/test_multihost.py` puts tests/ (not the repo root) at
    # sys.path[0]; the child needs the ncnet_tpu package importable
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    _child_main()
