import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
from ncnet_tpu.models.neigh_consensus import neigh_consensus_apply
from ncnet_tpu.models.resnet import RESNET101_STAGES, EXPANSION
from ncnet_tpu.utils import convert_torch


def _fake_resnet_state_dict(prefix="FeatureExtraction.model."):
    """Synthetic state dict with torchvision Sequential-index naming and
    correct shapes (what the reference checkpoints contain)."""
    g = torch.Generator().manual_seed(0)
    sd = {}

    def conv(name, cout, cin, k):
        sd[name + ".weight"] = torch.randn(cout, cin, k, k, generator=g)

    def bn(name, c):
        sd[name + ".weight"] = torch.randn(c, generator=g)
        sd[name + ".bias"] = torch.randn(c, generator=g)
        sd[name + ".running_mean"] = torch.randn(c, generator=g)
        sd[name + ".running_var"] = torch.rand(c, generator=g) + 0.5
        sd[name + ".num_batches_tracked"] = torch.tensor(0)

    conv(prefix + "0", 64, 3, 7)
    bn(prefix + "1", 64)
    cin = 64
    for si, (n_blocks, planes, _) in enumerate(RESNET101_STAGES):
        seq_idx = 4 + si
        for bi in range(n_blocks):
            p = f"{prefix}{seq_idx}.{bi}."
            conv(p + "conv1", planes, cin, 1)
            bn(p + "bn1", planes)
            conv(p + "conv2", planes, planes, 3)
            bn(p + "bn2", planes)
            conv(p + "conv3", planes * EXPANSION, planes, 1)
            bn(p + "bn3", planes * EXPANSION)
            if bi == 0:
                conv(p + "downsample.0", planes * EXPANSION, cin, 1)
                bn(p + "downsample.1", planes * EXPANSION)
            cin = planes * EXPANSION
    return sd


def _fake_vgg_state_dict(prefix="FeatureExtraction.model."):
    """Reference-style vgg checkpoint keys: torchvision ``features``
    Sequential indices under ``FeatureExtraction.model.`` (conv layers at
    0,2,5,7,... with ReLU/pool gaps — lib/model.py:24-35)."""
    from ncnet_tpu.models.vgg import VGG16_TO_POOL4

    g = torch.Generator().manual_seed(4)
    sd = {}
    cin, idx = 3, 0
    for c in VGG16_TO_POOL4:
        if c == "M":
            idx += 1  # pool occupies one Sequential slot
            continue
        sd[f"{prefix}{idx}.weight"] = torch.randn(c, cin, 3, 3, generator=g)
        sd[f"{prefix}{idx}.bias"] = torch.randn(c, generator=g)
        cin = c
        idx += 2  # conv + its ReLU
    return sd


def test_vgg_checkpoint_conversion(tmp_path):
    """Reference-schema vgg .pth.tar: the converter must read the arch from
    the embedded args, map the Sequential-index keys in order, and produce
    a tree identical in structure to init_vgg16_trunk."""
    import argparse

    from ncnet_tpu.models.vgg import init_vgg16_trunk, vgg16_trunk_apply

    sd = _fake_vgg_state_dict()
    g = torch.Generator().manual_seed(5)
    w0 = torch.randn(16, 1, 3, 3, 3, 3, generator=g).permute(2, 0, 1, 3, 4, 5)
    w1 = torch.randn(1, 16, 3, 3, 3, 3, generator=g).permute(2, 0, 1, 3, 4, 5)
    sd["NeighConsensus.conv.0.weight"] = w0.contiguous()
    sd["NeighConsensus.conv.0.bias"] = torch.randn(16, generator=g)
    sd["NeighConsensus.conv.2.weight"] = w1.contiguous()
    sd["NeighConsensus.conv.2.bias"] = torch.randn(1, generator=g)

    args = argparse.Namespace(
        ncons_kernel_sizes=[3, 3],
        ncons_channels=[16, 1],
        feature_extraction_cnn="vgg",
    )
    path = str(tmp_path / "ref_vgg.pth.tar")
    torch.save({"state_dict": sd, "args": args, "epoch": 5}, path)

    config, params = convert_torch.convert_checkpoint(path)
    assert config.feature_extraction_cnn == "vgg"
    ref = init_vgg16_trunk(jax.random.PRNGKey(0))
    ref_flat, ref_tree = jax.tree.flatten(ref)
    got_flat, got_tree = jax.tree.flatten(params["feature_extraction"])
    assert ref_tree == got_tree
    for a, b in zip(ref_flat, got_flat):
        assert np.shape(a) == np.shape(b)
    # converted weights must match the source values layer-by-layer, in
    # features order (sorted numerically, not lexically: index 10 > 2)
    np.testing.assert_allclose(
        np.asarray(params["feature_extraction"][2]["kernel"]),
        sd["FeatureExtraction.model.5.weight"].numpy().transpose(2, 3, 1, 0),
    )
    out = vgg16_trunk_apply(
        [{k: jnp.asarray(v) for k, v in p.items()} for p in params["feature_extraction"]],
        jnp.zeros((1, 32, 32, 3), jnp.float32),
    )
    assert out.shape == (1, 2, 2, 512)


def test_load_trunk_weights_vgg_raw_torchvision(tmp_path):
    """A raw torchvision vgg16 state dict (``features.N.weight`` keys, as
    downloaded from the zoo) loads through load_trunk_weights."""
    sd = _fake_vgg_state_dict(prefix="features.")
    path = str(tmp_path / "vgg16_zoo.pth")
    torch.save(sd, path)
    params = convert_torch.load_trunk_weights(path, cnn="vgg")
    assert len(params) == 10
    assert params[0]["kernel"].shape == (3, 3, 3, 64)
    assert params[-1]["kernel"].shape == (3, 3, 512, 512)


def test_resnet_conversion_structure_matches_init():
    sd = _fake_resnet_state_dict()
    converted = convert_torch.convert_resnet101_trunk(sd)
    ref = init_immatchnet(
        jax.random.PRNGKey(0), ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
    )["feature_extraction"]
    ref_flat, ref_tree = jax.tree.flatten(ref)
    got_flat, got_tree = jax.tree.flatten(converted)
    assert ref_tree == got_tree
    for a, b in zip(ref_flat, got_flat):
        assert np.shape(a) == np.shape(b)


def test_conv4d_weight_conversion_semantics():
    """A reference-style pre-permuted Conv4d weight must convert to a kernel
    that makes our conv4d agree with torch's conv3d tap decomposition."""
    import torch.nn.functional as F

    g = torch.Generator().manual_seed(1)
    k, cin, cout = 3, 1, 2
    w_native = torch.randn(cout, cin, k, k, k, k, generator=g)  # torch layout
    bias = torch.randn(cout, generator=g)
    # the reference stores weights permuted: (2,0,1,3,4,5) (lib/conv4d.py:72-77)
    w_stored = w_native.permute(2, 0, 1, 3, 4, 5).contiguous()
    sd = {"NeighConsensus.conv.0.weight": w_stored, "NeighConsensus.conv.0.bias": bias}
    params = convert_torch.convert_neigh_consensus(sd)

    x = torch.randn(1, cin, 4, 4, 4, 4, generator=g)
    pad = k // 2
    xpad = F.pad(x, (0, 0, 0, 0, 0, 0, pad, pad))
    want = torch.zeros(1, cout, 4, 4, 4, 4)
    for i in range(4):
        for p in range(k):
            want[:, :, i] += F.conv3d(
                xpad[:, :, i + p],
                w_native[:, :, p],
                bias=bias if p == pad else None,
                padding=pad,
            )
    want_np = want.numpy().transpose(0, 2, 3, 4, 5, 1)[..., :]

    from ncnet_tpu.ops.conv4d import conv4d

    x_jax = jnp.asarray(x.numpy().transpose(0, 2, 3, 4, 5, 1))
    got = conv4d(x_jax, jnp.asarray(params[0]["kernel"]), jnp.asarray(params[0]["bias"]))
    np.testing.assert_allclose(np.asarray(got), want_np, rtol=1e-4, atol=1e-4)


def test_full_checkpoint_conversion(tmp_path):
    """Round-trip a reference-schema .pth.tar through convert_checkpoint."""
    import argparse

    sd = _fake_resnet_state_dict()
    g = torch.Generator().manual_seed(2)
    # NeighConsensus.conv indices 0, 2 (ReLUs at odd indices), kernels 3-3, ch 16-1
    w0 = torch.randn(16, 1, 3, 3, 3, 3, generator=g).permute(2, 0, 1, 3, 4, 5)
    w1 = torch.randn(1, 16, 3, 3, 3, 3, generator=g).permute(2, 0, 1, 3, 4, 5)
    sd["NeighConsensus.conv.0.weight"] = w0.contiguous()
    sd["NeighConsensus.conv.0.bias"] = torch.randn(16, generator=g)
    sd["NeighConsensus.conv.2.weight"] = w1.contiguous()
    sd["NeighConsensus.conv.2.bias"] = torch.randn(1, generator=g)

    args = argparse.Namespace(
        ncons_kernel_sizes=[3, 3], ncons_channels=[16, 1], fe_arch="resnet101"
    )
    ckpt = {"state_dict": sd, "args": args, "epoch": 5}
    path = str(tmp_path / "ref.pth.tar")
    torch.save(ckpt, path)

    config, params = convert_torch.convert_checkpoint(path)
    assert config.ncons_kernel_sizes == (3, 3)
    assert config.ncons_channels == (16, 1)
    assert params["neigh_consensus"][0]["kernel"].shape == (3, 3, 3, 3, 1, 16)
    assert params["neigh_consensus"][1]["kernel"].shape == (3, 3, 3, 3, 16, 1)
    # converted params must run through the NC stack
    corr = jnp.asarray(np.random.RandomState(0).randn(1, 4, 4, 4, 4).astype(np.float32))
    out = neigh_consensus_apply(
        [
            {k: jnp.asarray(v) for k, v in layer.items()}
            for layer in params["neigh_consensus"]
        ],
        corr,
    )
    assert out.shape == corr.shape
