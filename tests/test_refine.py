"""Coarse-to-fine refinement (ncnet_tpu.refine) + its serving tier.

The design contract under test: with ``refine_factor == 1`` and
``refine_radius == 0`` the pool is an identity and every re-scoring
window holds exactly its own candidate, so the refined band must equal
the plain sparse band BITWISE in eager mode — and chained with the
band's own ``K = hB*wB`` completeness contract (tests/test_sparse.py)
the whole ladder reduces to the dense pipeline. That anchor is what the
genuinely multi-resolution cases (factor 2 geometry, jit parity, the
padding independence, the served quality-ladder flip at zero recompiles,
and the analytic FLOP ledger) ride on.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import (
    ImMatchNetConfig,
    init_immatchnet,
    match_pipeline,
)
from ncnet_tpu.refine import (
    check_refine_config,
    pool_features,
    refine_match_pipeline,
    refine_rescore,
    refine_window_indices,
)
from ncnet_tpu.sparse.pipeline import sparse_match_pipeline

BASE = dict(ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1))
#: the band's bitwise dense anchor (tests/test_sparse.py): conv lowering
#: + bias placement mirror the band GEMMs term-for-term
DENSE_MIRROR = ImMatchNetConfig(
    conv4d_impl="gemm4/gemm4", symmetric_batch=False, **BASE
)


def _feats(rng, b, h, w, c=7):
    return (
        jnp.asarray(rng.randn(b, h, w, c).astype(np.float32)),
        jnp.asarray(rng.randn(b, h, w, c).astype(np.float32)),
    )


# --- pooling -----------------------------------------------------------------


def test_pool_factor1_is_identity_object():
    """factor 1 must return the INPUT, not a renormalized copy — the
    r==1 rung is the bitwise exactness anchor, and re-dividing by a
    computed ~1.0 norm would perturb the last bit."""
    x = jnp.asarray(np.random.RandomState(0).randn(1, 4, 4, 3), jnp.float32)
    assert pool_features(x, 1) is x


def test_pool_factor2_mean_then_renorm():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 6, 5).astype(np.float32)
    got = np.asarray(pool_features(jnp.asarray(x), 2))
    assert got.shape == (2, 2, 3, 5)
    want = x.reshape(2, 2, 2, 3, 2, 5).mean(axis=(2, 4))
    want /= np.sqrt((want**2).sum(-1, keepdims=True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        (got**2).sum(-1), np.ones((2, 2, 3)), rtol=1e-5
    )
    raw = np.asarray(pool_features(jnp.asarray(x), 2, normalize=False))
    np.testing.assert_allclose(
        raw, x.reshape(2, 2, 2, 3, 2, 5).mean(axis=(2, 4)), rtol=1e-6
    )


def test_pool_rejects_nondividing_grid():
    x = jnp.zeros((1, 5, 4, 3), jnp.float32)
    with pytest.raises(ValueError, match="does not divide"):
        pool_features(x, 2)
    with pytest.raises(ValueError, match=">= 1"):
        pool_features(x, 0)


# --- window pointer table ----------------------------------------------------


def test_refine_window_indices_numpy_golden():
    """factor 2, radius 1 on a 2x3 coarse grid: every pointer checked
    against the brute-force fine-cell enumeration, off-grid slots must
    hold the null index (fine-grid size) with valid=False."""
    h_lo, w_lo, r, radius = 2, 3, 2, 1
    h_hi, w_hi = h_lo * r, w_lo * r
    rng = np.random.RandomState(2)
    idx = rng.randint(0, h_lo * w_lo, size=(1, 2, 2, 3)).astype(np.int32)
    widx, valid = refine_window_indices(
        jnp.asarray(idx), (h_lo, w_lo), (h_hi, w_hi), r, radius
    )
    side = r * (2 * radius + 1)
    assert widx.shape == (1, 2, 2, 3, side * side)
    widx, valid = np.asarray(widx), np.asarray(valid)
    null = h_hi * w_hi
    for a1 in range(2):
        for a2 in range(2):
            for k in range(3):
                pi, pj = divmod(int(idx[0, a1, a2, k]), w_lo)
                for u in range(side):
                    for v in range(side):
                        fi = pi * r + u - radius * r
                        fj = pj * r + v - radius * r
                        t = u * side + v
                        on = 0 <= fi < h_hi and 0 <= fj < w_hi
                        assert valid[0, a1, a2, k, t] == on
                        want = fi * w_hi + fj if on else null
                        assert widx[0, a1, a2, k, t] == want


def test_refine_window_indices_rejects_grid_mismatch():
    with pytest.raises(ValueError, match="not the coarse grid"):
        refine_window_indices(
            jnp.zeros((1, 2, 2, 1), jnp.int32), (2, 2), (5, 4), 2
        )


# --- the exactness contract --------------------------------------------------


def test_refined_equals_band_bitwise_eager():
    """factor 1 + radius 0: single-entry windows, softmax gain exactly
    1.0 — refined values AND indices bitwise the plain band's."""
    rng = np.random.RandomState(3)
    fa, fb = _feats(rng, 2, 4, 4)
    cfg = ImMatchNetConfig(**BASE)
    params = init_immatchnet(jax.random.PRNGKey(3), cfg)
    nc = params["neigh_consensus"]
    k = 5
    vb, ib, gb = sparse_match_pipeline(
        nc, cfg.replace(nc_topk=k), fa, fb
    )
    vr, ir, gr = refine_match_pipeline(
        nc, cfg.replace(refine_factor=1, refine_topk=k), fa, fb
    )
    assert gr == gb
    np.testing.assert_array_equal(np.asarray(ir), np.asarray(ib))
    np.testing.assert_array_equal(np.asarray(vr), np.asarray(vb))


def test_refined_full_k_matches_dense_bitwise_eager():
    """The chained anchor: factor 1 at the COMPLETE band width reduces
    the whole coarse-to-fine ladder to the dense pipeline, bitwise
    against the gemm-mirror dense lowering."""
    rng = np.random.RandomState(4)
    fa, fb = _feats(rng, 2, 4, 4)
    params = init_immatchnet(jax.random.PRNGKey(4), DENSE_MIRROR)
    nc = params["neigh_consensus"]
    out_d = np.asarray(match_pipeline(nc, DENSE_MIRROR, fa, fb))
    out_r = np.asarray(
        match_pipeline(
            nc,
            DENSE_MIRROR.replace(refine_factor=1, refine_topk=16),
            fa, fb,
        )
    )
    np.testing.assert_array_equal(out_r, out_d)


def test_refined_jit_matches_eager():
    rng = np.random.RandomState(5)
    fa, fb = _feats(rng, 2, 4, 4)
    cfg = ImMatchNetConfig(
        refine_factor=2, refine_topk=3, refine_radius=1, **BASE
    )
    params = init_immatchnet(jax.random.PRNGKey(5), cfg)
    nc = params["neigh_consensus"]
    ve, ie, ge = refine_match_pipeline(nc, cfg, fa, fb)
    vj, ij, gj = jax.jit(
        lambda p, a, b: refine_match_pipeline(p, cfg, a, b)
    )(nc, fa, fb)
    assert gj == ge
    np.testing.assert_array_equal(np.asarray(ij), np.asarray(ie))
    np.testing.assert_allclose(
        np.asarray(vj), np.asarray(ve), rtol=1e-6, atol=1e-7
    )


def test_refined_factor2_geometry_and_window_containment():
    """factor 2 on a 6x6 fine grid: the refined band lives on the FINE
    grids, every relocated index is on-grid, and each one lies inside
    its own coarse candidate's window (the gather can only choose among
    the cells the pointer table enumerates)."""
    rng = np.random.RandomState(6)
    fa, fb = _feats(rng, 1, 6, 6)
    cfg = ImMatchNetConfig(refine_factor=2, refine_topk=4, **BASE)
    params = init_immatchnet(jax.random.PRNGKey(6), cfg)
    nc = params["neigh_consensus"]
    # the coarse band the refinement consumed, recomputed for reference
    cv, ci, (h_lo, w_lo) = sparse_match_pipeline(
        nc, cfg.replace(refine_factor=0, nc_topk=4),
        pool_features(fa, 2), pool_features(fb, 2),
    )
    vals, idx, (h_hi, w_hi) = refine_rescore(cv, ci, (h_lo, w_lo), fa, fb, 2)
    assert (h_hi, w_hi) == (6, 6)
    assert vals.shape == idx.shape == (1, 6, 6, 4)
    idx, ci = np.asarray(idx), np.asarray(ci)
    assert idx.min() >= 0 and idx.max() < h_hi * w_hi
    for ai in range(6):
        for aj in range(6):
            for k in range(4):
                pi, pj = divmod(int(ci[0, ai // 2, aj // 2, k]), w_lo)
                fi, fj = divmod(int(idx[0, ai, aj, k]), w_hi)
                assert pi * 2 <= fi < (pi + 1) * 2
                assert pj * 2 <= fj < (pj + 1) * 2


def test_batch_rows_independent_of_batchmates():
    """The padding contract's function-level core: a pair's refined band
    does not depend on what else rides in the batch (the serve engine
    pads batches with row duplicates)."""
    rng = np.random.RandomState(7)
    fa, fb = _feats(rng, 2, 4, 4)
    cfg = ImMatchNetConfig(refine_factor=2, refine_topk=3, **BASE)
    params = init_immatchnet(jax.random.PRNGKey(7), cfg)
    nc = params["neigh_consensus"]
    v2, i2, _ = refine_match_pipeline(nc, cfg, fa, fb)
    v1, i1, _ = refine_match_pipeline(nc, cfg, fa[:1], fb[:1])
    np.testing.assert_array_equal(np.asarray(i2)[:1], np.asarray(i1))
    np.testing.assert_allclose(
        np.asarray(v2)[:1], np.asarray(v1), rtol=1e-6, atol=1e-7
    )


# --- config plumbing ---------------------------------------------------------


def test_check_refine_config_validation():
    check_refine_config(ImMatchNetConfig(refine_factor=0))
    check_refine_config(ImMatchNetConfig(refine_factor=2, refine_topk=8))
    with pytest.raises(ValueError, match="negative"):
        check_refine_config(ImMatchNetConfig(refine_factor=-1))
    with pytest.raises(ValueError, match="band width"):
        check_refine_config(
            ImMatchNetConfig(refine_factor=2, refine_topk=0)
        )
    with pytest.raises(ValueError, match="negative"):
        check_refine_config(
            ImMatchNetConfig(refine_factor=2, refine_radius=-1)
        )
    with pytest.raises(ValueError, match="relocalization"):
        check_refine_config(
            ImMatchNetConfig(refine_factor=2, relocalization_k_size=2)
        )


def test_config_roundtrip_and_legacy_dicts():
    cfg = ImMatchNetConfig(refine_factor=4, refine_topk=8, refine_radius=1)
    again = ImMatchNetConfig.from_dict(cfg.to_dict())
    assert (again.refine_factor, again.refine_topk, again.refine_radius) \
        == (4, 8, 1)
    # checkpoints written before the refine path have no refine keys
    legacy = cfg.to_dict()
    for key in ("refine_factor", "refine_topk", "refine_radius"):
        del legacy[key]
    old = ImMatchNetConfig.from_dict(legacy)
    assert (old.refine_factor, old.refine_topk, old.refine_radius) \
        == (0, 16, 0)


# --- the quality ladder ------------------------------------------------------


def test_quality_ladder_walks_one_rung_per_flip():
    from ncnet_tpu.serve.resilience import QualityLadder

    lad = QualityLadder(up_count=2, down_count=2)
    assert lad.variant == "standard" and not lad.degraded
    # sustained pressure climbs ONE rung toward cheaper per flip
    lad.update(0.9)
    assert lad.update(0.9) == "degraded" and lad.flips == 1
    assert lad.degraded
    # a recovering queue re-earns each level one flip at a time
    lad.update(0.1)
    assert lad.update(0.1) == "standard" and lad.flips == 2
    lad.update(0.1)
    assert lad.update(0.1) == "refined" and lad.flips == 3
    assert not lad.degraded  # 'refined' is a NAMED rung, not a mode bit
    # dead-band readings reset both streaks
    lad2 = QualityLadder(up_count=2, down_count=2)
    lad2.update(0.9)
    lad2.update(0.5)
    lad2.update(0.9)
    assert lad2.variant == "standard" and lad2.flips == 0


def test_quality_ladder_validation():
    from ncnet_tpu.serve.resilience import QualityLadder

    with pytest.raises(ValueError, match=">= 2 rungs"):
        QualityLadder(rungs=("standard",))
    with pytest.raises(ValueError, match="duplicate"):
        QualityLadder(rungs=("standard", "standard"))
    with pytest.raises(ValueError, match="start rung"):
        QualityLadder(rungs=("refined", "standard"), start="degraded")
    two = QualityLadder(rungs=("refined", "standard"), start="standard")
    assert not two.degraded  # this ladder has no degraded rung to report


def test_serve_refined_tier_flip_zero_recompiles():
    """The served quality ladder: three program families pre-warmed per
    (bucket, batch size); pinning the controller to each rung dispatches
    that rung's program (results prove which one ran) with ZERO traces
    after warmup — a tier flip never compiles. The controller is pinned
    because the engine's dispatch thread calls update() on every loop
    iteration with live queue pressure, racing any scripted sequence."""
    from ncnet_tpu.serve import ServeEngine, payload_spec
    from ncnet_tpu.serve.resilience import QualityLadder

    params = {"w": jnp.asarray(3.0, jnp.float32)}

    class Pinned(QualityLadder):
        def update(self, pressure):
            self.last_pressure = float(pressure)
            return self.variant

        def pin(self, variant):
            self._i = self.rungs.index(variant)

    lad = Pinned()

    def mk(mult):
        def apply(p, batch):
            return {"y": batch["x"] * p["w"] * mult}
        return apply

    with ServeEngine(
        mk(1.0), params,
        max_batch=2, max_wait=0.005, batch_sizes=(1, 2),
        degraded_apply_fn=mk(-1.0),
        refined_apply_fn=mk(10.0),
        quality_controller=lad,
    ) as eng:
        eng.warmup(
            [("A", payload_spec({"x": np.ones((3,), np.float32)}))]
        )
        warm_traces = eng.compile_count
        for variant, mult in (
            ("standard", 3.0), ("refined", 30.0), ("degraded", -3.0),
            ("refined", 30.0),
        ):
            lad.pin(variant)
            fut = eng.submit(
                key="A", payload={"x": np.full((3,), 2.0, np.float32)}
            )
            np.testing.assert_array_equal(
                fut.result(timeout=60)["y"],
                np.full((3,), 2.0 * mult, np.float32),
            )
        stats = eng.report()
    assert eng.compile_count == warm_traces  # nothing retraced on flips
    assert stats["recompiles_after_warmup"] == 0
    assert stats["refined_batches"] >= 2
    assert stats["degraded_batches"] >= 1
    assert stats["quality_variant"] == "refined"


# --- analytic FLOP ledger ----------------------------------------------------


def test_refine_flop_closed_forms():
    from ncnet_tpu.ops.accounting import (
        refine_match_flops,
        refine_rescore_flops,
        refine_window,
        train_step_flops_for_batch,
    )

    assert refine_window(2) == 4
    assert refine_window(2, radius=1) == 36
    assert refine_rescore_flops(
        batch=1, grid_hi=4, nc_topk=3, window=4, feat_ch=8
    ) == 2.0 * 16 * 3 * 4 * 8
    # K clamps to the coarse grid's nB: factor 2 on grid 4 -> nB_lo = 4
    clamped = refine_match_flops(
        1, (3,), (1,), grid_hi=4, factor=2, nc_topk=999, feat_ch=8,
        from_features=True,
    )
    exact = refine_match_flops(
        1, (3,), (1,), grid_hi=4, factor=2, nc_topk=4, feat_ch=8,
        from_features=True,
    )
    assert clamped == exact
    with pytest.raises(ValueError, match="divide"):
        refine_match_flops(
            1, (3,), (1,), grid_hi=5, factor=2, nc_topk=4, feat_ch=8
        )
    # the train-step dispatcher routes refined configs to the refine form
    cfg = ImMatchNetConfig(
        feature_extraction_cnn="patch16", ncons_kernel_sizes=(3,),
        ncons_channels=(1,), refine_factor=2, refine_topk=4,
    )
    refined = train_step_flops_for_batch(
        cfg, batch={"source_image": np.zeros((2, 64, 64, 3))},
        from_features=False,
    )
    dense = train_step_flops_for_batch(
        cfg.replace(refine_factor=0),
        batch={"source_image": np.zeros((2, 64, 64, 3))},
        from_features=False,
    )
    assert refined != dense and refined > 0


def test_refine_audit_programs_clean_and_walk_exact():
    """The auditor's FLOP walk over the REAL refined programs agrees
    with the closed form to round-off — the MFU-numerator tripwire for
    the refine path (same gate scripts/audit.py runs in CI)."""
    from ncnet_tpu.analysis.jaxpr_audit import audit

    result = audit(["train/refine", "refine/rescore"])
    assert result.all_findings == [], [
        f.format() for f in result.all_findings
    ]
    for r in result.reports:
        assert r["flops_expected"], r
        drift = (
            abs(r["flops_walked"] - r["flops_expected"])
            / r["flops_expected"]
        )
        assert drift < 1e-9, (r["program"], r["flops_walked"])


# --- multi-resolution feature store ------------------------------------------


def test_pooled_digest_binds_base_and_factor():
    from ncnet_tpu.features import pooled_digest

    d = pooled_digest("a" * 64, 2)
    assert d == pooled_digest("a" * 64, 2)  # deterministic
    assert d != pooled_digest("a" * 64, 4)  # factor-sensitive
    assert d != pooled_digest("b" * 64, 2)  # base-sensitive
    assert d != "a" * 64
    with pytest.raises(ValueError, match=">= 1"):
        pooled_digest("a" * 64, 0)


def test_multires_store_roundtrip_and_torn_pair(tmp_path):
    from ncnet_tpu.features import MultiResFeatureStore

    cfg = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
    store = MultiResFeatureStore.open_or_create(
        str(tmp_path / "mr"), "c" * 64, cfg, (64, 64), 2, factor=2
    )
    rng = np.random.RandomState(8)
    hi = [rng.randn(4, 4, 3).astype(np.float32) for _ in range(2)]
    lo = [rng.randn(2, 2, 3).astype(np.float32) for _ in range(2)]
    store.put(0, hi[0], hi[1], lo[0], lo[1])
    (ghs, ght), (gls, glt) = store.get(0)
    np.testing.assert_array_equal(np.asarray(ghs), hi[0])
    np.testing.assert_array_equal(np.asarray(ght), hi[1])
    np.testing.assert_array_equal(np.asarray(gls), lo[0])
    np.testing.assert_array_equal(np.asarray(glt), lo[1])
    # a pair with only ONE tier written is still missing: a crash
    # between the two writes re-extracts instead of serving a torn
    # resolution ladder
    store.hi.put(1, hi[0], hi[1])
    assert not store.has(1)
    assert store.missing() == [1] and not store.complete()
    store.lo.put(1, lo[0], lo[1])
    assert store.complete()


def test_multires_store_stale_tiers_rejected(tmp_path):
    from ncnet_tpu.features import (
        FeatureCacheMismatch,
        FeatureStore,
        MultiResFeatureStore,
        pooled_digest,
    )

    cfg = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
    root = str(tmp_path / "mr")
    MultiResFeatureStore.open_or_create(
        root, "c" * 64, cfg, (64, 64), 1, factor=2
    )
    # a different trunk digest refuses BOTH on open and on open_or_create
    with pytest.raises(FeatureCacheMismatch):
        MultiResFeatureStore.open_store(root, 2, expected_digest="d" * 64)
    with pytest.raises(FeatureCacheMismatch):
        MultiResFeatureStore.open_or_create(
            root, "d" * 64, cfg, (64, 64), 1, factor=2
        )
    # a leftover pooled tier from an OLDER trunk under a fresh hi tier:
    # the derived-digest chain refuses the pairing
    hi_root, lo_root = MultiResFeatureStore._roots(root, 2)
    import shutil

    shutil.rmtree(lo_root)
    FeatureStore.create(
        lo_root, pooled_digest("e" * 64, 2), cfg, (64, 64), 1
    )
    with pytest.raises(FeatureCacheMismatch):
        MultiResFeatureStore.open_store(root, 2, expected_digest="c" * 64)


def test_populate_store_multires_pools_the_same_trunk_pass(tmp_path):
    """End-to-end: one trunk forward fills BOTH tiers; the stored lo
    features equal pooling the stored hi features (they came from the
    same pass), and re-populating a complete store is a no-op."""
    from ncnet_tpu.data.pairs import SyntheticPairDataset
    from ncnet_tpu.features import (
        MultiResFeatureStore,
        populate_store_multires,
        trunk_digest,
    )

    cfg = ImMatchNetConfig(
        feature_extraction_cnn="patch16", ncons_kernel_sizes=(3,),
        ncons_channels=(1,),
    )
    params = init_immatchnet(jax.random.PRNGKey(9), cfg)
    ds = SyntheticPairDataset(n=4, output_size=(64, 64), seed=11)
    store = MultiResFeatureStore.open_or_create(
        str(tmp_path / "mr"),
        trunk_digest(params["feature_extraction"], cfg, (64, 64)),
        cfg, (64, 64), len(ds), factor=2,
    )
    assert populate_store_multires(
        store, params, cfg, ds, batch_size=2
    ) == 4
    assert store.complete()
    (src_hi, _), (src_lo, _) = store.get(2)
    assert src_hi.shape[:2] == (4, 4) and src_lo.shape[:2] == (2, 2)
    np.testing.assert_allclose(
        np.asarray(src_lo),
        np.asarray(pool_features(jnp.asarray(src_hi)[None], 2)[0]),
        rtol=1e-5, atol=1e-6,
    )
    assert populate_store_multires(store, params, cfg, ds) == 0  # lazy


# --- PCK: refinement beats its own coarse band -------------------------------


def test_synthetic_pck_refine_sweep():
    """The accuracy side of the compute ladder, on the pretrained-free
    synthetic construction (patch16 + identity NC): the factor-1
    complete-band cell must equal dense EXACTLY (the chained exactness
    anchor through the sweep API), and the factor-2 refined PCK must
    beat the plain coarse band at the SAME K — re-scoring the survivors
    at high res is what recovers the resolution the pool gave up."""
    from ncnet_tpu.data.pairs import SyntheticPairDataset
    from ncnet_tpu.eval.synthetic import (
        synthetic_pck_vs_refine,
        synthetic_pck_vs_topk,
    )

    size = 64  # patch16: fine grid 4x4, coarse 2x2 at factor 2
    cfg = ImMatchNetConfig(
        feature_extraction_cnn="patch16",
        ncons_kernel_sizes=(3,), ncons_channels=(1,), nc_init="identity",
    )
    params = init_immatchnet(jax.random.PRNGKey(12), cfg)
    ds = SyntheticPairDataset(
        n=4, output_size=(size, size), seed=5, return_shift=True,
        granularity=32,
    )
    batch = {
        key: np.stack([ds[i][key] for i in range(len(ds))])
        for key in ("source_image", "target_image", "shift")
    }
    sweep = synthetic_pck_vs_refine(
        params, cfg, [batch], factors=(0, 1, 2), ks=(4, 16),
        n_side=2, alpha=0.15,
    )
    dense = sweep[(0, 0)]
    assert dense > 0.5  # the construction resolves shifts at all
    # factor 1 at the complete band: the dense anchor through the sweep
    assert sweep[(1, 16)] == pytest.approx(dense, abs=1e-7)
    # factor 2: refinement recovers (at least) the coarse band's PCK of
    # the SAME width measured on the POOLED pipeline
    coarse_only = synthetic_pck_vs_topk(
        params, cfg, [batch], ks=(4,), n_side=2, alpha=0.15
    )
    assert sweep[(2, 4)] >= 0.9 * coarse_only[4]
