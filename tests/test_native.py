"""Native C++ resize fast path: build, load, numpy parity.

The library is compiled IN-TEST with g++ (baked into the image) into a tmp
dir and loaded via the NCNET_NATIVE_LIB env override, so the test works
from a clean tree and guards the .cpp against regressions.
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def native_lib(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("g++ not available")
    out = tmp_path_factory.mktemp("native") / "libncnet_native.so"
    subprocess.run(
        [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
            os.path.join(REPO, "native", "resize.cpp"), "-o", str(out),
        ],
        check=True,
    )
    return str(out)


def _fresh_native(monkeypatch, lib_path):
    """Import native.py with a fresh load state pointed at lib_path."""
    import importlib

    from ncnet_tpu.data import native

    monkeypatch.setenv("NCNET_NATIVE_LIB", lib_path)
    importlib.reload(native)
    return native


def test_native_resize_matches_numpy(native_lib, monkeypatch):
    native = _fresh_native(monkeypatch, native_lib)
    assert native.native_available()

    from ncnet_tpu.data.images import resize_bilinear_np

    rng = np.random.RandomState(0)
    for (h, w), (oh, ow) in [((37, 53), (25, 25)), ((8, 8), (16, 24)),
                             ((10, 10), (1, 1)), ((5, 7), (5, 7))]:
        img = rng.rand(h, w, 3).astype(np.float32) * 255.0
        got = native.resize_bilinear_native(img, oh, ow)
        assert got is not None and got.shape == (oh, ow, 3)
        # numpy fallback path, bypassing the native hook
        want_src = img if (h, w) != (oh, ow) else img.copy()
        fy = np.linspace(0.0, h - 1.0, oh)
        fx = np.linspace(0.0, w - 1.0, ow)
        y0 = np.floor(fy).astype(int)
        x0 = np.floor(fx).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (fy - y0)[:, None, None]
        wx = (fx - x0)[None, :, None]
        top = want_src[y0][:, x0] * (1 - wx) + want_src[y0][:, x1] * wx
        bot = want_src[y1][:, x0] * (1 - wx) + want_src[y1][:, x1] * wx
        want = top * (1 - wy) + bot * wy
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-3)
        # and the public entry agrees with itself via images.py fallback
        np.testing.assert_allclose(
            got, resize_bilinear_np(img, oh, ow), rtol=1e-5, atol=1e-3
        )


def test_native_absent_returns_none(monkeypatch, tmp_path):
    native = _fresh_native(monkeypatch, str(tmp_path / "missing.so"))
    assert not native.native_available()
    assert native.resize_bilinear_native(np.zeros((4, 4, 3), np.float32), 2, 2) is None
