import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ncnet_tpu.models import resnet, vgg
from ncnet_tpu.models.resnet import _bn_apply, _conv, _max_pool_3x3_s2


def test_bn_matches_torch_eval_mode():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    rng = np.random.RandomState(0)
    c = 8
    x = rng.randn(2, 5, 5, c).astype(np.float32)
    p = {
        "scale": rng.rand(c).astype(np.float32) + 0.5,
        "offset": rng.randn(c).astype(np.float32),
        "mean": rng.randn(c).astype(np.float32),
        "var": rng.rand(c).astype(np.float32) + 0.1,
    }
    got = np.asarray(_bn_apply({k: jnp.asarray(v) for k, v in p.items()}, jnp.asarray(x)))
    want = F.batch_norm(
        torch.from_numpy(x.transpose(0, 3, 1, 2)),
        torch.from_numpy(p["mean"]),
        torch.from_numpy(p["var"]),
        torch.from_numpy(p["scale"]),
        torch.from_numpy(p["offset"]),
        training=False,
        eps=1e-5,
    ).numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize(
    "hw,stride,ksize,pad",
    [(10, 1, 3, 1), (10, 2, 3, 1), (11, 2, 3, 1), (10, 2, 1, 0), (11, 2, 1, 0), (14, 2, 7, 3)],
)
def test_conv_padding_matches_torch(hw, stride, ksize, pad):
    """Stride/padding parity with torch — the sample-position alignment that
    SURVEY.md §7.3 flags as the backbone-parity hazard."""
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    rng = np.random.RandomState(1)
    cin, cout = 3, 4
    x = rng.randn(1, hw, hw, cin).astype(np.float32)
    w = rng.randn(ksize, ksize, cin, cout).astype(np.float32)
    padding = ((pad, pad), (pad, pad)) if pad else "SAME" if stride == 1 and ksize > 1 else ((0, 0), (0, 0))
    got = np.asarray(_conv(jnp.asarray(x), jnp.asarray(w), stride=stride, padding=padding))
    want = F.conv2d(
        torch.from_numpy(x.transpose(0, 3, 1, 2)),
        torch.from_numpy(w.transpose(3, 2, 0, 1)),
        stride=stride,
        padding=pad,
    ).numpy().transpose(0, 2, 3, 1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_maxpool_matches_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    rng = np.random.RandomState(2)
    for hw in (10, 11):
        x = rng.randn(1, hw, hw, 4).astype(np.float32)
        got = np.asarray(_max_pool_3x3_s2(jnp.asarray(x)))
        want = F.max_pool2d(
            torch.from_numpy(x.transpose(0, 3, 1, 2)), 3, stride=2, padding=1
        ).numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_resnet101_trunk_shape_and_stride():
    params = resnet.init_resnet101_trunk(jax.random.PRNGKey(0))
    x = jnp.zeros((1, 64, 64, 3))
    feats = resnet.resnet101_trunk_apply(params, x)
    assert feats.shape == (1, 4, 4, 1024)
    # 400x400 PF-Pascal config -> 25x25 grid (SURVEY.md §3.1)
    assert len(params["layer3"]) == 23


def test_vgg16_trunk_shape():
    params = vgg.init_vgg16_trunk(jax.random.PRNGKey(0))
    feats = vgg.vgg16_trunk_apply(params, jnp.zeros((1, 64, 64, 3)))
    assert feats.shape == (1, 4, 4, 512)


def test_patch16_trunk_orthogonal_and_discriminative():
    """The patch16 trunk (models/patch.py) must (a) produce stride-16
    features, (b) preserve patch inner products (orthonormal projection),
    and (c) make exact patch matches the correlation argmax — the
    property that justifies its existence for the synthetic proofs."""
    from ncnet_tpu.models import patch
    from ncnet_tpu.models.feature_extraction import (
        backbone_channels,
        backbone_stride,
        feature_extraction_apply,
        init_feature_extraction,
    )

    assert backbone_stride("patch16") == 16
    params = init_feature_extraction(jax.random.PRNGKey(0), "patch16")
    k = np.asarray(params["kernel"]).reshape(-1, patch.CHANNELS)
    np.testing.assert_allclose(
        k.T @ k, np.eye(patch.CHANNELS), atol=1e-4
    )  # orthonormal columns

    rng = np.random.RandomState(0)
    img = rng.rand(1, 64, 64, 3).astype(np.float32)
    feats = patch.patch_trunk_apply(params, jnp.asarray(img))
    assert feats.shape == (1, 4, 4, backbone_channels("patch16"))
    # inner products preserved: <Q p1, Q p2> == <p1 - ?, ...> up to the
    # rank-256 projection; identical patches must map to identical feats
    img2 = np.roll(img, 16, axis=2)  # shift by exactly one patch
    feats2 = patch.patch_trunk_apply(params, jnp.asarray(img2))
    np.testing.assert_allclose(
        np.asarray(feats)[0, :, :3], np.asarray(feats2)[0, :, 1:4], atol=1e-5
    )

    # correlation argmax picks the true (shifted) patch, full trunk path
    fa = feature_extraction_apply({"kernel": params["kernel"]}, jnp.asarray(img), cnn="patch16", center=True)
    fb = feature_extraction_apply({"kernel": params["kernel"]}, jnp.asarray(img2), cnn="patch16", center=True)
    from ncnet_tpu.ops.correlation import correlation_4d

    corr = np.asarray(correlation_4d(fa, fb))[0]
    for i in range(4):
        for j in range(3):
            ia, ja = divmod(corr[:, :, i, j + 1].reshape(-1).argmax(), 4)
            assert (ia, ja) == (i, j), (i, j, ia, ja)
