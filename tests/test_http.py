"""HTTP front door (ISSUE 17): the typed-outcome -> status-code wire
contract (table-pinned), X-Deadline-Ms / X-Quality header propagation
into the serving stack, per-bucket cost-aware degradation with ZERO
recompiles across rung flips and pins, the deadline-aware micro-batch
flush + `next_deadline` seam fix under a fake clock, the ordered
healthz-unready drain, the SIGTERM drain drill over a real subprocess
of scripts/serve_http.py, and the streaming telemetry bridge."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future
from pathlib import Path

import numpy as np
import pytest

import jax.numpy as jnp

from ncnet_tpu.resilience import faultinject
from ncnet_tpu.serve import (
    AdmissionRejected,
    DeadlineExceeded,
    MicroBatcher,
    QualityLadder,
    ReplicaDown,
    RequestShed,
    ServeEngine,
    StageFailure,
    outcome_status,
    payload_spec,
    start_http_server,
)
from ncnet_tpu.serve.batcher import Request

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.clear()
    yield
    faultinject.clear()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _toy_engine(**kw):
    params = {"w": jnp.asarray(3.0, jnp.float32)}

    def apply(p, batch):
        return {"y": batch["x"] * p["w"]}

    return ServeEngine(apply, params, **kw)


def _toy_payload(n, fill):
    return {"x": np.full((n,), fill, np.float32)}


def _call(url, method="GET", data=None, headers=None, timeout=30.0):
    """(status, headers, parsed-body). urllib treats non-2xx as raised
    HTTPError; fold both paths into one return."""
    req = urllib.request.Request(
        url, data=data, headers=dict(headers or {}), method=method
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            status, hdrs, raw = resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as exc:
        status, hdrs, raw = exc.code, dict(exc.headers), exc.read()
    ctype = hdrs.get("Content-Type", "")
    body = json.loads(raw) if ctype.startswith("application/json") else (
        raw.decode("utf-8")
    )
    return status, hdrs, body


def _post_match(base, payload, deadline_ms=None, quality=None, timeout=30.0):
    headers = {"Content-Type": "application/json"}
    if deadline_ms is not None:
        headers["X-Deadline-Ms"] = str(deadline_ms)
    if quality is not None:
        headers["X-Quality"] = quality
    body = json.dumps(
        {"payload": {k: np.asarray(v).tolist() for k, v in payload.items()}}
    ).encode("utf-8")
    return _call(base + "/v1/match", "POST", body, headers, timeout)


def _identity(stats):
    assert stats["submitted"] == (
        stats["completed"] + stats["failed"] + stats["shed"]
        + stats["deadline_exceeded"]
    )


def _stop(front, httpd, thread, timeout=10.0):
    front.begin_drain(timeout=timeout)
    httpd.server_close()
    thread.join(timeout=5.0)


# ----------------------------------------------------------------------
# the wire contract, pure-unit: outcome_status is the single source of
# truth the front door consults


@pytest.mark.parametrize(
    "exc, status, retry, error",
    [
        (AdmissionRejected("queue full", retry_after_s=0.25),
         429, 0.25, "admission_rejected"),
        (RequestShed("over budget", reason="admission", estimated_s=0.2,
                     deadline_s=0.1, retry_after_s=0.4),
         429, 0.4, "shed"),
        (RequestShed("draining", reason="drain"), 503, None, "draining"),
        (DeadlineExceeded("late", stage="readout", deadline_s=0.05),
         504, None, "deadline_exceeded"),
        (ReplicaDown("replica 1 died", replica=1, dispatched=True),
         502, None, "replica_down"),
        (StageFailure("dispatch", "no heartbeat", hang=True),
         500, None, "stage_failure"),
        (RuntimeError("boom"), 500, None, "RuntimeError"),
    ],
)
def test_outcome_status_table(exc, status, retry, error):
    got_status, got_retry, body = outcome_status(exc)
    assert got_status == status
    assert got_retry == retry
    assert body["error"] == error
    assert "detail" in body


def test_outcome_status_carries_diagnostics():
    # the body must carry what a caller would branch on, not just a code
    _, _, body = outcome_status(
        DeadlineExceeded("late", stage="dispatch", deadline_s=1.0)
    )
    assert body["stage"] == "dispatch"
    _, _, body = outcome_status(
        ReplicaDown("dead", replica=3, dispatched=False)
    )
    assert body["replica"] == 3 and body["dispatched"] is False
    _, _, body = outcome_status(
        RequestShed("m", reason="admission", estimated_s=0.2, deadline_s=0.1)
    )
    assert body["reason"] == "admission"
    assert body["estimated_s"] == 0.2 and body["deadline_s"] == 0.1
    _, _, body = outcome_status(StageFailure("prep", "died", hang=False))
    assert body["stage"] == "prep" and body["hang"] is False
    # deadline-exceeded must hit ITS row, not the RequestShed superclass
    st, _, _ = outcome_status(
        DeadlineExceeded("late", stage="prep", deadline_s=0.1)
    )
    assert st == 504


# ----------------------------------------------------------------------
# the wire status table over a REAL socket: a stub server injects each
# typed outcome; the client must see the exact (status, Retry-After,
# body) tuple


class _StubServer:
    """ServeEngine-shaped stand-in: submit raises ``submit_exc`` or
    returns a future pre-resolved to ``outcome`` / ``result``."""

    def __init__(self, outcome=None, submit_exc=None):
        self.outcome = outcome
        self.submit_exc = submit_exc
        self.drained = False

    def submit(self, *, key=None, payload=None, deadline_s=None,
               variant=None, timeout=None):
        del key, deadline_s, variant, timeout
        if self.submit_exc is not None:
            raise self.submit_exc
        fut = Future()
        if self.outcome is not None:
            fut.set_exception(self.outcome)
        else:
            fut.set_result({"y": np.asarray(payload["x"]) * 2.0})
        return fut

    def drain(self, timeout=None):
        del timeout
        self.drained = True


@pytest.mark.parametrize(
    "kw, status, retry_after, retry_ms, error, extra",
    [
        # submit-time rejection: the Retry-After pair must be on the wire
        (dict(submit_exc=AdmissionRejected("full", retry_after_s=0.25)),
         429, "1", "250.000", "admission_rejected", {}),
        (dict(outcome=RequestShed("over", reason="admission",
                                  estimated_s=0.2, deadline_s=0.1,
                                  retry_after_s=2.5)),
         429, "3", "2500.000", "shed", {"reason": "admission"}),
        (dict(outcome=RequestShed("bye", reason="drain")),
         503, None, None, "draining", {}),
        (dict(outcome=DeadlineExceeded("late", stage="readout",
                                       deadline_s=0.05)),
         504, None, None, "deadline_exceeded", {"stage": "readout"}),
        (dict(outcome=ReplicaDown("dead", replica=1, dispatched=True)),
         502, None, None, "replica_down",
         {"replica": 1, "dispatched": True}),
        (dict(outcome=StageFailure("dispatch", "no heartbeat", hang=True)),
         500, None, None, "stage_failure",
         {"stage": "dispatch", "hang": True}),
        (dict(outcome=RuntimeError("boom")),
         500, None, None, "RuntimeError", {}),
    ],
)
def test_http_status_over_the_wire(kw, status, retry_after, retry_ms,
                                   error, extra):
    server = _StubServer(**kw)
    front, httpd, thread = start_http_server(server)
    base = "http://%s:%d" % httpd.server_address[:2]
    try:
        got, hdrs, body = _post_match(base, _toy_payload(3, 1.0))
        assert got == status
        assert body["error"] == error
        assert hdrs.get("Retry-After") == retry_after
        assert hdrs.get("X-Retry-After-Ms") == retry_ms
        if retry_after is not None:
            assert body["retry_after_s"] == pytest.approx(
                float(retry_ms) / 1e3
            )
        for k, v in extra.items():
            assert body[k] == v
        assert front.status_tally() == {status: 1}
    finally:
        _stop(front, httpd, thread)
    assert server.drained


def test_http_success_and_edge_requests():
    server = _StubServer()
    front, httpd, thread = start_http_server(server)
    base = "http://%s:%d" % httpd.server_address[:2]
    try:
        status, _, body = _post_match(base, _toy_payload(3, 2.0))
        assert status == 200
        assert body["result"]["y"] == [4.0, 4.0, 4.0]

        status, _, body = _call(base + "/healthz")
        assert status == 200 and body["status"] == "ok"

        status, _, text = _call(base + "/metrics")
        assert status == 200
        assert "http_requests_total" in text
        assert "http_responses_200_total" in text

        # malformed requests are 400s, never 500s
        for raw in (b"{not json", b"[1, 2]", b"{}",
                    b'{"payload": {}}', b'{"payload": 7}'):
            status, _, body = _call(
                base + "/v1/match", "POST", raw,
                {"Content-Type": "application/json"},
            )
            assert status == 400, raw
            assert body["error"] == "bad_request"
        # bad headers on a well-formed body
        for hdr in ({"X-Deadline-Ms": "abc"}, {"X-Deadline-Ms": "-5"},
                    {"X-Quality": "ultra"}):
            data = json.dumps({"payload": {"x": [1.0]}}).encode()
            status, _, body = _call(base + "/v1/match", "POST", data, hdr)
            assert status == 400, hdr
        status, _, _ = _call(base + "/nope")
        assert status == 404
        status, _, _ = _call(base + "/nope", "POST", b"")
        assert status == 404
        tally = front.status_tally()
        assert tally[200] == 3  # match + healthz + metrics
        assert tally[400] == 8 and tally[404] == 2
    finally:
        _stop(front, httpd, thread)


# ----------------------------------------------------------------------
# deadline-budget propagation: X-Deadline-Ms reaches admission control


def test_deadline_header_propagates_to_admission():
    eng = _toy_engine(max_batch=2, max_wait=0.002, host_workers=1)
    with eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        front, httpd, thread = start_http_server(
            eng, key_fn=lambda payload: "A"
        )
        base = "http://%s:%d" % httpd.server_address[:2]
        try:
            # generous budgets: all served, and they warm the estimator
            for i in range(8):
                status, _, body = _post_match(
                    base, _toy_payload(3, float(i)), deadline_ms=5000
                )
                assert status == 200
                assert body["result"]["y"] == [i * 3.0] * 3
            # a 0.2 ms budget cannot cover even the batcher max_wait:
            # admission sheds (429) or the pipeline drops it (504) —
            # either way the budget header did its job, typed
            sheds = 0
            for _ in range(4):
                status, _, body = _post_match(
                    base, _toy_payload(3, 1.0), deadline_ms=0.2
                )
                assert status in (429, 504), body
                assert body["error"] in ("shed", "deadline_exceeded")
                sheds += 1
            stats = eng.report()
            _identity(stats)
            assert stats["shed"] + stats["deadline_exceeded"] == sheds
            assert stats["completed"] == 8
            assert stats["deadline_flush"] is True  # engine default
            tally = front.status_tally()
            assert tally[200] == 8
            assert tally.get(429, 0) + tally.get(504, 0) == sheds
        finally:
            _stop(front, httpd, thread)
    assert eng.report()["recompiles_after_warmup"] == 0


# ----------------------------------------------------------------------
# X-Quality pins + per-bucket cost-aware ladders: mixed traffic, rung
# flips, ZERO recompiles


def test_quality_pins_and_per_bucket_flips_zero_recompiles():
    params = {"w": jnp.asarray(3.0, jnp.float32)}

    def apply(p, batch):
        return {"y": batch["x"] * p["w"]}

    def degraded(p, batch):
        return {"y": batch["x"] * p["w"] * 0.5}

    def refined(p, batch):
        return {"y": batch["x"] * p["w"] * 2.0}

    # a ladder that steps down on ANY pressure and never climbs back:
    # the organic per-bucket flip happens deterministically on the first
    # unpinned batch
    def eager_ladder():
        return QualityLadder(
            rungs=("standard", "degraded"), start="standard",
            high=0.0, low=-1.0, up_count=1, down_count=10**9,
        )

    eng = ServeEngine(
        apply, params,
        degraded_apply_fn=degraded, refined_apply_fn=refined,
        per_bucket_quality=True, bucket_ladder=eager_ladder,
        max_batch=2, max_wait=0.002, host_workers=1,
    )
    with eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        warmed = eng.report()["compiled_programs"]
        front, httpd, thread = start_http_server(
            eng, key_fn=lambda payload: "A"
        )
        base = "http://%s:%d" % httpd.server_address[:2]
        try:
            # unpinned traffic: the eager per-bucket ladder flips the
            # bucket to its degraded rung on the first batch
            for _ in range(4):
                status, _, body = _post_match(base, _toy_payload(3, 2.0))
                assert status == 200
                assert body["result"]["y"] == [3.0] * 3  # 2 * 3 * 0.5
            # pins override the ladder, each at its own warmed program
            expected = {"standard": 6.0, "degraded": 3.0, "refined": 12.0}
            for quality, y in expected.items():
                status, _, body = _post_match(
                    base, _toy_payload(3, 2.0), quality=quality
                )
                assert status == 200, (quality, body)
                assert body["result"]["y"] == [y] * 3, quality
            # an unservable pin is a 400 at submit, not a 500 later
            status, _, body = _post_match(
                base, _toy_payload(3, 2.0), quality="ultra"
            )
            assert status == 400
            stats = eng.report()
            _identity(stats)
            assert stats["completed"] == 7
            assert stats["pinned"] == 3
            assert stats["degrade_flips"] >= 1  # the organic bucket flip
            assert stats["bucket_quality"] == {"A": "degraded"}
            # THE tentpole invariant: warmup covered every (bucket,
            # batch-size, variant); flips and pins compiled nothing
            assert stats["recompiles_after_warmup"] == 0
            assert stats["compiled_programs"] == warmed
            tally = front.status_tally()
            assert tally[200] == stats["completed"]
            assert tally[400] == 1
        finally:
            _stop(front, httpd, thread)


# ----------------------------------------------------------------------
# the batcher seam (satellite): deadline-aware flush + the next_deadline
# fix, deterministic under a fake clock


def _req(key, i, deadline=None, variant=None):
    return Request(key, {"x": i}, Future(), 0.0, deadline, variant)


def test_deadline_aware_flush_and_next_deadline():
    clk = FakeClock(0.0)
    est = {"A": 0.03}
    mb = MicroBatcher(
        max_batch=8, max_wait=0.05, clock=clk, estimate_fn=est.get
    )
    assert mb.deadline_aware

    # tight budget: flush_at = min(0.05, 0.06 - 0.05 - 0.03) = -0.02,
    # i.e. ALREADY due — next_deadline must report it (the pre-fix bug:
    # the dispatcher slept the full max_wait through tight budgets)
    assert mb.add(_req("A", 0, deadline=0.06)) is None
    assert mb.next_deadline(0.0) == pytest.approx(-0.02)
    (batch,) = mb.ready(0.0)
    assert len(batch.requests) == 1

    # no deadline: fixed-wait behavior unchanged
    assert mb.add(_req("A", 1)) is None
    assert mb.next_deadline(0.0) == pytest.approx(0.05)
    assert mb.ready(0.0) == []
    clk.t = 0.05
    assert len(mb.ready()) == 1

    # cold estimator (no estimate for the bucket): the pull-forward
    # still applies with est = 0
    clk.t = 0.0
    assert mb.add(_req("B", 0, deadline=0.06)) is None
    assert mb.next_deadline(0.0) == pytest.approx(0.01)
    mb.drain()

    # the tightest member governs the whole group
    assert mb.add(_req("A", 0, deadline=10.0)) is None
    assert mb.add(_req("A", 1, deadline=0.06)) is None
    assert mb.next_deadline(0.0) == pytest.approx(-0.02)
    mb.drain()


def test_fixed_wait_baseline_ignores_deadlines():
    # estimate_fn=None is the A/B baseline arm: deadlines must not move
    # the flush time
    clk = FakeClock(0.0)
    mb = MicroBatcher(max_batch=8, max_wait=0.05, clock=clk)
    assert not mb.deadline_aware
    assert mb.add(_req("A", 0, deadline=0.06)) is None
    assert mb.next_deadline(0.0) == pytest.approx(0.05)
    assert mb.ready(0.0) == []
    clk.t = 0.05
    assert len(mb.ready()) == 1


def test_batcher_groups_by_pinned_variant():
    clk = FakeClock(0.0)
    mb = MicroBatcher(max_batch=2, max_wait=10.0, clock=clk)
    # a pinned request must never coalesce with unpinned ones on the
    # same bucket: three adds, only the two UNPINNED form a full batch
    assert mb.add(_req("A", 0)) is None
    assert mb.add(_req("A", 1, variant="degraded")) is None
    full = mb.add(_req("A", 2))
    assert full is not None and full.variant is None
    assert [r.payload["x"] for r in full.requests] == [0, 2]
    # the pinned group fills separately and carries its rung
    pinned = mb.add(_req("A", 3, variant="degraded"))
    assert pinned is not None and pinned.variant == "degraded"
    assert mb.pending() == 0
    # keys() dedups variants: router affinity is per compiled bucket
    mb.add(_req("A", 4))
    mb.add(_req("A", 5, variant="refined"))
    mb.add(_req("B", 6))
    assert mb.keys() == ("A", "B")
    leftovers = mb.drain()
    assert {(b.key, b.variant) for b in leftovers} == {
        ("A", None), ("A", "refined"), ("B", None)
    }


# ----------------------------------------------------------------------
# the ordered drain over live HTTP (satellite): healthz flips unready
# and new requests 503 WHILE the in-flight request finishes 2xx


def test_http_drain_ordering_inflight_finishes():
    eng = _toy_engine(max_batch=2, max_wait=0.002, host_workers=1)
    with eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        front, httpd, thread = start_http_server(
            eng, key_fn=lambda payload: "A"
        )
        base = "http://%s:%d" % httpd.server_address[:2]
        try:
            status, _, _ = _call(base + "/healthz")
            assert status == 200
            # hold the next request in prep long enough to drain around
            faultinject.configure("serve.request=delay:0.5")
            inflight = {}

            def _slow_post():
                inflight["resp"] = _post_match(base, _toy_payload(3, 2.0))

            poster = threading.Thread(target=_slow_post)
            poster.start()
            time.sleep(0.15)  # the request is in the prep stage now

            drainer = threading.Thread(
                target=front.begin_drain, kwargs={"timeout": 10.0}
            )
            drainer.start()
            time.sleep(0.1)
            # mid-drain, listener still open: LB sees unready, new
            # traffic is refused typed — the in-flight one is NOT
            status, _, body = _call(base + "/healthz")
            assert status == 503 and body["status"] == "unready"
            status, _, body = _post_match(base, _toy_payload(3, 9.0))
            assert status == 503 and body["error"] == "draining"
            assert not front.accepting

            poster.join(timeout=15.0)
            assert not poster.is_alive()
            status, _, body = inflight["resp"]
            assert status == 200
            assert body["result"]["y"] == [6.0] * 3
            drainer.join(timeout=15.0)
            assert not drainer.is_alive()
        finally:
            httpd.server_close()
            thread.join(timeout=5.0)
        stats = eng.report()
        _identity(stats)
        assert stats["completed"] == 1
        assert stats["recompiles_after_warmup"] == 0


# ----------------------------------------------------------------------
# the SIGTERM drain drill over a real subprocess of scripts/serve_http.py
# (the ops contract, end to end over real sockets)


def test_http_cli_sigterm_drain_drill(tmp_path):
    """SIGTERM against a live scripts/serve_http.py: in-flight HTTP
    requests finish 2xx, /healthz flips unready before the listener
    closes, late traffic gets 503/refused, the process exits 0, and the
    printed report's accounting identity reconciles with the HTTP
    status tally."""
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig
    from ncnet_tpu.serve import BucketSpec

    cfg = ImMatchNetConfig(
        ncons_kernel_sizes=(3,), ncons_channels=(1,),
        feature_extraction_cnn="patch16",
    )
    spec = BucketSpec(32, max(cfg.relocalization_k_size, 1))
    h, w = spec.bucket(32, 32)
    img = np.zeros((h, w, 3), np.float32).tolist()
    body = json.dumps(
        {"payload": {"source_image": img, "target_image": img}}
    ).encode("utf-8")

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        NCNET_FAULTS="serve.request=delay:0.05",  # hold requests in prep
    )
    proc = subprocess.Popen(
        [
            sys.executable, str(REPO / "scripts" / "serve_http.py"),
            "--synthetic",
            "--image-size", "32",
            "--port", "0",
            "--max-batch", "2",
            "--max-wait-ms", "10",
            "--drain-timeout", "10",
            "--telemetry", str(tmp_path / "tele"),
            "--telemetry-stream-s", "0.2",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(REPO),
    )
    statuses = []  # [(status, error-or-None)] every match response seen
    health = []  # healthz statuses observed after SIGTERM
    stats_lock = threading.Lock()
    try:
        base = None
        while True:  # readline blocks through the compile phase
            line = proc.stdout.readline()
            assert line, "serve_http.py exited before opening its listener"
            if line.startswith("serving: "):
                base = line.split("serving: ", 1)[1].strip()
                break
        stop_posting = threading.Event()

        def _client():
            while not stop_posting.is_set():
                try:
                    status, _, resp = _call(
                        base + "/v1/match", "POST", body,
                        {"Content-Type": "application/json"}, timeout=30,
                    )
                except (urllib.error.URLError, ConnectionError, OSError):
                    return  # listener closed: the drill is over
                err = resp.get("error") if isinstance(resp, dict) else None
                with stats_lock:
                    statuses.append((status, err))
                if status == 503:
                    return

        clients = [threading.Thread(target=_client) for _ in range(3)]
        for c in clients:
            c.start()
        time.sleep(0.7)  # traffic flowing, some requests mid-prep
        proc.send_signal(signal.SIGTERM)
        # the drain window: healthz must answer UNREADY while in-flight
        # requests finish, before the listener closes
        for _ in range(400):
            try:
                status, _, _ = _call(base + "/healthz", timeout=5)
            except (urllib.error.URLError, ConnectionError, OSError):
                break  # listener closed — the END of the ordered drain
            health.append(status)
            time.sleep(0.005)
        stop_posting.set()
        for c in clients:
            c.join(timeout=30)
        out, err = proc.communicate(timeout=180)
    finally:
        proc.kill()
    assert proc.returncode == 0, err[-2000:]
    # SIGTERM delivery -> the drain watcher flipping unready can take a
    # couple of poll ticks, so the first few probes may still see 200 —
    # but once unready, healthz NEVER recovers before the listener closes
    assert 503 in health, "healthz never flipped unready during the drain"
    assert all(s == 503 for s in health[health.index(503):]), health

    report = json.loads(out[out.index("{"):])
    match_200 = sum(1 for s, _ in statuses if s == 200)
    assert match_200 >= 1  # traffic was served before the signal
    # every client-visible status is a typed one from the contract
    assert {s for s, _ in statuses} <= {200, 429, 503, 504}
    _identity(report)
    assert report["recompiles_after_warmup"] == 0
    # reconciliation: the engine ledger vs the HTTP tally vs what the
    # clients SAW (tally keys arrive as strings through JSON; healthz
    # probes land in the same per-status counters as match traffic)
    tally = {k: v for k, v in report["http_status_tally"].items()}
    assert report["completed"] == match_200
    assert tally.get("200", 0) == match_200 + health.count(200)
    assert tally.get("503", 0) == (
        sum(1 for s, _ in statuses if s == 503) + health.count(503)
    )
    # the streaming bridge ran: the live events log has metric records
    from ncnet_tpu.telemetry.export import find_event_logs, read_events

    logs = find_event_logs(str(tmp_path / "tele"))
    assert logs
    events = [e for p in logs for e in read_events(p)]
    assert any(e.get("type") == "metric" for e in events)


# ----------------------------------------------------------------------
# streaming telemetry bridge (satellite): incremental metric flushes a
# scraper can tail, same schema the report reader already parses


def test_metric_streamer_incremental_flushes(tmp_path):
    from ncnet_tpu.telemetry.export import read_events
    from ncnet_tpu.telemetry.registry import MetricsRegistry
    from ncnet_tpu.telemetry.session import TelemetrySession

    reg = MetricsRegistry()
    counter = reg.counter("drill_total", "streamed test counter")
    session = TelemetrySession(str(tmp_path), registry=reg, label="stream")
    try:
        streamer = session.start_streaming(0.02)
        with pytest.raises(RuntimeError):
            session.start_streaming(0.02)  # one streamer per session
        counter.inc()
        deadline = time.monotonic() + 5.0
        while streamer.flushes < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert streamer.flushes >= 3
        counter.inc()
    finally:
        session.stop()
    assert not streamer.thread.is_alive()

    events = read_events(session.events_path)
    records = [
        e for e in events
        if e.get("type") == "metric" and e.get("name") == "drill_total"
    ]
    # incremental records DURING the run, not just the stop snapshot
    assert len(records) >= 3
    # last-record-wins: the report reader's rule still lands on final
    assert records[-1]["value"] == 2


def test_metric_streamer_survives_flush_errors():
    from ncnet_tpu.telemetry.export import MetricStreamer

    with pytest.raises(ValueError):
        MetricStreamer(lambda: None, 0.0)

    calls = []

    def boom():
        calls.append(1)
        raise OSError("disk full")

    streamer = MetricStreamer(boom, 0.01).start()
    deadline = time.monotonic() + 5.0
    while streamer.errors < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    streamer.stop()
    streamer.stop()  # idempotent
    assert streamer.errors >= 3  # kept ticking through failures
    assert streamer.flushes == 0
    assert not streamer.thread.is_alive()
