"""Golden-finding tests for the jaxpr auditor (ncnet_tpu.analysis.jaxpr_audit).

Each jaxpr rule gets a synthetic jitted program that PROVABLY violates it
(the f64 leak, the captured constant, the omitted donation, ...) plus a
clean twin — the executable form of the rule catalog — and the end-to-end
gate: auditing the repo's REAL train/serve/eval entry programs yields zero
unsuppressed findings, with the analytic FLOP walk agreeing with
`ops.accounting`'s closed form.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.analysis.findings import Finding, format_sarif
from ncnet_tpu.analysis.jaxpr_audit import (
    JAXPR_RULES,
    PROGRAMS,
    BuiltProgram,
    audit,
    format_report_table,
    jaxpr_flops,
    rules_meta,
    run_jaxpr_rules,
    trace_program,
)


def run_rules(built, waivers=None, rules=None, name="synthetic"):
    tp = trace_program(name, built)
    return run_jaxpr_rules(tp, waivers, rules)


# --- f64-leak ----------------------------------------------------------------


def test_f64_leak_golden():
    @jax.jit
    def leaky(x):
        # the classic promotion: an explicit f64 cast (stand-in for an
        # unannotated numpy double scalar) drags the chain to f64
        return jnp.asarray(x, jnp.float64) * 2.0

    from jax.experimental import enable_x64

    with enable_x64():
        tp = trace_program(
            "syn/f64", BuiltProgram(fn=leaky, args=(np.ones(4, np.float32),))
        )
    findings, _ = run_jaxpr_rules(tp, rules=["f64-leak"])
    assert [f.rule for f in findings] == ["f64-leak"]
    assert findings[0].severity == "error"
    assert findings[0].detail["dtype"] == "float64"


def test_f64_leak_clean_on_f32():
    @jax.jit
    def fine(x):
        return x * 2.0

    findings, _ = run_rules(
        BuiltProgram(fn=fine, args=(np.ones(4, np.float32),)),
        rules=["f64-leak"],
    )
    assert findings == []


# --- bf16-promotion-drift ----------------------------------------------------


def test_bf16_drift_golden():
    @jax.jit
    def f32_contraction(a, b):
        return a @ b  # f32 dot in a program that declares bf16

    a = np.ones((8, 8), np.float32)
    findings, _ = run_rules(
        BuiltProgram(
            fn=f32_contraction, args=(a, a), declared_dtype="bfloat16"
        ),
        rules=["bf16-promotion-drift"],
    )
    assert [f.rule for f in findings] == ["bf16-promotion-drift"]
    assert findings[0].detail["f32_contractions"] == 1


def test_bf16_drift_clean_when_contractions_are_bf16():
    @jax.jit
    def bf16_contraction(a, b):
        return (a @ b).astype(jnp.float32)  # f32 ELEMENTWISE cast is fine

    a = np.ones((8, 8), np.float16).astype(jnp.bfloat16)
    findings, _ = run_rules(
        BuiltProgram(
            fn=bf16_contraction, args=(a, a), declared_dtype="bfloat16"
        ),
        rules=["bf16-promotion-drift"],
    )
    assert findings == []


def test_bf16_drift_ignores_undeclared_programs():
    @jax.jit
    def f32_contraction(a, b):
        return a @ b

    a = np.ones((8, 8), np.float32)
    findings, _ = run_rules(
        BuiltProgram(fn=f32_contraction, args=(a, a)),  # no declared dtype
        rules=["bf16-promotion-drift"],
    )
    assert findings == []


# --- host-callback-in-jit ----------------------------------------------------


def test_host_callback_golden():
    @jax.jit
    def chatty(x):
        jax.debug.print("x has mean {m}", m=x.mean())
        return x + 1

    findings, _ = run_rules(
        BuiltProgram(fn=chatty, args=(np.ones(4, np.float32),)),
        rules=["host-callback-in-jit"],
    )
    assert [f.rule for f in findings] == ["host-callback-in-jit"]
    assert findings[0].severity == "error"
    assert "callback" in findings[0].detail["primitive"]


def test_host_callback_clean():
    @jax.jit
    def quiet(x):
        return x + 1

    findings, _ = run_rules(
        BuiltProgram(fn=quiet, args=(np.ones(4, np.float32),)),
        rules=["host-callback-in-jit"],
    )
    assert findings == []


# --- missing-donation --------------------------------------------------------


def _carry_fn(donate):
    def step(state, x):
        return jax.tree_util.tree_map(lambda s: s + x, state), x * 2

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def _carry_args():
    state = {"w": np.zeros((128, 128), np.float32),
             "b": np.zeros((128,), np.float32)}
    return (state, np.float32(1.0))


def test_missing_donation_golden():
    findings, _ = run_rules(
        BuiltProgram(
            fn=_carry_fn(donate=False),
            args=_carry_args(),
            donate_expect={0: "carried state"},
        ),
        rules=["missing-donation"],
    )
    assert [f.rule for f in findings] == ["missing-donation"]
    # wasted bytes = the whole undonated carry: 128*128*4 + 128*4
    assert findings[0].detail["wasted_bytes"] == 128 * 128 * 4 + 128 * 4
    assert findings[0].detail["undonated_leaves"] == 2


def test_missing_donation_clean_when_donated():
    findings, _ = run_rules(
        BuiltProgram(
            fn=_carry_fn(donate=True),
            args=_carry_args(),
            donate_expect={0: "carried state"},
        ),
        rules=["missing-donation"],
    )
    assert findings == []


# --- oversized-constant ------------------------------------------------------


def test_oversized_constant_golden():
    baked = jnp.asarray(np.ones((600, 600), np.float32))  # 1.44 MB captured

    @jax.jit
    def apply(x):
        return x @ baked

    findings, _ = run_rules(
        BuiltProgram(fn=apply, args=(np.ones((4, 600), np.float32),)),
        rules=["oversized-constant"],
    )
    assert [f.rule for f in findings] == ["oversized-constant"]
    assert findings[0].detail["bytes"] == 600 * 600 * 4


def test_oversized_constant_clean_when_passed_as_arg():
    @jax.jit
    def apply(x, w):
        return x @ w

    findings, _ = run_rules(
        BuiltProgram(
            fn=apply,
            args=(np.ones((4, 600), np.float32),
                  np.ones((600, 600), np.float32)),
        ),
        rules=["oversized-constant"],
    )
    assert findings == []


# --- flop-accounting-drift ---------------------------------------------------


def test_flop_walk_counts_dot_general_exactly():
    @jax.jit
    def mm(a, b):
        return a @ b

    tp = trace_program(
        "syn/mm",
        BuiltProgram(
            fn=mm,
            args=(np.ones((8, 16), np.float32), np.ones((16, 8), np.float32)),
        ),
    )
    assert jaxpr_flops(tp.jaxpr) == 2 * 8 * 8 * 16


def test_flop_drift_golden_and_clean():
    @jax.jit
    def mm(a, b):
        return a @ b

    args = (np.ones((8, 16), np.float32), np.ones((16, 8), np.float32))
    exact = 2 * 8 * 8 * 16

    findings, _ = run_rules(
        BuiltProgram(fn=mm, args=args, expected_flops=exact * 2),
        rules=["flop-accounting-drift"],
    )
    assert [f.rule for f in findings] == ["flop-accounting-drift"]
    assert findings[0].detail["walked_flops"] == exact

    findings, _ = run_rules(
        BuiltProgram(fn=mm, args=args, expected_flops=exact),
        rules=["flop-accounting-drift"],
    )
    assert findings == []


# --- waivers (the audit's suppression mechanism) -----------------------------


def test_waiver_with_reason_moves_finding_aside():
    @jax.jit
    def chatty(x):
        jax.debug.print("{x}", x=x)
        return x

    findings, waived = run_rules(
        BuiltProgram(fn=chatty, args=(np.ones(2, np.float32),)),
        waivers={"host-callback-in-jit": "debug-only program"},
        rules=["host-callback-in-jit"],
    )
    assert findings == []
    assert [f.rule for f in waived] == ["host-callback-in-jit"]


def test_waiver_without_reason_is_an_error():
    @jax.jit
    def quiet(x):
        return x

    findings, _ = run_rules(
        BuiltProgram(fn=quiet, args=(np.ones(2, np.float32),)),
        waivers={"host-callback-in-jit": "  "},
    )
    assert any(f.rule == "bad-waiver" and f.severity == "error"
               for f in findings)


# --- the rule catalog --------------------------------------------------------


def test_jaxpr_rule_catalog():
    assert len(JAXPR_RULES) >= 6
    for r in JAXPR_RULES.values():
        assert r.doc.strip(), f"jaxpr rule {r.rule_id} has no catalog doc"
    meta = rules_meta()
    assert "bad-waiver" in meta and "audit-trace-failure" in meta


def test_unjitted_program_is_rejected():
    def plain(x):
        return x + 1

    with pytest.raises(ValueError, match="pjit"):
        trace_program(
            "syn/plain",
            BuiltProgram(fn=plain, args=(np.ones(2, np.float32),)),
        )


# --- end-to-end over the REAL entry programs ---------------------------------


def test_real_programs_zero_unsuppressed_findings():
    """The acceptance gate: >= 5 distinct real entry programs trace clean.

    This is what `scripts/audit.py` (and CI) runs — dense train, cached
    train, sparse train, a serve bucket program, and the eval match fn
    all audited with zero unsuppressed findings.
    """
    result = audit()
    assert result.all_findings == [], [
        f.format() for f in result.all_findings
    ]
    names = {r["program"] for r in result.reports}
    assert {
        "train/dense", "train/cached", "train/sparse",
        "serve/bucket", "serve/sharded", "eval/match",
    } <= names
    assert len(names) >= 6


def test_real_train_programs_flop_walk_matches_accounting():
    """The walk and the closed form agree on every f32 train program —
    the regression tripwire for the MFU numerator."""
    result = audit(["train/dense", "train/cached", "train/sparse"])
    assert result.all_findings == []
    for r in result.reports:
        expected = r["flops_expected"]
        assert expected, r
        drift = abs(r["flops_walked"] - expected) / expected
        assert drift < 1e-9, (r["program"], r["flops_walked"], expected)


def test_real_programs_donate_their_carried_buffers():
    result = audit(["train/dense", "serve/bucket"])
    by_name = {r["program"]: r for r in result.reports}
    # train: the carried state dominates the input bytes and is donated
    train = by_name["train/dense"]
    assert train["bytes_donated"] > 0
    # serve: the padded batch (both images) is donated, params are not
    serve = by_name["serve/bucket"]
    assert serve["bytes_donated"] == 2 * 2 * 64 * 64 * 3 * 4
    assert serve["bytes_donated"] < serve["bytes_in"]


def test_report_table_renders():
    result = audit(["eval/match"])
    table = format_report_table(result.reports)
    assert "eval/match" in table and "flops(walk)" in table


def test_program_registry_waiver_reasons_nonempty():
    for spec in PROGRAMS.values():
        for rule_id, reason in spec.waivers.items():
            assert reason.strip(), (spec.name, rule_id)


# --- shared findings schema: JSON + SARIF ------------------------------------


def test_sarif_document_shape():
    fs = [
        Finding("jaxpr:train/dense", 1, 0, "missing-donation", "warning",
                "arg 0 not donated", {"wasted_bytes": 5}),
        Finding("ncnet_tpu/train/loop.py", 12, 4, "host-sync-in-jit",
                "warning", "sync"),
    ]
    doc = json.loads(format_sarif(fs, "audit", rules_meta()))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "audit"
    ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert "missing-donation" in ids
    first = run["results"][0]
    assert first["ruleId"] == "missing-donation"
    assert first["properties"]["wasted_bytes"] == 5
    loc = first["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 1
    assert loc["artifactLocation"]["uri"] == "jaxpr:train/dense"


def test_audit_cli_json_and_gate(capsys):
    import sys

    sys.path.insert(0, "scripts")
    try:
        from audit import main
    finally:
        sys.path.pop(0)

    assert main(["--format", "json", "--programs", "eval/match"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["tool"] == "audit"
    assert payload["schema_version"] == 1
    assert payload["count"] == 0


def test_nclint_sarif_output(tmp_path, capsys):
    from ncnet_tpu.analysis.cli import main

    bad = tmp_path / "mod.py"
    bad.write_text("def f(xs=[]):\n    return xs\n")
    assert main([str(bad), "--format", "sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["tool"]["driver"]["name"] == "nclint"
    assert doc["runs"][0]["results"][0]["ruleId"] == "mutable-default-arg"


# --- HLO-level pass (ncnet_tpu.analysis.hlo_audit) ---------------------------


def _hlo_program(**kw):
    """A synthetic HloProgram for golden rule tests (no compile)."""
    from ncnet_tpu.analysis.hlo_audit import HloProgram

    base = dict(
        name="synthetic", built=None, entry_ops={"fusion": 10, "dot": 2},
        contractions=2, peak_bytes_est=1000, bytes_in=1000,
        hlo_temp_bytes=None,
    )
    base.update(kw)
    return HloProgram(**base)


def test_hlo_rule_catalog_and_meta():
    from ncnet_tpu.analysis.hlo_audit import HLO_RULES

    assert set(HLO_RULES) == {
        "fusion-fragmentation", "layout-churn", "memory-highwater"
    }
    meta = rules_meta()
    for rid in HLO_RULES:
        assert rid in meta and meta[rid]["doc"]
    assert "audit-compile-failure" in meta


def test_fusion_fragmentation_golden_and_clean(monkeypatch):
    from ncnet_tpu.analysis import hlo_audit
    from ncnet_tpu.analysis.hlo_audit import run_hlo_rules

    hp = _hlo_program(
        entry_ops={"fusion": 50, "dot": 2, "parameter": 5}, contractions=2
    )
    monkeypatch.setattr(hlo_audit, "FRAGMENTATION_OPS_PER_CONTRACTION", 10.0)
    monkeypatch.setattr(hlo_audit, "FRAGMENTATION_MIN_OPS", 1)
    findings, _ = run_hlo_rules(hp)
    assert [f.rule for f in findings] == ["fusion-fragmentation"]
    assert findings[0].path == "hlo:synthetic"
    assert findings[0].detail["launches"] == 52  # parameters are free
    # clean twin: same census, budget above the ratio
    monkeypatch.setattr(hlo_audit, "FRAGMENTATION_OPS_PER_CONTRACTION", 100.0)
    assert run_hlo_rules(hp) == ([], [])
    # tiny programs never fire regardless of ratio
    monkeypatch.setattr(hlo_audit, "FRAGMENTATION_OPS_PER_CONTRACTION", 0.1)
    monkeypatch.setattr(hlo_audit, "FRAGMENTATION_MIN_OPS", 1000)
    assert run_hlo_rules(hp) == ([], [])


def test_layout_churn_golden_and_clean(monkeypatch):
    from ncnet_tpu.analysis import hlo_audit
    from ncnet_tpu.analysis.hlo_audit import run_hlo_rules

    hp = _hlo_program(
        entry_ops={"fusion": 10, "transpose": 6, "copy": 3}, contractions=5
    )
    monkeypatch.setattr(hlo_audit, "LAYOUT_CHURN_MIN_OPS", 4)
    monkeypatch.setattr(hlo_audit, "LAYOUT_CHURN_FRACTION", 0.0)
    findings, _ = run_hlo_rules(hp, rules=["layout-churn"])
    assert [f.rule for f in findings] == ["layout-churn"]
    assert findings[0].detail == {
        "transpose": 6, "copy": 3, "entry_ops": 19, "budget": 4,
    }
    # the budget is the MAX of the floor and the fraction term
    monkeypatch.setattr(hlo_audit, "LAYOUT_CHURN_FRACTION", 1.0)
    assert run_hlo_rules(hp, rules=["layout-churn"]) == ([], [])


def test_memory_highwater_golden_and_clean(monkeypatch):
    from ncnet_tpu.analysis import hlo_audit
    from ncnet_tpu.analysis.hlo_audit import run_hlo_rules

    hp = _hlo_program(peak_bytes_est=5000, bytes_in=1000)
    monkeypatch.setattr(hlo_audit, "MEM_HIGHWATER_ABS_FLOOR", 100)
    monkeypatch.setattr(hlo_audit, "MEM_HIGHWATER_INPUT_RATIO", 2.0)
    findings, _ = run_hlo_rules(hp, rules=["memory-highwater"])
    assert [f.rule for f in findings] == ["memory-highwater"]
    assert findings[0].detail["budget"] == 2000
    monkeypatch.setattr(hlo_audit, "MEM_HIGHWATER_INPUT_RATIO", 10.0)
    assert run_hlo_rules(hp, rules=["memory-highwater"]) == ([], [])


def test_hlo_waiver_moves_finding_aside(monkeypatch):
    from ncnet_tpu.analysis import hlo_audit
    from ncnet_tpu.analysis.hlo_audit import run_hlo_rules

    hp = _hlo_program(peak_bytes_est=5000, bytes_in=1000)
    monkeypatch.setattr(hlo_audit, "MEM_HIGHWATER_ABS_FLOOR", 100)
    monkeypatch.setattr(hlo_audit, "MEM_HIGHWATER_INPUT_RATIO", 2.0)
    findings, waived = run_hlo_rules(
        hp, waivers={"memory-highwater": "known gather transient"}
    )
    assert findings == []
    assert [f.rule for f in waived] == ["memory-highwater"]


def test_parse_entry_opcodes_excludes_fusion_bodies():
    from ncnet_tpu.analysis.hlo_audit import parse_entry_opcodes

    hlo = """\
HloModule jit_f

%fused_computation (p0: f32[4]) -> f32[4] {
  %p0 = f32[4] parameter(0)
  %t = f32[4] transpose(%p0), dimensions={0}
  ROOT %m = f32[4] multiply(%t, %t)
}

ENTRY %main (a: f32[4]) -> (f32[4]) {
  %a = f32[4] parameter(0)
  %fus = f32[4] fusion(%a), kind=kLoop, calls=%fused_computation
  %d = f32[4] add(%fus, %a)
  ROOT %out = (f32[4]) tuple(%d)
}
"""
    ops = parse_entry_opcodes(hlo)
    # the transpose/multiply live INSIDE the fusion body — not launches
    assert ops == {"parameter": 1, "fusion": 1, "add": 1, "tuple": 1}
    with pytest.raises(ValueError, match="ENTRY"):
        parse_entry_opcodes("HloModule empty")


def test_jaxpr_memory_highwater_linear_chain():
    """x -> y -> z chain of [4,4] f32: peak is two 64-byte buffers live
    across one equation (alloc-at-def, free-after-last-use)."""
    from ncnet_tpu.analysis.hlo_audit import jaxpr_memory_highwater

    def f(x):
        y = x * 2.0
        return y + 1.0

    jaxpr = jax.make_jaxpr(f)(jnp.zeros((4, 4), jnp.float32)).jaxpr
    assert jaxpr_memory_highwater(jaxpr) == 128


def test_audit_hlo_integration_real_program():
    """The end-to-end HLO pass on a real registered program: compiles,
    reports the HLO columns, and is finding-free at the seed budgets."""
    result = audit(["eval/match"], hlo=True)
    assert result.all_findings == []
    (report,) = [r for r in result.reports if r["program"] == "eval/match"]
    for key in ("hlo_entry_ops", "hlo_fusions", "hlo_churn",
                "mem_highwater_est", "compile_seconds"):
        assert key in report, key
    assert report["hlo_entry_ops"] > 0
    assert report["mem_highwater_est"] > 0
    table = format_report_table(result.reports)
    assert "fusions" in table and "mem(hw)" in table
