"""Serving fleet (PR 11): router placement + fleet-wide admission
(shed only when NO replica meets the budget), device-pinned replicas
that never cross-dispatch, the replica chaos drill (kill mid-load:
every accepted future resolves exactly once, queued work requeues onto
survivors, zero post-warmup recompiles), quarantine/rejoin with
re-warmup, the batch-axis shard_map program's bitwise parity contract,
fleet throughput scaling on sleep-dominated load, and the merged
{replica=R} telemetry view."""

import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.analysis import concurrency
from ncnet_tpu.parallel.mesh import make_batch_sharded_apply, make_mesh
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.resilience.faultinject import InjectedFault
from ncnet_tpu.serve.fleet import _Request
from ncnet_tpu.serve import (
    DeadlineExceeded,
    FleetRouter,
    LatencyEstimator,
    ReplicaDown,
    ReplicaView,
    RequestShed,
    ServeEngine,
    ServeFleet,
    ServeResilienceError,
)
from ncnet_tpu.telemetry import trace
from ncnet_tpu.telemetry.session import TelemetrySession

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))  # for scripts.telemetry_report

from scripts.telemetry_report import (  # noqa: E402
    aggregate_spans,
    final_metrics,
    load_events,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.clear()
    yield
    faultinject.clear()


# decided at IMPORT time from NCNET_LOCK_AUDIT so a plain run stays on
# bare threading.Lock (zero audit overhead in the tier-1 suite)
_LOCK_AUDIT = concurrency.is_enabled()


@pytest.fixture(autouse=True)
def _lock_audit_sweep():
    """Under ``NCNET_LOCK_AUDIT=1`` every fleet test — the chaos drills
    in particular — doubles as a schedule-exploration run: all serve
    locks are instrumented, a seeded fuzzer perturbs interleavings, and
    any observed lock-order cycle fails the test that produced it."""
    if not _LOCK_AUDIT:
        yield
        return
    concurrency.clear()
    concurrency.enable()
    with concurrency.ScheduleFuzzer(seed=1311, p=0.25, max_sleep_s=5e-5):
        yield
    cycles = concurrency.find_cycles()
    assert cycles == [], (
        f"lock-order cycle(s) under audit: {cycles}\n"
        + "\n".join(f.format() for f in concurrency.lock_findings())
    )
    concurrency.clear()


TOY_PARAMS = {"w": jnp.asarray(3.0, jnp.float32)}
KEY = ("k", 2)
SPEC = {"x": ((2,), np.float32)}


def _toy_apply(p, batch):
    return {"y": batch["x"] * p["w"]}


def _toy_fleet(**kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait", 0.002)
    return ServeFleet(_toy_apply, TOY_PARAMS, **kw)


def _toy_payload(n, fill):
    return {"x": np.full((n,), fill, np.float32)}


def _identity(stats):
    """The fleet's exactly-once ledger: every accepted request lands in
    exactly one outcome counter."""
    assert stats["submitted"] == (
        stats["completed"] + stats["failed"] + stats["shed"]
        + stats["deadline_exceeded"] + stats["requeued_then_completed"]
    )


def _view(rid, est_s=None, queued=0, keys=(), max_wait=0.005,
          max_batch=8):
    est = LatencyEstimator()
    if est_s is not None:
        est.observe(KEY, est_s)
    return ReplicaView(
        rid, estimator=est, queued_fn=lambda: queued,
        keys_fn=lambda: tuple(keys), max_wait=max_wait,
        max_batch=max_batch,
    )


# ----------------------------------------------------------------------
# router: placement + fleet-wide admission policy


def test_replica_down_taxonomy():
    exc = ReplicaDown("m", replica=3, dispatched=True)
    assert isinstance(exc, ServeResilienceError)
    assert not isinstance(exc, RequestShed)  # a failure, not a choice
    assert exc.replica == 3 and exc.dispatched
    assert not ReplicaDown("m").dispatched


def test_router_unavailable_when_no_replicas():
    with pytest.raises(RequestShed) as ei:
        FleetRouter().route([])
    assert ei.value.reason == "unavailable"


def test_router_sheds_only_when_no_replica_meets_deadline():
    router = FleetRouter()
    slow = _view(0, est_s=2.0)
    fast = _view(1, est_s=1.0)
    # even the best ETA misses the budget -> fleet-wide admission shed
    with pytest.raises(RequestShed) as ei:
        router.route([slow, fast], key=KEY, deadline_s=0.5)
    exc = ei.value
    assert exc.reason == "admission"
    assert exc.estimated_s == pytest.approx(1.005, rel=0.01)
    assert exc.retry_after_s == exc.estimated_s
    # one replica CAN meet it: route there, never shed
    assert router.route([slow, fast], key=KEY, deadline_s=1.5).replica == 1
    # a BLIND replica admits: estimator-less capacity must attract
    # traffic (or it never gets a sample), same contract as the engine
    blind = _view(2)
    chosen = router.route([slow, fast, blind], key=KEY, deadline_s=0.5)
    assert chosen.replica == 2


def test_router_prefers_min_eta_and_backlog_scales_it():
    router = FleetRouter()
    # same EWMA, but replica 0 has a full max_batch of queued work: its
    # ETA doubles and replica 1 wins
    busy = _view(0, est_s=1.0, queued=8)
    idle = _view(1, est_s=1.0)
    assert router.route([busy, idle], key=KEY).replica == 1
    assert router.last_decision["replica"] == 1
    assert not router.last_decision["affinity"]


def test_router_bucket_affinity_within_slack_only():
    router = FleetRouter(affinity_slack=1.5)
    plain = _view(0, est_s=1.0)
    half_batch = _view(1, est_s=1.0, keys=(KEY,))
    chosen = router.route([plain, half_batch], key=KEY)
    assert chosen.replica == 1  # completes the half-filled batch
    assert router.last_decision["affinity"]
    # affinity may NOT trade more than the slack bound of latency
    laggard = _view(2, est_s=5.0, keys=(KEY,))
    assert router.route([plain, laggard], key=KEY).replica == 0


def test_router_round_robin_spreads_idle_fleet():
    router = FleetRouter()
    views = [_view(i) for i in range(4)]  # all blind, all equal
    chosen = {router.route(views).replica for _ in range(8)}
    assert chosen == {0, 1, 2, 3}


# ----------------------------------------------------------------------
# device pinning: co-resident engines never cross-dispatch


def test_fleet_engines_pinned_one_per_device():
    devices = jax.devices()
    assert len(devices) >= 4, "conftest provisions the 8-device proxy mesh"
    fleet = _toy_fleet(replicas=4)
    try:
        engines = fleet.engines()
        for rid, eng in engines.items():
            for leaf in jax.tree_util.tree_leaves(eng._params):
                assert leaf.devices() == {devices[rid]}, (
                    f"replica {rid} params not pinned to its device"
                )
        fleet.warmup([(KEY, SPEC)])
        futs = [
            fleet.submit(key=KEY, payload=_toy_payload(2, float(i)))
            for i in range(16)
        ]
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(
                np.asarray(f.result(timeout=10)["y"]),
                np.full((2,), 3.0 * i, np.float32),
            )
    finally:
        fleet.close()


# ----------------------------------------------------------------------
# accounting identity


def test_fleet_accounting_identity_across_outcomes():
    fleet = _toy_fleet(replicas=2)
    try:
        fleet.warmup([(KEY, SPEC)])
        futs = [
            fleet.submit(key=KEY, payload=_toy_payload(2, 1.0))
            for _ in range(10)
        ]
        # an already-expired budget resolves typed at the route stage
        dead = fleet.submit(
            key=KEY, payload=_toy_payload(2, 1.0), deadline_s=-1.0
        )
        for f in futs:
            f.result(timeout=10)
        with pytest.raises(DeadlineExceeded) as ei:
            dead.result(timeout=10)
        assert ei.value.stage == "route"
        # an injected routing crash resolves the future, never raises
        # into the caller
        faultinject.inject("serve.router.route", "crash", at=1)
        broken = fleet.submit(key=KEY, payload=_toy_payload(2, 1.0))
        assert isinstance(broken.exception(timeout=10), InjectedFault)
    finally:
        fleet.close()
    stats = fleet.report()
    assert stats["submitted"] == 12
    assert stats["completed"] == 10
    assert stats["deadline_exceeded"] == 1
    assert stats["failed"] == 1
    _identity(stats)


def test_fleet_close_resolves_everything_and_refuses_new_work():
    fleet = _toy_fleet(replicas=2)
    fleet.warmup([(KEY, SPEC)])
    futs = [
        fleet.submit(key=KEY, payload=_toy_payload(2, 1.0))
        for _ in range(8)
    ]
    fleet.close()
    assert all(f.done() for f in futs)
    _identity(fleet.report())
    with pytest.raises(RuntimeError):
        fleet.submit(key=KEY, payload=_toy_payload(2, 1.0))
    fleet.close()  # idempotent


# ----------------------------------------------------------------------
# the chaos drill: kill a replica mid-load


def test_fleet_chaos_drill_replica_kill_mid_load():
    fleet = _toy_fleet(replicas=4)
    try:
        fleet.warmup([(KEY, SPEC)])
        # the 10th dispatch kills its routed-to replica under real load
        faultinject.inject("serve.replica.kill", "crash", at=10)
        futs = [
            fleet.submit(key=KEY, payload=_toy_payload(2, float(i)))
            for i in range(60)
        ]
        outcomes = {"ok": 0, "down": 0}
        for i, f in enumerate(futs):
            try:
                np.testing.assert_array_equal(
                    np.asarray(f.result(timeout=10)["y"]),
                    np.full((2,), 3.0 * i, np.float32),
                )
                outcomes["ok"] += 1
            except ReplicaDown as exc:
                # only a batch already ON the dead device may fail;
                # queued work must requeue instead
                assert exc.dispatched
                outcomes["down"] += 1
        # every accepted future resolved exactly once
        assert all(f.done() for f in futs)
        assert outcomes["ok"] + outcomes["down"] == 60
        stats = fleet.report()
        _identity(stats)
        assert stats["replicas_down"] == 1
        assert len(stats["quarantined"]) == 1
        assert len(stats["healthy"]) == 3
        # survivors keep their warm caches: zero recompiles fleet-wide
        for rid, rep in stats["per_replica"].items():
            assert rep["recompiles_after_warmup"] == 0, f"replica {rid}"
    finally:
        fleet.close()


def test_fleet_quarantine_rejoin_zero_recompiles():
    fleet = _toy_fleet(replicas=3)
    try:
        fleet.warmup([(KEY, SPEC)])
        faultinject.inject("serve.replica.kill", "crash", at=5)
        futs = [
            fleet.submit(key=KEY, payload=_toy_payload(2, 1.0))
            for i in range(20)
        ]
        for f in futs:
            try:
                f.result(timeout=10)
            except ReplicaDown:
                pass
        faultinject.clear()
        dead = fleet.quarantined_ids()
        assert len(dead) == 1
        # rejoin: fresh engine, same device, re-warmed from the fleet's
        # recorded specs BEFORE it takes traffic
        n = fleet.rejoin(dead[0])
        assert n > 0
        with pytest.raises(ValueError):
            fleet.rejoin(dead[0])  # healthy again: a double rejoin is a bug
        assert fleet.quarantined_ids() == []
        assert fleet.replica_ids() == [0, 1, 2]
        futs = [
            fleet.submit(key=KEY, payload=_toy_payload(2, 2.0))
            for i in range(20)
        ]
        for f in futs:
            f.result(timeout=10)
        stats = fleet.report()
        _identity(stats)
        assert stats["rejoins"] == 1
        # the rejoined replica included: zero post-warmup recompiles
        # survive a kill + rejoin cycle
        for rid, rep in stats["per_replica"].items():
            assert rep["recompiles_after_warmup"] == 0, f"replica {rid}"
    finally:
        fleet.close()


def test_fleet_watchdog_kills_hung_replica_and_fleet_survives():
    # the replica_hang_timeout supervision path: a device call that
    # never returns must be declared dead BY THE WATCHDOG (not an
    # injected fault), its in-flight future failed typed, and the fleet
    # keep serving on survivors. Regression: kill_replica runs ON the
    # watchdog thread; Watchdog.stop must not try to join itself, or
    # the kill dies mid-flight and the poison future below hangs.
    release = threading.Event()

    def hang_apply(p, batch):
        def maybe_hang(x):
            if float(x.ravel()[0]) < 0:
                release.wait(30.0)  # "wedged device" until test teardown
            return x

        y = jax.pure_callback(
            maybe_hang,
            jax.ShapeDtypeStruct(batch["x"].shape, batch["x"].dtype),
            batch["x"],
        )
        return {"y": y * p["w"]}

    fleet = ServeFleet(
        hang_apply, TOY_PARAMS, replicas=3, max_batch=1, max_wait=0.001,
        replica_hang_timeout=0.25,
    )
    try:
        fleet.warmup([(KEY, SPEC)])
        poison = fleet.submit(key=KEY, payload=_toy_payload(2, -1.0))
        with pytest.raises(ReplicaDown) as ei:
            poison.result(timeout=15)
        assert ei.value.dispatched  # on-device when killed: lost, typed
        # kill_replica quarantines BEFORE it fails futures, so the dead
        # replica is already out of routing
        assert len(fleet.quarantined_ids()) == 1
        assert len(fleet.replica_ids()) == 2
        futs = [
            fleet.submit(key=KEY, payload=_toy_payload(2, float(i)))
            for i in range(12)
        ]
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(
                np.asarray(f.result(timeout=10)["y"]),
                np.full((2,), 3.0 * i, np.float32),
            )
        stats = fleet.report()
        _identity(stats)
        assert stats["replicas_down"] == 1
        for rid, rep in stats["per_replica"].items():
            assert rep["recompiles_after_warmup"] == 0, f"replica {rid}"
    finally:
        release.set()
        fleet.close()


def test_fleet_dispatch_racing_close_sheds_typed():
    # a record that reaches _dispatch_to after close() shut the engines
    # down must shed typed (reason="drain"), not bounce between closed
    # replicas until RecursionError: close() leaves engines in the
    # replica table, so re-routing there can never succeed
    fleet = _toy_fleet(replicas=2)
    fleet.warmup([(KEY, SPEC)])
    rid = fleet.replica_ids()[0]
    fleet.close()
    record = _Request(None, KEY, _toy_payload(2, 1.0), None)
    with fleet._pending_lock:
        fleet._pending.add(record)
    fleet._dispatch_to(rid, record)
    with pytest.raises(RequestShed) as ei:
        record.future.result(timeout=5)
    assert ei.value.reason == "drain"


# ----------------------------------------------------------------------
# fleet scaling: 8 replicas vs 1 on the same synthetic load


def _sleep_apply(p, batch):
    # sleep-dominated device stage: a host callback that sleeps stands
    # in for a TPU chip's compute — the CPU proxy has ONE core, so only
    # a GIL-releasing sleep makes 8 virtual devices truly concurrent
    def host_sleep(x):
        time.sleep(0.08)
        return x

    y = jax.pure_callback(
        host_sleep,
        jax.ShapeDtypeStruct(batch["x"].shape, batch["x"].dtype),
        batch["x"],
    )
    return {"y": y * p["w"]}


def _timed_fleet_run(replicas, n_requests):
    fleet = ServeFleet(
        _sleep_apply, TOY_PARAMS, replicas=replicas,
        max_batch=1, max_wait=0.001,
    )
    try:
        fleet.warmup([(KEY, SPEC)])
        t0 = time.perf_counter()
        futs = [
            fleet.submit(key=KEY, payload=_toy_payload(2, 1.0))
            for _ in range(n_requests)
        ]
        for f in futs:
            f.result(timeout=60)
        wall = time.perf_counter() - t0
        _identity(fleet.report())
    finally:
        fleet.close()
    return wall


def test_fleet_scaling_8x_replicas_beats_5x():
    assert len(jax.devices()) >= 8
    n = 32
    wall_1 = _timed_fleet_run(1, n)   # serial: >= 32 * 80ms
    wall_8 = _timed_fleet_run(8, n)   # ~4 sleeps per replica
    speedup = wall_1 / wall_8
    assert speedup >= 5.0, (
        f"8 replicas gave only {speedup:.1f}x over 1 "
        f"({wall_1:.2f}s -> {wall_8:.2f}s)"
    )


# ----------------------------------------------------------------------
# batch-axis shard_map: the parity contract


def _dot_apply(p, batch):
    # a reduction makes parity meaningful: codegen differences between
    # programs would show up in the contraction's float associativity
    return {"y": jnp.dot(batch["x"], p["w"])}


def test_shard_map_bitwise_parity_per_shard():
    mesh = make_mesh()
    n = mesh.size
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.standard_normal(4).astype(np.float32))}
    x = rng.standard_normal((n, 4)).astype(np.float32)
    sharded = jax.jit(make_batch_sharded_apply(_dot_apply, mesh))
    single = jax.jit(_dot_apply)
    out = np.asarray(sharded(params, {"x": x})["y"])
    # the contract: bitwise the single-device program applied per shard
    # and concatenated (across different batch SIZES only few-ulp
    # associativity is promised — PR 6 pins that separately)
    per_shard = np.concatenate([
        np.asarray(single(params, {"x": x[i:i + 1]})["y"])
        for i in range(n)
    ])
    assert np.array_equal(out, per_shard)


def test_engine_sharded_dispatch_bitwise_and_warm():
    mesh = make_mesh()
    n = mesh.size
    rng = np.random.default_rng(1)
    params = {"w": jnp.asarray(rng.standard_normal(4).astype(np.float32))}
    key = ("dot", 4)
    spec = {"x": ((4,), np.float32)}
    engine = ServeEngine(
        _dot_apply, params, max_batch=n, max_wait=0.5,
        shard_mesh=mesh, shard_min_batch=n,
    )
    try:
        engine.warmup([(key, spec)])
        xs = [rng.standard_normal(4).astype(np.float32) for _ in range(n)]
        futs = [
            engine.submit(key=key, payload={"x": x.copy()}) for x in xs
        ]
        results = [np.asarray(f.result(timeout=30)["y"]) for f in futs]
        single = jax.jit(_dot_apply)
        for x, got in zip(xs, results):
            want = np.asarray(single(params, {"x": x[None]})["y"])[0]
            assert np.array_equal(got, want)
        stats = engine.report()
        assert stats["sharded_batches"] >= 1
        assert stats["recompiles_after_warmup"] == 0
    finally:
        engine.close()


def test_engine_small_batches_stay_single_device():
    mesh = make_mesh()
    n = mesh.size
    engine = ServeEngine(
        _toy_apply, TOY_PARAMS, max_batch=n, max_wait=0.001,
        shard_mesh=mesh, shard_min_batch=n,
    )
    try:
        engine.warmup([(KEY, SPEC)])
        # a lone request pads to 1: not divisible by the mesh, so the
        # single-device program serves it — no cross-device batch of one
        fut = engine.submit(key=KEY, payload=_toy_payload(2, 5.0))
        np.testing.assert_array_equal(
            np.asarray(fut.result(timeout=10)["y"]),
            np.full((2,), 15.0, np.float32),
        )
        stats = engine.report()
        assert stats["sharded_batches"] == 0
        assert stats["recompiles_after_warmup"] == 0
    finally:
        engine.close()


# ----------------------------------------------------------------------
# telemetry: one fleet view with {replica=R} tags


def test_fleet_telemetry_merged_replica_view(tmp_path):
    sess = TelemetrySession(str(tmp_path), label="fleet")
    fleet = None
    try:
        fleet = _toy_fleet(replicas=2)
        for rid, eng in fleet.engines().items():
            sess.add_registry(eng.metrics, tags={"replica": rid})
        fleet.warmup([(KEY, SPEC)])
        futs = [
            fleet.submit(key=KEY, payload=_toy_payload(2, 1.0))
            for _ in range(12)
        ]
        for f in futs:
            f.result(timeout=10)
        fleet.close()
    finally:
        if fleet is not None:
            fleet.close()
        sess.stop()
        trace.disable()
        trace.drain()
    events = load_events(str(tmp_path))
    # metrics: one final value PER replica, keyed with the tag — private
    # registries kept the totals apart, the tags keep them attributable
    metrics = final_metrics(events)
    per_replica = [
        metrics[f"serve_requests_submitted_total{{replica={r}}}"]["value"]
        for r in (0, 1)
    ]
    assert sum(per_replica) == 12
    assert all(v > 0 for v in per_replica)  # the router spread the load
    # spans: worker threads carried their replica tag into the log
    spans = aggregate_spans(events)
    tagged = [p for p in spans if "{replica=" in p]
    assert tagged, f"no replica-tagged spans in {sorted(spans)[:8]}"
