"""ncnet_tpu.serve: bucket parity with eval/inloc, micro-batcher policy
(deterministic via an injected fake clock), engine compile discipline
(zero recompiles after warmup, counted at trace time), the padded-batch
numerical contract (padding bitwise-masked; lone requests bitwise the
per-pair pipeline; cross-batch-size agreement to XLA codegen ulps) for
dense AND sparse NC, backpressure, fault-isolated requests, and the
serving PF-Pascal eval."""

import json
import os
import queue
import subprocess
import sys
import time
from concurrent.futures import Future
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.resilience.faultinject import InjectedFault
from ncnet_tpu.serve import (
    SCALE_FACTOR,
    BucketSpec,
    MicroBatcher,
    ServeEngine,
    default_batch_sizes,
    make_serve_match_step,
    pair_bucket,
    payload_spec,
    quantized_resize_shape,
    request_buckets,
)
from ncnet_tpu.serve.batcher import Request, pad_size

REPO = Path(__file__).resolve().parent.parent

TINY = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.clear()
    yield
    faultinject.clear()


# ----------------------------------------------------------------------
# buckets: one resize rule, shared with eval/inloc


def test_inloc_resize_rule_is_serve_buckets():
    """inloc must consume THE shared rule, not a drifted copy."""
    from ncnet_tpu.eval import inloc

    assert inloc.quantized_resize_shape is quantized_resize_shape
    assert inloc.SCALE_FACTOR == SCALE_FACTOR


def test_bucket_spec_matches_rule_and_quantizes():
    spec = BucketSpec(3200, 2)
    for h, w in [(1600, 1200), (1201, 1600), (999, 1333), (3200, 2400)]:
        assert spec.bucket(h, w) == quantized_resize_shape(h, w, 3200, 2)
        bh, bw = spec.bucket(h, w)
        # feature grid (stride 16) divides k_size=2
        assert bh % 32 == 0 and bw % 32 == 0
    # k_size <= 1: plain aspect-preserving integer resize
    assert BucketSpec(3200, 1).bucket(1600, 1200) == (3200, 2400)


def test_request_buckets_distinct_sorted():
    spec = BucketSpec(64, 1)
    pairs = [
        ((480, 640), (640, 480)),
        ((481, 641), (640, 480)),  # same bucket after quantization
        ((640, 480), (480, 640)),  # reversed directions: distinct key
    ]
    keys = request_buckets(spec, pairs)
    assert len(keys) == 2
    assert keys == sorted(keys)
    assert pair_bucket(spec, (480, 640), (640, 480)) in keys
    assert pair_bucket(spec, (481, 641), (640, 480)) in keys  # same key
    assert pair_bucket(spec, (640, 480), (480, 640)) in keys


# ----------------------------------------------------------------------
# batcher: policy under a fake clock (no sleeps)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _req(key, i=0):
    return Request(key, {"x": np.full((2,), i, np.float32)}, Future(), 0.0)


def test_default_batch_sizes():
    assert default_batch_sizes(1) == (1,)
    assert default_batch_sizes(8) == (1, 2, 4, 8)
    assert default_batch_sizes(6) == (1, 2, 4, 6)
    with pytest.raises(ValueError):
        default_batch_sizes(0)


def test_pad_size():
    assert pad_size(3, (1, 2, 4, 8)) == 4
    assert pad_size(8, (1, 2, 4, 8)) == 8
    with pytest.raises(ValueError):
        pad_size(9, (1, 2, 4, 8))


def test_batcher_cap_flush():
    clk = FakeClock()
    mb = MicroBatcher(max_batch=4, max_wait=10.0, clock=clk)
    assert all(mb.add(_req("A", i)) is None for i in range(3))
    batch = mb.add(_req("A", 3))
    assert batch is not None
    assert batch.key == "A"
    assert len(batch.requests) == 4 and batch.pad_to == 4
    assert batch.occupancy == 1.0
    assert mb.pending() == 0


def test_batcher_keys_do_not_mix():
    clk = FakeClock()
    mb = MicroBatcher(max_batch=2, max_wait=10.0, clock=clk)
    assert mb.add(_req("A")) is None
    assert mb.add(_req("B")) is None
    batch = mb.add(_req("A"))  # fills A only
    assert batch.key == "A" and len(batch.requests) == 2
    assert mb.pending() == 1  # B still waiting


def test_batcher_deadline_flush_and_padding():
    clk = FakeClock()
    mb = MicroBatcher(max_batch=8, max_wait=0.1, clock=clk)
    for i in range(3):
        mb.add(_req("A", i))
    assert mb.ready(now=0.05) == []
    assert mb.next_deadline(now=0.05) == pytest.approx(0.05)
    clk.t = 0.1
    (batch,) = mb.ready()
    assert len(batch.requests) == 3 and batch.pad_to == 4  # padded up
    assert batch.occupancy == 0.75
    assert mb.next_deadline() is None and mb.pending() == 0


def test_batcher_drain():
    clk = FakeClock()
    mb = MicroBatcher(max_batch=8, max_wait=10.0, clock=clk)
    mb.add(_req("A"))
    mb.add(_req("B"))
    batches = mb.drain()
    assert {b.key for b in batches} == {"A", "B"}
    assert mb.pending() == 0 and mb.drain() == []


# ----------------------------------------------------------------------
# engine mechanics on a trivial apply fn (fast: no model)


def _toy_engine(**kw):
    params = {"w": jnp.asarray(3.0, jnp.float32)}

    def apply(p, batch):
        return {"y": batch["x"] * p["w"]}

    return ServeEngine(apply, params, **kw)


def _toy_payload(n, fill):
    return {"x": np.full((n,), fill, np.float32)}


def test_engine_zero_recompiles_after_warmup():
    """Warmup compiles every (bucket, padded size); mixed live traffic —
    full batches, deadline partials, a second bucket — must then trigger
    ZERO traces (the counting-jit assertion) and report it."""
    with _toy_engine(max_batch=4, max_wait=0.01) as eng:
        eng.warmup(
            [
                ("A", payload_spec(_toy_payload(4, 0.0))),
                ("B", payload_spec(_toy_payload(6, 0.0))),
            ]
        )
        warm_traces = eng.compile_count
        assert warm_traces == 2 * len(default_batch_sizes(4))  # 2 keys x (1,2,4)

        futs = [
            eng.submit(key="A", payload=_toy_payload(4, float(i)))
            for i in range(7)  # one full batch of 4 + a deadline partial of 3
        ]
        futs.append(eng.submit(key="B", payload=_toy_payload(6, 9.0)))
        for i, f in enumerate(futs[:7]):
            np.testing.assert_array_equal(
                f.result(timeout=30)["y"], np.full((4,), 3.0 * i, np.float32)
            )
        np.testing.assert_array_equal(
            futs[7].result(timeout=30)["y"], np.full((6,), 27.0, np.float32)
        )
        stats = eng.report()
    assert eng.compile_count == warm_traces  # nothing retraced
    assert stats["recompiles_after_warmup"] == 0
    assert stats["completed"] == 8 and stats["failed"] == 0
    assert stats["real_samples"] == 8
    # 7 A-requests flush as 4 + 3-padded-to-4; the lone B pads to 1
    assert stats["padded_samples"] >= stats["real_samples"]
    assert 0.0 < stats["mean_occupancy"] <= 1.0


def test_engine_counts_unwarmed_bucket_as_recompile():
    with _toy_engine(max_batch=2, max_wait=0.005) as eng:
        eng.warmup([("A", payload_spec(_toy_payload(4, 0.0)))])
        fut = eng.submit(key="B", payload=_toy_payload(5, 1.0))  # never warmed
        np.testing.assert_array_equal(
            fut.result(timeout=30)["y"], np.full((5,), 3.0, np.float32)
        )
        stats = eng.report()
    assert stats["recompiles_after_warmup"] == 1


def test_engine_backpressure_queue_full():
    """The bounded submit queue rejects (timeout=0) while prep is stalled
    by an injected per-request delay — and every ACCEPTED request still
    resolves on close."""
    faultinject.inject("serve.request", "delay", arg=0.3)
    eng = _toy_engine(max_batch=2, max_wait=0.005, queue_limit=1, host_workers=1)
    try:
        accepted = []
        with pytest.raises(queue.Full):
            for i in range(4):  # limit 1 + one in-flight: must refuse by #4
                accepted.append(
                    eng.submit(key="A", payload=_toy_payload(3, float(i)), timeout=0)
                )
        assert 1 <= len(accepted) <= 3
    finally:
        faultinject.clear()  # let the drain run undelayed
        eng.close()
    for i, f in enumerate(accepted):
        np.testing.assert_array_equal(
            f.result(timeout=5)["y"], np.full((3,), 3.0 * i, np.float32)
        )


def test_engine_slow_request_does_not_stall_others():
    """A single injected-slow request (serve.request delay on hit 1) must
    not block later requests: with 2 host workers the fast ones flush and
    resolve while the slow one is still sleeping."""
    faultinject.inject("serve.request", "delay", arg=2.0, at=1)
    with _toy_engine(max_batch=4, max_wait=0.01, host_workers=2) as eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        t0 = time.monotonic()
        slow = eng.submit(key="A", payload=_toy_payload(3, 0.0))
        fast = [
            eng.submit(key="A", payload=_toy_payload(3, float(i)))
            for i in range(1, 4)
        ]
        for f in fast:
            f.result(timeout=5)
        assert time.monotonic() - t0 < 1.5  # well under the 2 s delay
        assert not slow.done()
        slow.result(timeout=10)  # and the slow one still completes


def test_engine_crash_fault_fails_only_that_request():
    faultinject.inject("serve.request", "crash", at=2)
    with _toy_engine(max_batch=2, max_wait=0.005, host_workers=1) as eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        futs = [
            eng.submit(key="A", payload=_toy_payload(3, float(i)))
            for i in range(3)
        ]
        with pytest.raises(InjectedFault):
            futs[1].result(timeout=10)
        for i in (0, 2):
            np.testing.assert_array_equal(
                futs[i].result(timeout=10)["y"],
                np.full((3,), 3.0 * i, np.float32),
            )
        stats = eng.report()
    assert stats["failed"] == 1 and stats["completed"] == 2


def test_engine_prep_retry_uses_loader_machinery():
    """A transiently-failing prep succeeds under ``prep_retries`` (the
    data loader's `retry_call`); with retries 0 it fails the future."""
    calls = {"n": 0}

    def flaky_prep(raw):
        calls["n"] += 1
        if calls["n"] % 2 == 1:  # every first attempt fails
            raise OSError("transient decode failure")
        return ("A", _toy_payload(3, float(raw)))

    with _toy_engine(
        max_batch=2, max_wait=0.005, host_workers=1,
        prep_fn=flaky_prep, prep_retries=2, retry_backoff=0.0,
    ) as eng:
        fut = eng.submit(7.0)
        np.testing.assert_array_equal(
            fut.result(timeout=10)["y"], np.full((3,), 21.0, np.float32)
        )
        assert eng.report()["failed"] == 0
    with _toy_engine(
        max_batch=2, max_wait=0.005, host_workers=1, prep_fn=flaky_prep
    ) as eng:
        fut = eng.submit(7.0)  # calls["n"] odd again: first attempt fails
        with pytest.raises(OSError, match="transient"):
            fut.result(timeout=10)
        assert eng.report()["failed"] == 1


def test_engine_submit_after_close_raises():
    eng = _toy_engine(max_batch=2)
    eng.close()
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(key="A", payload=_toy_payload(2, 0.0))
    eng.close()  # idempotent


# ----------------------------------------------------------------------
# the tentpole guarantee: padded batches == per-pair pipeline, bitwise


@pytest.mark.parametrize("topk", [0, 8], ids=["dense", "nc_topk8"])
def test_padded_batch_bitwise_parity(topk):
    """The engine's numerical contract, dense AND sparse NC band:

    * stacking/padding/readout are EXACT — a served batch returns
      bitwise what the same compiled program returns on the same padded
      array (padding rows never perturb real rows);
    * a lone request (padded to bs 1) is bitwise the per-pair jit;
    * across different batch sizes results agree to the few-ulp
      float-associativity of XLA's batch-size-dependent codegen (the
      only permitted difference — NOT a padding leak);
    * zero recompiles after warmup under this mixed traffic.

    The patch16 trunk keeps the 8 traces this needs (per-pair + batched
    references x two buckets + warmup) off the resnet101 compile cost —
    stack/pad/mask/readout exactness is trunk-independent.
    """
    cfg = TINY.replace(feature_extraction_cnn="patch16", nc_topk=topk)
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    apply_fn = make_serve_match_step(cfg)
    rng = np.random.RandomState(7)

    def pair(src_hw, tgt_hw):
        return {
            "source_image": rng.rand(*src_hw, 3).astype(np.float32),
            "target_image": rng.rand(*tgt_hw, 3).astype(np.float32),
        }

    # bucket A x4 (one full batch), bucket B x3 (padded 3 -> 4)
    payloads = [pair((32, 48), (48, 32)) for _ in range(4)]
    payloads += [pair((48, 32), (32, 48)) for _ in range(3)]
    keys = [
        (p["source_image"].shape, p["target_image"].shape) for p in payloads
    ]

    ref = jax.jit(apply_fn)
    per_pair = [
        np.asarray(ref(params, {k: v[None] for k, v in p.items()})["matches"])[0]
        for p in payloads
    ]

    def stacked(plist, pad_to):
        rows = [p for p in plist] + [plist[-1]] * (pad_to - len(plist))
        return {
            name: np.stack([p[name] for p in rows]) for name in plist[0]
        }

    # same-program references: full bs-4 batch for A, padded bs-4 (3 real
    # + replicated pad row) for B — what stack/pad/slice must reproduce
    expected_a = np.asarray(ref(params, stacked(payloads[:4], 4))["matches"])
    expected_b = np.asarray(ref(params, stacked(payloads[4:], 4))["matches"])[:3]

    # batch_sizes (1, 4): bs 2 is irrelevant to this traffic, and each
    # avoided warmup trace saves seconds of tier-1 budget
    with ServeEngine(
        apply_fn, params, max_batch=4, max_wait=0.05, batch_sizes=(1, 4)
    ) as eng:
        eng.warmup(
            {k: (k, payload_spec(p)) for k, p in zip(keys, payloads)}.values()
        )
        warm_traces = eng.compile_count
        futs = [
            eng.submit(key=k, payload=p) for k, p in zip(keys, payloads)
        ]
        results = [f.result(timeout=120)["matches"] for f in futs]
        # a lone request flushes alone at the deadline: bs-1 program,
        # bitwise the per-pair pipeline
        lone = eng.submit(key=keys[0], payload=payloads[0])
        lone_result = lone.result(timeout=120)["matches"]
        stats = eng.report()

    np.testing.assert_array_equal(np.stack(results[:4]), expected_a)
    np.testing.assert_array_equal(np.stack(results[4:]), expected_b)
    np.testing.assert_array_equal(lone_result, per_pair[0])
    for got, want in zip(results, per_pair):  # across batch sizes: ulps
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert stats["recompiles_after_warmup"] == 0
    assert eng.compile_count == warm_traces
    assert stats["batches"] == 3 and stats["real_samples"] == 8


def test_evaluate_serving_bitwise_matches_evaluate():
    """The --batch PF-Pascal path: identical per-pair PCK to the
    sequential eval (same step body, padding masked; the patch16 trunk
    keeps the compile cost down, as in the parity test), plus stats."""
    from ncnet_tpu.eval.pf_pascal import evaluate, evaluate_serving

    cfg = TINY.replace(feature_extraction_cnn="patch16")
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)

    rng = np.random.RandomState(2)

    def mk_batch(n, hw):
        h, w = hw
        pts = rng.randint(5, min(h, w) - 5, size=(n, 2, 3)).astype(np.float32)
        pts[:, :, 2] = -1.0  # padded keypoint slot
        size = np.tile(np.asarray([h, w, 3], np.float32), (n, 1))
        return {
            "source_image": rng.rand(n, h, w, 3).astype(np.float32),
            "target_image": rng.rand(n, h, w, 3).astype(np.float32),
            "source_points": pts,
            "target_points": pts.copy(),
            "source_im_size": size,
            "target_im_size": size.copy(),
            "L_pck": np.full((n, 1), 224.0, np.float32),
        }

    # square images (the PCK point transfer's default square grid), full
    # loader batches == the serving cap, so both paths run THE bs-4
    # program; one bucket keeps the warmup to a single program set
    # (multi-bucket traffic is covered by the parity test above)
    loader = [mk_batch(4, (32, 32)), mk_batch(4, (32, 32))]
    seq = evaluate(params, cfg, loader, verbose=False)
    srv = evaluate_serving(
        params, cfg, loader, verbose=False, max_batch=4, max_wait=0.2
    )
    assert srv["per_pair"] == seq["per_pair"]  # exact float equality
    assert srv["n_valid"] == seq["n_valid"]
    assert srv["pck"] == seq["pck"]
    assert srv["serve"]["recompiles_after_warmup"] == 0
    assert srv["serve"]["completed"] == 8


# ----------------------------------------------------------------------
# CLI smoke: scripts/serve.py end to end on a tiny checkpoint


def test_serve_cli_smoke(tmp_path):
    from PIL import Image

    from ncnet_tpu.train.checkpoint import CheckpointData, save_checkpoint

    cfg = TINY.replace(feature_extraction_cnn="patch16")
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    ckpt = tmp_path / "tiny.msgpack"
    save_checkpoint(
        str(ckpt),
        CheckpointData(config=cfg, params=params, opt_state=None, epoch=0),
    )

    imgdir = tmp_path / "imgs"
    imgdir.mkdir()
    rng = np.random.RandomState(0)
    for i in range(4):  # consecutive pairing -> 2 requests, one bucket
        Image.fromarray(
            rng.randint(0, 255, (48, 64, 3), np.uint8)
        ).save(imgdir / f"im{i}.png")

    report_path = tmp_path / "report.json"
    telem_dir = tmp_path / "telem"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [
            sys.executable, str(REPO / "scripts" / "serve.py"),
            "--checkpoint", str(ckpt),
            "--images", str(imgdir),
            "--image-size", "64",
            "--concurrency", "2",
            "--max-batch", "2",
            "--max-wait-ms", "20",
            "--report", str(report_path),
            "--telemetry", str(telem_dir),
        ],
        capture_output=True, text=True, env=env, cwd=str(REPO), timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    report = json.loads(report_path.read_text())
    assert report["mode"] == "serve"
    assert report["completed"] == 2 and report["failed"] == 0
    assert report["recompiles_after_warmup"] == 0
    assert report["buckets"] == 1
    assert report["pairs_per_s"] > 0
    assert report["latency_p95_ms"] >= report["latency_p50_ms"]

    # the same run produced a renderable telemetry log (acceptance
    # criterion: one --telemetry flag -> a per-process event log +
    # .prom snapshot that telemetry_report.py understands); report
    # rendering runs in-process — it is jax-free by contract
    from ncnet_tpu.telemetry.export import events_name, prom_name, read_events
    from scripts.telemetry_report import render, report as telem_report

    assert (telem_dir / events_name(0)).exists()
    prom = (telem_dir / prom_name(0)).read_text()
    assert "# TYPE serve_requests_completed_total counter" in prom
    assert "serve_requests_completed_total 2" in prom
    assert "# TYPE serve_request_latency_seconds histogram" in prom

    events = read_events(str(telem_dir / events_name(0)))
    kinds = {e["type"] for e in events}
    assert {"meta", "span", "metric"} <= kinds
    agg = telem_report(str(telem_dir))
    # the engine's three pipeline stages all produced spans
    roots = {p.split(">", 1)[0] for p in agg["spans"]}
    assert {"serve/prep", "serve/dispatch", "serve/readout"} <= roots
    assert agg["metrics"]["serve_batches_total"]["value"] >= 1
    text = render(events)
    assert "== serve spans ==" in text and "== metrics ==" in text
