import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet, match_pipeline
from ncnet_tpu.parallel.mesh import make_mesh
from ncnet_tpu.parallel.spatial import make_sharded_match_pipeline

CFG = ImMatchNetConfig(ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1))


@pytest.mark.parametrize("n_shards", [2, 4])
def test_sharded_pipeline_matches_unsharded(n_shards):
    assert len(jax.devices()) >= n_shards
    mesh = make_mesh(
        (n_shards,), ("spatial",), devices=jax.devices()[:n_shards]
    )
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    rng = np.random.RandomState(0)
    # grid rows (8) divisible by shard counts (symmetric mode reshards the
    # B rows too); columns may be ragged
    fa = jnp.asarray(rng.randn(2, 8, 5, 16).astype(np.float32))
    fb = jnp.asarray(rng.randn(2, 8, 7, 16).astype(np.float32))

    want = np.asarray(match_pipeline(params["neigh_consensus"], CFG, fa, fb))

    sharded = make_sharded_match_pipeline(CFG, mesh)
    got = np.asarray(sharded(params["neigh_consensus"], fa, fb))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sharded_pipeline_symmetric_square():
    """Symmetric-mode all_to_all transpose path on a square grid."""
    mesh = make_mesh((4,), ("spatial",), devices=jax.devices()[:4])
    params = init_immatchnet(jax.random.PRNGKey(1), CFG)
    rng = np.random.RandomState(1)
    fa = jnp.asarray(rng.randn(1, 8, 8, 8).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 8, 8).astype(np.float32))
    want = np.asarray(match_pipeline(params["neigh_consensus"], CFG, fa, fb))
    got = np.asarray(make_sharded_match_pipeline(CFG, mesh)(params["neigh_consensus"], fa, fb))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sharded_pipeline_with_relocalization():
    """Sharded fused correlate+maxpool4d: pooled corr AND argmax deltas
    must agree with the unsharded pipeline (the InLoc high-res config)."""
    cfg = CFG.replace(relocalization_k_size=2)
    mesh = make_mesh((2,), ("spatial",), devices=jax.devices()[:2])
    params = init_immatchnet(jax.random.PRNGKey(2), cfg)
    rng = np.random.RandomState(2)
    # A rows = 8: divisible by 2 shards x k=2; pooled B rows 4 % 2 == 0
    fa = jnp.asarray(rng.randn(1, 8, 6, 8).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 6, 8).astype(np.float32))

    want_corr, want_d = match_pipeline(params["neigh_consensus"], cfg, fa, fb)
    got_corr, got_d = make_sharded_match_pipeline(cfg, mesh)(
        params["neigh_consensus"], fa, fb
    )
    np.testing.assert_allclose(
        np.asarray(got_corr), np.asarray(want_corr), rtol=1e-4, atol=1e-5
    )
    for g, w in zip(got_d, want_d):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_sharded_pipeline_high_res_rect_grid_8_shards():
    """BASELINE config-5 shaped: a large rectangular grid (the InLoc
    aspect-preserving resize regime) sharded over all 8 devices, with
    relocalization — the configuration whose corr4d exceeds single-chip
    HBM at full scale."""
    cfg = ImMatchNetConfig(
        ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1),
        relocalization_k_size=2,
    )
    mesh = make_mesh((8,), ("spatial",), devices=jax.devices()[:8])
    params = init_immatchnet(jax.random.PRNGKey(4), cfg)
    rng = np.random.RandomState(4)
    # A rows 32: divides 8 shards x k=2; rectangular B grid 32x24
    fa = jnp.asarray(rng.randn(1, 32, 24, 16).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 32, 24, 16).astype(np.float32))

    want_corr, want_d = match_pipeline(params["neigh_consensus"], cfg, fa, fb)
    got_corr, got_d = make_sharded_match_pipeline(cfg, mesh)(
        params["neigh_consensus"], fa, fb
    )
    np.testing.assert_allclose(
        np.asarray(got_corr), np.asarray(want_corr), rtol=1e-4, atol=1e-5
    )
    for g, w in zip(got_d, want_d):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_inloc_match_fn_sharded_agrees_with_unsharded():
    """End-to-end InLoc surface (BASELINE config-5 shaped): make_match_fn
    with a spatial mesh produces the same match lists as single-device."""
    from ncnet_tpu.eval.inloc import make_match_fn

    cfg = ImMatchNetConfig(
        feature_extraction_cnn="vgg",
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(4, 1),
        relocalization_k_size=2,
    )
    mesh = make_mesh((2,), ("spatial",), devices=jax.devices()[:2])
    params = init_immatchnet(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(3)
    # 128x96 -> vgg stride 16 -> grid 8x6; aspect-rectangular like InLoc
    src = jnp.asarray(rng.randn(1, 128, 96, 3).astype(np.float32))
    tgt = jnp.asarray(rng.randn(1, 128, 128, 3).astype(np.float32))

    want = make_match_fn(cfg)(params, src, tgt)
    got = make_match_fn(cfg, mesh=mesh)(params, src, tgt)
    for w_dir, g_dir in zip(want, got):
        for w, g in zip(w_dir, g_dir):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=1e-4, atol=1e-5
            )


def test_sharded_pipeline_per_layer_impls():
    """The sharded NC stack accepts the same comma-separated per-layer
    conv4d impl lists as the unsharded one."""
    cfg = CFG.replace(conv4d_impl="tlc,scan")
    mesh = make_mesh((2,), ("spatial",), devices=jax.devices()[:2])
    params = init_immatchnet(jax.random.PRNGKey(6), cfg)
    rng = np.random.RandomState(6)
    fa = jnp.asarray(rng.randn(1, 8, 5, 8).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 5, 8).astype(np.float32))
    want = np.asarray(match_pipeline(params["neigh_consensus"], cfg, fa, fb))
    got = np.asarray(
        make_sharded_match_pipeline(cfg, mesh)(params["neigh_consensus"], fa, fb)
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
