import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet, match_pipeline
from ncnet_tpu.parallel.mesh import make_mesh
from ncnet_tpu.parallel.spatial import make_sharded_match_pipeline

CFG = ImMatchNetConfig(ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1))


@pytest.mark.parametrize("n_shards", [2, 4])
def test_sharded_pipeline_matches_unsharded(n_shards):
    assert len(jax.devices()) >= n_shards
    mesh = make_mesh(
        (n_shards,), ("spatial",), devices=jax.devices()[:n_shards]
    )
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    rng = np.random.RandomState(0)
    # grid rows (8) divisible by shard counts (symmetric mode reshards the
    # B rows too); columns may be ragged
    fa = jnp.asarray(rng.randn(2, 8, 5, 16).astype(np.float32))
    fb = jnp.asarray(rng.randn(2, 8, 7, 16).astype(np.float32))

    want = np.asarray(match_pipeline(params["neigh_consensus"], CFG, fa, fb))

    sharded = make_sharded_match_pipeline(CFG, mesh)
    got = np.asarray(sharded(params["neigh_consensus"], fa, fb))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sharded_pipeline_symmetric_square():
    """Symmetric-mode all_to_all transpose path on a square grid."""
    mesh = make_mesh((4,), ("spatial",), devices=jax.devices()[:4])
    params = init_immatchnet(jax.random.PRNGKey(1), CFG)
    rng = np.random.RandomState(1)
    fa = jnp.asarray(rng.randn(1, 8, 8, 8).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 8, 8).astype(np.float32))
    want = np.asarray(match_pipeline(params["neigh_consensus"], CFG, fa, fb))
    got = np.asarray(make_sharded_match_pipeline(CFG, mesh)(params["neigh_consensus"], fa, fb))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sharded_pipeline_with_relocalization():
    """Sharded fused correlate+maxpool4d: pooled corr AND argmax deltas
    must agree with the unsharded pipeline (the InLoc high-res config)."""
    cfg = CFG.replace(relocalization_k_size=2)
    mesh = make_mesh((2,), ("spatial",), devices=jax.devices()[:2])
    params = init_immatchnet(jax.random.PRNGKey(2), cfg)
    rng = np.random.RandomState(2)
    # A rows = 8: divisible by 2 shards x k=2; pooled B rows 4 % 2 == 0
    fa = jnp.asarray(rng.randn(1, 8, 6, 8).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 6, 8).astype(np.float32))

    want_corr, want_d = match_pipeline(params["neigh_consensus"], cfg, fa, fb)
    got_corr, got_d = make_sharded_match_pipeline(cfg, mesh)(
        params["neigh_consensus"], fa, fb
    )
    np.testing.assert_allclose(
        np.asarray(got_corr), np.asarray(want_corr), rtol=1e-4, atol=1e-5
    )
    for g, w in zip(got_d, want_d):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_sharded_pipeline_high_res_rect_grid_8_shards():
    """BASELINE config-5 shaped: a large rectangular grid (the InLoc
    aspect-preserving resize regime) sharded over all 8 devices, with
    relocalization — the configuration whose corr4d exceeds single-chip
    HBM at full scale."""
    cfg = ImMatchNetConfig(
        ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1),
        relocalization_k_size=2,
    )
    mesh = make_mesh((8,), ("spatial",), devices=jax.devices()[:8])
    params = init_immatchnet(jax.random.PRNGKey(4), cfg)
    rng = np.random.RandomState(4)
    # A rows 32: divides 8 shards x k=2; rectangular B grid 32x24
    fa = jnp.asarray(rng.randn(1, 32, 24, 16).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 32, 24, 16).astype(np.float32))

    want_corr, want_d = match_pipeline(params["neigh_consensus"], cfg, fa, fb)
    got_corr, got_d = make_sharded_match_pipeline(cfg, mesh)(
        params["neigh_consensus"], fa, fb
    )
    np.testing.assert_allclose(
        np.asarray(got_corr), np.asarray(want_corr), rtol=1e-4, atol=1e-5
    )
    for g, w in zip(got_d, want_d):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_inloc_match_fn_sharded_agrees_with_unsharded():
    """End-to-end InLoc surface (BASELINE config-5 shaped): make_match_fn
    with a spatial mesh produces the same match lists as single-device."""
    from ncnet_tpu.eval.inloc import make_match_fn

    cfg = ImMatchNetConfig(
        feature_extraction_cnn="vgg",
        ncons_kernel_sizes=(3, 3),
        ncons_channels=(4, 1),
        relocalization_k_size=2,
    )
    mesh = make_mesh((2,), ("spatial",), devices=jax.devices()[:2])
    params = init_immatchnet(jax.random.PRNGKey(3), cfg)
    rng = np.random.RandomState(3)
    # 128x96 -> vgg stride 16 -> grid 8x6; aspect-rectangular like InLoc
    src = jnp.asarray(rng.randn(1, 128, 96, 3).astype(np.float32))
    tgt = jnp.asarray(rng.randn(1, 128, 128, 3).astype(np.float32))

    want = make_match_fn(cfg)(params, src, tgt)
    got = make_match_fn(cfg, mesh=mesh)(params, src, tgt)
    for w_dir, g_dir in zip(want, got):
        for w, g in zip(w_dir, g_dir):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=1e-4, atol=1e-5
            )


def test_dump_matches_sharded_equals_unsharded(tmp_path):
    """`dump_matches(mesh=...)` — the whole-dump surface with the spatial
    sharding AND the round-5 pipelined consume loop + device_resize —
    writes the same .mat as the unsharded dump (the sharded resize rule
    widens the grid quantization, so compare at a shape both paths
    produce)."""
    from PIL import Image
    from scipy.io import loadmat

    from ncnet_tpu.eval.inloc import dump_matches
    from tests.test_eval import write_shortlist

    cfg = ImMatchNetConfig(
        ncons_kernel_sizes=(3,),
        ncons_channels=(1,),
        relocalization_k_size=2,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(9)
    qdir, pdir = tmp_path / "query", tmp_path / "pano"
    qdir.mkdir()
    pdir.mkdir()
    # 128x128 at image_size 128: both quantizations (k=2 and k*shards=4)
    # land on the same 128x128 bucket -> outputs directly comparable
    Image.fromarray(rng.randint(0, 255, (128, 128, 3), np.uint8)).save(
        qdir / "q0.png"
    )
    Image.fromarray(rng.randint(0, 255, (128, 128, 3), np.uint8)).save(
        pdir / "p0.png"
    )
    write_shortlist(tmp_path / "shortlist.mat", [("q0.png", ["p0.png"])])

    outs = {}
    for name, mesh in (
        ("unsharded", None),
        ("sharded", make_mesh((2,), ("spatial",),
                              devices=jax.devices()[:2])),
    ):
        out_dir = tmp_path / f"matches_{name}"
        dump_matches(
            params,
            cfg,
            shortlist_path=str(tmp_path / "shortlist.mat"),
            query_path=str(qdir),
            pano_path=str(pdir),
            output_dir=str(out_dir),
            image_size=128,
            n_queries=1,
            n_panos=1,
            verbose=False,
            mesh=mesh,
            device_preprocess=True,
            device_resize=True,
        )
        outs[name] = loadmat(out_dir / "1.mat")["matches"]
    np.testing.assert_allclose(
        outs["sharded"], outs["unsharded"], rtol=1e-4, atol=1e-5
    )


def test_sharded_pipeline_per_layer_impls():
    """The sharded NC stack accepts the same comma-separated per-layer
    conv4d impl lists as the unsharded one."""
    cfg = CFG.replace(conv4d_impl="tlc,scan")
    mesh = make_mesh((2,), ("spatial",), devices=jax.devices()[:2])
    params = init_immatchnet(jax.random.PRNGKey(6), cfg)
    rng = np.random.RandomState(6)
    fa = jnp.asarray(rng.randn(1, 8, 5, 8).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 5, 8).astype(np.float32))
    want = np.asarray(match_pipeline(params["neigh_consensus"], cfg, fa, fb))
    got = np.asarray(
        make_sharded_match_pipeline(cfg, mesh)(params["neigh_consensus"], fa, fb)
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
