import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet, match_pipeline
from ncnet_tpu.parallel.mesh import make_mesh
from ncnet_tpu.parallel.spatial import make_sharded_match_pipeline

CFG = ImMatchNetConfig(ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1))


@pytest.mark.parametrize("n_shards", [2, 4])
def test_sharded_pipeline_matches_unsharded(n_shards):
    assert len(jax.devices()) >= n_shards
    mesh = make_mesh(
        (n_shards,), ("spatial",), devices=jax.devices()[:n_shards]
    )
    params = init_immatchnet(jax.random.PRNGKey(0), CFG)
    rng = np.random.RandomState(0)
    # grid rows (8) divisible by shard counts (symmetric mode reshards the
    # B rows too); columns may be ragged
    fa = jnp.asarray(rng.randn(2, 8, 5, 16).astype(np.float32))
    fb = jnp.asarray(rng.randn(2, 8, 7, 16).astype(np.float32))

    want = np.asarray(match_pipeline(params["neigh_consensus"], CFG, fa, fb))

    sharded = make_sharded_match_pipeline(CFG, mesh)
    got = np.asarray(sharded(params["neigh_consensus"], fa, fb))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sharded_pipeline_symmetric_square():
    """Symmetric-mode all_to_all transpose path on a square grid."""
    mesh = make_mesh((4,), ("spatial",), devices=jax.devices()[:4])
    params = init_immatchnet(jax.random.PRNGKey(1), CFG)
    rng = np.random.RandomState(1)
    fa = jnp.asarray(rng.randn(1, 8, 8, 8).astype(np.float32))
    fb = jnp.asarray(rng.randn(1, 8, 8, 8).astype(np.float32))
    want = np.asarray(match_pipeline(params["neigh_consensus"], CFG, fa, fb))
    got = np.asarray(make_sharded_match_pipeline(CFG, mesh)(params["neigh_consensus"], fa, fb))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
