"""Parity against the reference's OWN code, imported as the oracle.

Round-2 verdict: the strongest attainable correctness proof in this
environment is running the reference implementation itself (torch is
installed; these modules need neither torchvision weights nor a GPU) on
identical inputs/weights and asserting agreement — converting "we
transcribed the math carefully" into "the reference itself agrees".

Imports `/root/reference/lib/{conv4d,model,point_tnf,eval_util}.py`
directly (module-level torchvision/skimage imports are satisfied with
empty stub modules — those libraries are only exercised by code paths
these tests never touch), and extracts ``weak_loss`` from the reference's
``train.py`` source by AST (the file is an argparse script and cannot be
imported).
"""

import ast
import sys
import types

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")

REF_ROOT = "/root/reference"

if not __import__("os").path.isdir(f"{REF_ROOT}/lib"):
    pytest.skip(
        f"reference checkout not present at {REF_ROOT}",
        allow_module_level=True,
    )

# The checkout is PUBLIC UNTRUSTED CONTENT and importing it executes its
# module bodies — so the exact files this module imports (or exec's, for
# train.py's weak_loss) are pinned by content hash, and a mismatch skips
# the whole module instead of running unvetted code at collection time.
# Set NCNET_ORACLE_UNPINNED=1 to run against a changed checkout anyway
# (e.g. after auditing a legitimately updated reference).
_PINNED_SHA256 = {
    "lib/conv4d.py":
        "7492575a0a52ed2bd86732c54a39751020dd96e5d3dcf303c401a74d3e624f6b",
    "lib/model.py":
        "62d881cbeaa3ef820a9c119ad12ea0f83a5a9732a3db34950fa1fe28cbbd79c7",
    "lib/point_tnf.py":
        "2f65ef4a1a83181a0727e4b51dfa20d9c909a24157285ff3e54a62bbb29cae27",
    "lib/eval_util.py":
        "37cbfbfacea529774c1ce432cb25f54f1230984ba115b15a902ab35c1fbad1e1",
    "train.py":
        "d461e082e32bcc71edc1c71b376a06b0407623d3d461078385e37bc929005c8b",
}

if __import__("os").environ.get("NCNET_ORACLE_UNPINNED", "") != "1":
    import hashlib

    def _differs(rel, want):
        try:
            with open(f"{REF_ROOT}/{rel}", "rb") as f:
                return hashlib.sha256(f.read()).hexdigest() != want
        except OSError:  # missing file = changed checkout -> skip, not error
            return True

    _changed = [
        rel for rel, want in _PINNED_SHA256.items() if _differs(rel, want)
    ]
    if _changed:
        pytest.skip(
            f"reference files {_changed} differ from the pinned hashes — "
            "refusing to import/exec an unvetted checkout (set "
            "NCNET_ORACLE_UNPINNED=1 after auditing it)",
            allow_module_level=True,
        )

# All conv4d lowerings that run on the CPU test platform.
CONV4D_IMPLS = [
    "xla", "taps", "scan", "tlc", "btl", "tlcv", "tf3", "tf2",
    "cf", "cfs", "cf1", "cf1s", "ck1", "tk1", "btl2", "btl4", "btl5", "gemm", "gemms",
]


def _import_reference():
    """Import the reference's lib modules with unused heavy deps stubbed."""
    for name in (
        "torchvision",
        "torchvision.models",
        "skimage",
        "skimage.io",
        "skimage.draw",
    ):
        if name not in sys.modules:
            sys.modules[name] = types.ModuleType(name)
    sys.modules["torchvision"].models = sys.modules["torchvision.models"]
    sys.modules["skimage"].io = sys.modules["skimage.io"]
    sys.modules["skimage"].draw = sys.modules["skimage.draw"]
    if REF_ROOT not in sys.path:
        sys.path.insert(0, REF_ROOT)
    import lib.conv4d as ref_conv4d
    import lib.eval_util as ref_eval_util
    import lib.model as ref_model
    import lib.point_tnf as ref_point_tnf

    return ref_conv4d, ref_model, ref_point_tnf, ref_eval_util


REF_CONV4D, REF_MODEL, REF_TNF, REF_EVAL = _import_reference()


def _extract_weak_loss():
    """Compile the reference's ``weak_loss`` (train.py:110-156) out of the
    script source — the module body runs argparse and cannot be imported."""
    with open(f"{REF_ROOT}/train.py") as f:
        tree = ast.parse(f.read())
    fn = next(
        n
        for n in tree.body
        if isinstance(n, ast.FunctionDef) and n.name == "weak_loss"
    )
    ns = {"torch": torch, "np": np}
    exec(compile(ast.Module([fn], []), "train.py", "exec"), ns)
    return ns["weak_loss"]


def _t(x):
    return torch.from_numpy(np.asarray(x))


# ------------------------------------------------------------------ conv4d


@pytest.mark.parametrize("impl", CONV4D_IMPLS)
def test_conv4d_vs_reference_loop(impl):
    """Every lowering vs the reference's conv3d tap loop
    (lib/conv4d.py:11-51), including the bias-once semantics, on a
    non-hypercubic grid."""
    from ncnet_tpu.ops.conv4d import conv4d

    rng = np.random.RandomState(0)
    x = rng.randn(2, 6, 5, 4, 7, 3).astype(np.float32)  # [b,i,j,k,l,c]
    w = rng.randn(3, 3, 3, 3, 3, 5).astype(np.float32) * 0.2
    bias = rng.randn(5).astype(np.float32)

    with torch.no_grad():
        want = REF_CONV4D.conv4d(
            _t(x.transpose(0, 5, 1, 2, 3, 4)),  # [b,c,i,j,k,l]
            _t(w.transpose(5, 4, 0, 1, 2, 3)),  # [cout,cin,ki,kj,kk,kl]
            bias=_t(bias),
            permute_filters=True,
        ).numpy().transpose(0, 2, 3, 4, 5, 1)

    got = np.asarray(conv4d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(bias), impl=impl))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def _ref_neigh_consensus(ksizes, channels, seed):
    """Instantiate the reference NeighConsensus on CPU with seeded weight
    init; returns the module.

    torch >= 1.x added a required ``padding_mode`` arg to ``_ConvNd`` that
    the 0.3-era reference doesn't pass; shim it for the construction only.
    """
    torch.manual_seed(seed)
    try:
        return REF_MODEL.NeighConsensus(
            use_cuda=False,
            kernel_sizes=list(ksizes),
            channels=list(channels),
            symmetric_mode=True,
        )
    except TypeError:
        from torch.nn.modules.conv import _ConvNd

        orig = _ConvNd.__init__

        def patched(self, in_c, out_c, ks, st, pad, dil, tr, outp, grp, bias):
            orig(
                self, in_c, out_c, ks, st, pad, dil, tr, outp, grp, bias,
                padding_mode="zeros",
            )

        _ConvNd.__init__ = patched
        try:
            return REF_MODEL.NeighConsensus(
                use_cuda=False,
                kernel_sizes=list(ksizes),
                channels=list(channels),
                symmetric_mode=True,
            )
        finally:
            _ConvNd.__init__ = orig


def test_neigh_consensus_vs_reference_module():
    """Our symmetric NC stack vs the reference's NeighConsensus module,
    weights converted from its own (pre-permuted) state dict."""
    from ncnet_tpu.models.neigh_consensus import neigh_consensus_apply
    from ncnet_tpu.utils.convert_torch import convert_neigh_consensus

    net = _ref_neigh_consensus((5, 5), (6, 1), seed=0)
    sd = {k: v.detach() for k, v in net.state_dict().items()}
    params = convert_neigh_consensus(sd, prefix="conv.")

    rng = np.random.RandomState(1)
    corr = rng.randn(2, 5, 5, 5, 5).astype(np.float32)

    with torch.no_grad():
        want = net(_t(corr)[:, None]).numpy()[:, 0]
    got = np.asarray(neigh_consensus_apply(params, jnp.asarray(corr)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


# ------------------------------------------------- elementwise model pieces


def test_feature_l2norm_vs_reference():
    from ncnet_tpu.ops.norm import feature_l2norm

    rng = np.random.RandomState(2)
    f = rng.randn(2, 8, 4, 5).astype(np.float32)  # [b,c,h,w]
    with torch.no_grad():
        want = REF_MODEL.featureL2Norm(_t(f)).numpy()
    got = np.asarray(feature_l2norm(jnp.asarray(f.transpose(0, 2, 3, 1))))
    np.testing.assert_allclose(got.transpose(0, 3, 1, 2), want, rtol=1e-5, atol=1e-6)


def test_mutual_matching_vs_reference():
    from ncnet_tpu.ops.matching import mutual_matching

    rng = np.random.RandomState(3)
    corr = rng.rand(2, 4, 5, 6, 3).astype(np.float32)
    with torch.no_grad():
        want = REF_MODEL.MutualMatching(_t(corr)[:, None]).numpy()[:, 0]
    got = np.asarray(mutual_matching(jnp.asarray(corr)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)


def test_correlation_4d_vs_reference():
    from ncnet_tpu.ops.correlation import correlation_4d

    corr_layer = REF_MODEL.FeatureCorrelation(shape="4D", normalization=False)
    rng = np.random.RandomState(4)
    fa = rng.randn(2, 7, 4, 5).astype(np.float32)  # [b,c,hA,wA]
    fb = rng.randn(2, 7, 3, 6).astype(np.float32)
    with torch.no_grad():
        want = corr_layer(_t(fa), _t(fb)).numpy()[:, 0]
    got = np.asarray(
        correlation_4d(
            jnp.asarray(fa.transpose(0, 2, 3, 1)),
            jnp.asarray(fb.transpose(0, 2, 3, 1)),
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_maxpool4d_vs_reference():
    """Pooled values AND the decoded per-dim argmax offsets
    (lib/model.py:177-191)."""
    from ncnet_tpu.ops.matching import maxpool4d

    rng = np.random.RandomState(5)
    corr = rng.randn(1, 8, 6, 4, 6).astype(np.float32)
    k = 2
    with torch.no_grad():
        want, wi, wj, wk, wl = REF_MODEL.maxpool4d(_t(corr)[:, None], k_size=k)
    pooled, (di, dj, dk, dl) = maxpool4d(jnp.asarray(corr), k)
    np.testing.assert_allclose(np.asarray(pooled), want.numpy()[:, 0], rtol=1e-6)
    for g, w in zip((di, dj, dk, dl), (wi, wj, wk, wl)):
        np.testing.assert_array_equal(
            np.asarray(g), w.numpy()[:, 0].astype(np.int32)
        )


def test_fused_correlation_maxpool4d_vs_reference():
    """The fused correlate+pool (which never materializes the pre-pool
    tensor) vs the reference's explicit correlation -> maxpool4d."""
    from ncnet_tpu.ops.correlation import correlation_maxpool4d

    corr_layer = REF_MODEL.FeatureCorrelation(shape="4D", normalization=False)
    rng = np.random.RandomState(6)
    fa = rng.randn(1, 5, 6, 4).astype(np.float32)
    fb = rng.randn(1, 5, 4, 6).astype(np.float32)
    k = 2
    with torch.no_grad():
        corr = corr_layer(_t(fa), _t(fb))
        want, wi, wj, wk, wl = REF_MODEL.maxpool4d(corr, k_size=k)
    pooled, (di, dj, dk, dl) = correlation_maxpool4d(
        jnp.asarray(fa.transpose(0, 2, 3, 1)),
        jnp.asarray(fb.transpose(0, 2, 3, 1)),
        k,
    )
    np.testing.assert_allclose(
        np.asarray(pooled), want.numpy()[:, 0], rtol=1e-4, atol=1e-5
    )
    for g, w in zip((di, dj, dk, dl), (wi, wj, wk, wl)):
        np.testing.assert_array_equal(
            np.asarray(g), w.numpy()[:, 0].astype(np.int32)
        )


# ---------------------------------------------------------------- readout


@pytest.mark.parametrize("invert", [False, True])
@pytest.mark.parametrize("do_softmax", [False, True])
@pytest.mark.parametrize("scale", ["centered", "positive"])
def test_corr_to_matches_vs_reference(invert, do_softmax, scale):
    """Batch 1: the reference's coordinate gathers `.view(-1)` an expanded
    tensor, which modern torch rejects for batch > 1 (and the reference
    eval scripts only ever call this at batch 1); our batch-correct
    behavior is covered by tests/test_matches.py."""
    from ncnet_tpu.ops.matches import corr_to_matches

    rng = np.random.RandomState(7)
    corr = rng.randn(1, 4, 5, 3, 6).astype(np.float32)
    with torch.no_grad():
        want = REF_TNF.corr_to_matches(
            _t(corr)[:, None],
            do_softmax=do_softmax,
            scale=scale,
            invert_matching_direction=invert,
        )
    got = corr_to_matches(
        jnp.asarray(corr),
        do_softmax=do_softmax,
        scale=scale,
        invert_matching_direction=invert,
    )
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g), w.numpy(), rtol=1e-5, atol=1e-6
        )


def test_corr_to_matches_relocalization_vs_reference():
    """The k_size=2 delta4d readout path (eval_inloc configuration;
    reference delta gather assumes batch 1, lib/point_tnf.py:63-70)."""
    from ncnet_tpu.ops.matches import corr_to_matches
    from ncnet_tpu.ops.matching import maxpool4d

    rng = np.random.RandomState(8)
    corr_hres = rng.randn(1, 8, 6, 4, 6).astype(np.float32)
    k = 2
    with torch.no_grad():
        pooled_t, wi, wj, wk, wl = REF_MODEL.maxpool4d(
            _t(corr_hres)[:, None], k_size=k
        )
        # torch 0.3's integer .div returned longs; torch 2 returns floats —
        # cast back so the reference's own index arithmetic works unchanged
        want = REF_TNF.corr_to_matches(
            pooled_t,
            delta4d=tuple(d.long() for d in (wi, wj, wk, wl)),
            k_size=k,
            do_softmax=True,
            scale="positive",
        )
    pooled, deltas = maxpool4d(jnp.asarray(corr_hres), k)
    got = corr_to_matches(
        pooled, delta4d=deltas, k_size=k, do_softmax=True, scale="positive"
    )
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g), w.numpy(), rtol=1e-5, atol=1e-6
        )


def test_point_transfer_and_coords_vs_reference():
    """bilinearInterpPointTnf + nearestNeighPointTnf + the 1-indexed
    pixel<->unit coordinate transforms (lib/point_tnf.py:82-167)."""
    from ncnet_tpu.ops.coords import points_to_pixel_coords, points_to_unit_coords
    from ncnet_tpu.ops.matches import (
        bilinear_point_transfer,
        corr_to_matches,
        nearest_point_transfer,
    )

    rng = np.random.RandomState(9)
    corr = rng.randn(1, 5, 5, 5, 5).astype(np.float32)
    pts = (rng.rand(1, 2, 7) * 1.6 - 0.8).astype(np.float32)
    im_size = np.array([[240.0, 320.0]], np.float32)

    with torch.no_grad():
        wm = REF_TNF.corr_to_matches(_t(corr)[:, None], do_softmax=True)
        want_bil = REF_TNF.bilinearInterpPointTnf(wm[:4], _t(pts)).numpy()
        want_nn = REF_TNF.nearestNeighPointTnf(wm[:4], _t(pts)).numpy()
        want_px = REF_TNF.PointsToPixelCoords(_t(pts), _t(im_size)).numpy()
        want_un = REF_TNF.PointsToUnitCoords(
            _t(want_px.copy()), _t(im_size)
        ).numpy()

    gm = corr_to_matches(jnp.asarray(corr), do_softmax=True)
    got_bil = np.asarray(bilinear_point_transfer(gm[:4], jnp.asarray(pts)))
    got_nn = np.asarray(nearest_point_transfer(gm[:4], jnp.asarray(pts)))
    got_px = np.asarray(
        points_to_pixel_coords(jnp.asarray(pts), jnp.asarray(im_size))
    )
    got_un = np.asarray(
        points_to_unit_coords(jnp.asarray(got_px), jnp.asarray(im_size))
    )
    np.testing.assert_allclose(got_bil, want_bil, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got_nn, want_nn, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_px, want_px, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got_un, want_un, rtol=1e-5, atol=1e-6)


def test_pck_vs_reference():
    """Reference pck slices the first N contiguous valid columns; ours
    masks — equivalent because padding is trailing (lib/eval_util.py:12-24)."""
    from ncnet_tpu.ops.metrics import pck

    rng = np.random.RandomState(10)
    src = rng.rand(3, 2, 8).astype(np.float32) * 200
    src[0, :, 6:] = -1  # trailing -1 padding
    src[2, :, 3:] = -1
    warped = src + rng.randn(3, 2, 8).astype(np.float32) * 15
    l_pck = np.array([150.0, 80.0, 220.0], np.float32)

    with torch.no_grad():
        want = REF_EVAL.pck(_t(src), _t(warped), _t(l_pck)).numpy()
    got = np.asarray(pck(jnp.asarray(src), jnp.asarray(warped), jnp.asarray(l_pck)))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


# ------------------------------------------------------------ whole chains


def test_full_chain_corr_to_pck_vs_reference():
    """corr -> MM -> NC -> MM -> softmax readout -> bilinear transfer ->
    pixel coords -> PCK: the reference's entire post-backbone eval chain
    (lib/model.py:261-282 + eval_pf_pascal.py:69-81) on identical weights."""
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, match_pipeline
    from ncnet_tpu.ops.coords import points_to_pixel_coords, points_to_unit_coords
    from ncnet_tpu.ops.matches import bilinear_point_transfer, corr_to_matches
    from ncnet_tpu.ops.metrics import pck
    from ncnet_tpu.ops.norm import feature_l2norm
    from ncnet_tpu.utils.convert_torch import convert_neigh_consensus

    net = _ref_neigh_consensus((3, 3), (8, 1), seed=11)
    sd = {k: v.detach() for k, v in net.state_dict().items()}
    nc_params = convert_neigh_consensus(sd, prefix="conv.")
    corr_layer = REF_MODEL.FeatureCorrelation(shape="4D", normalization=False)

    rng = np.random.RandomState(11)
    fa = rng.randn(1, 16, 6, 6).astype(np.float32)  # [b,c,h,w]
    fb = rng.randn(1, 16, 6, 6).astype(np.float32)
    tgt_pts = (rng.rand(1, 2, 9) * 150 + 20).astype(np.float32)
    src_pts = (rng.rand(1, 2, 9) * 150 + 20).astype(np.float32)
    im_size = np.array([[200.0, 180.0]], np.float32)
    l_pck = np.array([120.0], np.float32)

    with torch.no_grad():
        tfa = REF_MODEL.featureL2Norm(_t(fa))
        tfb = REF_MODEL.featureL2Norm(_t(fb))
        corr = corr_layer(tfa, tfb)
        corr = REF_MODEL.MutualMatching(corr)
        corr = net(corr)
        corr = REF_MODEL.MutualMatching(corr)
        wm = REF_TNF.corr_to_matches(corr, do_softmax=True)
        tp_norm = REF_TNF.PointsToUnitCoords(_t(tgt_pts), _t(im_size))
        warped_norm = REF_TNF.bilinearInterpPointTnf(wm[:4], tp_norm)
        warped = REF_TNF.PointsToPixelCoords(warped_norm, _t(im_size))
        want_pck = REF_EVAL.pck(_t(src_pts), warped, _t(l_pck)).numpy()
        want_corr = corr.numpy()[:, 0]

    config = ImMatchNetConfig(ncons_kernel_sizes=(3, 3), ncons_channels=(8, 1))
    jfa = feature_l2norm(jnp.asarray(fa.transpose(0, 2, 3, 1)))
    jfb = feature_l2norm(jnp.asarray(fb.transpose(0, 2, 3, 1)))
    got_corr = match_pipeline(nc_params, config, jfa, jfb)
    gm = corr_to_matches(got_corr, do_softmax=True)
    jp_norm = points_to_unit_coords(jnp.asarray(tgt_pts), jnp.asarray(im_size))
    warped_norm_j = bilinear_point_transfer(gm[:4], jp_norm)
    warped_j = points_to_pixel_coords(warped_norm_j, jnp.asarray(im_size))
    got_pck = np.asarray(
        pck(jnp.asarray(src_pts), warped_j, jnp.asarray(l_pck))
    )

    np.testing.assert_allclose(
        np.asarray(got_corr), want_corr, rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(got_pck, want_pck, rtol=1e-6)


def test_weak_loss_vs_reference():
    """The reference's own ``weak_loss`` source (extracted from train.py,
    incl. its in-place source-batch roll) vs our functional loss math, with
    the backbone factored out: both sides consume the same L2-normalized
    feature maps through the same NC weights."""
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, match_pipeline
    from ncnet_tpu.train.loss import match_score
    from ncnet_tpu.utils.convert_torch import convert_neigh_consensus

    weak_loss_ref = _extract_weak_loss()

    net = _ref_neigh_consensus((3, 3), (8, 1), seed=12)
    sd = {k: v.detach() for k, v in net.state_dict().items()}
    nc_params = convert_neigh_consensus(sd, prefix="conv.")
    corr_layer = REF_MODEL.FeatureCorrelation(shape="4D", normalization=False)

    rng = np.random.RandomState(12)
    b = 4
    fa = rng.randn(b, 16, 6, 6).astype(np.float32)
    fb = rng.randn(b, 16, 6, 6).astype(np.float32)

    class StubModel:
        """Reference ImMatchNet.forward with the trunk replaced by identity:
        batch['source_image'] / ['target_image'] ARE the feature maps."""

        def __call__(self, batch):
            with torch.no_grad():
                sfa = REF_MODEL.featureL2Norm(batch["source_image"])
                sfb = REF_MODEL.featureL2Norm(batch["target_image"])
                corr = corr_layer(sfa, sfb)
                corr = REF_MODEL.MutualMatching(corr)
                corr = net(corr)
                return REF_MODEL.MutualMatching(corr)

    batch = {"source_image": _t(fa.copy()), "target_image": _t(fb.copy())}
    want = float(weak_loss_ref(StubModel(), batch, normalization="softmax"))

    config = ImMatchNetConfig(ncons_kernel_sizes=(3, 3), ncons_channels=(8, 1))
    from ncnet_tpu.ops.norm import feature_l2norm

    jfa = feature_l2norm(jnp.asarray(fa.transpose(0, 2, 3, 1)))
    jfb = feature_l2norm(jnp.asarray(fb.transpose(0, 2, 3, 1)))
    jfa_neg = jnp.roll(jfa, -1, axis=0)  # train.py:137's np.roll pairing
    corr_pos = match_pipeline(nc_params, config, jfa, jfb)
    corr_neg = match_pipeline(nc_params, config, jfa_neg, jfb)
    got = float(match_score(corr_neg) - match_score(corr_pos))

    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
