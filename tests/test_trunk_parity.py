"""Full-trunk and full-model numerical parity against in-test torch oracles.

SURVEY.md §7.3-4 flags backbone parity as the top correctness hazard: small
feature drift is amplified by the correlation/argmax readout. These tests
assemble the torch-side computation directly from a reference-style state
dict (torch only — torchvision is not installed here), convert the same
state dict with `ncnet_tpu.utils.convert_torch`, and require the two
forwards to agree:

  * whole ResNet-101 trunk (conv1 .. layer3, reference lib/model.py:37-44)
  * whole DenseNet-201 trunk (conv0 .. transition2, lib/model.py:69-74)
  * the fully assembled ImMatchNet pipeline: trunk -> L2 norm -> 4D
    correlation -> MutualMatching -> symmetric NeighConsensus ->
    MutualMatching (lib/model.py:261-282), with reference eps values.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

from ncnet_tpu.models.densenet import TRUNK_BLOCKS, densenet201_trunk_apply
from ncnet_tpu.models.resnet import RESNET101_STAGES, resnet101_trunk_apply
from ncnet_tpu.models.vgg import VGG16_TO_POOL4, vgg16_trunk_apply
from ncnet_tpu.utils import convert_torch

EXPANSION = 4

# torchvision vgg16.features Sequential indices of the conv layers up to
# pool4 (ReLUs and pools occupy the gaps; reference lib/model.py:24-35)
VGG16_CONV_INDICES = (0, 2, 5, 7, 10, 12, 14, 17, 19, 21)


# ---------------------------------------------------------------- state dicts


def _conv(sd, g, name, cout, cin, k, scale=0.1):
    sd[name + ".weight"] = torch.randn(cout, cin, k, k, generator=g) * scale


def _bn(sd, g, name, c, scale_lo=0.5):
    sd[name + ".weight"] = torch.rand(c, generator=g) * 0.5 + scale_lo
    sd[name + ".bias"] = torch.randn(c, generator=g) * 0.1
    sd[name + ".running_mean"] = torch.randn(c, generator=g) * 0.1
    sd[name + ".running_var"] = torch.rand(c, generator=g) + 0.5


def _resnet_sd(prefix=""):
    # conv/BN scales kept small: 23 random residual blocks otherwise
    # amplify activations to ~1e20, where fp32 parity is meaningless
    g = torch.Generator().manual_seed(0)
    sd = {}
    _conv(sd, g, prefix + "conv1", 64, 3, 7)
    _bn(sd, g, prefix + "bn1", 64)
    cin = 64
    for si, (n_blocks, planes, _) in enumerate(RESNET101_STAGES):
        for bi in range(n_blocks):
            p = f"{prefix}layer{si + 1}.{bi}."
            _conv(sd, g, p + "conv1", planes, cin, 1, scale=0.03)
            _bn(sd, g, p + "bn1", planes, scale_lo=0.3)
            _conv(sd, g, p + "conv2", planes, planes, 3, scale=0.03)
            _bn(sd, g, p + "bn2", planes, scale_lo=0.3)
            _conv(sd, g, p + "conv3", planes * EXPANSION, planes, 1, scale=0.03)
            _bn(sd, g, p + "bn3", planes * EXPANSION, scale_lo=0.3)
            if bi == 0:
                _conv(sd, g, p + "downsample.0", planes * EXPANSION, cin, 1)
                _bn(sd, g, p + "downsample.1", planes * EXPANSION)
            cin = planes * EXPANSION
    return sd


def _densenet_sd(prefix=""):
    g = torch.Generator().manual_seed(1)
    sd = {}
    _conv(sd, g, prefix + "conv0", 64, 3, 7)
    _bn(sd, g, prefix + "norm0", 64)
    cin = 64
    for bi, n_layers in enumerate(TRUNK_BLOCKS):
        for li in range(n_layers):
            p = f"{prefix}denseblock{bi + 1}.denselayer{li + 1}."
            _bn(sd, g, p + "norm1", cin)
            _conv(sd, g, p + "conv1", 128, cin, 1)
            _bn(sd, g, p + "norm2", 128)
            _conv(sd, g, p + "conv2", 32, 128, 3)
            cin += 32
        t = f"{prefix}transition{bi + 1}."
        _bn(sd, g, t + "norm", cin)
        _conv(sd, g, t + "conv", cin // 2, cin, 1)
        cin //= 2
    return sd


def _vgg_sd(prefix=""):
    """torchvision ``vgg16.features`` state dict truncated at pool4 — the
    exact key set a reference 'vgg' checkpoint stores under
    ``FeatureExtraction.model.`` (Sequential indices, biases present,
    no BatchNorm)."""
    g = torch.Generator().manual_seed(3)
    sd = {}
    cin = 3
    convs = [c for c in VGG16_TO_POOL4 if c != "M"]
    assert len(convs) == len(VGG16_CONV_INDICES)
    for idx, cout in zip(VGG16_CONV_INDICES, convs):
        sd[f"{prefix}{idx}.weight"] = torch.randn(cout, cin, 3, 3, generator=g) * 0.05
        sd[f"{prefix}{idx}.bias"] = torch.randn(cout, generator=g) * 0.1
        cin = cout
    return sd


# -------------------------------------------------------------- torch oracles


def _tbn(sd, name, t):
    return F.batch_norm(
        t,
        sd[name + ".running_mean"],
        sd[name + ".running_var"],
        sd[name + ".weight"],
        sd[name + ".bias"],
        training=False,
        eps=1e-5,
    )


def _torch_resnet_trunk(sd, x):
    x = F.conv2d(x, sd["conv1.weight"], stride=2, padding=3)
    x = F.relu(_tbn(sd, "bn1", x))
    x = F.max_pool2d(x, 3, stride=2, padding=1)
    for si, (n_blocks, _, stride) in enumerate(RESNET101_STAGES):
        for bi in range(n_blocks):
            p = f"layer{si + 1}.{bi}."
            s = stride if bi == 0 else 1
            out = F.relu(_tbn(sd, p + "bn1", F.conv2d(x, sd[p + "conv1.weight"])))
            out = F.relu(
                _tbn(
                    sd,
                    p + "bn2",
                    F.conv2d(out, sd[p + "conv2.weight"], stride=s, padding=1),
                )
            )
            out = _tbn(sd, p + "bn3", F.conv2d(out, sd[p + "conv3.weight"]))
            if p + "downsample.0.weight" in sd:
                sc = _tbn(
                    sd,
                    p + "downsample.1",
                    F.conv2d(x, sd[p + "downsample.0.weight"], stride=s),
                )
            else:
                sc = x
            x = F.relu(out + sc)
    return x


def _torch_densenet_trunk(sd, x):
    x = F.conv2d(x, sd["conv0.weight"], stride=2, padding=3)
    x = F.relu(_tbn(sd, "norm0", x))
    x = F.max_pool2d(x, 3, stride=2, padding=1)
    for bi, n_layers in enumerate(TRUNK_BLOCKS):
        for li in range(n_layers):
            p = f"denseblock{bi + 1}.denselayer{li + 1}."
            out = F.conv2d(F.relu(_tbn(sd, p + "norm1", x)), sd[p + "conv1.weight"])
            out = F.conv2d(
                F.relu(_tbn(sd, p + "norm2", out)), sd[p + "conv2.weight"], padding=1
            )
            x = torch.cat([x, out], dim=1)
        t = f"transition{bi + 1}."
        x = F.conv2d(F.relu(_tbn(sd, t + "norm", x)), sd[t + "conv.weight"])
        x = F.avg_pool2d(x, 2, stride=2)
    return x


def _torch_vgg_trunk(sd, x):
    """torchvision VGG-16 ``features[:pool4+1]`` forward (conv+ReLU runs
    separated by 2x2/2 max-pools — reference lib/model.py:24-35)."""
    ci = iter(VGG16_CONV_INDICES)
    for c in VGG16_TO_POOL4:
        if c == "M":
            x = F.max_pool2d(x, 2, stride=2)
        else:
            idx = next(ci)
            x = F.relu(F.conv2d(x, sd[f"{idx}.weight"], sd[f"{idx}.bias"], padding=1))
    return x


def _torch_l2norm(f):
    # reference featureL2Norm (lib/model.py:14-17): eps added to the sum
    return f / torch.pow(torch.sum(torch.pow(f, 2), 1) + 1e-6, 0.5).unsqueeze(1)


def _torch_correlation4d(fa, fb):
    # reference FeatureCorrelation shape='4D' (lib/model.py:106-115)
    b, c, ha, wa = fa.shape
    hb, wb = fb.shape[2:]
    mul = torch.bmm(fa.view(b, c, ha * wa).transpose(1, 2), fb.view(b, c, hb * wb))
    return mul.view(b, ha, wa, hb, wb).unsqueeze(1)


def _torch_mutual_matching(corr4d):
    # reference MutualMatching (lib/model.py:155-175), eps 1e-5
    b, ch, fs1, fs2, fs3, fs4 = corr4d.shape
    corr_b = corr4d.view(b, fs1 * fs2, fs3, fs4)
    corr_a = corr4d.view(b, fs1, fs2, fs3 * fs4)
    b_max, _ = torch.max(corr_b, dim=1, keepdim=True)
    a_max, _ = torch.max(corr_a, dim=3, keepdim=True)
    eps = 1e-5
    corr_b = (corr_b / (b_max + eps)).view_as(corr4d)
    corr_a = (corr_a / (a_max + eps)).view_as(corr4d)
    return corr4d * (corr_a * corr_b)


def _torch_conv4d(x, w, bias):
    """Reference conv4d tap decomposition (lib/conv4d.py:39-48): loop over
    the leading kernel dim, conv3d per tap, bias once at the center tap."""
    ksize = w.shape[2]
    pad = ksize // 2
    b, c, i, j, k, l = x.shape
    cout = w.shape[0]
    xpad = F.pad(x, (0, 0, 0, 0, 0, 0, pad, pad))
    out = torch.zeros(b, cout, i, j, k, l)
    for oi in range(i):
        for p in range(ksize):
            out[:, :, oi] += F.conv3d(
                xpad[:, :, oi + p],
                w[:, :, p],
                bias=bias if p == pad else None,
                padding=pad,
            )
    return out


def _torch_neigh_consensus(corr4d, weights, biases):
    def net(x):
        for w, bb in zip(weights, biases):
            x = F.relu(_torch_conv4d(x, w, bb))
        return x

    # symmetric mode (lib/model.py:144-150)
    swapped = corr4d.permute(0, 1, 4, 5, 2, 3)
    return net(corr4d) + net(swapped).permute(0, 1, 4, 5, 2, 3)


# --------------------------------------------------------------------- tests


def test_resnet101_full_trunk_parity():
    sd = _resnet_sd()
    params = convert_torch.convert_resnet101_trunk(sd, prefix="")
    rng = np.random.RandomState(0)
    x = rng.randn(1, 64, 64, 3).astype(np.float32)

    got = np.asarray(resnet101_trunk_apply(params, jnp.asarray(x)))
    want = (
        _torch_resnet_trunk(sd, torch.from_numpy(x.transpose(0, 3, 1, 2)))
        .numpy()
        .transpose(0, 2, 3, 1)
    )
    assert got.shape == want.shape == (1, 4, 4, 1024)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_densenet201_full_trunk_parity():
    sd = _densenet_sd()
    params = convert_torch.convert_densenet201_trunk(sd, prefix="")
    rng = np.random.RandomState(1)
    x = rng.randn(1, 64, 64, 3).astype(np.float32)

    got = np.asarray(densenet201_trunk_apply(params, jnp.asarray(x)))
    want = (
        _torch_densenet_trunk(sd, torch.from_numpy(x.transpose(0, 3, 1, 2)))
        .numpy()
        .transpose(0, 2, 3, 1)
    )
    assert got.shape == want.shape == (1, 4, 4, 256)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_vgg16_full_trunk_parity():
    """Whole VGG-16 trunk through pool4 vs the torch oracle on identical
    weights (reference lib/model.py:24-35) — closes the round-4 gap where
    the vgg variant was shape-tested only."""
    sd = _vgg_sd()
    params = convert_torch.convert_vgg16_trunk(sd, prefix="")
    rng = np.random.RandomState(3)
    x = rng.randn(1, 64, 64, 3).astype(np.float32)

    got = np.asarray(vgg16_trunk_apply(params, jnp.asarray(x)))
    want = (
        _torch_vgg_trunk(sd, torch.from_numpy(x.transpose(0, 3, 1, 2)))
        .numpy()
        .transpose(0, 2, 3, 1)
    )
    assert got.shape == want.shape == (1, 4, 4, 512)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_vgg_conversion_structure_matches_init():
    from ncnet_tpu.models.vgg import init_vgg16_trunk

    sd = _vgg_sd()
    converted = convert_torch.convert_vgg16_trunk(sd, prefix="")
    ref = init_vgg16_trunk(jax.random.PRNGKey(0))
    ref_flat, ref_tree = jax.tree.flatten(ref)
    got_flat, got_tree = jax.tree.flatten(converted)
    assert ref_tree == got_tree
    for a, b in zip(ref_flat, got_flat):
        assert np.shape(a) == np.shape(b)


def test_densenet_conversion_structure_matches_init():
    from ncnet_tpu.models.densenet import init_densenet201_trunk

    sd = _densenet_sd()
    converted = convert_torch.convert_densenet201_trunk(sd, prefix="")
    ref = init_densenet201_trunk(jax.random.PRNGKey(0))
    ref_flat, ref_tree = jax.tree.flatten(ref)
    got_flat, got_tree = jax.tree.flatten(converted)
    assert ref_tree == got_tree
    for a, b in zip(ref_flat, got_flat):
        assert np.shape(a) == np.shape(b)


def test_densenet_legacy_zoo_key_names():
    """The torchvision zoo densenet files use 'denselayerN.norm.1.weight'
    style keys (regex-remapped by torchvision at load); the converter must
    accept them identically to modern names."""
    import re

    sd = _densenet_sd()
    legacy = {
        re.sub(r"(denselayer\d+\.(?:norm|conv))(\d)\.", r"\1.\2.", k): v
        for k, v in sd.items()
    }
    assert legacy.keys() != sd.keys()  # the rename actually did something
    a = convert_torch.convert_densenet201_trunk(sd, prefix="")
    b = convert_torch.convert_densenet201_trunk(legacy, prefix="")
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(x, y)


def test_full_immatchnet_pipeline_parity():
    """Assembled model vs the torch reference math on identical weights —
    the whole forward of lib/model.py:261-282 at the demo grid."""
    from ncnet_tpu.models.immatchnet import ImMatchNetConfig, immatchnet_apply

    trunk_sd = _resnet_sd()
    g = torch.Generator().manual_seed(2)
    ksizes, chans = (3, 3), (8, 1)
    nc_weights, nc_biases, nc_sd = [], [], {}
    cin = 1
    for li, (k, cout) in enumerate(zip(ksizes, chans)):
        w = torch.randn(cout, cin, k, k, k, k, generator=g) * (
            1.0 / (cin * k**4) ** 0.5
        )
        bb = torch.randn(cout, generator=g) * 0.01
        nc_weights.append(w)
        nc_biases.append(bb)
        # reference checkpoints store Conv4d weights PRE-PERMUTED
        # (lib/conv4d.py:72-77): [k1, cout, cin, k2, k3, k4]
        nc_sd[f"NeighConsensus.conv.{2 * li}.weight"] = w.permute(2, 0, 1, 3, 4, 5)
        nc_sd[f"NeighConsensus.conv.{2 * li}.bias"] = bb
        cin = cout

    params = {
        "feature_extraction": convert_torch.convert_resnet101_trunk(
            trunk_sd, prefix=""
        ),
        "neigh_consensus": convert_torch.convert_neigh_consensus(nc_sd),
    }
    config = ImMatchNetConfig(ncons_kernel_sizes=ksizes, ncons_channels=chans)

    rng = np.random.RandomState(2)
    src = rng.randn(1, 64, 64, 3).astype(np.float32)
    tgt = rng.randn(1, 64, 64, 3).astype(np.float32)

    got = np.asarray(
        immatchnet_apply(params, config, jnp.asarray(src), jnp.asarray(tgt))
    )

    ts, tt = (
        torch.from_numpy(src.transpose(0, 3, 1, 2)),
        torch.from_numpy(tgt.transpose(0, 3, 1, 2)),
    )
    fa = _torch_l2norm(_torch_resnet_trunk(trunk_sd, ts))
    fb = _torch_l2norm(_torch_resnet_trunk(trunk_sd, tt))
    corr = _torch_correlation4d(fa, fb)
    corr = _torch_mutual_matching(corr)
    corr = _torch_neigh_consensus(corr, nc_weights, nc_biases)
    corr = _torch_mutual_matching(corr)
    want = corr.squeeze(1).numpy()  # drop the channel axis like ours

    assert got.shape == want.shape == (1, 4, 4, 4, 4)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
