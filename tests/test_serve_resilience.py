"""SLO-aware serving resilience (PR 10): typed outcomes, admission
control against the EWMA estimate, in-pipeline deadline drops at every
stage, the overload -> pre-warmed degraded-program flip (bitwise the
nc_topk band program's own output), stage supervision drills (killed
prep worker, hung dispatch, crashed readout — ONLY in-flight requests
fail, typed; the stage restarts; zero recompiles after), bounded drain
("shutdown returned => every accepted future resolved exactly once"),
the micro-batcher under a backwards-jumping clock, and the SIGTERM
drain drill through scripts/serve.py."""

import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ncnet_tpu.models.immatchnet import ImMatchNetConfig, init_immatchnet
from ncnet_tpu.resilience import faultinject
from ncnet_tpu.resilience.signals import PreemptionGuard
from ncnet_tpu.serve import (
    AdmissionRejected,
    DeadlineExceeded,
    HysteresisController,
    LatencyEstimator,
    MicroBatcher,
    RequestShed,
    ServeEngine,
    ServeResilienceError,
    StageFailure,
    Watchdog,
    drain_on_preemption,
    make_serve_match_step,
    payload_spec,
    run_supervised,
)
from ncnet_tpu.serve.batcher import Request

REPO = Path(__file__).resolve().parent.parent

TINY = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))


@pytest.fixture(autouse=True)
def _clean_faults():
    faultinject.clear()
    yield
    faultinject.clear()


def _toy_engine(**kw):
    params = {"w": jnp.asarray(3.0, jnp.float32)}

    def apply(p, batch):
        return {"y": batch["x"] * p["w"]}

    return ServeEngine(apply, params, **kw)


def _toy_payload(n, fill):
    return {"x": np.full((n,), fill, np.float32)}


def _invariant(stats):
    """The exactly-once ledger: every accepted request lands in exactly
    one outcome counter."""
    assert stats["submitted"] == (
        stats["completed"] + stats["failed"] + stats["shed"]
        + stats["deadline_exceeded"]
    )


# ----------------------------------------------------------------------
# the typed-outcome taxonomy (what callers branch on)


def test_exception_taxonomy():
    shed = RequestShed("m", reason="admission", retry_after_s=0.5)
    ddl = DeadlineExceeded("m", stage="readout", deadline_s=1.0)
    rej = AdmissionRejected("m", retry_after_s=0.1)
    hang = StageFailure("dispatch", "no heartbeat", hang=True)
    for exc in (shed, ddl, rej, hang):
        assert isinstance(exc, ServeResilienceError)
        assert isinstance(exc, RuntimeError)
    assert isinstance(ddl, RequestShed) and ddl.reason == "deadline"
    assert ddl.stage == "readout"
    # pre-PR-10 backpressure handlers catch queue.Full: must keep working
    assert isinstance(rej, queue.Full)
    assert rej.retry_after_s == 0.1
    assert hang.stage == "dispatch" and hang.hang
    assert "hang" in str(hang)
    assert not StageFailure("prep", "boom").hang


# ----------------------------------------------------------------------
# admission control primitives


def test_latency_estimator_ewma_and_fallback():
    est = LatencyEstimator(alpha=0.5)
    assert est.estimate("A") is None  # admit blind before any sample
    est.observe("A", 1.0)
    assert est.estimate("A") == 1.0
    est.observe("A", 3.0)
    assert est.estimate("A") == pytest.approx(2.0)  # 1 + .5*(3-1)
    # unknown key falls back to the global EWMA, never None after a sample
    assert est.estimate("B") == pytest.approx(2.0)
    assert est.estimate() == pytest.approx(2.0)
    with pytest.raises(ValueError):
        LatencyEstimator(alpha=0.0)


def test_hysteresis_controller_dwell_and_dead_band():
    c = HysteresisController(high=0.75, low=0.25, up_count=2, down_count=2)
    assert not c.update(0.9)  # one high reading is not enough
    assert c.update(0.5) is False  # dead band resets the streak
    assert not c.update(0.9)
    assert c.update(0.9) is True  # 2 consecutive highs: flip up
    assert c.flips == 1
    assert c.update(0.1) is True  # one low reading is not enough
    assert c.update(0.5) is True  # dead band keeps the mode (the point)
    c.update(0.1)
    assert c.update(0.1) is False  # 2 consecutive lows: flip back
    assert c.flips == 2
    assert c.last_pressure == 0.1
    with pytest.raises(ValueError):
        HysteresisController(high=0.2, low=0.5)
    with pytest.raises(ValueError):
        HysteresisController(up_count=0)


def test_run_supervised_restarts_and_stopping():
    crashes = []
    state = {"n": 0}

    def loop():
        state["n"] += 1
        if state["n"] < 3:
            raise RuntimeError(f"crash {state['n']}")

    run_supervised(loop, on_crash=crashes.append)
    assert state["n"] == 3 and len(crashes) == 2  # restarted twice, done

    state["n"] = 0

    def always_crash():
        state["n"] += 1
        raise RuntimeError("boom")

    run_supervised(
        always_crash, on_crash=crashes.append,
        stopping=lambda: state["n"] >= 2,
    )
    assert state["n"] == 2  # stopping() short-circuits the restart


def test_watchdog_fires_only_when_busy_and_stale():
    hangs = []
    busy = {"v": False}
    dog = Watchdog(
        0.05, beat_fn=lambda: 0.0, busy_fn=lambda: busy["v"],
        on_hang=lambda: hangs.append(time.monotonic()),
        clock=time.monotonic,
    ).start()
    try:
        time.sleep(0.2)
        assert hangs == []  # stale beat but idle: not a hang
        busy["v"] = True
        deadline = time.monotonic() + 5.0
        while not hangs and time.monotonic() < deadline:
            time.sleep(0.01)
        assert hangs
    finally:
        dog.stop(join_timeout=5.0)
    with pytest.raises(ValueError):
        Watchdog(0.0, beat_fn=lambda: 0, busy_fn=lambda: 0,
                 on_hang=lambda: None)


def test_watchdog_stop_from_its_own_on_hang():
    # the fleet's hang handler stops the very watchdog that fired it
    # (kill_replica runs ON the watchdog thread); stop() must not join
    # the current thread — that raises and kills the handler mid-kill
    box = {}
    handled = threading.Event()

    def on_hang():
        box["dog"].stop(join_timeout=0)  # pre-fix: RuntimeError here
        handled.set()

    dog = Watchdog(0.05, beat_fn=lambda: 0.0, busy_fn=lambda: True,
                   on_hang=on_hang)
    box["dog"] = dog
    dog.start()
    assert handled.wait(5.0)  # the handler ran to completion
    dog._thread.join(5.0)
    assert not dog._thread.is_alive()  # _stop alone ended the loop


# ----------------------------------------------------------------------
# admission control + deadlines on the engine


def test_admission_shed_on_primed_estimate():
    with _toy_engine(max_batch=2, max_wait=0.005) as eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        eng.estimator.observe("A", 10.0)  # "a batch takes 10 s"
        fut = eng.submit(
            key="A", payload=_toy_payload(3, 1.0), deadline_s=0.05
        )
        assert fut.done()  # shed at admission: no queue slot occupied
        with pytest.raises(RequestShed) as ei:
            fut.result()
        exc = ei.value
        assert exc.reason == "admission"
        assert not isinstance(exc, DeadlineExceeded)
        assert exc.retry_after_s == pytest.approx(10.0)
        # deadline-aware flush (the default) does not charge max_wait at
        # admission — a tight group flushes early instead of waiting
        assert exc.estimated_s == pytest.approx(10.0)
        # a deadline the estimate CAN meet is admitted and served
        ok = eng.submit(
            key="A", payload=_toy_payload(3, 2.0), deadline_s=30.0
        )
        np.testing.assert_array_equal(
            ok.result(timeout=10)["y"], np.full((3,), 6.0, np.float32)
        )
        stats = eng.report()
    assert stats["shed"] == 1 and stats["completed"] == 1
    assert stats["deadline_exceeded"] == 0
    _invariant(stats)


def test_admission_admits_blind_before_first_observation():
    with _toy_engine(max_batch=2, max_wait=0.005) as eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        # no EWMA sample yet: even a tight deadline is admitted rather
        # than shed on a guess (and the toy pipeline meets it)
        fut = eng.submit(
            key="A", payload=_toy_payload(3, 1.0), deadline_s=30.0
        )
        fut.result(timeout=10)
        stats = eng.report()
    assert stats["shed"] == 0 and stats["completed"] == 1


@pytest.mark.parametrize(
    "point,stage",
    [
        ("serve.request", "prep"),
        ("serve.dispatch.hang", "dispatch"),
        ("serve.readout.delay", "readout"),
    ],
)
def test_deadline_expires_in_pipeline(point, stage):
    """An injected stage delay outlives the request's budget: the request
    resolves with DeadlineExceeded naming the stage that dropped it (and
    never occupies a device slot past its deadline)."""
    faultinject.inject(point, "delay", arg=0.4, at=1)
    with _toy_engine(max_batch=1, host_workers=1) as eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        if stage == "prep":
            # the delay wedges the single worker INSIDE r1's prep; r2's
            # budget expires while queued behind it
            r1 = eng.submit(key="A", payload=_toy_payload(3, 0.0))
            victim = eng.submit(
                key="A", payload=_toy_payload(3, 1.0), deadline_s=0.05
            )
            r1.result(timeout=10)
        else:
            victim = eng.submit(
                key="A", payload=_toy_payload(3, 1.0), deadline_s=0.05
            )
        with pytest.raises(DeadlineExceeded) as ei:
            victim.result(timeout=10)
        assert ei.value.stage == stage
        stats = eng.report()
    assert stats["deadline_exceeded"] == 1
    assert stats["failed"] == 0  # a deadline drop is not a failure
    _invariant(stats)


def test_admission_rejected_typed_with_retry_hint():
    faultinject.inject("serve.request", "delay", arg=0.4)
    eng = _toy_engine(
        max_batch=2, max_wait=0.005, queue_limit=1, host_workers=1
    )
    try:
        accepted, rejected = [], None
        for i in range(4):  # 1 in-flight + 1 queued: must refuse by #4
            try:
                accepted.append(eng.submit(
                    key="A", payload=_toy_payload(3, float(i)), timeout=0
                ))
            except queue.Full as exc:  # the pre-PR-10 handler still works
                rejected = exc
                break
        assert isinstance(rejected, AdmissionRejected)
        assert rejected.retry_after_s is not None
        assert "queue full" in str(rejected)
    finally:
        faultinject.clear()
        eng.close()
    for f in accepted:
        f.result(timeout=10)  # every ACCEPTED future still resolves
    assert eng.report()["admission_rejected"] >= 1
    _invariant(eng.report())


# ----------------------------------------------------------------------
# overload degradation


def _forced_controller():
    # every pressure reading (>= 0) is "overload": flips on the dispatch
    # loop's first observation — degradation without having to race a
    # real queue build-up
    return HysteresisController(high=0.0, low=-1.0, up_count=1)


def test_degraded_flip_serves_degraded_program_toy():
    params = {"w": jnp.asarray(3.0, jnp.float32)}

    def dense(p, batch):
        return {"y": batch["x"] * p["w"]}

    def degraded(p, batch):
        return {"y": batch["x"] + p["w"]}

    with ServeEngine(
        dense, params, max_batch=1,
        degraded_apply_fn=degraded, degrade_controller=_forced_controller(),
    ) as eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        warm = eng.compile_count
        assert warm == 2  # both variants pre-warmed at bs 1
        fut = eng.submit(key="A", payload=_toy_payload(3, 2.0))
        np.testing.assert_array_equal(
            fut.result(timeout=10)["y"],
            np.full((3,), 5.0, np.float32),  # x + w: the DEGRADED program
        )
        stats = eng.report()
        assert eng.compile_count == warm  # the flip compiled NOTHING
    assert stats["degraded_mode"] is True
    assert stats["degraded_batches"] == 1
    assert stats["degrade_flips"] >= 1  # the flip event is counted
    assert stats["recompiles_after_warmup"] == 0
    # the flip/counter state is scrapeable from the metrics registry
    assert eng.metrics.get("serve_degrade_flips_total").value >= 1


def test_no_degradation_without_pressure():
    params = {"w": jnp.asarray(3.0, jnp.float32)}

    def dense(p, batch):
        return {"y": batch["x"] * p["w"]}

    def degraded(p, batch):
        return {"y": batch["x"] + p["w"]}

    with ServeEngine(
        dense, params, max_batch=1, degraded_apply_fn=degraded,
    ) as eng:  # default controller: idle traffic never reaches high water
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        fut = eng.submit(key="A", payload=_toy_payload(3, 2.0))
        np.testing.assert_array_equal(
            fut.result(timeout=10)["y"],
            np.full((3,), 6.0, np.float32),  # x * w: still the dense one
        )
        stats = eng.report()
    assert stats["degraded_mode"] is False
    assert stats["degraded_batches"] == 0 and stats["degrade_flips"] == 0


def test_degraded_flip_is_bitwise_the_prewarmed_band_program():
    """Under forced overload the engine serves the real model's nc_topk
    band program, and the served result is BITWISE that program's own
    output — the flip changes which pre-warmed executable runs, nothing
    about how it runs (the patch16 trunk keeps the 2 traces cheap)."""
    cfg = TINY.replace(feature_extraction_cnn="patch16")  # dense NC
    band_cfg = cfg.replace(nc_topk=8)
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    dense_fn = make_serve_match_step(cfg)
    band_fn = make_serve_match_step(band_cfg)

    rng = np.random.RandomState(3)
    payload = {
        "source_image": rng.rand(32, 48, 3).astype(np.float32),
        "target_image": rng.rand(48, 32, 3).astype(np.float32),
    }
    expected = np.asarray(
        jax.jit(band_fn)(params, {k: v[None] for k, v in payload.items()})
        ["matches"]
    )[0]

    with ServeEngine(
        dense_fn, params, max_batch=1,
        degraded_apply_fn=band_fn, degrade_controller=_forced_controller(),
    ) as eng:
        eng.warmup([("K", payload_spec(payload))])
        warm = eng.compile_count
        fut = eng.submit(key="K", payload=payload)
        got = fut.result(timeout=120)["matches"]
        stats = eng.report()
        assert eng.compile_count == warm
    np.testing.assert_array_equal(got, expected)
    assert stats["degraded_batches"] == 1
    assert stats["recompiles_after_warmup"] == 0


# ----------------------------------------------------------------------
# supervision drills: a stage dies, ONLY in-flight requests fail (typed),
# the stage restarts, the warm compile cache survives


def test_prep_worker_crash_drill():
    faultinject.inject("serve.worker.crash", "crash", at=2)
    with _toy_engine(max_batch=2, max_wait=0.01, host_workers=1) as eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        warm = eng.compile_count
        futs = [
            eng.submit(key="A", payload=_toy_payload(3, float(i)))
            for i in range(3)
        ]
        with pytest.raises(StageFailure) as ei:
            futs[1].result(timeout=10)  # the in-flight one, and ONLY it
        assert ei.value.stage == "prep" and not ei.value.hang
        for i in (0, 2):  # before and AFTER the restart: served warm
            np.testing.assert_array_equal(
                futs[i].result(timeout=10)["y"],
                np.full((3,), 3.0 * i, np.float32),
            )
        stats = eng.report()
        assert eng.compile_count == warm
    assert stats["stage_restarts"]["prep"] == 1
    assert stats["failed"] == 1 and stats["completed"] == 2
    assert stats["recompiles_after_warmup"] == 0
    _invariant(stats)


def test_dispatch_hang_drill_watchdog_recovers():
    """A wedged dispatch (injected 3 s stall, unkillable in Python) is
    detected by the heartbeat watchdog well before it wakes: the in-flight
    batch fails typed (hang=True), a fresh dispatch thread takes over, and
    the next request is served from the intact warm cache."""
    faultinject.inject("serve.dispatch.hang", "delay", arg=3.0, at=1)
    with _toy_engine(max_batch=1, hang_timeout=0.25) as eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        warm = eng.compile_count
        t0 = time.monotonic()
        victim = eng.submit(key="A", payload=_toy_payload(3, 1.0))
        with pytest.raises(StageFailure) as ei:
            victim.result(timeout=10)
        assert time.monotonic() - t0 < 2.5  # recovered, not slept through
        assert ei.value.stage == "dispatch" and ei.value.hang
        fut = eng.submit(key="A", payload=_toy_payload(3, 2.0))
        np.testing.assert_array_equal(
            fut.result(timeout=10)["y"], np.full((3,), 6.0, np.float32)
        )
        stats = eng.report()
        assert eng.compile_count == warm
    assert stats["dispatch_hangs"] == 1
    assert stats["stage_restarts"]["dispatch"] == 1
    assert stats["failed"] == 1 and stats["completed"] == 1
    assert stats["recompiles_after_warmup"] == 0
    _invariant(stats)


def test_readout_crash_drill():
    faultinject.inject("serve.readout.delay", "crash", at=1)
    with _toy_engine(max_batch=1) as eng:
        eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
        victim = eng.submit(key="A", payload=_toy_payload(3, 1.0))
        with pytest.raises(StageFailure) as ei:
            victim.result(timeout=10)
        assert ei.value.stage == "readout"
        fut = eng.submit(key="A", payload=_toy_payload(3, 2.0))
        np.testing.assert_array_equal(
            fut.result(timeout=10)["y"], np.full((3,), 6.0, np.float32)
        )
        stats = eng.report()
    assert stats["stage_restarts"]["readout"] == 1
    assert stats["recompiles_after_warmup"] == 0
    _invariant(stats)


# ----------------------------------------------------------------------
# drain: shutdown returned => every accepted future resolved exactly once


def test_bounded_shutdown_resolves_every_future_exactly_once():
    faultinject.inject("serve.request", "delay", arg=0.2)  # every request
    eng = _toy_engine(max_batch=2, max_wait=0.005, host_workers=1)
    eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
    settled = []
    futs = [
        eng.submit(key="A", payload=_toy_payload(3, float(i)))
        for i in range(6)
    ]
    for f in futs:
        f.add_done_callback(settled.append)
    # ~1.2 s of prep left; the drain budget covers a fraction of it
    eng.shutdown(timeout=0.4)
    assert all(f.done() for f in futs)
    assert len(settled) == 6  # each settled exactly once
    drained = 0
    for f in futs:
        exc = f.exception()
        if exc is not None:
            assert isinstance(exc, RequestShed) and exc.reason == "drain"
            drained += 1
    assert drained >= 1  # the budget really did expire on stragglers
    stats = eng.report()
    _invariant(stats)
    assert stats["shed"] == drained
    eng.shutdown(timeout=0.4)  # idempotent, returns promptly
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(key="A", payload=_toy_payload(3, 0.0))


def test_concurrent_shutdown_blocks_until_drained():
    """A second shutdown() must not return while the first is still
    draining — callers use "shutdown returned" as "my futures resolved"
    (scripts/serve.py tallies right after engine.drain())."""
    faultinject.inject("serve.request", "delay", arg=0.3)
    eng = _toy_engine(max_batch=2, max_wait=0.005, host_workers=1)
    eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
    futs = [
        eng.submit(key="A", payload=_toy_payload(3, float(i)))
        for i in range(3)
    ]
    first = threading.Thread(target=eng.shutdown)  # unbounded drain
    first.start()
    time.sleep(0.05)  # the first owns the drain by now
    eng.shutdown()  # the follower: must block until the drain finishes
    assert all(f.done() for f in futs)
    first.join(timeout=10)
    for f in futs:
        f.result(timeout=0)  # unbounded drain: all completed
    _invariant(eng.report())


def test_drain_on_preemption_programmatic_trigger():
    guard = PreemptionGuard()  # .request() stands in for SIGTERM
    eng = _toy_engine(max_batch=2, max_wait=0.005)
    eng.warmup([("A", payload_spec(_toy_payload(3, 0.0)))])
    watcher = drain_on_preemption(eng, guard, timeout=5.0, poll_s=0.01)
    fut = eng.submit(key="A", payload=_toy_payload(3, 1.0))
    fut.result(timeout=10)
    guard.request()
    watcher.join(timeout=10)
    assert not watcher.is_alive()
    assert eng.closed
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(key="A", payload=_toy_payload(3, 0.0))
    _invariant(eng.report())


# ----------------------------------------------------------------------
# micro-batcher under a backwards-jumping clock (NTP step / VM migration)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _req(key, i=0):
    return Request(key, {"x": np.full((2,), i, np.float32)}, Future(), 0.0)


def test_batcher_tolerates_backwards_clock():
    clk = FakeClock(100.0)
    mb = MicroBatcher(max_batch=4, max_wait=0.1, clock=clk)
    mb.add(_req("A", 0))
    mb.add(_req("A", 1))
    clk.t = 50.0  # the clock STEPS BACKWARDS mid-wait
    assert mb.ready() == []  # no early flush...
    assert mb.pending() == 2  # ...and nothing lost
    assert mb.next_deadline() is not None
    clk.t = 100.05  # back past the jump: deadline stretched, not skipped
    assert mb.ready() == []
    clk.t = 100.2  # comfortably past t0 + max_wait (fp-safe margin)
    (batch,) = mb.ready()
    assert len(batch.requests) == 2 and batch.key == "A"
    # cap flush and drain are clock-independent: they work at t < 0 too
    clk.t = -7.0
    assert all(mb.add(_req("B", i)) is None for i in range(3))
    assert mb.add(_req("B", 3)) is not None
    mb.add(_req("C", 0))
    (leftover,) = mb.drain()
    assert leftover.key == "C"
    assert mb.pending() == 0


# ----------------------------------------------------------------------
# the SIGTERM drain drill through scripts/serve.py (the ops contract)


def test_serve_cli_sigterm_drain_drill(tmp_path):
    """SIGTERM mid-run: admission stops, the engine drains under
    --drain-timeout, EVERY accepted future resolves (result or typed
    shed), the accounting adds up, and the process exits 0 with its
    report written."""
    from PIL import Image

    from ncnet_tpu.train.checkpoint import CheckpointData, save_checkpoint

    cfg = TINY.replace(feature_extraction_cnn="patch16")
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    ckpt = tmp_path / "tiny.msgpack"
    save_checkpoint(
        str(ckpt),
        CheckpointData(config=cfg, params=params, opt_state=None, epoch=0),
    )
    imgdir = tmp_path / "imgs"
    imgdir.mkdir()
    rng = np.random.RandomState(0)
    for i in range(2):  # one pair, repeated: a single warm bucket
        Image.fromarray(
            rng.randint(0, 255, (32, 32, 3), np.uint8)
        ).save(imgdir / f"im{i}.png")

    report_path = tmp_path / "report.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        # ~50 ms per prep x 400 requests: >> the post-warmup signal point
        NCNET_FAULTS="serve.request=delay:0.05",
    )
    proc = subprocess.Popen(
        [
            sys.executable, str(REPO / "scripts" / "serve.py"),
            "--checkpoint", str(ckpt),
            "--images", str(imgdir),
            "--image-size", "32",
            "--concurrency", "2",
            "--max-batch", "2",
            "--max-wait-ms", "10",
            "--repeat", "400",
            "--drain-timeout", "10",
            "--report", str(report_path),
        ],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(REPO),
    )
    try:
        # the warmup line is the serving-phase marker; readline blocks
        # until the script prints it (compile time varies by machine)
        while True:
            line = proc.stdout.readline()
            assert line, "serve.py exited before finishing warmup"
            if line.startswith("warmup:"):
                break
        time.sleep(1.0)  # let some requests complete
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=180)
    finally:
        proc.kill()
    assert proc.returncode == 0, err[-2000:]
    report = json.loads(report_path.read_text())
    assert report["preempted"] is True
    assert report["unsubmitted"] > 0  # the signal landed mid-run
    assert report["completed"] >= 1  # ...with traffic already served
    # accepted futures all resolved, each into exactly one bin
    assert report["submitted"] + report["unsubmitted"] == report["n_requests"]
    assert report["submitted"] == (
        report["completed"] + report["failed"] + report["shed"]
        + report["deadline_exceeded"]
    )
    assert report["recompiles_after_warmup"] == 0
