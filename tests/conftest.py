"""Test environment: 8 virtual CPU devices (standard way to test
pjit/shard_map sharding without a TPU pod — SURVEY.md §4)."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import re  # noqa: E402
import socket  # noqa: E402
import subprocess  # noqa: E402
import sys  # noqa: E402

import jax  # noqa: E402
import pytest  # noqa: E402

# The env var JAX_PLATFORMS is ignored when a TPU plugin is present in this
# image; the config update reliably forces the CPU backend for tests.
jax.config.update("jax_platforms", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def multiprocess_cpu_supported():
    """Whether THIS jaxlib can run a real multi-process CPU cluster (gloo
    collectives present and wireable). Multi-process tests skip at
    collection time when it can't, instead of failing inside a child."""
    from ncnet_tpu.parallel.mesh import multiprocess_cpu_collectives_available

    return multiprocess_cpu_collectives_available()


def free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def spawn_cpu_cluster(script, n_procs=2, local_devices=2, timeout=280,
                      extra_env=None, per_proc_env=None, args=()):
    """Spawn ``n_procs`` child interpreters forming a 2-phase-commit-capable
    ``jax.distributed`` CPU cluster and wait for all of them.

    Each child runs ``script`` with ``JAX_PLATFORMS=cpu``,
    ``local_devices`` virtual CPU devices, and the coordinator wiring in
    ``_NCNET_MH_COORD`` / ``_NCNET_MH_PID`` / ``_NCNET_MH_NPROCS`` — the
    child is expected to call `initialize_multihost` with them (which also
    selects gloo CPU collectives). ``per_proc_env`` ({pid: {VAR: val}})
    targets one process, e.g. an ``NCNET_FAULTS`` kill drill on a single
    host. Returns ``[(returncode, combined_output), ...]`` in pid order; a
    child that outlives ``timeout`` (e.g. blocked on a barrier its killed
    peer will never reach) is killed and reports returncode None or -9.
    """
    port = free_port()
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    ).strip()
    procs = []
    for pid in range(n_procs):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(
                flags
                + f" --xla_force_host_platform_device_count={local_devices}"
            ).strip(),
            _NCNET_MH_COORD=f"localhost:{port}",
            _NCNET_MH_PID=str(pid),
            _NCNET_MH_NPROCS=str(n_procs),
        )
        if extra_env:
            env.update(extra_env)
        if per_proc_env and pid in per_proc_env:
            env.update(per_proc_env[pid])
        procs.append(
            subprocess.Popen(
                [sys.executable, script, *args],
                cwd=REPO,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    results = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out = (out or "") + "\n[spawn_cpu_cluster] child timed out"
        results.append((p.returncode, out))
    return results


@pytest.fixture(scope="session")
def uninterrupted_run(tmp_path_factory):
    """ONE uninterrupted run of the kill-drill training schedule, shared
    session-wide (the tier-1 suite-budget lever, PR 17): before it,
    tests/test_resilience.py and tests/test_distributed_ckpt.py each
    paid this IDENTICAL 2-epoch compile+train in their own module-scoped
    fixture. Schedule and seeds are pinned here; both modules' ``_run``
    helpers must keep matching them (their bitwise comparisons fail
    loudly on drift). Saves use the sharded (distributed_checkpoints)
    format — the richer artifact: the distributed tests inspect the
    save directories, while the resilience tests compare only loaded
    VALUES, which test_sharded_training_matches_legacy_bitwise pins as
    bitwise-equal across formats.

    Returns ``(ck, metrics_lines, ckdir)``.
    """
    import json

    from ncnet_tpu.data.loader import DataLoader
    from ncnet_tpu.data.pairs import SyntheticPairDataset
    from ncnet_tpu.models.immatchnet import (
        ImMatchNetConfig,
        init_immatchnet,
    )
    from ncnet_tpu.train.checkpoint import load_latest_valid_any
    from ncnet_tpu.train.loop import train

    cfg = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
    ds = SyntheticPairDataset(n=8, output_size=(32, 32), seed=11)
    loader = DataLoader(
        ds, 2, shuffle=True, seed=5, drop_last=True,
        num_workers=1, prefetch=0,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    ckdir = tmp_path_factory.mktemp("uninterrupted_shared")
    train(
        cfg, params, loader, None,
        num_epochs=2, checkpoint_dir=str(ckdir), data_parallel=False,
        log_every=100, save_every_steps=2, keep_checkpoints=4,
        distributed_checkpoints=True,
    )
    ck, _ = load_latest_valid_any(
        os.path.join(str(ckdir), "ncnet_tpu.msgpack")
    )
    lines = [
        json.loads(line)
        for line in open(os.path.join(str(ckdir), "metrics.jsonl"))
    ]
    return ck, lines, ckdir


@pytest.fixture(scope="session")
def legacy_format_run(tmp_path_factory):
    """ONE legacy-format (monolithic msgpack) run of the SAME pinned
    schedule as `uninterrupted_run`, shared session-wide (the tier-1
    budget lever, PR 18): the save-format parity drill
    (tests/test_distributed_ckpt.py::
    test_sharded_training_matches_legacy_bitwise) compares the two
    fixtures instead of paying its own 2-epoch legacy training arm.
    Schedule and seeds MUST stay identical to `uninterrupted_run` above
    — the bitwise comparison fails loudly on drift, so the drill is not
    weakened, only de-duplicated.

    Returns ``(ck, metrics_lines, ckdir)`` with ``ck`` read through the
    legacy single-file loader (the format under test).
    """
    import json

    from ncnet_tpu.data.loader import DataLoader
    from ncnet_tpu.data.pairs import SyntheticPairDataset
    from ncnet_tpu.models.immatchnet import (
        ImMatchNetConfig,
        init_immatchnet,
    )
    from ncnet_tpu.train.checkpoint import load_checkpoint
    from ncnet_tpu.train.loop import train

    cfg = ImMatchNetConfig(ncons_kernel_sizes=(3,), ncons_channels=(1,))
    ds = SyntheticPairDataset(n=8, output_size=(32, 32), seed=11)
    loader = DataLoader(
        ds, 2, shuffle=True, seed=5, drop_last=True,
        num_workers=1, prefetch=0,
    )
    params = init_immatchnet(jax.random.PRNGKey(0), cfg)
    ckdir = tmp_path_factory.mktemp("legacy_shared")
    train(
        cfg, params, loader, None,
        num_epochs=2, checkpoint_dir=str(ckdir), data_parallel=False,
        log_every=100, save_every_steps=2, keep_checkpoints=4,
        distributed_checkpoints=False,
    )
    ck = load_checkpoint(os.path.join(str(ckdir), "ncnet_tpu.msgpack"))
    lines = [
        json.loads(line)
        for line in open(os.path.join(str(ckdir), "metrics.jsonl"))
    ]
    return ck, lines, ckdir


@pytest.fixture(scope="session")
def multihost_oracle_loss():
    """The single-process reference arm of the 2-process cluster drill
    (tests/test_multihost.py), shared session-wide (the tier-1 budget
    lever, PR 18): one data-parallel train step of the PINNED multihost
    geometry — config ``(3, 3)/(4, 1)``, the seed-7 global batch of four
    32x32 pairs, ``PRNGKey(0)`` init — on a 4-device mesh in THIS
    process. The constants here must stay identical to the child script
    in tests/test_multihost.py; the drill's allclose against the
    cluster's psum-reduced loss fails loudly on drift.

    Returns the oracle loss as a Python float.
    """
    import numpy as np

    from ncnet_tpu.models.immatchnet import (
        ImMatchNetConfig,
        init_immatchnet,
    )
    from ncnet_tpu.parallel.mesh import make_mesh, replicate, shard_batch
    from ncnet_tpu.train.step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    grid_devices, image = 4, 32  # 2 processes x 2 local devices
    config = ImMatchNetConfig(
        ncons_kernel_sizes=(3, 3), ncons_channels=(4, 1)
    )
    rng = np.random.RandomState(7)
    batch_np = {
        "source_image": rng.randn(grid_devices, image, image, 3).astype(
            np.float32
        ),
        "target_image": rng.randn(grid_devices, image, image, 3).astype(
            np.float32
        ),
    }
    mesh = make_mesh(devices=jax.devices()[:grid_devices])
    params = init_immatchnet(jax.random.PRNGKey(0), config)
    optimizer = make_optimizer()
    state = create_train_state(replicate(mesh, params), optimizer)
    state = state._replace(opt_state=replicate(mesh, state.opt_state))
    batch = shard_batch(mesh, batch_np)
    _, loss = make_train_step(config, optimizer, donate=False)(state, batch)
    return float(loss)
