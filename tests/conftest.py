"""Test environment: 8 virtual CPU devices (standard way to test
pjit/shard_map sharding without a TPU pod — SURVEY.md §4)."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The env var JAX_PLATFORMS is ignored when a TPU plugin is present in this
# image; the config update reliably forces the CPU backend for tests.
jax.config.update("jax_platforms", "cpu")
